#!/usr/bin/env python
"""bench_diff — CI regression gate between two BENCH snapshots.

The repo accumulates one `BENCH_r*.json` per round (the driver saves
`python bench.py`'s one-line JSON under `parsed`), but until now nothing
DIFFED them — a 10% ITL regression only surfaced if a human eyeballed
two blobs.  This tool compares every numeric metric two snapshots
share, classifies each as higher-better (throughput, MFU, speedups) or
lower-better (latencies, overheads, bytes, recompiles), and fails with
a CI-able exit code when any metric regressed past its threshold.

Usage:
  python tools/bench_diff.py OLD.json NEW.json
          [--threshold 0.05]            # default regression tolerance
          [--rule PATH=FRAC ...]        # per-metric override, e.g.
                                        #   --rule extra.mfu=0.02
          [--metrics GLOB[,GLOB...]]    # only compare matching paths
          [--json]                      # machine-readable report

Inputs may be driver snapshots ({"parsed": {...}}) or bare bench lines
({"metric": ..., "value": ..., "extra": {...}}).  Metric paths are
dot-joined ("value", "extra.mfu", "extra.ragged.itl_chunked_p99_ms").
Config-shaped leaves (batch/seq/steps/trial counts...) are ignored:
they describe the workload, not its performance.

Exit codes: 0 = no regression, 1 = regression(s) past threshold,
2 = unusable input.
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import sys

# config-shaped leaf names: equality is not a goal, so never diff them
_SKIP_LEAVES = {
    "batch", "seq", "steps", "n", "trials", "model_params", "vocab",
    "page_size", "spec_k", "num_pages", "streams", "new_tokens",
    "prompt", "prompt_len", "requests", "schedules", "replicas", "seed",
    "count", "window", "bound_pct", "failover_trials", "block_q",
    "chunk", "hops", "num_slots", "max_seq", "quantile", "target_s",
    # prefix_reuse workload shape + neutral footprint counters (a COW
    # copy count or cache size has no better/worse direction)
    "mix", "shared_prefix", "suffix", "shared_fraction", "cow_copies",
    "cached_pages",
    # measured/predicted step time: 1.0 is best, so neither direction
    # is a regression — not diffable as a scalar ordering
    "cost_model_ratio",
    # fused-decode A/B bookkeeping: how many steps routed fused is
    # routing shape, not a performance ordering; chunk_tokens is the
    # per-leg workload knob
    "fused_decode_steps", "chunk_tokens",
}

# exact leaves that are lower-better but carry no unit suffix — the
# prefix_reuse gates: prefill work per request must SHRINK as splicing
# serves more of each prompt, and the fused-decode A/B ratio: fused
# p50 over unfused p50, gated <= 0.9 (its _ms legs and the
# dispatch_sample_*_ms attribution keys classify lower by suffix; the
# ratio carries no unit, so pin it here — "itl" in the leaf would
# already catch it, but an A/B gate must not hang off a substring)
_LOWER_LEAVES = {
    "prefill_tokens_mean", "prefill_tokens_hit95_vs_cold",
    "itl_fused_vs_unfused",
    # disagg gates: decode-tail A/B ratio under a prefill burst and the
    # host-tier warm-start TTFT ratio ("itl"/"ttft" substrings would
    # already classify these, but A/B gates must not hang off substrings)
    "itl_burst_disagg_vs_mixed", "ttft_warm_vs_cold",
    # QoS gates: the paced high-priority tenant's p99 TTFT and
    # end-to-end per-token latency with WFQ/priority admission on vs
    # the untagged-FIFO baseline, both <= 0.8 (same no-substring rule)
    "ttft_hipri_qos_on_vs_off", "itl_hipri_qos_on_vs_off",
}

# time/size units marking a LOWER-is-better metric — matched as leaf
# SUFFIXES only ("decode_tokens_per_sec" must NOT match "_s")
_LOWER_SUFFIXES = ("_ms", "_s", "_us", "_ns", "_bytes", "_pct")
# whole-word-ish markers, safe as substrings of the leaf
_LOWER_SUBSTR = (
    "seconds", "latency", "overhead", "recompile", "loss", "itl",
    "ttft", "violations", "dropped", "failed", "errors", "frag",
    "preemptions", "anomal",
)


def classify(path: str) -> str:
    """'higher' | 'lower' | 'skip' for one dot-joined metric path."""
    leaf = path.rsplit(".", 1)[-1]
    dotted = f".{path}."
    if leaf in _SKIP_LEAVES or ".workload." in dotted \
            or ".schedule." in dotted or ".phase_shares." in dotted:
        # phase SHARES are zero-sum fractions: one phase speeding up
        # shifts every other share — not orderable as better/worse
        return "skip"
    # throughputs are higher-better NO MATTER what unit suffix they
    # carry ("tokens_per_sec" ends in neither _s nor _sec by suffix
    # matching, but be explicit — an inverted gate passes regressions)
    if "per_sec" in leaf or "throughput" in leaf:
        return "higher"
    if leaf in _LOWER_LEAVES:
        return "lower"
    if leaf.endswith(_LOWER_SUFFIXES):
        return "lower"
    for sub in _LOWER_SUBSTR:
        if sub in leaf:
            return "lower"
    # containers whose CHILDREN are the metrics (mem-peak tables keyed
    # by model name, latency tables keyed by percentile, threadlint /
    # kernellint severity counts keyed by module or kernel — every race
    # or kernel-contract finding is a defect)
    for sub in ("bytes", "mem_peak", "latency", "overhead", "threadlint",
                "kernellint"):
        if sub in path:
            return "lower"
    return "higher"


def flatten(d, prefix: str = "") -> dict:
    """Numeric leaves of a nested dict as {dot.path: float}.  Bools,
    strings, lists, and nulls are not metrics."""
    out = {}
    if not isinstance(d, dict):
        return out
    for k, v in d.items():
        path = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            out.update(flatten(v, path))
        elif isinstance(v, bool) or v is None:
            continue
        elif isinstance(v, (int, float)):
            out[path] = float(v)
    return out


def load_bench(path: str) -> dict:
    """One snapshot's metric dict: the driver envelope's `parsed`, or
    the bare bench line itself."""
    with open(path) as f:
        d = json.load(f)
    if isinstance(d, dict) and isinstance(d.get("parsed"), dict):
        d = d["parsed"]
    if not isinstance(d, dict):
        raise ValueError(f"{path!r} is not a bench snapshot")
    return d


def diff(old: dict, new: dict, threshold: float = 0.05,
         rules: dict = None, metrics=None) -> dict:
    """Compare two flattened-able bench dicts.  Returns {compared,
    regressions, improvements, skipped, missing} where `regressions`
    is the CI verdict list."""
    rules = rules or {}
    fo, fn = flatten(old), flatten(new)
    compared, regressions, improvements, skipped = [], [], [], []
    for path in sorted(set(fo) & set(fn)):
        if metrics and not any(fnmatch.fnmatch(path, g) for g in metrics):
            continue
        direction = classify(path)
        if direction == "skip":
            skipped.append(path)
            continue
        ov, nv = fo[path], fn[path]
        if ov == 0.0:
            skipped.append(path)    # no ratio against a zero baseline
            continue
        change = (nv - ov) / abs(ov)
        thr = rules.get(path, threshold)
        worse = (change < -thr) if direction == "higher" \
            else (change > thr)
        row = {"metric": path, "old": ov, "new": nv,
               "change_pct": round(change * 100, 2),
               "direction": direction, "threshold_pct": thr * 100}
        compared.append(row)
        if worse:
            regressions.append(row)
        elif (change > thr) if direction == "higher" else (change < -thr):
            improvements.append(row)
    missing = sorted((set(fo) - set(fn)))
    if metrics:
        missing = [p for p in missing
                   if any(fnmatch.fnmatch(p, g) for g in metrics)]
    missing = [p for p in missing if classify(p) != "skip"]
    return {"compared": compared, "regressions": regressions,
            "improvements": improvements, "skipped": skipped,
            "missing_in_new": missing}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="regression gate between two BENCH snapshots")
    ap.add_argument("old", metavar="OLD.json")
    ap.add_argument("new", metavar="NEW.json")
    ap.add_argument("--threshold", type=float, default=0.05,
                    help="default regression tolerance as a fraction "
                         "(0.05 = 5%%)")
    ap.add_argument("--rule", action="append", default=[],
                    metavar="PATH=FRAC",
                    help="per-metric threshold override (repeatable)")
    ap.add_argument("--metrics", default=None, metavar="GLOBS",
                    help="comma-separated path globs to compare "
                         "(default: everything classifiable)")
    ap.add_argument("--fail-on-missing", action="store_true",
                    help="also exit 1 when a metric in OLD is absent "
                         "from NEW (a silently dropped benchmark)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    args = ap.parse_args(argv)

    rules = {}
    for spec in args.rule:
        try:
            path, frac = spec.split("=", 1)
            rules[path] = float(frac)
        except ValueError:
            print(f"bad --rule {spec!r} (want PATH=FRACTION)",
                  file=sys.stderr)
            return 2
    metrics = ([g.strip() for g in args.metrics.split(",") if g.strip()]
               if args.metrics else None)

    try:
        old, new = load_bench(args.old), load_bench(args.new)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"cannot load snapshots: {e!r}", file=sys.stderr)
        return 2

    report = diff(old, new, threshold=args.threshold, rules=rules,
                  metrics=metrics)
    failed = bool(report["regressions"]) or \
        (args.fail_on_missing and report["missing_in_new"])

    if args.as_json:
        report["ok"] = not failed
        print(json.dumps(report, sort_keys=True))
        return 1 if failed else 0

    if report["compared"]:
        print(f"{'metric':44}  {'old':>12}  {'new':>12}  {'change':>8}  "
              f"verdict")
        for row in report["compared"]:
            if row in report["regressions"]:
                verdict = "REGRESSED"
            elif row in report["improvements"]:
                verdict = "improved"
            else:
                verdict = "ok"
            arrow = "v" if row["direction"] == "lower" else "^"
            print(f"{row['metric'][:44]:44}  {row['old']:>12.4g}  "
                  f"{row['new']:>12.4g}  {row['change_pct']:>7.2f}%  "
                  f"{verdict} ({arrow} better"
                  f"{'' if row['threshold_pct'] == args.threshold * 100 else ', thr %.1f%%' % row['threshold_pct']})")
    else:
        print("no comparable metrics between the two snapshots")
    if report["missing_in_new"]:
        print(f"missing in NEW: {', '.join(report['missing_in_new'][:20])}"
              + (" ..." if len(report["missing_in_new"]) > 20 else ""))
    print(f"{len(report['compared'])} compared, "
          f"{len(report['regressions'])} regressed, "
          f"{len(report['improvements'])} improved")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
