#!/usr/bin/env python
"""graphlint — run the Graph Doctor (paddle_tpu.analysis) over the shipped
bench models end to end, BOTH tiers: the jaxpr walk (trace-level) and the
HLO pass (each target lowered + compiled once; fusion / collective /
layout / buffer-assignment findings the trace cannot see).

Targets (default: all):
  llama              ShardedTrainState train step, LlamaConfig.tiny
  moe_llama_gmm      MoE train step, dropless Pallas grouped-matmul dispatch
  moe_llama_scatter  MoE train step, capacity-based scatter dispatch
  generate_paged     paged-KV single-shot generation (prefill + decode scan)
  engine_ragged      LLMEngine's ONE jitted unified step: decode spans and
                     prefill chunks in the same ragged batch (single
                     signature — expected_signatures defaults to 1)
  engine_ragged_fused  the fused single-dispatch decode step (sampling
                     epilogue inside the dispatch) plain decode routes
                     through by default — same one-signature contract
  engine_swap_out    LLMEngine's preemption page-gather (KV -> host)
  engine_swap_in     LLMEngine's resume page-scatter (host -> fresh pages)

Usage:
  python tools/graphlint.py [targets...] [--json] [--verbose] [--fix]
                            [--apply] [--suppress CODE[@pathglob]]...
                            [--fail-on LVL] [--no-hlo] [--config RC]
                            [--baseline B.json | --write-baseline B.json]
  python tools/graphlint.py --threads [modules...] [--json] [--verbose]
                            [--baseline B.json | --write-baseline B.json]
  python tools/graphlint.py --kernels [kernel-targets...] [--json]
                            [--verbose] [--chip KIND]
                            [--baseline B.json | --write-baseline B.json]

--kernels flips to the Pallas kernel verifier (analysis.kernellint): the
positionals become KERNEL TARGET names (default: every shipped kernel —
flash_attention, grouped_matmul, ragged_attention, paged_attention,
rms_norm, adaln, decode_step, plus the GENERATED fused_chain, i.e. the
same emission path the rewrite tier uses).  Each target is traced (grad
traces pull in the backward kernels) and every pallas_call is statically
verified: block index maps proven in-bounds and outputs covered
exactly once (KERNEL_OOB_BLOCK / KERNEL_OUT_UNCOVERED /
KERNEL_OUT_OVERLAP / KERNEL_DEAD_GRID_CELL), the VMEM footprint priced
against the --chip budget (KERNEL_VMEM_OVERFLOW), and accumulator
dtypes checked (KERNEL_LOWP_ACCUM / KERNEL_DTYPE_MISMATCH).  The
baseline's "kernels" section (schema v5) diffs per-kernel finding codes
AND counts, merged into the same shared snapshot doc.

--threads flips to the lock-discipline tier (analysis.threadlint): the
positionals become MODULE names (default: paddle_tpu.inference and
paddle_tpu.obs), linted for unguarded shared-field writes/reads, static
lock-order cycles, blocking calls under locks, and leaked threads —
`# threadlint:` annotations suppress findings in-source and are
VERIFIED, not trusted.  --verbose adds the full lock/thread inventory.
The baseline's "threads" section (schema v4) diffs per-module finding
codes AND counts; --write-baseline merges into the shared snapshot
without touching the model targets' section.

Exit code is 0 when every target is clean at --fail-on (default: warning)
after suppressions, 1 otherwise.  --json emits one machine-readable object
(finding lists + counts + jaxpr-tier mem_peak_bytes per target) so BENCH
rounds can track lint drift and the memory-peak trend alongside perf.

--fix prints concrete patch suggestions (exact donate_argnums, constraint
insertion points, bucket-menu edits) for the fixable findings.

--fix --apply goes further: the rewrite tier (analysis/rewrite.py) runs
over each target — dead-code elimination, dtype unification, fusion
stitching, donation injection — every pass gated by the equivalence
harness (probe-input forward match + re-lint) and ROLLED BACK on any
mismatch.  The per-target RewriteReport (per-pass eqn deltas and static
FLOPs/bytes deltas) lands in the JSON under "rewrite"; a rollback fails
the run (that is the CI regression signal — a rewrite that used to
verify no longer does).  This is a dry run over traced jaxprs: nothing
edits your source; the report tells you what the passes would buy.

--baseline B.json flips to DIFF mode for CI: exit 0 while no target grows
a finding code (or escalates one's severity) beyond the stored snapshot,
exit 1 listing what is new; pre-existing findings don't re-fail the run.
--write-baseline records the current state.

A `.graphlintrc` at the repo root (or --config PATH) adds project-level
suppressions and severity overrides; per-call --suppress flags stack on
top (union — flags cannot un-suppress the rc file).

Suppression syntax (same as analysis.analyze(suppress=...)):
  DTYPE_F64_PROMOTION          exact code
  DTYPE_*                      code glob
  DEAD_CODE@*scan/body*        code scoped to eqn paths matching the glob
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


# axis sizes for the train targets' mesh, set by --mesh (None = the
# single-device default).  Accepts fleet-style aliases: dp=data,
# tp=model, pp=pipe, sp=sep, zero=sharding, ep=expert.
MESH_SIZES = None

_MESH_ALIASES = {
    "dp": "data", "tp": "model", "pp": "pipe", "sp": "sep",
    "zero": "sharding", "ep": "expert",
    "data": "data", "model": "model", "pipe": "pipe", "sep": "sep",
    "sharding": "sharding", "expert": "expert",
}


def _parse_mesh(spec: str) -> dict:
    """"dp=2,tp=4" -> {"data": 2, "model": 4}."""
    sizes = {}
    for part in spec.split(","):
        key, eq, val = part.partition("=")
        key = key.strip().lower()
        try:
            size = int(val)
        except ValueError:
            size = -1
        if not eq or key not in _MESH_ALIASES or size < 1:
            raise SystemExit(
                f"graphlint: bad --mesh entry {part!r} (want "
                f"axis=N, N >= 1, axis in {sorted(set(_MESH_ALIASES))})")
        sizes[_MESH_ALIASES[key]] = size
    return sizes


def _mesh_devices(sizes: dict) -> int:
    n = 1
    for v in sizes.values():
        n *= max(1, int(v))
    return n


def _train_target(model_name, **cfg_overrides):
    import dataclasses
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import llama, moe_llama
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.distributed.parallelize import ShardedTrainState
    from paddle_tpu.optimizer.functional import AdamW

    model = {"llama": llama, "moe_llama": moe_llama}[model_name]
    cfg = (llama.LlamaConfig.tiny() if model_name == "llama"
           else moe_llama.MoELlamaConfig.tiny())
    if cfg_overrides:
        cfg = dataclasses.replace(cfg, **cfg_overrides)
    sizes = dict(MESH_SIZES or {})
    known = {k: sizes.pop(k) for k in
             ("data", "pipe", "sharding", "sep", "model")
             if k in sizes}
    mesh = mesh_lib.make_mesh(**(known or {"data": 1}),
                              extra_axes=sizes or None)
    dpz = mesh.shape.get("data", 1) * mesh.shape.get("sharding", 1)
    st = ShardedTrainState(cfg, model, mesh,
                           AdamW(learning_rate=1e-4, grad_clip_norm=1.0))
    params, opt_state = st.init(jax.random.PRNGKey(0))
    toks = np.random.default_rng(0).integers(
        0, cfg.vocab_size, (max(2, 2 * dpz), 17))
    batch = st.shard_batch(model.lm_batch_from_tokens(
        jnp.asarray(toks, jnp.int32)))
    return st.jitted_step(batch), (params, opt_state, batch), {"mesh": mesh}


def target_llama():
    return _train_target("llama")


def target_moe_llama_gmm():
    return _train_target("moe_llama", moe_dispatch="gmm")


def target_moe_llama_scatter():
    return _train_target("moe_llama", moe_dispatch="scatter")


def _tiny_llama():
    import jax
    from paddle_tpu.models import llama
    cfg = llama.LlamaConfig.tiny()
    return cfg, llama.init_params(cfg, jax.random.PRNGKey(0))


def target_generate_paged():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import generation

    cfg, params = _tiny_llama()
    B, S, new, ps = 2, 8, 4, 4
    total = S + new
    pps = -(-total // ps)
    cache = generation.PagedKVCache(cfg, num_pages=1 + B * pps, page_size=ps,
                                    max_slots=B, pages_per_seq=pps)
    for _ in range(B):
        cache.ensure_capacity(cache.acquire_slot(), total)
    fn = functools.partial(
        generation._generate_paged_core, config=cfg, max_new_tokens=new,
        temperature=0.0, top_k=0, top_p=1.0, eos_id=None)
    ids = jnp.zeros((B, S), jnp.int32)
    args = (params, ids, cache.pools["k"], cache.pools["v"],
            cache.page_table, jax.random.PRNGKey(0))
    return fn, args, {}


def _engine():
    from paddle_tpu.inference import LLMEngine
    cfg, params = _tiny_llama()
    return LLMEngine(params, cfg, num_slots=2, page_size=4, max_seq_len=16), \
        params


def target_engine_ragged():
    eng, params = _engine()
    # the unified ragged step replaced both the bucketed prefill menu and
    # the separate decode dispatch: ONE fixed-shape signature serves every
    # mix of prompt lengths, so the shape-poly gate expects exactly one
    # compile (the default) — any second signature is a regression
    return eng._ragged, eng.ragged_probe_args(), {}


def target_engine_ragged_fused():
    # the fused single-dispatch decode step: the SAME trunk plus the
    # lm_head matmul + filter + sample epilogue inside the dispatch
    # (kernels/pallas_decode_step.py); plain decode steps route through
    # it by default, so it must lint as clean as the unfused step and
    # hold the same one-signature contract
    eng, params = _engine()
    return eng._ragged_fused, eng.ragged_fused_probe_args(), {}


def target_engine_swap_out():
    # preemption swap path: gather a victim's KV pages for the host copy
    # (reads the pools — correctly NOT donated)
    import jax.numpy as jnp
    eng, params = _engine()
    idx = jnp.zeros((eng.cache.pages_per_seq,), jnp.int32)
    args = (eng.cache.pools["k"], eng.cache.pools["v"], idx)
    return eng._swap_out, args, {}


def target_engine_swap_in():
    # resume path: scatter the host KV copy back into fresh pages (the
    # pools are donated, like the decode step)
    import jax
    import jax.numpy as jnp
    eng, params = _engine()
    pool = eng.cache.pools["k"]
    idx = jnp.zeros((eng.cache.pages_per_seq,), jnp.int32)
    host = jax.ShapeDtypeStruct(
        (pool.shape[0], eng.cache.pages_per_seq) + pool.shape[2:],
        pool.dtype)
    args = (eng.cache.pools["k"], eng.cache.pools["v"], idx, host, host)
    return eng._swap_in, args, {}


TARGETS = {
    "llama": target_llama,
    "moe_llama_gmm": target_moe_llama_gmm,
    "moe_llama_scatter": target_moe_llama_scatter,
    "generate_paged": target_generate_paged,
    "engine_ragged": target_engine_ragged,
    "engine_ragged_fused": target_engine_ragged_fused,
    "engine_swap_out": target_engine_swap_out,
    "engine_swap_in": target_engine_swap_in,
}

# documented suppressions for the shipped models (none today: dead
# AD-partial-eval residue lints as INFO, below the warning gate).  Add
# entries as "CODE@pathglob" with a comment justifying each.
SHIPPED_SUPPRESSIONS: tuple = ()


def _severity_rank(s: str) -> int:
    return {"info": 1, "warning": 2, "error": 3}.get(s, 0)


def _spmd_summary(report) -> "dict | None":
    """Flatten the SPMD tier's findings (COLLECTIVE_BOUND roofline +
    SPMD_SUMMARY table + SHARD_RESHARD count) into the per-target JSON
    block bench.py's extra.spmd and the baseline snapshot consume.
    None when the tier did not run (no --mesh / single-device mesh)."""
    bound = next((f for f in report.findings
                  if f.code == "COLLECTIVE_BOUND"), None)
    summary = next((f for f in report.findings
                    if f.code == "SPMD_SUMMARY"), None)
    if bound is None or summary is None:
        return None
    roof = bound.data.get("roofline", {})
    return {
        "mesh": bound.data.get("mesh", {}),
        "chip": bound.data.get("chip", ""),
        "bound": roof.get("bound", ""),
        "t_comm_ms": float(roof.get("t_comm_s", 0.0)) * 1e3,
        "t_compute_ms": float(roof.get("t_compute_s", 0.0)) * 1e3,
        "n_eqns": int(summary.data.get("n_eqns", 0)),
        "n_collectives": int(roof.get("n_collectives", 0)),
        "collective_bytes": int(roof.get("collective_bytes", 0)),
        "reshard_count": sum(1 for f in report.findings
                             if f.code == "SHARD_RESHARD"),
        "collectives": list(bound.data.get("collectives", ())),
        "rows": list(summary.data.get("rows", ())),
    }


# bump when the snapshot schema changes; readers WARN (not crash) on
# keys they don't know, so a newer tool's baseline still gates an older
# checkout and vice versa.  v3: per-target "spmd" counters (--mesh
# runs).  v4: top-level "threads" — per-module threadlint code/count
# snapshots (--threads runs); --write-baseline MERGES into an existing
# file, so the model targets and the threads section share one doc.
# v5: top-level "kernels" — per-kernel kernellint code/count snapshots
# (--kernels runs), same merge semantics.
BASELINE_SCHEMA_VERSION = 5
_KNOWN_BASELINE_KEYS = {"schema_version", "targets", "mesh", "threads",
                        "kernels"}
_KNOWN_TARGET_KEYS = {"codes", "rewrite", "spmd"}
_KNOWN_THREADS_KEYS = {"codes", "counts"}
_KNOWN_KERNELS_KEYS = {"codes", "counts"}


def _baseline_snapshot(out: dict) -> dict:
    """{target: {code: worst_severity}} (+ rewrite counters when --apply
    ran) — what --write-baseline stores and --baseline diffs against."""
    snap = {}
    for name, rep in out.items():
        codes: dict = {}
        for f in rep["findings"]:
            if _severity_rank(f["severity"]) > _severity_rank(
                    codes.get(f["code"], "")):
                codes[f["code"]] = f["severity"]
        snap[name] = {"codes": codes}
        rw = rep.get("rewrite")
        if rw is not None:
            snap[name]["rewrite"] = {
                "applied": len(rw.get("applied", ())),
                "rolled_back": len(rw.get("rolled_back", ()))}
        sp = rep.get("spmd")
        if sp is not None:
            snap[name]["spmd"] = {
                "reshard_count": int(sp.get("reshard_count", 0)),
                "bound": sp.get("bound", "")}
    return snap


def _load_baseline(path: str) -> dict:
    """Read a baseline snapshot, WARNING (never crashing) on unknown
    keys — counters added by newer tool versions must not break older
    checkouts reading the shipped file."""
    with open(path) as f:
        baseline = json.load(f)
    unknown = sorted(set(baseline) - _KNOWN_BASELINE_KEYS -
                     ({"targets"} if "targets" in baseline else
                      set(baseline)))  # legacy: bare target map
    for k in unknown:
        print(f"graphlint: warning: unknown baseline key {k!r} "
              f"(newer schema?) — ignored", file=sys.stderr)
    for tname, tsnap in baseline.get("targets", {}).items():
        if isinstance(tsnap, dict):
            for k in sorted(set(tsnap) - _KNOWN_TARGET_KEYS):
                print(f"graphlint: warning: unknown baseline key "
                      f"{tname}.{k!r} — ignored", file=sys.stderr)
    for mname, msnap in baseline.get("threads", {}).items():
        if isinstance(msnap, dict):
            for k in sorted(set(msnap) - _KNOWN_THREADS_KEYS):
                print(f"graphlint: warning: unknown baseline key "
                      f"threads.{mname}.{k!r} — ignored", file=sys.stderr)
    for kname, ksnap in baseline.get("kernels", {}).items():
        if isinstance(ksnap, dict):
            for k in sorted(set(ksnap) - _KNOWN_KERNELS_KEYS):
                print(f"graphlint: warning: unknown baseline key "
                      f"kernels.{kname}.{k!r} — ignored", file=sys.stderr)
    return baseline


def _write_baseline_doc(path: str, targets=None, mesh=None,
                        threads=None, kernels=None) -> None:
    """MERGE one section into the baseline file: a --threads or
    --kernels run must not drop the model-target snapshot and vice
    versa (one shipped doc gates all three surfaces)."""
    doc = {}
    if os.path.isfile(path):
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            doc = {}
    doc["schema_version"] = BASELINE_SCHEMA_VERSION
    if targets is not None:
        doc["targets"] = targets
    if mesh is not None:
        doc["mesh"] = mesh
    if threads is not None:
        doc["threads"] = threads
    if kernels is not None:
        doc["kernels"] = kernels
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)


def _baseline_diff(current: dict, baseline: dict) -> list:
    """New finding codes (or severity escalations) vs the snapshot."""
    news = []
    for name, cur in current.items():
        base = baseline.get("targets", baseline).get(name, {}).get(
            "codes", {})
        for code, sev in cur["codes"].items():
            if code not in base:
                news.append(f"{name}: NEW code {code} ({sev})")
            elif _severity_rank(sev) > _severity_rank(base[code]):
                news.append(f"{name}: {code} escalated "
                            f"{base[code]} -> {sev}")
        # spmd tier: a reshard-count REGRESSION fails even when the code
        # itself is already baselined (counts matter: each one is a
        # collective on the hot path)
        cur_sp = cur.get("spmd") or {}
        base_sp = baseline.get("targets", baseline).get(name, {}).get(
            "spmd") or {}
        if cur_sp and base_sp and int(cur_sp.get("reshard_count", 0)) \
                > int(base_sp.get("reshard_count", 0)):
            news.append(
                f"{name}: SHARD_RESHARD count grew "
                f"{base_sp.get('reshard_count', 0)} -> "
                f"{cur_sp.get('reshard_count', 0)}")
    return news


def _threads_snapshot(reports: dict) -> dict:
    """{module: {"codes": {code: worst_sev}, "counts": {code: n}}} —
    the v4 baseline's threads section.  Counts matter here (unlike the
    model tiers): a second unguarded write to the same field is a
    second race, so count growth fails the diff."""
    snap = {}
    for mod, rep in reports.items():
        codes: dict = {}
        counts: dict = {}
        for f in rep.findings:
            sev = f.severity.name.lower()
            if _severity_rank(sev) > _severity_rank(codes.get(f.code, "")):
                codes[f.code] = sev
            counts[f.code] = counts.get(f.code, 0) + 1
        snap[mod] = {"codes": codes, "counts": counts}
    return snap


def _threads_diff(current: dict, baseline: dict) -> list:
    """New codes, severity escalations, or count growth vs the
    baseline's threads section."""
    base_all = baseline.get("threads", {})
    news = []
    for mod, cur in current.items():
        base = base_all.get(mod, {})
        bcodes = base.get("codes", {})
        bcounts = base.get("counts", {})
        for code, sev in cur["codes"].items():
            if code not in bcodes:
                news.append(f"{mod}: NEW code {code} ({sev})")
            elif _severity_rank(sev) > _severity_rank(bcodes[code]):
                news.append(f"{mod}: {code} escalated "
                            f"{bcodes[code]} -> {sev}")
            elif cur["counts"].get(code, 0) > int(bcounts.get(code, 0)):
                news.append(f"{mod}: {code} count grew "
                            f"{bcounts.get(code, 0)} -> "
                            f"{cur['counts'][code]}")
    return news


def _threads_main(args, analysis, config) -> int:
    """--threads mode: the lock-discipline tier over serving modules
    (positionals are MODULE names, not bench targets)."""
    from paddle_tpu.analysis import threadlint

    t0 = time.perf_counter()
    modules = list(args.targets) or list(threadlint.DEFAULT_MODULES)
    fail_on = analysis.Severity[args.fail_on.upper()]
    suppress = list(args.suppress)
    reports = threadlint.analyze_modules(
        tuple(modules), suppress=suppress, config=config)
    tier_seconds = {"threads": time.perf_counter() - t0}
    out, all_ok = {}, True
    for mod, rep in reports.items():
        ok = rep.ok(fail_on)
        all_ok &= ok
        out[mod] = dict(rep.to_json(), ok=ok)
        if not args.as_json:
            shown = [f for f in rep
                     if args.verbose
                     or f.severity >= analysis.Severity.WARNING]
            print(f"== {mod}: {'clean' if ok else 'FINDINGS'} "
                  f"({rep.counts()}, {rep.suppressed} suppressed)")
            for f in shown:
                print(f"   {f}")
    if args.verbose and not args.as_json:
        inv = threadlint.inventory(tuple(modules))
        print(f"-- inventory: {len(inv['locks'])} lock(s), "
              f"{len(inv['threads'])} thread entry point(s), "
              f"{len(inv['lock_order_edges'])} static lock-order "
              "edge(s)")
        for lk in inv["locks"]:
            print(f"   lock   {lk['lock']:<34} {lk['kind']:<10} "
                  f"{lk['file']}:{lk['line']}")
        for th in inv["threads"]:
            print(f"   thread {th['where']} -> {th['target']} "
                  f"(daemon={th['daemon']}, stored as "
                  f"{th['stored_as']}) {th['file']}:{th['line']}")
        for edge in inv["lock_order_edges"]:
            print(f"   order  {edge}")
    snap = _threads_snapshot(reports)
    if args.write_baseline:
        _write_baseline_doc(args.write_baseline, threads=snap)
        if not args.as_json:
            print(f"graphlint: threads baseline written to "
                  f"{args.write_baseline}")
    if args.baseline:
        baseline = _load_baseline(args.baseline)
        news = _threads_diff(snap, baseline)
        if args.as_json:
            print(json.dumps({"threads": out, "new_vs_baseline": news,
                              "tier_seconds": tier_seconds,
                              "ok": not news}))
        else:
            for n in news:
                print(f"baseline: {n}")
            print(f"graphlint: "
                  f"{'no new threadlint findings' if not news else f'{len(news)} NEW threadlint finding(s)'} "
                  f"vs {args.baseline}")
        return 1 if news else 0
    if args.as_json:
        counts = {k: out[k]["counts"] for k in out}
        print(json.dumps({"threads": out, "counts": counts,
                          "tier_seconds": tier_seconds, "ok": all_ok}))
    elif all_ok:
        print(f"graphlint: {len(modules)} module(s) thread-clean at "
              f">={args.fail_on}")
    return 0 if all_ok else 1


def _kernels_snapshot(reports: dict) -> dict:
    """{kernel_id: {"codes": {code: worst_sev}, "counts": {code: n}}} —
    the v5 baseline's kernels section.  Counts matter (as in threads):
    a second OOB operand is a second bug, so count growth fails."""
    snap = {}
    for kid, rep in reports.items():
        codes: dict = {}
        counts: dict = {}
        for f in rep.findings:
            sev = f.severity.name.lower()
            if _severity_rank(sev) > _severity_rank(codes.get(f.code, "")):
                codes[f.code] = sev
            counts[f.code] = counts.get(f.code, 0) + 1
        snap[kid] = {"codes": codes, "counts": counts}
    return snap


def _kernels_diff(current: dict, baseline: dict) -> list:
    """New codes, severity escalations, or count growth vs the
    baseline's kernels section."""
    base_all = baseline.get("kernels", {})
    news = []
    for kid, cur in current.items():
        base = base_all.get(kid, {})
        bcodes = base.get("codes", {})
        bcounts = base.get("counts", {})
        for code, sev in cur["codes"].items():
            if code not in bcodes:
                news.append(f"{kid}: NEW code {code} ({sev})")
            elif _severity_rank(sev) > _severity_rank(bcodes[code]):
                news.append(f"{kid}: {code} escalated "
                            f"{bcodes[code]} -> {sev}")
            elif cur["counts"].get(code, 0) > int(bcounts.get(code, 0)):
                news.append(f"{kid}: {code} count grew "
                            f"{bcounts.get(code, 0)} -> "
                            f"{cur['counts'][code]}")
    return news


def _kernels_main(args, analysis, config) -> int:
    """--kernels mode: the Pallas kernel verifier over shipped kernel
    targets (positionals are KERNEL TARGET names, not bench targets)."""
    import time

    from paddle_tpu.analysis import kernellint

    t0 = time.perf_counter()
    targets = list(args.targets) or None
    fail_on = analysis.Severity[args.fail_on.upper()]
    suppress = list(args.suppress)
    options = {}
    if args.chip:
        options["kernellint_chip"] = args.chip
    try:
        reports = kernellint.analyze_kernels(
            targets, options=options, suppress=suppress, config=config)
    except ValueError as e:
        print(f"graphlint: {e}", file=sys.stderr)
        return 2
    tier_seconds = {"kernels": time.perf_counter() - t0}
    out, all_ok = {}, True
    for kid, rep in reports.items():
        ok = rep.ok(fail_on)
        all_ok &= ok
        out[kid] = dict(rep.to_json(), ok=ok)
        for f in rep.by_code("KERNEL_VMEM_FOOTPRINT"):
            out[kid]["vmem_bytes"] = int(f.data.get("vmem_bytes", 0))
            out[kid]["vmem_budget_bytes"] = int(
                f.data.get("budget_bytes", 0))
            break
        if not args.as_json:
            shown = [f for f in rep
                     if args.verbose
                     or f.severity >= analysis.Severity.WARNING]
            vm = out[kid].get("vmem_bytes")
            vm_s = f", vmem {vm / (1 << 10):.0f} KiB" if vm else ""
            print(f"== {kid}: {'clean' if ok else 'FINDINGS'} "
                  f"({rep.counts()}, {rep.suppressed} suppressed{vm_s})")
            for f in shown:
                print(f"   {f}")
    snap = _kernels_snapshot(reports)
    if args.write_baseline:
        _write_baseline_doc(args.write_baseline, kernels=snap)
        if not args.as_json:
            print(f"graphlint: kernels baseline written to "
                  f"{args.write_baseline}")
    if args.baseline:
        baseline = _load_baseline(args.baseline)
        news = _kernels_diff(snap, baseline)
        if args.as_json:
            print(json.dumps({"kernels": out, "new_vs_baseline": news,
                              "tier_seconds": tier_seconds,
                              "ok": not news}))
        else:
            for n in news:
                print(f"baseline: {n}")
            print(f"graphlint: "
                  f"{'no new kernellint findings' if not news else f'{len(news)} NEW kernellint finding(s)'} "
                  f"vs {args.baseline}")
        return 1 if news else 0
    if args.as_json:
        counts = {k: out[k]["counts"] for k in out}
        print(json.dumps({"kernels": out, "counts": counts,
                          "tier_seconds": tier_seconds, "ok": all_ok}))
    elif all_ok:
        print(f"graphlint: {len(reports)} kernel(s) clean at "
              f">={args.fail_on}")
    return 0 if all_ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="lint the shipped bench models with paddle_tpu.analysis")
    ap.add_argument("targets", nargs="*", default=[],
                    help="bench targets (default: all); with --threads: "
                         "module names (default: the serving stack)")
    ap.add_argument("--threads", action="store_true",
                    help="run the lock-discipline tier "
                         "(analysis.threadlint) over serving MODULES "
                         "instead of linting bench models")
    ap.add_argument("--kernels", action="store_true",
                    help="run the Pallas kernel verifier "
                         "(analysis.kernellint) over shipped KERNEL "
                         "targets instead of linting bench models")
    ap.add_argument("--chip", default=None, metavar="KIND",
                    help="with --kernels: chip kind for the VMEM "
                         "budget (v3/v4/v5e/v5p/v6e; default v5e)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON object instead of text")
    ap.add_argument("--verbose", action="store_true",
                    help="also print INFO findings")
    ap.add_argument("--suppress", action="append", default=[],
                    metavar="CODE[@pathglob]",
                    help="suppress a finding code (repeatable)")
    ap.add_argument("--fail-on", default="warning",
                    choices=["info", "warning", "error"],
                    help="lowest severity that fails the lint")
    ap.add_argument("--fix", action="store_true",
                    help="print patch suggestions for fixable findings")
    ap.add_argument("--apply", action="store_true",
                    help="with --fix: run the VERIFIED rewrite tier over "
                         "each target (dry run on the traced jaxpr) and "
                         "report per-pass eqn/static-cost deltas; a "
                         "rewrite that fails verification rolls back AND "
                         "fails the run")
    ap.add_argument("--mesh", default=None, metavar="SPEC",
                    help="run the train targets under a named mesh and "
                         "enable the SPMD propagation tier, e.g. "
                         "'dp=2,tp=4' or 'data=2,model=2' (forces the "
                         "host-platform device count when jax is not "
                         "yet initialized)")
    ap.add_argument("--no-hlo", action="store_true",
                    help="skip the HLO tier (no lowering/compiling)")
    ap.add_argument("--config", default=None, metavar="RC",
                    help=".graphlintrc path (default: repo root)")
    ap.add_argument("--baseline", default=None, metavar="B.json",
                    help="diff mode: fail only on NEW codes vs snapshot")
    ap.add_argument("--write-baseline", default=None, metavar="B.json",
                    help="store the current findings as the snapshot")
    args = ap.parse_args(argv)

    if not args.threads and not args.kernels:
        bad = sorted(set(args.targets) - set(TARGETS))
        if bad:
            ap.error(f"unknown target(s) {', '.join(bad)} (choose from "
                     f"{', '.join(TARGETS)}; module names need "
                     "--threads, kernel targets need --kernels)")

    global MESH_SIZES
    MESH_SIZES = None
    if args.mesh:
        sizes = _parse_mesh(args.mesh)
        need = _mesh_devices(sizes)
        if "jax" not in sys.modules:
            flags = os.environ.get("XLA_FLAGS", "")
            if "--xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count="
                    f"{max(need, 8)}").strip()
        import jax
        if len(jax.devices()) < need:
            print(f"graphlint: --mesh {args.mesh} needs {need} devices, "
                  f"jax sees {len(jax.devices())} (set XLA_FLAGS "
                  "--xla_force_host_platform_device_count before jax "
                  "initializes)", file=sys.stderr)
            return 2
        MESH_SIZES = sizes

    from paddle_tpu import analysis

    rc_path = args.config or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".graphlintrc")
    config = analysis.load_rcfile(rc_path) if os.path.isfile(rc_path) \
        else None

    if args.threads:
        return _threads_main(args, analysis, config)
    if args.kernels:
        return _kernels_main(args, analysis, config)

    if args.apply:
        args.fix = True
    fail_on = analysis.Severity[args.fail_on.upper()]
    suppress = list(SHIPPED_SUPPRESSIONS) + list(args.suppress)
    names = list(args.targets) or list(TARGETS)
    out, mem_peaks, all_ok, apply_ok = {}, {}, True, True
    # per-tier wall time (satellite of the kernellint PR): CI reads
    # tier_seconds from the --json report to see WHICH tier regressed
    # when the lint step slows down
    tier_seconds: dict = {}

    def _tick(bucket, t0):
        tier_seconds[bucket] = (tier_seconds.get(bucket, 0.0)
                                + time.perf_counter() - t0)

    for name in names:
        fn, call_args, extra = TARGETS[name]()
        t0 = time.perf_counter()
        report = analysis.analyze(
            fn, *call_args, suppress=suppress, mesh=extra.get("mesh"),
            probe_args=extra.get("probe_args"),
            options=extra.get("options"), config=config)
        _tick("spmd" if extra.get("mesh") is not None else "jaxpr", t0)
        if not args.no_hlo:
            t0 = time.perf_counter()
            report = analysis.merge_reports(report, analysis.analyze_hlo(
                fn, *call_args, suppress=suppress,
                options=extra.get("options"), config=config))
            _tick("hlo", t0)
        ok = report.ok(fail_on)
        all_ok &= ok
        # jaxpr-tier static memory peak (the attributable estimate; the
        # HLO tier's MEM_PEAK carries the compiled ground truth)
        for f in report.by_code("MEM_PEAK"):
            if f.checker == "memory":
                mem_peaks[name] = int(f.data.get("peak_bytes", 0))
                break
        out[name] = dict(report.to_json(), ok=ok,
                         mem_peak_bytes=mem_peaks.get(name))
        spmd_sum = _spmd_summary(report)
        if spmd_sum is not None:
            out[name]["spmd"] = spmd_sum
        patches = analysis.fixes.suggest_fixes(report) if args.fix else []
        if args.fix:
            out[name]["fixes"] = [p.to_dict() for p in patches]
        rw = None
        if args.apply:
            # the rewrite tier, gated by the equivalence harness: grads
            # are skipped here for CLI budget (tests/test_rewrite.py
            # covers grad equivalence per pass); a rollback = regression
            t0 = time.perf_counter()
            _newfn, rw = analysis.rewrite(
                fn, *call_args, report=report, mesh=extra.get("mesh"),
                options=extra.get("options"), suppress=suppress,
                config=config, verify_grads=False)
            _tick("rewrite", t0)
            apply_ok &= rw.ok
            out[name]["rewrite"] = rw.to_json()
        if not args.as_json:
            shown = [f for f in report
                     if args.verbose or f.severity >= analysis.Severity.WARNING]
            print(f"== {name}: {'clean' if ok else 'FINDINGS'} "
                  f"({report.counts()}, {report.suppressed} suppressed)")
            for f in shown:
                print(f"   {f}")
            if spmd_sum is not None:
                print(f"-- spmd [{name}]: mesh {spmd_sum['mesh']}, "
                      f"{spmd_sum['n_eqns']} eqn(s) annotated, "
                      f"{spmd_sum['reshard_count']} reshard(s), "
                      f"{spmd_sum['n_collectives']} collective(s), "
                      f"{spmd_sum['bound']}-bound "
                      f"(comm ~{spmd_sum['t_comm_ms']:.3g} ms vs compute "
                      f"~{spmd_sum['t_compute_ms']:.3g} ms on "
                      f"{spmd_sum['chip']})")
                if args.verbose:
                    for row in spmd_sum["rows"]:
                        print(f"     {row['path']}: "
                              f"{', '.join(row['out_specs'])}")
            if patches:
                print(analysis.fixes.format_patches(patches))
            if rw is not None:
                print(f"-- rewrite [{name}]: "
                      f"{'ok' if rw.ok else 'VERIFICATION REGRESSED'}")
                print("   " + str(rw).replace("\n", "\n   "))

    snap = _baseline_snapshot(out)
    if args.write_baseline:
        _write_baseline_doc(args.write_baseline, targets=snap,
                            mesh=args.mesh or None)
        if not args.as_json:
            print(f"graphlint: baseline written to {args.write_baseline}")
    if args.baseline:
        baseline = _load_baseline(args.baseline)
        news = _baseline_diff(snap, baseline)
        if args.as_json:
            print(json.dumps({"targets": out, "new_vs_baseline": news,
                              "tier_seconds": tier_seconds,
                              "ok": not news and apply_ok}))
        else:
            for n in news:
                print(f"baseline: {n}")
            print(f"graphlint: {'no new codes' if not news else f'{len(news)} NEW finding code(s)'} vs {args.baseline}")
            if not apply_ok:
                print("graphlint: rewrite verification REGRESSED "
                      "(see rollbacks above)")
        return 1 if (news or not apply_ok) else 0

    if args.as_json:
        counts = {k: out[k]["counts"] for k in out}
        print(json.dumps({"targets": out, "counts": counts,
                          "mem_peak_bytes": mem_peaks,
                          "tier_seconds": tier_seconds,
                          "ok": all_ok and apply_ok}))
    elif all_ok and apply_ok:
        print(f"graphlint: all {len(names)} target(s) clean at "
              f">={args.fail_on}"
              + (" (rewrite tier verified)" if args.apply else ""))
    elif not apply_ok:
        print("graphlint: rewrite verification REGRESSED")
    return 0 if (all_ok and apply_ok) else 1


if __name__ == "__main__":
    sys.exit(main())
