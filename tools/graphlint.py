#!/usr/bin/env python
"""graphlint — run the Graph Doctor (paddle_tpu.analysis) over the shipped
bench models end to end.

Targets (default: all):
  llama              ShardedTrainState train step, LlamaConfig.tiny
  moe_llama_gmm      MoE train step, dropless Pallas grouped-matmul dispatch
  moe_llama_scatter  MoE train step, capacity-based scatter dispatch
  generate_paged     paged-KV single-shot generation (prefill + decode scan)
  engine_decode      LLMEngine's jitted continuous-batching decode step
  engine_prefill     LLMEngine's jitted admission prefill
  engine_swap_out    LLMEngine's preemption page-gather (KV -> host)
  engine_swap_in     LLMEngine's resume page-scatter (host -> fresh pages)

Usage:
  python tools/graphlint.py [targets...] [--json] [--verbose]
                            [--suppress CODE[@pathglob]]... [--fail-on LEVEL]

Exit code is 0 when every target is clean at --fail-on (default: warning)
after suppressions, 1 otherwise.  --json emits one machine-readable object
(finding lists + counts per target) so BENCH rounds can track finding
counts alongside perf numbers.

Suppression syntax (same as analysis.analyze(suppress=...)):
  DTYPE_F64_PROMOTION          exact code
  DTYPE_*                      code glob
  DEAD_CODE@*scan/body*        code scoped to eqn paths matching the glob
"""

from __future__ import annotations

import argparse
import functools
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _train_target(model_name, **cfg_overrides):
    import dataclasses
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import llama, moe_llama
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.distributed.parallelize import ShardedTrainState
    from paddle_tpu.optimizer.functional import AdamW

    model = {"llama": llama, "moe_llama": moe_llama}[model_name]
    cfg = (llama.LlamaConfig.tiny() if model_name == "llama"
           else moe_llama.MoELlamaConfig.tiny())
    if cfg_overrides:
        cfg = dataclasses.replace(cfg, **cfg_overrides)
    mesh = mesh_lib.make_mesh(data=1)
    st = ShardedTrainState(cfg, model, mesh,
                           AdamW(learning_rate=1e-4, grad_clip_norm=1.0))
    params, opt_state = st.init(jax.random.PRNGKey(0))
    toks = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 17))
    batch = st.shard_batch(model.lm_batch_from_tokens(
        jnp.asarray(toks, jnp.int32)))
    return st.jitted_step(batch), (params, opt_state, batch), {"mesh": mesh}


def target_llama():
    return _train_target("llama")


def target_moe_llama_gmm():
    return _train_target("moe_llama", moe_dispatch="gmm")


def target_moe_llama_scatter():
    return _train_target("moe_llama", moe_dispatch="scatter")


def _tiny_llama():
    import jax
    from paddle_tpu.models import llama
    cfg = llama.LlamaConfig.tiny()
    return cfg, llama.init_params(cfg, jax.random.PRNGKey(0))


def target_generate_paged():
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import generation

    cfg, params = _tiny_llama()
    B, S, new, ps = 2, 8, 4, 4
    total = S + new
    pps = -(-total // ps)
    cache = generation.PagedKVCache(cfg, num_pages=1 + B * pps, page_size=ps,
                                    max_slots=B, pages_per_seq=pps)
    for _ in range(B):
        cache.ensure_capacity(cache.acquire_slot(), total)
    fn = functools.partial(
        generation._generate_paged_core, config=cfg, max_new_tokens=new,
        temperature=0.0, top_k=0, top_p=1.0, eos_id=None)
    ids = jnp.zeros((B, S), jnp.int32)
    args = (params, ids, cache.pools["k"], cache.pools["v"],
            cache.page_table, jax.random.PRNGKey(0))
    return fn, args, {}


def _engine():
    from paddle_tpu.inference import LLMEngine
    cfg, params = _tiny_llama()
    return LLMEngine(params, cfg, num_slots=2, page_size=4, max_seq_len=16), \
        params


def target_engine_decode():
    import jax.numpy as jnp
    eng, params = _engine()
    toks = jnp.zeros((2,), jnp.int32)
    ctx = jnp.zeros((2,), jnp.int32)
    args = (params, toks, ctx, eng.cache.page_table,
            eng.cache.pools["k"], eng.cache.pools["v"])
    return eng._decode, args, {}


def target_engine_prefill():
    import jax.numpy as jnp
    eng, params = _engine()
    # probe the power-of-two prompt buckets the engine compiles: distinct
    # bucket widths are EXPECTED recompiles — assert there are exactly the
    # bucketed signatures, nothing shape-polymorphic beyond them
    ids8 = jnp.zeros((1, 8), jnp.int32)
    args = (params, ids8, eng.cache.pools["k"], eng.cache.pools["v"],
            eng.cache.page_table[0][None], jnp.int32(5))
    return eng._prefill, args, {}


def target_engine_swap_out():
    # preemption swap path: gather a victim's KV pages for the host copy
    # (reads the pools — correctly NOT donated)
    import jax.numpy as jnp
    eng, params = _engine()
    idx = jnp.zeros((eng.cache.pages_per_seq,), jnp.int32)
    args = (eng.cache.pools["k"], eng.cache.pools["v"], idx)
    return eng._swap_out, args, {}


def target_engine_swap_in():
    # resume path: scatter the host KV copy back into fresh pages (the
    # pools are donated, like the decode step)
    import jax
    import jax.numpy as jnp
    eng, params = _engine()
    pool = eng.cache.pools["k"]
    idx = jnp.zeros((eng.cache.pages_per_seq,), jnp.int32)
    host = jax.ShapeDtypeStruct(
        (pool.shape[0], eng.cache.pages_per_seq) + pool.shape[2:],
        pool.dtype)
    args = (eng.cache.pools["k"], eng.cache.pools["v"], idx, host, host)
    return eng._swap_in, args, {}


TARGETS = {
    "llama": target_llama,
    "moe_llama_gmm": target_moe_llama_gmm,
    "moe_llama_scatter": target_moe_llama_scatter,
    "generate_paged": target_generate_paged,
    "engine_decode": target_engine_decode,
    "engine_prefill": target_engine_prefill,
    "engine_swap_out": target_engine_swap_out,
    "engine_swap_in": target_engine_swap_in,
}

# documented suppressions for the shipped models (none today: dead
# AD-partial-eval residue lints as INFO, below the warning gate).  Add
# entries as "CODE@pathglob" with a comment justifying each.
SHIPPED_SUPPRESSIONS: tuple = ()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="lint the shipped bench models with paddle_tpu.analysis")
    ap.add_argument("targets", nargs="*", choices=[[], *TARGETS],
                    default=[], help="targets (default: all)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON object instead of text")
    ap.add_argument("--verbose", action="store_true",
                    help="also print INFO findings")
    ap.add_argument("--suppress", action="append", default=[],
                    metavar="CODE[@pathglob]",
                    help="suppress a finding code (repeatable)")
    ap.add_argument("--fail-on", default="warning",
                    choices=["info", "warning", "error"],
                    help="lowest severity that fails the lint")
    args = ap.parse_args(argv)

    from paddle_tpu import analysis

    fail_on = analysis.Severity[args.fail_on.upper()]
    suppress = list(SHIPPED_SUPPRESSIONS) + list(args.suppress)
    names = list(args.targets) or list(TARGETS)
    out, all_ok = {}, True
    for name in names:
        fn, call_args, extra = TARGETS[name]()
        report = analysis.analyze(fn, *call_args, suppress=suppress,
                                  mesh=extra.get("mesh"))
        ok = report.ok(fail_on)
        all_ok &= ok
        out[name] = dict(report.to_json(), ok=ok)
        if not args.as_json:
            shown = [f for f in report
                     if args.verbose or f.severity >= analysis.Severity.WARNING]
            print(f"== {name}: {'clean' if ok else 'FINDINGS'} "
                  f"({report.counts()}, {report.suppressed} suppressed)")
            for f in shown:
                print(f"   {f}")
    if args.as_json:
        counts = {k: out[k]["counts"] for k in out}
        print(json.dumps({"targets": out, "counts": counts, "ok": all_ok}))
    elif all_ok:
        print(f"graphlint: all {len(names)} target(s) clean at "
              f">={args.fail_on}")
    return 0 if all_ok else 1


if __name__ == "__main__":
    sys.exit(main())
