#!/usr/bin/env python
"""trace_summary — per-span time/percentile table from an exported trace.

Consumes the Chrome/Perfetto JSON the obs tracer writes (engine spans via
`LLMEngine(tracer=...)`, training spans via the hapi ObsCallback /
`examples/train_llama.py --trace`, profiler spans via
`profiler.export_chrome_tracing`) and prints count / total / mean / p50 /
p90 / p99 / max per span name, heaviest total first.

Usage:
  python tools/trace_summary.py TRACE.json [--unit ms|us|s] [--json]
          [--top N]

--json emits the aggregate as one machine-readable object instead of the
table (same shape as paddle_tpu.obs.summarize)."""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-span summary table of an exported chrome trace")
    ap.add_argument("trace", help="trace JSON written by "
                    "Tracer.export_chrome / export_chrome_tracing")
    ap.add_argument("--unit", default="ms", choices=["s", "ms", "us"])
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON object instead of the table")
    ap.add_argument("--top", type=int, default=None, metavar="N",
                    help="only the N heaviest span names by total time")
    args = ap.parse_args(argv)

    from paddle_tpu.obs import trace as obs_trace

    summary = obs_trace.summarize(args.trace)
    if args.top is not None:
        keep = sorted(summary, key=lambda k: -summary[k]["total_s"])
        summary = {k: summary[k] for k in keep[: args.top]}
    if args.as_json:
        print(json.dumps(summary, sort_keys=True))
    elif not summary:
        print("no complete spans in trace (nothing recorded, or only "
              "instant events)")
    else:
        print(obs_trace.format_summary(summary, time_unit=args.unit))
    return 0


if __name__ == "__main__":
    sys.exit(main())
