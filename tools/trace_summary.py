#!/usr/bin/env python
"""trace_summary — span/replica/request breakdowns of exported traces.

Consumes the Chrome/Perfetto JSON the obs tracer writes — a single
export (`Tracer.export_chrome`), SEVERAL of them (one per replica), or
one merged fleet trace (`obs.trace.export_merged`, which carries a
process track per replica plus request flow events) — and prints:

  * the per-span time/percentile table (count / total / mean / p50 /
    p90 / p99 / max, heaviest total first) — the default;
  * `--by-replica`: one table per replica (process tracks in a merged
    trace; one file = one replica when several files are given);
  * `--requests`: the per-request breakdown from the request lifecycle
    events a merged export embeds (id, hop count, replicas visited,
    event count, wall duration);
  * `--request ID`: one request's full timeline, event by event;
  * `--counters`: the counter-track table (`ph:"C"` events the engine
    emits for its pool/queue/batch gauges): min / max / last / samples
    per counter series per replica.

Usage:
  python tools/trace_summary.py TRACE.json [MORE.json ...]
          [--unit ms|us|s] [--json] [--top N]
          [--by-replica] [--requests] [--request ID] [--counters]

--json emits the chosen aggregate as one machine-readable object."""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _load_many(paths):
    """Load several traces into one event list.  Each file's pids are
    namespaced (pid -> (file_index, pid)) so two single-replica exports
    from the same process never collide; process_name metadata (merged
    traces) or the file basename names each track."""
    from paddle_tpu.obs import trace as obs_trace

    events = []
    names = {}           # (file_idx, pid) -> replica/track name
    for fi, path in enumerate(paths):
        default = os.path.splitext(os.path.basename(path))[0]
        for e in obs_trace.load_trace(path):
            key = (fi, e.get("pid", 0))
            if e.get("ph") == "M" and e.get("name") == "process_name":
                names[key] = e["args"]["name"]
                continue
            names.setdefault(key, default)
            ev = dict(e)
            ev["_track"] = key
            events.append(ev)
    return events, names


def _requests_index(events, names):
    """Per-request breakdown from the lifecycle events a merged export
    embeds (cat="req" instants carrying args.req)."""
    reqs = {}
    for e in events:
        if e.get("cat") != "req" or e.get("ph") != "X":
            continue
        rid = (e.get("args") or {}).get("req")
        if rid is None:
            continue
        r = reqs.setdefault(rid, {"events": []})
        r["events"].append(e)
    out = {}
    for rid, r in reqs.items():
        evs = sorted(r["events"], key=lambda e: e["ts"])
        replicas = []
        hops = set()
        for e in evs:
            name = names.get(e["_track"], str(e.get("pid")))
            if name not in replicas:
                replicas.append(name)
            hop = (e.get("args") or {}).get("hop")
            if hop is not None:
                hops.add(int(hop))
        out[rid] = {
            "events": len(evs),
            "replicas": replicas,
            "hops": len(hops) if hops else 1,
            "first": evs[0]["name"],
            "last": evs[-1]["name"],
            "duration_s": (evs[-1]["ts"] - evs[0]["ts"]) * 1e-6,
            "timeline": [{"t_s": e["ts"] * 1e-6, "name": e["name"],
                          "track": names.get(e["_track"],
                                             str(e.get("pid"))),
                          "args": {k: v for k, v in
                                   (e.get("args") or {}).items()
                                   if k != "req"}}
                         for e in evs],
        }
    return out


def counters_index(events, names):
    """Counter-track aggregate over `ph:"C"` events: {replica: {counter:
    {series: {n, min, max, last}}}}.  `last` follows the latest ts, so
    "did free_pages read back to baseline by trace end" is one lookup."""
    out = {}
    last_ts = {}
    for e in events:
        if e.get("ph") != "C":
            continue
        track = names.get(e.get("_track"), str(e.get("pid")))
        for series, v in (e.get("args") or {}).items():
            try:
                v = float(v)
            except (TypeError, ValueError):
                continue
            key = (track, e["name"], series)
            s = out.setdefault(track, {}).setdefault(
                e["name"], {}).setdefault(
                series, {"n": 0, "min": v, "max": v, "last": v})
            s["n"] += 1
            s["min"] = min(s["min"], v)
            s["max"] = max(s["max"], v)
            if e["ts"] >= last_ts.get(key, float("-inf")):
                s["last"] = v
                last_ts[key] = e["ts"]
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="span/replica/request summary of exported traces")
    ap.add_argument("traces", nargs="+", metavar="TRACE",
                    help="trace JSON written by Tracer.export_chrome / "
                         "export_merged / export_chrome_tracing; several "
                         "files merge (one replica per file)")
    ap.add_argument("--unit", default="ms", choices=["s", "ms", "us"])
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit one JSON object instead of the table")
    ap.add_argument("--top", type=int, default=None, metavar="N",
                    help="only the N heaviest span names by total time")
    ap.add_argument("--by-replica", action="store_true",
                    help="one span table per replica/process track")
    ap.add_argument("--requests", action="store_true", dest="by_request",
                    help="per-request breakdown (merged fleet traces)")
    ap.add_argument("--request", default=None, metavar="ID",
                    help="print one request's full timeline")
    ap.add_argument("--counters", action="store_true", dest="by_counter",
                    help="counter-track table (min/max/last per counter "
                         "series per replica)")
    args = ap.parse_args(argv)

    from paddle_tpu.obs import trace as obs_trace

    events, names = _load_many(args.traces)

    if args.by_counter:
        idx = counters_index(events, names)
        if args.as_json:
            print(json.dumps(idx, sort_keys=True))
            return 0
        if not idx:
            print("no counter events in trace (the engine emits ph:\"C\" "
                  "samples for its pool/queue/batch gauges each step "
                  "while its tracer is enabled)")
            return 0
        print(f"{'replica':>12}  {'counter':22}  {'series':10}  "
              f"{'n':>6}  {'min':>10}  {'max':>10}  {'last':>10}")
        for track in sorted(idx):
            for counter in sorted(idx[track]):
                for series, s in sorted(idx[track][counter].items()):
                    print(f"{track[:12]:>12}  {counter[:22]:22}  "
                          f"{series[:10]:10}  {s['n']:>6}  "
                          f"{s['min']:>10g}  {s['max']:>10g}  "
                          f"{s['last']:>10g}")
        return 0

    if args.request is not None or args.by_request:
        reqs = _requests_index(events, names)
        if args.request is not None:
            r = reqs.get(args.request)
            if r is None:
                print(f"no request {args.request!r} in "
                      f"{', '.join(args.traces)} (known: "
                      f"{sorted(reqs) if reqs else 'none'})")
                return 1
            if args.as_json:
                print(json.dumps({args.request: r}, sort_keys=True))
                return 0
            print(f"request {args.request}: {r['events']} events, "
                  f"{r['hops']} hop(s), replicas "
                  f"{' -> '.join(r['replicas'])}, "
                  f"{r['duration_s'] * 1e3:.3f} ms")
            t0 = r["timeline"][0]["t_s"]
            for e in r["timeline"]:
                extra = (" " + json.dumps(e["args"], sort_keys=True)
                         if e["args"] else "")
                print(f"  +{(e['t_s'] - t0) * 1e3:10.3f} ms  "
                      f"[{e['track']:>12}] {e['name']}{extra}")
            return 0
        if args.as_json:
            slim = {rid: {k: v for k, v in r.items() if k != "timeline"}
                    for rid, r in reqs.items()}
            print(json.dumps(slim, sort_keys=True))
            return 0
        if not reqs:
            print("no request events in trace (export_merged with a "
                  "RequestRegistry embeds them)")
            return 0
        print(f"{'request':18}  {'hops':>4}  {'events':>6}  "
              f"{'dur(ms)':>10}  journey")
        for rid, r in sorted(reqs.items(),
                             key=lambda kv: -kv[1]["duration_s"]):
            print(f"{rid[:18]:18}  {r['hops']:>4}  {r['events']:>6}  "
                  f"{r['duration_s'] * 1e3:>10.3f}  "
                  f"{' -> '.join(r['replicas'])}")
        return 0

    span_events = [e for e in events if e.get("cat") != "req"]
    if args.by_replica:
        groups = {}
        for e in span_events:
            groups.setdefault(names.get(e["_track"],
                                        str(e.get("pid"))), []).append(e)
        out = {}
        for name in sorted(groups):
            summary = obs_trace.summarize(groups[name])
            if args.top is not None:
                keep = sorted(summary,
                              key=lambda k: -summary[k]["total_s"])
                summary = {k: summary[k] for k in keep[: args.top]}
            out[name] = summary
        if args.as_json:
            print(json.dumps(out, sort_keys=True))
            return 0
        for name, summary in out.items():
            print(f"== {name} ==")
            if summary:
                print(obs_trace.format_summary(summary,
                                               time_unit=args.unit))
            else:
                print("(no complete spans)")
            print()
        return 0

    summary = obs_trace.summarize(span_events)
    if args.top is not None:
        keep = sorted(summary, key=lambda k: -summary[k]["total_s"])
        summary = {k: summary[k] for k in keep[: args.top]}
    if args.as_json:
        print(json.dumps(summary, sort_keys=True))
    elif not summary:
        print("no complete spans in trace (nothing recorded, or only "
              "instant events)")
    else:
        print(obs_trace.format_summary(summary, time_unit=args.unit))
    return 0


if __name__ == "__main__":
    sys.exit(main())
