#!/usr/bin/env python
"""Fleet chaos soak + router micro-bench for the replica Router.

Runs seeded random FLEET fault schedules (paddle_tpu.inference.faults.
fleet_random_schedule) against an N-replica fleet of ScriptedEngines —
the real LLMEngine scheduler with scripted compute, so replica deaths
(crashed step threads), health flaps, stale stats and slow score reads
all execute at chaos-suite speed — and asserts the fleet invariants
after every schedule: every request resolved exactly once fleet-wide,
retried outputs token-exact vs a single healthy engine, zero leaked
pages/slots per live replica, fleet still serving a fresh probe.

Usage:
    python tools/chaos_fleet.py                    # 25 schedules, seed 0
    python tools/chaos_fleet.py --schedules 200 --replicas 3
    python tools/chaos_fleet.py --threaded         # background-thread mode
    python tools/chaos_fleet.py --flight-dir /tmp/flight  # black-box armed:
                                                   # every replica death must
                                                   # leave a loadable dump
    python tools/chaos_fleet.py --disagg           # disaggregated fleet:
                                                   # replica 0 prefill-class,
                                                   # rest decode-class, shared
                                                   # tiered prefix store; the
                                                   # kv_transfer fault point
                                                   # fires on real handoffs
    python tools/chaos_fleet.py --bench --json     # router micro-bench
                                                   # (bench.py extra.router)

--bench measures the two numbers the roadmap's fleet item is judged by:
placement overhead per submit (score + hop placement, no model compute)
and failover-to-first-token latency under an injected replica death
(submit -> death mid-prefill -> health tick detects -> retry on the
surviving replica -> token), against the no-death baseline.

Exit code 1 when any schedule violates a fleet invariant.  CPU-only.
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _percentiles(samples):
    from paddle_tpu.obs import metrics as obs_metrics

    return {"p50": obs_metrics.percentile(samples, 0.5),
            "p99": obs_metrics.percentile(samples, 0.99),
            "n": len(samples)}


def run_bench(trials: int = 15, prefill_chunk: int = 6) -> dict:
    """Router micro-bench on 2-replica scripted fleets."""
    from paddle_tpu.inference import faults as F
    from paddle_tpu.inference.router import Router
    from paddle_tpu.inference.supervisor import EngineSupervisor

    def mk():
        return F.ScriptedEngine(num_slots=2, page_size=4, max_seq_len=16,
                                prefill_chunk_tokens=prefill_chunk,
                                block_q=2)

    # placement overhead: N submits through the scoring path (manual
    # mode, drained between batches so queues stay comparable)
    router = Router(factory=mk, num_replicas=2, threaded=False)
    for batch in range(20):
        hs = [router.submit([1, 2, batch], 1) for _ in range(10)]
        F.drive_fleet(router, hs, settle=False)
    placement = router.metrics.get("fleet_placement_seconds").samples()
    router.shutdown()
    place_us = {k: (round(v * 1e6, 2) if k != "n" else v)
                for k, v in _percentiles(placement).items()}

    # failover TTFT: threaded fleet, replica 0 dies mid-prefill of the
    # measured request; the health tick must detect, retry on replica 1,
    # and deliver.  Baseline: same fleet shape, no death.
    def one_trial(inject_death: bool) -> float:
        engines = [mk(), mk()]
        if inject_death:
            engines[0].faults = F.FaultInjector(
                [F.FaultRule("prefill", nth=1, crash=True)])
        router = Router(engines, supervisor=EngineSupervisor(mk),
                        threaded=True, health_interval=0.005,
                        backoff_base=0.02)
        try:
            t0 = time.monotonic()
            h = router.submit([1, 2, 3], 1)
            h.result(timeout=60)
            dt = time.monotonic() - t0
            if inject_death:
                assert h.hops == [0, 1], h.hops
            return dt
        finally:
            router.shutdown(timeout=10)

    baseline = sorted(one_trial(False) for _ in range(trials))
    failover = sorted(one_trial(True) for _ in range(trials))
    return {
        "placement_overhead_us": place_us,
        "baseline_first_token_s": round(_percentiles(baseline)["p50"], 5),
        "failover_first_token_s": round(_percentiles(failover)["p50"], 5),
        "failover_trials": trials,
    }


def run_hostile_tenants(args) -> dict:
    """Hostile-tenant tier: a flooding low-priority tenant (bursts of
    `--flood-factor` bulk requests, its per-tenant queue cap turning the
    excess into typed FleetQueueFull backpressure) against a paced
    high-priority gold tenant, on a threaded fleet that loses replica 0
    mid-mix (EngineSupervisor rebuilds it).  The verdict: gold's p99
    TTFT and inter-token latency — read from the per-tenant SLO windows
    the engines already keep, never re-derived — must stay under the
    `--hipri-*-bound` limits, every handle must resolve exactly once
    token-exact, and no per-tenant counter may drift from the allocator
    ground truth (fleet_check_invariants arms those identities on every
    live replica)."""
    import numpy as np

    from paddle_tpu.inference import faults as F
    from paddle_tpu.inference.router import FleetQueueFull, Router
    from paddle_tpu.inference.supervisor import EngineSupervisor
    from paddle_tpu.obs import metrics as obs_metrics

    tenant_table = {
        "gold": {"priority": 0, "weight": 4.0},
        "bulk": {"priority": 3, "weight": 1.0,
                 "max_pending": max(2, args.flood_factor // 2)},
    }

    def mk():
        return F.ScriptedEngine(num_slots=2, page_size=4, max_seq_len=16,
                                prefill_chunk_tokens=args.prefill_chunk,
                                block_q=2, tenants=tenant_table)

    def ref(h):
        return F.ScriptedEngine.reference_tokens(
            h.prompt, h.max_new_tokens, h.eos_id)

    rng = np.random.default_rng(args.seed)
    engines = [mk() for _ in range(max(2, args.replicas))]
    # the fault schedule: replica 0 crashes partway through the mix, so
    # gold's latency bound holds ACROSS a death+rebuild, not just in
    # steady state
    engines[0].faults = F.FaultInjector(
        [F.FaultRule("prefill", nth=10, crash=True)])
    router = Router(engines, supervisor=EngineSupervisor(mk),
                    threaded=True, health_interval=0.01,
                    backoff_base=0.05)
    handles, rejected = [], 0
    violations = []
    try:
        for _ in range(args.bursts):
            for _ in range(args.flood_factor):
                prompt = rng.integers(
                    0, F.ScriptedEngine.DEFAULT_VOCAB,
                    int(rng.integers(2, 9))).tolist()
                try:
                    handles.append(router.submit(
                        prompt, int(rng.integers(2, 7)), tenant="bulk"))
                except FleetQueueFull:
                    rejected += 1   # the cap working, not a failure
            prompt = rng.integers(0, F.ScriptedEngine.DEFAULT_VOCAB,
                                  int(rng.integers(2, 9))).tolist()
            handles.append(router.submit(
                prompt, int(rng.integers(2, 7)), tenant="gold"))
            time.sleep(args.pace)
        for h in handles:
            try:
                h.result(timeout=120)
            except Exception:  # noqa: BLE001 — terminal typed errors
                pass           # (death mid-decode) are legal outcomes;
                               # exactly-once is checked below
        # gold latency verdict from the per-tenant SLO windows
        ttft, itl = [], []
        for r in router.replicas:
            if r.dead:
                continue
            slo = getattr(r.engine, "_tenant_slo", {}).get("gold")
            if slo is None:
                continue
            ttft.extend(v for _, v in slo._samples.get("ttft", ()))
            itl.extend(v for _, v in slo._samples.get("inter_token", ()))
        p99_ttft = obs_metrics.percentile(ttft, 0.99) if ttft else 0.0
        p99_itl = obs_metrics.percentile(itl, 0.99) if itl else 0.0
        if not ttft:
            violations.append("hostile tier: no gold TTFT samples "
                              "survived — the paced tenant never ran")
        if p99_ttft > args.hipri_ttft_bound:
            violations.append(
                f"hostile tier: gold p99 TTFT {p99_ttft:.3f}s exceeds "
                f"the {args.hipri_ttft_bound}s bound under the flood")
        if p99_itl > args.hipri_itl_bound:
            violations.append(
                f"hostile tier: gold p99 ITL {p99_itl:.3f}s exceeds "
                f"the {args.hipri_itl_bound}s bound under the flood")
        # exactly-once + token-exactness + per-replica zero leaks +
        # per-tenant counter identities vs allocator ground truth
        inv = F.fleet_check_invariants(router, handles, reference=ref,
                                       raise_on_violation=False)
        violations.extend(inv["violations"])
        per_tenant = {}
        for r in router.replicas:
            if r.dead:
                continue
            for t, snap in r.engine.tenant_snapshot().items():
                agg = per_tenant.setdefault(
                    t, dict.fromkeys(snap["counters"], 0))
                for k, v in snap["counters"].items():
                    agg[k] = agg.get(k, 0) + v
        return {
            "ok": not violations,
            "violations": violations,
            "submitted": len(handles),
            "rejected_backpressure": rejected,
            "gold_p99_ttft_s": round(p99_ttft, 5),
            "gold_p99_itl_s": round(p99_itl, 5),
            "ttft_bound_s": args.hipri_ttft_bound,
            "itl_bound_s": args.hipri_itl_bound,
            "deaths": inv["stats"].get("deaths", 0),
            "rebuilds": inv["stats"].get("rebuilds", 0),
            "tenants": per_tenant,
        }
    finally:
        router.shutdown(timeout=10)


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--schedules", type=int, default=25)
    ap.add_argument("--seed", type=int, default=0,
                    help="base seed (schedule i uses seed+i)")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--requests", type=int, default=6,
                    help="requests per schedule")
    ap.add_argument("--threaded", action="store_true",
                    help="background step/health threads instead of the "
                         "deterministic pump")
    ap.add_argument("--probe-every", type=int, default=5,
                    help="run the fleet serving probe every Nth schedule")
    ap.add_argument("--bench", action="store_true",
                    help="run the router micro-bench instead of the soak")
    ap.add_argument("--prefill-chunk", type=int, default=6,
                    help="prefill_chunk_tokens for every replica engine "
                         "(small default -> multi-chunk prefills, so "
                         "replica death mid-chunk is actually exercised)")
    ap.add_argument("--disagg", action="store_true",
                    help="run the soak against a disaggregated fleet "
                         "(roles prefill=1,decode=N-1 plus a shared "
                         "TieredPrefixStore) so every multi-chunk "
                         "request crosses a real prefill->decode KV "
                         "handoff while the schedules kill replicas — "
                         "including mid-kv_transfer")
    ap.add_argument("--tenants", action="store_true",
                    help="multi-tenant QoS tier: every replica gets a "
                         "two-tier tenant table and each schedule's "
                         "workload arrives tagged ~70%%/30%% bulk/gold "
                         "(per-tenant counter identities arm inside "
                         "every invariant check); after the seeded "
                         "soak, a hostile-mix pass floods the bulk "
                         "tenant while pacing gold under an injected "
                         "replica death and FAILS if gold p99 "
                         "TTFT/ITL degrades past the bounds below or "
                         "any per-tenant counter drifts from the "
                         "allocator ground truth")
    ap.add_argument("--bursts", type=int, default=12,
                    help="hostile tier: number of flood+paced bursts")
    ap.add_argument("--flood-factor", type=int, default=10,
                    help="hostile tier: bulk requests per burst (the "
                         "flooding tenant; per-tenant caps turn the "
                         "excess into typed backpressure)")
    ap.add_argument("--pace", type=float, default=0.02,
                    help="hostile tier: sleep between gold requests "
                         "(the paced high-priority tenant)")
    ap.add_argument("--hipri-ttft-bound", type=float, default=2.0,
                    help="hostile tier: max tolerated gold p99 TTFT "
                         "seconds (CPU-generous default)")
    ap.add_argument("--hipri-itl-bound", type=float, default=1.0,
                    help="hostile tier: max tolerated gold p99 "
                         "inter-token seconds (CPU-generous default)")
    ap.add_argument("--flight-dir", default=None, metavar="DIR",
                    help="arm a flight recorder on every replica: a "
                         "replica death MUST leave a loadable dump here "
                         "or the soak fails (SIGTERM dumps too)")
    ap.add_argument("--no-witness", dest="witness", action="store_false",
                    help="disarm the fleet-wide lock-order witness "
                         "(armed by default: router + every replica "
                         "lock is wrapped under ONE witness, and an "
                         "order inversion, a lock held across a fenced "
                         "dispatch, or a thread leaked past shutdown "
                         "fails the soak)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    if args.bench:
        out = run_bench(prefill_chunk=args.prefill_chunk)
        print(json.dumps(out, indent=None if args.json else 2))
        return 0

    import glob
    import itertools

    import numpy as np

    from paddle_tpu.inference import faults as F

    recorders = []
    rec_seq = itertools.count()
    if args.flight_dir:
        from paddle_tpu.obs import flight as obs_flight

        obs_flight.install_sigterm(recorders)

    def _dumps():
        if not args.flight_dir:
            return []
        return sorted(glob.glob(os.path.join(args.flight_dir,
                                             "flight_*.json")))

    # soak-mode tenant table: two tiers, NO bulk queue cap — a capped
    # tenant would turn fleet_run_schedule's submits into FleetQueueFull
    # mid-schedule; the hostile tier below is where caps bite
    soak_tenants = {
        "gold": {"priority": 0, "weight": 4.0},
        "bulk": {"priority": 3, "weight": 1.0},
    } if args.tenants else None

    def mk():
        eng = F.ScriptedEngine(num_slots=2, page_size=4, max_seq_len=16,
                               prefill_chunk_tokens=args.prefill_chunk,
                               block_q=2, tenants=soak_tenants)
        if args.flight_dir:
            from paddle_tpu.obs import flight as obs_flight

            rec = obs_flight.FlightRecorder(
                dir=args.flight_dir, name=f"e{next(rec_seq)}")
            rec.attach_engine(eng)
            recorders.append(rec)
        return eng

    def ref(h):
        return F.ScriptedEngine.reference_tokens(
            h.prompt, h.max_new_tokens, h.eos_id)

    if args.disagg and args.replicas < 2:
        print("--disagg needs --replicas >= 2 (one prefill class, at "
              "least one decode class)", file=sys.stderr)
        return 2

    reports, violations = [], 0
    totals = {"fired": 0, "completed": 0, "failed": 0, "retried": 0,
              "deaths": 0, "rebuilds": 0, "ejections": 0,
              "handoffs": 0, "role_flips": 0, "lock_acquisitions": 0,
              "thread_leaks": 0}
    for i in range(args.schedules):
        seed = args.seed + i
        engine_rules, router_rules = F.fleet_random_schedule(
            seed, n_replicas=args.replicas)
        rng = np.random.default_rng(seed)
        workload = []
        for _ in range(args.requests):
            prompt = rng.integers(0, F.ScriptedEngine.DEFAULT_VOCAB,
                                  int(rng.integers(2, 9))).tolist()
            max_new = int(rng.integers(2, 7))
            if args.tenants:
                tenant = "bulk" if rng.random() < 0.7 else "gold"
                workload.append((prompt, max_new, {"tenant": tenant}))
            else:
                workload.append((prompt, max_new))
        router_kw = None
        if args.disagg:
            # fresh store per schedule: cross-schedule warmth would make
            # the token-exactness oracle depend on schedule ORDER
            from paddle_tpu.inference.kvstore import TieredPrefixStore

            router_kw = {"roles": f"prefill=1,decode={args.replicas - 1}",
                         "kvstore": TieredPrefixStore()}
        dumps_before = set(_dumps())
        try:
            report = F.fleet_run_schedule(
                mk, engine_rules, router_rules, workload,
                n_replicas=args.replicas, threaded=args.threaded,
                reference=ref, probe=i % args.probe_every == 0,
                router_kw=router_kw, witness=args.witness)
        except F.InvariantViolation as e:
            violations += 1
            report = {"ok": False, "violations": str(e),
                      "schedule": {
                          "engines": {r: [x.to_dict() for x in rules]
                                      for r, rules in engine_rules.items()},
                          "router": [x.to_dict() for x in router_rules]}}
        report["seed"] = seed
        # the black-box contract: every induced replica death leaves at
        # least one NEW, LOADABLE crash dump (step_thread_death from the
        # dying thread, or replica_death from the router's death tick)
        if args.flight_dir and report.get("ok") \
                and report["stats"]["deaths"] > 0:
            from paddle_tpu.obs import flight as obs_flight

            new = sorted(set(_dumps()) - dumps_before)
            crash = []
            for p in new:
                try:
                    d = obs_flight.load_dump(p)
                except Exception as e:  # noqa: BLE001 — unloadable dump
                    violations += 1
                    report["ok"] = False
                    report["violations"] = f"unloadable flight dump " \
                                           f"{p}: {e!r}"
                    break
                if d["reason"] in ("step_thread_death", "replica_death"):
                    crash.append(p)
            else:
                if not crash:
                    violations += 1
                    report["ok"] = False
                    report["violations"] = (
                        f"{report['stats']['deaths']} replica death(s) "
                        "left no loadable crash dump")
                report["flight_dumps"] = len(new)
        reports.append(report)
        if report["ok"]:
            for k in ("completed", "failed", "retried"):
                totals[k] += report[k]
            totals["fired"] += len(report["fired"])
            for k in ("deaths", "rebuilds", "ejections",
                      "handoffs", "role_flips"):
                totals[k] += report["stats"].get(k, 0)
            threads = report.get("threads", {})
            totals["thread_leaks"] += len(threads.get("leaked", ()))
            totals["lock_acquisitions"] += threads.get(
                "witness", {}).get("acquisitions", 0)
        status = "ok " if report["ok"] else "LEAK"
        line = f"[{status}] seed={seed}"
        if report["ok"]:
            line += (f" fired={len(report['fired'])}"
                     f" completed={report['completed']}"
                     f" failed={report['failed']}"
                     f" retried={report['retried']}"
                     f" deaths={report['stats']['deaths']}"
                     f" rebuilds={report['stats']['rebuilds']}")
            if args.disagg:
                line += f" handoffs={report['stats'].get('handoffs', 0)}"
        else:
            line += f" violations={report['violations']}"
        print(line)

    # end-of-soak telemetry verdict: per live replica, the pool/slot
    # gauges must have read back to baseline at quiescence and agreed
    # with faults.check_invariants (mismatches already fail the soak as
    # violations; this makes the gauge-based leak detector visible)
    telemetry_checked = sum(1 for r in reports if "telemetry" in r)
    telemetry_bad = sum(1 for r in reports
                        if r.get("telemetry")
                        and not r["telemetry"]["ok"])
    print(f"telemetry: replica gauges agreed with the invariant checker "
          f"in {telemetry_checked - telemetry_bad}/{telemetry_checked} "
          f"checked schedule(s)")
    if args.witness:
        # thread-discipline verdict: one shared witness spanned router
        # + replicas per schedule (order inversions, locks across
        # dispatch, threads leaked past shutdown already count as
        # violations above) — this line makes the coverage visible
        print(f"threads: witness observed "
              f"{totals['lock_acquisitions']} lock acquisition(s) "
              f"fleet-wide, {totals['thread_leaks']} thread leak(s) "
              "past shutdown")

    hostile = None
    if args.tenants:
        # the hostile-mix pass: flood bulk, pace gold, kill a replica —
        # gold's p99 bounds and the per-tenant drift identities are the
        # soak verdict, same exit-code contract as the schedules above
        hostile = run_hostile_tenants(args)
        if not hostile["ok"]:
            violations += len(hostile["violations"])
            for v in hostile["violations"]:
                print(f"[QOS ] {v}")
        print(f"hostile tenants: gold p99 ttft="
              f"{hostile['gold_p99_ttft_s']}s "
              f"(bound {hostile['ttft_bound_s']}s) p99 itl="
              f"{hostile['gold_p99_itl_s']}s "
              f"(bound {hostile['itl_bound_s']}s) "
              f"submitted={hostile['submitted']} "
              f"backpressured={hostile['rejected_backpressure']} "
              f"deaths={hostile['deaths']}")

    summary = {"schedules": args.schedules, "replicas": args.replicas,
               "disagg": bool(args.disagg), "violations": violations,
               "telemetry_mismatches": telemetry_bad,
               "witness_armed": bool(args.witness),
               "tenants_armed": bool(args.tenants), **totals}
    if hostile is not None:
        summary["hostile_tenants"] = hostile
    if args.json:
        print(json.dumps({"summary": summary, "reports": reports},
                         indent=2, default=str))
    else:
        print("\nfleet invariant report:", json.dumps(summary))
        print("zero losses" if violations == 0
              else f"{violations} schedule(s) VIOLATED fleet invariants")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
