"""One-shot chip tuning sweep (run manually when real TPU time is
available; bench.py stays the driver's single-line benchmark).

Usage:  python tools/bench_sweep.py [llama|dit|moe|all]

Measures, on the real chip:
  * llama: B x S grid around the headline shape (B2/S8192 was the round-3
    62.1% MFU point) to re-find the MFU peak after code drift;
  * dit:   attn impl (xla vs flash) x fused-adaLN x head layouts x batch;
  * moe:   scatter vs einsum dispatch x token counts (8k/16k/32k) x head
    layout (8x128 Mixtral-style vs 16x64 whose D=64 pads to the lane tile)
    x capacity_factor (1.0 / 1.25 / 2.0).

Prints one JSON line per point; nothing here is driver-consumed.
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
# reuse bench.py's timing loop (the float(loss) axon-completion workaround
# lives there) and its per-chip peak-FLOPs table; importing bench runs its
# backend probe once, which is exactly right for a manual chip session
import bench  # noqa: E402

STEPS = 8


def _timed(st, params, opt_state, batch):
    # fixed STEPS: every throughput formula below assumes it
    return bench._timed_steps(st, params, opt_state, batch, STEPS)


def _peak():
    return bench._peak_flops(jax.devices()[0]) or 197e12


def _emit(**kw):
    print(json.dumps(kw), flush=True)


def sweep_llama():
    from paddle_tpu.models import llama
    from paddle_tpu.models.llama import LlamaConfig
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.distributed.parallelize import ShardedTrainState
    from paddle_tpu.optimizer.functional import AdamW

    # the bench.py headline config (697M; r3 peak 62.1% MFU at B2/S8192)
    cfg = LlamaConfig(
        vocab_size=32000, hidden_size=2048, intermediate_size=5632,
        num_hidden_layers=12, num_attention_heads=16, num_key_value_heads=8,
        max_position_embeddings=16384, dtype=jnp.bfloat16, remat=True)
    mesh = mesh_lib.make_mesh(data=1)
    for B, S in ((2, 8192), (4, 4096), (2, 4096), (1, 16384), (4, 8192)):
        try:
            st = ShardedTrainState(
                dataclasses.replace(cfg, max_position_embeddings=max(S, 8192)),
                llama, mesh, AdamW(learning_rate=1e-4, grad_clip_norm=1.0))
            params, opt = st.init(jax.random.PRNGKey(0))
            toks = np.random.default_rng(0).integers(
                0, cfg.vocab_size, (B, S + 1))
            batch = st.shard_batch(llama.lm_batch_from_tokens(
                jnp.asarray(toks, jnp.int32)))
            dt, loss = _timed(st, params, opt, batch)
            tok_s = B * S * STEPS / dt
            _emit(kind="llama", B=B, S=S, tok_s=round(tok_s, 1),
                  mfu=round(llama.flops_per_token(cfg, S) * tok_s
                            / _peak(), 4), loss=loss)
        except Exception as e:  # noqa: BLE001 — OOMs expected at the edges
            _emit(kind="llama", B=B, S=S, error=repr(e)[:160])


def sweep_dit():
    from paddle_tpu.models import dit
    from paddle_tpu.models.dit import DiTConfig
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.distributed.parallelize import ShardedTrainState
    from paddle_tpu.optimizer.functional import AdamW

    mesh = mesh_lib.make_mesh(data=1)
    # r5 chip session: 9x128 + fused adaLN + attn_impl=xla + B160 won
    # (139.0 img/s, 50.2% MFU); flash attn 134.4; fused_qkv slower (125);
    # B=192 regressed, B=224 OOM
    for heads, fused, attn, B in ((9, True, "xla", 160),
                                  (9, True, "auto", 160),
                                  (9, False, "xla", 160),
                                  (16, True, "xla", 160),
                                  (9, True, "xla", 128)):
        try:
            cfg = dataclasses.replace(DiTConfig.XL_2(), num_heads=heads,
                                      fused_adaln=fused, attn_impl=attn)
            st = ShardedTrainState(cfg, dit, mesh,
                                   AdamW(learning_rate=1e-4,
                                         grad_clip_norm=1.0))
            params, opt = st.init(jax.random.PRNGKey(0))
            rng = np.random.default_rng(0)
            imgs = jnp.asarray(rng.standard_normal(
                (B, cfg.in_channels, cfg.image_size, cfg.image_size)),
                jnp.float32)
            labs = jnp.asarray(rng.integers(0, cfg.num_classes, (B,)),
                               jnp.int32)
            batch = st.shard_batch(dit.dit_batch(
                imgs, labs, jax.random.PRNGKey(1), cfg))
            dt, loss = _timed(st, params, opt, batch)
            _emit(kind="dit", heads=heads, fused_adaln=fused, attn=attn,
                  B=B, img_s=round(B * STEPS / dt, 2), loss=loss)
        except Exception as e:  # noqa: BLE001
            _emit(kind="dit", heads=heads, fused_adaln=fused, attn=attn,
                  B=B, error=repr(e)[:160])


def sweep_moe():
    from paddle_tpu.models import llama, moe_llama
    from paddle_tpu.models.moe_llama import MoELlamaConfig
    from paddle_tpu.distributed import mesh as mesh_lib
    from paddle_tpu.distributed.parallelize import ShardedTrainState
    from paddle_tpu.optimizer.functional import AdamW

    mesh = mesh_lib.make_mesh(data=1)
    # r5 chip winner: 8x128 heads (40.4k tok/s / 40.6% MFU at B2/S8192
    # scatter vs 31.8k / 32.1% for 16x64)
    base = MoELlamaConfig(
        vocab_size=32000, hidden_size=1024, intermediate_size=2816,
        num_hidden_layers=8, num_attention_heads=8, num_key_value_heads=4,
        max_position_embeddings=16384, dtype=jnp.bfloat16, remat=True,
        num_experts=8, moe_top_k=2)
    # scatter and einsum at MATCHING shapes so dispatch cost separates
    # from shape cost; the 16x64 point tracks the padded-D attention tax
    # cf: capacity_factor — 1.0 trades token drops for less padded expert
    # compute (r5 chip, UNROLLED layers: cf1.0 44.1k tok/s / 44.4% MFU,
    # cf1.25 40.6k / 40.9%, cf2.0 32.0k / 32.3%; the scan-layers numbers
    # above are ~0.5% lower); bench default stays 1.25 (GShard training
    # convention)
    for disp, B, S, hq, hkv, cf in (("einsum", 2, 4096, 8, 4, 1.25),
                                    ("scatter", 2, 4096, 8, 4, 1.25),
                                    ("einsum", 2, 8192, 8, 4, 1.25),
                                    ("scatter", 2, 8192, 8, 4, 1.25),
                                    ("scatter", 2, 8192, 16, 8, 1.25),
                                    ("scatter", 2, 8192, 8, 4, 1.0),
                                    ("scatter", 2, 8192, 8, 4, 2.0),
                                    ("scatter", 2, 16384, 8, 4, 1.25),
                                    ("scatter", 4, 8192, 8, 4, 1.25)):
        try:
            cfg = dataclasses.replace(base, moe_dispatch=disp,
                                      num_attention_heads=hq,
                                      num_key_value_heads=hkv,
                                      capacity_factor=cf)
            st = ShardedTrainState(cfg, moe_llama, mesh,
                                   AdamW(learning_rate=1e-4,
                                         grad_clip_norm=1.0))
            params, opt = st.init(jax.random.PRNGKey(0))
            toks = np.random.default_rng(0).integers(
                0, cfg.vocab_size, (B, S + 1))
            batch = st.shard_batch(llama.lm_batch_from_tokens(
                jnp.asarray(toks, jnp.int32)))
            dt, loss = _timed(st, params, opt, batch)
            tok_s = B * S * STEPS / dt
            mfu_flops = moe_llama.flops_per_token(cfg, S) * tok_s
            _emit(kind="moe", dispatch=disp, B=B, S=S, cf=cf,
                  heads=f"{hq}x{cfg.hidden_size//hq}", tok_s=round(tok_s, 1),
                  mfu=round(mfu_flops / _peak(), 4), loss=loss)
        except Exception as e:  # noqa: BLE001
            _emit(kind="moe", dispatch=disp, B=B, S=S, cf=cf,
                  heads=f"{hq}x{base.hidden_size//hq}",
                  error=repr(e)[:160])


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which not in ("llama", "dit", "moe", "all"):
        sys.exit(f"usage: python tools/bench_sweep.py [llama|dit|moe|all] "
                 f"(got {which!r})")
    if which in ("llama", "all"):
        sweep_llama()
    if which in ("dit", "all"):
        sweep_dit()
    if which in ("moe", "all"):
        sweep_moe()
