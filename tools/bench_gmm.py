"""Grouped-matmul micro-benchmark (run manually when real TPU time is
available; bench.py stays the driver's single-line benchmark).

Usage:  python tools/bench_gmm.py [N_TOKENS]

Times one MoE SwiGLU expert-FFN step (forward + backward) two ways over a
sweep of expert-imbalance ratios:

  * gmm    — the dropless Pallas grouped matmul
    (kernels/pallas_grouped_matmul.py): compute scales with the ACTUAL
    per-expert token counts.
  * padded — the capacity-padded batched einsum the einsum/scatter
    dispatch modes run: every expert pays for C = max(tokens per expert)
    rows, so imbalance inflates compute linearly (a 4x-hot expert makes
    every other expert pad 4x).

The imbalance ratio r is the hottest expert's share of all tokens
(r = 1/X is perfectly balanced; r = 1.0 routes everything to one expert).
Prints one JSON line per point; nothing here is driver-consumed.
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from paddle_tpu.kernels import pallas_grouped_matmul as pg  # noqa: E402

STEPS = 10


def _on_tpu() -> bool:
    return jax.default_backend() in ("tpu", "axon")


def _group_sizes(n_tokens: int, num_experts: int, ratio: float):
    """Hottest expert takes `ratio` of the tokens, rest spread evenly."""
    hot = int(n_tokens * ratio)
    rest = (n_tokens - hot) // (num_experts - 1)
    sizes = [hot] + [rest] * (num_experts - 1)
    sizes[-1] += n_tokens - sum(sizes)
    return jnp.asarray(sizes, jnp.int32)


def _swiglu_gmm(x, w_gate, w_up, w_down, gs):
    g = pg.grouped_matmul(x, w_gate, gs)
    u = pg.grouped_matmul(x, w_up, gs)
    h = jax.nn.silu(g.astype(jnp.float32)).astype(u.dtype) * u
    return pg.grouped_matmul(h, w_down, gs)


def _swiglu_padded(xp, w_gate, w_up, w_down):
    """Capacity-padded batched einsum form (xp: (X, C, E))."""
    g = jnp.einsum("xce,xef->xcf", xp, w_gate)
    u = jnp.einsum("xce,xef->xcf", xp, w_up)
    h = jax.nn.silu(g.astype(jnp.float32)).astype(u.dtype) * u
    return jnp.einsum("xcf,xfe->xce", h, w_down)


def _time(f, *args):
    jax.block_until_ready(f(*args))                # compile + warm
    t = time.perf_counter()
    for _ in range(STEPS):
        out = f(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t) / STEPS


def main():
    if _on_tpu():
        N, E, F, X = 16384, 1024, 2816, 8          # the moe bench shape
        dtype = jnp.bfloat16
    else:
        N, E, F, X = 1024, 64, 128, 4
        dtype = jnp.float32
    if len(sys.argv) > 1:
        N = int(sys.argv[1])

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(N, E)), dtype)
    w_gate = jnp.asarray(rng.normal(size=(X, E, F)) * 0.02, dtype)
    w_up = jnp.asarray(rng.normal(size=(X, E, F)) * 0.02, dtype)
    w_down = jnp.asarray(rng.normal(size=(X, F, E)) * 0.02, dtype)

    for ratio in sorted({1.0 / X, 2.0 / X, min(4.0 / X, 1.0), 1.0}):
        gs = _group_sizes(N, X, ratio)
        C = int(gs.max())

        gmm_step = jax.jit(jax.grad(
            lambda a: _swiglu_gmm(a, w_gate, w_up, w_down, gs)
            .astype(jnp.float32).sum()))
        dt_gmm = _time(gmm_step, x)

        # the padded path's dispatch cost is excluded: this measures the
        # expert-FFN compute alone, which is where capacity padding hurts
        xp = jnp.zeros((X, C, E), dtype)
        offs = np.concatenate([[0], np.cumsum(np.asarray(gs))])
        for g in range(X):
            xp = xp.at[g, : int(gs[g])].set(x[offs[g]:offs[g + 1]])
        pad_step = jax.jit(jax.grad(
            lambda a: _swiglu_padded(a, w_gate, w_up, w_down)
            .astype(jnp.float32).sum()))
        dt_pad = _time(pad_step, xp)

        print(json.dumps({
            "imbalance_ratio": round(ratio, 3),
            "group_sizes": np.asarray(gs).tolist(),
            "capacity_rows": X * C,
            "actual_rows": N,
            "gmm_tokens_per_sec": round(N / dt_gmm),
            "padded_tokens_per_sec": round(N / dt_pad),
            "gmm_vs_padded": round(dt_pad / dt_gmm, 3),
        }), flush=True)


if __name__ == "__main__":
    main()
