"""Chunk-budget sweep for the unified ragged prefill+decode step (run
manually; bench.py's extra.ragged stays the driver's single-line A/B).

Usage:  python tools/bench_ragged.py [--budgets 4,8,16,40] [--long 40]
                                     [--streams 2] [--new-tokens 16]
                                     [--fused on|off|ab] [--temperature T]

Workload per point: `--streams` short requests decode continuously while
one `--long`-token prompt prefills through the SAME unified ragged
dispatch, its chunks bounded by the point's `prefill_chunk_tokens`
budget.  The sweep exposes the knob's latency/throughput trade:

  * small budget  -> tight inter-token p99 for the in-flight streams
    (each step carries at most a small chunk) but later time-to-first-
    token for the long prompt, and a smaller fixed batch (cheaper
    steady-state steps).
  * budget >= prompt -> the whole prefill lands in ONE step: fastest
    TTFT for the long prompt, worst head-of-line stall for everyone
    else — the old two-dispatch world's behavior, reproduced inside the
    unified step.

Every point is ONE compiled executable regardless of prompt length (the
batch arrays are fixed-shape) — the sweep never recompiles mid-workload,
which is the point of killing the bucket menu.  Prints one JSON line per
budget; nothing here is driver-consumed.

`--fused` picks the decode inner loop: `on` (the shipped default — the
fused single-dispatch step, sampling inside the dispatch), `off` (the
unfused dispatch+sample path), or `ab` (each point runs BOTH and prints
a line per leg tagged `"fused": true/false` — the token streams are
identical by construction, so the diff is purely latency).  Set
`--temperature` > 0 to make the A/B exercise the sampled epilogue the
fusion folds in; greedy keeps the epilogue to a single argmax and the
legs nearly tie.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--budgets", default="4,8,16,40",
                    help="comma-separated prefill_chunk_tokens points")
    ap.add_argument("--long", type=int, default=40,
                    help="long prompt length (tokens)")
    ap.add_argument("--streams", type=int, default=2,
                    help="concurrent short decoding requests")
    ap.add_argument("--new-tokens", type=int, default=16,
                    help="tokens each stream decodes")
    ap.add_argument("--page-size", type=int, default=4)
    ap.add_argument("--block-q", type=int, default=4)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--fused", choices=("on", "off", "ab"), default="on",
                    help="decode inner loop: the fused single-dispatch "
                         "step (on, default), the unfused dispatch+"
                         "sample path (off), or both per point (ab)")
    ap.add_argument("--temperature", type=float, default=0.0,
                    help="sampling temperature (0 = greedy); > 0 makes "
                         "the --fused A/B exercise the sampled epilogue")
    ap.add_argument("--spec-k", default="",
                    help="comma-separated spec_k points (e.g. 0,2,4,8): "
                         "sweep speculative draft depth instead of the "
                         "chunk budget — repetitive-continuation "
                         "workload, reports emitted tokens/sec + "
                         "acceptance per point")
    args = ap.parse_args()

    import numpy as np
    import jax

    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.models import llama
    from paddle_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(args.seed)
    max_seq = max(64, args.long + 8)
    long_prompt = rng.integers(0, cfg.vocab_size, args.long).tolist()
    shorts = [rng.integers(0, cfg.vocab_size, 3).tolist()
              for _ in range(args.streams)]

    if args.spec_k:
        # speculative draft-depth sweep: repetitive-continuation prompts
        # (the drafter's friendly case) decode a long tail per point;
        # block_q rides k+1 so every point keeps the verify span inside
        # the decode span's padded row block (same rows as plain decode)
        new_tokens = max(args.new_tokens, 64)
        prompts = [(rng.integers(0, cfg.vocab_size, 3).tolist() * 4)[:8]
                   for _ in range(args.streams)]
        for k in (int(v) for v in args.spec_k.split(",")):
            bq = max(args.block_q, k + 1)
            eng = LLMEngine(params, cfg, num_slots=args.streams,
                            page_size=args.page_size,
                            max_seq_len=max(max_seq, 8 + new_tokens),
                            prefill_chunk_tokens=bq, block_q=bq,
                            spec_k=k)
            eng.generate([[1, 2, 3]], max_new_tokens=2)   # warm
            t0 = time.perf_counter()
            hs = [eng.submit(p, max_new_tokens=new_tokens)
                  for p in prompts]
            while not all(h.done() for h in hs):
                eng.step()
            dt = time.perf_counter() - t0
            snap = eng.stats_snapshot()
            itl = eng.latency_snapshot()["inter_token_s"]
            accept = eng.metrics.get("llm_spec_acceptance_rate").value
            emitted = sum(len(h.result(timeout=0)) for h in hs)
            eng.shutdown()
            print(json.dumps({
                "spec_k": k,
                "block_q": bq,
                "emitted_tokens_per_sec": round(emitted / dt, 2),
                "acceptance_rate": round(accept, 4),
                "spec_drafted": snap["spec_drafted"],
                "spec_accepted": snap["spec_accepted"],
                "stream_itl_p50_ms": round((itl["p50"] or 0.0) * 1e3, 3),
                "stream_itl_p99_ms": round((itl["p99"] or 0.0) * 1e3, 3),
                "steps": snap["steps_total"],
                "wall_s": round(dt, 3),
            }))
        return 0

    legs = {"on": (True,), "off": (False,), "ab": (True, False)}[args.fused]
    for budget in (int(b) for b in args.budgets.split(",")):
        for fused in legs:
            eng = LLMEngine(params, cfg, num_slots=args.streams + 2,
                            page_size=args.page_size, max_seq_len=max_seq,
                            prefill_chunk_tokens=budget,
                            block_q=args.block_q, fused_decode=fused,
                            temperature=args.temperature, seed=args.seed)
            eng.generate([[1, 2, 3]], max_new_tokens=2)  # warm the
            hs = [eng.submit(p, max_new_tokens=args.new_tokens)
                  for p in shorts]
            for _ in range(3):
                eng.step()           # streams decoding before the burst
            t0 = time.perf_counter()
            lh = eng.submit(long_prompt, max_new_tokens=2)
            while not lh.done() or not all(h.done() for h in hs):
                eng.step()
            dt = time.perf_counter() - t0
            snap = eng.stats_snapshot()
            lat = eng.latency_snapshot()
            itl = lat["inter_token_s"]
            eng.shutdown()
            print(json.dumps({
                "prefill_chunk_tokens": budget,
                "fused": bool(fused),
                "long_ttft_ms": round(
                    (lh.t_first_token - lh.t_submit) * 1e3, 2),
                "stream_itl_p50_ms": round((itl["p50"] or 0.0) * 1e3, 3),
                "stream_itl_p99_ms": round((itl["p99"] or 0.0) * 1e3, 3),
                "decode_tokens_per_sec": round(
                    snap["decode_tokens"] / dt, 2),
                "fused_decode_steps": snap["fused_decode_steps"],
                "prefill_chunks": snap["prefill_chunks"],
                "ragged_batch_tokens": snap["ragged_batch_tokens"],
                "steps": snap["steps_total"],
                "wall_s": round(dt, 3),
            }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
