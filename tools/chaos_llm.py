#!/usr/bin/env python
"""Chaos soak for the preemptible LLMEngine.

Runs seeded random fault schedules (paddle_tpu.inference.faults) against a
tiny model on a deliberately undersized page pool — so preemption/resume,
admission, swap and dispatch paths all execute under injected faults — and
asserts the zero-leak invariants after every schedule: no leaked
pages/slots, live pools, every handle resolved exactly once, engine still
serving.

Usage:
    python tools/chaos_llm.py                      # 25 schedules, seed 0
    python tools/chaos_llm.py --schedules 200 --seed 7 --mode recompute
    python tools/chaos_llm.py --flight-dir /tmp/flight   # black-box armed
    python tools/chaos_llm.py --json               # machine-readable report

Exit code 1 when any schedule violates an invariant.  CPU-only (the
Pallas kernel runs in interpret mode); no chip needed.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _flight_dumps(flight_dir):
    import glob
    if not flight_dir:
        return []
    return sorted(glob.glob(os.path.join(flight_dir, "flight_*.json")))


def main():
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--schedules", type=int, default=25,
                    help="number of seeded random schedules to run")
    ap.add_argument("--seed", type=int, default=0,
                    help="base seed (schedule i uses seed+i)")
    ap.add_argument("--mode", choices=["swap", "recompute", "alternate"],
                    default="alternate", help="preemption mode under test")
    ap.add_argument("--slots", type=int, default=2)
    ap.add_argument("--num-pages", type=int, default=5,
                    help="page pool size (default is BELOW the 2-slot "
                         "worst case, forcing preemption)")
    ap.add_argument("--requests", type=int, default=4,
                    help="requests per schedule")
    ap.add_argument("--prefill-chunk", type=int, default=6,
                    help="prefill_chunk_tokens: per-step token budget for "
                         "prefill chunks riding the unified ragged batch "
                         "(small by default so multi-chunk prefills — and "
                         "mid-prefill faults/preemptions — actually occur)")
    ap.add_argument("--prefix-mix", type=float, default=0.0,
                    metavar="FRAC",
                    help="fraction of each schedule's requests sharing "
                         "one base prompt (0..1): hit admissions SPLICE "
                         "cached prefix pages, so faults/preemption land "
                         "on refcounted shared pages and the COW + "
                         "LRU-eviction paths soak under pressure")
    ap.add_argument("--spec-k", type=int, default=0,
                    help="speculative draft depth (0 = off): soak the "
                         "draft->verify->commit path — an always-propose "
                         "drafter keeps verify spans in every step, so "
                         "rollback runs under every injected fault")
    ap.add_argument("--probe-every", type=int, default=5,
                    help="run the fresh-request serving probe every Nth "
                         "schedule (1 = always; probes dominate runtime)")
    ap.add_argument("--flight-dir", default=None, metavar="DIR",
                    help="arm a flight recorder on every engine: dumps "
                         "land here on invariant violations and SIGTERM, "
                         "and the soak FAILS if any dump is unloadable "
                         "or a violation produced none")
    ap.add_argument("--tenants", action="store_true",
                    help="multi-tenant QoS tier: engines get a two-tier "
                         "tenant table (gold: priority 0, weight 4; "
                         "bulk: priority 3, weight 1, capped queue) and "
                         "~70%% of each schedule's requests arrive "
                         "tagged bulk vs ~30%% gold, so WFQ admission, "
                         "tier-aware preemption and the per-tenant "
                         "counter identities all soak under faults")
    ap.add_argument("--no-witness", dest="witness", action="store_false",
                    help="disarm the lock-order witness (armed by "
                         "default: every schedule's locks are wrapped, "
                         "and an acquisition-order inversion, a lock "
                         "held across a fenced dispatch, or a leaked "
                         "thread fails the soak)")
    ap.add_argument("--json", action="store_true",
                    help="print the full per-schedule reports as JSON")
    args = ap.parse_args()

    import numpy as np
    import jax

    from paddle_tpu.inference import LLMEngine
    from paddle_tpu.inference import faults as F
    from paddle_tpu.models import llama
    from paddle_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny()
    params = llama.init_params(cfg, jax.random.PRNGKey(0))

    drafter = F.EchoDrafter() if args.spec_k else None

    recorders = []
    if args.flight_dir:
        from paddle_tpu.obs import flight as obs_flight

        obs_flight.install_sigterm(recorders)

    # hostile-tenant tier: a heavyweight high-priority tenant next to a
    # capped bulk tenant — the per-tenant invariant identities in
    # faults.check_invariants arm automatically once the engine carries
    # a tenant table
    tenant_table = {
        "gold": {"priority": 0, "weight": 4.0},
        "bulk": {"priority": 3, "weight": 1.0, "max_pending": 6},
    } if args.tenants else None

    def make_engine(mode, tag):
        def make():
            eng = LLMEngine(
                params, cfg, num_slots=args.slots, page_size=4,
                max_seq_len=16, num_pages=args.num_pages,
                preempt_mode=mode,
                prefill_chunk_tokens=args.prefill_chunk, block_q=2,
                spec_k=args.spec_k, drafter=drafter,
                tenants=tenant_table)
            if args.flight_dir:
                from paddle_tpu.obs import flight as obs_flight

                rec = obs_flight.FlightRecorder(
                    dir=args.flight_dir, name=tag)
                rec.attach_engine(eng)
                recorders.append(rec)
            return eng
        return make

    reports, violations = [], 0
    totals = {"fired": 0, "completed": 0, "failed": 0, "preemptions": 0,
              "swapped_in": 0, "prefix_hits": 0, "prefix_cow_copies": 0,
              "prefix_evictions": 0, "lock_acquisitions": 0,
              "thread_leaks": 0}
    tenant_totals = {}  # tenant -> summed counters across schedules
    for i in range(args.schedules):
        seed = args.seed + i
        mode = (args.mode if args.mode != "alternate"
                else ("swap" if i % 2 == 0 else "recompute"))
        rules = F.random_schedule(seed)
        rng = np.random.default_rng(seed)
        base = rng.integers(0, cfg.vocab_size, 6).tolist()
        workload = []
        for _ in range(args.requests):
            if rng.random() < args.prefix_mix:
                # shared base + short unique suffix: a prefix-cache hit
                # once any sibling's prefill registered the base
                prompt = base + rng.integers(
                    0, cfg.vocab_size, int(rng.integers(1, 4))).tolist()
            else:
                prompt = rng.integers(0, cfg.vocab_size,
                                      int(rng.integers(2, 9))).tolist()
            if args.tenants:
                tenant = "bulk" if rng.random() < 0.7 else "gold"
                workload.append((prompt, int(rng.integers(2, 7)),
                                 {"tenant": tenant}))
            else:
                workload.append((prompt, int(rng.integers(2, 7))))
        dumps_before = len(_flight_dumps(args.flight_dir))
        try:
            report = F.run_schedule(make_engine(mode, f"s{seed}"), rules,
                                    workload,
                                    probe=i % args.probe_every == 0,
                                    witness=args.witness)
        except F.InvariantViolation as e:
            violations += 1
            report = {"ok": False, "violations": str(e),
                      "schedule": [r.to_dict() for r in rules]}
            # an invariant violation must leave a loadable black box —
            # that is what the flight recorder is FOR
            if args.flight_dir and \
                    len(_flight_dumps(args.flight_dir)) <= dumps_before:
                report["flight_missing"] = True
                print(f"[FLIGHT] seed={seed}: violation produced no "
                      "flight dump")
        report["seed"] = seed
        report["mode"] = mode
        reports.append(report)
        if report["ok"]:
            totals["fired"] += len(report["fired"])
            totals["completed"] += report["completed"]
            totals["failed"] += report["failed"]
            totals["preemptions"] += report["stats"]["preemptions"]
            totals["swapped_in"] += report["stats"]["swapped_in"]
            totals["prefix_hits"] += report["stats"].get("prefix_hits", 0)
            totals["prefix_cow_copies"] += \
                report["stats"].get("prefix_cow_copies", 0)
            totals["prefix_evictions"] += \
                report["stats"].get("prefix_evictions", 0)
            threads = report.get("threads", {})
            totals["thread_leaks"] += len(threads.get("leaked", ()))
            totals["lock_acquisitions"] += threads.get(
                "witness", {}).get("acquisitions", 0)
            for tname, tsnap in report["stats"].get("tenants",
                                                    {}).items():
                agg = tenant_totals.setdefault(
                    tname, dict.fromkeys(tsnap["counters"], 0))
                for k, v in tsnap["counters"].items():
                    agg[k] = agg.get(k, 0) + v
        status = "ok " if report["ok"] else "LEAK"
        line = (f"[{status}] seed={seed} mode={mode:9s} "
                f"rules={[repr(r) for r in rules]}")
        if report["ok"]:
            line += (f" fired={len(report['fired'])}"
                     f" completed={report['completed']}"
                     f" failed={report['failed']}"
                     f" preemptions={report['stats']['preemptions']}")
            if args.tenants:
                tn = report["stats"].get("tenants", {})
                line += " tenants=" + ",".join(
                    f"{t}:{s['counters']['completed']}"
                    for t, s in sorted(tn.items()))
        else:
            line += f" violations={report['violations']}"
        print(line)

    flight_bad = 0
    if args.flight_dir:
        from paddle_tpu.obs import flight as obs_flight

        paths = _flight_dumps(args.flight_dir)
        for p in paths:
            try:
                obs_flight.load_dump(p)
            except Exception as e:  # noqa: BLE001 — unloadable dump
                flight_bad += 1
                print(f"[FLIGHT] unloadable dump {p}: {e!r}")
        flight_missing = sum(1 for r in reports
                             if r.get("flight_missing"))
        violations += flight_bad + flight_missing
        print(f"flight recorder: {len(paths)} dump(s), "
              f"{flight_bad} unloadable, {flight_missing} missing")

    # end-of-soak telemetry verdict: at every schedule's quiescence the
    # pool/slot GAUGES must have read back to baseline and agreed with
    # faults.check_invariants' direct allocator checks (a mismatch is
    # already a violation — this line makes the cross-check visible)
    telemetry_checked = sum(1 for r in reports if "telemetry" in r)
    telemetry_bad = sum(1 for r in reports
                        if r.get("telemetry")
                        and not r["telemetry"]["ok"])
    print(f"telemetry: gauges agreed with the invariant checker in "
          f"{telemetry_checked - telemetry_bad}/{telemetry_checked} "
          f"checked schedule(s)")
    if args.witness:
        # thread-discipline verdict: the witness saw every wrapped-lock
        # acquisition and the leak proof ran post-quiescence — order
        # inversions / locks-across-dispatch / leaked threads are
        # already violations above; this line makes the coverage visible
        print(f"threads: witness observed "
              f"{totals['lock_acquisitions']} lock acquisition(s), "
              f"{totals['thread_leaks']} thread leak(s)")

    if args.tenants:
        # per-tenant QoS verdict: these counters were already checked
        # against the untagged totals (sum identities) and the queue
        # ground truth inside every schedule's check_invariants — the
        # line makes the coverage visible in the soak output
        print("tenants: " + json.dumps(tenant_totals, sort_keys=True))

    summary = {"schedules": args.schedules, "violations": violations,
               "telemetry_mismatches": telemetry_bad,
               "witness_armed": bool(args.witness),
               "tenants_armed": bool(args.tenants), **totals}
    if args.tenants:
        summary["tenant_totals"] = tenant_totals
    if args.json:
        print(json.dumps({"summary": summary, "reports": reports},
                         indent=2, default=str))
    else:
        print("\ninvariant report:", json.dumps(summary))
        print("zero leaks" if violations == 0
              else f"{violations} schedule(s) LEAKED")
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
