"""Op registry — the single-source op table (SURVEY C10).

Reference analog: `paddle/phi/api/yaml/ops.yaml` + `paddle/phi/api/yaml/
backward.yaml` and their generators, which produce the C++ API, VJP rules and
per-op test coverage.  Under JAX the API surface and VJPs come from jnp/XLA,
so the registry's job shrinks to what still needs a single source of truth:

  * which PUBLIC binding implements each op (name -> namespace path, checked
    by tests so the table cannot rot),
  * the supported dtypes + per-dtype tolerances (drives the GENERATED
    dtype x mode numeric sweep in tests/test_op_registry.py — the analog of
    the reference OpTest running every op across places/dtypes,
    test/legacy_test/eager_op_test.py:381),
  * whether the op is differentiable (grad sweep) and its sampler (valid
    example inputs, respecting each op's domain),
  * the GSPMD sharding class (elementwise/broadcast/reduce/contract/gather/
    shape) — documentation of how the op partitions; XLA derives the actual
    propagation rule.

Registering is additive metadata: impls stay the existing hand-written jnp
compositions in ops/* and nn/functional.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

import numpy as np

__all__ = ["OpDef", "register", "get", "all_ops", "REGISTRY"]

_FLOATS = ("float32", "float16", "bfloat16")


@dataclasses.dataclass(frozen=True)
class OpDef:
    name: str                      # public path under paddle_tpu, e.g. "exp"
    dtypes: Tuple[str, ...] = _FLOATS
    has_vjp: bool = True           # include in the grad sweep
    sample: Optional[Callable] = None   # rng -> (args, kwargs)
    # per-dtype (rtol, atol) overrides for the low-precision sweep
    tol: Optional[Dict[str, Tuple[float, float]]] = None
    sharding: str = "elementwise"  # gspmd class: elementwise | broadcast |
    #                                reduce | contract | gather | shape | rng


REGISTRY: Dict[str, OpDef] = {}


def register(name: str, **kw) -> OpDef:
    if name in REGISTRY:
        raise ValueError(f"op '{name}' already registered")
    op = OpDef(name=name, **kw)
    REGISTRY[name] = op
    return op


def get(name: str) -> OpDef:
    return REGISTRY[name]


def all_ops():
    return list(REGISTRY.values())


# ---------------------------------------------------------------------------
# samplers — produce (args, kwargs) of NUMPY float32 arrays; the sweep casts
# them to the dtype under test
# ---------------------------------------------------------------------------


def _u(shape=(4, 8)):
    def f(rng):
        return (rng.standard_normal(shape).astype(np.float32),), {}
    return f


def _u_pos(shape=(4, 8), lo=0.1, hi=3.0):
    def f(rng):
        return (rng.uniform(lo, hi, shape).astype(np.float32),), {}
    return f


def _u_unit(shape=(4, 8), eps=0.05):
    def f(rng):
        return (rng.uniform(-1 + eps, 1 - eps, shape).astype(np.float32),), {}
    return f


def _u01(shape=(4, 8), eps=0.05):
    def f(rng):
        return (rng.uniform(eps, 1 - eps, shape).astype(np.float32),), {}
    return f


def _b(shape=(4, 8)):
    def f(rng):
        return (rng.standard_normal(shape).astype(np.float32),
                rng.standard_normal(shape).astype(np.float32)), {}
    return f


def _b_pos(shape=(4, 8)):
    def f(rng):
        return (rng.uniform(0.2, 3.0, shape).astype(np.float32),
                rng.uniform(0.2, 3.0, shape).astype(np.float32)), {}
    return f


def _mat(m=4, k=8, n=4):
    def f(rng):
        return (rng.standard_normal((m, k)).astype(np.float32) / np.sqrt(k),
                rng.standard_normal((k, n)).astype(np.float32) / np.sqrt(k)), {}
    return f


def _spd(n=4):
    def f(rng):
        a = rng.standard_normal((n, n)).astype(np.float32)
        return (a @ a.T + n * np.eye(n, dtype=np.float32),), {}
    return f


def _sq(n=4):
    def f(rng):
        a = rng.standard_normal((n, n)).astype(np.float32)
        return (a + n * np.eye(n, dtype=np.float32),), {}
    return f


def _ints(shape=(4, 8), hi=8):
    def f(rng):
        return (rng.integers(0, hi, shape).astype(np.int32),
                rng.integers(0, hi, shape).astype(np.int32)), {}
    return f


def _bools(shape=(4, 8)):
    def f(rng):
        return (rng.integers(0, 2, shape).astype(bool),
                rng.integers(0, 2, shape).astype(bool)), {}
    return f


_BF = {"bfloat16": (1e-1, 1e-1), "float16": (3e-2, 3e-2)}
_LOOSE = {"bfloat16": (2e-1, 2e-1), "float16": (6e-2, 6e-2)}


def _reg_many(names, **kw):
    for n in names:
        register(n, **kw)


# -- elementwise unary ------------------------------------------------------

_reg_many(
    ["abs", "neg", "sign", "ceil", "floor", "round", "trunc", "frac",
     "sin", "cos", "tanh", "sigmoid", "erf", "sinh", "cosh",
     "deg2rad", "rad2deg", "square", "stanh"],
    sample=_u(), tol=_BF)
_reg_many(["exp", "expm1"], sample=_u(), tol=_LOOSE)
_reg_many(["tan"], sample=_u_unit(), tol=_LOOSE)
_reg_many(["asin", "acos", "atan", "atanh", "erfinv"],
          sample=_u_unit(), tol=_LOOSE)
register("asinh", sample=_u(), tol=_BF)
register("acosh", sample=_u_pos(lo=1.1, hi=4.0), tol=_LOOSE)
_reg_many(["sqrt", "rsqrt", "log", "log2", "log10", "log1p", "lgamma",
           "digamma", "reciprocal"],
          sample=_u_pos(), tol=_LOOSE)
register("logit", sample=_u01(), tol=_LOOSE)
_reg_many(["i0", "i1"], sample=_u_pos(hi=2.0), tol=_LOOSE,
          dtypes=("float32",))
_reg_many(["isnan", "isinf", "isfinite"], sample=_u(), has_vjp=False)
register("nan_to_num", sample=_u(), tol=_BF)

# -- elementwise binary -----------------------------------------------------

_reg_many(["add", "subtract", "multiply", "maximum", "minimum",
           "fmax", "fmin", "copysign"],
          sample=_b(), tol=_BF, sharding="broadcast")
_reg_many(["divide", "atan2", "hypot", "logaddexp"],
          sample=_b_pos(), tol=_LOOSE, sharding="broadcast")
_reg_many(["pow", "heaviside"], sample=_b_pos(), tol=_LOOSE,
          sharding="broadcast")
# modulo is discontinuous: a low-precision rounding of x/y across an integer
# boundary flips the result by |y|, so only f32 is swept
_reg_many(["mod", "remainder", "floor_mod", "floor_divide"],
          sample=_b_pos(), has_vjp=False, dtypes=("float32",),
          sharding="broadcast")
register("nextafter", sample=_b(), has_vjp=False, dtypes=("float32",),
         sharding="broadcast")
register("lerp", tol=_BF, sharding="broadcast",
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),
                              rng.standard_normal((4, 8)).astype(np.float32),
                              np.float32(0.3)), {}))

# -- comparisons / logical / bitwise ---------------------------------------

_reg_many(["equal", "not_equal", "greater_than", "greater_equal",
           "less_than", "less_equal", "isclose"],
          sample=_b(), has_vjp=False, sharding="broadcast")
_reg_many(["logical_and", "logical_or", "logical_xor"],
          sample=_bools(), has_vjp=False, dtypes=("bool",),
          sharding="broadcast")
register("logical_not", has_vjp=False, dtypes=("bool",),
         sample=lambda rng: ((rng.integers(0, 2, (4, 8)).astype(bool),), {}))
_reg_many(["bitwise_and", "bitwise_or", "bitwise_xor"],
          sample=_ints(), has_vjp=False, dtypes=("int32",),
          sharding="broadcast")
register("bitwise_not", has_vjp=False, dtypes=("int32",),
         sample=lambda rng: ((rng.integers(0, 8, (4, 8)).astype(np.int32),), {}))
_reg_many(["gcd", "lcm"], sample=_ints(), has_vjp=False, dtypes=("int32",),
          sharding="broadcast")

# -- reductions -------------------------------------------------------------

_reg_many(["sum", "mean", "max", "min", "amax", "amin", "logsumexp",
           "nansum", "nanmean"],
          sample=_u(), tol=_LOOSE, sharding="reduce")
register("prod", sample=_u_pos(lo=0.5, hi=1.5), tol=_LOOSE, sharding="reduce")
_reg_many(["std", "var"], sample=_u(), tol=_LOOSE, sharding="reduce")
_reg_many(["median", "nanmedian"], sample=_u(), has_vjp=False,
          tol=_LOOSE, sharding="reduce")
# quantile interpolates between order statistics — rank flips under rounding
register("quantile", has_vjp=False, dtypes=("float32",), sharding="reduce",
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),),
                             {"q": 0.5}))
_reg_many(["any", "all"], has_vjp=False, dtypes=("bool",), sharding="reduce",
          sample=lambda rng: ((rng.integers(0, 2, (4, 8)).astype(bool),), {}))
register("count_nonzero", sample=_u(), has_vjp=False, sharding="reduce")
_reg_many(["cumsum", "logcumsumexp"], sample=_u(), tol=_LOOSE,
          sharding="reduce")
register("cumprod", tol=_LOOSE, sharding="reduce",
         sample=lambda rng: ((rng.uniform(0.5, 1.5, (4, 8)).astype(np.float32),),
                             {"dim": 1}))

# -- contractions -----------------------------------------------------------

_reg_many(["matmul", "mm"], sample=_mat(), tol=_LOOSE, sharding="contract")
register("bmm", tol=_LOOSE, sharding="contract",
         sample=lambda rng: ((rng.standard_normal((2, 4, 8)).astype(np.float32),
                              rng.standard_normal((2, 8, 4)).astype(np.float32)),
                             {}))
register("dot", tol=_LOOSE, sharding="contract",
         sample=lambda rng: ((rng.standard_normal(8).astype(np.float32),
                              rng.standard_normal(8).astype(np.float32)), {}))
_reg_many(["inner", "outer"], sample=lambda rng: (
    (rng.standard_normal(6).astype(np.float32),
     rng.standard_normal(6).astype(np.float32)), {}),
    tol=_LOOSE, sharding="contract")
register("kron", sample=_b(shape=(2, 3)), tol=_LOOSE, sharding="contract")

# -- manipulation (shape class: dtype-independent data movement) ------------

register("reshape", has_vjp=True, sharding="shape", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),),
                             {"shape": [8, 4]}))
register("transpose", sharding="shape", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),),
                             {"perm": [1, 0]}))
_reg_many(["t", "flatten"], sample=_u(), tol=_BF, sharding="shape")
register("flip", sharding="shape", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),),
                             {"axis": 1}))
register("roll", sharding="shape", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),),
                             {"shifts": 2, "axis": 1}))
register("tile", sharding="shape", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),),
                             {"repeat_times": [2, 1]}))
register("broadcast_to", sharding="broadcast", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((1, 8)).astype(np.float32),),
                             {"shape": [4, 8]}))
_reg_many(["tril", "triu", "diag", "diagonal"], sample=_u(shape=(5, 5)),
          tol=_BF, sharding="shape")
register("squeeze", sharding="shape", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 1, 8)).astype(np.float32),),
                             {"axis": 1}))
register("unsqueeze", sharding="shape", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),),
                             {"axis": 1}))
register("moveaxis", sharding="shape", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((2, 3, 4)).astype(np.float32),),
                             {"source": 0, "destination": 2}))
register("rot90", sharding="shape", tol=_BF, sample=_u(shape=(4, 4)))
register("repeat_interleave", sharding="shape", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),),
                             {"repeats": 2, "axis": 0}))
register("masked_fill", sharding="broadcast", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),
                              rng.integers(0, 2, (4, 8)).astype(bool),
                              np.float32(0.0)), {}))
register("where", sharding="broadcast", tol=_BF,
         sample=lambda rng: ((rng.integers(0, 2, (4, 8)).astype(bool),
                              rng.standard_normal((4, 8)).astype(np.float32),
                              rng.standard_normal((4, 8)).astype(np.float32)),
                             {}))
register("clip", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),),
                             {"min": -0.5, "max": 0.5}))
register("scale", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),),
                             {"scale": 2.0, "bias": 1.0}))

# -- gather / scatter -------------------------------------------------------

register("gather", sharding="gather", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((6, 3)).astype(np.float32),
                              rng.integers(0, 6, (4,)).astype(np.int32)), {}))
register("index_select", sharding="gather", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((6, 3)).astype(np.float32),
                              rng.integers(0, 6, (4,)).astype(np.int32)), {}))
register("take_along_axis", sharding="gather", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),
                              rng.integers(0, 8, (4, 2)).astype(np.int64)),
                             {"axis": 1}))
register("index_sample", sharding="gather", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),
                              rng.integers(0, 8, (4, 2)).astype(np.int32)), {}))

# -- linalg -----------------------------------------------------------------

register("cholesky", sample=_spd(), dtypes=("float32",), sharding="contract")
_reg_many(["inverse", "det", "slogdet", "matrix_exp"], sample=_sq(),
          dtypes=("float32",), sharding="contract")
register("trace", sample=_u(shape=(5, 5)), tol=_LOOSE, sharding="reduce")
register("norm", sample=_u(), tol=_LOOSE, sharding="reduce")
register("solve", dtypes=("float32",), sharding="contract",
         sample=lambda rng: ((_sq()(rng)[0][0],
                              rng.standard_normal((4, 2)).astype(np.float32)),
                             {}))
_reg_many(["qr", "svd", "eigh", "pinv"], sample=_sq(), dtypes=("float32",),
          has_vjp=False, sharding="contract")
register("matrix_power", dtypes=("float32",), sharding="contract",
         sample=lambda rng: ((_sq()(rng)[0][0],), {"n": 2}))

# -- nn.functional activations (paths with dots resolve namespaces) ---------

_reg_many(
    ["nn.functional." + n for n in
     ["relu", "relu6", "gelu", "silu", "elu", "selu", "leaky_relu",
      "hardtanh", "hardsigmoid", "hardswish", "hardshrink", "softshrink",
      "tanhshrink", "softplus", "softsign", "mish", "swish", "celu"]],
    sample=_u(), tol=_LOOSE)
_reg_many(["nn.functional.softmax", "nn.functional.log_softmax"],
          sample=_u(), tol=_LOOSE, sharding="reduce")
register("nn.functional.normalize", sample=_u(), tol=_LOOSE,
         sharding="reduce")
register("nn.functional.glu", sample=_u(), tol=_LOOSE)


# -- tranche 2: creation / manipulation / search / linalg / complex / rng ---
# (round-3 expansion toward the reference's full ops.yaml surface)


def _static(*args, **kw):
    """Sampler for ops whose example inputs are fixed python values."""
    def f(rng):
        return args, dict(kw)
    return f


def _perm(shape=(4, 8)):
    """Distinct integer-valued floats: ordering-based ops (sort/topk/...)
    give identical results in every float dtype (no ties, exact values)."""
    def f(rng):
        n = int(np.prod(shape))
        return (rng.permutation(n).reshape(shape).astype(np.float32),), {}
    return f


def _listof(n=2, shape=(4, 8)):
    def f(rng):
        return ([rng.standard_normal(shape).astype(np.float32)
                 for _ in range(n)],), {}
    return f


def _one(shape=(4, 8), **kw):
    def f(rng):
        return (rng.standard_normal(shape).astype(np.float32),), dict(kw)
    return f


# creation (shape class: no dtype numerics to sweep, binding + run checked)
register("zeros", sample=_static((3, 4)), has_vjp=False, sharding="shape")
register("ones", sample=_static((3, 4)), has_vjp=False, sharding="shape")
register("full", sample=_static((3, 4), 2.5), has_vjp=False, sharding="shape")
register("eye", sample=_static(4), has_vjp=False, sharding="shape")
register("arange", sample=_static(0, 8, 2), has_vjp=False, sharding="shape")
register("linspace", sample=_static(0.0, 1.0, 5), has_vjp=False,
         sharding="shape")
register("logspace", sample=_static(0.0, 2.0, 5), has_vjp=False,
         sharding="shape")
register("zeros_like", sample=_u(), has_vjp=False, sharding="shape")
register("ones_like", sample=_u(), has_vjp=False, sharding="shape")
register("full_like", sample=_one(fill_value=1.5), has_vjp=False,
         sharding="shape", tol=_BF)
register("tril_indices", sample=_static(4, 4), has_vjp=False,
         dtypes=("float32",), sharding="shape")
register("triu_indices", sample=_static(4, 4), has_vjp=False,
         dtypes=("float32",), sharding="shape")
register("vander", sample=_u_pos(shape=(5,), hi=2.0), has_vjp=False,
         tol=_LOOSE, sharding="shape")

# manipulation over lists / shapes
register("concat", sample=_listof(), tol=_BF, sharding="shape")
register("stack", sample=_listof(), tol=_BF, sharding="shape")
register("add_n", sample=_listof(3), tol=_BF, sharding="elementwise")
register("broadcast_tensors", sample=_listof(2), tol=_BF,
         sharding="broadcast", has_vjp=False)
register("meshgrid", tol=_BF, has_vjp=False, sharding="shape",
         sample=lambda rng: ((rng.standard_normal(3).astype(np.float32),
                              rng.standard_normal(4).astype(np.float32)), {}))
register("split", sample=_one(num_or_sections=2), tol=_BF, sharding="shape")
register("chunk", sample=_one(chunks=2), tol=_BF, sharding="shape")
register("tensor_split", sample=_one(num_or_indices=2), tol=_BF,
         sharding="shape", has_vjp=False)
register("unstack", sample=_u(), tol=_BF, sharding="shape")
register("unbind", sample=_u(), tol=_BF, sharding="shape")
register("expand", tol=_BF, sharding="broadcast",
         sample=lambda rng: ((rng.standard_normal((1, 8)).astype(np.float32),
                              (4, 8)), {}))
register("expand_as", tol=_BF, sharding="broadcast",
         sample=lambda rng: ((rng.standard_normal((1, 8)).astype(np.float32),
                              rng.standard_normal((4, 8)).astype(np.float32)),
                             {}))
register("swapaxes", sample=_one(axis0=0, axis1=1), tol=_BF, sharding="shape")
register("diff", sample=_u(), tol=_BF, sharding="shape")
register("cast", sample=_one(dtype="float32"), has_vjp=False,
         sharding="elementwise")
register("clone", sample=_u(), tol=_BF, sharding="elementwise")
register("assign", sample=_u(), tol=_BF, sharding="elementwise")
register("numel", sample=_u(), has_vjp=False, sharding="reduce")
register("rank", sample=_u(), has_vjp=False, sharding="reduce")
_reg_many(["atleast_1d", "atleast_2d", "atleast_3d"], sample=_u(),
          has_vjp=False, tol=_BF, sharding="shape")

# indexing / scatter-gather
register("gather_nd", sharding="gather", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),
                              rng.integers(0, 4, (3, 1)).astype(np.int64)),
                             {}))
register("scatter", sharding="gather", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((6, 8)).astype(np.float32),
                              np.array([0, 2, 4], np.int64),
                              rng.standard_normal((3, 8)).astype(np.float32)),
                             {}))
register("scatter_nd_add", sharding="gather", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((6, 8)).astype(np.float32),
                              rng.integers(0, 6, (3, 1)).astype(np.int64),
                              rng.standard_normal((3, 8)).astype(np.float32)),
                             {}))
register("scatter_nd", sharding="gather", tol=_BF, has_vjp=False,
         sample=lambda rng: ((rng.integers(0, 6, (3, 1)).astype(np.int64),
                              rng.standard_normal((3, 8)).astype(np.float32),
                              (6, 8)), {}))
register("put_along_axis", sharding="gather", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),
                              rng.integers(0, 8, (4, 8)).astype(np.int64),
                              rng.standard_normal((4, 8)).astype(np.float32)),
                             {"axis": 1}))
register("index_add", sharding="gather", tol=_BF,
         sample=lambda rng: ((rng.standard_normal((6, 8)).astype(np.float32),
                              np.array([1, 3], np.int64), 0,
                              rng.standard_normal((2, 8)).astype(np.float32)),
                             {}))
register("masked_select", sharding="gather", has_vjp=False,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),
                              rng.integers(0, 2, (4, 8)).astype(bool)), {}))
register("nonzero", sample=_u(), has_vjp=False, sharding="gather")
register("bucketize", sharding="gather", has_vjp=False,
         sample=lambda rng: ((rng.standard_normal((4, 8)).astype(np.float32),
                              np.sort(rng.standard_normal(10)
                                      ).astype(np.float32)), {}))
register("searchsorted", sharding="gather", has_vjp=False,
         sample=lambda rng: ((np.sort(rng.standard_normal(10)
                                      ).astype(np.float32),
                              rng.standard_normal((4, 8)).astype(np.float32)),
                             {}))

# search / ordering (permutation samplers: tie-free in every dtype)
register("sort", sample=_perm(), tol=_BF, sharding="reduce")
register("argsort", sample=_perm(), has_vjp=False, sharding="reduce")
register("argmax", sample=_perm(), has_vjp=False, sharding="reduce")
register("argmin", sample=_perm(), has_vjp=False, sharding="reduce")
register("topk", has_vjp=False, sharding="reduce", tol=_BF,
         sample=lambda rng: ((rng.permutation(32).reshape(4, 8)
                              .astype(np.float32), 3), {}))
register("kthvalue", has_vjp=False, sharding="reduce", tol=_BF,
         sample=lambda rng: ((rng.permutation(32).reshape(4, 8)
                              .astype(np.float32), 3), {}))
register("unique", sample=_perm(shape=(8,)), has_vjp=False,
         dtypes=("float32",), sharding="reduce")
register("unique_consecutive", sample=_perm(shape=(8,)), has_vjp=False,
         dtypes=("float32",), sharding="reduce")
register("bincount", has_vjp=False, dtypes=("float32",), sharding="reduce",
         sample=lambda rng: ((rng.integers(0, 6, (16,)).astype(np.int64),),
                             {}))
register("histogram", has_vjp=False, dtypes=("float32",), sharding="reduce",
         sample=lambda rng: ((rng.standard_normal((16,)).astype(np.float32),),
                             {"bins": 8, "min": -3, "max": 3}))
_reg_many(["cummax", "cummin"], sample=_perm(), has_vjp=False, tol=_BF,
          sharding="reduce")
register("allclose", sample=_b(), has_vjp=False, sharding="reduce")
register("equal_all", sample=_b(), has_vjp=False, sharding="reduce")
register("mode", sample=_perm(), has_vjp=False, dtypes=("float32",),
         sharding="reduce")

# linalg tranche 2
register("cross", tol=_LOOSE, sharding="contract",
         sample=lambda rng: ((rng.standard_normal((4, 3)).astype(np.float32),
                              rng.standard_normal((4, 3)).astype(np.float32)),
                             {}))
register("dist", sample=_b(), tol=_LOOSE, sharding="reduce")
register("multi_dot", sample=_listof(3, shape=(4, 4)), tol=_LOOSE,
         sharding="contract")
register("tensordot", tol=_LOOSE, sharding="contract",
         sample=lambda rng: ((rng.standard_normal((4, 6)).astype(np.float32),
                              rng.standard_normal((6, 5)).astype(np.float32)),
                             {"axes": 1}))
register("triangular_solve", dtypes=("float32",), sharding="contract",
         sample=lambda rng: ((np.triu(rng.standard_normal((4, 4))
                                      + 4 * np.eye(4)).astype(np.float32),
                              rng.standard_normal((4, 2)).astype(np.float32)),
                             {}))
register("cholesky_solve", dtypes=("float32",), sharding="contract",
         sample=lambda rng: (
             (rng.standard_normal((4, 2)).astype(np.float32),
              np.linalg.cholesky(
                  (lambda a: a @ a.T + 4 * np.eye(4))(
                      rng.standard_normal((4, 4)))).astype(np.float32)), {}))
_reg_many(["eig", "eigvals"], sample=_sq(), has_vjp=False,
          dtypes=("float32",), sharding="contract")
register("eigvalsh", sample=_spd(), dtypes=("float32",), has_vjp=False,
         sharding="contract")
register("lstsq", has_vjp=False, dtypes=("float32",), sharding="contract",
         sample=lambda rng: ((rng.standard_normal((6, 4)).astype(np.float32),
                              rng.standard_normal((6, 2)).astype(np.float32)),
                             {}))
register("lu", sample=_sq(), has_vjp=False, dtypes=("float32",),
         sharding="contract")
register("matrix_rank", sample=_sq(), has_vjp=False, dtypes=("float32",),
         sharding="contract")
register("corrcoef", sample=_u(shape=(4, 16)), has_vjp=False,
         dtypes=("float32",), sharding="reduce")
register("cov", sample=_u(shape=(4, 16)), tol=_LOOSE, sharding="reduce")

# complex views (fp32 only: complex dtypes don't sweep)
register("as_complex", dtypes=("float32",), sharding="elementwise",
         sample=_u(shape=(4, 8, 2)))
_reg_many(["real", "imag", "conj", "angle"], sample=_u(),
          dtypes=("float32",), has_vjp=False, sharding="elementwise")
register("complex", sample=_b(), dtypes=("float32",), has_vjp=False,
         sharding="elementwise")

# rng ops: fp32 smoke only (draws differ per call; nothing to compare)
register("bernoulli", sample=_u01(), has_vjp=False, dtypes=("float32",),
         sharding="rng")
register("multinomial", has_vjp=False, dtypes=("float32",), sharding="rng",
         sample=lambda rng: ((rng.uniform(0.1, 1, (4, 8)).astype(np.float32),),
                             {"num_samples": 2, "replacement": True}))
register("poisson", sample=_u_pos(hi=4.0), has_vjp=False,
         dtypes=("float32",), sharding="rng")
register("rand", sample=_static((3, 4)), has_vjp=False,
         dtypes=("float32",), sharding="rng")
register("randn", sample=_static((3, 4)), has_vjp=False,
         dtypes=("float32",), sharding="rng")
register("randint", sample=_static(0, 10, (3, 4)), has_vjp=False,
         dtypes=("float32",), sharding="rng")
register("randperm", sample=_static(8), has_vjp=False,
         dtypes=("float32",), sharding="rng")
register("uniform", sample=_static((3, 4)), has_vjp=False,
         dtypes=("float32",), sharding="rng")
register("standard_normal", sample=_static((3, 4)), has_vjp=False,
         dtypes=("float32",), sharding="rng")


# -- tranche 3: fft + signal (round-4; reference python/paddle/fft.py:1,
# python/paddle/signal.py:1).  Transforms run in f32 (complex64) only — bf16
# has no complex analog.  Complex-OUTPUT ops are marked has_vjp=False for the
# generated sweep (its quadratic loss assumes real outputs); analytic grads
# are covered by tests/test_fft_signal.py instead.

_reg_many(["fft." + n for n in
           ["fft", "ifft", "rfft", "ihfft", "fftn", "ifftn", "rfftn",
            "ihfftn"]],
          sample=_u(), has_vjp=False, dtypes=("float32",), sharding="reduce")
_reg_many(["fft." + n for n in ["fft2", "ifft2", "rfft2", "ihfft2"]],
          sample=_u(shape=(4, 8, 8)), has_vjp=False, dtypes=("float32",),
          sharding="reduce")
# real-output transforms keep the grad sweep
_reg_many(["fft." + n for n in ["irfft", "hfft", "irfftn", "hfftn"]],
          sample=_u(), dtypes=("float32",), sharding="reduce")
_reg_many(["fft." + n for n in ["irfft2", "hfft2"]],
          sample=_u(shape=(4, 8, 8)), dtypes=("float32",), sharding="reduce")
register("fft.fftfreq", sample=_static(8), has_vjp=False,
         dtypes=("float32",), sharding="shape")
register("fft.rfftfreq", sample=_static(8), has_vjp=False,
         dtypes=("float32",), sharding="shape")
_reg_many(["fft.fftshift", "fft.ifftshift"], sample=_u(), tol=_BF,
          sharding="shape")

register("signal.frame", dtypes=("float32",), sharding="shape",
         sample=lambda rng: ((rng.standard_normal((2, 16))
                              .astype(np.float32),),
                             {"frame_length": 8, "hop_length": 4}))
register("signal.overlap_add", dtypes=("float32",), sharding="shape",
         sample=lambda rng: ((rng.standard_normal((2, 8, 3))
                              .astype(np.float32),),
                             {"hop_length": 4}))
register("signal.stft", has_vjp=False, dtypes=("float32",),
         sharding="reduce",
         sample=lambda rng: ((rng.standard_normal((2, 32))
                              .astype(np.float32),),
                             {"n_fft": 8}))
register("signal.istft", dtypes=("float32",), sharding="reduce",
         sample=lambda rng: ((rng.standard_normal((2, 5, 7))
                              .astype(np.float32),),
                             {"n_fft": 8}))


# --- recurrent cell steps (nn/functional/rnn.py; reference nn/layer/rnn.py
# SimpleRNNCell/LSTMCell/GRUCell forward math) -------------------------------


def _rnn_cell_sample(kind):
    gates = {"simple": 1, "gru": 3, "lstm": 4}[kind]

    def f(rng):
        b, i, h = 4, 8, 6
        x = rng.standard_normal((b, i)).astype(np.float32)
        hs = rng.standard_normal((b, h)).astype(np.float32)
        w_ih = (0.3 * rng.standard_normal((gates * h, i))).astype(np.float32)
        w_hh = (0.3 * rng.standard_normal((gates * h, h))).astype(np.float32)
        b_ih = (0.1 * rng.standard_normal((gates * h,))).astype(np.float32)
        b_hh = (0.1 * rng.standard_normal((gates * h,))).astype(np.float32)
        if kind == "lstm":
            c = rng.standard_normal((b, h)).astype(np.float32)
            return (x, hs, c, w_ih, w_hh, b_ih, b_hh), {}
        return (x, hs, w_ih, w_hh, b_ih, b_hh), {}
    return f


register("nn.functional.simple_rnn_cell", sample=_rnn_cell_sample("simple"),
         tol=_LOOSE, sharding="contract")
register("nn.functional.lstm_cell", sample=_rnn_cell_sample("lstm"),
         tol=_LOOSE, sharding="contract")
register("nn.functional.gru_cell", sample=_rnn_cell_sample("gru"),
         tol=_LOOSE, sharding="contract")


# --- spatial transformers (nn/functional/vision.py; reference
# nn/functional/vision.py:26,130) --------------------------------------------

register("nn.functional.affine_grid", sharding="broadcast",
         sample=lambda rng: ((rng.standard_normal((2, 2, 3))
                              .astype(np.float32),),
                             {"out_shape": [2, 3, 4, 5]}))
register("nn.functional.grid_sample", sharding="gather", tol=_LOOSE,
         sample=lambda rng: ((rng.standard_normal((2, 3, 5, 6))
                              .astype(np.float32),
                              (rng.standard_normal((2, 4, 4, 2)) * 0.9)
                              .astype(np.float32)), {}))


# --- round-5 long-tail ops (ops/compat.py, nn/functional/extras.py) --------

register("addmm", sample=lambda rng: (
    (rng.standard_normal((4, 4)).astype(np.float32),
     rng.standard_normal((4, 6)).astype(np.float32),
     rng.standard_normal((6, 4)).astype(np.float32)), {}),
    tol=_LOOSE, sharding="contract")
register("cdist", sample=lambda rng: (
    (rng.standard_normal((4, 6)).astype(np.float32),
     rng.standard_normal((8, 6)).astype(np.float32)), {}),
    tol=_LOOSE, sharding="contract")
register("mv", sample=lambda rng: (
    (rng.standard_normal((4, 6)).astype(np.float32),
     rng.standard_normal((6,)).astype(np.float32)), {}),
    tol=_LOOSE, sharding="contract")
register("sgn", sample=_u(), sharding="elementwise")
register("i0e", sample=_u(), tol=_LOOSE, sharding="elementwise")
register("i1e", sample=_u(), tol=_LOOSE, sharding="elementwise")
register("trapezoid", sample=_u(), tol=_LOOSE, sharding="reduce")
register("cumulative_trapezoid", sample=_u(), tol=_LOOSE, sharding="reduce")
register("renorm", sharding="reduce", tol=_LOOSE,
         sample=lambda rng: ((rng.standard_normal((4, 8))
                              .astype(np.float32),),
                             {"p": 2.0, "axis": 0, "max_norm": 1.0}))
register("unflatten", sharding="shape",
         sample=lambda rng: ((rng.standard_normal((4, 6))
                              .astype(np.float32),),
                             {"axis": 1, "shape": [2, 3]}))
register("unfold", sharding="gather",
         sample=lambda rng: ((rng.standard_normal((4, 12))
                              .astype(np.float32),),
                             {"axis": 1, "size": 4, "step": 2}))
register("nn.functional.adaptive_avg_pool3d", tol=_LOOSE, sharding="reduce",
         sample=lambda rng: ((rng.standard_normal((2, 3, 4, 4, 4))
                              .astype(np.float32),), {"output_size": 2}))
register("nn.functional.adaptive_max_pool3d", tol=_LOOSE, sharding="reduce",
         sample=lambda rng: ((rng.standard_normal((2, 3, 4, 4, 4))
                              .astype(np.float32),), {"output_size": 2}))
register("nn.functional.zeropad2d", sharding="shape",
         sample=lambda rng: ((rng.standard_normal((2, 3, 4, 4))
                              .astype(np.float32),),
                             {"padding": [1, 1, 1, 1]}))
register("nn.functional.soft_margin_loss", tol=_LOOSE, sharding="reduce",
         sample=lambda rng: ((rng.standard_normal((4, 8))
                              .astype(np.float32),
                              np.sign(rng.standard_normal((4, 8)))
                              .astype(np.float32)), {}))
register("nn.functional.gaussian_nll_loss", tol=_LOOSE, sharding="reduce",
         sample=lambda rng: ((rng.standard_normal((4, 8))
                              .astype(np.float32),
                              rng.standard_normal((4, 8))
                              .astype(np.float32),
                              (np.abs(rng.standard_normal((4, 8))) + 0.5)
                              .astype(np.float32)), {}))
