"""Linear algebra ops (python/paddle/tensor/linalg.py + paddle.linalg parity).

matmul/einsum are the MXU hot path: dispatched through apply_op so AMP can keep
them in bfloat16 (the reference's analog is legacy_ops.yaml:649 matmul with its
MatmulSpmdInferForward sharding rule; here GSPMD infers sharding from operands).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..tensor import Tensor, apply_op, to_tensor

__all__ = [
    "matmul", "mm", "bmm", "einsum", "norm", "dist", "cholesky", "inverse",
    "det", "slogdet", "svd", "qr", "eig", "eigh", "eigvals", "eigvalsh",
    "solve", "triangular_solve", "cholesky_solve", "lstsq", "matrix_power",
    "matrix_rank", "pinv", "lu", "tensordot", "multi_dot", "cond", "cov",
    "corrcoef", "l2_normalize", "householder_product", "matrix_exp", "vander",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    x, y = _t(x), _t(y)

    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)

    return apply_op("matmul", f, x, y)


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return matmul(x, y)


def einsum(equation, *operands):
    tensors = [_t(o) for o in operands]
    return apply_op("einsum", lambda *xs: jnp.einsum(equation, *xs), *tensors)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    x = _t(x)
    if p is None:
        p = 2 if axis is not None or x.ndim == 1 else "fro"

    def f(a):
        if p == "fro":
            ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
            return jnp.sqrt(jnp.sum(jnp.square(a), axis=ax, keepdims=keepdim))
        if p == "nuc":
            return jnp.sum(jnp.linalg.svd(a, compute_uv=False), axis=-1, keepdims=keepdim)
        if p == float("inf"):
            return jnp.max(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == float("-inf"):
            return jnp.min(jnp.abs(a), axis=axis, keepdims=keepdim)
        if p == 0:
            return jnp.sum((a != 0).astype(a.dtype), axis=axis, keepdims=keepdim)
        ax = tuple(axis) if isinstance(axis, (list, tuple)) else axis
        return jnp.sum(jnp.abs(a) ** p, axis=ax, keepdims=keepdim) ** (1.0 / p)

    return apply_op("norm", f, x)


def dist(x, y, p=2, name=None):
    return norm(apply_op("sub", jnp.subtract, _t(x), _t(y)), p=float(p))


def l2_normalize(x, axis=-1, epsilon=1e-12, name=None):
    return apply_op("l2_normalize", lambda a: a / jnp.maximum(jnp.linalg.norm(a, axis=axis, keepdims=True), epsilon), _t(x))


def cholesky(x, upper=False, name=None):
    def f(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return apply_op("cholesky", f, _t(x))


def inverse(x, name=None):
    return apply_op("inverse", jnp.linalg.inv, _t(x))


def det(x, name=None):
    return apply_op("det", jnp.linalg.det, _t(x))


def slogdet(x, name=None):
    def f(a):
        sign, logabs = jnp.linalg.slogdet(a)
        return jnp.stack([sign, logabs])
    return apply_op("slogdet", f, _t(x))


def svd(x, full_matrices=False, name=None):
    return apply_op("svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), _t(x))


def qr(x, mode="reduced", name=None):
    return apply_op("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), _t(x))


def eig(x, name=None):
    x = _t(x)
    w, v = np.linalg.eig(np.asarray(x._data))
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    x = _t(x)
    return Tensor(jnp.asarray(np.linalg.eigvals(np.asarray(x._data))))


def eigh(x, UPLO="L", name=None):
    return apply_op("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), _t(x))


def eigvalsh(x, UPLO="L", name=None):
    return apply_op("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), _t(x))


def solve(x, y, name=None):
    return apply_op("solve", jnp.linalg.solve, _t(x), _t(y))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(a, b, lower=not upper, trans=1 if transpose else 0, unit_diagonal=unitriangular)
    return apply_op("triangular_solve", f, _t(x), _t(y))


def cholesky_solve(x, y, upper=False, name=None):
    def f(b, l):
        return jax.scipy.linalg.cho_solve((l, not upper), b)
    return apply_op("cholesky_solve", f, _t(x), _t(y))


def lstsq(x, y, rcond=None, driver=None, name=None):
    x, y = _t(x), _t(y)
    sol, res, rank_, sv = np.linalg.lstsq(np.asarray(x._data), np.asarray(y._data), rcond=rcond)
    return (Tensor(jnp.asarray(sol)), Tensor(jnp.asarray(res)), Tensor(jnp.asarray(rank_)), Tensor(jnp.asarray(sv)))


def matrix_power(x, n, name=None):
    return apply_op("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), _t(x))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op("matrix_rank", lambda a: jnp.linalg.matrix_rank(a, rtol=tol), _t(x))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op("pinv", lambda a: jnp.linalg.pinv(a, rtol=rcond, hermitian=hermitian), _t(x))


def lu(x, pivot=True, get_infos=False, name=None):
    x = _t(x)
    lu_, piv = jax.scipy.linalg.lu_factor(x._data)
    out = (Tensor(lu_), Tensor(piv.astype(jnp.int32) + 1))
    if get_infos:
        return out + (Tensor(jnp.zeros((), dtype=jnp.int32)),)
    return out


def tensordot(x, y, axes=2, name=None):
    x, y = _t(x), _t(y)
    ax = axes
    if isinstance(ax, Tensor):
        ax = ax.tolist()
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(a.tolist() if isinstance(a, Tensor) else a) if isinstance(a, (list, tuple, Tensor)) else a for a in ax)
    return apply_op("tensordot", lambda a, b: jnp.tensordot(a, b, axes=ax), x, y)


def multi_dot(x, name=None):
    tensors = [_t(i) for i in x]
    return apply_op("multi_dot", lambda *xs: jnp.linalg.multi_dot(list(xs)), *tensors)


def cond(x, p=None, name=None):
    return apply_op("cond", lambda a: jnp.linalg.cond(a, p=p), _t(x))


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op("cov", lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), _t(x))


def corrcoef(x, rowvar=True, name=None):
    return apply_op("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), _t(x))


def householder_product(x, tau, name=None):
    def f(a, t):
        return jax.scipy.linalg.lu(a)[0] if False else _householder(a, t)
    def _householder(a, t):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(n):
            v = jnp.concatenate([jnp.zeros(i, a.dtype), jnp.ones(1, a.dtype), a[i + 1:, i]])
            q = q - t[i] * (q @ v[:, None]) @ v[None, :]
        return q[:, :n]
    return apply_op("householder_product", f, _t(x), _t(tau))


def matrix_exp(x, name=None):
    return apply_op("matrix_exp", jax.scipy.linalg.expm, _t(x))


def vander(x, n=None, increasing=False, name=None):
    return apply_op("vander", lambda a: jnp.vander(a, N=n, increasing=increasing), _t(x))


def inv(x, name=None):
    """Alias of inverse (reference linalg.inv)."""
    return inverse(x, name=name)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack lu()'s packed factors into (P, L, U) (reference
    tensor/linalg.py lu_unpack; pivots are 1-based like lu())."""
    x, y = _t(x), _t(y)
    m, n = int(x.shape[-2]), int(x.shape[-1])
    k = min(m, n)

    def lu_part(a):
        tril_ = jnp.tril(a[..., :, :k], k=-1)
        eye = jnp.eye(m, k, dtype=a.dtype)
        return tril_ + eye

    def u_part(a):
        return jnp.triu(a[..., :k, :])

    L = apply_op("lu_unpack_l", lu_part, x) if unpack_ludata else None
    U = apply_op("lu_unpack_u", u_part, x) if unpack_ludata else None
    P = None
    if unpack_pivots:
        piv = np.asarray(y._data) - 1          # back to 0-based
        batch = piv.reshape(-1, piv.shape[-1])
        pmats = []
        for row in batch:                      # one P per batch element
            perm = np.arange(m)
            for i, pv in enumerate(row[:k]):
                perm[[i, int(pv)]] = perm[[int(pv), i]]
            pm = np.zeros((m, m), np.float32)
            pm[perm, np.arange(m)] = 1.0
            pmats.append(pm)
        pmat = np.stack(pmats).reshape(piv.shape[:-1] + (m, m))
        if np.asarray(x._data).dtype != np.dtype("bfloat16"):
            pmat = pmat.astype(np.asarray(x._data).dtype)
        P = to_tensor(pmat)
    return P, L, U


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA (reference tensor/linalg.py pca_lowrank): returns
    (U, S, V) with x ~ U diag(S) V^T over the top-q components."""
    x = _t(x)
    m, n = int(x.shape[-2]), int(x.shape[-1])
    if q is None:
        q = min(6, m, n)
    if not 0 <= q <= min(m, n):
        raise ValueError(f"q={q} out of range for shape {(m, n)}")

    # oversampled randomized range finder (Halko et al.; the reference
    # delegates to the same scheme) with re-orthonormalized power steps
    s_over = min(q + 6, m, n)

    def f(a, key):
        af = a.astype(jnp.float32)
        if center:
            af = af - af.mean(-2, keepdims=True)
        omega = jax.random.normal(key, a.shape[:-2] + (n, s_over),
                                  jnp.float32)
        y_, _ = jnp.linalg.qr(af @ omega)
        for _ in range(niter):
            z_, _ = jnp.linalg.qr(af.swapaxes(-1, -2) @ y_)
            y_, _ = jnp.linalg.qr(af @ z_)
        b = y_.swapaxes(-1, -2) @ af
        u_b, s, vt = jnp.linalg.svd(b, full_matrices=False)
        u = y_ @ u_b
        return u[..., :q], s[..., :q], vt.swapaxes(-1, -2)[..., :q]

    from ..framework import next_rng_key
    key = next_rng_key()
    return apply_op("pca_lowrank", lambda a: f(a, key), x)


__all__ += ["inv", "lu_unpack", "pca_lowrank"]
