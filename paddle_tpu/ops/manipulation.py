"""Shape / indexing / search ops (python/paddle/tensor/{manipulation,search}.py parity)."""

from __future__ import annotations

import builtins

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..framework import convert_dtype, to_jax_dtype
from ..tensor import Tensor, apply_op, to_tensor

__all__ = [
    "reshape", "reshape_", "transpose", "flatten", "squeeze", "squeeze_",
    "unsqueeze", "unsqueeze_", "concat", "stack", "split", "chunk", "tile",
    "expand", "expand_as", "broadcast_to", "broadcast_tensors", "cast", "cast_",
    "gather", "gather_nd", "scatter", "scatter_", "scatter_nd", "scatter_nd_add",
    "index_select", "index_sample", "index_add", "index_put", "masked_select",
    "masked_fill", "roll", "flip", "rot90", "unbind", "unstack",
    "repeat_interleave", "take_along_axis", "put_along_axis", "moveaxis",
    "swapaxes", "t", "as_complex", "as_real", "argmax", "argmin", "argsort",
    "sort", "topk", "nonzero", "unique", "unique_consecutive", "searchsorted",
    "kthvalue", "mode", "bucketize", "slice", "strided_slice", "shard_index",
    "numel", "rank", "shape", "tolist", "flatten_", "tensor_split", "view",
    "view_as", "atleast_1d", "atleast_2d", "atleast_3d", "diag_embed",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _static_shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def reshape(x, shape, name=None):
    x = _t(x)
    shp = _static_shape(shape)
    return apply_op("reshape", lambda a: a.reshape(shp), x)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return cast(x, shape_or_dtype)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def transpose(x, perm=None, name=None):
    x = _t(x)
    p = tuple(perm) if perm is not None else None
    return apply_op("transpose", lambda a: jnp.transpose(a, p), x)


def t(x, name=None):
    x = _t(x)
    if x.ndim < 2:
        return apply_op("t", lambda a: a, x)
    return apply_op("t", lambda a: a.T, x)


def moveaxis(x, source, destination, name=None):
    return apply_op("moveaxis", lambda a: jnp.moveaxis(a, source, destination), _t(x))


def swapaxes(x, axis0, axis1, name=None):
    return apply_op("swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), _t(x))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    x = _t(x)
    nd = x.ndim
    s = start_axis % nd if nd else 0
    e = stop_axis % nd if nd else 0
    shp = x.shape[:s] + [int(np.prod(x.shape[s:e + 1] or [1]))] + x.shape[e + 1:]
    return apply_op("flatten", lambda a: a.reshape(tuple(shp)), x)


def flatten_(x, start_axis=0, stop_axis=-1, name=None):
    out = flatten(x, start_axis, stop_axis)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


def squeeze(x, axis=None, name=None):
    x = _t(x)
    if axis is None:
        return apply_op("squeeze", lambda a: jnp.squeeze(a), x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    ax = tuple(a for a in ax if x.shape[a] == 1)
    return apply_op("squeeze", lambda a: jnp.squeeze(a, axis=ax) if ax else a, x)


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


def unsqueeze(x, axis, name=None):
    x = _t(x)
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    def f(a):
        out = a
        for d in sorted([d % (out.ndim + len(ax)) if d < 0 else d for d in ax]):
            out = jnp.expand_dims(out, d)
        return out
    return apply_op("unsqueeze", f, x)


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


def concat(x, axis=0, name=None):
    tensors = [_t(i) for i in x]
    ax = int(axis._data) if isinstance(axis, Tensor) else axis
    return apply_op("concat", lambda *xs: jnp.concatenate(xs, axis=ax), *tensors)


def stack(x, axis=0, name=None):
    tensors = [_t(i) for i in x]
    return apply_op("stack", lambda *xs: jnp.stack(xs, axis=axis), *tensors)


def split(x, num_or_sections, axis=0, name=None):
    x = _t(x)
    ax = int(axis._data) if isinstance(axis, Tensor) else axis
    dim = x.shape[ax]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"paddle.split: axis {ax} size {dim} is not divisible by "
                f"num={num_or_sections}; pass a sections list instead")
        sections = [dim // num_or_sections] * num_or_sections
    else:
        sections = [dim // len(num_or_sections) if s in (-1, None) else int(s) for s in num_or_sections]
        rem = dim - sum(s for s in sections)
        # resolve a single -1
        raw = list(num_or_sections)
        if any(s in (-1, None) for s in raw):
            known = sum(int(s) for s in raw if s not in (-1, None))
            sections = [int(s) if s not in (-1, None) else dim - known for s in raw]
    offsets = np.cumsum([0] + sections[:-1])
    def f(a):
        return tuple(jax.lax.slice_in_dim(a, int(o), int(o) + int(s), axis=ax) for o, s in zip(offsets, sections))
    return list(apply_op("split", f, x))


def tensor_split(x, num_or_indices, axis=0, name=None):
    x = _t(x)
    def f(a):
        return tuple(jnp.array_split(a, num_or_indices, axis=axis))
    return list(apply_op("tensor_split", f, x))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def unbind(x, axis=0, name=None):
    x = _t(x)
    n = x.shape[axis]
    def f(a):
        return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(a, n, axis=axis))
    return list(apply_op("unbind", f, x))


def unstack(x, axis=0, num=None, name=None):
    return unbind(x, axis)


def tile(x, repeat_times, name=None):
    reps = _static_shape(repeat_times)
    return apply_op("tile", lambda a: jnp.tile(a, reps), _t(x))


def expand(x, shape, name=None):
    x = _t(x)
    shp = list(_static_shape(shape))
    cur = x.shape
    for i in range(1, len(cur) + 1):
        if shp[-i] == -1:
            shp[-i] = cur[-i]
    return apply_op("expand", lambda a: jnp.broadcast_to(a, tuple(shp)), x)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    tensors = [_t(i) for i in inputs]
    return list(apply_op("broadcast_tensors", lambda *xs: tuple(jnp.broadcast_arrays(*xs)), *tensors))


def cast(x, dtype, name=None):
    x = _t(x)
    jd = to_jax_dtype(convert_dtype(dtype))
    return apply_op("cast", lambda a: a.astype(jd), x)


def cast_(x, dtype, name=None):
    out = cast(x, dtype)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


# -- indexing ---------------------------------------------------------------


def gather(x, index, axis=0, name=None):
    x, index = _t(x), _t(index)
    ax = int(axis._data) if isinstance(axis, Tensor) else axis
    return apply_op("gather", lambda a, i: jnp.take(a, i.reshape(-1) if i.ndim > 1 else i, axis=ax), x, index, nondiff=(1,))


def gather_nd(x, index, name=None):
    x, index = _t(x), _t(index)
    def f(a, i):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a[idx]
    return apply_op("gather_nd", f, x, index, nondiff=(1,))


def scatter(x, index, updates, overwrite=True, name=None):
    x, index, updates = _t(x), _t(index), _t(updates)
    def f(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            return a.at[i].set(u)
        return a.at[i].add(u)

    def f_zero(a, i, u):
        # paddle scatter overwrite=False semantics: zero the rows then add
        i = i.reshape(-1)
        zeroed = a.at[i].set(jnp.zeros_like(u))
        return zeroed.at[i].add(u)

    return apply_op("scatter", f if overwrite else f_zero, x, index, updates, nondiff=(1,))


def scatter_(x, index, updates, overwrite=True, name=None):
    out = scatter(x, index, updates, overwrite)
    x._data, x._node, x._out_idx = out._data, out._node, out._out_idx
    return x


def scatter_nd(index, updates, shape, name=None):
    index, updates = _t(index), _t(updates)
    shp = _static_shape(shape)
    def f(i, u):
        zeros = jnp.zeros(shp, dtype=u.dtype)
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return zeros.at[idx].add(u)
    return apply_op("scatter_nd", f, index, updates, nondiff=(0,))


def scatter_nd_add(x, index, updates, name=None):
    x, index, updates = _t(x), _t(index), _t(updates)
    def f(a, i, u):
        idx = tuple(jnp.moveaxis(i, -1, 0))
        return a.at[idx].add(u)
    return apply_op("scatter_nd_add", f, x, index, updates, nondiff=(1,))


def index_select(x, index, axis=0, name=None):
    x, index = _t(x), _t(index)
    return apply_op("index_select", lambda a, i: jnp.take(a, i, axis=axis), x, index, nondiff=(1,))


def index_sample(x, index):
    x, index = _t(x), _t(index)
    return apply_op("index_sample", lambda a, i: jnp.take_along_axis(a, i, axis=1), x, index, nondiff=(1,))


def index_add(x, index, axis, value, name=None):
    x, index, value = _t(x), _t(index), _t(value)
    def f(a, i, v):
        a_m = jnp.moveaxis(a, axis, 0)
        v_m = jnp.moveaxis(v, axis, 0)
        out = a_m.at[i].add(v_m)
        return jnp.moveaxis(out, 0, axis)
    return apply_op("index_add", f, x, index, value, nondiff=(1,))


def index_put(x, indices, value, accumulate=False, name=None):
    x = _t(x)
    idx = tuple(i._data if isinstance(i, Tensor) else i for i in indices)
    value = _t(value)
    def f(a, v):
        return a.at[idx].add(v) if accumulate else a.at[idx].set(v)
    return apply_op("index_put", f, x, value)


def masked_select(x, mask, name=None):
    x, mask = _t(x), _t(mask)
    # Dynamic output shape — eager only (not jit-traceable), like the reference op.
    arr = np.asarray(x._data)[np.asarray(mask._data)]
    return Tensor(jnp.asarray(arr))


def masked_fill(x, mask, value, name=None):
    x, mask = _t(x), _t(mask)
    v = value._data if isinstance(value, Tensor) else value
    return apply_op("masked_fill", lambda a, m: jnp.where(m, v, a), x, mask, nondiff=(1,))


def roll(x, shifts, axis=None, name=None):
    return apply_op("roll", lambda a: jnp.roll(a, shifts, axis=axis), _t(x))


def flip(x, axis, name=None):
    ax = tuple(axis) if isinstance(axis, (list, tuple)) else (axis,)
    return apply_op("flip", lambda a: jnp.flip(a, axis=ax), _t(x))


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), _t(x))


def repeat_interleave(x, repeats, axis=None, name=None):
    x = _t(x)
    r = repeats._data if isinstance(repeats, Tensor) else repeats
    return apply_op("repeat_interleave", lambda a: jnp.repeat(a, r, axis=axis), x)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    arr, indices = _t(arr), _t(indices)
    return apply_op("take_along_axis", lambda a, i: jnp.take_along_axis(a, i, axis=axis), arr, indices, nondiff=(1,))


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):
    arr, indices = _t(arr), _t(indices)
    values = _t(values)
    if reduce not in ("assign", "add", "mul", "multiply"):
        raise ValueError(
            f"put_along_axis reduce must be 'assign', 'add', 'mul' or "
            f"'multiply', got {reduce!r}")

    def f(a, i, v):
        # reference manipulation.py:4648 — reduce applies INTO the existing
        # values (include_self semantics); .at[] accumulates duplicates
        v = jnp.broadcast_to(v, i.shape).astype(a.dtype)
        a_m = jnp.moveaxis(a, axis, -1)
        i_m = jnp.moveaxis(i, axis, -1)
        v_m = jnp.moveaxis(v, axis, -1)
        idx_grid = jnp.indices(i_m.shape[:-1])
        full_idx = tuple(g[..., None] * jnp.ones_like(i_m) for g in idx_grid) + (i_m,)
        if reduce == "add":
            out = a_m.at[full_idx].add(v_m)
        elif reduce in ("mul", "multiply"):
            out = a_m.at[full_idx].multiply(v_m)
        else:
            out = a_m.at[full_idx].set(v_m)
        return jnp.moveaxis(out, -1, axis)

    return apply_op("put_along_axis", f, arr, indices, values, nondiff=(1,))


def as_complex(x, name=None):
    return apply_op("as_complex", lambda a: jax.lax.complex(a[..., 0], a[..., 1]), _t(x))


def as_real(x, name=None):
    return apply_op("as_real", lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], axis=-1), _t(x))


def atleast_1d(*inputs, name=None):
    outs = [apply_op("atleast_1d", jnp.atleast_1d, _t(x)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op("atleast_2d", jnp.atleast_2d, _t(x)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op("atleast_3d", jnp.atleast_3d, _t(x)) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    x = _t(input)
    def f(a):
        n = a.shape[-1]
        k = n + abs(offset)           # rows AND cols grow with the offset
        out = jnp.zeros(a.shape[:-1] + (k, k), dtype=a.dtype)
        eye_idx = jnp.arange(n)
        out = out.at[..., eye_idx + max(-offset, 0),
                     eye_idx + max(offset, 0)].set(a)
        # place dims
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        # build permutation mapping last two dims to d1, d2
        target = [None] * nd
        target[d1] = nd - 2
        target[d2] = nd - 1
        it = iter(perm)
        for i in range(nd):
            if target[i] is None:
                target[i] = next(it)
        return jnp.transpose(out, tuple(np.argsort(np.argsort(target)) if False else target))
    return apply_op("diag_embed", f, x)


# -- search -----------------------------------------------------------------


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply_op("argmax", lambda a: jnp.argmax(a, axis=axis, keepdims=keepdim).astype(to_jax_dtype(convert_dtype(dtype))), _t(x))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply_op("argmin", lambda a: jnp.argmin(a, axis=axis, keepdims=keepdim).astype(to_jax_dtype(convert_dtype(dtype))), _t(x))


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        idx = jnp.argsort(a, axis=axis, stable=stable or True)
        if descending:
            idx = jnp.flip(idx, axis=axis)
        return idx
    return apply_op("argsort", f, _t(x))


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        s = jnp.sort(a, axis=axis)
        if descending:
            s = jnp.flip(s, axis=axis)
        return s
    return apply_op("sort", f, _t(x))


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    x = _t(x)
    kk = int(k._data) if isinstance(k, Tensor) else int(k)
    def f(a):
        ax = axis % a.ndim
        a_m = jnp.moveaxis(a, ax, -1)
        if largest:
            v, i = jax.lax.top_k(a_m, kk)
        else:
            v, i = jax.lax.top_k(-a_m, kk)
            v = -v
        return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i.astype(jnp.int64), -1, ax)
    return apply_op("topk", f, x)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = _t(x)
    def f(a):
        s = jnp.sort(a, axis=axis)
        i = jnp.argsort(a, axis=axis, stable=True)
        v = jnp.take(s, k - 1, axis=axis)
        ix = jnp.take(i, k - 1, axis=axis).astype(jnp.int64)
        if keepdim:
            v = jnp.expand_dims(v, axis)
            ix = jnp.expand_dims(ix, axis)
        return v, ix
    return apply_op("kthvalue", f, x)


def _mode_1d(a):
    """Reference semantics (test/legacy_test/test_mode_op.py:29): among the
    most frequent values pick the smallest; the index is the LAST original
    position of that value (stable argsort order)."""
    si = np.argsort(a, kind="stable")
    sa = a[si]
    new_run = np.concatenate([[True], sa[1:] != sa[:-1]])
    run_ids = np.cumsum(new_run) - 1
    counts = np.bincount(run_ids)
    best = int(np.argmax(counts))     # first max -> smallest value
    end = int(np.flatnonzero(run_ids == best)[-1])
    return sa[end], si[end]


def mode(x, axis=-1, keepdim=False, name=None):
    """(values, indices) of the most frequent element along `axis`
    (reference python/paddle/tensor/search.py mode + mode_kernel)."""
    x = _t(x)
    arr = np.asarray(x._data)
    ax = axis % arr.ndim if arr.ndim else 0
    moved = np.moveaxis(arr, ax, -1)
    flat = moved.reshape(-1, moved.shape[-1])
    vals = np.empty(flat.shape[0], dtype=arr.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for i in range(flat.shape[0]):
        vals[i], idxs[i] = _mode_1d(flat[i])
    vals = vals.reshape(moved.shape[:-1])
    idxs = idxs.reshape(moved.shape[:-1])
    if keepdim:
        vals = np.expand_dims(vals, ax)
        idxs = np.expand_dims(idxs, ax)
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(idxs))


def nonzero(x, as_tuple=False, name=None):
    x = _t(x)
    nz = np.nonzero(np.asarray(x._data))
    if as_tuple:
        return tuple(Tensor(jnp.asarray(n[:, None])) for n in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)))


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = _t(x)
    res = np.unique(np.asarray(x._data), return_index=return_index, return_inverse=return_inverse, return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    x = _t(x)
    arr = np.asarray(x._data)
    if axis is None:
        arr = arr.reshape(-1)
        keep = np.concatenate([[True], arr[1:] != arr[:-1]])
        out = arr[keep]
        outs = [Tensor(jnp.asarray(out))]
        if return_inverse:
            inv = np.cumsum(keep) - 1
            outs.append(Tensor(jnp.asarray(inv)))
        if return_counts:
            idx = np.flatnonzero(keep)
            counts = np.diff(np.append(idx, arr.size))
            outs.append(Tensor(jnp.asarray(counts)))
        return outs[0] if len(outs) == 1 else tuple(outs)
    # N-D path: dedupe consecutive SLICES along `axis`
    # (reference unique_consecutive_kernel axis branch)
    ax = axis % arr.ndim
    moved = np.moveaxis(arr, ax, 0)
    if moved.shape[0] == 0:
        keep = np.zeros((0,), bool)
    else:
        diff = np.any(moved[1:] != moved[:-1],
                      axis=tuple(range(1, moved.ndim)))
        keep = np.concatenate([[True], diff])
    out = np.moveaxis(moved[keep], 0, ax)
    outs = [Tensor(jnp.asarray(out))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        outs.append(Tensor(jnp.asarray(inv)))
    if return_counts:
        idx = np.flatnonzero(keep)
        counts = np.diff(np.append(idx, moved.shape[0]))
        outs.append(Tensor(jnp.asarray(counts)))
    return outs[0] if len(outs) == 1 else tuple(outs)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    s, v = _t(sorted_sequence), _t(values)
    side = "right" if right else "left"
    def f(a, b):
        if a.ndim == 1:
            out = jnp.searchsorted(a, b, side=side)
        else:
            out = jax.vmap(lambda aa, bb: jnp.searchsorted(aa, bb, side=side))(a.reshape(-1, a.shape[-1]), b.reshape(-1, b.shape[-1])).reshape(b.shape)
        return out.astype(jnp.int32 if out_int32 else jnp.int64)
    return apply_op("searchsorted", f, s, v)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    x = _t(input)
    size = index_num // nshards
    def f(i):
        shard = i // size
        return jnp.where(shard == shard_id, i % size, ignore_value)
    return apply_op("shard_index", f, x)


def slice(input, axes, starts, ends):
    x = _t(input)
    idx = [builtins.slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        st = int(st._data) if isinstance(st, Tensor) else int(st)
        en = int(en._data) if isinstance(en, Tensor) else int(en)
        idx[ax] = builtins.slice(st, en)
    idx = tuple(idx)
    return apply_op("slice", lambda a: a[idx], x)


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = _t(x)
    idx = [builtins.slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = builtins.slice(int(st), int(en), int(sd))
    idx = tuple(idx)
    return apply_op("strided_slice", lambda a: a[idx], x)


# -- metadata ---------------------------------------------------------------


def numel(x, name=None):
    return Tensor(jnp.asarray(int(np.prod(_t(x).shape)) if _t(x).shape else 1, dtype=jnp.int64))


def rank(input):
    return Tensor(jnp.asarray(_t(input).ndim, dtype=jnp.int32))


def shape(input):
    return Tensor(jnp.asarray(_t(input).shape, dtype=jnp.int32))


def tolist(x):
    return _t(x).tolist()
