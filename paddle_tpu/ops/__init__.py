"""Op namespace + Tensor method/operator patching.

Single source of op definitions; this module plays the role of the reference's
YAML→codegen pipeline output (phi/api/yaml + eager_math_op_patch.cc): each op is
defined once and exposed as (a) a paddle_tpu.* function, (b) a Tensor method,
(c) an operator overload.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..tensor import Tensor, apply_op, to_tensor
from . import creation, linalg, manipulation, math, random  # noqa: F401
from .creation import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403

__all__ = (
    list(creation.__all__) + list(math.__all__) + list(manipulation.__all__)
    + list(linalg.__all__) + list(random.__all__)
)


def _swap(fn):
    return lambda self, other: fn(other, self)


def _patch_tensor_methods():
    T = Tensor
    # arithmetic operators
    T.__add__ = lambda s, o: math.add(s, o)
    T.__radd__ = lambda s, o: math.add(o, s)
    T.__sub__ = lambda s, o: math.subtract(s, o)
    T.__rsub__ = lambda s, o: math.subtract(o, s)
    T.__mul__ = lambda s, o: math.multiply(s, o)
    T.__rmul__ = lambda s, o: math.multiply(o, s)
    T.__truediv__ = lambda s, o: math.divide(s, o)
    T.__rtruediv__ = lambda s, o: math.divide(o, s)
    T.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    T.__rfloordiv__ = lambda s, o: math.floor_divide(o, s)
    T.__mod__ = lambda s, o: math.mod(s, o)
    T.__rmod__ = lambda s, o: math.mod(o, s)
    T.__pow__ = lambda s, o: math.pow(s, o)
    T.__rpow__ = lambda s, o: math.pow(o, s)
    T.__matmul__ = lambda s, o: linalg.matmul(s, o)
    T.__rmatmul__ = lambda s, o: linalg.matmul(o, s)
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__invert__ = lambda s: math.logical_not(s)
    T.__and__ = lambda s, o: math.bitwise_and(s, o)
    T.__or__ = lambda s, o: math.bitwise_or(s, o)
    T.__xor__ = lambda s, o: math.bitwise_xor(s, o)
    # comparisons
    T.__eq__ = lambda s, o: math.equal(s, o)
    T.__ne__ = lambda s, o: math.not_equal(s, o)
    T.__lt__ = lambda s, o: math.less_than(s, o)
    T.__le__ = lambda s, o: math.less_equal(s, o)
    T.__gt__ = lambda s, o: math.greater_than(s, o)
    T.__ge__ = lambda s, o: math.greater_equal(s, o)

    # methods: every op whose first arg is a tensor
    method_sources = [creation, math, manipulation, linalg, random]
    skip = {"zeros", "ones", "full", "empty", "arange", "linspace", "logspace",
            "eye", "meshgrid", "rand", "randn", "randint", "randperm", "uniform",
            "normal", "standard_normal", "tril_indices", "triu_indices",
            "broadcast_shape", "as_tensor", "log_normal", "binomial", "scatter_nd"}
    for mod in method_sources:
        for name in mod.__all__:
            if name in skip or hasattr(T, name):
                continue
            setattr(T, name, getattr(mod, name))

    # paddle-specific method aliases
    T.astype = lambda s, dtype: manipulation.cast(s, dtype)
    T.cast = lambda s, dtype: manipulation.cast(s, dtype)
    T.dim = lambda s: s.ndim
    T.add_ = lambda s, o: _inplace(s, math.add(s, o))
    T.subtract_ = lambda s, o: _inplace(s, math.subtract(s, o))
    T.multiply_ = lambda s, o: _inplace(s, math.multiply(s, o))
    T.divide_ = lambda s, o: _inplace(s, math.divide(s, o))
    T.clip_ = lambda s, min=None, max=None: _inplace(s, math.clip(s, min, max))
    T.scale_ = lambda s, scale=1.0, bias=0.0, bias_after_scale=True, act=None: _inplace(
        s, math.scale(s, scale, bias, bias_after_scale))
    T.zero_ = lambda s: _inplace(s, creation.zeros_like(s))
    T.fill_ = lambda s, v: _inplace(s, creation.full_like(s, v))
    T.exp_ = lambda s: _inplace(s, math.exp(s))
    T.sqrt_ = lambda s: _inplace(s, math.sqrt(s))
    T.rsqrt_ = lambda s: _inplace(s, math.rsqrt(s))
    T.tanh_ = lambda s: _inplace(s, math.tanh(s))
    T.remainder_ = lambda s, o: _inplace(s, math.mod(s, o))
    T.floor_ = lambda s: _inplace(s, math.floor(s))
    T.ceil_ = lambda s: _inplace(s, math.ceil(s))
    T.round_ = lambda s: _inplace(s, math.round(s))
    T.abs_ = lambda s: _inplace(s, math.abs(s))
    T.sigmoid_ = lambda s: _inplace(s, math.sigmoid(s))

    @property
    def _T(s):
        return manipulation.transpose(s, list(range(s.ndim))[::-1])

    T.T = _T

    @property
    def _mT(s):
        perm = list(range(s.ndim))
        perm[-1], perm[-2] = perm[-2], perm[-1]
        return manipulation.transpose(s, perm)

    T.mT = _mT


def _inplace(t, out):
    node = out._node
    if node is not None:
        # the recorded node input must keep pointing at the ORIGINAL
        # value/history — after redirection `t` IS the node's output, and a
        # self-referential input would cut the backward chain (grad through
        # x*2 -> tanh_(y) -> sum never reached x)
        ghost = Tensor(t._data, stop_gradient=t.stop_gradient)
        ghost._node, ghost._out_idx = t._node, t._out_idx
        node.inputs = tuple(ghost if i is t else i for i in node.inputs)
        if t._node is not None:
            # the old producer must now hand ITS cotangent slot to the
            # ghost (backward keys accumulators by tensor identity)
            oo = list(t._node.outputs)
            oo[t._out_idx] = ghost
            t._node.outputs = tuple(oo)
    t._data, t._node, t._out_idx = out._data, out._node, out._out_idx
    if node is not None:
        outs = list(node.outputs)
        outs[out._out_idx] = t
        node.outputs = tuple(outs)
    return t


_patch_tensor_methods()

# long-tail compat surface (imported AFTER _inplace above — the compat
# op_ family resolves `_inplace` from this module at call time)
from . import compat  # noqa: E402,F401
from .compat import *  # noqa: E402,F401,F403

__all__ = __all__ + list(compat.__all__)

_TENSOR_METHOD_SAFE = [
    n for n in compat.__all__
    if n not in {"finfo", "iinfo", "set_printoptions", "get_rng_state",
                 "set_rng_state", "get_cuda_rng_state", "set_cuda_rng_state",
                 "disable_signal_handler", "check_shape", "flops", "batch",
                 "LazyGuard", "DataParallel", "create_parameter",
                 "CUDAPinnedPlace", "polar", "is_empty"}
]
for _n in _TENSOR_METHOD_SAFE:
    if not hasattr(Tensor, _n):
        setattr(Tensor, _n, getattr(compat, _n))
del _n


# Tensor-method parity stragglers (reference tensor/__init__.py
# tensor_method_func): names that are module-level factories/predicates
# the reference ALSO binds as methods.  The erfinv_/lerp_/reciprocal_/
# put_along_axis_ inplace family is generated by compat's _INPLACE_BASES
# like every other op_.
def _bind_method_stragglers():
    from ..tensor import is_tensor as _is_tensor

    if not hasattr(Tensor, "is_tensor"):
        Tensor.is_tensor = lambda self: _is_tensor(self)
    if "create_tensor" not in globals():
        def _create_tensor(dtype="float32", *a, **k):
            from .creation import zeros
            return zeros([0], dtype=dtype)
        globals()["create_tensor"] = _create_tensor
        __all__.append("create_tensor")
    # factories bind as STATIC methods (self must not become `shape`)
    _static = {"broadcast_shape", "create_tensor", "create_parameter"}
    for fact in ("broadcast_shape", "create_tensor", "scatter_nd", "polar",
                 "is_empty", "create_parameter"):
        fn = globals().get(fact) or getattr(compat, fact, None)
        if fn is not None and not hasattr(Tensor, fact):
            setattr(Tensor, fact,
                    staticmethod(fn) if fact in _static else fn)


_bind_method_stragglers()
del _bind_method_stragglers
