"""Tensor creation ops (python/paddle/tensor/creation.py parity)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from .. import framework
from ..framework import convert_dtype, to_jax_dtype
from ..tensor import Tensor, apply_op, to_tensor

__all__ = [
    "zeros", "ones", "full", "empty", "zeros_like", "ones_like", "full_like",
    "empty_like", "arange", "linspace", "logspace", "eye", "diag", "diagflat",
    "tril", "triu", "meshgrid", "assign", "clone", "tril_indices", "triu_indices",
    "complex", "as_tensor",
]


def _dt(dtype):
    return to_jax_dtype(convert_dtype(dtype) if dtype is not None else framework.get_default_dtype())


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), dtype=_dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), dtype=_dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    if dtype is None:
        if isinstance(fill_value, bool):
            dtype = "bool"
        elif isinstance(fill_value, int):
            dtype = framework.get_default_dtype()
        else:
            dtype = framework.get_default_dtype()
    return Tensor(jnp.full(_shape(shape), fill_value, dtype=_dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype=dtype)


def zeros_like(x, dtype=None, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return Tensor(jnp.zeros_like(x._data, dtype=to_jax_dtype(convert_dtype(dtype)) if dtype else None))


def ones_like(x, dtype=None, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return Tensor(jnp.ones_like(x._data, dtype=to_jax_dtype(convert_dtype(dtype)) if dtype else None))


def full_like(x, fill_value, dtype=None, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return Tensor(jnp.full_like(x._data, fill_value, dtype=to_jax_dtype(convert_dtype(dtype)) if dtype else None))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype=dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange expects python scalars")
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = "int64" if all(isinstance(v, int) for v in (start, end, step)) else framework.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=_dt(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    return Tensor(jnp.linspace(float(start), float(stop), int(num), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(float(start), float(stop), int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), num_columns if num_columns is None else int(num_columns), dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    if x.ndim == 1 and padding_value != 0:
        return apply_op(
            "diag",
            lambda a: jnp.where(jnp.eye(a.shape[0], dtype=bool), 0, padding_value).astype(a.dtype)
            + jnp.diag(a, k=offset),
            x,
        )
    return apply_op("diag", lambda a: jnp.diag(a, k=offset), x)


def diagflat(x, offset=0, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return apply_op("diagflat", lambda a: jnp.diagflat(a, k=offset), x)


def tril(x, diagonal=0, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return apply_op("tril", lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    return apply_op("triu", lambda a: jnp.triu(a, k=diagonal), x)


def tril_indices(row, col, offset=0, dtype="int64"):
    r, c = np.tril_indices(row, offset, col)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r, c = np.triu_indices(row, offset, col if col is not None else row)
    return Tensor(jnp.asarray(np.stack([r, c]), dtype=_dt(dtype)))


def meshgrid(*args, **kwargs):
    tensors = [to_tensor(a) if not isinstance(a, Tensor) else a for a in (args[0] if len(args) == 1 and isinstance(args[0], (list, tuple)) else args)]
    return apply_op("meshgrid", lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), *tensors)


def assign(x, output=None):
    x = to_tensor(x) if not isinstance(x, Tensor) else x
    out = apply_op("assign", lambda a: a + 0, x)
    if output is not None:
        output._data = out._data
        output._node = out._node
        output._out_idx = out._out_idx
        return output
    return out


def clone(x, name=None):
    return assign(x)


def complex(real, imag, name=None):
    return apply_op("complex", jnp.complex_ if False else (lambda r, i: r + 1j * i), real, imag)


def as_tensor(data, dtype=None):
    return to_tensor(data, dtype=dtype)
