"""Math / reduction / logic ops (python/paddle/tensor/{math,logic,stat}.py parity).

Every op is a thin pure-JAX function dispatched through `apply_op`, which records
the autograd tape and applies AMP casts — the analog of the generated
paddle::experimental API (phi/api/yaml/generator/api_gen.py output).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..framework import convert_dtype, to_jax_dtype
from ..tensor import Tensor, apply_op, to_tensor

__all__ = []  # populated below


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _export(name, fn):
    globals()[name] = fn
    __all__.append(name)
    return fn


# ---------------------------------------------------------------------------
# unary elementwise
# ---------------------------------------------------------------------------

_UNARY = {
    "exp": jnp.exp, "expm1": jnp.expm1, "log": jnp.log, "log2": jnp.log2,
    "log10": jnp.log10, "log1p": jnp.log1p, "sqrt": jnp.sqrt,
    "rsqrt": jax.lax.rsqrt, "abs": jnp.abs, "sign": jnp.sign,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan, "asin": jnp.arcsin,
    "acos": jnp.arccos, "atan": jnp.arctan, "sinh": jnp.sinh, "cosh": jnp.cosh,
    "tanh": jnp.tanh, "asinh": jnp.arcsinh, "acosh": jnp.arccosh,
    "atanh": jnp.arctanh, "floor": jnp.floor, "ceil": jnp.ceil,
    "round": jnp.round, "trunc": jnp.trunc, "frac": lambda x: x - jnp.trunc(x),
    "square": jnp.square, "reciprocal": lambda x: 1.0 / x,
    "neg": jnp.negative, "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv, "lgamma": jax.scipy.special.gammaln,
    "digamma": jax.scipy.special.digamma, "i0": jax.scipy.special.i0,
    "i1": jax.scipy.special.i1, "sigmoid": jax.nn.sigmoid,
    "angle": jnp.angle, "conj": jnp.conj,
    "real": jnp.real, "imag": jnp.imag, "rad2deg": jnp.rad2deg,
    "deg2rad": jnp.deg2rad, "exponential_": None,
}

for _name, _jfn in _UNARY.items():
    if _jfn is None:
        continue
    def _make(nm, jfn):
        def fn(x, name=None):
            return apply_op(nm, jfn, _t(x))
        fn.__name__ = nm
        return fn
    _export(_name, _make(_name, _jfn))

def _logit(x, eps=None, name=None):
    """Reference tensor/math.py:5166 — x clamped to [eps, 1-eps] first when
    eps is given; eps=None leaves out-of-range inputs to produce NaN."""
    x = _t(x)

    def f(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jax.scipy.special.logit(a)

    return apply_op("logit", f, x)


_export("logit", _logit)

_export("isnan", lambda x, name=None: apply_op("isnan", jnp.isnan, _t(x)))
_export("isinf", lambda x, name=None: apply_op("isinf", jnp.isinf, _t(x)))
_export("isfinite", lambda x, name=None: apply_op("isfinite", jnp.isfinite, _t(x)))


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op("nan_to_num", lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), _t(x))


_export("nan_to_num", nan_to_num)


# ---------------------------------------------------------------------------
# binary elementwise
# ---------------------------------------------------------------------------

_BINARY = {
    "add": jnp.add, "subtract": jnp.subtract, "multiply": jnp.multiply,
    "divide": jnp.divide, "floor_divide": jnp.floor_divide,
    "mod": jnp.mod, "remainder": jnp.mod, "floor_mod": jnp.mod,
    "pow": jnp.power, "maximum": jnp.maximum, "minimum": jnp.minimum,
    "fmax": jnp.fmax, "fmin": jnp.fmin, "atan2": jnp.arctan2,
    "logaddexp": jnp.logaddexp, "hypot": jnp.hypot,
    "gcd": jnp.gcd, "lcm": jnp.lcm, "heaviside": jnp.heaviside,
    "copysign": jnp.copysign, "nextafter": jnp.nextafter,
    "ldexp": jnp.ldexp, "inner": jnp.inner, "outer": jnp.outer,
    "kron": jnp.kron,
}

for _name, _jfn in _BINARY.items():
    def _make2(nm, jfn):
        def fn(x, y, name=None):
            return apply_op(nm, jfn, _t(x), _t(y))
        fn.__name__ = nm
        return fn
    _export(_name, _make2(_name, _jfn))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s, b = scale, bias
    if isinstance(s, Tensor):
        s = s._data
    if bias_after_scale:
        out = apply_op("scale", lambda a: a * s + b, _t(x))
    else:
        out = apply_op("scale", lambda a: (a + b) * s, _t(x))
    return out


_export("scale", scale)


def lerp(x, y, weight, name=None):
    if isinstance(weight, Tensor):
        return apply_op("lerp", lambda a, b, w: a + w * (b - a), _t(x), _t(y), weight)
    return apply_op("lerp", lambda a, b: a + weight * (b - a), _t(x), _t(y))


_export("lerp", lerp)


def clip(x, min=None, max=None, name=None):
    lo = min._data if isinstance(min, Tensor) else min
    hi = max._data if isinstance(max, Tensor) else max
    return apply_op("clip", lambda a: jnp.clip(a, lo, hi), _t(x))


_export("clip", clip)


def add_n(inputs, name=None):
    inputs = [_t(i) for i in (inputs if isinstance(inputs, (list, tuple)) else [inputs])]
    # NB builtins.sum, NOT this module's reduce `sum` (which _export binds
    # into globals and whose second positional arg is `axis`)
    import builtins
    return apply_op("add_n",
                    lambda *xs: builtins.sum(xs[1:], xs[0]), *inputs)


_export("add_n", add_n)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), _t(x))


_export("stanh", stanh)


def multiplex(inputs, index, name=None):
    inputs = [_t(i) for i in inputs]
    idx = _t(index)
    return apply_op(
        "multiplex",
        lambda ix, *xs: jnp.stack(xs, 0)[ix.reshape(-1), jnp.arange(xs[0].shape[0])],
        idx, *inputs, nondiff=(0,),
    )


_export("multiplex", multiplex)


# ---------------------------------------------------------------------------
# reductions
# ---------------------------------------------------------------------------


def _norm_axis(axis):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return axis if axis is None else int(axis)


def _make_reduce(nm, jfn, dtype_arg=False):
    def fn(x, axis=None, keepdim=False, name=None, dtype=None):
        x = _t(x)
        ax = _norm_axis(axis)
        kw = {}
        if dtype_arg and dtype is not None:
            kw["dtype"] = to_jax_dtype(convert_dtype(dtype))
        return apply_op(nm, lambda a: jfn(a, axis=ax, keepdims=keepdim, **kw), x)
    fn.__name__ = nm
    return fn


_export("sum", _make_reduce("sum", jnp.sum, dtype_arg=True))
_export("mean", _make_reduce("mean", jnp.mean))
_export("prod", _make_reduce("prod", jnp.prod, dtype_arg=True))
_export("max", _make_reduce("max", jnp.max))
_export("min", _make_reduce("min", jnp.min))
_export("amax", _make_reduce("amax", jnp.max))
_export("amin", _make_reduce("amin", jnp.min))
_export("nansum", _make_reduce("nansum", jnp.nansum, dtype_arg=True))
_export("nanmean", _make_reduce("nanmean", jnp.nanmean))
_export("all", _make_reduce("all", jnp.all))
_export("any", _make_reduce("any", jnp.any))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op("std", lambda a: jnp.std(a, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), _t(x))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op("var", lambda a: jnp.var(a, axis=_norm_axis(axis), ddof=1 if unbiased else 0, keepdims=keepdim), _t(x))


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply_op("median", lambda a: jnp.median(a, axis=_norm_axis(axis), keepdims=keepdim), _t(x))


def nanmedian(x, axis=None, keepdim=False, name=None):
    return apply_op("nanmedian", lambda a: jnp.nanmedian(a, axis=_norm_axis(axis), keepdims=keepdim), _t(x))


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply_op("quantile", lambda a: jnp.quantile(a, jnp.asarray(q), axis=_norm_axis(axis), keepdims=keepdim, method=interpolation), _t(x))


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply_op("logsumexp", lambda a: jax.scipy.special.logsumexp(a, axis=_norm_axis(axis), keepdims=keepdim), _t(x))


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op("count_nonzero", lambda a: jnp.count_nonzero(a, axis=_norm_axis(axis), keepdims=keepdim), _t(x))


for _n in ("std", "var", "median", "nanmedian", "quantile", "logsumexp", "count_nonzero"):
    _export(_n, globals()[_n])


# ---------------------------------------------------------------------------
# cumulative
# ---------------------------------------------------------------------------


def cumsum(x, axis=None, dtype=None, name=None):
    x = _t(x)
    ax = axis
    if ax is None:
        return apply_op("cumsum", lambda a: jnp.cumsum(a.reshape(-1)), x)
    return apply_op("cumsum", lambda a: jnp.cumsum(a, axis=ax), x)


def cumprod(x, dim=None, dtype=None, name=None):
    x = _t(x)
    if dim is None:
        return apply_op("cumprod", lambda a: jnp.cumprod(a.reshape(-1)), x)
    return apply_op("cumprod", lambda a: jnp.cumprod(a, axis=dim), x)


def _cum_extreme(nm, cmp):
    def fn(x, axis=None, dtype="int64", name=None):
        x = _t(x)
        flat = axis is None
        ax = 0 if flat else axis
        def f(a):
            if flat:
                a = a.reshape(-1)
            iota = jax.lax.broadcasted_iota(jnp.int64, a.shape, ax if ax >= 0 else a.ndim + ax)
            def combine(l, r):
                lv, li = l
                rv, ri = r
                take_r = cmp(rv, lv)
                return jnp.where(take_r, rv, lv), jnp.where(take_r, ri, li)
            return jax.lax.associative_scan(combine, (a, iota), axis=ax)
        return apply_op(nm, f, x)
    fn.__name__ = nm
    return fn


cummax = _cum_extreme("cummax", lambda r, l: r >= l)
cummin = _cum_extreme("cummin", lambda r, l: r <= l)


def logcumsumexp(x, axis=None, name=None):
    x = _t(x)
    ax = axis
    def f(a):
        if ax is None:
            a = a.reshape(-1)
            axis_ = 0
        else:
            axis_ = ax
        return jax.lax.associative_scan(jnp.logaddexp, a, axis=axis_)
    return apply_op("logcumsumexp", f, x)


for _n in ("cumsum", "cumprod", "cummax", "cummin", "logcumsumexp"):
    _export(_n, globals()[_n])


# ---------------------------------------------------------------------------
# comparisons & logic
# ---------------------------------------------------------------------------

_CMP = {
    "equal": jnp.equal, "not_equal": jnp.not_equal,
    "less_than": jnp.less, "less_equal": jnp.less_equal,
    "greater_than": jnp.greater, "greater_equal": jnp.greater_equal,
    "logical_and": jnp.logical_and, "logical_or": jnp.logical_or,
    "logical_xor": jnp.logical_xor,
    "bitwise_and": jnp.bitwise_and, "bitwise_or": jnp.bitwise_or,
    "bitwise_xor": jnp.bitwise_xor,
}

for _name, _jfn in _CMP.items():
    def _makec(nm, jfn):
        def fn(x, y, name=None):
            return apply_op(nm, jfn, _t(x), _t(y))
        fn.__name__ = nm
        return fn
    _export(_name, _makec(_name, _jfn))

_export("logical_not", lambda x, name=None: apply_op("logical_not", jnp.logical_not, _t(x)))
_export("bitwise_not", lambda x, name=None: apply_op("bitwise_not", jnp.bitwise_not, _t(x)))


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op("isclose", lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), _t(x), _t(y))


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op("allclose", lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol, equal_nan=equal_nan), _t(x), _t(y))


def equal_all(x, y, name=None):
    return apply_op("equal_all", lambda a, b: jnp.array_equal(a, b), _t(x), _t(y))


def where(condition, x=None, y=None, name=None):
    condition = _t(condition)
    if x is None and y is None:
        import jax.numpy as _j
        nz = np.nonzero(np.asarray(condition._data))
        return tuple(Tensor(jnp.asarray(n)) for n in nz)
    return apply_op("where", lambda c, a, b: jnp.where(c, a, b), condition, _t(x), _t(y), nondiff=(0,))


for _n in ("isclose", "allclose", "equal_all", "where"):
    _export(_n, globals()[_n])


# ---------------------------------------------------------------------------
# misc math
# ---------------------------------------------------------------------------


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("trace", lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), _t(x))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op("diagonal", lambda a: jnp.diagonal(a, offset=offset, axis1=axis1, axis2=axis2), _t(x))


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    return apply_op("diff", lambda a: jnp.diff(a, n=n, axis=axis), _t(x))


def cross(x, y, axis=9, name=None):
    ax = axis if axis != 9 else -1
    return apply_op("cross", lambda a, b: jnp.cross(a, b, axis=ax), _t(x), _t(y))


def dot(x, y, name=None):
    return apply_op("dot", lambda a, b: jnp.sum(a * b, axis=-1), _t(x), _t(y))


def histogram(x, bins=100, min=0, max=0, name=None):
    x = _t(x)
    arr = np.asarray(x._data)
    lo, hi = (arr.min(), arr.max()) if min == 0 and max == 0 else (min, max)
    h, _ = np.histogram(arr, bins=bins, range=(lo, hi))
    return Tensor(jnp.asarray(h, dtype=jnp.int64))


def bincount(x, weights=None, minlength=0, name=None):
    x = _t(x)
    if weights is not None:
        return apply_op("bincount", lambda i, w: jnp.bincount(i, weights=w, minlength=minlength, length=None), x, _t(weights), nondiff=(0,))
    arr = np.asarray(x._data)
    return Tensor(jnp.asarray(np.bincount(arr, minlength=minlength)))


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


for _n in ("trace", "diagonal", "diff", "cross", "dot", "histogram", "bincount", "broadcast_shape"):
    _export(_n, globals()[_n])
