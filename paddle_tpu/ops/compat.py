"""Long-tail tensor API (reference python/paddle/tensor/{math,manipulation,
attribute}.py odds and ends + python/paddle/framework shims).

Three groups:
  * remaining base ops (addmm, cdist, take, renorm, trapezoid family, ...)
    — plain apply_op compositions like the rest of ops/;
  * the inplace ``op_`` family — paddle's eager inplace API.  TPU arrays
    are immutable, so "inplace" here means: compute out-of-place, then
    redirect the SAME python Tensor at the result (data + tape node), which
    reproduces the reference's user-visible semantics (the variable you
    held is updated, autograd still flows);
  * dtype/info/infra shims (finfo/iinfo, rng state, set_printoptions,
    DataParallel, LazyGuard, flops, ...).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..tensor import Tensor, apply_op, to_tensor
from . import creation as _creation
from . import linalg as _linalg
from . import manipulation as _manip
from . import math as _math

__all__ = [
    # base ops
    "addmm", "cdist", "cumulative_trapezoid", "trapezoid", "frexp", "i0e",
    "i1e", "polygamma", "polar", "sgn", "take", "renorm", "nanquantile",
    "mv", "unflatten", "unfold", "vsplit", "reverse", "crop", "increment",
    "is_empty", "is_complex", "is_floating_point", "is_integer",
    "as_strided",
    # infra
    "finfo", "iinfo", "set_printoptions", "get_rng_state", "set_rng_state",
    "get_cuda_rng_state", "set_cuda_rng_state", "disable_signal_handler",
    "check_shape", "flops", "batch", "LazyGuard", "DataParallel",
    "create_parameter", "CUDAPinnedPlace", "where_",
]
# the inplace family is appended to __all__ at generation time below


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


# ---------------------------------------------------------------------------
# base ops
# ---------------------------------------------------------------------------


def i0e(x, name=None):
    return apply_op("i0e", jax.scipy.special.i0e, _t(x))


def i1e(x, name=None):
    return apply_op("i1e", jax.scipy.special.i1e, _t(x))


def mv(x, vec, name=None):
    """Matrix (M, N) times vector (N,) -> (M,)."""
    return apply_op("mv", lambda a, b: a @ b, _t(x), _t(vec))


def sgn(x, name=None):
    """sign for real; x/|x| for complex (reference tensor/math.py sgn)."""
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.where(mag == 0, 1, mag))
        return jnp.sign(a)
    return apply_op("sgn", f, _t(x))


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op("addmm",
                    lambda i, a, b: beta * i + alpha * (a @ b),
                    _t(input), _t(x), _t(y))


def polar(abs, angle, name=None):  # noqa: A002 — paddle arg name
    """abs * e^{i*angle} (complex64/128 output)."""
    return apply_op(
        "polar",
        lambda r, th: (r * jnp.exp(1j * th.astype(jnp.promote_types(
            th.dtype, jnp.float32)))).astype(
                jnp.complex128 if r.dtype == jnp.float64 else jnp.complex64),
        _t(abs), _t(angle))


def frexp(x, name=None):
    """(mantissa, exponent) with x = mantissa * 2**exponent."""
    def f(a):
        m, e = jnp.frexp(a)
        return m, e.astype(a.dtype)
    return apply_op("frexp", f, _t(x))


def take(x, index, mode="raise", name=None):
    """Flat-index gather over the flattened input (reference math.py take)."""
    if mode not in ("raise", "wrap", "clip"):
        raise ValueError(
            f"'mode' in 'take' should be 'raise', 'wrap', 'clip', but "
            f"received {mode}.")
    x, index = _t(x), _t(index)
    n = 1
    for d in x.shape:
        n *= int(d)
    if mode == "raise" and not isinstance(index._data, jax.core.Tracer):
        idx = np.asarray(index._data)
        if idx.size and (idx.min() < -n or idx.max() >= n):
            raise IndexError(
                f"take(): index out of range for input with {n} elements")

    def f(a, i):
        flat = a.reshape(-1)
        if mode == "wrap":
            i = ((i % n) + n) % n
        elif mode == "clip":
            i = jnp.clip(i, 0, n - 1)
        else:
            i = jnp.where(i < 0, i + n, i)
        return flat[i]

    return apply_op("take", f, x, index, nondiff=(1,))


def renorm(x, p, axis, max_norm, name=None):
    """Rescale slices along `axis` whose p-norm exceeds max_norm."""
    x = _t(x)
    nd = len(x.shape)
    if not -nd <= axis < nd:
        raise ValueError(f"axis {axis} out of range for rank {nd}")

    def f(a):
        m = jnp.moveaxis(a, axis, 0)
        flat = m.reshape(m.shape[0], -1)
        norms = (jnp.abs(flat.astype(jnp.float32)) ** p).sum(-1) ** (1.0 / p)
        scale = jnp.where(norms > max_norm, max_norm / (norms + 1e-7), 1.0)
        out = flat * scale[:, None].astype(a.dtype)
        return jnp.moveaxis(out.reshape(m.shape), 0, axis)

    return apply_op("renorm", f, x)


renorm_ = None  # defined by the inplace generator below


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Trapezoidal integration (reference math.py trapezoid)."""
    if x is not None and dx is not None:
        raise ValueError("trapezoid: pass either x or dx, not both")
    y = _t(y)
    if x is not None:
        return apply_op(
            "trapezoid",
            lambda a, b: jnp.trapezoid(a, x=b, axis=axis), y, _t(x))
    d = 1.0 if dx is None else dx
    return apply_op("trapezoid",
                    lambda a: jnp.trapezoid(a, dx=d, axis=axis), y)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    """Cumulative trapezoid along axis (one element shorter than input)."""
    if x is not None and dx is not None:
        raise ValueError("cumulative_trapezoid: pass either x or dx")
    y = _t(y)

    def slices(a, lo, hi):
        idx = [slice(None)] * a.ndim
        idx[axis] = slice(lo, hi)
        return a[tuple(idx)]

    if x is not None:
        def f(a, b):
            d = slices(b, 1, None) - slices(b, None, -1)
            avg = (slices(a, 1, None) + slices(a, None, -1)) * 0.5
            return jnp.cumsum(avg * d, axis=axis)
        return apply_op("cumulative_trapezoid", f, y, _t(x))

    d = 1.0 if dx is None else dx

    def f(a):
        avg = (slices(a, 1, None) + slices(a, None, -1)) * 0.5
        return jnp.cumsum(avg * d, axis=axis)
    return apply_op("cumulative_trapezoid", f, y)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary",
          name=None):
    """Batched pairwise p-distances: (..., P, M) x (..., R, M) -> (..., P, R)."""
    def f(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt((diff * diff).sum(-1) + 1e-30)
        if p == float("inf"):
            return jnp.abs(diff).max(-1)
        if p == 0:
            return (diff != 0).sum(-1).astype(a.dtype)
        return (jnp.abs(diff) ** p).sum(-1) ** (1.0 / p)
    return apply_op("cdist", f, _t(x), _t(y))


def polygamma(x, n, name=None):
    if n == 0:
        return apply_op("digamma", jax.lax.digamma, _t(x))
    return apply_op("polygamma",
                    lambda a: jax.scipy.special.polygamma(n, a), _t(x))


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear",
                name=None):
    return apply_op(
        "nanquantile",
        lambda a: jnp.nanquantile(a.astype(jnp.float32), q, axis=axis,
                                  keepdims=keepdim, method=interpolation),
        _t(x))


def unflatten(x, axis, shape, name=None):
    x = _t(x)
    nd = len(x.shape)
    axis = axis + nd if axis < 0 else axis
    new_shape = (tuple(int(s) for s in x.shape[:axis])
                 + tuple(int(s) for s in shape)
                 + tuple(int(s) for s in x.shape[axis + 1:]))
    return _manip.reshape(x, list(new_shape))


def unfold(x, axis, size, step, name=None):
    """Sliding windows: `axis` becomes n_windows, window size appended as
    the last dim (reference Tensor.unfold)."""
    x = _t(x)
    nd = len(x.shape)
    axis = axis + nd if axis < 0 else axis
    D = int(x.shape[axis])
    if size > D:
        raise ValueError(f"unfold: size {size} > dim {D}")
    starts = np.arange(0, D - size + 1, step)

    def f(a):
        wins = [jax.lax.slice_in_dim(a, int(s), int(s) + size, axis=axis)
                for s in starts]
        stacked = jnp.stack(wins, axis=axis)          # (..., n, size, ...)
        return jnp.moveaxis(stacked, axis + 1, -1)
    return apply_op("unfold", f, x)


def vsplit(x, num_or_indices, name=None):
    x = _t(x)
    if len(x.shape) < 2:
        raise ValueError("vsplit expects a tensor of at least rank 2")
    return _manip.split(x, num_or_indices, axis=0)


def reverse(x, axis, name=None):
    """Deprecated alias of flip (reference keeps it exported)."""
    return _manip.flip(_t(x), axis)


def crop(x, shape=None, offsets=None, name=None):
    x = _t(x)
    nd = len(x.shape)
    shape = list(x.shape) if shape is None else [
        int(x.shape[i]) - (0 if offsets is None else int(offsets[i]))
        if int(s) == -1 else int(s) for i, s in enumerate(shape)]
    offsets = [0] * nd if offsets is None else [int(o) for o in offsets]

    def f(a):
        return jax.lax.slice(a, offsets,
                             [o + s for o, s in zip(offsets, shape)])
    return apply_op("crop", f, x)


def increment(x, value=1.0, name=None):
    """x += value in place, returning the updated Tensor (reference
    tensor/math.py increment — a counter op, typically on stop-gradient
    scalars)."""
    x = _t(x)
    if (framework.is_grad_enabled() and not x.stop_gradient
            and x._node is None):
        raise RuntimeError(
            "increment: in-place operation on a leaf Tensor that requires "
            "grad is not allowed (matches the reference restriction)")
    out = apply_op("increment", lambda a: a + value, x)
    from . import _inplace
    return _inplace(x, out)


def where_(condition, x, y, name=None):
    """In-place where: x <- where(condition, x, y) — the inplace target is
    x, NOT the condition (reference tensor/search.py where_)."""
    x = _t(x)
    if (framework.is_grad_enabled() and not x.stop_gradient
            and x._node is None):
        raise RuntimeError(
            "where_: in-place operation on a leaf Tensor that requires "
            "grad is not allowed (matches the reference restriction)")
    out = apply_op("where", lambda c, a, b: jnp.where(c, a, b),
                   _t(condition), x, _t(y), nondiff=(0,))
    from . import _inplace
    return _inplace(x, out)


def is_empty(x, name=None):
    x = _t(x)
    n = 1
    for d in x.shape:
        n *= int(d)
    return to_tensor(np.array(n == 0))


def is_complex(x):
    return jnp.issubdtype(_t(x)._data.dtype, jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(_t(x)._data.dtype, jnp.floating)


def is_integer(x):
    return jnp.issubdtype(_t(x)._data.dtype, jnp.integer)


def as_strided(x, shape, stride, offset=0, name=None):
    """Strided view over the flattened input (gather-based — TPU arrays
    are immutable so this is a copy, matching reference values)."""
    x = _t(x)
    shape = [int(s) for s in shape]
    stride = [int(s) for s in stride]
    if len(shape) != len(stride):
        raise ValueError("as_strided: shape and stride ranks differ")
    idx = np.full(tuple(shape), int(offset), np.int64)
    for d, (s, st) in enumerate(zip(shape, stride)):
        ax = np.arange(s, dtype=np.int64) * st
        idx += ax.reshape((1,) * d + (s,) + (1,) * (len(shape) - d - 1))

    def f(a):
        return a.reshape(-1)[idx]
    return apply_op("as_strided", f, x)


# ---------------------------------------------------------------------------
# the inplace family
# ---------------------------------------------------------------------------


def _make_inplace(base_fn, name):
    def op_(x, *args, **kwargs):
        if (framework.is_grad_enabled() and isinstance(x, Tensor)
                and not x.stop_gradient and x._node is None):
            raise RuntimeError(
                f"{name}: in-place operation on a leaf Tensor that requires "
                "grad is not allowed (matches the reference restriction)")
        out = base_fn(x, *args, **kwargs)
        from . import _inplace
        return _inplace(x, out)
    op_.__name__ = name
    op_.__doc__ = f"In-place variant of `{base_fn.__name__}` (reference " \
                  f"paddle.{name})."
    return op_


_INPLACE_BASES = [
    "abs", "acos", "asin", "atan", "addmm", "bitwise_and", "bitwise_not",
    "bitwise_or", "bitwise_xor", "ceil", "cos", "cosh", "cumprod", "cumsum",
    "digamma", "divide", "equal", "erf", "exp", "expm1", "floor",
    "floor_divide", "floor_mod", "frac", "gcd", "greater_equal",
    "greater_than", "i0", "index_add", "index_put", "lcm", "ldexp",
    "less_equal", "less_than", "lgamma", "log", "log10", "log1p", "log2",
    "logical_and", "logical_not", "logical_or", "logical_xor", "logit",
    "mod", "multiply", "nan_to_num", "neg", "not_equal", "polygamma", "pow",
    "remainder", "renorm", "rsqrt", "sigmoid", "sin", "sinh", "sqrt",
    "square", "subtract", "tan", "tanh", "tril", "triu", "trunc",
    "erfinv", "lerp", "reciprocal", "put_along_axis",
]

_INPLACE = {}
_this = globals()
for _b in _INPLACE_BASES:
    _base = _this.get(_b) or getattr(_math, _b, None) \
        or getattr(_manip, _b, None) or getattr(_linalg, _b, None) \
        or getattr(_creation, _b, None)
    if _base is None:
        continue
    _nm = _b + "_"
    _INPLACE[_nm] = _make_inplace(_base, _nm)
    _this[_nm] = _INPLACE[_nm]
__all__ += sorted(_INPLACE)


# ---------------------------------------------------------------------------
# dtype/info/infra shims
# ---------------------------------------------------------------------------


class finfo:
    """Float dtype limits (reference paddle.finfo)."""

    def __init__(self, dtype):
        from ..framework import convert_dtype, to_jax_dtype
        f = np.finfo(np.dtype(jnp.dtype(to_jax_dtype(convert_dtype(dtype)))
                              .name) if convert_dtype(dtype) != "bfloat16"
                     else np.float32)
        if convert_dtype(dtype) == "bfloat16":
            self.min, self.max = -3.3895314e38, 3.3895314e38
            self.eps, self.tiny = 0.0078125, 1.1754944e-38
            self.bits, self.dtype = 16, "bfloat16"
        else:
            self.min, self.max = float(f.min), float(f.max)
            self.eps, self.tiny = float(f.eps), float(f.tiny)
            self.bits, self.dtype = f.bits, convert_dtype(dtype)
        self.smallest_normal = self.tiny
        self.resolution = self.eps


class iinfo:
    """Integer dtype limits (reference paddle.iinfo)."""

    def __init__(self, dtype):
        from ..framework import convert_dtype, to_jax_dtype
        i = np.iinfo(np.dtype(jnp.dtype(
            to_jax_dtype(convert_dtype(dtype))).name))
        self.min, self.max = int(i.min), int(i.max)
        self.bits, self.dtype = i.bits, convert_dtype(dtype)


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


def get_rng_state(device=None):
    """Opaque RNG state list (reference returns per-device generator
    states; here the default Generator's (seed, count) is the source)."""
    return [framework.default_generator().get_state()]


def set_rng_state(state_list, device=None):
    framework.default_generator().set_state(tuple(state_list[0]))


get_cuda_rng_state = get_rng_state
set_cuda_rng_state = set_rng_state


def disable_signal_handler():
    """No-op: the reference unhooks its C++ signal handlers; this runtime
    installs none."""


def check_shape(shape, op_name="") -> None:
    """Validate a shape argument (reference utils/layers_utils.check_shape)."""
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()
    for s in shape:
        if not isinstance(s, (int, np.integer)):
            raise TypeError(f"{op_name}: shape entries must be ints, got "
                            f"{type(s).__name__}")
        if int(s) < -1:
            raise ValueError(f"{op_name}: invalid shape entry {int(s)}")


def flops(net, input_size, custom_ops=None, print_detail=False):
    """FLOPs estimate by a hooked forward pass over zeros(input_size)
    (reference hapi/dynamic_flops.py).  Counts Linear/Conv multiply-adds
    x2; custom_ops: {LayerClass: fn(layer, inputs, output) -> flops}."""
    from ..nn.layer import Layer

    total = [0]
    hooks = []

    def count(layer, inputs, output):
        cls = type(layer)
        if custom_ops and cls in custom_ops:
            total[0] += int(custom_ops[cls](layer, inputs, output))
            return
        w = getattr(layer, "weight", None)
        if w is None or not isinstance(w, Tensor):
            return
        wn = 1
        for d in w.shape:
            wn *= int(d)
        out0 = output[0] if isinstance(output, (tuple, list)) else output
        if not isinstance(out0, Tensor):
            return
        spatial = 1
        if len(w.shape) > 2:            # conv kernels: per output position
            spatial = int(np.prod(out0.shape[2:]))
        batch = int(out0.shape[0]) if out0.shape else 1
        total[0] += 2 * wn * spatial * batch

    for sub in net.sublayers(include_self=True):
        hooks.append(sub.register_forward_post_hook(count))
    try:
        x = to_tensor(np.zeros(input_size, np.float32))
        net(x)
    finally:
        for h in hooks:
            h.remove()
    if print_detail:
        print(f"Total FLOPs: {total[0]}")
    return total[0]


def batch(reader, batch_size, drop_last=False):
    """Legacy reader decorator (reference paddle.batch)."""
    def batched():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


class LazyGuard:
    """Parameter-init deferral guard (reference paddle.LazyGuard).  This
    runtime initializes eagerly on host — construction under the guard is
    already cheap (numpy init, no device traffic), so the guard is a
    documented no-op kept for API parity."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class DataParallel:
    """Reference paddle.DataParallel wrapper.  Under GSPMD, data
    parallelism is a sharding annotation, not a wrapper — this class keeps
    the reference's surface (attribute passthrough, scale_loss/state_dict)
    while the mesh does the actual work (distributed/parallelize.py)."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        self._layers = layers

    def __call__(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass

    def state_dict(self, *a, **k):
        return self._layers.state_dict(*a, **k)

    def set_state_dict(self, *a, **k):
        return self._layers.set_state_dict(*a, **k)

    def __getattr__(self, name):
        return getattr(self.__dict__["_layers"], name)


def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Standalone Parameter factory (reference paddle.create_parameter)."""
    from ..nn import initializer as I
    from ..nn.layer import ParamAttr
    from ..tensor import Parameter
    from ..framework import convert_dtype

    attr = ParamAttr._to_attr(attr)
    if attr is False:
        return None
    init = (attr.initializer or default_initializer
            or (I.Constant(0.0) if is_bias else I.XavierNormal()))
    data = init([int(s) for s in shape], convert_dtype(dtype))
    p = Parameter(data, name=attr.name or name, trainable=attr.trainable)
    return p


CUDAPinnedPlace = lambda: "cpu"  # noqa: E731 — place objects are strings here
