"""Random ops over the Paddle-style global Generator (framework.Generator).

Each draw folds the global key (eager UX parity with paddle.seed); every op also
accepts key= so compiled/jitted code can thread keys functionally (the TPU-native
way — jax splittable threefry; see SURVEY.md C47 RNG control for the distributed
per-mesh-axis analog in distributed/random.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..framework import convert_dtype, to_jax_dtype
from ..tensor import Tensor, to_tensor

__all__ = [
    "rand", "randn", "randint", "randint_like", "uniform", "normal", "standard_normal",
    "randperm", "multinomial", "bernoulli", "poisson", "uniform_", "normal_", "exponential_",
    "binomial", "log_normal", "standard_gamma",
]


def _key(key=None):
    if key is not None:
        return key
    return framework.next_rng_key()


def _dt(dtype):
    return to_jax_dtype(convert_dtype(dtype) if dtype is not None else framework.get_default_dtype())


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._data))
    if isinstance(shape, int):
        return (shape,)
    return tuple(int(s._data) if isinstance(s, Tensor) else int(s) for s in shape)


def rand(shape, dtype=None, name=None, key=None):
    return Tensor(jax.random.uniform(_key(key), _shape(shape), dtype=_dt(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None, key=None):
    return Tensor(jax.random.uniform(_key(key), _shape(shape), dtype=_dt(dtype), minval=min, maxval=max))


def randn(shape, dtype=None, name=None, key=None):
    return Tensor(jax.random.normal(_key(key), _shape(shape), dtype=_dt(dtype)))


def standard_normal(shape, dtype=None, name=None, key=None):
    return randn(shape, dtype=dtype, key=key)


def normal(mean=0.0, std=1.0, shape=None, name=None, key=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(_key(key), shp) * s + m)
    shp = _shape(shape if shape is not None else [1])
    return Tensor(jax.random.normal(_key(key), shp) * std + mean)


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None, key=None):
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_key(key), _shape(shape), low, high, dtype=_dt(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None, key=None):
    x = x if isinstance(x, Tensor) else to_tensor(x)
    if high is None:
        low, high = 0, low
    return Tensor(jax.random.randint(_key(key), tuple(x.shape), low, high, dtype=x._data.dtype if dtype is None else _dt(dtype)))


def randperm(n, dtype="int64", name=None, key=None):
    return Tensor(jax.random.permutation(_key(key), n).astype(_dt(dtype)))


def multinomial(x, num_samples=1, replacement=False, name=None, key=None):
    x = x if isinstance(x, Tensor) else to_tensor(x)
    logits = jnp.log(jnp.maximum(x._data, 1e-30))
    k = _key(key)
    if replacement:
        # jax categorical's `shape` must be broadcast-compatible with the
        # BATCH shape as a suffix: draw (num_samples, *batch), then move
        # the sample axis last (paddle layout)
        batch = logits.shape[:-1]
        out = jax.random.categorical(k, logits, axis=-1,
                                     shape=(num_samples, *batch))
        out = jnp.moveaxis(out, 0, -1)
    else:
        # Gumbel top-k trick for sampling without replacement.
        g = jax.random.gumbel(k, logits.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def bernoulli(x, name=None, key=None):
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jax.random.bernoulli(_key(key), x._data).astype(x._data.dtype))


def poisson(x, name=None, key=None):
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jax.random.poisson(_key(key), x._data).astype(x._data.dtype))


def binomial(count, prob, name=None, key=None):
    count = count if isinstance(count, Tensor) else to_tensor(count)
    prob = prob if isinstance(prob, Tensor) else to_tensor(prob)
    return Tensor(jax.random.binomial(_key(key), count._data, prob._data).astype(jnp.int64))


def standard_gamma(x, name=None, key=None):
    x = x if isinstance(x, Tensor) else to_tensor(x)
    return Tensor(jax.random.gamma(_key(key), x._data))


def log_normal(mean=1.0, std=2.0, shape=None, name=None, key=None):
    return Tensor(jnp.exp(jax.random.normal(_key(key), _shape(shape or [1])) * std + mean))


# in-place variants (rebind data)

def uniform_(x, min=-1.0, max=1.0, seed=0, name=None, key=None):
    x._data = jax.random.uniform(_key(key), tuple(x.shape), dtype=x._data.dtype, minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None, key=None):
    x._data = jax.random.normal(_key(key), tuple(x.shape), dtype=x._data.dtype) * std + mean
    return x


def exponential_(x, lam=1.0, name=None, key=None):
    x._data = jax.random.exponential(_key(key), tuple(x.shape), dtype=x._data.dtype) / lam
    return x
