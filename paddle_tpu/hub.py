"""paddle.hub — list / help / load model entrypoints from a hubconf.py.

Reference: python/paddle/hub.py (facade) + python/paddle/hapi/hub.py
(implementation).  The reference resolves github/gitee specs by
downloading a tarball; this environment has zero egress, so:

  * source='local'  — fully supported: repo_dir is a directory containing
    `hubconf.py`; its public callables are the entrypoints.
  * source='github' / 'gitee' — resolved ONLY against an existing local
    cache (populated out of band, e.g. a pre-seeded ~/.cache/paddle/hub or
    a `git clone` done while online); a cache miss raises with the exact
    path it looked for, instead of attempting a download.
"""

from __future__ import annotations

import os
import sys

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"


def _hub_cache_dir():
    root = os.environ.get("PADDLE_HUB_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle", "hub")
    return root


def _parse_repo_info(repo):
    if ":" in repo:
        repo_info, ref = repo.split(":")
    else:
        repo_info, ref = repo, "main"
    owner, name = repo_info.split("/")
    return owner, name, ref


def _resolve_dir(repo_dir, source, force_reload):
    if source == "local":
        if not os.path.isdir(repo_dir):
            raise ValueError(f"local repo dir not found: {repo_dir}")
        return repo_dir
    owner, name, ref = _parse_repo_info(repo_dir)
    cached = os.path.join(_hub_cache_dir(), f"{owner}_{name}_{ref}")
    if os.path.isdir(cached):
        return cached
    raise RuntimeError(
        f"hub cache miss for {repo_dir!r} ({source}): this build has no "
        f"network egress; place the repo at {cached} (or pass a local "
        "path with source='local')")


def _import_hubconf(repo_dir):
    path = os.path.join(repo_dir, MODULE_HUBCONF)
    if not os.path.isfile(path):
        raise RuntimeError(f"no {MODULE_HUBCONF} in {repo_dir}")
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        f"paddle_tpu_hubconf_{abs(hash(repo_dir))}", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, repo_dir)
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.remove(repo_dir)
    return mod


def _check_source(source):
    if source not in ("github", "gitee", "local"):
        raise ValueError(
            f'Unknown source: "{source}". Allowed values: "github" | '
            '"gitee" | "local".')


def _entry(mod, name):
    fn = getattr(mod, name, None)
    if fn is None or not callable(fn):
        raise RuntimeError(f"Cannot find callable {name} in hubconf")
    return fn


def list(repo_dir, source="github", force_reload=False):  # noqa: A001
    """All public callable entrypoint names in the repo's hubconf.py."""
    _check_source(source)
    mod = _import_hubconf(_resolve_dir(repo_dir, source, force_reload))
    return [f for f in dir(mod)
            if callable(getattr(mod, f)) and not f.startswith("_")]


def help(repo_dir, model, source="github", force_reload=False):  # noqa: A001
    """Docstring of the named entrypoint."""
    _check_source(source)
    mod = _import_hubconf(_resolve_dir(repo_dir, source, force_reload))
    return _entry(mod, model).__doc__


def load(repo_dir, model, source="github", force_reload=False, **kwargs):
    """Call the named entrypoint with **kwargs and return its result."""
    _check_source(source)
    mod = _import_hubconf(_resolve_dir(repo_dir, source, force_reload))
    return _entry(mod, model)(**kwargs)
