"""paddle.device parity (python/paddle/device/__init__.py).

Devices are JAX/PJRT devices; streams/events are XLA-managed, so the stream API
is a semantic no-op kept for source compatibility (every op already runs async
on the TPU's single compute stream, with dispatch-order dependencies)."""

from __future__ import annotations

import jax

from .. import framework
from ..framework import get_device, set_device  # noqa: F401

__all__ = ["set_device", "get_device", "get_all_device_type", "get_available_device",
           "get_available_custom_device", "is_compiled_with_cinn", "is_compiled_with_cuda",
           "is_compiled_with_rocm", "is_compiled_with_xpu", "is_compiled_with_custom_device",
           "synchronize", "device_count", "Stream", "Event", "current_stream", "stream_guard",
           "set_stream", "cuda", "get_device_properties"]


def get_all_device_type():
    return ["cpu"] + ([jax.default_backend()] if jax.default_backend() != "cpu" else [])


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return []


def is_compiled_with_cinn():
    return True  # XLA plays CINN's role


def is_compiled_with_cuda():
    return False


def is_compiled_with_rocm():
    return False


def is_compiled_with_xpu():
    return False


def is_compiled_with_custom_device(device_type=None):
    return device_type in ("tpu", "axon")


def synchronize(device=None):
    for d in jax.devices():
        try:
            jax.device_put(0.0, d).block_until_ready()
        except Exception:  # noqa: BLE001
            pass


def device_count():
    return jax.device_count()


def get_device_properties(device=None):
    d = jax.devices()[0]
    class _Props:
        name = getattr(d, "device_kind", str(d))
        total_memory = None
        multi_processor_count = None
    return _Props()


class Stream:
    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_current_stream = Stream()


def current_stream(device=None):
    return _current_stream


def set_stream(stream):
    return _current_stream


class stream_guard:
    def __init__(self, stream):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


class cuda:
    """paddle.device.cuda namespace stub — no CUDA in the TPU build."""

    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def is_available():
        return False

    Stream = Stream
    Event = Event

    @staticmethod
    def synchronize(device=None):
        synchronize()

    @staticmethod
    def empty_cache():
        pass

    @staticmethod
    def max_memory_allocated(device=None):
        return 0

    @staticmethod
    def memory_allocated(device=None):
        return 0


IPUPlace = lambda *a: "ipu"    # noqa: E731 — place objects are strings here
XPUPlace = lambda *a: "xpu"    # noqa: E731


def get_all_custom_device_type():
    """Custom (plugin) device types registered with the runtime (reference
    device/__init__.py) — PJRT plugins beyond cpu/gpu/tpu."""
    import jax
    builtin = {"cpu", "gpu", "cuda", "rocm", "tpu"}
    try:
        plats = {d.platform for d in jax.devices()}
    except Exception:  # noqa: BLE001
        plats = set()
    return sorted(plats - builtin)


def get_cudnn_version():
    """None: no cuDNN in an XLA/TPU build (reference returns the int
    version on CUDA installs)."""
    return None


def is_compiled_with_ipu():
    return False


__all__ += ["IPUPlace", "XPUPlace", "get_all_custom_device_type",
            "get_cudnn_version", "is_compiled_with_ipu"]
