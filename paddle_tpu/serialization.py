"""paddle.save / paddle.load (python/paddle/framework/io.py:650,893 parity).

Pickle-protocol-4 nested state dicts with Tensors stored as numpy arrays
(bfloat16 goes through ml_dtypes, which numpy understands via jax).  Large
checkpoint use goes through paddle_tpu.distributed.checkpoint (per-shard
.npy files + reshard-on-load) — this module is the single-process path.
"""

from __future__ import annotations

import io as _io
import os
import pickle

import numpy as np

from .tensor import Parameter, Tensor, to_tensor


class _TensorPayload:
    """Pickle-stable wrapper (keeps bf16 via raw bytes + dtype name)."""

    def __init__(self, array: np.ndarray):
        self.dtype = str(array.dtype)
        self.shape = array.shape
        self.data = array.tobytes()

    def to_numpy(self):
        import ml_dtypes  # noqa: F401 — registers bfloat16 etc. with numpy

        return np.frombuffer(self.data, dtype=np.dtype(self.dtype)).reshape(self.shape)


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data))
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        arr = obj.to_numpy()
        return arr if return_numpy else to_tensor(arr)
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    # write-then-rename (same atomic pattern as distributed/checkpoint.py):
    # a crash mid-write leaves the old checkpoint intact, never a torn file
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(_pack(obj), f, protocol=protocol)
    os.replace(tmp, path)


def load(path, return_numpy=False, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _unpack(obj, return_numpy=return_numpy)


def save_to_buffer(obj, protocol=4) -> bytes:
    buf = _io.BytesIO()
    pickle.dump(_pack(obj), buf, protocol=protocol)
    return buf.getvalue()


def load_from_buffer(data: bytes, return_numpy=False):
    return _unpack(pickle.loads(data), return_numpy=return_numpy)
