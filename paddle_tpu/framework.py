"""Global framework state: dtypes, default device, RNG, grad mode, flags.

Reference parity: paddle/phi/common/data_type.h (dtype set), python/paddle/base/framework.py
(set_flags/get_flags, _dygraph_tracer grad mode), paddle/phi/core/generator.h (RNG
Generator).  TPU-native design: dtypes map 1:1 onto jax.numpy dtypes (bfloat16 is
first-class — it is the TPU MXU native type); the RNG is a counter-based stateful wrapper
over JAX's splittable threefry keys so the user-facing API is Paddle-like (`paddle.seed`)
while every draw stays functional underneath (safe under jit tracing).
"""

from __future__ import annotations

import contextlib
import os
import threading

import jax
import jax.numpy as jnp
import numpy as np

# ---------------------------------------------------------------------------
# dtype system
# ---------------------------------------------------------------------------

# Canonical names follow the reference's phi::DataType set (no float8 in that
# snapshot; we still expose fp8 aliases since TPU v5+ supports them natively).
_DTYPE_MAP = {
    "float32": jnp.float32,
    "float64": jnp.float64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "uint8": jnp.uint8,
    "uint16": jnp.uint16,
    "uint32": jnp.uint32,
    "uint64": jnp.uint64,
    "bool": jnp.bool_,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
    "float8_e4m3fn": jnp.float8_e4m3fn,
    "float8_e5m2": jnp.float8_e5m2,
}

_REVERSE_DTYPE_MAP = {np.dtype(v): k for k, v in _DTYPE_MAP.items()}

# Short aliases used throughout paddle code.
float32 = "float32"
float64 = "float64"
float16 = "float16"
bfloat16 = "bfloat16"
int8 = "int8"
int16 = "int16"
int32 = "int32"
int64 = "int64"
uint8 = "uint8"
bool_ = "bool"
complex64 = "complex64"
complex128 = "complex128"

FLOAT_DTYPES = ("float16", "bfloat16", "float32", "float64")
INT_DTYPES = ("int8", "int16", "int32", "int64", "uint8", "uint16", "uint32", "uint64")


def convert_dtype(dtype):
    """Normalize any dtype spec (str / np / jnp) to the canonical string name."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype in _DTYPE_MAP:
            return dtype
        raise ValueError(f"Unknown dtype {dtype!r}")
    try:
        return _REVERSE_DTYPE_MAP[np.dtype(dtype)]
    except Exception as e:  # noqa: BLE001
        raise ValueError(f"Unknown dtype {dtype!r}") from e


def to_jax_dtype(dtype):
    """Canonical string / np dtype → jnp dtype class (None passes through)."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        return _DTYPE_MAP[dtype]
    return np.dtype(dtype)


def is_floating_dtype(dtype) -> bool:
    d = convert_dtype(dtype)
    return d in FLOAT_DTYPES or d in ("complex64", "complex128")


# ---------------------------------------------------------------------------
# Global state
# ---------------------------------------------------------------------------


class _GlobalState(threading.local):
    def __init__(self):
        self.default_dtype = "float32"
        self.grad_enabled = True
        self.amp_state = None  # set by paddle_tpu.amp.auto_cast
        self.device = None  # lazily resolved; "tpu"/"cpu"/"gpu"
        # When set (by the jit engine), RNG draws fold this traced key instead of
        # the global generator, so dropout masks are fresh per compiled step.
        self.trace_key = None
        self.trace_key_count = 0
        # When set (by static.program_guard), tensor.apply_op records every op
        # into this Program so Executor.run can replay it under one jit.
        self.capture_program = None
        self.flags = {
            "FLAGS_check_nan_inf": bool(int(os.environ.get("FLAGS_check_nan_inf", "0"))),
            "FLAGS_cudnn_deterministic": False,
            "FLAGS_use_fused_kernels": True,
            "FLAGS_pallas_interpret": False,
            "FLAGS_embedding_deterministic": False,
            # record op fn/args on the tape for grad(create_graph=True)
            # replay; disable to shed the extra references on memory-bound
            # eager jobs (higher-order grad then raises)
            "FLAGS_enable_double_grad": True,
            # opt-in: let Graph Doctor rewrite call sites apply VERIFIED
            # fixes automatically (ShardedTrainState donation injection,
            # Program.rewrite defaults) — off by default; the lint always
            # runs, the transform only with consent
            "FLAGS_auto_graph_rewrite": False,
        }


_state = _GlobalState()


def get_state() -> _GlobalState:
    return _state


def set_default_dtype(dtype):
    _state.default_dtype = convert_dtype(dtype)


def get_default_dtype() -> str:
    return _state.default_dtype


def set_flags(flags: dict):
    """paddle.set_flags parity (base/framework.py set_flags)."""
    for k, v in flags.items():
        _state.flags[k] = v


def get_flags(keys=None):
    if keys is None:
        return dict(_state.flags)
    if isinstance(keys, str):
        keys = [keys]
    return {k: _state.flags.get(k) for k in keys}


# ---------------------------------------------------------------------------
# Grad mode
# ---------------------------------------------------------------------------


def is_grad_enabled() -> bool:
    return _state.grad_enabled


@contextlib.contextmanager
def _grad_mode(enabled: bool):
    prev = _state.grad_enabled
    _state.grad_enabled = enabled
    try:
        yield
    finally:
        _state.grad_enabled = prev


def no_grad_guard():
    return _grad_mode(False)


def enable_grad_guard():
    return _grad_mode(True)


# ---------------------------------------------------------------------------
# RNG: Paddle-style stateful seed over JAX functional keys
# ---------------------------------------------------------------------------


class Generator:
    """Counter-based RNG state (reference: phi/core/generator.h Generator).

    Holds a root JAX key; every `next_key()` derives a fresh fold so eager code
    gets Paddle's "global implicit RNG" UX.  Under jit tracing the caller should
    thread keys explicitly; ops accept an optional `key=` for that.
    """

    def __init__(self, seed: int = 0):
        self._seed = seed
        self._count = 0

    def manual_seed(self, seed: int):
        self._seed = int(seed)
        self._count = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def next_key(self):
        self._count += 1
        return jax.random.fold_in(jax.random.PRNGKey(self._seed), self._count)

    def get_state(self):
        return (self._seed, self._count)

    def set_state(self, state):
        self._seed, self._count = state


_default_generator = Generator(seed=np.random.randint(0, 2**31 - 1))


def seed(value: int):
    """paddle.seed parity."""
    _default_generator.manual_seed(value)
    return _default_generator


def default_generator() -> Generator:
    return _default_generator


def next_rng_key():
    if _state.trace_key is not None:
        _state.trace_key_count += 1
        return jax.random.fold_in(_state.trace_key, _state.trace_key_count)
    return _default_generator.next_key()


# ---------------------------------------------------------------------------
# Device control (python/paddle/device/__init__.py set_device parity)
# ---------------------------------------------------------------------------


def set_device(device: str):
    """Accepts "tpu", "cpu", "gpu", or "tpu:0" style strings."""
    _state.device = device.split(":")[0]
    return _state.device


def get_device() -> str:
    if _state.device is None:
        _state.device = jax.default_backend()
    plat = _state.device
    return f"{plat}:0"


def default_backend() -> str:
    return jax.default_backend()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_cinn() -> bool:
    # XLA plays CINN's role in the TPU build.
    return True


def device_count() -> int:
    return jax.device_count()
