"""paddle.quantization — QAT + PTQ (SURVEY C43; reference
python/paddle/quantization/{qat.py,ptq.py,config.py,quanter,observers}).

TPU-native mapping: int8 fake-quant is plain jnp math that XLA fuses into
the surrounding matmul; the straight-through estimator is
`x + stop_gradient(q(x) - x)` on the eager tape.  Layout and API mirror the
reference: a `QuantConfig` maps layer types to quanter/observer factories,
`QAT.quantize` swaps matching sublayers for quantized wrappers with
trainable fake-quanters, `PTQ.quantize` inserts observers for calibration,
and `.convert` freezes scales into int8 weights + dequant scales.
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, Optional, Type

import numpy as np

import jax.numpy as jnp

from .. import nn
from ..nn.layer import Layer
from ..tensor import Tensor, to_tensor

__all__ = [
    "QuantConfig", "QAT", "PTQ",
    "FakeQuanterWithAbsMaxObserver", "AbsmaxObserver", "QuantedLinear",
    "quanter",
]


def _absmax(x, axis=None):
    return jnp.max(jnp.abs(x), axis=axis) if axis is not None else jnp.max(jnp.abs(x))


def _fake_quant(raw, scale, bits=8):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-9) / qmax
    return jnp.clip(jnp.round(raw / s), -qmax - 1, qmax) * s


class BaseQuanter(Layer):
    bits = 8

    def scales(self):
        raise NotImplementedError


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Moving-absmax fake quanter with STE (reference
    quanter/abs_max.py FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 **_unused):
        super().__init__()
        self._rate = moving_rate
        self.bits = bit_length
        self._scale = None  # running absmax (python-held float)

    def scales(self):
        return to_tensor(np.float32(self._scale if self._scale else 0.0))

    def forward(self, x):
        import jax as _jax
        xt = x if isinstance(x, Tensor) else to_tensor(x)
        raw = xt._data
        if not isinstance(raw, _jax.core.Tracer):  # eager: update running max
            cur = float(_absmax(raw))
            self._scale = (cur if self._scale is None
                           else self._rate * self._scale + (1 - self._rate) * cur)
        scale = jnp.float32(self._scale if self._scale is not None else 1.0)
        q = Tensor(_fake_quant(raw, scale, self.bits), stop_gradient=True)
        # straight-through estimator: q and xt.detach() are both constants,
        # so d(out)/d(x) == identity while the VALUE is the quantized one
        return xt + (q - xt.detach())


class BaseObserver(Layer):
    bits = 8

    def scales(self):
        raise NotImplementedError


class AbsmaxObserver(BaseObserver):
    """Calibration observer: tracks the max |x| seen (reference
    observers/abs_max.py AbsmaxObserver) — forward is identity."""

    def __init__(self, quant_bits: int = 8, **_unused):
        super().__init__()
        self.bits = quant_bits
        self._max = 0.0

    def scales(self):
        return to_tensor(np.float32(self._max))

    def forward(self, x):
        import jax as _jax
        raw = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        if not isinstance(raw, _jax.core.Tracer):
            self._max = max(self._max, float(_absmax(raw)))
        return x


def quanter(name):
    """Decorator parity shim (reference quantization/factory.py)."""
    def deco(cls):
        return cls
    return deco


class QuantConfig:
    """Maps layer types to (activation, weight) quanter factories
    (reference quantization/config.py QuantConfig)."""

    def __init__(self, activation=None, weight=None):
        self._default = (activation, weight)
        self._by_type: Dict[Type, tuple] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._by_type[t] = (activation, weight)

    def _lookup(self, layer):
        for t, cfg in self._by_type.items():
            if isinstance(layer, t):
                return cfg
        if any(self._default):
            return self._default
        return None


class QuantedLinear(Layer):
    """Linear with fake-quantized activations + weights (reference
    nn/quant/qat/linear.py QuantedLinear)."""

    def __init__(self, linear, act_quanter=None, weight_quanter=None):
        super().__init__()
        self.weight = linear.weight
        self.bias = linear.bias
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return nn.functional.linear(x, w, self.bias)


class _ConvertedLinear(Layer):
    """Inference form: int8 weight + per-tensor dequant scale."""

    def __init__(self, qlinear):
        super().__init__()
        w = qlinear.weight._data
        scale = float(jnp.max(jnp.abs(w)))
        qmax = 127.0
        s = max(scale, 1e-9) / qmax
        self.w_int8 = to_tensor(
            jnp.clip(jnp.round(w / s), -128, 127).astype(jnp.int8))
        self.weight_scale = to_tensor(np.float32(s))
        self.bias = qlinear.bias

    def forward(self, x):
        w = self.w_int8._data.astype(jnp.float32) * self.weight_scale._data
        return nn.functional.linear(x, Tensor(w), self.bias)


_DEFAULT_TYPES = (nn.Linear,)


def _swap(model, make_wrapper):
    for name, sub in list(model._sub_layers.items()):
        replaced = make_wrapper(sub)
        if replaced is not None:
            model._sub_layers[name] = replaced
        else:
            _swap(sub, make_wrapper)
    return model


class QAT:
    """Quantization-aware training driver (reference quantization/qat.py)."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)

        def wrap(layer):
            if not isinstance(layer, _DEFAULT_TYPES):
                return None
            cfg = self._config._lookup(layer)
            if cfg is None:
                return None
            act_f, w_f = cfg
            return QuantedLinear(layer,
                                 act_f() if act_f else None,
                                 w_f() if w_f else None)

        return _swap(model, wrap)

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)

        def wrap(layer):
            if isinstance(layer, QuantedLinear):
                return _ConvertedLinear(layer)
            return None

        return _swap(model, wrap)


class PTQ:
    """Post-training quantization driver (reference quantization/ptq.py):
    quantize() inserts observers, run calibration batches, convert()."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)

        def wrap(layer):
            if not isinstance(layer, _DEFAULT_TYPES):
                return None
            cfg = self._config._lookup(layer)
            if cfg is None:
                return None
            act_f, w_f = cfg
            return QuantedLinear(layer,
                                 act_f() if act_f else None,
                                 w_f() if w_f else None)

        return _swap(model, wrap)

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)

        def wrap(layer):
            if isinstance(layer, QuantedLinear):
                return _ConvertedLinear(layer)
            return None

        return _swap(model, wrap)
