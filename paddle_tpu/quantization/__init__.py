"""paddle.quantization — QAT + PTQ (SURVEY C43; reference
python/paddle/quantization/{qat.py,ptq.py,config.py,quanter,observers}).

TPU-native mapping: int8 fake-quant is plain jnp math that XLA fuses into
the surrounding matmul; the straight-through estimator is
`x + stop_gradient(q(x) - x)` on the eager tape.  Layout and API mirror the
reference: a `QuantConfig` maps layer types to quanter/observer factories,
`QAT.quantize` swaps matching sublayers for quantized wrappers with
trainable fake-quanters, `PTQ.quantize` inserts observers for calibration,
and `.convert` freezes scales into int8 weights + dequant scales.
"""

from __future__ import annotations

import copy
import functools
from typing import Callable, Dict, Optional, Type

import numpy as np

import jax.numpy as jnp

from .. import nn
from ..nn.layer import Layer
from ..tensor import Tensor, to_tensor

__all__ = [
    "QuantConfig", "QAT", "PTQ",
    "FakeQuanterWithAbsMaxObserver", "FakeQuanterChannelWiseAbsMax",
    "AbsmaxObserver", "PerChannelAbsmaxObserver",
    "MovingAverageAbsmaxObserver", "QuantedLinear", "QuantedConv2D",
    "quanter",
]


def _absmax(x, axis=None):
    return jnp.max(jnp.abs(x), axis=axis) if axis is not None else jnp.max(jnp.abs(x))


def _fake_quant(raw, scale, bits=8):
    qmax = float(2 ** (bits - 1) - 1)
    s = jnp.maximum(scale, 1e-9) / qmax
    return jnp.clip(jnp.round(raw / s), -qmax - 1, qmax) * s


class BaseQuanter(Layer):
    bits = 8

    def scales(self):
        raise NotImplementedError


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Moving-absmax fake quanter with STE (reference
    quanter/abs_max.py FakeQuanterWithAbsMaxObserver)."""

    def __init__(self, moving_rate: float = 0.9, bit_length: int = 8,
                 **_unused):
        super().__init__()
        self._rate = moving_rate
        self.bits = bit_length
        self._scale = None  # running absmax (python-held float)

    def scales(self):
        return to_tensor(np.float32(self._scale if self._scale else 0.0))

    def forward(self, x):
        import jax as _jax
        xt = x if isinstance(x, Tensor) else to_tensor(x)
        raw = xt._data
        if not isinstance(raw, _jax.core.Tracer):  # eager: update running max
            cur = float(_absmax(raw))
            self._scale = (cur if self._scale is None
                           else self._rate * self._scale + (1 - self._rate) * cur)
        scale = jnp.float32(self._scale if self._scale is not None else 1.0)
        q = Tensor(_fake_quant(raw, scale, self.bits), stop_gradient=True)
        # straight-through estimator: q and xt.detach() are both constants,
        # so d(out)/d(x) == identity while the VALUE is the quantized one
        return xt + (q - xt.detach())


class FakeQuanterChannelWiseAbsMax(BaseQuanter):
    """Per-channel weight fake quanter with STE (reference
    quanters/abs_max.py FakeQuanterChannelWiseAbsMax): one scale per
    quant_axis channel — the standard recipe for conv/linear weights,
    where per-tensor scales lose small channels to one outlier."""

    def __init__(self, bit_length: int = 8, quant_axis: Optional[int] = None,
                 **_unused):
        super().__init__()
        self.bits = bit_length
        # None = auto: output channels — axis 0 for conv OIHW weights
        # (reference default quant_axis=0 for conv), last axis for linear
        # (in_features, out_features) weights
        self.quant_axis = quant_axis
        self._scale = None

    def scales(self):
        return to_tensor(self._scale if self._scale is not None
                         else np.float32(0.0))

    def _axis(self, ndim):
        if self.quant_axis is None:
            return 0 if ndim == 4 else ndim - 1
        return self.quant_axis % ndim

    def forward(self, x):
        import jax as _jax
        xt = x if isinstance(x, Tensor) else to_tensor(x)
        raw = xt._data
        ax = self._axis(raw.ndim)
        reduce_axes = tuple(i for i in range(raw.ndim) if i != ax)
        cur = jnp.max(jnp.abs(raw), axis=reduce_axes, keepdims=True)
        if not isinstance(raw, _jax.core.Tracer):
            self._scale = np.asarray(cur)
        q = Tensor(_fake_quant(raw, cur, self.bits), stop_gradient=True)
        return xt + (q - xt.detach())


class BaseObserver(Layer):
    bits = 8

    def scales(self):
        raise NotImplementedError


class AbsmaxObserver(BaseObserver):
    """Calibration observer: tracks the max |x| seen (reference
    observers/abs_max.py AbsmaxObserver) — forward is identity."""

    def __init__(self, quant_bits: int = 8, **_unused):
        super().__init__()
        self.bits = quant_bits
        self._max = 0.0

    def scales(self):
        return to_tensor(np.float32(self._max))

    def forward(self, x):
        import jax as _jax
        raw = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        if not isinstance(raw, _jax.core.Tracer):
            self._max = max(self._max, float(_absmax(raw)))
        return x


class PerChannelAbsmaxObserver(BaseObserver):
    """Per-channel calibration observer: one running absmax per
    `quant_axis` channel (the reference's per-channel observer capability;
    VERDICT r3 weak #2 — absmax-only was the gap)."""

    def __init__(self, quant_bits: int = 8,
                 quant_axis: Optional[int] = None, **_unused):
        super().__init__()
        self.bits = quant_bits
        self.quant_axis = quant_axis  # None = auto (conv OIHW -> 0)
        self._max = None

    def scales(self):
        return to_tensor(self._max if self._max is not None
                         else np.float32(0.0))

    def forward(self, x):
        import jax as _jax
        raw = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        if not isinstance(raw, _jax.core.Tracer):
            if self.quant_axis is None:
                ax = 0 if raw.ndim == 4 else raw.ndim - 1
            else:
                ax = self.quant_axis % raw.ndim
            reduce_axes = tuple(i for i in range(raw.ndim) if i != ax)
            cur = np.asarray(jnp.max(jnp.abs(raw), axis=reduce_axes,
                                     keepdims=True))
            self._max = cur if self._max is None else np.maximum(self._max,
                                                                 cur)
        return x


class MovingAverageAbsmaxObserver(BaseObserver):
    """EMA absmax calibration observer (reference
    imperative/moving-average observer family): robust to a single outlier
    batch during PTQ calibration."""

    def __init__(self, quant_bits: int = 8, moving_rate: float = 0.9,
                 **_unused):
        super().__init__()
        self.bits = quant_bits
        self._rate = moving_rate
        self._max = None

    def scales(self):
        return to_tensor(np.float32(self._max if self._max is not None
                                    else 0.0))

    def forward(self, x):
        import jax as _jax
        raw = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        if not isinstance(raw, _jax.core.Tracer):
            cur = float(_absmax(raw))
            self._max = (cur if self._max is None
                         else self._rate * self._max + (1 - self._rate) * cur)
        return x


def quanter(name):
    """Decorator parity shim (reference quantization/factory.py)."""
    def deco(cls):
        return cls
    return deco


class QuantConfig:
    """Maps layer types to (activation, weight) quanter factories
    (reference quantization/config.py QuantConfig)."""

    def __init__(self, activation=None, weight=None):
        self._default = (activation, weight)
        self._by_type: Dict[Type, tuple] = {}

    def add_type_config(self, layer_type, activation=None, weight=None):
        for t in (layer_type if isinstance(layer_type, (list, tuple))
                  else [layer_type]):
            self._by_type[t] = (activation, weight)

    def _lookup(self, layer):
        for t, cfg in self._by_type.items():
            if isinstance(layer, t):
                return cfg
        if any(self._default):
            return self._default
        return None


class QuantedLinear(Layer):
    """Linear with fake-quantized activations + weights (reference
    nn/quant/qat/linear.py QuantedLinear)."""

    def __init__(self, linear, act_quanter=None, weight_quanter=None):
        super().__init__()
        self.weight = linear.weight
        self.bias = linear.bias
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return nn.functional.linear(x, w, self.bias)


class QuantedConv2D(Layer):
    """Conv2D with fake-quantized activations + weights (reference
    nn/quant/qat/conv.py:23 QuantedConv2D)."""

    def __init__(self, conv, act_quanter=None, weight_quanter=None):
        super().__init__()
        self.weight = conv.weight
        self.bias = conv.bias
        self._stride = conv._stride
        self._padding = conv._padding
        self._dilation = conv._dilation
        self._groups = conv._groups
        self._data_format = conv._data_format
        self.activation_quanter = act_quanter
        self.weight_quanter = weight_quanter

    def forward(self, x):
        if self.activation_quanter is not None:
            x = self.activation_quanter(x)
        w = self.weight
        if self.weight_quanter is not None:
            w = self.weight_quanter(w)
        return nn.functional.conv2d(x, w, self.bias, self._stride,
                                    self._padding, self._dilation,
                                    self._groups, self._data_format)


def _int8_weight(w, quant_axis=None):
    """(int8 weight, dequant scale) — per-tensor or per-`quant_axis`."""
    if quant_axis is None:
        s = max(float(jnp.max(jnp.abs(w))), 1e-9) / 127.0
        scale = jnp.float32(s)
    else:
        ax = quant_axis % w.ndim
        reduce_axes = tuple(i for i in range(w.ndim) if i != ax)
        scale = jnp.maximum(jnp.max(jnp.abs(w), axis=reduce_axes,
                                    keepdims=True), 1e-9) / 127.0
    q = jnp.clip(jnp.round(w / scale), -128, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def _quant_axis_of(quanter_layer, weight_ndim, default=None):
    """Resolve the per-channel axis a quanter/observer used (None-auto
    follows FakeQuanterChannelWiseAbsMax._axis: conv OIHW -> 0, else last)."""
    if isinstance(quanter_layer, (FakeQuanterChannelWiseAbsMax,
                                  PerChannelAbsmaxObserver)):
        ax = quanter_layer.quant_axis
        if ax is None:
            return 0 if weight_ndim == 4 else weight_ndim - 1
        return ax
    return default


class _ConvertedLinear(Layer):
    """Inference form: int8 weight + dequant scale (per-tensor, or
    per-output-channel when the weight quanter was channel-wise).  The
    weight-only-int8 pattern: the dequantized matmul runs in the activation
    dtype while weights sit in HBM at 1/4 size."""

    def __init__(self, qlinear):
        super().__init__()
        axis = _quant_axis_of(qlinear.weight_quanter,
                              qlinear.weight._data.ndim)
        q, s = _int8_weight(qlinear.weight._data, axis)
        self.w_int8 = to_tensor(q)
        self.weight_scale = to_tensor(s)
        self.bias = qlinear.bias

    def forward(self, x):
        w = self.w_int8._data.astype(jnp.float32) * self.weight_scale._data
        return nn.functional.linear(x, Tensor(w), self.bias)


class _ConvertedConv2D(Layer):
    """Inference conv: int8 OIHW weight + per-output-channel dequant."""

    def __init__(self, qconv):
        super().__init__()
        axis = _quant_axis_of(qconv.weight_quanter,
                              qconv.weight._data.ndim, default=0)
        q, s = _int8_weight(qconv.weight._data, axis)
        self.w_int8 = to_tensor(q)
        self.weight_scale = to_tensor(s)
        self.bias = qconv.bias
        for a in ("_stride", "_padding", "_dilation", "_groups",
                  "_data_format"):
            setattr(self, a, getattr(qconv, a))

    def forward(self, x):
        w = self.w_int8._data.astype(jnp.float32) * self.weight_scale._data
        return nn.functional.conv2d(x, Tensor(w), self.bias, self._stride,
                                    self._padding, self._dilation,
                                    self._groups, self._data_format)


_DEFAULT_TYPES = (nn.Linear, nn.Conv2D)


def _wrap_quant(layer, config):
    """Swap a matching layer for its fake-quantized wrapper."""
    if not isinstance(layer, _DEFAULT_TYPES):
        return None
    cfg = config._lookup(layer)
    if cfg is None:
        return None
    act_f, w_f = cfg
    act = act_f() if act_f else None
    w = w_f() if w_f else None
    if isinstance(layer, nn.Conv2D):
        return QuantedConv2D(layer, act, w)
    return QuantedLinear(layer, act, w)


def _wrap_convert(layer):
    if isinstance(layer, QuantedLinear):
        return _ConvertedLinear(layer)
    if isinstance(layer, QuantedConv2D):
        return _ConvertedConv2D(layer)
    return None


def _swap(model, make_wrapper):
    for name, sub in list(model._sub_layers.items()):
        replaced = make_wrapper(sub)
        if replaced is not None:
            model._sub_layers[name] = replaced
        else:
            _swap(sub, make_wrapper)
    return model


class QAT:
    """Quantization-aware training driver (reference quantization/qat.py)."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)

        # the model itself may BE a matching layer (quantize(Linear(...)))
        root = _wrap_quant(model, config=self._config)
        if root is not None:
            return root
        return _swap(model, functools.partial(_wrap_quant,
                                              config=self._config))

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)

        root = _wrap_convert(model)
        if root is not None:
            return root
        return _swap(model, _wrap_convert)


class PTQ:
    """Post-training quantization driver (reference quantization/ptq.py):
    quantize() inserts observers, run calibration batches, convert()."""

    def __init__(self, config: QuantConfig):
        self._config = config

    def quantize(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)

        # the model itself may BE a matching layer (quantize(Linear(...)))
        root = _wrap_quant(model, config=self._config)
        if root is not None:
            return root
        return _swap(model, functools.partial(_wrap_quant,
                                              config=self._config))

    def convert(self, model: Layer, inplace: bool = False) -> Layer:
        if not inplace:
            model = copy.deepcopy(model)

        root = _wrap_convert(model)
        if root is not None:
            return root
        return _swap(model, _wrap_convert)
