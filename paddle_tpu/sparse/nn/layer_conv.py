"""Sparse NN layers: conv, norm, pooling.

Reference: python/paddle/sparse/nn/layer/conv.py:27 (_Conv3D/_Conv2D,
Conv3D:239, SubmConv3D:509, Conv2D:374, SubmConv2D:649), norm.py:99
(BatchNorm), :305 (SyncBatchNorm), pooling.py:75 (MaxPool3D).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from ...nn import initializer as I
from ...nn.layer import Layer
from ...tensor import apply_op
from . import functional as F


def _tuple(v, n):
    return (int(v),) * n if isinstance(v, (int, np.integer)) \
        else tuple(int(e) for e in v)


class _ConvND(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False, key=None,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NDHWC", n_sp=3):
        super().__init__()
        if padding_mode != "zeros":
            raise NotImplementedError(
                "sparse conv only supports padding_mode='zeros'")
        if groups != 1:
            raise NotImplementedError("sparse conv only supports groups=1")
        self.in_channels = in_channels
        self.out_channels = out_channels
        self._n_sp = n_sp
        self._kernel_size = _tuple(kernel_size, n_sp)
        self._stride = _tuple(stride, n_sp)
        self._padding = _tuple(padding, n_sp)
        self._dilation = _tuple(dilation, n_sp)
        self._subm = subm
        self._data_format = data_format
        fan_in = in_channels * int(np.prod(self._kernel_size))
        std = 1.0 / math.sqrt(fan_in)
        self.weight = self.create_parameter(
            (*self._kernel_size, in_channels, out_channels), weight_attr,
            default_initializer=I.Uniform(-std, std))
        self.bias = self.create_parameter(
            (out_channels,), bias_attr, is_bias=True,
            default_initializer=I.Uniform(-std, std))

    def forward(self, x):
        fn = {(3, False): F.conv3d, (3, True): F.subm_conv3d,
              (2, False): F.conv2d, (2, True): F.subm_conv2d}[
                  (self._n_sp, self._subm)]
        return fn(x, self.weight, self.bias, stride=self._stride,
                  padding=self._padding, dilation=self._dilation,
                  data_format=self._data_format)


class Conv3D(_ConvND):
    """Reference sparse/nn/layer/conv.py:239."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, False, None,
                         padding_mode, weight_attr, bias_attr, data_format, 3)


class SubmConv3D(_ConvND):
    """Reference sparse/nn/layer/conv.py:509."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NDHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, True, key,
                         padding_mode, weight_attr, bias_attr, data_format, 3)


class Conv2D(_ConvND):
    """Reference sparse/nn/layer/conv.py:374."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, False, None,
                         padding_mode, weight_attr, bias_attr, data_format, 2)


class SubmConv2D(_ConvND):
    """Reference sparse/nn/layer/conv.py:649."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 key=None, weight_attr=None, bias_attr=None,
                 data_format="NHWC"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, True, key,
                         padding_mode, weight_attr, bias_attr, data_format, 2)


class BatchNorm(Layer):
    """Batch norm over the dense channel values of a sparse tensor — the
    nnz sites are the batch (reference sparse/nn/layer/norm.py:99, which
    reuses dense BN over the value tensor the same way).
    """

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        self._momentum = momentum
        self._epsilon = epsilon
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), weight_attr, default_initializer=I.Constant(1.0))
        self.bias = self.create_parameter(
            (num_features,), bias_attr, is_bias=True,
            default_initializer=I.Constant(0.0))
        self.register_buffer("_mean", jnp.zeros((num_features,), jnp.float32))
        self.register_buffer("_variance",
                             jnp.ones((num_features,), jnp.float32))

    def forward(self, x):
        from .. import SparseCooTensor
        from jax.experimental import sparse as jsparse

        b = x._bcoo
        vals = x.values()
        use_running = (self._use_global_stats
                       or (self._use_global_stats is None
                           and not self.training))
        if use_running:
            mean, var = self._mean._data, self._variance._data
        else:
            # running-stat update happens outside the recorded op (no grad);
            # the NORMALIZING stats are recomputed INSIDE fn so the vjp
            # carries the d(mean)/dx and d(var)/dx terms (same reasoning as
            # the dense batch_norm, nn/functional/__init__.py batch_norm)
            raw = vals._data.astype(jnp.float32)
            m = self._momentum
            self._mean._data = (m * self._mean._data
                                + (1 - m) * raw.mean(axis=0))
            self._variance._data = (m * self._variance._data
                                    + (1 - m) * raw.var(axis=0))
            mean = var = None

        def fn(v, w, bias):
            vf = v.astype(jnp.float32)
            mu = mean if mean is not None else vf.mean(axis=0)
            vr = var if var is not None else vf.var(axis=0)
            vn = (vf - mu) / jnp.sqrt(vr + self._epsilon)
            return (vn * w + bias).astype(v.dtype)

        out = apply_op("sparse_batch_norm", fn, vals, self.weight, self.bias)
        return SparseCooTensor(jsparse.BCOO((out._data, b.indices),
                                            shape=b.shape), values_t=out)


class SyncBatchNorm(BatchNorm):
    """Reference sparse/nn/layer/norm.py:305.  Under pjit/GSPMD the batch
    statistics are computed over the GLOBAL value set automatically (XLA
    inserts the cross-device reductions), so sync == plain BatchNorm here.
    """

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, BatchNorm) and not isinstance(layer,
                                                           SyncBatchNorm):
            new = SyncBatchNorm(layer.weight.shape[0],
                                momentum=layer._momentum,
                                epsilon=layer._epsilon,
                                use_global_stats=layer._use_global_stats)
            new.weight = layer.weight
            new.bias = layer.bias
            # the learned running stats must survive conversion
            # (reference nn/layer/norm.py:1755 copies both buffers)
            new._mean._data = layer._mean._data
            new._variance._data = layer._variance._data
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class MaxPool3D(Layer):
    """Reference sparse/nn/layer/pooling.py:75."""

    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False, data_format="NDHWC",
                 name=None):
        super().__init__()
        if return_mask:
            raise NotImplementedError("sparse MaxPool3D: return_mask")
        self._kernel_size = kernel_size
        self._stride = stride
        self._padding = padding
        self._ceil_mode = ceil_mode
        self._data_format = data_format

    def forward(self, x):
        return F.max_pool3d(x, self._kernel_size, self._stride,
                            self._padding, self._ceil_mode,
                            self._data_format)
