"""paddle.sparse.nn — activations on sparse tensors (reference
python/paddle/sparse/nn/layer/activation.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ...nn.layer import Layer

__all__ = ["ReLU", "LeakyReLU", "ReLU6", "Softmax", "functional",
           "Conv2D", "Conv3D", "SubmConv2D", "SubmConv3D",
           "BatchNorm", "SyncBatchNorm", "MaxPool3D"]


class _ValueAct(Layer):
    def forward(self, x):
        from .. import _unary
        return _unary(self._name, self._fn)(x)


class ReLU(_ValueAct):
    _name, _fn = "relu", staticmethod(jax.nn.relu)


class ReLU6(_ValueAct):
    _name, _fn = "relu6", staticmethod(jax.nn.relu6)


class LeakyReLU(_ValueAct):
    def __init__(self, negative_slope=0.01):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        from .. import _unary
        return _unary("leaky_relu",
                      lambda v: jax.nn.leaky_relu(v, self._slope))(x)


class Softmax(Layer):
    """Row softmax over stored values only (zeros act as -inf) — reference
    sparse/nn/layer/activation.py Softmax semantics for 2-D CSR/COO."""

    def __init__(self, axis=-1):
        super().__init__()
        if axis != -1:
            raise ValueError("sparse Softmax supports axis=-1")

    def forward(self, x):
        from .. import SparseCsrTensor, SparseCooTensor, _to_coo
        if isinstance(x, SparseCsrTensor):
            coo = x.to_coo()
            as_csr = True
        else:
            coo = _to_coo(x).coalesce()
            as_csr = False
        b = coo._bcoo
        rows = b.indices[:, 0]
        nrows = b.shape[0]
        vmax = jax.ops.segment_max(b.data, rows, num_segments=nrows)
        e = jnp.exp(b.data - vmax[rows])
        denom = jax.ops.segment_sum(e, rows, num_segments=nrows)
        vals = e / denom[rows]
        out = SparseCooTensor(jsparse.BCOO((vals, b.indices), shape=b.shape))
        return out.to_sparse_csr() if as_csr else out


from . import functional  # noqa: E402,F401
from .layer_conv import (  # noqa: E402,F401
    Conv2D, Conv3D, SubmConv2D, SubmConv3D,
    BatchNorm, SyncBatchNorm, MaxPool3D,
)
