"""Sparse convolution / pooling functionals (gather-scatter over COO sites).

Reference: python/paddle/sparse/nn/functional/conv.py (conv3d/subm_conv3d,
conv2d/subm_conv2d), pooling.py (max_pool3d).  The reference lowers to
gather-gemm-scatter CUDA kernels over a precomputed "rulebook" (offset ->
(input row, output row) pairs); here the rulebook is built eagerly in numpy
from the concrete COO indices, and the value computation is ONE tape op:
a static python loop over kernel offsets of gather -> (m, Cin) @ (Cin,
Cout) -> scatter-add, which XLA fuses per offset.  Gradients flow to
values, weight and bias through the op's vjp; indices are structural.

Layout matches the reference: x is a hybrid SparseCooTensor with indices
over (N, *spatial) and dense channel values (nnz, C); kernels are
channels-last (*kernel_sizes, Cin, Cout); data_format NDHWC / NHWC only.
"""

from __future__ import annotations

import itertools

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ....tensor import Tensor, apply_op

__all__ = ["conv3d", "subm_conv3d", "conv2d", "subm_conv2d", "max_pool3d"]


def _tuple(v, n, name):
    if isinstance(v, (int, np.integer)):
        return (int(v),) * n
    v = tuple(int(e) for e in v)
    if len(v) != n:
        raise ValueError(f"{name} should have {n} elements, got {v}")
    return v


def _check_input(x, n_sp, op):
    from ... import SparseCooTensor
    if not isinstance(x, SparseCooTensor):
        raise ValueError(f"{op} expects a SparseCooTensor input")
    b = x._bcoo
    if b.indices.shape[1] != n_sp + 1 or b.data.ndim != 2:
        raise ValueError(
            f"{op} expects hybrid COO indices over (N, {n_sp} spatial dims) "
            f"with dense channels; got indices over {b.indices.shape[1]} "
            f"dims, values ndim {b.data.ndim}")
    return b


def _rulebook(idx, sp_shape, out_sp, ksizes, stride, padding, dilation,
              subm):
    """Offset -> (input rows, output site keys); then unify the output-site
    set.  All-numpy over concrete indices (the reference's rulebook build,
    sparse/gpu/conv_kernel.cu, done host-side)."""
    n_sp = len(ksizes)
    batch = idx[:, 0].astype(np.int64)
    coords = idx[:, 1:].astype(np.int64)                       # (nnz, n_sp)

    def key_of(b, c):                                          # linearize
        k = b
        for d in range(n_sp):
            k = k * out_sp[d] + c[:, d]
        return k

    sel_rows, out_keys = [], []
    for off in itertools.product(*[range(k) for k in ksizes]):
        num = coords + np.array([padding[d] - off[d] * dilation[d]
                                 for d in range(n_sp)])
        q, r = np.divmod(num, np.array(stride))
        ok = (r == 0).all(1)
        for d in range(n_sp):
            ok &= (q[:, d] >= 0) & (q[:, d] < out_sp[d])
        rows = np.nonzero(ok)[0]
        sel_rows.append(rows)
        out_keys.append(key_of(batch[rows], q[rows]))

    if subm:
        site_keys = key_of(batch, coords)                      # out == in
        order = np.argsort(site_keys, kind="stable")
        skeys = site_keys[order]
        out_ids = []
        for oi in range(len(sel_rows)):
            pos = np.searchsorted(skeys, out_keys[oi])
            pos_c = np.minimum(pos, len(skeys) - 1) if len(skeys) else pos
            found = (pos < len(skeys)) & (skeys[pos_c] == out_keys[oi])
            sel_rows[oi] = sel_rows[oi][found]
            out_ids.append(order[pos[found]])
        uniq = site_keys
    else:
        allk = np.concatenate(out_keys) if out_keys else np.zeros(0, np.int64)
        uniq, inv = np.unique(allk, return_inverse=True)
        out_ids, p = [], 0
        for oi in range(len(sel_rows)):
            m = len(sel_rows[oi])
            out_ids.append(inv[p:p + m])
            p += m

    # un-linearize the unique output keys back to coordinates
    rem = uniq.copy()
    cols = []
    for d in reversed(range(n_sp)):
        rem, c = np.divmod(rem, out_sp[d])
        cols.append(c)
    out_idx = np.stack([rem] + cols[::-1], axis=1).astype(np.int32)
    return sel_rows, out_ids, out_idx


def _sparse_conv(x, weight, bias, stride, padding, dilation, groups, subm,
                 n_sp, op):
    from ... import SparseCooTensor
    if groups != 1:
        raise NotImplementedError(f"{op}: only groups=1 is supported")
    b = _check_input(x, n_sp, op)
    w = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    if w.ndim != n_sp + 2:
        raise ValueError(f"{op} kernel must be (*k_sizes, Cin, Cout), got "
                         f"shape {tuple(w.shape)}")
    ksizes = tuple(int(s) for s in w.shape[:n_sp])
    stride = _tuple(stride, n_sp, "stride")
    padding = _tuple(padding, n_sp, "padding")
    dilation = _tuple(dilation, n_sp, "dilation")
    if subm:
        if any(s != 1 for s in stride):
            raise ValueError(f"{op}: submanifold conv requires stride 1 "
                             "(output sites are the input sites)")
        # the reference ALWAYS centers the subm kernel: paddings are reset
        # to kernel/2 regardless of the caller's value
        # (paddle/phi/kernels/funcs/sparse/convolution.h:146
        # ResetSubmKernelSizeAndStrides)
        padding = tuple(dilation[d] * (ksizes[d] - 1) // 2
                        for d in range(n_sp))
    shape = x.shape
    sp_shape = shape[1:-1]
    if subm:
        out_sp = tuple(sp_shape)
    else:
        out_sp = tuple(
            (sp_shape[d] + 2 * padding[d]
             - dilation[d] * (ksizes[d] - 1) - 1) // stride[d] + 1
            for d in range(n_sp))
    idx = np.asarray(b.indices)
    sel_rows, out_ids, out_idx = _rulebook(
        idx, sp_shape, out_sp, ksizes, stride, padding, dilation, subm)
    n_out = out_idx.shape[0]
    cout = int(w.shape[-1])
    K = int(np.prod(ksizes))

    def fn(vals, w, bias):
        wf = w.reshape(K, w.shape[-2], w.shape[-1])
        out = jnp.zeros((n_out, cout), vals.dtype)
        for oi in range(K):
            if len(sel_rows[oi]) == 0:
                continue
            contrib = vals[sel_rows[oi]] @ wf[oi].astype(vals.dtype)
            out = out.at[out_ids[oi]].add(contrib)
        if bias is not None:
            out = out + bias.astype(vals.dtype)
        return out

    out_vals = apply_op(f"sparse_{op}", fn, x.values(), weight, bias)
    out_shape = (shape[0], *out_sp, cout)
    return SparseCooTensor(jsparse.BCOO(
        (out_vals._data, jnp.asarray(out_idx)), shape=out_shape),
        values_t=out_vals)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NDHWC", name=None):
    """Reference sparse/nn/functional/conv.py conv3d."""
    if data_format != "NDHWC":
        raise ValueError("sparse conv3d only supports NDHWC")
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        subm=False, n_sp=3, op="conv3d")


def subm_conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NDHWC", key=None, name=None):
    """Submanifold conv: output sites == input sites (no dilation of the
    active set across layers).  Reference subm_conv3d."""
    if data_format != "NDHWC":
        raise ValueError("sparse subm_conv3d only supports NDHWC")
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        subm=True, n_sp=3, op="subm_conv3d")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NHWC", name=None):
    if data_format != "NHWC":
        raise ValueError("sparse conv2d only supports NHWC")
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        subm=False, n_sp=2, op="conv2d")


def subm_conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1,
                groups=1, data_format="NHWC", key=None, name=None):
    if data_format != "NHWC":
        raise ValueError("sparse subm_conv2d only supports NHWC")
    return _sparse_conv(x, weight, bias, stride, padding, dilation, groups,
                        subm=True, n_sp=2, op="subm_conv2d")


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               data_format="NDHWC", name=None):
    """Max pooling over active sites only (reference sparse pooling.py:
    windows with no active input produce no output site)."""
    from ... import SparseCooTensor
    if data_format != "NDHWC":
        raise ValueError("sparse max_pool3d only supports NDHWC")
    if ceil_mode:
        raise NotImplementedError("sparse max_pool3d: ceil_mode")
    b = _check_input(x, 3, "max_pool3d")
    ksizes = _tuple(kernel_size, 3, "kernel_size")
    stride = _tuple(stride if stride is not None else kernel_size, 3,
                    "stride")
    padding = _tuple(padding, 3, "padding")
    shape = x.shape
    sp_shape = shape[1:-1]
    out_sp = tuple((sp_shape[d] + 2 * padding[d] - ksizes[d]) // stride[d] + 1
                   for d in range(3))
    idx = np.asarray(b.indices)
    sel_rows, out_ids, out_idx = _rulebook(
        idx, sp_shape, out_sp, ksizes, stride, padding, (1, 1, 1), False)
    n_out = out_idx.shape[0]
    C = int(b.data.shape[-1])

    def fn(vals):
        out = jnp.full((n_out, C), -jnp.inf, vals.dtype)
        for oi in range(len(sel_rows)):
            if len(sel_rows[oi]) == 0:
                continue
            out = out.at[out_ids[oi]].max(vals[sel_rows[oi]])
        return out

    out_vals = apply_op("sparse_max_pool3d", fn, x.values())
    return SparseCooTensor(jsparse.BCOO(
        (out_vals._data, jnp.asarray(out_idx)),
        shape=(shape[0], *out_sp, C)), values_t=out_vals)
