"""paddle.sparse.nn.functional — functional forms."""

from __future__ import annotations

import jax

__all__ = ["relu", "relu6", "leaky_relu", "softmax",
           "conv3d", "subm_conv3d", "conv2d", "subm_conv2d",
           "max_pool3d"]


def relu(x, name=None):
    from ... import _unary
    return _unary("relu", jax.nn.relu)(x)


def relu6(x, name=None):
    from ... import _unary
    return _unary("relu6", jax.nn.relu6)(x)


def leaky_relu(x, negative_slope=0.01, name=None):
    from ... import _unary
    return _unary("leaky_relu",
                  lambda v: jax.nn.leaky_relu(v, negative_slope))(x)


def softmax(x, axis=-1, name=None):
    from .. import Softmax
    return Softmax(axis=axis)(x)


from .conv import (  # noqa: E402,F401
    conv3d, subm_conv3d, conv2d, subm_conv2d, max_pool3d,
)
