"""paddle.sparse — COO/CSR tensors + ops (SURVEY C48 / reference
python/paddle/sparse/).

TPU-native design: XLA has no sparse kernels, so sparse storage is
STATIC-SHAPE arrays — COO rides `jax.experimental.sparse.BCOO` (indices
(nnz, ndim) + values (nnz,), padded/coalesced, differentiable dot), CSR
stores (crows, cols, values) directly.  Sparsity-preserving unary math
(f(0) == 0) runs on the value array alone — O(nnz) elementwise on the VPU;
`matmul` lowers through `bcoo_dot_general` (gather + MXU segments); anything
requiring pattern algebra (union add) concatenates indices and coalesces.

This mirrors the reference API surface (sparse/creation.py:72,187, unary.py,
binary.py, matmul.py) on the paddle Tensor wrapper.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

from ..tensor import Tensor, to_tensor
from . import nn  # noqa: F401  (re-export subpackage)

__all__ = [
    "SparseCooTensor", "SparseCsrTensor", "sparse_coo_tensor",
    "sparse_csr_tensor", "is_same_shape",
    "sin", "tan", "asin", "atan", "sinh", "tanh", "asinh", "atanh",
    "sqrt", "square", "log1p", "abs", "neg", "expm1", "rad2deg", "deg2rad",
    "pow", "cast", "coalesce", "add", "subtract", "multiply", "divide",
    "matmul", "masked_matmul", "transpose", "reshape", "sum", "to_dense",
    "addmm", "isnan", "mv", "slice", "pca_lowrank",
]


def _raw(x):
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


class SparseCooTensor:
    """COO sparse tensor over a BCOO core (indices (nnz, ndim), values).

    When produced by a differentiable sparse op, `_values_t` carries the
    tape-linked values Tensor so that `.values()` (and anything chained on
    it) participates in backward; the BCOO itself holds raw arrays.
    """

    def __init__(self, bcoo: jsparse.BCOO, values_t=None):
        self._bcoo = bcoo
        self._values_t = values_t

    # -- paddle surface -----------------------------------------------------
    @property
    def shape(self):
        return list(self._bcoo.shape)

    @property
    def dtype(self):
        return self._bcoo.dtype

    def indices(self):
        return to_tensor(self._bcoo.indices.T)  # paddle: (ndim, nnz)

    def values(self):
        if self._values_t is not None:
            return self._values_t
        return to_tensor(self._bcoo.data)

    def nnz(self):
        return int(self._bcoo.nse)

    def to_dense(self):
        if self._values_t is not None:
            # keep the tape link: scatter the taped values into the dense
            # result so conv -> to_dense -> loss backprops to the weights
            from ..tensor import apply_op
            idx = tuple(np.asarray(self._bcoo.indices).T)
            shape = tuple(self._bcoo.shape)
            return apply_op(
                "sparse_to_dense",
                lambda v: jnp.zeros(shape, v.dtype).at[idx].add(v),
                self._values_t)
        return to_tensor(self._bcoo.todense())

    def to_sparse_csr(self):
        dense = self._bcoo.todense()
        return _dense_to_csr(dense)

    def is_sparse_coo(self):
        return True

    def is_sparse_csr(self):
        return False

    def coalesce(self):
        return SparseCooTensor(self._bcoo.sum_duplicates())

    def numpy(self):
        return np.asarray(self._bcoo.todense())

    def __repr__(self):
        return (f"SparseCooTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")

    def matmul(self, other):
        return matmul(self, other)

    def __matmul__(self, other):
        return matmul(self, other)


class SparseCsrTensor:
    """CSR sparse matrix: crows (rows+1,), cols (nnz,), values (nnz,)."""

    def __init__(self, crows, cols, values, shape):
        self._crows = jnp.asarray(crows, jnp.int32)
        self._cols = jnp.asarray(cols, jnp.int32)
        self._values = jnp.asarray(values)
        self._shape = tuple(int(d) for d in shape)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def dtype(self):
        return self._values.dtype

    def crows(self):
        return to_tensor(self._crows)

    def cols(self):
        return to_tensor(self._cols)

    def values(self):
        return to_tensor(self._values)

    def nnz(self):
        return int(self._values.shape[0])

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True

    def to_coo(self) -> SparseCooTensor:
        rows = jnp.repeat(jnp.arange(self._shape[0]),
                          jnp.diff(self._crows),
                          total_repeat_length=self._values.shape[0])
        idx = jnp.stack([rows, self._cols], axis=1)
        return SparseCooTensor(jsparse.BCOO((self._values, idx),
                                            shape=self._shape))

    to_sparse_coo = to_coo

    def to_dense(self):
        return self.to_coo().to_dense()

    def numpy(self):
        return np.asarray(self.to_dense()._data)

    def __repr__(self):
        return (f"SparseCsrTensor(shape={self.shape}, nnz={self.nnz()}, "
                f"dtype={self.dtype})")


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    """Reference: sparse/creation.py:72.  indices: (ndim, nnz)."""
    idx = jnp.asarray(_raw(indices), jnp.int32)
    vals = _raw(values)
    if dtype is not None:
        from ..framework import convert_dtype, to_jax_dtype
        vals = vals.astype(to_jax_dtype(convert_dtype(dtype)))
    if shape is None:
        shape = tuple(int(d) for d in (jnp.max(idx, axis=1) + 1))
    bcoo = jsparse.BCOO((vals, idx.T), shape=tuple(shape))
    return SparseCooTensor(bcoo.sum_duplicates())


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    """Reference: sparse/creation.py:187."""
    vals = _raw(values)
    if dtype is not None:
        from ..framework import convert_dtype, to_jax_dtype
        vals = vals.astype(to_jax_dtype(convert_dtype(dtype)))
    return SparseCsrTensor(_raw(crows), _raw(cols), vals, shape)


def _dense_to_csr(dense) -> SparseCsrTensor:
    d = np.asarray(dense)
    assert d.ndim == 2, "CSR supports 2-D"
    rows, cols = np.nonzero(d)
    crows = np.zeros(d.shape[0] + 1, np.int32)
    np.add.at(crows, rows + 1, 1)
    crows = np.cumsum(crows).astype(np.int32)
    return SparseCsrTensor(crows, cols.astype(np.int32), d[rows, cols],
                           d.shape)


def is_same_shape(x, y) -> bool:
    return list(x.shape) == list(y.shape)


# -- unary (sparsity-preserving: f(0) == 0 -> value-only map) ---------------


def _unary(fname, fn):
    from ..tensor import apply_op

    def op(x, name=None):
        if isinstance(x, SparseCooTensor):
            b = x._bcoo
            out = apply_op(f"sparse_{fname}", fn, x.values())
            return SparseCooTensor(
                jsparse.BCOO((out._data, b.indices), shape=b.shape),
                values_t=out)
        if isinstance(x, SparseCsrTensor):
            return SparseCsrTensor(x._crows, x._cols, fn(x._values), x._shape)
        raise TypeError(f"sparse.{fname} expects a sparse tensor")
    op.__name__ = fname
    return op


sin = _unary("sin", jnp.sin)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
atanh = _unary("atanh", jnp.arctanh)
sqrt = _unary("sqrt", jnp.sqrt)
square = _unary("square", jnp.square)
log1p = _unary("log1p", jnp.log1p)
abs = _unary("abs", jnp.abs)  # noqa: A001 — paddle name
neg = _unary("neg", jnp.negative)
expm1 = _unary("expm1", jnp.expm1)
rad2deg = _unary("rad2deg", jnp.rad2deg)
deg2rad = _unary("deg2rad", jnp.deg2rad)


def pow(x, factor, name=None):  # noqa: A001 — paddle name
    return _unary("pow", lambda v: jnp.power(v, factor))(x)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    from ..framework import convert_dtype, to_jax_dtype
    vd = (to_jax_dtype(convert_dtype(value_dtype))
          if value_dtype is not None else None)
    if isinstance(x, SparseCooTensor):
        b = x._bcoo
        idx = (b.indices.astype(to_jax_dtype(convert_dtype(index_dtype)))
               if index_dtype is not None else b.indices)
        vals = b.data.astype(vd) if vd is not None else b.data
        return SparseCooTensor(jsparse.BCOO((vals, idx), shape=b.shape))
    vals = x._values.astype(vd) if vd is not None else x._values
    return SparseCsrTensor(x._crows, x._cols, vals, x._shape)


def coalesce(x, name=None):
    return x.coalesce()


# -- binary ------------------------------------------------------------------


def _to_coo(x):
    return x.to_coo() if isinstance(x, SparseCsrTensor) else x


def _union(x, y, fn_y):
    """Union-pattern combine: concat indices, apply fn to y's values, sum."""
    bx, by = _to_coo(x)._bcoo, _to_coo(y)._bcoo
    idx = jnp.concatenate([bx.indices, by.indices], axis=0)
    vals = jnp.concatenate([bx.data, fn_y(by.data)], axis=0)
    return SparseCooTensor(
        jsparse.BCOO((vals, idx), shape=bx.shape).sum_duplicates())


def add(x, y, name=None):
    if isinstance(y, (Tensor, jnp.ndarray)):  # sparse + dense -> dense
        return to_tensor(_to_coo(x)._bcoo.todense() + _raw(y))
    return _union(x, y, lambda v: v)


def subtract(x, y, name=None):
    return _union(x, y, jnp.negative)


def _same_pattern_combine(x, y, fn):
    bx = _to_coo(x).coalesce()._bcoo
    by = _to_coo(y).coalesce()._bcoo
    # paddle requires identical sparsity for mul/div; dense fallback keeps
    # the semantics when patterns differ
    if bx.indices.shape == by.indices.shape:
        return SparseCooTensor(jsparse.BCOO(
            (fn(bx.data, by.data), bx.indices), shape=bx.shape))
    d = fn(bx.todense(), by.todense())
    return sparse_coo_tensor(jnp.stack(jnp.nonzero(d)), d[jnp.nonzero(d)],
                             shape=bx.shape)


def multiply(x, y, name=None):
    if isinstance(y, (int, float)):
        return _unary("scale", lambda v: v * y)(x)
    return _same_pattern_combine(x, y, jnp.multiply)


def divide(x, y, name=None):
    if isinstance(y, (int, float)):
        return _unary("scale", lambda v: v / y)(x)
    return _same_pattern_combine(x, y, jnp.divide)


# -- matmul / layout ---------------------------------------------------------


def matmul(x, y, name=None):
    """sparse @ dense (spmm) or sparse @ sparse (-> dense @)."""
    if isinstance(x, (SparseCooTensor, SparseCsrTensor)):
        xb = _to_coo(x)._bcoo
        if isinstance(y, (SparseCooTensor, SparseCsrTensor)):
            return to_tensor(xb.todense() @ _to_coo(y)._bcoo.todense())
        return to_tensor(xb @ _raw(y))
    # dense @ sparse
    yb = _to_coo(y)._bcoo
    return to_tensor(_raw(x) @ yb.todense())


def masked_matmul(x, y, mask, name=None):
    """(dense @ dense) sampled at `mask`'s sparsity (SDDMM)."""
    out = _raw(x) @ _raw(y)
    b = _to_coo(mask)._bcoo
    vals = out[tuple(b.indices.T)]
    return SparseCooTensor(jsparse.BCOO((vals, b.indices), shape=b.shape))


def transpose(x, perm, name=None):
    b = _to_coo(x)._bcoo
    idx = b.indices[:, jnp.asarray(perm)]
    shape = tuple(b.shape[p] for p in perm)
    return SparseCooTensor(jsparse.BCOO((b.data, idx), shape=shape))


def reshape(x, shape, name=None):
    b = _to_coo(x)._bcoo
    flat = jnp.ravel_multi_index(tuple(b.indices.T), b.shape, mode="clip")
    shape = tuple(int(s) for s in shape)
    new_idx = jnp.stack(jnp.unravel_index(flat, shape), axis=1)
    return SparseCooTensor(jsparse.BCOO((b.data, new_idx), shape=shape))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    b = _to_coo(x)._bcoo
    if axis is None:
        out = jnp.sum(b.data)
        return to_tensor(out if not keepdim else
                         out.reshape((1,) * len(b.shape)))
    return to_tensor(jnp.sum(b.todense(), axis=axis, keepdims=keepdim))


def to_dense(x):
    return x.to_dense()


isnan = _unary("isnan", jnp.isnan)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) with sparse x (reference sparse/math.py
    addmm; dense output)."""
    from ..tensor import to_tensor as _tt
    xv = matmul(x, y)
    iv = input._data if isinstance(input, Tensor) else jnp.asarray(
        np.asarray(input))
    return _tt(beta * iv + alpha * (xv._data if isinstance(xv, Tensor)
                                    else jnp.asarray(xv.numpy())))


def mv(x, vec, name=None):
    """Sparse matrix x dense vector -> dense vector."""
    v = _raw(vec)
    if isinstance(x, SparseCsrTensor):
        x = x.to_coo()
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.mv expects a sparse tensor")
    b = x._bcoo
    rows, cols = b.indices[:, 0], b.indices[:, 1]
    import jax
    out = jax.ops.segment_sum(b.data * v[cols], rows,
                              num_segments=b.shape[0])
    return to_tensor(out)


def slice(x, axes, starts, ends, name=None):  # noqa: A001 — paddle name
    """Slice a sparse tensor along `axes` -> sparse (reference
    sparse/manipulation.py slice)."""
    if isinstance(x, SparseCsrTensor):
        return _dense_to_csr(
            np.asarray(slice(x.to_coo(), axes, starts, ends).to_dense()
                       .numpy()))
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse.slice expects a sparse tensor")
    b = x._bcoo
    idx = np.asarray(b.indices)
    vals = b.data
    shape = list(b.shape)
    n_sparse = idx.shape[1]
    keep = np.ones(idx.shape[0], bool)
    new_shape = list(shape)
    offs = {}
    for ax, st, en in zip(axes, starts, ends):
        ax = int(ax) + len(shape) if int(ax) < 0 else int(ax)
        st = int(st) + shape[ax] if int(st) < 0 else int(st)
        en = int(en) + shape[ax] if int(en) < 0 else min(int(en), shape[ax])
        st, en = max(0, st), max(0, en)
        new_shape[ax] = max(0, en - st)
        if ax >= n_sparse:
            raise NotImplementedError(
                "sparse.slice over dense (channel) dims is not supported")
        keep &= (idx[:, ax] >= st) & (idx[:, ax] < en)
        offs[ax] = st
    new_idx = idx[keep].copy()
    for ax, st in offs.items():
        new_idx[:, ax] -= st
    return SparseCooTensor(jsparse.BCOO(
        (vals[np.nonzero(keep)[0]], jnp.asarray(new_idx)),
        shape=tuple(new_shape)))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA over a (densified) sparse matrix (reference
    sparse/math.py pca_lowrank delegates the same way)."""
    from ..ops.linalg import pca_lowrank as _dense_pca
    return _dense_pca(x.to_dense(), q=q, center=center, niter=niter)
