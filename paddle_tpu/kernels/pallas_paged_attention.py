"""Pallas TPU kernel: paged decode attention over a block-paged KV cache.

Reference analog: the reference's decode kernel (`masked_multihead_attention`,
phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu) runs over a
dense per-sequence cache; production TPU serving replaces that with *paged*
KV (Ragged Paged Attention, arxiv 2604.15464): k/v live in fixed-size pages
drawn from a shared pool, and each sequence owns a page table.  Memory is
allocated in O(page_size) quanta — no per-sequence max-length reservation —
and attention compute scales with each sequence's ACTUAL length, not the
static batch max.

Layout contract:
  q:        (B, Hq, D)                 one decode step per sequence
  k_pool:   (num_pages, page_size, Hkv, D)   shared page pool
  v_pool:   (num_pages, page_size, Hkv, D)
  page_table: (B, pages_per_seq) int32 — page_table[b, j] is the pool page
              holding tokens [j*page_size, (j+1)*page_size) of sequence b
  lengths:  (B,) int32 — valid tokens per sequence (the current step's k/v
              already written); slot m of sequence b is live iff m < lengths[b]

Page-table invariants (enforced by the PagedKVCache manager):
  * entries for j < ceil(lengths[b]/page_size) are distinct allocated pages;
  * entries BEYOND the used range must still be VALID pool indices (the
    manager repeats the last allocated page) — the kernel's BlockSpec index
    map reads them for skipped grid steps, and repeating the previous index
    lets the Pallas pipeline skip the re-fetch entirely.

Kernel shape: grid (B, Hkv, pages_per_seq), page loop innermost; the page
table and lengths ride scalar prefetch (pltpu.PrefetchScalarGridSpec) so
BlockSpec index maps can chase page indirections.  GQA runs at Hkv width:
the q block for (b, h) is that kv-head's `rep` query heads, and one
(rep, page_size) score tile feeds an online-softmax accumulator — pages
past lengths[b] are skipped with pl.when, so per-sequence work is
O(actual_len / page_size) pages, not O(pages_per_seq).

`interpret=True` runs the same kernel through the Pallas interpreter
(pattern of pallas_attention.py tests) so CPU tier-1 tests exercise it; the
`paged_attention` wrapper picks interpret mode automatically off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# np scalars, not Python literals (see pallas_attention.py: f64 constants
# break Mosaic under jax_enable_x64)
_NEG_INF = np.float32(-1e30)
_TINY = np.float32(1e-30)
_0 = np.int32(0)

_LANES = 128


def _paged_kernel(lengths_ref, pt_ref, q_ref, k_ref, v_ref, o_ref,
                  m_scr, l_scr, acc_scr, *, scale: float, page_size: int,
                  pages_per_seq: int):
    b = pl.program_id(0)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lengths_ref[b]
    # skip pages entirely past this sequence's context: compute per sequence
    # is ceil(length/page_size) pages, not pages_per_seq
    @pl.when(j * page_size < length)
    def _compute():
        rep = q_ref.shape[2]
        q = q_ref[0, 0]                                   # (rep, D)
        k = k_ref[0, :, 0]                                # (ps, D)
        v = v_ref[0, :, 0]                                # (ps, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (rep, ps) f32
        slot = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (rep, page_size), 1)
        s = jnp.where(slot < length, s, _NEG_INF)
        m_prev = m_scr[...]                               # (rep, 128)
        m_cur = jax.lax.broadcast_in_dim(
            jnp.max(s, axis=-1), m_prev.shape, (0,))
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, :1])                     # (rep, ps)
        alpha = jnp.exp(m_prev - m_new)                   # (rep, 128)
        l_scr[...] = l_scr[...] * alpha + jax.lax.broadcast_in_dim(
            jnp.sum(p, axis=-1), m_prev.shape, (0,))
        acc_scr[...] = acc_scr[...] * alpha[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (rep, D)
        m_scr[...] = m_new

    @pl.when(j == pages_per_seq - 1)
    def _finalize():
        l = jnp.maximum(l_scr[...][:, :1], _TINY)
        o_ref[0, 0] = (acc_scr[...] / l).astype(o_ref.dtype)


def paged_attention_pallas(q, k_pool, v_pool, page_table, lengths,
                           scale=None, interpret=False):
    """Decode attention over paged KV.  q: (B, Hq, D); k_pool/v_pool:
    (P, ps, Hkv, D); page_table: (B, pages_per_seq) i32; lengths: (B,) i32.
    Returns (B, Hq, D) in q.dtype."""
    B, Hq, D = q.shape
    P, ps, Hkv, _ = k_pool.shape
    if Hq % Hkv:
        raise ValueError(f"Hq={Hq} must be a multiple of Hkv={Hkv}")
    rep = Hq // Hkv
    pages_per_seq = page_table.shape[1]
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    qg = q.reshape(B, Hkv, rep, D)
    kernel = functools.partial(
        _paged_kernel, scale=float(scale), page_size=ps,
        pages_per_seq=pages_per_seq)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                 # lengths, page_table
        grid=(B, Hkv, pages_per_seq),
        in_specs=[
            pl.BlockSpec((1, 1, rep, D),
                         lambda b, h, j, lens, pt: (b, h, _0, _0)),
            # page indirection: the block index along the pool's page axis
            # comes from the prefetched page table
            pl.BlockSpec((1, ps, 1, D),
                         lambda b, h, j, lens, pt: (pt[b, j], _0, h, _0)),
            pl.BlockSpec((1, ps, 1, D),
                         lambda b, h, j, lens, pt: (pt[b, j], _0, h, _0)),
        ],
        out_specs=pl.BlockSpec((1, 1, rep, D),
                               lambda b, h, j, lens, pt: (b, h, _0, _0)),
        scratch_shapes=[
            pltpu.VMEM((rep, _LANES), jnp.float32),
            pltpu.VMEM((rep, _LANES), jnp.float32),
            pltpu.VMEM((rep, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, Hkv, rep, D), q.dtype),
        interpret=interpret,
    )(lengths.astype(jnp.int32), page_table.astype(jnp.int32),
      qg, k_pool, v_pool)
    return out.reshape(B, Hq, D)


def paged_attention_reference(q, k_pool, v_pool, page_table, lengths,
                              scale=None):
    """Dense XLA reference: gather the page table into a contiguous cache and
    run masked attention — the oracle for the kernel and the fallback path."""
    B, Hq, D = q.shape
    _, ps, Hkv, _ = k_pool.shape
    rep = Hq // Hkv
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    M = page_table.shape[1] * ps
    ck = k_pool[page_table].reshape(B, M, Hkv, D)
    cv = v_pool[page_table].reshape(B, M, Hkv, D)
    qg = q.reshape(B, Hkv, rep, D).astype(jnp.float32) * scale
    s = jnp.einsum("bhrd,bkhd->bhrk", qg, ck.astype(jnp.float32))
    slot = jax.lax.broadcasted_iota(jnp.int32, (B, M), 1)
    keep = slot < lengths[:, None]                     # (B, M)
    s = jnp.where(keep[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhrk,bkhd->bhrd", p, cv.astype(jnp.float32))
    return o.reshape(B, Hq, D).astype(q.dtype)
