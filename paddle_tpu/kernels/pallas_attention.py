"""Pallas TPU kernels: FlashAttention fused attention, forward AND backward.

Reference analog: phi/kernels/gpu/flash_attn_kernel.cu:324 and
phi/kernels/gpu/flash_attn_grad_kernel.cu (the reference wraps the vendored
third_party/flashattn CUDA library for both directions).  TPU-native version:

* forward — online-softmax tiled kernel; q blocks stay resident in VMEM, k/v
  blocks stream from HBM, the (S,S) score matrix never materializes.  Saves
  the per-row logsumexp (O(S) residual) for the backward.
* backward — two tiled kernels with O(S·D) residuals (q, k, v, o, lse):
  a dq kernel (grid over q blocks, streaming k/v) and a dk/dv kernel (grid
  over k blocks, streaming q/do).  Scores are recomputed per block in the
  transposed (bk, bq) orientation so the saved lse / delta rows broadcast
  along sublanes for free (the splash-attention trick).  Nothing of size
  (S, S) is ever materialized in either direction.

GQA runs at Hkv width end to end: k/v are NEVER expanded with jnp.repeat —
the kernels map query-head h to kv-head h // rep in the BlockSpec index maps,
and the dk/dv kernel accumulates over the rep query heads of each group
directly in its VMEM accumulator.

Layout contract: (B, S, H, D) — the paddle flash_attention layout
(python/paddle/nn/functional/flash_attention.py:125 in the reference).
Pallas path needs S % 128 == 0 and D % 128 == 0.  64 <= D < 128 (GQA
slices, small hidden sizes) zero-pads D to the 128-lane tile and still rides
the tiled kernels — the XLA fallback would materialize the (S,S) scores,
which at S=8k is 8 GB.  Anything else takes the XLA fallback (still
GQA-grouped, no repeat).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# np scalars, not Python literals: under jax_enable_x64 a Python float lowers
# to an f64 constant, which Mosaic cannot truncate (tpu.truncf legalization).
_NEG_INF = np.float32(-1e30)
_TINY = np.float32(1e-30)
# index-map constants must stay i32 under jax_enable_x64 (Mosaic requirement)
_0 = np.int32(0)

_LANES = 128
_SUBLANES = 8  # f32 sublane tile; lse/delta rows are replicated to this


def _lanes(x, width):
    """Broadcast/repeat a (bq, 128) lane-replicated value to (bq, width)."""
    if width == _LANES:
        return x
    return pltpu.repeat(x, width // _LANES, axis=1)


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, bq: int, bk: int, nk: int):
    ik = pl.program_id(2)
    iq = pl.program_id(1)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # skip blocks strictly above the diagonal when causal
    run = (not causal) or (iq * bq + bq - 1 >= ik * bk)

    @pl.when(run)
    def _compute():
        D = q_ref.shape[-1]
        # operands stay in their native dtype (bf16 on the training path):
        # the MXU multiplies bf16 at a multiple of the f32 rate and
        # accumulates f32 via preferred_element_type — converting up front
        # would halve matmul throughput for no accuracy gain
        q = q_ref[0]                       # (bq, D)
        k = k_ref[0]                       # (bk, D)
        v = v_ref[0]                       # (bk, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk) f32
        if causal:
            rows = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_scr[...]                              # (bq, 128) lane-replicated
        m_cur = jax.lax.broadcast_in_dim(
            jnp.max(s, axis=-1), (bq, _LANES), (0,))
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - _lanes(m_new, bk))               # (bq, bk)
        alpha = jnp.exp(m_prev - m_new)                  # (bq, 128)
        l_cur = jax.lax.broadcast_in_dim(
            jnp.sum(p, axis=-1), (bq, _LANES), (0,))
        l_scr[...] = l_scr[...] * alpha + l_cur
        # p rounds to v's dtype for the second dot (the flash standard):
        # bf16 p keeps the MXU at full rate; accumulation stays f32
        acc_scr[...] = acc_scr[...] * _lanes(alpha, D) + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    @pl.when(ik == nk - 1)
    def _finalize():
        D = o_ref.shape[-1]
        l = jnp.maximum(l_scr[...], _TINY)
        o_ref[0] = (acc_scr[...] / _lanes(l, D)).astype(o_ref.dtype)
        lse_ref[0] = m_scr[...] + jnp.log(l)


def _block(n, pref):
    b = min(pref, n)
    while n % b:
        b //= 2
    return max(b, 1)


def _flash_fwd(q, k, v, scale, causal, rep, bq=1024, bk=512):
    """q: (BHq, S, D); k/v: (BHkv, S, D) with BHq == BHkv * rep.

    Returns (o, lse128) where lse128 is (BHq, S, 128) lane-replicated f32.
    Block defaults measured on v5e at S=4096 (bench shapes): 1024x512 beats
    512x512 by ~5% fwd / ~4% bwd; _block() shrinks them for smaller S.
    """
    BH, S, D = q.shape
    bq = _block(S, bq)
    bk = _block(S, bk)
    nq, nk = S // bq, S // bk
    # index-map arithmetic must stay i32 under jax_enable_x64 — a Python int
    # operand promotes to i64, which Mosaic cannot convert (recursion bug)
    _r = np.int32(rep)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk)
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // _r, j, _0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // _r, j, _0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _0)),
            pl.BlockSpec((1, bq, _LANES), lambda b, i, j: (b, i, _0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BH, S, D), q.dtype),
            jax.ShapeDtypeStruct((BH, S, _LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
    )(q, k, v)


# ---------------------------------------------------------------------------
# backward: dq kernel — grid over q blocks, stream k/v
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref, dq_ref, acc_scr,
               *, scale: float, causal: bool, bq: int, bk: int, nk: int):
    ik = pl.program_id(2)
    iq = pl.program_id(1)

    @pl.when(ik == 0)
    def _init():
        acc_scr[...] = jnp.zeros_like(acc_scr)

    run = (not causal) or (iq * bq + bq - 1 >= ik * bk)

    @pl.when(run)
    def _compute():
        # native-dtype operands on every MXU dot (see _fwd_kernel note)
        q = q_ref[0]                        # (bq, D)
        k = k_ref[0]                        # (bk, D)
        v = v_ref[0]                        # (bk, D)
        do = do_ref[0]                      # (bq, D)
        lse = lse_ref[0][:1]                # (1, bq) — broadcasts over sublanes
        delta = dl_ref[0][:1]               # (1, bq)
        # transposed orientation: (bk, bq) so lse/delta rows broadcast free
        st = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bk, bq) f32
        if causal:
            kpos = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 0)
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 1)
            st = jnp.where(qpos >= kpos, st, _NEG_INF)
        pt = jnp.exp(st - lse)                            # (bk, bq) f32
        dpt = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bk, bq) f32
        dst = (pt * (dpt - delta) * scale).astype(k.dtype)  # (bk, bq)
        acc_scr[...] += jax.lax.dot_general(
            dst, k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bq, D)

    @pl.when(ik == nk - 1)
    def _finalize():
        dq_ref[0] = acc_scr[...].astype(dq_ref.dtype)


# ---------------------------------------------------------------------------
# backward: dk/dv kernel — grid over k blocks, stream q/do over the whole
# query-head group (rep heads × nq blocks); accumulates at Hkv width.
# ---------------------------------------------------------------------------


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale: float, causal: bool, bq: int, bk: int,
                nq: int, nt: int):
    jk = pl.program_id(1)
    t = pl.program_id(2)
    iq = jax.lax.rem(t, np.int32(nq))

    @pl.when(t == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    # skip q blocks entirely above the diagonal (they never see this k block)
    run = jnp.logical_or(not causal, iq * bq + bq - 1 >= jk * bk)

    @pl.when(run)
    def _compute():
        # native-dtype operands on every MXU dot (see _fwd_kernel note)
        q = q_ref[0]                        # (bq, D)
        k = k_ref[0]                        # (bk, D)
        v = v_ref[0]                        # (bk, D)
        do = do_ref[0]                      # (bq, D)
        lse = lse_ref[0][:1]                # (1, bq)
        delta = dl_ref[0][:1]               # (1, bq)
        st = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bk, bq) f32
        if causal:
            kpos = jk * bk + jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 0)
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bk, bq), 1)
            st = jnp.where(qpos >= kpos, st, _NEG_INF)
        pt = jnp.exp(st - lse)                            # (bk, bq) f32
        dv_scr[...] += jax.lax.dot_general(
            pt.astype(do.dtype), do, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bk, D)
        dpt = jax.lax.dot_general(
            v, do, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bk, bq) f32
        dst = (pt * (dpt - delta) * scale).astype(q.dtype)  # (bk, bq)
        dk_scr[...] += jax.lax.dot_general(
            dst, q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bk, D)

    @pl.when(t == nt - 1)
    def _finalize():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _flash_bwd(q, k, v, o, lse, do, scale, causal, rep, bq=1024, bk=512):
    """All of q/o/do: (BHq, S, D); k/v: (BHkv, S, D); lse: (BHq, S) f32."""
    BH, S, D = q.shape
    BHkv = k.shape[0]
    bq = _block(S, bq)
    bk = _block(S, bk)
    nq, nk = S // bq, S // bk
    _r, _nq = np.int32(rep), np.int32(nq)  # keep index maps i32 (see _flash_fwd)

    # O(S) per-row residual work in plain XLA (fuses into one pass)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    lse_r = jnp.broadcast_to(lse[:, None, :], (BH, _SUBLANES, S))
    dl_r = jnp.broadcast_to(delta[:, None, :], (BH, _SUBLANES, S))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nk=nk),
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // _r, j, _0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b // _r, j, _0)),
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _0)),
            pl.BlockSpec((1, _SUBLANES, bq), lambda b, i, j: (b, _0, i)),
            pl.BlockSpec((1, _SUBLANES, bq), lambda b, i, j: (b, _0, i)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
    )(q, k, v, do, lse_r, dl_r)

    nt = nq * rep
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          bq=bq, bk=bk, nq=nq, nt=nt),
        grid=(BHkv, nk, nt),
        in_specs=[
            pl.BlockSpec((1, bq, D),
                         lambda b, j, t: (b * _r + t // _nq, t % _nq, _0)),
            pl.BlockSpec((1, bk, D), lambda b, j, t: (b, j, _0)),
            pl.BlockSpec((1, bk, D), lambda b, j, t: (b, j, _0)),
            pl.BlockSpec((1, bq, D),
                         lambda b, j, t: (b * _r + t // _nq, t % _nq, _0)),
            pl.BlockSpec((1, _SUBLANES, bq),
                         lambda b, j, t: (b * _r + t // _nq, _0, t % _nq)),
            pl.BlockSpec((1, _SUBLANES, bq),
                         lambda b, j, t: (b * _r + t // _nq, _0, t % _nq)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, D), lambda b, j, t: (b, j, _0)),
            pl.BlockSpec((1, bk, D), lambda b, j, t: (b, j, _0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((BHkv, S, D), k.dtype),
            jax.ShapeDtypeStruct((BHkv, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bk, D), jnp.float32),
            pltpu.VMEM((bk, D), jnp.float32),
        ],
    )(q, k, v, do, lse_r, dl_r)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# custom-vjp wrapper + XLA fallback
# ---------------------------------------------------------------------------


def _reference(q, k, v, scale, causal):
    """Same-head-count (BH, S, D) reference; kept for kernel tests."""
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _xla_attention(q, k, v, scale, causal):
    """(B, S, H, D) XLA fallback.  GQA stays grouped — dot_general carries the
    `rep` axis as a free lhs dimension, so Hkv-wide k/v are never repeated."""
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    rep = Hq // Hkv
    qg = q.reshape(B, S, Hkv, rep, D).astype(jnp.float32)
    s = jnp.einsum("bqhrd,bkhd->bhrqk", qg, k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        s = jnp.where(mask[None, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhrqk,bkhd->bqhrd", p, v.astype(jnp.float32))
    return o.reshape(B, S, Hq, D).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, scale, causal, rep):
    o, _ = _flash_fwd(q, k, v, scale, causal, rep)
    return o


def _flash_f(q, k, v, scale, causal, rep):
    o, lse128 = _flash_fwd(q, k, v, scale, causal, rep)
    # keep only lane 0 as the O(S) residual
    return o, (q, k, v, o, lse128[:, :, 0])


def _flash_b(scale, causal, rep, res, g):
    q, k, v, o, lse = res
    return _flash_bwd(q, k, v, o, lse, g, scale, causal, rep)


_flash.defvjp(_flash_f, _flash_b)


def flash_attention_pallas(q, k, v, causal=True, scale=None):
    """q: (B, S, Hq, D); k,v: (B, S, Hkv, D).  Returns (B, S, Hq, D)."""
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    if Hq % Hkv:
        raise ValueError(f"Hq={Hq} must be a multiple of Hkv={Hkv}")
    rep = Hq // Hkv
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if D % 128 != 0 and D >= 64 and S % 128 == 0:
        # Sub-tile head dims (64/96 are common: GQA slices, small hidden
        # sizes) ride the flash kernel by zero-padding D to the 128-lane
        # tile: padded q.k columns add 0 to every logit and padded v columns
        # yield all-zero output channels, sliced off below.  The softmax
        # scale is already fixed to 1/sqrt(D_true).  Costs <=2x attention
        # FLOPs but keeps O(S) memory — the XLA fallback materializes the
        # (B,H,S,S) score matrix, which at S=8k is 8 GB and OOMs the chip.
        pad = ((0, 0),) * 3 + ((0, (-D) % 128),)
        out = flash_attention_pallas(
            jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad),
            causal=causal, scale=scale)
        return out[..., :D]
    if D % 128 != 0 or S % 128 != 0:
        # lane-replication layout needs D,S multiples of 128; use the XLA path
        return _xla_attention(q, k, v, float(scale), bool(causal))
    to_bh = lambda x, h: jnp.swapaxes(x, 1, 2).reshape(B * h, S, D)  # noqa: E731
    out = _flash(to_bh(q, Hq), to_bh(k, Hkv), to_bh(v, Hkv),
                 float(scale), bool(causal), rep)
    return jnp.swapaxes(out.reshape(B, Hq, S, D), 1, 2)
