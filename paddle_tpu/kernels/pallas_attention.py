"""Pallas TPU kernel: FlashAttention-style fused attention (fwd) + custom VJP.

Reference analog: phi/kernels/gpu/flash_attn_kernel.cu:324 (wraps the vendored
third_party/flashattn CUDA library).  TPU-native version: an online-softmax
tiled kernel — q blocks stay resident in VMEM, k/v blocks stream from HBM, the
(S,S) score matrix never materializes.  Backward recomputes attention from the
saved (q,k,v) (flash-style residual strategy: O(S·D) residuals, not O(S²));
the recompute runs as plain XLA ops which fuse well on the MXU.

Layout contract: (B, S, H, D) — the paddle flash_attention layout
(python/paddle/nn/functional/flash_attention.py:125 in the reference).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# np scalars, not Python literals: under jax_enable_x64 a Python float lowers
# to an f64 constant, which Mosaic cannot truncate (tpu.truncf legalization).
_NEG_INF = np.float32(-1e30)
_TINY = np.float32(1e-30)
# index-map constants must stay i32 under jax_enable_x64 (Mosaic requirement)
_0 = np.int32(0)


_LANES = 128


def _lanes(x, width):
    """Broadcast/repeat a (bq, 128) lane-replicated value to (bq, width)."""
    if width == _LANES:
        return x
    return pltpu.repeat(x, width // _LANES, axis=1)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, bq: int, bk: int, nk: int):
    ik = pl.program_id(2)
    iq = pl.program_id(1)

    @pl.when(ik == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # skip blocks strictly above the diagonal when causal
    run = (not causal) or (iq * bq + bq - 1 >= ik * bk)

    @pl.when(run)
    def _compute():
        D = q_ref.shape[-1]
        q = q_ref[0].astype(jnp.float32)   # (bq, D)
        k = k_ref[0].astype(jnp.float32)   # (bk, D)
        v = v_ref[0].astype(jnp.float32)   # (bk, D)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale  # (bq, bk)
        if causal:
            rows = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            cols = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_prev = m_scr[...]                              # (bq, 128) lane-replicated
        m_cur = jax.lax.broadcast_in_dim(
            jnp.max(s, axis=-1), (bq, _LANES), (0,))
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - _lanes(m_new, bk))               # (bq, bk)
        alpha = jnp.exp(m_prev - m_new)                  # (bq, 128)
        l_cur = jax.lax.broadcast_in_dim(
            jnp.sum(p, axis=-1), (bq, _LANES), (0,))
        l_scr[...] = l_scr[...] * alpha + l_cur
        acc_scr[...] = acc_scr[...] * _lanes(alpha, D) + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    @pl.when(ik == nk - 1)
    def _finalize():
        D = o_ref.shape[-1]
        l = _lanes(jnp.maximum(l_scr[...], _TINY), D)
        o_ref[0] = (acc_scr[...] / l).astype(o_ref.dtype)


def _block(n, pref):
    b = min(pref, n)
    while n % b:
        b //= 2
    return max(b, 1)


def _flash_fwd(q, k, v, scale, causal, bq=512, bk=512):
    """q,k,v: (BH, S, D) same head count (GQA pre-expanded)."""
    BH, S, D = q.shape
    bq = _block(S, bq)
    bk = _block(S, bk)
    nq, nk = S // bq, S // bk
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk, nk=nk)
    return pl.pallas_call(
        kernel,
        grid=(BH, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, _0)),
            pl.BlockSpec((1, bk, D), lambda b, i, j: (b, j, _0)),
        ],
        out_specs=pl.BlockSpec((1, bq, D), lambda b, i, j: (b, i, _0)),
        out_shape=jax.ShapeDtypeStruct((BH, S, D), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
    )(q, k, v)


def _reference(q, k, v, scale, causal):
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32), k.astype(jnp.float32)) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), dtype=bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash(q, k, v, scale, causal):
    return _flash_fwd(q, k, v, scale, causal)


def _flash_f(q, k, v, scale, causal):
    return _flash_fwd(q, k, v, scale, causal), (q, k, v)


def _flash_b(scale, causal, res, g):
    q, k, v = res
    # recompute-based backward (O(S^2) compute, O(S·D) memory residuals)
    def f(q, k, v):
        return _reference(q, k, v, scale, causal)
    _, vjp = jax.vjp(f, q, k, v)
    return vjp(g)


_flash.defvjp(_flash_f, _flash_b)


def flash_attention_pallas(q, k, v, causal=True, scale=None):
    """q: (B, S, Hq, D); k,v: (B, S, Hkv, D).  Returns (B, S, Hq, D)."""
    B, S, Hq, D = q.shape
    Hkv = k.shape[2]
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    if D % 128 != 0 or S % 128 != 0:
        # lane-replication layout needs D,S multiples of 128; use the XLA path
        qt = jnp.swapaxes(q, 1, 2).reshape(B * Hq, S, D)
        rep = Hq // Hkv
        kt = jnp.swapaxes(jnp.repeat(k, rep, axis=2), 1, 2).reshape(B * Hq, S, D)
        vt = jnp.swapaxes(jnp.repeat(v, rep, axis=2), 1, 2).reshape(B * Hq, S, D)
        out = _reference(qt, kt, vt, float(scale), bool(causal))
        return jnp.swapaxes(out.reshape(B, Hq, S, D), 1, 2).astype(q.dtype)
    if Hkv != Hq:
        rep = Hq // Hkv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    to_bh = lambda x: jnp.swapaxes(x, 1, 2).reshape(B * Hq, S, D)  # noqa: E731
    out = _flash(to_bh(q), to_bh(k), to_bh(v), float(scale), bool(causal))
    return jnp.swapaxes(out.reshape(B, Hq, S, D), 1, 2)
