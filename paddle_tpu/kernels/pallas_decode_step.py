"""Fused decode-step epilogue: lm_head matmul + temperature/top-k/top-p
filtering + categorical sampling in ONE Pallas dispatch.

The decode inner loop's tail used to be three hops: the ragged forward
dispatch returned `(rows, V)` f32 logits, the host pulled them, and a
SECOND device round-trip (or eager op chain) sampled — per token.  This
kernel folds the tail into the forward dispatch itself: the engine's
fused path calls `forward_ragged_sample`, which ends in this kernel, and
the host pulls `(rows,)` int32 token ids.  One dispatch, no per-token
`(rows, V)` host transfer.

Sampling is device-side via the Gumbel-max construction — argmax over
`filtered_logits + gumbel(key)` — which is EXACTLY what
`jax.random.categorical` computes for a given key (same noise shape,
same key), so the fused path is not merely distribution-equal to
`generation.sample_logits`, it is draw-for-draw identical under the same
threaded PRNG key.  Greedy (temperature == 0) is a plain argmax: token-
exact vs the unfused epilogue by construction.  The filtering math is
`generation.filter_logits` — the SAME function the unfused sampler uses,
traced into the kernel body, so fused and unfused can only diverge in
the matmul (f32 on the interpret path: bit-identical).

Gate: `self_check()` runs the kernel against the reference epilogue on
random probes (greedy token-exact always; a chi-square
`equiv.verify_sampled` pass when a sampled config is given) and the
engine refuses to route through the fused path unless it passes —
verify-or-rollback, never silent (`llm_engine` warns when it falls
back).

Cost hooks: `_decode_step_kernel` registers whole-call FLOPs/bytes
formulas so graphlint's cost roll-up ranks the fused dispatch alongside
plain XLA eqns instead of scoring the opaque pallas_call zero.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from ..analysis import cost as _cost

__all__ = ["fused_decode_step_pallas", "decode_step_reference",
           "self_check"]


def _filter_logits(logits, temperature, top_k, top_p):
    # lazy import: models.generation imports kernels at module top
    from ..models import generation

    return generation.filter_logits(logits, temperature, top_k, top_p)


def _make_kernel(temperature: float, top_k: int, top_p: float):
    """Kernel over full blocks: sel (R, E), head (E, V), gumbel (R, V)
    f32 ((1, 1) dummy for greedy — never read), out (R, 1) i32.  The
    sampling knobs are STATIC (engine-lifetime constants), closed over so
    the traced body contains only the live branch."""

    def _decode_step_kernel(sel_ref, head_ref, g_ref, tok_ref):
        logits = jnp.dot(sel_ref[...], head_ref[...],
                         preferred_element_type=jnp.float32)
        if temperature != 0.0:
            logits = _filter_logits(logits, temperature, top_k, top_p)
            # Gumbel-max: -inf stays -inf (masked tokens never win)
            logits = logits + g_ref[...]
        tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        tok_ref[...] = tok[:, None]

    return _decode_step_kernel


def fused_decode_step_pallas(sel, head, key, temperature: float = 0.0,
                             top_k: int = 0, top_p: float = 1.0,
                             interpret: bool = True):
    """sel: (R, E) hidden rows at the out positions; head: (E, V) lm
    head; key: threaded PRNG key (ignored for greedy).  Returns (R,)
    int32 sampled/argmax token ids — the ONLY thing the host needs."""
    R, _E = sel.shape
    V = head.shape[-1]
    head = head.astype(sel.dtype)
    if temperature == 0.0:
        gumbel = jnp.zeros((1, 1), jnp.float32)
    else:
        # same construction jax.random.categorical uses internally, so a
        # caller holding the same key gets the identical draw
        gumbel = jax.random.gumbel(key, (R, V), jnp.float32)
    out = pl.pallas_call(
        _make_kernel(float(temperature), int(top_k), float(top_p)),
        out_shape=jax.ShapeDtypeStruct((R, 1), jnp.int32),
        interpret=interpret,
    )(sel, head, gumbel)
    return out[:, 0]


def decode_step_reference(sel, head, key, temperature: float = 0.0,
                          top_k: int = 0, top_p: float = 1.0):
    """Unfused epilogue — exactly the `forward_ragged` tail followed by
    `generation.sample_logits`: the ground truth the kernel is gated
    against, and the fallback when the kernel cannot lower."""
    from ..models import generation

    logits = (sel @ head.astype(sel.dtype)).astype(jnp.float32)
    return generation.sample_logits(logits, key, temperature, top_k, top_p)


# ---------------------------------------------------------------------------
# verify-or-rollback self-check (memoized per (knobs, backend) per process)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=16)
def self_check(temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, interpret: bool = True,
               seed: int = 0):
    """(ok, reason) for the fused kernel vs the reference epilogue on
    random probes.  Greedy must be TOKEN-EXACT (int outputs, the equiv.py
    bar); a sampled config additionally passes `equiv.verify_sampled`'s
    chi-square gate against `generation.filtered_probs` of the same
    logits.  Cached: the engine calls this at construction, every
    process pays for it once per knob set."""
    from ..analysis import equiv
    from ..models import generation

    R, E, V = 4, 16, 64
    kg = jax.random.PRNGKey(seed)
    k_sel, k_head, k_draw = jax.random.split(kg, 3)
    sel = jax.random.normal(k_sel, (R, E), jnp.float32)
    head = jax.random.normal(k_head, (E, V), jnp.float32)
    try:
        fused = np.asarray(fused_decode_step_pallas(
            sel, head, k_draw, temperature=0.0, interpret=interpret))
        ref = np.asarray(decode_step_reference(sel, head, k_draw,
                                               temperature=0.0))
    except Exception as e:  # noqa: BLE001 — lowering failure = rollback
        return False, f"fused decode kernel failed: {type(e).__name__}: {e}"
    if fused.shape != ref.shape or not (fused == ref).all():
        return False, ("fused decode kernel not token-exact vs reference "
                       "on greedy probes (integer outputs must be exact)")
    if temperature == 0.0:
        return True, ""

    logits = np.asarray((sel @ head).astype(jnp.float32))
    probs = generation.filtered_probs(logits, float(temperature),
                                      int(top_k), float(top_p))[0]

    def draw(k):
        return fused_decode_step_pallas(
            sel[:1], head, k, temperature=temperature, top_k=top_k,
            top_p=top_p, interpret=interpret)[0]

    res = equiv.verify_sampled(draw, probs, n_draws=2000, seed=seed)
    if not res.ok:
        return False, f"fused decode sampling gate failed: {res.reason}"
    return True, ""


# ---------------------------------------------------------------------------
# graphlint cost hooks: invars reach the kernel as (sel, head, gumbel)
# ---------------------------------------------------------------------------


def _decode_flops(eqn) -> float:
    sel, head = eqn.invars[0].aval, eqn.invars[1].aval
    R, E = sel.shape
    V = head.shape[-1]
    # lm_head matmul dominates; filtering/sampling epilogue ~ a few
    # elementwise+sort passes over (R, V)
    return 2.0 * R * E * V + 8.0 * R * V


def _decode_bytes(eqn) -> float:
    total = 0
    for v in list(eqn.invars) + list(eqn.outvars):
        if hasattr(v, "aval") and v.aval.shape is not None:
            total += int(np.prod(v.aval.shape, dtype=np.int64)) \
                * np.dtype(v.aval.dtype).itemsize
    return float(total)


_cost.register_pallas_flops("_decode_step_kernel", _decode_flops)
_cost.register_pallas_bytes("_decode_step_kernel", _decode_bytes)
