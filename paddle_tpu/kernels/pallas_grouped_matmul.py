"""Pallas TPU kernel: ragged grouped matmul (GMM) for dropless MoE.

Reference analog: the reference's MoE expert FFN pads every expert to a
static capacity (`incubate/distributed/models/moe/moe_layer.py` dispatch)
and runs a dense batched matmul over the padded buffers — compute scales
with `num_experts * capacity`, not with the tokens that actually routed.
The production-TPU replacement is a *grouped* matmul over tokens sorted by
destination expert (MegaBlocks, arXiv:2211.15841; the same ragged-kernel
line as `pallas_paged_attention.py`): given

  lhs:         (M, K)   token rows, sorted by group (expert)
  rhs:         (X, K, N) per-group weight matrices
  group_sizes: (X,) i32  rows per group, sum <= M

compute ``out[m] = lhs[m] @ rhs[g(m)]`` where g(m) is the group owning row
m.  Compute scales with the ACTUAL per-expert token counts — no capacity
padding, no token dropping.

Tiling scheme (tile-aligned ragged layout):
  Row tiles must not straddle group boundaries (each grid step multiplies
  one row tile against ONE group's weights), so the caller lays the sorted
  rows out with every group starting at a `tile_m`-aligned row
  (`make_layout` computes the layout; `grouped_matmul` applies it to a
  densely-packed input).  The pad rows between a group's last token and
  the next tile boundary are ZERO, so they contribute nothing to forward
  outputs or weight gradients — at most ``X * (tile_m - 1)`` wasted rows
  (~4% at the MoE bench shape), versus the unbounded capacity padding of
  the einsum/scatter dispatch.

Kernel shape:
  * forward `_gmm_kernel`: grid (row_tiles, n_tiles, k_tiles), k innermost
    accumulating into a VMEM f32 scratch.  A scalar-prefetched
    `tile_gids` table (PrefetchScalarGridSpec, pattern of
    pallas_paged_attention.py's page tables) drives the rhs BlockSpec
    index map: row tile `it` loads `rhs[tile_gids[it]]` — the group
    indirection costs nothing on the data path.  Dead tiles (all-pad)
    skip the MXU work via `pl.when` and emit zeros.
  * wgrad `_tgmm_kernel`: per-group transposed GMM,
    ``dW[g] = lhs_g^T @ dout_g``: grid (k_tiles, n_tiles, row_tiles) with
    row tiles innermost — consecutive row tiles of one group accumulate
    into the same output block, which flushes exactly once when the walk
    crosses a group boundary (tile_gids is non-decreasing, so no output
    block is ever revisited after its flush).
  * dgrad is the forward kernel against transposed weights.

Fallback matrix: TPU -> compiled Pallas; CPU tests -> the SAME kernels
through the Pallas interpreter (`impl="interpret"`, exercised by tier-1;
auto mode on CPU picks dense instead — the interpreter pays Python per
grid step); shapes the tiler can't serve (K/N not tile-divisible on TPU)
or FLAGS_use_fused_kernels=False -> `_gmm_dense` / `_tgmm_dense`, an XLA
one-matmul-per-group masked-sum form.  `gmm`/`grouped_matmul` carry a
`custom_vjp` so every path trains.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_0 = np.int32(0)

# test hook: set to "interpret"/"dense"/"pallas" to override the auto impl
# rule for calls that don't pass `impl` (tier-1 CPU tests run the real
# kernel through the interpreter this way)
_FORCE_IMPL = None


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


# ---------------------------------------------------------------------------
# Tile-aligned ragged layout
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class GmmLayout:
    """Tile-aligned layout for rows grouped by expert.

    `starts[g]` is the (tile_m-aligned, dynamic) first row of group g in
    the padded buffer; `tile_gids[t]` the group owning row tile t (clamped
    to the last group for trailing pad tiles); `tile_live[t]` is 0 for
    tiles holding only pad rows.  `padded_rows`/`tile_m` are static.
    """

    padded_rows: int
    tile_m: int
    starts: jax.Array      # (X,) i32
    tile_gids: jax.Array   # (padded_rows // tile_m,) i32
    tile_live: jax.Array   # (padded_rows // tile_m,) i32


def default_tile_m() -> int:
    # 128 rides the MXU natively; the interpreter pays per-grid-step
    # Python overhead, so CPU tests use small tiles on tiny shapes
    return 128 if _on_tpu() else 8


def make_layout(group_sizes, rows: int, tile_m: int | None = None) -> GmmLayout:
    """Layout for `rows` total rows split into len(group_sizes) groups.

    Static sizes only depend on `rows`/`tile_m`/X, so this traces cleanly:
    padded_rows = (ceil(rows/tile_m) + X) * tile_m covers the worst-case
    per-group round-up.
    """
    if tile_m is None:
        tile_m = default_tile_m()
    X = group_sizes.shape[0]
    gs = group_sizes.astype(jnp.int32)
    num_tiles = -(-rows // tile_m) + X
    padded_rows = num_tiles * tile_m
    padded = -(-gs // tile_m) * tile_m                       # per-group size
    ends_pad = jnp.cumsum(padded)
    starts = ends_pad - padded                               # (X,) aligned
    tile_start = jnp.arange(num_tiles, dtype=jnp.int32) * tile_m
    gid_raw = jnp.sum(tile_start[:, None] >= ends_pad[None, :],
                      axis=1).astype(jnp.int32)              # in [0, X]
    gid = jnp.minimum(gid_raw, X - 1)
    live = ((gid_raw < X)
            & (tile_start < starts[gid] + gs[gid])).astype(jnp.int32)
    return GmmLayout(padded_rows=padded_rows, tile_m=tile_m,
                     starts=starts, tile_gids=gid, tile_live=live)


# ---------------------------------------------------------------------------
# Pallas kernels
# ---------------------------------------------------------------------------


def _gmm_kernel(gids_ref, live_ref, x_ref, w_ref, o_ref, acc_ref, *,
                k_tiles: int):
    it = pl.program_id(0)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(live_ref[it] == 1)
    def _compute():
        acc_ref[...] += jax.lax.dot_general(
            x_ref[...], w_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kk == k_tiles - 1)
    def _store():
        o_ref[...] = acc_ref[...].astype(o_ref.dtype)


def _gmm_pallas(x, w, layout: GmmLayout, tk: int, tn: int, interpret: bool):
    """x: (Mp, K) tile-aligned; w: (X, K, N) -> (Mp, N) in x.dtype."""
    Mp, K = x.shape
    X, _, N = w.shape
    tm = layout.tile_m
    grid = (Mp // tm, N // tn, K // tk)
    kernel = functools.partial(_gmm_kernel, k_tiles=K // tk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,                     # tile_gids, tile_live
        grid=grid,
        in_specs=[
            pl.BlockSpec((tm, tk), lambda it, jn, kk, g, l: (it, kk)),
            # group indirection: row tile it reads rhs[tile_gids[it]]
            pl.BlockSpec((1, tk, tn),
                         lambda it, jn, kk, g, l: (g[it], kk, jn)),
        ],
        out_specs=pl.BlockSpec((tm, tn), lambda it, jn, kk, g, l: (it, jn)),
        scratch_shapes=[pltpu.VMEM((tm, tn), jnp.float32)],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((Mp, N), x.dtype),
        interpret=interpret,
    )(layout.tile_gids, layout.tile_live, x, w)


def _tgmm_kernel(gids_ref, live_ref, x_ref, g_ref, o_ref, acc_ref, *,
                 m_tiles: int):
    im = pl.program_id(2)
    gid = gids_ref[im]
    first = jnp.logical_or(im == 0, gids_ref[jnp.maximum(im - 1, 0)] != gid)
    last = jnp.logical_or(im == m_tiles - 1,
                          gids_ref[jnp.minimum(im + 1, m_tiles - 1)] != gid)

    @pl.when(first)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(live_ref[im] == 1)
    def _compute():
        # lhs_tile^T @ grad_tile: contract the row (tile_m) dimension
        acc_ref[...] += jax.lax.dot_general(
            x_ref[...], g_ref[...], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(last)
    def _store():
        o_ref[0] = acc_ref[...].astype(o_ref.dtype)


def _tgmm_pallas(x, g, num_groups: int, layout: GmmLayout, tk: int, tn: int,
                 interpret: bool):
    """dW[g] = sum over group-g rows of x[m]^T g[m].  x: (Mp, K) tile-
    aligned, g: (Mp, N) -> (X, K, N) f32."""
    Mp, K = x.shape
    _, N = g.shape
    tm = layout.tile_m
    m_tiles = Mp // tm
    kernel = functools.partial(_tgmm_kernel, m_tiles=m_tiles)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(K // tk, N // tn, m_tiles),          # row tiles innermost
        in_specs=[
            pl.BlockSpec((tm, tk), lambda ik, jn, im, gi, l: (im, ik)),
            pl.BlockSpec((tm, tn), lambda ik, jn, im, gi, l: (im, jn)),
        ],
        out_specs=pl.BlockSpec((1, tk, tn),
                               lambda ik, jn, im, gi, l: (gi[im], ik, jn)),
        scratch_shapes=[pltpu.VMEM((tk, tn), jnp.float32)],
    )
    return pl.pallas_call(
        kernel, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((num_groups, K, N), jnp.float32),
        interpret=interpret,
    )(layout.tile_gids, layout.tile_live, x, g)


# ---------------------------------------------------------------------------
# Dense XLA fallback (one masked matmul per group)
# ---------------------------------------------------------------------------


def _row_gids(layout: GmmLayout):
    tm = layout.tile_m
    gid = jnp.repeat(layout.tile_gids, tm)
    live = jnp.repeat(layout.tile_live, tm)
    # rows past a live tile's real tokens are zero in x, so row-level
    # liveness beyond the tile level is unnecessary for the fallback
    return gid, live


def _gmm_dense(x, w, layout: GmmLayout):
    gid, live = _row_gids(layout)
    out = jnp.zeros((x.shape[0], w.shape[2]), jnp.float32)
    for g in range(w.shape[0]):
        sel = ((gid == g) & (live == 1))[:, None]
        out = out + jnp.where(
            sel, jnp.einsum("mk,kn->mn", x, w[g],
                            preferred_element_type=jnp.float32), 0.0)
    return out.astype(x.dtype)


def _tgmm_dense(x, g, num_groups: int, layout: GmmLayout):
    gid, live = _row_gids(layout)
    outs = []
    for e in range(num_groups):
        sel = ((gid == e) & (live == 1))[:, None]
        outs.append(jnp.einsum("mk,mn->kn", jnp.where(sel, x, 0.0), g,
                               preferred_element_type=jnp.float32))
    return jnp.stack(outs)


# ---------------------------------------------------------------------------
# Differentiable entry points
# ---------------------------------------------------------------------------


def _pick_tiles(K: int, N: int):
    """(tk, tn) for the compiled TPU path; None = not tile-servable."""
    def pick(d):
        for t in (512, 256, 128):
            if d % t == 0:
                return t
        return None
    return pick(K), pick(N)


def _resolve_impl(impl, K: int, N: int):
    """-> (impl, tk, tn).  Auto rule: compiled Pallas on TPU; dense XLA on
    CPU (the interpreter pays Python per grid step — tests request
    impl="interpret" explicitly to exercise the real kernel logic), and
    dense whenever the tiler can't serve the shape or fused kernels are
    flagged off."""
    if impl is None:
        impl = _FORCE_IMPL
    if impl is None:
        from .. import framework
        if not framework.get_state().flags.get("FLAGS_use_fused_kernels", True):
            impl = "dense"
        elif _on_tpu():
            impl = "pallas"
        else:
            impl = "dense"
    if impl in ("pallas", "interpret"):
        if impl == "pallas":
            tk, tn = _pick_tiles(K, N)
        else:  # interpreter has no lane/sublane constraints: tiny tiles ok
            tk = K if K <= 512 else _pick_tiles(K, N)[0]
            tn = N if N <= 512 else _pick_tiles(K, N)[1]
        if tk is None or tn is None:
            return "dense", 0, 0
        return impl, tk, tn
    return "dense", 0, 0


def _gmm_fwd_impl(x, w, layout: GmmLayout, impl):
    impl, tk, tn = _resolve_impl(impl, w.shape[1], w.shape[2])
    if impl == "dense":
        return _gmm_dense(x, w, layout)
    return _gmm_pallas(x, w, layout, tk, tn, interpret=(impl == "interpret"))


def _tgmm_impl(x, g, num_groups: int, layout: GmmLayout, impl):
    impl, tk, tn = _resolve_impl(impl, x.shape[1], g.shape[1])
    if impl == "dense":
        return _tgmm_dense(x, g, num_groups, layout)
    return _tgmm_pallas(x, g, num_groups, layout, tk, tn,
                        interpret=(impl == "interpret"))


def _int_zero(a):
    return np.zeros(a.shape, jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def gmm(x, w, group_sizes, padded_rows: int, tile_m: int, impl=None):
    """Tile-aligned grouped matmul, differentiable on every impl path.

    x: (padded_rows, K) rows laid out by `make_layout` (pad rows ZERO);
    w: (X, K, N); group_sizes: (X,) i32.  Returns (padded_rows, N) in
    x.dtype; pad rows of the output are zero.
    """
    layout = make_layout(group_sizes, _layout_rows(padded_rows, tile_m,
                                                   group_sizes.shape[0]),
                         tile_m)
    return _gmm_fwd_impl(x, w, layout, impl)


def _layout_rows(padded_rows: int, tile_m: int, num_groups: int) -> int:
    # invert make_layout's padded_rows formula so gmm can rebuild the
    # layout from static ints (custom_vjp residuals stay small)
    return (padded_rows // tile_m - num_groups) * tile_m


def _gmm_fwd(x, w, group_sizes, padded_rows, tile_m, impl):
    return gmm(x, w, group_sizes, padded_rows, tile_m, impl), \
        (x, w, group_sizes)


def _gmm_bwd(padded_rows, tile_m, impl, res, dout):
    x, w, group_sizes = res
    X = w.shape[0]
    layout = make_layout(group_sizes, _layout_rows(padded_rows, tile_m, X),
                         tile_m)
    # dgrad: a GMM against transposed weights
    dx = _gmm_fwd_impl(dout.astype(x.dtype),
                       jnp.swapaxes(w, 1, 2), layout, impl)
    # wgrad: per-group transposed GMM; empty groups own no rows -> zero
    dw = _tgmm_impl(x, dout.astype(x.dtype), X, layout, impl)
    dw = jnp.where(group_sizes[:, None, None] > 0, dw, 0.0).astype(w.dtype)
    return dx, dw, _int_zero(group_sizes)


gmm.defvjp(_gmm_fwd, _gmm_bwd)


def grouped_matmul(lhs, rhs, group_sizes, tile_m: int | None = None,
                   impl=None):
    """Dense-packed grouped matmul: ``out[m] = lhs[m] @ rhs[g(m)]``.

    lhs: (M, K) rows sorted by group, group g occupying rows
    [offsets[g], offsets[g+1]); rhs: (X, K, N); group_sizes: (X,) i32 with
    sum == M.  Returns (M, N).  Internally scatters into the tile-aligned
    layout, runs the `gmm` kernel, gathers back — differentiable end to
    end (scatter/gather are linear; `gmm` carries the custom_vjp).
    """
    M, K = lhs.shape
    if tile_m is None:
        tile_m = default_tile_m()
    gs = group_sizes.astype(jnp.int32)
    layout = make_layout(gs, M, tile_m)
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32), jnp.cumsum(gs)])
    row = jnp.arange(M, dtype=jnp.int32)
    g_row = jnp.sum(row[:, None] >= offs[None, 1:], axis=1).astype(jnp.int32)
    dest = layout.starts[g_row] + (row - offs[g_row])
    x_pad = jnp.zeros((layout.padded_rows, K), lhs.dtype).at[dest].set(
        lhs, unique_indices=True)
    out_pad = gmm(x_pad, rhs, gs, layout.padded_rows, tile_m, impl)
    return out_pad[dest]


def grouped_matmul_reference(lhs, rhs, group_sizes):
    """Dense oracle on the packed layout (for tests and parity checks)."""
    M = lhs.shape[0]
    offs = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(group_sizes.astype(jnp.int32))])
    row = jnp.arange(M, dtype=jnp.int32)
    g_row = jnp.sum(row[:, None] >= offs[None, 1:], axis=1)
    out = jnp.zeros((M, rhs.shape[2]), jnp.float32)
    for g in range(rhs.shape[0]):
        out = out + jnp.where(
            (g_row == g)[:, None],
            jnp.einsum("mk,kn->mn", lhs, rhs[g],
                       preferred_element_type=jnp.float32), 0.0)
    return out.astype(lhs.dtype)
