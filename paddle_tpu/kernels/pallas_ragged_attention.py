"""Pallas TPU kernel: unified ragged prefill+decode attention over paged KV.

The serving step used to be TWO dispatches: a bucketed static-shape flash
prefill (one compiled executable per menu bucket, policed by the
RECOMPILE_BUCKET_MISS lint) and the separate paged decode kernel.  The
Ragged Paged Attention paper (arxiv 2604.15464) shows the TPU-native fix:
ONE kernel over the paged pools where each sequence contributes a query
span of *arbitrary* length — 1 token for a decoding sequence, a
chunk-size span for a prefilling one, the whole context for a resume.
A mixed batch is then a single dispatch with a single compiled shape, so
prefill interleaves with decode and the whole prefill-bucket recompile
class disappears.

Layout contract (the RAGGED batch):
  q:         (T, Hq, D)  — T query rows, laid out as consecutive per-seq
             spans, each span starting on a `block_q` row boundary (the
             builder pads the tail of every span's last block).
  k_pool:    (num_pages, page_size, Hkv, D)   shared page pool
  v_pool:    (num_pages, page_size, Hkv, D)
  span_pt:   (S, pages_per_seq) int32 — page table row per SPAN; entry j
             is the pool page holding context tokens
             [j*page_size, (j+1)*page_size) of that span's sequence.
  block_seq: (T // block_q,) int32 — which span each row-block belongs to
  block_qpos:(T // block_q,) int32 — the block's first row's position
             WITHIN its span (0, block_q, 2*block_q, ... per span)
  span_len:  (S,) int32 — valid query rows in the span (0 = padding span)
  ctx_len:   (S,) int32 — the sequence's TOTAL context length once this
             span's k/v are in the pool (so the span's query row i sits
             at absolute position ctx_len - span_len + i)

Causality: query row i of span s attends to context slots
j <= ctx_len[s] - span_len[s] + i — for span_len == 1 that is exactly the
old decode kernel's `slot < lengths[b]` rule, and for a prefill chunk it
is causal attention against everything already cached plus the chunk's
own earlier rows (their k/v are scattered into the pool before the kernel
runs).

Kernel shape: grid (num_row_blocks, Hkv, pages_per_seq), page loop
innermost; the block/span metadata and the span page tables ride scalar
prefetch (pltpu.PrefetchScalarGridSpec) so BlockSpec index maps can chase
the page indirections.  GQA runs at Hkv width: the q block for (b, h) is
(block_q, rep, D) flattened to (block_q*rep, D) rows, and one
(block_q*rep, page_size) score tile feeds an online-softmax accumulator.
Pages past the block's causal horizon are skipped with pl.when, so
per-block work is O(needed context / page_size) pages, not
O(pages_per_seq); padding spans (span_len == 0) skip every page.

`interpret=True` runs the same kernel through the Pallas interpreter so
CPU tier-1 tests exercise the real grid/index-map logic; the
`kernels.ragged_attention` wrapper picks interpret mode automatically
off-TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# np scalars, not Python literals (f64 constants break Mosaic under
# jax_enable_x64 — see pallas_attention.py)
_NEG_INF = np.float32(-1e30)
_TINY = np.float32(1e-30)
_0 = np.int32(0)

_LANES = 128


def _ragged_kernel(bseq_ref, bqpos_ref, slen_ref, clen_ref, pt_ref,
                   q_ref, k_ref, v_ref, o_ref, m_scr, l_scr, acc_scr, *,
                   scale: float, page_size: int, pages_per_seq: int,
                   block_q: int):
    b = pl.program_id(0)
    j = pl.program_id(2)

    s = bseq_ref[b]
    q0 = bqpos_ref[b]           # block's first row position within its span
    sl = slen_ref[s]
    cl = clen_ref[s]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    # causal horizon of this block's LAST live row: context slots
    # < cl - sl + min(q0 + block_q, sl) are the only ones any row needs;
    # pages wholly past it are skipped (padding spans have cl == sl == 0,
    # so they skip every page)
    horizon = cl - sl + jnp.minimum(q0 + block_q, sl)

    @pl.when(j * page_size < horizon)
    def _compute():
        rep = q_ref.shape[2]
        rows = block_q * rep
        q = q_ref[:, 0].reshape(rows, q_ref.shape[3])     # (bq*rep, D)
        k = k_ref[0, :, 0]                                # (ps, D)
        v = v_ref[0, :, 0]                                # (ps, D)
        sc = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bq*rep, ps)
        # row r of the tile is query row r // rep of the block; its span
        # position is q0 + r // rep, its absolute position cl - sl + that
        qpos = q0 + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page_size), 0) // rep
        slot = j * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (rows, page_size), 1)
        keep = (qpos < sl) & (slot <= cl - sl + qpos)
        sc = jnp.where(keep, sc, _NEG_INF)
        m_prev = m_scr[...]                               # (bq*rep, 128)
        m_cur = jax.lax.broadcast_in_dim(
            jnp.max(sc, axis=-1), m_prev.shape, (0,))
        m_new = jnp.maximum(m_prev, m_cur)
        # zero masked entries EXPLICITLY: a fully-dead row (span padding)
        # has sc == m_new == -inf, where exp(sc - m_new) would be 1
        p = jnp.where(keep, jnp.exp(sc - m_new[:, :1]), 0.0)  # (bq*rep, ps)
        alpha = jnp.exp(m_prev - m_new)                   # (bq*rep, 128)
        l_scr[...] = l_scr[...] * alpha + jax.lax.broadcast_in_dim(
            jnp.sum(p, axis=-1), m_prev.shape, (0,))
        acc_scr[...] = acc_scr[...] * alpha[:, :1] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bq*rep, D)
        m_scr[...] = m_new

    @pl.when(j == pages_per_seq - 1)
    def _finalize():
        rep = q_ref.shape[2]
        # dead rows (span padding) have l == 0 -> output 0, never read
        l = jnp.maximum(l_scr[...][:, :1], _TINY)
        o_ref[:, 0] = (acc_scr[...] / l).astype(o_ref.dtype).reshape(
            block_q, rep, o_ref.shape[3])


def ragged_attention_pallas(q, k_pool, v_pool, span_pt, block_seq,
                            block_qpos, span_len, ctx_len, scale=None,
                            interpret=False):
    """Unified ragged prefill+decode attention.  q: (T, Hq, D) span-packed
    query rows; k_pool/v_pool: (P, ps, Hkv, D); span_pt: (S, pages_per_seq)
    i32; block_seq/block_qpos: (T // block_q,) i32; span_len/ctx_len: (S,)
    i32.  Returns (T, Hq, D) in q.dtype (padding rows are zero)."""
    T, Hq, D = q.shape
    P, ps, Hkv, _ = k_pool.shape
    if Hq % Hkv:
        raise ValueError(f"Hq={Hq} must be a multiple of Hkv={Hkv}")
    rep = Hq // Hkv
    nb = block_seq.shape[0]
    if T % nb:
        raise ValueError(f"T={T} must be a multiple of num_blocks={nb}")
    block_q = T // nb
    pages_per_seq = span_pt.shape[1]
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    qg = q.reshape(T, Hkv, rep, D)
    kernel = functools.partial(
        _ragged_kernel, scale=float(scale), page_size=ps,
        pages_per_seq=pages_per_seq, block_q=block_q)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,     # block_seq, block_qpos, span_len,
        #                            ctx_len, span_pt
        grid=(nb, Hkv, pages_per_seq),
        in_specs=[
            # axis-0 block index b selects query rows
            # [b*block_q, (b+1)*block_q) — the b-th row block
            pl.BlockSpec((block_q, 1, rep, D),
                         lambda b, h, j, bs, bp, sl, cl, pt:
                         (b, h, _0, _0)),
            # page indirection: the block index along the pool's page axis
            # comes from the prefetched per-span page table
            pl.BlockSpec((1, ps, 1, D),
                         lambda b, h, j, bs, bp, sl, cl, pt:
                         (pt[bs[b], j], _0, h, _0)),
            pl.BlockSpec((1, ps, 1, D),
                         lambda b, h, j, bs, bp, sl, cl, pt:
                         (pt[bs[b], j], _0, h, _0)),
        ],
        out_specs=pl.BlockSpec((block_q, 1, rep, D),
                               lambda b, h, j, bs, bp, sl, cl, pt:
                               (b, h, _0, _0)),
        scratch_shapes=[
            pltpu.VMEM((block_q * rep, _LANES), jnp.float32),
            pltpu.VMEM((block_q * rep, _LANES), jnp.float32),
            pltpu.VMEM((block_q * rep, D), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((T, Hkv, rep, D), q.dtype),
        interpret=interpret,
    )(block_seq.astype(jnp.int32), block_qpos.astype(jnp.int32),
      span_len.astype(jnp.int32), ctx_len.astype(jnp.int32),
      span_pt.astype(jnp.int32), qg, k_pool, v_pool)
    return out.reshape(T, Hq, D)


def ragged_attention_reference(q, k_pool, v_pool, span_pt, block_seq,
                               block_qpos, span_len, ctx_len, scale=None):
    """Dense XLA reference: gather each span's page table into a contiguous
    cache, expand per query row, run masked attention — the oracle for the
    kernel and the fallback path.  Padding rows return zeros."""
    T, Hq, D = q.shape
    _, ps, Hkv, _ = k_pool.shape
    rep = Hq // Hkv
    nb = block_seq.shape[0]
    bq = T // nb
    if scale is None:
        scale = 1.0 / (D ** 0.5)
    S, pps = span_pt.shape
    M = pps * ps
    block_seq = block_seq.astype(jnp.int32)
    block_qpos = block_qpos.astype(jnp.int32)
    row_seq = jnp.repeat(block_seq, bq)                       # (T,)
    row_qpos = (jnp.repeat(block_qpos, bq)
                + jnp.arange(T, dtype=jnp.int32) % bq)        # (T,)
    ck = k_pool[span_pt].reshape(S, M, Hkv, D)
    cv = v_pool[span_pt].reshape(S, M, Hkv, D)
    ckr = ck[row_seq]                                         # (T, M, Hkv, D)
    cvr = cv[row_seq]
    qg = q.reshape(T, Hkv, rep, D).astype(jnp.float32) * scale
    s = jnp.einsum("thrd,tmhd->thrm", qg, ckr.astype(jnp.float32))
    sl = span_len.astype(jnp.int32)[row_seq]                  # (T,)
    cl = ctx_len.astype(jnp.int32)[row_seq]
    slot = jax.lax.broadcasted_iota(jnp.int32, (T, M), 1)
    keep = (row_qpos < sl)[:, None] & (slot <= (cl - sl + row_qpos)[:, None])
    s = jnp.where(keep[:, None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    # rows with no live slot (padding) would softmax to uniform: zero them
    live = jnp.any(keep, axis=-1)[:, None, None, None]
    o = jnp.einsum("thrm,tmhd->thrd", p, cvr.astype(jnp.float32))
    o = jnp.where(live, o, 0.0)
    return o.reshape(T, Hq, D).astype(q.dtype)
