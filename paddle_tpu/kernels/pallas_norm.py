"""Pallas TPU kernel: fused RMSNorm (+ scale) with custom VJP.

Reference analog: phi/kernels/fusion/gpu/fused_layernorm_kernel.cu /
fused_rms_norm — a single HBM round-trip for normalize+scale instead of the
mean/rsqrt/mul chain.  Layout: rows blocked over the grid, feature dim kept
whole in VMEM (lane-dim multiple of 128 enforced by the dispatcher).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_BLOCK_ROWS = 256
# index-map constants must stay i32 under jax_enable_x64 (Mosaic requirement)
_0 = np.int32(0)


def _fwd_kernel(x_ref, w_ref, o_ref, eps: float):
    x = x_ref[...].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    o_ref[...] = (x * inv * w_ref[0].astype(jnp.float32)).astype(o_ref.dtype)


def _rows_grid(n_rows: int):
    block = min(_BLOCK_ROWS, n_rows)
    while n_rows % block:
        block //= 2
    return max(block, 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def rms_norm_pallas(x, weight, epsilon: float = 1e-6):
    return _fwd(x, weight, epsilon)[0]


def _fwd(x, weight, epsilon):
    shape = x.shape
    E = shape[-1]
    x2 = x.reshape(-1, E)
    R = x2.shape[0]
    br = _rows_grid(R)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, eps=epsilon),
        grid=(R // br,),
        in_specs=[
            pl.BlockSpec((br, E), lambda i: (i, _0)),
            pl.BlockSpec((1, E), lambda i: (_0, _0)),
        ],
        out_specs=pl.BlockSpec((br, E), lambda i: (i, _0)),
        out_shape=jax.ShapeDtypeStruct((R, E), x.dtype),
    )(x2, weight.reshape(1, E))
    return out.reshape(shape), (x, weight)


def _bwd(epsilon, res, g):
    x, weight = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    wf = weight.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + epsilon)
    xhat = xf * inv
    dw = jnp.sum(gf * xhat, axis=tuple(range(x.ndim - 1))).astype(weight.dtype)
    gw = gf * wf
    dx = inv * gw - xhat * inv * jnp.mean(gw * xhat, axis=-1, keepdims=True)
    return dx.astype(x.dtype), dw


rms_norm_pallas.defvjp(lambda x, w, eps=1e-6: _fwd(x, w, eps), _bwd)


# ---------------------------------------------------------------------------
# fused adaLN modulate: LayerNorm (non-affine) + x*(1+scale)+shift in ONE
# HBM round trip — the DiT block's per-image conditioning
# (models/dit.py _modulate; reference analog fused_layernorm with
# residual/bias fusions, phi/kernels/fusion/fused_layernorm_kernel.cu)
# ---------------------------------------------------------------------------


def _adaln_kernel(x_ref, sh_ref, sc_ref, o_ref, eps: float):
    x = x_ref[...].astype(jnp.float32)                  # (1, bn, E)
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
    xn = (x - mu) * jax.lax.rsqrt(var + eps)
    sh = sh_ref[...].astype(jnp.float32)                # (1, 1, E)
    sc = sc_ref[...].astype(jnp.float32)
    o_ref[...] = (xn * (1.0 + sc) + sh).astype(o_ref.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def adaln_modulate_pallas(x, shift, scale, epsilon: float = 1e-6):
    """x (B, N, E) any float dtype; shift/scale (B, E).  Output in x.dtype:
    LN(x) * (1 + scale) + shift with f32 statistics."""
    return _adaln_fwd(x, shift, scale, epsilon)[0]


def _adaln_fwd(x, shift, scale, epsilon):
    B, N, E = x.shape
    bn = _rows_grid(N)
    out = pl.pallas_call(
        functools.partial(_adaln_kernel, eps=epsilon),
        grid=(B, N // bn),
        in_specs=[
            pl.BlockSpec((1, bn, E), lambda b, n: (b, n, _0)),
            pl.BlockSpec((1, 1, E), lambda b, n: (b, _0, _0)),
            pl.BlockSpec((1, 1, E), lambda b, n: (b, _0, _0)),
        ],
        out_specs=pl.BlockSpec((1, bn, E), lambda b, n: (b, n, _0)),
        out_shape=jax.ShapeDtypeStruct((B, N, E), x.dtype),
    )(x, shift.reshape(B, 1, E), scale.reshape(B, 1, E))
    return out, (x, shift, scale)


def _adaln_bwd(epsilon, res, g):
    x, shift, scale = res
    xf = x.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + epsilon)
    xn = (xf - mu) * inv
    dsh = jnp.sum(gf, axis=1).astype(shift.dtype)
    dsc = jnp.sum(gf * xn, axis=1).astype(scale.dtype)
    gl = gf * (1.0 + scale.astype(jnp.float32)[:, None, :])
    dx = inv * (gl - jnp.mean(gl, axis=-1, keepdims=True)
                - xn * jnp.mean(gl * xn, axis=-1, keepdims=True))
    return dx.astype(x.dtype), dsh, dsc


adaln_modulate_pallas.defvjp(
    lambda x, sh, sc, eps=1e-6: _adaln_fwd(x, sh, sc, eps), _adaln_bwd)
