"""Generated fused-elementwise-chain kernels for the rewrite tier.

`analysis/rewrite.py`'s fusion pass matches FUSION_BREAK findings back
to jaxpr eqn spans and hands this module the span as a pure closure
(`chain_fn(*same_shape_arrays) -> array`).  We emit it as ONE call:

  * TPU, tile-aligned:   a generated Pallas kernel — rows blocked over
                         the grid, whole chain evaluated in VMEM, one
                         HBM read per input + one write for the output
                         (the fusion XLA declined, now guaranteed);
  * elsewhere/unaligned: the same pallas_call through the interpret
                         path — identical eqn shape, so the rewritten
                         jaxpr looks the same on CPU tests, the cost
                         formula applies, and the call stays OPAQUE to
                         the jaxpr checkers (a `mode="jit"` closure is
                         available but its pjit eqn re-enters the
                         donation checker's field of view).

The kernel name carries the chain length and a caller-supplied SITE tag
(``_fused_chain<N>_s<site>_kernel``) so the registered cost formula
stays truthful — N flops per output element — and two equal-length
chains fused in ONE target never alias: without the site tag their
kernels are name-identical, so per-kernel cost attribution and stepprof
shape-class keys silently merge.  Bytes fall out of the generic
operand+result rule, which for a fused elementwise call IS the real HBM
traffic.

Differentiation: `jax.custom_vjp` around the pallas path — forward runs
the kernel, backward runs `jax.vjp` of the pure chain closure (exact,
XLA-fused), so rewritten models keep training.
"""

from __future__ import annotations

import functools
import re

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

from . import _on_tpu
from ..analysis import cost as _cost

__all__ = ["fused_elementwise_chain"]

_BLOCK_ROWS = 256
_0 = np.int32(0)        # index-map constants stay i32 under x64 (Mosaic)


def _rows_block(n_rows: int) -> int:
    block = min(_BLOCK_ROWS, max(n_rows, 1))
    while n_rows % block:
        block //= 2
    return max(block, 1)


def _chain_name(n_ops: int, site: str) -> str:
    # chain length FIRST (the `fused_chain(\d+)` cost key parses it),
    # site tag second; empty site keeps the historical name
    return f"fused_chain{n_ops}" + (f"_s{site}" if site else "")


def _make_kernel(chain_fn, n_inputs: int, n_ops: int, site: str = ""):
    def kernel(*refs):
        ins, o_ref = refs[:n_inputs], refs[n_inputs]
        o_ref[...] = chain_fn(*(r[...] for r in ins))

    kernel.__name__ = f"_{_chain_name(n_ops, site)}_kernel"
    return kernel


def _pallas_chain(chain_fn, n_ops: int, interpret: bool, site: str = ""):
    def call(*xs):
        shape, dtype = xs[0].shape, xs[0].dtype
        last = shape[-1] if len(shape) else 1
        flat = [x.reshape(-1, last) for x in xs]
        rows = flat[0].shape[0]
        br = _rows_block(rows)
        kernel = _make_kernel(chain_fn, len(xs), n_ops, site)
        out = pl.pallas_call(
            kernel,
            grid=(rows // br,),
            in_specs=[pl.BlockSpec((br, last), lambda i: (i, _0))
                      for _ in xs],
            out_specs=pl.BlockSpec((br, last), lambda i: (i, _0)),
            out_shape=jax.ShapeDtypeStruct((rows, last), dtype),
            interpret=interpret,
        )(*flat)
        return out.reshape(shape)

    return call


def fused_elementwise_chain(chain_fn, n_ops: int, mode: str = "auto",
                            site: str = ""):
    """One fused call for an elementwise chain.

    chain_fn: pure closure over same-shape/same-dtype arrays returning
    one array of that shape.  n_ops: eqns in the chain (cost formula).
    mode: "auto"/"pallas" (a pallas_call everywhere — compiled on TPU,
    interpret elsewhere: opaque to the checkers, cost formula attached),
    or "jit" (a named jitted closure; NOTE the resulting pjit eqn is
    visible to the donation checker, so the rewrite engine's re-lint
    gate may reject it when the chain input aval-matches the output).
    site: short stable tag of the fusion SITE (the rewrite engine hashes
    the eqn path) baked into the kernel name, so equal-length chains in
    one target stay distinguishable to cost/stepprof attribution.
    """
    if mode not in ("auto", "pallas", "jit"):
        raise ValueError(f"fused chain mode must be auto/pallas/jit, "
                         f"got {mode!r}")
    on_tpu = _on_tpu()
    if mode == "auto":
        mode = "pallas"
    if mode == "jit":
        chain_fn.__name__ = _chain_name(n_ops, site)
        return jax.jit(chain_fn)

    pallas_fwd = _pallas_chain(chain_fn, n_ops, interpret=not on_tpu,
                               site=site)

    @jax.custom_vjp
    def fused(*xs):
        return pallas_fwd(*xs)

    def fwd(*xs):
        return pallas_fwd(*xs), xs

    def bwd(xs, g):
        _out, pullback = jax.vjp(chain_fn, *xs)
        return pullback(g)

    fused.defvjp(fwd, bwd)

    def call(*xs):
        if on_tpu and (xs[0].ndim < 2 or xs[0].shape[-1] % 128):
            # unaligned lane dim: the Mosaic path would pad; fall back
            # to the jitted closure rather than lower something slower
            f = jax.jit(chain_fn)
            return f(*xs)
        return fused(*xs)

    return call


# cost formula: the kernel name carries the chain length
_CHAIN_RE = re.compile(r"fused_chain(\d+)")


def _numel_out(eqn) -> int:
    return max((int(np.prod(v.aval.shape, dtype=np.int64))
                for v in eqn.outvars if hasattr(v, "aval")), default=0)


def _fused_chain_flops(eqn) -> float:
    name = str(eqn.params.get("name") or "") + " " + str(
        eqn.params.get("name_and_src_info") or "")
    m = _CHAIN_RE.search(name)
    n_ops = int(m.group(1)) if m else 1
    return float(n_ops * _numel_out(eqn))


_cost.register_pallas_flops("fused_chain", _fused_chain_flops)
# bytes: the generic pallas rule (sum of operand+result avals) is exact
# for a fused elementwise call — one read per input, one write out
