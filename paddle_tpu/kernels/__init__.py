"""Fused kernels: Pallas on TPU, pure-XLA reference path elsewhere.

Reference analog: paddle/phi/kernels/fusion/ (fused_rope, fused_layernorm,
fused_bias_act, flash_attn via third_party/flashattn).  On TPU the hot ops are
Pallas kernels (pallas.py); on CPU (tests, 8-virtual-device mesh) we use the
jnp reference implementations, which XLA fuses well anyway.

Dispatch rule: use Pallas when running on a real TPU backend and shapes are
tile-aligned; otherwise the reference path.  FLAGS_use_fused_kernels=False
forces the reference path everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .. import framework


@functools.lru_cache(maxsize=1)
def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


def _use_pallas() -> bool:
    return _on_tpu() and framework.get_state().flags.get("FLAGS_use_fused_kernels", True)


_warned_fallbacks = set()


def _warn_pallas_fallback(name: str) -> None:
    """One-time warning so a silently-degraded hot path is visible."""
    if name not in _warned_fallbacks:
        _warned_fallbacks.add(name)
        import warnings

        warnings.warn(
            f"pallas kernel '{name}' failed to lower; using the XLA reference "
            f"path (slower). Set FLAGS_use_fused_kernels=False to silence.",
            RuntimeWarning, stacklevel=3)


# ---------------------------------------------------------------------------
# RMSNorm
# ---------------------------------------------------------------------------


def rms_norm_reference(x, weight=None, epsilon=1e-6):
    dt = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + epsilon)
    out = out.astype(dt)
    if weight is not None:
        out = out * weight
    return out


def rms_norm(x, weight=None, epsilon=1e-6):
    if _use_pallas() and x.ndim >= 2 and x.shape[-1] % 128 == 0 and weight is not None:
        from .pallas_norm import rms_norm_pallas  # broken module should fail loudly

        try:
            return rms_norm_pallas(x, weight, epsilon)
        except Exception:  # noqa: BLE001 — fall back on any lowering issue
            _warn_pallas_fallback("rms_norm")
    return rms_norm_reference(x, weight, epsilon)


# ---------------------------------------------------------------------------
# Attention (B, S, H, D) — paddle flash_attention layout
# ---------------------------------------------------------------------------


def attention_reference(q, k, v, mask=None, causal=False, scale=None):
    dt = q.dtype
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d**0.5)
    # (B, S, H, D) -> (B, H, S, D)
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    # grouped-query attention: repeat kv heads if fewer than q heads
    hq, hk = qt.shape[1], kt.shape[1]
    if hk != hq:
        kt = jnp.repeat(kt, hq // hk, axis=1)
        vt = jnp.repeat(vt, hq // hk, axis=1)
    logits = jnp.einsum("bhqd,bhkd->bhqk", qt, kt, preferred_element_type=jnp.float32) * scale
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(cm, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask.astype(logits.dtype)
    if mask is not None:
        # fully-masked rows return 0 under EITHER mask encoding (bool ->
        # row max -inf; additive -1e9 -> row max ~ -1e9), matching
        # distributed.context_parallel.ring_attention's convention
        dead = jnp.max(logits, axis=-1, keepdims=True) <= -1e8
        probs = jax.nn.softmax(jnp.where(dead, 0.0, logits), axis=-1)
        probs = jnp.where(dead, 0.0, probs).astype(dt)
    else:
        probs = jax.nn.softmax(logits, axis=-1).astype(dt)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


def attention(q, k, v, mask=None, causal=False, scale=None):
    if (
        _use_pallas()
        and mask is None
        and q.shape[-1] >= 64
        and q.shape[1] == k.shape[1]  # flash folds (B,S,H,D)->(B*H,S,D)
        and q.shape[1] % 128 == 0
    ):
        from .pallas_attention import flash_attention_pallas  # fail loudly if broken

        try:
            return flash_attention_pallas(q, k, v, causal=causal, scale=scale)
        except Exception:  # noqa: BLE001
            _warn_pallas_fallback("attention")
    return attention_reference(q, k, v, mask=mask, causal=causal, scale=scale)


# ---------------------------------------------------------------------------
# Paged decode attention (serving path: block-paged KV + page tables)
# ---------------------------------------------------------------------------


def paged_attention(q, k_pool, v_pool, page_table, lengths, scale=None):
    """Decode attention over a block-paged KV cache.  q: (B, Hq, D);
    k_pool/v_pool: (num_pages, page_size, Hkv, D); page_table:
    (B, pages_per_seq) i32; lengths: (B,) i32 valid tokens per sequence.

    Pallas kernel on TPU; the SAME kernel through the Pallas interpreter on
    CPU (tier-1 tests exercise the real grid/index-map logic), with the
    dense-gather XLA path as the fallback."""
    from .pallas_paged_attention import (paged_attention_pallas,
                                         paged_attention_reference)

    if framework.get_state().flags.get("FLAGS_use_fused_kernels", True):
        try:
            return paged_attention_pallas(q, k_pool, v_pool, page_table,
                                          lengths, scale=scale,
                                          interpret=not _on_tpu())
        except Exception:  # noqa: BLE001 — fall back on any lowering issue
            _warn_pallas_fallback("paged_attention")
    return paged_attention_reference(q, k_pool, v_pool, page_table, lengths,
                                     scale=scale)


# ---------------------------------------------------------------------------
# Ragged prefill+decode attention (the unified serving dispatch: per-seq
# query spans of arbitrary length over the same paged pools)
# ---------------------------------------------------------------------------


def ragged_attention(q, k_pool, v_pool, span_pt, block_seq, block_qpos,
                     span_len, ctx_len, scale=None):
    """Unified ragged attention over a block-paged KV cache: each sequence
    contributes a query span of arbitrary length (1 = decode, chunk-size =
    prefill).  q: (T, Hq, D) span-packed rows (spans start on block
    boundaries); k_pool/v_pool: (num_pages, page_size, Hkv, D); span_pt:
    (S, pages_per_seq) i32 page table per span; block_seq/block_qpos:
    (T // block_q,) i32 row-block metadata; span_len/ctx_len: (S,) i32.

    Pallas kernel on TPU; the SAME kernel through the Pallas interpreter on
    CPU (tier-1 tests exercise the real grid/index-map logic), with the
    dense-gather XLA path as the fallback."""
    from .pallas_ragged_attention import (ragged_attention_pallas,
                                          ragged_attention_reference)

    if framework.get_state().flags.get("FLAGS_use_fused_kernels", True):
        try:
            return ragged_attention_pallas(q, k_pool, v_pool, span_pt,
                                           block_seq, block_qpos, span_len,
                                           ctx_len, scale=scale,
                                           interpret=not _on_tpu())
        except Exception:  # noqa: BLE001 — fall back on any lowering issue
            _warn_pallas_fallback("ragged_attention")
    return ragged_attention_reference(q, k_pool, v_pool, span_pt, block_seq,
                                      block_qpos, span_len, ctx_len,
                                      scale=scale)


# ---------------------------------------------------------------------------
# Fused decode step (serving path: lm_head matmul + filter + sample in one
# Pallas dispatch — the engine's plain-decode epilogue)
# ---------------------------------------------------------------------------


def fused_decode_step(sel, head, key, temperature: float = 0.0,
                      top_k: int = 0, top_p: float = 1.0):
    """Fused decode epilogue: sel (R, E) out-row hiddens @ head (E, V),
    temperature/top-k/top-p filtering, categorical sampling (Gumbel-max,
    draw-for-draw identical to `generation.sample_logits` under the same
    key) — ONE pallas_call returning (R,) int32 token ids.

    Pallas kernel on TPU; the SAME kernel through the Pallas interpreter
    on CPU; the unfused matmul+sample_logits reference as the fallback.
    Greedy is token-exact vs the reference on every path."""
    from .pallas_decode_step import (decode_step_reference,
                                     fused_decode_step_pallas)

    if framework.get_state().flags.get("FLAGS_use_fused_kernels", True):
        try:
            return fused_decode_step_pallas(sel, head, key,
                                            temperature=temperature,
                                            top_k=top_k, top_p=top_p,
                                            interpret=not _on_tpu())
        except Exception:  # noqa: BLE001 — fall back on any lowering issue
            _warn_pallas_fallback("fused_decode_step")
    return decode_step_reference(sel, head, key, temperature=temperature,
                                 top_k=top_k, top_p=top_p)


def fused_decode_self_check(temperature: float = 0.0, top_k: int = 0,
                            top_p: float = 1.0):
    """(ok, reason) verify-or-rollback gate for the fused decode kernel:
    greedy token-exact + chi-square sampled equality vs the reference
    epilogue (memoized per knob set).  The engine consults this before
    routing plain decode through the fused dispatch."""
    from .pallas_decode_step import self_check

    return self_check(float(temperature), int(top_k), float(top_p),
                      interpret=not _on_tpu())


# ---------------------------------------------------------------------------
# Grouped matmul (dropless MoE dispatch: ragged per-expert FFN)
# ---------------------------------------------------------------------------


def grouped_matmul(lhs, rhs, group_sizes):
    """Ragged grouped matmul: out[m] = lhs[m] @ rhs[g(m)].  lhs: (M, K)
    rows sorted by group (group g contiguous), rhs: (X, K, N),
    group_sizes: (X,) i32 summing to M.

    Pallas kernel on TPU; the SAME kernel through the Pallas interpreter
    on CPU; XLA one-matmul-per-group dense form when shapes aren't
    tile-servable or FLAGS_use_fused_kernels=False.  Differentiable on
    every path (custom_vjp: dgrad = GMM vs transposed weights, wgrad =
    per-group transposed GMM)."""
    from .pallas_grouped_matmul import grouped_matmul as _gmm

    impl = None if framework.get_state().flags.get(
        "FLAGS_use_fused_kernels", True) else "dense"
    return _gmm(lhs, rhs, group_sizes, impl=impl)


# ---------------------------------------------------------------------------
# Rotary position embedding (reference: fused_rope_kernel.cu /
# incubate/nn/functional/fused_rotary_position_embedding.py)
# ---------------------------------------------------------------------------


def apply_rotary_emb(x, cos, sin, rotate_half_style="neox"):
    """x: (B, S, H, D); cos/sin: (S, D) or (1, S, 1, D)."""
    if cos.ndim == 2:
        cos = cos[None, :, None, :]
        sin = sin[None, :, None, :]
    cos = cos.astype(x.dtype)
    sin = sin.astype(x.dtype)
    if rotate_half_style == "neox":
        d2 = x.shape[-1] // 2
        x1, x2 = x[..., :d2], x[..., d2:]
        rotated = jnp.concatenate([-x2, x1], axis=-1)
    else:  # GPT-J interleaved
        x1 = x[..., ::2]
        x2 = x[..., 1::2]
        rotated = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
    return x * cos + rotated * sin


def fused_rotary_position_embedding(q, k, v=None, sin=None, cos=None, position_ids=None,
                                    use_neox_rotary_style=True):
    style = "neox" if use_neox_rotary_style else "gptj"
    if position_ids is not None:
        cos = jnp.take(cos.reshape(cos.shape[-2], cos.shape[-1]), position_ids, axis=0)[:, :, None, :]
        sin = jnp.take(sin.reshape(sin.shape[-2], sin.shape[-1]), position_ids, axis=0)[:, :, None, :]
    outs = [apply_rotary_emb(q, cos, sin, style)]
    if k is not None:
        outs.append(apply_rotary_emb(k, cos, sin, style))
    if v is not None:
        outs.append(apply_rotary_emb(v, cos, sin, style))
    return tuple(outs) if len(outs) > 1 else outs[0]


# ---------------------------------------------------------------------------
# Fused bias+activation (reference: fused_bias_act_kernel.cu)
# ---------------------------------------------------------------------------


def fused_bias_act(x, bias=None, act="gelu"):
    if bias is not None:
        x = x + bias
    if act == "gelu":
        return jax.nn.gelu(x)
    if act in ("silu", "swish"):
        return jax.nn.silu(x)
    if act == "relu":
        return jax.nn.relu(x)
    if act == "swiglu":
        a, b = jnp.split(x, 2, axis=-1)
        return jax.nn.silu(a) * b
    if act in (None, "none", "identity"):
        return x
    raise ValueError(f"unknown act {act}")


def swiglu(x, y=None):
    """reference: phi swiglu op (fused_ops) — silu(x) * y."""
    if y is None:
        a, b = jnp.split(x, 2, axis=-1)
        return jax.nn.silu(a) * b
    return jax.nn.silu(x) * y


# ---------------------------------------------------------------------------
# Decode-phase masked multi-head attention (reference:
# phi/kernels/fusion/gpu/masked_multihead_attention_kernel.cu)
# ---------------------------------------------------------------------------


def masked_multihead_attention_reference(x, cache_kv, bias=None, src_mask=None,
                                         sequence_lengths=None,
                                         rotary_tensor=None,
                                         rotary_emb_dims=0,
                                         use_neox_rotary_style=False):
    """x: (B, 3*H*D) fused qkv, one step; cache_kv: (2, B, H, M, D).

    Returns (out (B, H*D), updated cache (2, B, H, M, D)).
    """
    B = x.shape[0]
    _, _, H, M, D = cache_kv.shape
    if bias is not None:
        x = x + bias.astype(x.dtype)
    qkv = x.reshape(B, 3, H, D)
    q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]          # (B, H, D)
    if sequence_lengths is None:
        t = jnp.zeros((B,), jnp.int32)
    else:
        t = sequence_lengths.reshape(B).astype(jnp.int32)

    if rotary_emb_dims and rotary_tensor is not None:
        # rotary_tensor: (B, 1, 1, M, D) — cos in [..., :D//2], sin in the
        # mirrored half; gather this step's row per sequence
        rot = rotary_tensor.reshape(B, M, rotary_tensor.shape[-1])
        row = jnp.take_along_axis(rot, t[:, None, None], axis=1)[:, 0]  # (B, Dr)
        d2 = row.shape[-1] // 2
        cos, sin = row[:, None, :d2], row[:, None, d2:]

        if use_neox_rotary_style:
            def rope(u):  # half-split pairing: (x_i, x_{i+d/2})
                u1, u2 = u[..., :d2], u[..., d2:]
                return jnp.concatenate(
                    [u1 * cos - u2 * sin, u2 * cos + u1 * sin], axis=-1
                ).astype(u.dtype)
        else:
            def rope(u):  # GPT-J interleaved pairing: (x_{2i}, x_{2i+1})
                u1, u2 = u[..., 0::2], u[..., 1::2]
                out = jnp.stack(
                    [u1 * cos - u2 * sin, u2 * cos + u1 * sin], axis=-1)
                return out.reshape(u.shape).astype(u.dtype)

        q, k = rope(q), rope(k)

    # scatter this step's k/v at slot t per sequence
    slot = t[:, None, None, None]                      # (B,1,1,1)
    pos = jax.lax.broadcasted_iota(jnp.int32, (B, H, M, D), 2)
    ck = jnp.where(pos == slot, k[:, :, None, :].astype(cache_kv.dtype),
                   cache_kv[0])
    cv = jnp.where(pos == slot, v[:, :, None, :].astype(cache_kv.dtype),
                   cache_kv[1])

    scale = 1.0 / (D ** 0.5)
    s = jnp.einsum("bhd,bhmd->bhm", q.astype(jnp.float32),
                   ck.astype(jnp.float32)) * scale
    mpos = jax.lax.broadcasted_iota(jnp.int32, (B, H, M), 2)
    s = jnp.where(mpos <= t[:, None, None], s, -1e30)
    if src_mask is not None:
        s = s + src_mask.astype(jnp.float32).reshape(B, 1, M)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhm,bhmd->bhd", p, cv.astype(jnp.float32))
    out = o.reshape(B, H * D).astype(x.dtype)
    return out, jnp.stack([ck, cv])


# ---------------------------------------------------------------------------
# fused adaLN modulate (DiT conditioning): LN + x*(1+scale)+shift
# ---------------------------------------------------------------------------


def adaln_modulate_reference(x, shift, scale, epsilon=1e-6):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = xf.var(-1, keepdims=True)
    xn = (xf - mu) * jax.lax.rsqrt(var + epsilon)
    out = (xn * (1.0 + scale.astype(jnp.float32)[:, None, :])
           + shift.astype(jnp.float32)[:, None, :])
    return out.astype(x.dtype)


def adaln_modulate(x, shift, scale, epsilon=1e-6):
    """x (B, N, E); shift/scale (B, E) -> LN(x)*(1+scale)+shift in x.dtype."""
    if _use_pallas() and x.ndim == 3 and x.shape[-1] % 128 == 0:
        from .pallas_norm import adaln_modulate_pallas

        try:
            return adaln_modulate_pallas(x, shift, scale, epsilon)
        except Exception:  # noqa: BLE001 — fall back on any lowering issue
            _warn_pallas_fallback("adaln_modulate")
    return adaln_modulate_reference(x, shift, scale, epsilon)


# ---------------------------------------------------------------------------
# Graph Doctor cost hooks: pallas_call is opaque to the generic jaxpr cost
# model (its kernel jaxpr runs once PER GRID STEP), so each kernel family
# registers a whole-call FLOPs formula keyed on its kernel-fn name.  The
# analysis.cost roll-up (cost checker, profiler.static_cost) then ranks
# Pallas eqns alongside plain XLA eqns instead of scoring them zero.
# ---------------------------------------------------------------------------


def _register_graphlint_costs() -> None:
    import numpy as _np

    from ..analysis import cost as _cost

    def _numel(v):
        return int(_np.prod(v.aval.shape, dtype=_np.int64))

    def _attention_file(eqn):
        # operands reach the kernel head-flattened: q, k are (B*H, S, D).
        # fwd ~ 4*(B*H)*Sq*Sk*D (qk^T + p@v, no causal discount — the
        # repo's MFU convention); backward legs scale that up
        name = (f"{eqn.params.get('name') or ''} "
                f"{eqn.params.get('name_and_src_info', '')}")
        q, k = eqn.invars[0].aval, eqn.invars[1].aval
        BH, Sq, D = q.shape
        Sk = k.shape[1]
        base = 4.0 * BH * Sq * Sk * D
        if "_dq_kernel" in name:
            return 1.5 * base
        if "_dkv_kernel" in name:
            return 2.0 * base
        return base

    def _paged(eqn):
        # q arrives grouped (B, Hkv, rep, D); pools (P, ps, Hkv, D); page
        # table (B, pages_per_seq).  Upper bound: attention over the full
        # table (the kernel skips pages past lengths[b] at runtime)
        q, kp = eqn.invars[2].aval, eqn.invars[3].aval
        pt = eqn.invars[1].aval
        B, hkv, rep, D = q.shape
        max_len = pt.shape[1] * kp.shape[1]
        return 4.0 * B * hkv * rep * D * max_len

    def _paged_bytes(eqn):
        # the kernel reads each sequence's TABLE pages (scalar-prefetched
        # page table), never the whole (P, ps, Hkv, D) pool the generic
        # whole-aval rule would charge — the pool is sized for worst-case
        # occupancy, the traffic is sized for the batch's pages
        q, kp = eqn.invars[2].aval, eqn.invars[3].aval
        pt = eqn.invars[1].aval
        B, pps = pt.shape
        _P, ps, hkv, D = kp.shape
        kv_read = 2 * B * pps * ps * hkv * D * _np.dtype(kp.dtype).itemsize
        q_io = 2 * int(_np.prod(q.shape, dtype=_np.int64)) \
            * _np.dtype(q.dtype).itemsize
        return float(kv_read + q_io + 4 * B * (pps + 1))

    def _ragged(eqn):
        # scalar-prefetch order: block_seq, block_qpos, span_len, ctx_len,
        # span_pt, then q (T, Hkv, rep, D) and the pools (P, ps, Hkv, D).
        # Upper bound: every query row attends the full per-span table
        # (the kernel skips pages past each block's causal horizon)
        q, kp = eqn.invars[5].aval, eqn.invars[6].aval
        pt = eqn.invars[4].aval
        T, hkv, rep, D = q.shape
        max_len = pt.shape[1] * kp.shape[1]
        return 4.0 * T * hkv * rep * D * max_len

    def _ragged_bytes(eqn):
        # KV traffic is per ROW-BLOCK: each of the T/block_q blocks reads
        # its span's table pages (scalar-prefetched page table), never the
        # whole pool; q/o move once each
        q, kp = eqn.invars[5].aval, eqn.invars[6].aval
        pt = eqn.invars[4].aval
        T = q.shape[0]
        S, pps = pt.shape
        _P, ps, hkv, D = kp.shape
        kv_read = 2 * S * pps * ps * hkv * D * _np.dtype(kp.dtype).itemsize
        q_io = 2 * int(_np.prod(q.shape, dtype=_np.int64)) \
            * _np.dtype(q.dtype).itemsize
        meta = 4 * (2 * (T // max(1, ps)) + S * (pps + 2))
        return float(kv_read + q_io + meta)

    def _gmm(eqn):
        # x (Mp, K) @ per-group w (X, K, N) -> (Mp, N): dense-equivalent
        x = next(v.aval for v in eqn.invars if len(v.aval.shape) == 2
                 and _np.issubdtype(v.aval.dtype, _np.floating))
        w = next(v.aval for v in eqn.invars if len(v.aval.shape) == 3)
        return 2.0 * x.shape[0] * w.shape[1] * w.shape[2]

    def _tgmm(eqn):
        # wgrad: x (Mp, K) and grads (Mp, N) are both 2-D inputs; the 3-D
        # (X, K, N) array is the OUTPUT — same dense-equivalent 2*Mp*K*N
        x, g = (v.aval for v in eqn.invars
                if len(v.aval.shape) == 2
                and _np.issubdtype(v.aval.dtype, _np.floating))
        return 2.0 * x.shape[0] * x.shape[1] * g.shape[1]

    def _norm_file(eqn):
        return 8.0 * max(_numel(v) for v in eqn.invars)

    # file keys catch every kernel in the module via name_and_src_info;
    # the unambiguous fn-name keys keep backward kernels matched even on
    # jax versions that only populate the bare 'name' param
    _cost.register_pallas_flops("pallas_attention.py", _attention_file)
    _cost.register_pallas_flops("_dq_kernel", _attention_file)
    _cost.register_pallas_flops("_dkv_kernel", _attention_file)
    _cost.register_pallas_flops("_paged_kernel", _paged)
    _cost.register_pallas_bytes("_paged_kernel", _paged_bytes)
    _cost.register_pallas_flops("_ragged_kernel", _ragged)
    _cost.register_pallas_bytes("_ragged_kernel", _ragged_bytes)
    _cost.register_pallas_flops("_gmm_kernel", _gmm)
    _cost.register_pallas_flops("_tgmm_kernel", _tgmm)
    _cost.register_pallas_flops("pallas_norm.py", _norm_file)


try:
    _register_graphlint_costs()
except Exception:  # noqa: BLE001 — cost hooks must never break kernels
    pass
