"""Static per-eqn FLOPs/bytes roll-up over jaxprs.

The analysis-side analog of the reference's cost-model passes (the
auto-tuner's op cost tables); here the numbers come straight from eqn
shapes.  Conventions:

  * dot_general: 2 * batch * M * N * K
  * conv_general_dilated: 2 * prod(out) * prod(kernel_spatial) * Cin / groups
  * everything else: max(prod(in), prod(out)) — one flop per element
  * bytes: sum of operand + result nbytes (a proxy for HBM traffic; XLA
    fusion will beat this, but the *ranking* of heavy eqns survives) —
    EXCEPT indexed copies (gather/scatter/dynamic slices), which count
    only the bytes that move (2x slice/updates + indices): the engine's
    KV page-swap path reads pages, not the whole pool.  Kernels may
    register precise pallas bytes via `register_pallas_bytes`
  * scan bodies multiply by the static trip count; `while` bodies count
    once (trip counts are not static); both `cond` branches count (upper
    bound); pallas_call is opaque — kernels register their own FLOPs
    formulas via `register_pallas_flops` (see paddle_tpu/kernels).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from .core import aval_bytes, format_path, iter_eqns

__all__ = ["eqn_flops", "eqn_bytes", "per_eqn_costs", "estimate",
           "total_flops", "register_pallas_flops", "register_pallas_bytes"]

# substring of the pallas kernel name -> fn(eqn) -> flops
_PALLAS_FLOPS: Dict[str, Callable] = {}
# substring of the pallas kernel name -> fn(eqn) -> bytes
_PALLAS_BYTES: Dict[str, Callable] = {}


def register_pallas_flops(name_substr: str, fn: Callable) -> None:
    """Register a FLOPs estimator for pallas_call eqns whose kernel name
    contains `name_substr`.  `fn(eqn) -> float` sees the raw eqn (shapes
    via eqn.invars/outvars avals)."""
    _PALLAS_FLOPS[name_substr] = fn


def register_pallas_bytes(name_substr: str, fn: Callable) -> None:
    """Register a BYTES (HBM traffic) estimator for pallas_call eqns —
    the generic rule sums full operand avals, which wildly overstates a
    kernel that random-accesses a big pool (paged attention touches
    pages_per_seq pages, not the whole pool)."""
    _PALLAS_BYTES[name_substr] = fn


def _pallas_kernel_name(eqn) -> str:
    """Kernel-name string registrations match against: the bare 'name'
    param AND 'name_and_src_info' (which carries the source path), joined —
    so both fn-name keys ('_gmm_kernel') and file keys
    ('pallas_attention.py') keep matching across jax versions that
    populate either param."""
    name = eqn.params.get("name")
    info = eqn.params.get("name_and_src_info", "")
    return f"{name if isinstance(name, str) else ''} {info}"


def _match_pallas_formula(table: Dict[str, Callable],
                          name: str) -> Optional[Callable]:
    """Longest-match-wins over the registered name substrings: '_ragged'
    must not swallow a '_ragged_fused' registration (dict order made the
    winner depend on import order, silently aliasing cost attribution
    between kernels)."""
    best = None
    best_len = -1
    for sub, fn in table.items():
        if sub in name and len(sub) > best_len:
            best, best_len = fn, len(sub)
    return best


def _numel(aval) -> int:
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    return int(np.prod(shape, dtype=np.int64))


def _dot_general_flops(eqn) -> float:
    (contract, batch) = eqn.params["dimension_numbers"]
    (lc, rc), (lb, _rb) = contract, batch
    lhs, rhs = eqn.invars[0].aval, eqn.invars[1].aval
    k = int(np.prod([lhs.shape[d] for d in lc], dtype=np.int64)) or 1
    b = int(np.prod([lhs.shape[d] for d in lb], dtype=np.int64)) or 1
    m = _numel(lhs) // max(k * b, 1)
    n = _numel(rhs) // max(k * b, 1)
    return 2.0 * b * m * n * k


def _conv_flops(eqn) -> float:
    out = eqn.outvars[0].aval
    rhs = eqn.invars[1].aval           # kernel: (O, I/g, *spatial) in XLA dnums
    groups = int(eqn.params.get("feature_group_count", 1) or 1)
    # per output element: one MAC per kernel element per input channel slice
    kernel_elems = _numel(rhs) // max(rhs.shape[0], 1)
    return 2.0 * _numel(out) * kernel_elems / max(groups, 1)


def eqn_flops(eqn) -> float:
    """Estimated FLOPs of one eqn (containers and opaque kernels -> 0
    unless a pallas estimator is registered)."""
    prim = eqn.primitive.name
    try:
        if prim == "dot_general":
            return _dot_general_flops(eqn)
        if prim == "conv_general_dilated":
            return _conv_flops(eqn)
        if prim == "pallas_call":
            ce = eqn.params.get("cost_estimate")
            if ce is not None and getattr(ce, "flops", None):
                return float(ce.flops)
            name = _pallas_kernel_name(eqn)
            fn = _match_pallas_formula(_PALLAS_FLOPS, name)
            if fn is not None:
                return float(fn(eqn))
            return 0.0
        if prim in ("pjit", "scan", "while", "cond", "custom_jvp_call",
                    "custom_vjp_call", "custom_vjp_call_jaxpr", "remat",
                    "checkpoint", "closed_call", "core_call", "named_call"):
            return 0.0  # containers: cost lives in their sub-eqns
        if prim in _DATA_MOVEMENT_PRIMS:
            return 0.0  # pure copies (engine swap gather/scatter): the
            # cost is bytes, not flops — see eqn_bytes
        ins = max((_numel(v.aval) for v in eqn.invars
                   if hasattr(v, "aval")), default=0)
        outs = max((_numel(v.aval) for v in eqn.outvars
                    if hasattr(v, "aval")), default=0)
        return float(max(ins, outs))
    except Exception:  # noqa: BLE001 — cost must never break analysis
        return 0.0


# compute-free indexed copies.  Their HBM traffic is what MOVES (slice /
# updates + indices), not the operand pool: the engine's KV-swap path
# (generation.gather_kv_pages / scatter_kv_pages over an (L, P, ps, Hkv,
# D) pool) copies pages_per_seq pages, and summing whole-pool avals would
# misrank it as the most expensive eqn in the serving path.  scatter-add
# and friends stay on the generic rule (they do compute).
_DATA_MOVEMENT_PRIMS = frozenset({
    "gather", "scatter", "dynamic_slice", "dynamic_update_slice",
})


def _moved_bytes(eqn) -> int:
    prim = eqn.primitive.name
    if prim in ("gather", "dynamic_slice"):
        moved = sum(aval_bytes(v.aval) for v in eqn.outvars
                    if hasattr(v, "aval"))
        idx = sum(aval_bytes(v.aval) for v in eqn.invars[1:]
                  if hasattr(v, "aval"))
    else:       # scatter: (operand, indices, updates); dus: (op, update, *)
        upd = eqn.invars[2] if prim == "scatter" else eqn.invars[1]
        moved = aval_bytes(upd.aval) if hasattr(upd, "aval") else 0
        idx = (aval_bytes(eqn.invars[1].aval)
               if prim == "scatter" and hasattr(eqn.invars[1], "aval")
               else 0)
    return 2 * moved + idx          # read source + write destination


def eqn_bytes(eqn) -> int:
    try:
        prim = eqn.primitive.name
        if prim == "pallas_call":
            name = _pallas_kernel_name(eqn)
            fn = _match_pallas_formula(_PALLAS_BYTES, name)
            if fn is not None:
                return int(fn(eqn))
        elif prim in _DATA_MOVEMENT_PRIMS:
            return _moved_bytes(eqn)
        return sum(aval_bytes(v.aval) for v in list(eqn.invars)
                   + list(eqn.outvars) if hasattr(v, "aval"))
    except Exception:  # noqa: BLE001
        return 0


def per_eqn_costs(closed_jaxpr, max_depth: int = 32) -> List[dict]:
    """[{primitive, path, flops, bytes, weight}] over all eqns, with scan
    trip counts multiplied in.  Container eqns contribute 0 themselves."""
    out = []
    for eqn, path, weight in iter_eqns(closed_jaxpr, max_depth=max_depth):
        fl, by = eqn_flops(eqn), eqn_bytes(eqn)
        if fl or by:
            out.append({
                "primitive": eqn.primitive.name,
                "path": format_path(path, eqn),
                "flops": fl * weight,
                "bytes": by * weight,
                "weight": weight,
            })
    return out


def estimate(fn_or_jaxpr, *args, top_k: Optional[int] = None, **kwargs):
    """Roll up {total_flops, total_bytes, top} for a callable (traced with
    *args) or an already-closed jaxpr.  `top` holds the top_k heaviest
    eqns by FLOPs (ties broken by bytes) — the profiler's static view."""
    import jax

    if args or kwargs or callable(fn_or_jaxpr):
        import functools
        traced = (functools.partial(fn_or_jaxpr, **kwargs) if kwargs
                  else fn_or_jaxpr)
        closed = jax.make_jaxpr(traced)(*args)
    else:
        closed = fn_or_jaxpr
    costs = per_eqn_costs(closed)
    costs.sort(key=lambda c: (-c["flops"], -c["bytes"]))
    return {
        "total_flops": float(sum(c["flops"] for c in costs)),
        "total_bytes": int(sum(c["bytes"] for c in costs)),
        # top_k=0 means NO top list (not the default 5)
        "top": costs[:5] if top_k is None else costs[:top_k],
    }


def total_flops(fn_or_jaxpr, *args, **kwargs) -> float:
    """Just the FLOPs roll-up of one target — the per-target lookup
    obs.mfu joins with measured step times (runtime MFU /
    cost_model_ratio).  Same tracing rules as `estimate`."""
    return estimate(fn_or_jaxpr, *args, top_k=0, **kwargs)["total_flops"]
