"""kernellint — Graph Doctor tier 6: static verification of Pallas kernels.

Every other tier stops at the `pallas_call` boundary and trusts the kernel
body blindly — only runtime interpret-mode tests catch index bugs.  This
tier opens the eqn's params (`grid_mapping`, the kernel jaxpr) and proves
what it can about the kernel CONTRACT before anything runs:

  * an interval-arithmetic evaluator over each BlockSpec index-map jaxpr
    and the grid proves in-bounds block reads/writes and exactly-once
    output coverage;
  * a per-chip-generation VMEM footprint model (double-buffered blocks +
    scratch, keyed on the same v3..v6e table style `comm_cost.py` uses)
    predicts OOMs statically and is exported as `vmem_bytes(...)` so the
    autotuner item can prune invalid block-shape sweep points before
    ever compiling them;
  * dtype discipline inside the kernel jaxpr: low-precision dots without
    an f32 accumulator and scratch/output precision laundering.

Finding codes:
  KERNEL_OOB_BLOCK      an index map emits a block index outside
                        [0, ceil(dim/block)-1] for some grid cell (ERROR)
  KERNEL_OUT_UNCOVERED  an output dimension has blocks no grid cell
                        writes (ERROR)
  KERNEL_OUT_OVERLAP    grid dims unused by an output index map are not
                        the innermost suffix — revisits of the same
                        output block are non-consecutive, so the
                        accumulate-then-flush idiom cannot apply (WARNING)
  KERNEL_DEAD_GRID_CELL a `pl.when` predicate is statically false for
                        EVERY grid cell — the guarded body never runs
                        (WARNING)
  KERNEL_VMEM_OVERFLOW  static footprint exceeds the chip's VMEM budget
                        (WARNING; budget from `VMEM_BYTES_BY_KIND` or the
                        `kernellint_vmem_budget_bytes` option)
  KERNEL_LOWP_ACCUM     bf16/f16 dot whose result stays low-precision, or
                        a low-precision scratch ref that is both read and
                        written (a running sum losing mantissa) (WARNING)
  KERNEL_DTYPE_MISMATCH float scratch strictly narrower than a float
                        output — accumulating below output precision
                        (WARNING)
  KERNEL_ASSUME         (INFO) sites where in-bounds/coverage is ASSUMED,
                        not proven: data-dependent prefetch indices (the
                        PagedKVCache invariant that page-table entries are
                        valid pool indices), unproven surjectivity,
                        trailing-dim accumulate revisits
  KERNEL_VMEM_FOOTPRINT (INFO) the static footprint with a per-operand
                        breakdown — bench and the CLI surface it

Soundness: intervals over-approximate, so OOB/UNCOVERED fire only when
the violating endpoint is *attained* (tracked by `Ival.exact`: constants,
grid vars, +,-,*, //const and %const preserve attainment) or the WHOLE
interval is out of range.  Approximate bounds that merely straddle the
limit are demoted to KERNEL_ASSUME.  Correlated subexpressions (``i-i``)
can defeat the attainment claim in principle; real index maps are affine
and the shipped-kernel suite pins zero false positives.

Two surfaces: the registered checker ``kernellint`` runs inside every
`analyze`/`analyze_jaxpr` call — which makes the rewrite tier's re-lint
gate reject generated kernels that fail these checks (rollback for free)
— and `analyze_kernels()` traces the shipped kernel wrappers directly
(grad traces pull in the backward kernels) for `tools/graphlint.py
--kernels`.
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .core import (
    CheckContext, Finding, Report, Severity, aval_bytes, finalize_findings,
    format_path, iter_eqns, register_checker, sub_jaxprs,
)

__all__ = [
    "VMEM_BYTES_BY_KIND", "vmem_budget", "vmem_bytes", "kernel_id",
    "lint_pallas_eqn", "analyze_kernels", "shipped_kernel_targets",
    "Ival",
]

# ---------------------------------------------------------------------------
# per-chip VMEM budgets (bytes) — most-specific-first substring match on the
# device-kind string, same convention as comm_cost.LINK_BW_BY_KIND and
# obs.mfu.PEAK_FLOPS_BY_KIND.  Conservative usable budgets (~16 MB/core per
# the TPU memory hierarchy; newer parts carry more): the point is a STATIC
# OOM predictor, so erring low turns a compile-time Mosaic failure into a
# lint finding.  The `kernellint_vmem_budget_bytes` option overrides.
VMEM_BYTES_BY_KIND: Tuple[Tuple[str, int], ...] = (
    ("v6e", 32 << 20), ("v6", 32 << 20),
    ("v5 lite", 16 << 20), ("v5e", 16 << 20), ("v5litepod", 16 << 20),
    ("v5p", 32 << 20), ("v5", 32 << 20),
    ("v4", 16 << 20),
    ("v3", 16 << 20),
)

_DEFAULT_CHIP = "v5e"


def vmem_budget(chip: Optional[str] = None) -> int:
    """VMEM byte budget for a chip-kind string ("TPU v5 lite", "v4", ...).
    Unknown/CPU chips budget at the v5e number so CPU lint runs still
    predict what the default fleet chip would fit."""
    kind = (chip or _DEFAULT_CHIP).lower()
    for k, b in VMEM_BYTES_BY_KIND:
        if k in kind:
            return b
    return dict(VMEM_BYTES_BY_KIND)["v5e"]


# ---------------------------------------------------------------------------
# interval arithmetic
# ---------------------------------------------------------------------------

_INF = float("inf")


@dataclasses.dataclass(frozen=True)
class Ival:
    """Closed integer interval [lo, hi] with two provenance bits.

    `exact`: both endpoints are attained by some grid cell — the license
    to report `hi > max` as a REAL out-of-bounds access instead of an
    artifact of over-approximation.  `from_prefetch`: the value depends
    on an SMEM scalar-prefetch load (page tables, group offsets) — never
    provable statically, always reported as an assumption."""

    lo: float
    hi: float
    exact: bool = True
    from_prefetch: bool = False

    @property
    def bounded(self) -> bool:
        return self.lo > -_INF and self.hi < _INF

    @property
    def singleton(self) -> bool:
        # lo == hi means the VALUE is known (bounds are always sound),
        # independent of the attainment flag
        return self.lo == self.hi


TOP = Ival(-_INF, _INF, exact=False)
PREFETCH_TOP = Ival(-_INF, _INF, exact=False, from_prefetch=True)


def _const(v) -> Ival:
    return Ival(float(v), float(v))


def _taint(*xs: Ival) -> bool:
    return any(x.from_prefetch for x in xs)


def _mulc(x: float, y: float) -> float:
    return 0.0 if (x == 0 or y == 0) else x * y  # kill inf*0 NaNs


def _add(a: Ival, b: Ival) -> Ival:
    return Ival(a.lo + b.lo, a.hi + b.hi, a.exact and b.exact, _taint(a, b))


def _sub(a: Ival, b: Ival) -> Ival:
    return Ival(a.lo - b.hi, a.hi - b.lo, a.exact and b.exact, _taint(a, b))


def _mul(a: Ival, b: Ival) -> Ival:
    c = (_mulc(a.lo, b.lo), _mulc(a.lo, b.hi),
         _mulc(a.hi, b.lo), _mulc(a.hi, b.hi))
    return Ival(min(c), max(c), a.exact and b.exact, _taint(a, b))


def _neg(a: Ival) -> Ival:
    return Ival(-a.hi, -a.lo, a.exact, a.from_prefetch)


def _tdiv1(x: float, d: float) -> float:
    if abs(x) == _INF:
        return x if d > 0 else -x
    return float(math.trunc(x / d))


def _tdiv(a: Ival, b: Ival) -> Ival:
    """lax.div — truncating integer division."""
    t = _taint(a, b)
    if b.singleton and b.lo != 0:
        d = b.lo
        c = (_tdiv1(a.lo, d), _tdiv1(a.hi, d))
        # trunc-div by a constant is monotonic: endpoints stay attained
        return Ival(min(c), max(c), a.exact and b.exact, t)
    if a.bounded and b.bounded and (b.lo > 0 or b.hi < 0):
        c = [_tdiv1(x, d) for x in (a.lo, a.hi) for d in (b.lo, b.hi)]
        return Ival(min(c), max(c), False, t)
    return dataclasses.replace(TOP, from_prefetch=t)


def _trem(a: Ival, b: Ival) -> Ival:
    """lax.rem — C-style remainder (sign of the dividend)."""
    t = _taint(a, b)
    if b.singleton and b.lo != 0:
        d = abs(b.lo)
        if a.lo >= 0 and a.hi < d:
            return dataclasses.replace(a, from_prefetch=t)  # identity
        if a.lo >= 0:
            full = a.bounded and (a.hi - a.lo + 1) >= d
            return Ival(0.0, d - 1.0, a.exact and full, t)
        return Ival(-(d - 1.0), d - 1.0, False, t)
    return dataclasses.replace(TOP, from_prefetch=t)


def _floordiv(a: Ival, b: Ival) -> Optional[Ival]:
    """jnp floor_divide (the `pjit[name=floor_divide]` wrapper)."""
    if b.singleton and b.lo != 0 and a.bounded:
        d = b.lo
        c = (math.floor(a.lo / d), math.floor(a.hi / d))
        return Ival(float(min(c)), float(max(c)), a.exact and b.exact,
                    _taint(a, b))
    return None


def _pymod(a: Ival, b: Ival) -> Optional[Ival]:
    """jnp remainder/mod (Python semantics: sign of the divisor)."""
    if b.singleton and b.lo > 0:
        d = b.lo
        t = _taint(a, b)
        if a.lo >= 0 and a.hi < d:
            return dataclasses.replace(a, from_prefetch=t)
        full = a.bounded and (a.hi - a.lo + 1) >= d
        return Ival(0.0, d - 1.0, a.exact and full, t)
    return None


def _cmp(prim: str, a: Ival, b: Ival) -> Ival:
    t = _taint(a, b)

    def definite(v: int) -> Ival:
        return Ival(float(v), float(v), True, t)

    if prim == "lt":
        if a.hi < b.lo:
            return definite(1)
        if a.lo >= b.hi:
            return definite(0)
    elif prim == "le":
        if a.hi <= b.lo:
            return definite(1)
        if a.lo > b.hi:
            return definite(0)
    elif prim == "gt":
        if a.lo > b.hi:
            return definite(1)
        if a.hi <= b.lo:
            return definite(0)
    elif prim == "ge":
        if a.lo >= b.hi:
            return definite(1)
        if a.hi < b.lo:
            return definite(0)
    elif prim == "eq":
        if a.hi < b.lo or b.hi < a.lo:
            return definite(0)
        if a.singleton and b.singleton and a.lo == b.lo:
            return definite(1)
    elif prim == "ne":
        if a.hi < b.lo or b.hi < a.lo:
            return definite(1)
        if a.singleton and b.singleton and a.lo == b.lo:
            return definite(0)
    return Ival(0.0, 1.0, False, t)


def _bool_and(a: Ival, b: Ival) -> Ival:
    t = _taint(a, b)
    if a.hi == 0 or b.hi == 0:
        return Ival(0.0, 0.0, True, t)
    if a.lo >= 1 and b.lo >= 1:
        return Ival(1.0, 1.0, True, t)
    return Ival(0.0, 1.0, False, t)


def _bool_or(a: Ival, b: Ival) -> Ival:
    t = _taint(a, b)
    if a.lo >= 1 or b.lo >= 1:
        return Ival(1.0, 1.0, True, t)
    if a.hi == 0 and b.hi == 0:
        return Ival(0.0, 0.0, True, t)
    return Ival(0.0, 1.0, False, t)


def _sign(a: Ival) -> Ival:
    if a.lo > 0:
        return Ival(1.0, 1.0, True, a.from_prefetch)
    if a.hi < 0:
        return Ival(-1.0, -1.0, True, a.from_prefetch)
    lo = -1.0 if a.lo < 0 else 0.0
    hi = 1.0 if a.hi > 0 else 0.0
    return Ival(lo, hi, a.exact, a.from_prefetch)


_IDENTITY_PRIMS = frozenset({
    "convert_element_type", "stop_gradient", "squeeze", "reshape",
    "broadcast_in_dim", "copy",
})


def _apply_prim(prim: str, params: dict, ins: List[Ival],
                grid: Optional[Tuple[int, ...]]) -> Optional[List[Ival]]:
    """Interval transfer function for one primitive over scalar int/bool
    operands.  None = unhandled (caller defaults the outputs to TOP)."""
    if prim == "program_id":
        ax = int(params.get("axis", 0))
        if grid is not None and 0 <= ax < len(grid):
            return [Ival(0.0, float(int(grid[ax])) - 1.0)]
        return [TOP]
    if prim in _IDENTITY_PRIMS and len(ins) == 1:
        return [ins[0]]
    if len(ins) == 2:
        a, b = ins
        if prim == "add":
            return [_add(a, b)]
        if prim == "sub":
            return [_sub(a, b)]
        if prim == "mul":
            return [_mul(a, b)]
        if prim == "div":
            return [_tdiv(a, b)]
        if prim == "rem":
            return [_trem(a, b)]
        if prim == "max":
            return [Ival(max(a.lo, b.lo), max(a.hi, b.hi),
                         a.exact and b.exact, _taint(a, b))]
        if prim == "min":
            return [Ival(min(a.lo, b.lo), min(a.hi, b.hi),
                         a.exact and b.exact, _taint(a, b))]
        if prim in ("lt", "le", "gt", "ge", "eq", "ne"):
            return [_cmp(prim, a, b)]
        if prim == "and":
            return [_bool_and(a, b)]
        if prim == "or":
            return [_bool_or(a, b)]
    if len(ins) == 1:
        a = ins[0]
        if prim == "neg":
            return [_neg(a)]
        if prim == "sign":
            return [_sign(a)]
        if prim == "abs":
            c = (abs(a.lo), abs(a.hi), 0.0 if a.lo <= 0 <= a.hi else _INF)
            lo = min(abs(a.lo), abs(a.hi)) if not (a.lo <= 0 <= a.hi) else 0.0
            return [Ival(lo, max(abs(a.lo), abs(a.hi)), a.exact,
                         a.from_prefetch)]
        if prim == "not":
            return [Ival(1.0 - a.hi, 1.0 - a.lo, a.exact, a.from_prefetch)]
    if prim == "select_n" and len(ins) >= 2:
        pred, cases = ins[0], ins[1:]
        if pred.singleton and 0 <= int(pred.lo) < len(cases):
            return [cases[int(pred.lo)]]
        return [Ival(min(c.lo for c in cases), max(c.hi for c in cases),
                     False, _taint(*ins))]
    return None


def _read(env: dict, atom) -> Ival:
    """Atom -> interval: Literals become singletons, unknown vars TOP."""
    val = getattr(atom, "val", None)
    if val is not None or type(atom).__name__ == "Literal":
        try:
            arr = np.asarray(val)
            if arr.ndim == 0 and arr.dtype.kind in "iub":
                return _const(int(arr))
        except Exception:  # noqa: BLE001 — opaque literal payloads
            pass
        return TOP
    return env.get(atom, TOP)


def _eval_jaxpr(jaxpr_or_closed, in_ivals: Sequence[Ival],
                grid: Optional[Tuple[int, ...]] = None) -> List[Ival]:
    """Evaluate a (Closed)Jaxpr of scalar index arithmetic over intervals.
    `get` (an SMEM scalar-prefetch load in an index map) yields
    PREFETCH_TOP; unhandled primitives yield TOP — both sound."""
    closed = jaxpr_or_closed
    jaxpr = getattr(closed, "jaxpr", closed)
    consts = list(getattr(closed, "consts", ()) or ())
    env: dict = {}
    for v, iv in zip(jaxpr.invars, in_ivals):
        env[v] = iv
    for v, c in zip(jaxpr.constvars, consts):
        try:
            arr = np.asarray(c)
            if arr.ndim == 0 and arr.dtype.kind in "iub":
                env[v] = _const(int(arr))
        except Exception:  # noqa: BLE001 — non-scalar consts stay TOP
            pass
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        ins = [_read(env, a) for a in eqn.invars]
        if prim == "get":
            outs: Optional[List[Ival]] = [PREFETCH_TOP] * len(eqn.outvars)
        elif prim == "pjit":
            name = str(eqn.params.get("name", ""))
            special = None
            if len(ins) == 2:
                if name == "floor_divide":
                    special = _floordiv(ins[0], ins[1])
                elif name in ("remainder", "mod", "floor_remainder"):
                    special = _pymod(ins[0], ins[1])
            if special is not None:
                outs = [special]
            else:
                outs = _eval_jaxpr(eqn.params["jaxpr"], ins, grid)
        else:
            outs = _apply_prim(prim, eqn.params, ins, grid)
        if outs is None:
            outs = [TOP] * len(eqn.outvars)
        for ov, iv in zip(eqn.outvars, outs):
            env[ov] = iv
    return [_read(env, ov) for ov in jaxpr.outvars]


# ---------------------------------------------------------------------------
# structural helpers over the index-map jaxpr
# ---------------------------------------------------------------------------


def _is_literal(atom) -> bool:
    return type(atom).__name__ == "Literal" or hasattr(atom, "val")


def _grid_deps(jaxpr, n_grid: int) -> Dict[Any, Tuple[set, bool]]:
    """var -> (set of grid-invar indices it depends on, prefetch bit)."""
    deps: Dict[Any, Tuple[set, bool]] = {}
    for i, v in enumerate(jaxpr.invars):
        deps[v] = ({i}, False) if i < n_grid else (set(), True)
    for v in jaxpr.constvars:
        deps[v] = (set(), False)
    for eqn in jaxpr.eqns:
        g: set = set()
        pf = False
        for a in eqn.invars:
            if _is_literal(a):
                continue
            dg, dp = deps.get(a, (set(), False))
            g |= dg
            pf |= dp
        if eqn.primitive.name == "get":
            pf = True
        for ov in eqn.outvars:
            deps[ov] = (g, pf)
    return deps


def _resolve_identity(jaxpr, atom):
    """Follow single-input identity eqns (convert_element_type & co) back
    to the underlying atom, so `i32(i)` still reads as the grid var i."""
    defs = {}
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            defs[ov] = eqn
    seen = 0
    while not _is_literal(atom) and atom in defs and seen < 8:
        eqn = defs[atom]
        if eqn.primitive.name in _IDENTITY_PRIMS and len(eqn.invars) == 1:
            atom = eqn.invars[0]
            seen += 1
        else:
            break
    return atom


# ---------------------------------------------------------------------------
# kernel identity + VMEM footprint
# ---------------------------------------------------------------------------

_CHAIN_NAME_RE = re.compile(r"fused_chain\d+")


def kernel_id(eqn) -> str:
    """Stable `module.kernel_name` identity for baselines: resolves the
    `_fwd_kernel` name collision between modules, and normalizes
    generated `fused_chain<N>_s<site>` kernels to one id (site tags and
    chain lengths are rewrite-run-unstable)."""
    info = str(eqn.params.get("name_and_src_info")
               or eqn.params.get("name") or "pallas_kernel")
    name = info.split(" at ", 1)[0].strip() or "pallas_kernel"
    mod = ""
    if " at " in info:
        src = info.split(" at ", 1)[1].split(":", 1)[0]
        base = src.replace("\\", "/").rsplit("/", 1)[-1]
        mod = base[:-3] if base.endswith(".py") else base
    if _CHAIN_NAME_RE.search(name):
        return f"{mod or 'pallas_fused_chain'}.fused_chain"
    return f"{mod}.{name}" if mod else name


def _block_numel(block_shape) -> int:
    n = 1
    for b in block_shape:
        n *= int(b) if isinstance(b, (int, np.integer)) else 1
    return n


def _eqn_vmem_breakdown(eqn) -> Tuple[int, Dict[str, int]]:
    """(total_bytes, {operand: bytes}) for one pallas_call eqn: every
    block-mapped operand double-buffered (Mosaic pipelines the grid) plus
    the scratch/accumulator refs at full size."""
    gm = eqn.params.get("grid_mapping")
    kj = eqn.params.get("jaxpr")
    total = 0
    rows: Dict[str, int] = {}
    for idx, bm in enumerate(getattr(gm, "block_mappings", ()) or ()):
        arr = getattr(bm, "array_shape_dtype", None)
        if arr is None:
            continue
        try:
            item = np.dtype(arr.dtype).itemsize
        except Exception:  # noqa: BLE001 — opaque dtypes price at 0
            item = 0
        n = _block_numel(getattr(bm, "block_shape", ()) or ())
        b = n * item * 2
        rows[str(getattr(bm, "origin", f"operand[{idx}]"))] = b
        total += b
    num_scratch = int(getattr(gm, "num_scratch_operands", 0) or 0)
    if kj is not None and num_scratch:
        for i, v in enumerate(kj.invars[len(kj.invars) - num_scratch:]):
            b = aval_bytes(v.aval)
            rows[f"scratch[{i}]"] = b
            total += b
    return total, rows


def vmem_bytes(kernel, shapes: Sequence = (), chip: Optional[str] = None,
               **kwargs) -> int:
    """Static VMEM footprint (bytes) of the pallas_call(s) a callable
    traces to — the autotuner's sweep-point pruner: compare against
    `vmem_budget(chip)` before compiling a candidate block shape.

    kernel: a callable (traced at `shapes`, which may be arrays or
    ShapeDtypeStructs), an already-traced ClosedJaxpr, or a pallas_call
    eqn.  Returns the MAX footprint across the pallas_calls found; the
    `chip` argument is accepted for call-site symmetry with
    `vmem_budget` and future per-chip packing rules."""
    del chip  # the byte count is chip-independent; the budget is not
    if hasattr(kernel, "primitive"):            # a pallas_call eqn
        return _eqn_vmem_breakdown(kernel)[0]
    closed = kernel
    if callable(kernel) and not hasattr(kernel, "jaxpr"):
        import jax

        closed = jax.make_jaxpr(
            lambda *a: kernel(*a, **kwargs))(*shapes)
    sizes = [
        _eqn_vmem_breakdown(eqn)[0]
        for eqn, _path, _w in iter_eqns(closed)
        if eqn.primitive.name == "pallas_call"
    ]
    if not sizes:
        raise ValueError("no pallas_call found in the traced kernel")
    return max(sizes)


# ---------------------------------------------------------------------------
# the linter proper
# ---------------------------------------------------------------------------

_LOW_FLOATS = ("bfloat16", "float16")


def _dtype_name(dt) -> str:
    try:
        return np.dtype(dt).name
    except Exception:  # noqa: BLE001 — opaque dtypes never match
        return str(dt)


def _is_float(dt) -> bool:
    try:
        d = np.dtype(dt)
    except Exception:  # noqa: BLE001
        return False
    return d.kind == "f" or d.name in _LOW_FLOATS


def _opt(ctx, key: str, default=None):
    if ctx is not None:
        return ctx.opt(key, default)
    from .core import _DEFAULT_OPTIONS

    return _DEFAULT_OPTIONS.get(key, default)


def lint_pallas_eqn(eqn, path: Tuple[str, ...] = (),
                    ctx=None) -> List[Finding]:
    """All kernellint findings for ONE pallas_call eqn."""
    p = eqn.params if isinstance(eqn.params, dict) else {}
    gm = p.get("grid_mapping")
    kj = p.get("jaxpr")
    if gm is None or kj is None:
        return []
    kid = kernel_id(eqn)
    loc = f"{format_path(tuple(path), eqn)}[{kid}]"
    findings: List[Finding] = []
    assumes: List[str] = []

    grid = tuple(getattr(gm, "grid", ()) or ())
    static_grid = all(isinstance(g, (int, np.integer)) for g in grid)
    igrid: Optional[Tuple[int, ...]] = \
        tuple(int(g) for g in grid) if static_grid else None
    if not static_grid:
        assumes.append("dynamic grid: block bounds/coverage not provable")
    n_grid = len(grid)
    num_inputs = int(getattr(gm, "num_inputs", 0) or 0)
    num_scratch = int(getattr(gm, "num_scratch_operands", 0) or 0)
    bms = tuple(getattr(gm, "block_mappings", ()) or ())

    out_dtypes: List[Any] = []
    for idx, bm in enumerate(bms):
        is_out = idx >= num_inputs
        arr = getattr(bm, "array_shape_dtype", None)
        imj = getattr(bm, "index_map_jaxpr", None)
        block = tuple(getattr(bm, "block_shape", ()) or ())
        origin = str(getattr(bm, "origin", "") or f"operand[{idx}]")
        if arr is None or imj is None:
            continue
        if is_out:
            out_dtypes.append(arr.dtype)
        if "unblocked" in str(getattr(bm, "indexing_mode", "")).lower():
            assumes.append(f"{origin}: Unblocked indexing not modeled")
            continue
        dims = tuple(int(d) for d in getattr(arr, "shape", ()))
        if igrid is None:
            continue
        jx = getattr(imj, "jaxpr", imj)
        in_ivals = [Ival(0.0, float(g) - 1.0) for g in igrid]
        in_ivals += [PREFETCH_TOP] * max(0, len(jx.invars) - n_grid)
        out_ivals = _eval_jaxpr(imj, in_ivals, igrid)
        nblocks_by_dim = []
        for d in range(min(len(dims), len(block), len(out_ivals))):
            bsz = block[d] if isinstance(block[d], (int, np.integer)) else 1
            nblocks = -(-dims[d] // max(int(bsz), 1))
            nblocks_by_dim.append(nblocks)
            iv = out_ivals[d]
            mx = nblocks - 1
            tag = f"{origin} dim{d}"
            rng = f"[{iv.lo:g}, {iv.hi:g}]"
            if iv.from_prefetch:
                assumes.append(
                    f"{tag}: data-dependent block index (scalar prefetch); "
                    "in-bounds assumed — the caller's table invariant")
            elif not iv.bounded:
                assumes.append(f"{tag}: unbounded block index; "
                               "in-bounds assumed")
            elif iv.lo > mx or iv.hi < 0:
                findings.append(Finding(
                    Severity.ERROR, "KERNEL_OOB_BLOCK", loc,
                    f"{tag}: every grid cell reads block index {rng}, "
                    f"entirely outside [0, {mx}] "
                    f"({dims[d]} elements / block {block[d]})",
                    suggestion="fix the BlockSpec index map or the grid",
                    data={"kernel": kid, "operand": origin, "dim": d,
                          "index_lo": iv.lo, "index_hi": iv.hi,
                          "nblocks": nblocks}))
            elif iv.exact and (iv.hi > mx or iv.lo < 0):
                findings.append(Finding(
                    Severity.ERROR, "KERNEL_OOB_BLOCK", loc,
                    f"{tag}: index map emits block index {rng} for some "
                    f"grid cell; valid range is [0, {mx}] "
                    f"({dims[d]} elements / block {block[d]})",
                    suggestion="fix the BlockSpec index map or the grid",
                    data={"kernel": kid, "operand": origin, "dim": d,
                          "index_lo": iv.lo, "index_hi": iv.hi,
                          "nblocks": nblocks}))
            elif iv.hi > mx or iv.lo < 0:
                assumes.append(
                    f"{tag}: approximate bounds {rng} straddle [0, {mx}]; "
                    "not provably OOB")
        if is_out:
            findings += _coverage_findings(
                jx, igrid, n_grid, nblocks_by_dim, origin, kid, loc, assumes)

    # VMEM footprint vs the chip budget ---------------------------------
    fp, rows = _eqn_vmem_breakdown(eqn)
    chip = _opt(ctx, "kernellint_chip") or _DEFAULT_CHIP
    budget = _opt(ctx, "kernellint_vmem_budget_bytes") or vmem_budget(chip)
    if fp > budget:
        findings.append(Finding(
            Severity.WARNING, "KERNEL_VMEM_OVERFLOW", loc,
            f"static VMEM footprint {fp} B (double-buffered blocks + "
            f"scratch) exceeds the {chip} budget {int(budget)} B",
            suggestion="shrink block shapes or scratch accumulators",
            data={"kernel": kid, "vmem_bytes": fp,
                  "budget_bytes": int(budget), "chip": chip,
                  "breakdown": rows}))
    findings.append(Finding(
        Severity.INFO, "KERNEL_VMEM_FOOTPRINT", loc,
        f"static VMEM footprint {fp} B of {int(budget)} B ({chip})",
        data={"kernel": kid, "vmem_bytes": fp, "budget_bytes": int(budget),
              "chip": chip, "breakdown": rows,
              "grid": [int(g) if isinstance(g, (int, np.integer)) else -1
                       for g in grid]}))

    # kernel-body checks: dead pl.when cells + dtype discipline ---------
    findings += _lint_kernel_body(eqn, kj, igrid, num_scratch,
                                  out_dtypes, kid, loc)

    if assumes:
        shown = "; ".join(assumes[:3]) + ("; ..." if len(assumes) > 3 else "")
        findings.append(Finding(
            Severity.INFO, "KERNEL_ASSUME", loc,
            f"{len(assumes)} unproven assumption(s): {shown}",
            data={"kernel": kid, "assumptions": assumes}))
    return findings


def _coverage_findings(jx, igrid, n_grid, nblocks_by_dim, origin, kid,
                       loc, assumes) -> List[Finding]:
    """Exactly-once output coverage: every output dim must be a bare grid
    var of matching extent or a constant over a single block; grid dims
    unused by the map must be the innermost suffix (accumulate idiom)."""
    findings: List[Finding] = []
    deps = _grid_deps(jx, n_grid)
    used: set = set()
    for d, nblocks in enumerate(nblocks_by_dim):
        if d >= len(jx.outvars):
            break
        ov = _resolve_identity(jx, jx.outvars[d])
        if _is_literal(ov):
            if nblocks > 1:
                findings.append(Finding(
                    Severity.ERROR, "KERNEL_OUT_UNCOVERED", loc,
                    f"{origin} dim{d}: constant block index writes 1 of "
                    f"{nblocks} blocks — the rest are never written",
                    suggestion="index the dim with a grid variable",
                    data={"kernel": kid, "dim": d, "nblocks": nblocks}))
            continue
        k = next((i for i in range(n_grid) if jx.invars[i] is ov), None)
        if k is not None:
            used.add(k)
            if igrid[k] < nblocks:
                findings.append(Finding(
                    Severity.ERROR, "KERNEL_OUT_UNCOVERED", loc,
                    f"{origin} dim{d}: grid dim {k} spans "
                    f"{igrid[k]} block(s) but the output needs {nblocks} "
                    f"— blocks [{igrid[k]}, {nblocks - 1}] never written",
                    suggestion="grow the grid dim to ceil(dim/block)",
                    data={"kernel": kid, "dim": d, "grid_dim": k,
                          "grid_size": igrid[k], "nblocks": nblocks}))
            continue
        g, pf = deps.get(ov, (set(), False))
        used |= g
        why = "data-dependent (prefetch)" if pf else "computed"
        assumes.append(f"{origin} dim{d}: {why} output index; "
                       "exactly-once coverage assumed")
    nontrivial = {d for d in range(n_grid) if igrid[d] > 1}
    unused = nontrivial - used
    used_nt = used & nontrivial
    if unused:
        if used_nt and min(unused) < max(used_nt):
            findings.append(Finding(
                Severity.WARNING, "KERNEL_OUT_OVERLAP", loc,
                f"{origin}: grid dim(s) {sorted(unused)} revisit the same "
                f"output block NON-consecutively (a used dim "
                f"{max(used_nt)} iterates inside them) — the "
                "accumulate-then-flush idiom cannot apply; later visits "
                "overwrite finished blocks",
                suggestion="move reduction dims innermost (last) in the "
                           "grid",
                data={"kernel": kid, "unused_dims": sorted(unused),
                      "used_dims": sorted(used_nt)}))
        else:
            assumes.append(
                f"{origin}: revisited over trailing grid dim(s) "
                f"{sorted(unused)}; accumulate-then-flush assumed")
    return findings


def _lint_kernel_body(eqn, kj, igrid, num_scratch, out_dtypes, kid,
                      loc) -> List[Finding]:
    findings: List[Finding] = []
    scratch_vars = list(kj.invars[len(kj.invars) - num_scratch:]) \
        if num_scratch else []
    ops: List[set] = [set() for _ in scratch_vars]
    refmap = {v: i for i, v in enumerate(scratch_vars)}
    dead_paths: List[str] = []
    lowp_dots: List[str] = []

    def walk(jaxpr, env, rmap, depth):
        if depth > 12:
            return
        for e in jaxpr.eqns:
            pn = e.primitive.name
            ins = [_read(env, a) for a in e.invars]
            if pn in ("get", "swap", "addupdate"):
                tgt = e.invars[0]
                if not _is_literal(tgt) and tgt in rmap:
                    ops[rmap[tgt]].add(
                        {"get": "r", "swap": "w", "addupdate": "acc"}[pn])
                for ov in e.outvars:
                    env[ov] = PREFETCH_TOP
                continue
            if pn == "cond":
                branches = e.params.get("branches", ())
                idx = ins[0] if ins else TOP
                if (igrid is not None and idx.singleton and idx.lo == 0
                        and len(branches) >= 2):
                    live = [getattr(b, "jaxpr", b) for b in branches[1:]]
                    if any(b.eqns for b in live):
                        dead_paths.append(
                            "pl.when predicate statically false for every "
                            "grid cell")
                for b in branches:
                    bj = getattr(b, "jaxpr", b)
                    sub_env, sub_rmap = {}, {}
                    for bv, av in zip(bj.invars, e.invars[1:]):
                        if not _is_literal(av) and av in rmap:
                            sub_rmap[bv] = rmap[av]
                        sub_env[bv] = _read(env, av)
                    walk(bj, sub_env, sub_rmap, depth + 1)
                continue
            if pn == "dot_general":
                ldt = _dtype_name(getattr(e.invars[0].aval, "dtype", ""))
                odt = _dtype_name(getattr(e.outvars[0].aval, "dtype", ""))
                if ldt in _LOW_FLOATS and odt in _LOW_FLOATS:
                    lowp_dots.append(f"{ldt} dot accumulating in {odt}")
            outs = _apply_prim(pn, e.params, ins, igrid)
            if outs is not None:
                for ov, iv in zip(e.outvars, outs):
                    env[ov] = iv
            for _label, sub, _w in sub_jaxprs(e):
                sj = getattr(sub, "jaxpr", sub)
                sub_env, sub_rmap = {}, {}
                for bv, av in zip(sj.invars, e.invars):
                    if not _is_literal(av) and av in rmap:
                        sub_rmap[bv] = rmap[av]
                    sub_env[bv] = _read(env, av)
                walk(sj, sub_env, sub_rmap, depth + 1)

    walk(kj, {}, refmap, 0)

    for msg in dead_paths[:4]:
        findings.append(Finding(
            Severity.WARNING, "KERNEL_DEAD_GRID_CELL", loc,
            f"{msg} — the guarded body never runs on any of the "
            f"{int(np.prod(igrid or [1]))} grid cell(s)",
            suggestion="drop the pl.when or fix its predicate",
            data={"kernel": kid, "grid": list(igrid or ())}))
    for msg in lowp_dots[:4]:
        findings.append(Finding(
            Severity.WARNING, "KERNEL_LOWP_ACCUM", loc,
            f"{msg} — partial products lose mantissa before the reduce",
            suggestion="pass preferred_element_type=jnp.float32 to the dot",
            data={"kernel": kid}))
    for i, v in enumerate(scratch_vars):
        dt = _dtype_name(getattr(v.aval, "dtype", ""))
        if dt in _LOW_FLOATS and ("acc" in ops[i]
                                  or {"r", "w"} <= ops[i]):
            findings.append(Finding(
                Severity.WARNING, "KERNEL_LOWP_ACCUM", loc,
                f"scratch[{i}] is {dt} and is read AND written — a "
                "running sum accumulating below f32",
                suggestion="allocate the accumulator as f32 scratch and "
                           "cast on the final flush",
                data={"kernel": kid, "scratch": i, "dtype": dt}))
    out_f = [np.dtype(d) for d in out_dtypes if _is_float(d)]
    scr_f = [np.dtype(getattr(v.aval, "dtype", "O"))
             for v in scratch_vars
             if _is_float(getattr(v.aval, "dtype", None))]
    if out_f and scr_f:
        smin = min(scr_f, key=lambda d: d.itemsize)
        omax = max(out_f, key=lambda d: d.itemsize)
        if smin.itemsize < omax.itemsize:
            findings.append(Finding(
                Severity.WARNING, "KERNEL_DTYPE_MISMATCH", loc,
                f"float scratch {smin.name} is narrower than the "
                f"{omax.name} output it feeds — the extra output "
                "precision is laundered, not computed",
                suggestion="widen the scratch dtype to the output dtype",
                data={"kernel": kid, "scratch_dtype": smin.name,
                      "out_dtype": omax.name}))
    return findings


# ---------------------------------------------------------------------------
# surfaces: the registered checker + the standalone shipped-kernel sweep
# ---------------------------------------------------------------------------


@register_checker("kernellint")
def check_pallas_kernels(ctx: CheckContext):
    """Tier-6 registered checker: walks every pallas_call eqn (pjit/scan
    included — iter_eqns recurses; only the pallas body itself is opaque
    to the OTHER tiers).  Running inside analyze_jaxpr means the rewrite
    tier's re-lint gate rejects generated kernels that fail kernellint."""
    for eqn, path, _w in iter_eqns(ctx.closed_jaxpr):
        if eqn.primitive.name == "pallas_call":
            yield from lint_pallas_eqn(eqn, path, ctx)


def _t_flash():
    import jax
    import jax.numpy as jnp

    from ..kernels.pallas_attention import flash_attention_pallas

    B, S, Hq, Hkv, D = 1, 256, 2, 1, 64
    q = jnp.zeros((B, S, Hq, D), jnp.float32)
    k = jnp.zeros((B, S, Hkv, D), jnp.float32)
    v = jnp.zeros((B, S, Hkv, D), jnp.float32)

    def loss(q, k, v):
        return flash_attention_pallas(q, k, v, causal=True).sum()

    return jax.make_jaxpr(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)


def _t_gmm():
    import jax
    import jax.numpy as jnp

    from ..kernels.pallas_grouped_matmul import grouped_matmul

    lhs = jnp.zeros((256, 128), jnp.float32)
    rhs = jnp.zeros((2, 128, 128), jnp.float32)
    gs = jnp.array([128, 128], jnp.int32)

    def loss(lhs, rhs):
        return grouped_matmul(lhs, rhs, gs, impl="interpret").sum()

    return jax.make_jaxpr(jax.grad(loss, argnums=(0, 1)))(lhs, rhs)


def _t_ragged():
    import jax
    import jax.numpy as jnp

    from ..kernels.pallas_ragged_attention import ragged_attention_pallas

    T, Hq, Hkv, D, ps = 8, 2, 1, 128, 4
    q = jnp.zeros((T, Hq, D), jnp.float32)
    kp = jnp.zeros((4, ps, Hkv, D), jnp.float32)
    vp = jnp.zeros_like(kp)
    span_pt = jnp.array([[0, 1], [2, 3]], jnp.int32)
    block_seq = jnp.array([0, 1], jnp.int32)
    block_qpos = jnp.array([0, 0], jnp.int32)
    span_len = jnp.array([4, 4], jnp.int32)
    ctx_len = jnp.array([8, 8], jnp.int32)
    return jax.make_jaxpr(
        lambda *a: ragged_attention_pallas(*a, interpret=True))(
            q, kp, vp, span_pt, block_seq, block_qpos, span_len, ctx_len)


def _t_paged():
    import jax
    import jax.numpy as jnp

    from ..kernels.pallas_paged_attention import paged_attention_pallas

    q = jnp.zeros((2, 2, 128), jnp.float32)
    kp = jnp.zeros((4, 4, 1, 128), jnp.float32)
    vp = jnp.zeros_like(kp)
    pt = jnp.array([[0, 1], [2, 3]], jnp.int32)
    lengths = jnp.array([8, 6], jnp.int32)
    return jax.make_jaxpr(
        lambda *a: paged_attention_pallas(*a, interpret=True))(
            q, kp, vp, pt, lengths)


def _t_norm():
    import jax
    import jax.numpy as jnp

    from ..kernels.pallas_norm import rms_norm_pallas

    x = jnp.zeros((64, 128), jnp.float32)
    w = jnp.ones((128,), jnp.float32)
    return jax.make_jaxpr(rms_norm_pallas)(x, w)


def _t_adaln():
    import jax
    import jax.numpy as jnp

    from ..kernels.pallas_norm import adaln_modulate_pallas

    x = jnp.zeros((2, 256, 128), jnp.float32)
    shift = jnp.zeros((2, 128), jnp.float32)
    scale = jnp.zeros((2, 128), jnp.float32)
    return jax.make_jaxpr(adaln_modulate_pallas)(x, shift, scale)


def _t_decode():
    import jax
    import jax.numpy as jnp

    from ..kernels.pallas_decode_step import fused_decode_step_pallas

    sel = jnp.zeros((8, 128), jnp.float32)
    head = jnp.zeros((128, 256), jnp.float32)
    key = jax.random.PRNGKey(0)
    return jax.make_jaxpr(
        lambda s, h, k: fused_decode_step_pallas(
            s, h, k, temperature=0.0, interpret=True))(sel, head, key)


def _t_chain():
    import jax
    import jax.numpy as jnp

    from ..kernels.pallas_fused_chain import fused_elementwise_chain

    fn = fused_elementwise_chain(
        lambda a, b: jnp.tanh(a) * b + a, n_ops=3, mode="pallas",
        site="kernellint")
    x = jnp.zeros((512, 128), jnp.float32)
    y = jnp.ones((512, 128), jnp.float32)
    return jax.make_jaxpr(fn)(x, y)


def shipped_kernel_targets() -> Dict[str, Callable[[], Any]]:
    """name -> zero-arg builder returning a traced ClosedJaxpr containing
    the shipped Pallas kernels.  Grad traces pull in the backward kernels
    (_dq/_dkv via flash, _tgmm via grouped_matmul); `fused_chain` is a
    GENERATED kernel — the same emission path the rewrite tier uses."""
    return {
        "flash_attention": _t_flash,
        "grouped_matmul": _t_gmm,
        "ragged_attention": _t_ragged,
        "paged_attention": _t_paged,
        "rms_norm": _t_norm,
        "adaln": _t_adaln,
        "decode_step": _t_decode,
        "fused_chain": _t_chain,
    }


def analyze_kernels(targets: Optional[Sequence[str]] = None,
                    options: Optional[dict] = None,
                    suppress: Sequence[str] = (),
                    config: Optional[dict] = None) -> Dict[str, Report]:
    """Standalone tier-6 sweep: trace each shipped kernel target and lint
    every pallas_call found.  Returns {kernel_id: Report}, aggregated
    across targets (one kernel reached from several traces reports
    once per reaching eqn)."""
    builders = shipped_kernel_targets()
    names = list(targets) if targets else list(builders)
    unknown = sorted(set(names) - set(builders))
    if unknown:
        raise ValueError(f"unknown kernel target(s) {unknown}; "
                         f"available: {sorted(builders)}")
    ctx = CheckContext(closed_jaxpr=None, options=dict(options or {}))
    per: Dict[str, List[Finding]] = {}
    for tname in names:
        closed = builders[tname]()
        for eqn, path, _w in iter_eqns(closed):
            if eqn.primitive.name != "pallas_call":
                continue
            per.setdefault(kernel_id(eqn), []).extend(
                lint_pallas_eqn(eqn, (tname,) + tuple(path), ctx))
    return {
        kid: finalize_findings(list(fs), ["kernellint"], ctx, suppress,
                               config)
        for kid, fs in sorted(per.items())
    }
