"""`--fix`: turn findings into concrete patch suggestions.

The Graph Doctor diagnoses; this module prescribes.  `suggest_fixes`
reads the structured `Finding.data` the checkers attach (exact argnums,
byte counts, suggested bucket menus) and emits `Patch` objects whose
`diff` is a unified-diff-STYLE snippet — not a literal patch against a
file (the lint runs on traced functions, not source text), but the exact
edit to make, named precisely enough to paste:

    DONATION_MISSING        the donate_argnums=(...) tuple to add, with
                            the exact argnums
    SHARD_REPLICATED        the with_sharding_constraint insertion point
    DTYPE_F64_PROMOTION /   the dtype-cast site (astype / jnp.float32
    DTYPE_WEAK_F64 / INPUT  wrapper)
    RECOMPILE_CONST_CAPTURE hoist-to-argument rewrite
    RECOMPILE_BUCKET_MISS   the prefill_buckets menu edit
    LAYOUT_TRANSPOSE /      HLO-tier textual suggestions (no jaxpr eqn to
    COLLECTIVE_SEQ          edit; same Patch schema so --json consumers
                            see one shape for both tiers)

`tools/graphlint.py --fix` prints these after the findings.  Patches
dedupe by (kind, target) — linting one fn under two entry points emits
ONE donate_argnums patch — and carry a stable `patch_id` in --json.
Since the rewrite tier (`analysis/rewrite.py`, `--fix --apply`), the
donation/dtype/dead-code/fusion families are also APPLIED mechanically
at the jaxpr level with a verification gate; the suggestions here remain
the human-readable source edit.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Dict, List

from .core import Finding, Report, fmt_bytes

__all__ = ["Patch", "suggest_fixes", "format_patches"]


@dataclasses.dataclass
class Patch:
    """One suggested edit: which findings it settles, and the edit."""

    title: str
    codes: List[str]
    eqn_paths: List[str]
    diff: str                   # unified-diff-style snippet
    note: str = ""
    target: str = ""            # identity when the title is generic

    @property
    def kind(self) -> str:
        """The patch family — its primary finding code."""
        return self.codes[0] if self.codes else "?"

    @property
    def patch_id(self) -> str:
        """Stable id over (kind, target): the same fn linted under two
        entry points dedupes to ONE patch, and --json consumers can key
        on the id across runs.  Builders whose title names the edit
        (donation) leave `target` empty; generic-title builders set it
        to the site/edit so DISTINCT sites never collapse."""
        return hashlib.sha1(
            f"{self.kind}|{self.target or self.title}".encode()
        ).hexdigest()[:12]

    def to_dict(self) -> dict:
        return {"patch_id": self.patch_id, "kind": self.kind,
                "title": self.title, "codes": list(self.codes),
                "eqn_paths": list(self.eqn_paths), "diff": self.diff,
                "note": self.note}

    def __str__(self):
        lines = [f"fix: {self.title}"]
        if self.note:
            lines.append(f"  {self.note}")
        lines += ["  " + ln for ln in self.diff.splitlines()]
        return "\n".join(lines)


def _donation_patches(findings: List[Finding]) -> List[Patch]:
    """Group DONATION_MISSING per pjit EQN (eqn_path disambiguates two
    jitted fns that share a __name__); one patch naming ALL argnums
    (donate_argnums is one tuple per jit call)."""
    by_fn: Dict[tuple, List[Finding]] = {}
    for f in findings:
        key = (f.eqn_path, str(f.data.get("jit_name", "?")))
        by_fn.setdefault(key, []).append(f)
    out = []
    for (_path, fn_name), fs in by_fn.items():
        argnums = sorted({f.data["argnum"] for f in fs
                          if f.data.get("argnum") is not None})
        args = ", ".join(f.data.get("arg", "?") for f in fs)
        nbytes = sum(int(f.data.get("bytes", 0)) for f in fs)
        if not argnums:
            continue
        tup = "(" + ", ".join(str(a) for a in argnums) + ",)" \
            if len(argnums) == 1 else \
            "(" + ", ".join(str(a) for a in argnums) + ")"
        diff = (f"--- {fn_name} (copies {fmt_bytes(nbytes)}/step)\n"
                f"+++ {fn_name} (updates in place)\n"
                f"-@jax.jit\n"
                f"+@functools.partial(jax.jit, donate_argnums={tup})\n"
                f" def {fn_name}(...):")
        out.append(Patch(
            title=f"donate argnums {tup} of {fn_name!r}",
            codes=["DONATION_MISSING"],
            eqn_paths=[f.eqn_path for f in fs], diff=diff,
            note=f"read-write args {args} aval-match outputs; donation "
                 "lets XLA reuse their buffers instead of copying"))
    return out


def _pspec_repr(spec) -> str:
    entries = ", ".join(
        repr(tuple(e)) if isinstance(e, (list, tuple)) else repr(e)
        if e is not None else "None" for e in spec)
    return f"P({entries})"


def _shard_patch(f: Finding) -> Patch:
    shape = f.message.split(" ", 1)[0]
    spec = f.data.get("spec")
    target = f.data.get("target") or f.eqn_path
    if spec is not None:
        # the SPMD tier computed the exact spec: emit it verbatim (the
        # same spec the shard_constraint rewrite pass injects)
        diff = (f" big = <the value created at {target}>\n"
                "+big = jax.lax.with_sharding_constraint(\n"
                f"+    big, NamedSharding(mesh, {_pspec_repr(spec)}))")
        note = (f"dim {f.data.get('dim')} divides mesh axis "
                f"{f.data.get('axis')!r}; graphlint --fix --apply "
                "injects (and verifies) this constraint mechanically")
    else:
        diff = (" big = <the value created at the flagged eqn>\n"
                "+big = jax.lax.with_sharding_constraint(\n"
                "+    big, NamedSharding(mesh, P('data', None)))  "
                "# pick the axis that matches its producers")
        note = ("any sharded PartitionSpec reaching the value stops GSPMD "
                "from replicating it on every device")
    return Patch(
        title=f"shard the replicated {shape} at {f.eqn_path}",
        codes=[f.code], eqn_paths=[f.eqn_path], diff=diff, note=note,
        target=target)


def _reshard_patch(f: Finding) -> Patch:
    """SPMD tier: an eqn boundary whose operand/result specs disagree —
    the patch names the implied collective and both layouts."""
    kind = str(f.data.get("collective", "all_gather"))
    src = f.data.get("src_spec")
    dst = f.data.get("dst_spec")
    lay = (f"-# producer layout {_pspec_repr(src)} vs consumer "
           f"{_pspec_repr(dst)}\n" if src is not None and dst is not None
           else "")
    diff = (lay
            + f"-y = <resharded here: implied {kind} of "
            f"{fmt_bytes(int(f.data.get('bytes', 0)))}>\n"
            "+# align the constraint/in_sharding with the producer's "
            "layout,\n"
            "+# or move the reshard off the per-step hot path")
    return Patch(
        title=f"eliminate the {kind} at {f.eqn_path}",
        codes=[f.code], eqn_paths=[f.eqn_path], diff=diff,
        note="predicted by the SPMD propagation tier (analysis/spmd.py); "
             "see COLLECTIVE_BOUND for what it costs per step",
        target=f.eqn_path)


def _dtype_patch(f: Finding) -> Patch:
    if f.code == "DTYPE_WEAK_F64":
        diff = ("-y = x * 2.0                  # Python float leaks f64\n"
                "+y = x * jnp.float32(2.0)")
        note = "wrap leaked Python scalars in the intended dtype"
    elif f.code == "DTYPE_F64_INPUT":
        diff = ("-fn(x_f64)\n"
                "+fn(x_f64.astype(jnp.float32))  # cast at the boundary")
        note = "TPUs emulate f64 in software; cast inputs unless f64 is "\
               "numerically required"
    else:
        diff = ("-wide = op(a, b)              # promotes to float64\n"
                "+wide = op(a, b.astype(jnp.float32))")
        note = "pin the f64 operand (np scalar / np.array default dtype /"\
               " explicit astype) at the eqn path above"
    return Patch(title=f"cast at {f.eqn_path} ({f.code})", codes=[f.code],
                 eqn_paths=[f.eqn_path], diff=diff, note=note)


def _const_capture_patch(f: Finding) -> Patch:
    diff = ("-TABLE = jnp.asarray(...)        # captured: baked into the\n"
            "-def fn(x): return x @ TABLE     # executable at trace time\n"
            "+def fn(x, table): return x @ table  # jit caches shape/dtype")
    return Patch(
        title="pass the captured constant as an argument",
        codes=[f.code], eqn_paths=[f.eqn_path], diff=diff,
        note="a new value then reuses the compiled program instead of "
             "retracing (and the executable stops embedding the data)",
        target=f.eqn_path)


def _bucket_patch(f: Finding) -> Patch:
    # DEPRECATED alongside lint_bucket_menu: LLMEngine's unified ragged
    # step retired the menu, but saved reports carrying the code must
    # still render a patch
    menu = f.data.get("menu")
    suggested = f.data.get("suggested_menu")
    if suggested is None:
        diff = f"+prefill_buckets = {menu} + [<bucket covering the " \
               f"length in the finding>]"
    else:
        diff = (f"-prefill_buckets = {menu}\n"
                f"+prefill_buckets = {suggested}")
    return Patch(
        title="edit the prefill bucket menu",
        codes=[f.code], eqn_paths=[f.eqn_path], diff=diff,
        note="edit the call site's bucket menu and re-run "
             "lint_bucket_menu to confirm the straddle is gone (LLMEngine "
             "itself no longer buckets: its ragged step is one signature)",
        target=diff)


def _layout_patch(f: Finding) -> Patch:
    """HLO tier: a materialized transpose/relayout copy.  No jaxpr eqn
    to edit — the patch is the dims reorder at the op_name's source."""
    op_name = str(f.data.get("op_name") or f.eqn_path)
    if f.data.get("user_written"):
        diff = ("-out = x.transpose(...) @ w        # materialized shuffle\n"
                "+out = jnp.einsum('...ij,jk->...ik', x, w)  "
                "# let dot dims absorb it")
        note = ("a user-written transpose survived compilation at "
                f"{op_name}: reorder the einsum/dot dims so it folds "
                "into dimension numbers")
    else:
        diff = (" # two consumers want different physical layouts of the\n"
                " # same value; keep it in ONE layout end-to-end, e.g.\n"
                "+x = jax.lax.with_sharding_constraint(x, ...)  "
                "# or restructure the second consumer")
        note = (f"compiler-inserted relayout at {op_name} "
                f"({fmt_bytes(int(f.data.get('bytes', 0)))} through HBM)")
    return Patch(title=f"eliminate the relayout at {op_name}",
                 codes=[f.code], eqn_paths=[f.eqn_path], diff=diff,
                 note=note)


def _collective_patch(f: Finding) -> Patch:
    """HLO tier: independent same-group collectives that could combine."""
    kind = str(f.data.get("kind", "all_reduce"))
    n = int(f.data.get("count", 2))
    api = {"all_reduce": "jax.lax.psum",
           "all_gather": "jax.lax.all_gather",
           "reduce_scatter": "jax.lax.psum_scatter"}.get(kind, "jax.lax.psum")
    diff = (f"-a = {api}(x, axis); b = {api}(y, axis)   # {n} launches\n"
            f"+a, b = {api}((x, y), axis)               # one combined op")
    return Patch(
        title=f"combine {n} {kind} ops into one",
        codes=[f.code], eqn_paths=[f.eqn_path], diff=diff,
        note=f"{fmt_bytes(int(f.data.get('bytes', 0)))} total moves once "
             "instead of paying per-op latency",
        target=f.eqn_path)


def _dedupe(patches: List[Patch]) -> List[Patch]:
    """Drop identical (kind, target) patches — the same fn linted under
    two entry points suggests the same donate_argnums tuple twice."""
    seen: Dict[str, Patch] = {}
    out = []
    for p in patches:
        prev = seen.get(p.patch_id)
        if prev is not None:
            # keep one patch; remember the extra eqn_paths it covers
            prev.eqn_paths += [e for e in p.eqn_paths
                               if e not in prev.eqn_paths]
            continue
        seen[p.patch_id] = p
        out.append(p)
    return out


def suggest_fixes(report: Report) -> List[Patch]:
    """Patches for every fixable finding in the report (BOTH tiers —
    jaxpr and HLO findings share this one schema), most impactful first
    (donation > sharding > dtype > fusion-adjacent HLO > recompile),
    deduped by (kind, target) with a stable `patch_id`."""
    fixable = [f for f in report]
    patches: List[Patch] = []
    patches += _donation_patches(
        [f for f in fixable if f.code == "DONATION_MISSING"])
    patches += [_shard_patch(f) for f in fixable
                if f.code == "SHARD_REPLICATED"]
    patches += [_reshard_patch(f) for f in fixable
                if f.code == "SHARD_RESHARD"]
    patches += [_dtype_patch(f) for f in fixable
                if f.code.startswith("DTYPE_")]
    patches += [_layout_patch(f) for f in fixable
                if f.code == "LAYOUT_TRANSPOSE"]
    patches += [_collective_patch(f) for f in fixable
                if f.code == "COLLECTIVE_SEQ"]
    patches += [_const_capture_patch(f) for f in fixable
                if f.code == "RECOMPILE_CONST_CAPTURE"]
    patches += [_bucket_patch(f) for f in fixable
                if f.code == "RECOMPILE_BUCKET_MISS"]
    return _dedupe(patches)


def format_patches(patches: List[Patch]) -> str:
    if not patches:
        return "no auto-fixable findings"
    return "\n\n".join(str(p) for p in patches)
