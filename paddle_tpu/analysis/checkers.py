"""The shipped Graph Doctor checkers.

Six checker families over a ClosedJaxpr (see core.iter_eqns for the
recursive walk).  Severity policy: WARNING = costs real TPU time/HBM or
risks silent wrong numerics; INFO = worth knowing, fine to ship.

  dtype_promotion    DTYPE_F64_PROMOTION, DTYPE_WEAK_F64, DTYPE_F64_INPUT
  donation           DONATION_MISSING
  sharding           SHARD_REPLICATED, SHARD_GAP
  recompile_hazard   RECOMPILE_CONST_CAPTURE, RECOMPILE_SHAPE_POLY,
                     RECOMPILE_MUTABLE_CLOSURE
  cost               COST_SUMMARY, COST_HOTSPOT
  dead_code          DEAD_CODE, CONST_SUBGRAPH
"""

from __future__ import annotations

import functools
import re
from typing import List

import numpy as np

import jax

from . import cost as cost_lib
from .core import (
    CheckContext, Finding, Severity, aval_bytes, fmt_aval, fmt_bytes,
    format_path, is_array_var, iter_eqns, iter_jaxprs, register_checker,
    sub_jaxprs, _as_open,
)

_WIDE_FLOATS = ("float64", "complex128")


def _dtype(v) -> str:
    return str(getattr(getattr(v, "aval", None), "dtype", ""))


def _weak(v) -> bool:
    return bool(getattr(getattr(v, "aval", None), "weak_type", False))


# ---------------------------------------------------------------------------
# 1. dtype promotion: silent f64/c128 creep (x64 is globally ON in this
#    package for reference dtype parity, so one leaked np.float64 scalar
#    doubles the width of everything downstream of it)
# ---------------------------------------------------------------------------


@register_checker("dtype_promotion")
def check_dtype_promotion(ctx: CheckContext):
    findings: List[Finding] = []
    jaxpr = ctx.closed_jaxpr.jaxpr
    for i, v in enumerate(jaxpr.invars):
        if _dtype(v) in _WIDE_FLOATS:
            findings.append(Finding(
                Severity.INFO, "DTYPE_F64_INPUT", "<top>",
                f"input {ctx.invar_name(v)} is {_dtype(v)} "
                f"({fmt_aval(v.aval)}) — TPUs have no f64 units; every op "
                "touching it emulates in software",
                "cast to float32/bfloat16 at the boundary unless f64 is "
                "numerically required"))
    for eqn, path, _w in iter_eqns(ctx.closed_jaxpr):
        for ov in eqn.outvars:
            dt = _dtype(ov)
            if dt not in _WIDE_FLOATS:
                continue
            in_dts = [_dtype(v) for v in eqn.invars if _dtype(v)]
            # the PROMOTION POINT: a wide output none of whose inputs was
            # already wide-and-strong.  Downstream wide eqns inherit a wide
            # input and stay silent — one finding per leak, not per use.
            strong_wide_in = any(
                d in _WIDE_FLOATS and not _weak(v)
                for d, v in zip(in_dts, [v for v in eqn.invars if _dtype(v)]))
            if strong_wide_in:
                continue
            if _weak(ov):
                findings.append(Finding(
                    Severity.INFO, "DTYPE_WEAK_F64", format_path(path, eqn),
                    f"weak-typed {dt} scalar (a Python number leaked into "
                    f"the graph) at {eqn.primitive.name}",
                    "wrap the scalar in jnp.float32(...) or an array of the "
                    "intended dtype"))
                continue
            narrow = [d for d in in_dts if d not in _WIDE_FLOATS]
            findings.append(Finding(
                Severity.WARNING, "DTYPE_F64_PROMOTION",
                format_path(path, eqn),
                f"{eqn.primitive.name} promotes "
                f"{'/'.join(sorted(set(narrow))) or 'constants'} -> {dt} "
                f"({fmt_aval(ov.aval)})",
                "find the f64 operand (np.float64 scalar, np.array default "
                "dtype, or an explicit astype) and pin it to float32"))
    return findings


# ---------------------------------------------------------------------------
# 2. donation: large read-write args to jitted regions that are not donated
#    get COPIED every step (params, optimizer state, KV pools)
# ---------------------------------------------------------------------------


def _aval_key(v):
    a = v.aval
    return (tuple(a.shape), str(a.dtype))


@register_checker("donation")
def check_donation(ctx: CheckContext):
    findings: List[Finding] = []
    thresh = ctx.opt("donation_min_bytes")
    for eqn, path, _w in iter_eqns(ctx.closed_jaxpr):
        if eqn.primitive.name != "pjit":
            continue
        donated = eqn.params.get("donated_invars")
        if donated is None:
            continue
        out_pool: dict = {}
        for ov in eqn.outvars:
            if is_array_var(ov):
                k = _aval_key(ov)
                out_pool[k] = out_pool.get(k, 0) + 1

        def take(k):
            if out_pool.get(k, 0) > 0:
                out_pool[k] -= 1
                return True
            return False

        # donated invars claim their matching outputs first: a donated
        # params arg must not leave its aval free to accuse a twin
        undonated = []
        for v, don in zip(eqn.invars, donated):
            if not is_array_var(v):
                continue
            if don:
                take(_aval_key(v))
            else:
                undonated.append(v)
        for v in undonated:
            if aval_bytes(v.aval) < thresh:
                continue
            if take(_aval_key(v)):
                label = ctx.invar_name(v)
                m = re.match(r"args\[(\d+)\]", label)
                findings.append(Finding(
                    Severity.WARNING, "DONATION_MISSING",
                    format_path(path, eqn),
                    f"jitted fn {eqn.params.get('name', '?')!r}: arg "
                    f"{label} ({fmt_aval(v.aval)}, "
                    f"{fmt_bytes(aval_bytes(v.aval))}) matches an output "
                    "but is not donated — XLA keeps both buffers live and "
                    "copies the update",
                    "add its position to donate_argnums in jax.jit "
                    "(read-write step args: params, opt state, KV pools)",
                    data={"argnum": int(m.group(1)) if m else None,
                          "arg": label,
                          "jit_name": str(eqn.params.get("name", "?")),
                          "bytes": aval_bytes(v.aval)}))
    return findings


# ---------------------------------------------------------------------------
# 3. sharding: under a >1-device mesh, big intermediates never reached by
#    any sharded value (or any with_sharding_constraint) end up replicated
#    on every device; replicating an already-sharded value is an all-gather
# ---------------------------------------------------------------------------


def _sharding_is_sharded(s) -> bool:
    try:
        return not s.is_fully_replicated
    except Exception:  # noqa: BLE001 — UnspecifiedValue / AUTO
        return False


def _arg_taint(ctx: CheckContext) -> List[bool]:
    leaves = jax.tree_util.tree_leaves((ctx.args, ctx.kwargs))
    taint = []
    for x in leaves:
        s = getattr(x, "sharding", None)
        taint.append(bool(s is not None and _sharding_is_sharded(s)))
    invars = ctx.closed_jaxpr.jaxpr.invars
    if len(taint) != len(invars):       # static args / captured consts
        taint = (taint + [False] * len(invars))[:len(invars)]
    return taint


# eqns GSPMD propagates a sharding BACKWARD through cheaply (a constraint
# on a cast/transpose of x effectively shards x too)
_BWD_PROP_PRIMS = frozenset({
    "convert_element_type", "transpose", "reshape", "copy", "squeeze",
    "expand_dims", "sharding_constraint",
})


@register_checker("sharding")
def check_sharding(ctx: CheckContext):
    mesh = ctx.mesh
    if mesh is None or getattr(mesh, "size", 1) <= 1:
        return []
    # the mesh-aware SPMD tier (analysis/spmd.py) subsumes this taint
    # walk — spec-precise SHARD_REPLICATED (exact PartitionSpec) and
    # priced SHARD_GAP/SHARD_RESHARD — so when it runs IN THIS CALL the
    # taint walk stands down rather than double-reporting the same
    # sites; an explicit checkers=["sharding"] still gets it.
    # `legacy_sharding_taint=True` forces the taint walk back on.
    if "spmd" in ctx.active_checkers \
            and not ctx.opt("legacy_sharding_taint"):
        return []
    thresh = ctx.opt("sharding_min_bytes")
    findings: List[Finding] = []

    def walk(jaxpr, invar_taint, path) -> List[bool]:
        jaxpr = _as_open(jaxpr)
        tainted = {v for v, t in zip(jaxpr.invars, invar_taint) if t}
        big_repl = set()            # big replicated vars seen (for dedup)
        deferred = []               # (var, eqn, path) candidate reports

        def is_t(v):
            return is_array_var(v) and v in tainted

        for eqn in jaxpr.eqns:
            in_t = any(is_t(v) for v in eqn.invars)
            prim = eqn.primitive.name
            if prim == "sharding_constraint":
                sh = eqn.params.get("sharding")
                if _sharding_is_sharded(sh):
                    tainted.update(eqn.outvars)
                elif in_t and aval_bytes(eqn.outvars[0].aval) >= thresh:
                    findings.append(Finding(
                        Severity.WARNING, "SHARD_GAP",
                        format_path(path, eqn),
                        "with_sharding_constraint re-replicates a sharded "
                        f"{fmt_aval(eqn.outvars[0].aval)} "
                        f"({fmt_bytes(aval_bytes(eqn.outvars[0].aval))}) — "
                        "an implicit all-gather on every device",
                        "constrain to a sharded PartitionSpec, or drop the "
                        "constraint and let GSPMD propagate"))
                continue
            if prim == "pjit":
                inner = eqn.params["jaxpr"]
                in_sh = eqn.params.get("in_shardings") or ()
                sub_in = []
                for i, v in enumerate(eqn.invars):
                    t = is_t(v)
                    if i < len(in_sh) and _sharding_is_sharded(in_sh[i]):
                        t = True
                    sub_in.append(t)
                out_t = walk(inner, sub_in,
                             path + (f"pjit:{eqn.params.get('name', '')}",))
                out_sh = eqn.params.get("out_shardings") or ()
                for i, ov in enumerate(eqn.outvars):
                    t = out_t[i] if i < len(out_t) else False
                    if i < len(out_sh) and _sharding_is_sharded(out_sh[i]):
                        t = True
                    if t:
                        tainted.add(ov)
            else:
                subs = list(sub_jaxprs(eqn))
                sub_out_t = False
                for label, sj, _w in subs:
                    oj = _as_open(sj)
                    ot = walk(oj, [in_t] * len(oj.invars),
                              path + (prim, label))
                    sub_out_t = sub_out_t or any(ot)
                if in_t or sub_out_t:
                    tainted.update(v for v in eqn.outvars if is_array_var(v))
            # candidate: big tensor no sharded value reaches.  Report only
            # the CREATION point (consumers of a flagged var stay silent)
            # and only after the backward pass below clears constraints
            # applied downstream (GSPMD propagates shardings backward too).
            inherits = any(v in big_repl for v in eqn.invars
                           if is_array_var(v))
            for ov in eqn.outvars:
                if not is_array_var(ov) or ov in tainted:
                    continue
                nb = aval_bytes(ov.aval)
                if nb >= thresh:
                    big_repl.add(ov)
                    if not inherits:
                        deferred.append((ov, eqn, path))
        # backward sweep: inputs of sharded sharding_constraints (and of
        # cheap view chains above them) count as sharded
        btaint = set()
        for eqn in reversed(jaxpr.eqns):
            prim = eqn.primitive.name
            if prim == "sharding_constraint" and _sharding_is_sharded(
                    eqn.params.get("sharding")):
                btaint.update(v for v in eqn.invars if is_array_var(v))
            elif prim in _BWD_PROP_PRIMS and any(
                    v in btaint for v in eqn.outvars if is_array_var(v)):
                btaint.update(v for v in eqn.invars if is_array_var(v))
        for ov, eqn, p in deferred:
            if ov in btaint:
                continue
            findings.append(Finding(
                Severity.WARNING, "SHARD_REPLICATED",
                format_path(p, eqn),
                f"{fmt_aval(ov.aval)} ({fmt_bytes(aval_bytes(ov.aval))}) "
                "is reached by no sharded input or "
                "with_sharding_constraint — GSPMD will replicate it on "
                f"all {mesh.size} devices",
                "add jax.lax.with_sharding_constraint with a sharded "
                "PartitionSpec, or derive it from a sharded value"))
        return [is_t(v) or v in btaint if is_array_var(v) else False
                for v in jaxpr.outvars]

    walk(ctx.closed_jaxpr.jaxpr, _arg_taint(ctx), ())
    return findings


# ---------------------------------------------------------------------------
# 4. recompile hazards: captured array constants (baked into the program),
#    mutable Python closures (silently NOT retraced), and shape-polymorphic
#    call sites (one compile per distinct signature)
# ---------------------------------------------------------------------------


def _unwrap(fn):
    seen = 0
    while seen < 8:
        seen += 1
        if isinstance(fn, functools.partial):
            fn = fn.func
            continue
        inner = getattr(fn, "__wrapped__", None)
        if inner is not None and inner is not fn:
            fn = inner
            continue
        break
    return fn


@register_checker("recompile_hazard")
def check_recompile_hazard(ctx: CheckContext):
    findings: List[Finding] = []
    thresh = ctx.opt("const_capture_min_bytes")
    for c in ctx.closed_jaxpr.consts:
        nb = getattr(c, "nbytes", 0) or 0
        if nb >= thresh:
            findings.append(Finding(
                Severity.WARNING, "RECOMPILE_CONST_CAPTURE", "<top>",
                f"captured array constant {np.shape(c)} "
                f"{np.result_type(c)} ({fmt_bytes(int(nb))}) is baked into "
                "the compiled program — a new value means a new trace, and "
                "the constant bloats every executable that embeds it",
                "pass it as an argument (jit caches on shape/dtype, not "
                "value) or construct it inside the function"))
    fn = _unwrap(ctx.fn) if ctx.fn is not None else None
    closure = getattr(fn, "__closure__", None) or ()
    for cell in closure:
        try:
            val = cell.cell_contents
        except ValueError:
            continue
        if isinstance(val, (list, dict, set, bytearray)):
            findings.append(Finding(
                Severity.INFO, "RECOMPILE_MUTABLE_CLOSURE", "<top>",
                f"closure captures a mutable {type(val).__name__} — jit "
                "traced its current contents; later mutation will NOT "
                "retrigger tracing (silently stale) ",
                "capture immutable values, or pass it as a (static) "
                "argument"))
    sigs = {s for s in ctx.probe_signatures}
    # expected_signatures: a deliberate compile menu (the engine's prefill
    # buckets) registers its SIZE here — the gate is count-based, so probe
    # the full menu alongside any real call sites: a signature outside the
    # menu then pushes the distinct count past expected and fires
    expected = max(1, int(ctx.opt("expected_signatures") or 1))
    if len(sigs) > expected:
        findings.append(Finding(
            Severity.WARNING, "RECOMPILE_SHAPE_POLY", "<top>",
            f"compile-cache probe: {len(sigs)} distinct arg signatures "
            f"across {len(ctx.probe_signatures)} call sites"
            + (f" (menu allows {expected})" if expected > 1 else "")
            + " — each one compiles (and caches) a separate executable",
            "pad/bucket dynamic dims to a fixed menu of shapes (the engine "
            "buckets prompt lengths to powers of two for exactly this)",
            data={"signatures": len(sigs), "expected": expected}))
    return findings


# ---------------------------------------------------------------------------
# 5. cost: top-k heaviest eqns (static FLOPs/bytes roll-up -> profiler)
# ---------------------------------------------------------------------------


@register_checker("cost")
def check_cost(ctx: CheckContext):
    top_k = ctx.opt("cost_top_k")
    est = cost_lib.estimate(ctx.closed_jaxpr, top_k=top_k)
    findings = [Finding(
        Severity.INFO, "COST_SUMMARY", "<top>",
        f"~{est['total_flops']:.3g} FLOPs, ~{fmt_bytes(est['total_bytes'])} "
        "operand traffic per call (static estimate, scan lengths included)",
        "profiler.static_cost(fn, *args) returns the same roll-up as data")]
    for c in est["top"]:
        if c["flops"] <= 0 and c["bytes"] <= 0:
            continue
        findings.append(Finding(
            Severity.INFO, "COST_HOTSPOT", c["path"],
            f"{c['primitive']}: ~{c['flops']:.3g} FLOPs, "
            f"{fmt_bytes(c['bytes'])}"
            + (f" (x{c['weight']} scan trips)" if c["weight"] > 1 else ""),
            ""))
    return findings


# ---------------------------------------------------------------------------
# 6. dead / constant subgraphs (jaxpr-level analog of static/passes.py's
#    dead_code_elimination + constant_folding record passes)
# ---------------------------------------------------------------------------

# value-creation prims that are trivially folded/streamed by XLA: a
# const-only zeros/iota is idiomatic, not a finding
_CREATION_PRIMS = frozenset({
    "broadcast_in_dim", "iota", "reshape", "convert_element_type",
    "transpose", "squeeze", "expand_dims", "concatenate", "slice",
    "broadcast", "copy", "device_put",
})


@register_checker("dead_code")
def check_dead_code(ctx: CheckContext):
    findings: List[Finding] = []
    const_thresh = ctx.opt("const_subgraph_min_bytes")
    for jaxpr, path, _w in iter_jaxprs(ctx.closed_jaxpr):
        # -- dead eqns: reverse liveness from this jaxpr's outvars ---------
        live = {v for v in jaxpr.outvars if is_array_var(v)}
        keep = [False] * len(jaxpr.eqns)
        for i in range(len(jaxpr.eqns) - 1, -1, -1):
            eqn = jaxpr.eqns[i]
            if eqn.effects or any(is_array_var(v) and v in live
                                  for v in eqn.outvars):
                keep[i] = True
                live.update(v for v in eqn.invars if is_array_var(v))
        for i, eqn in enumerate(jaxpr.eqns):
            if not keep[i]:
                out = (fmt_aval(eqn.outvars[0].aval) if eqn.outvars
                       else "(no outputs)")
                # cheap dead eqns (AD partial-eval routinely strands a few
                # small ops; XLA DCEs them for free) are INFO; dead eqns
                # doing real compute or holding real memory are WARNING
                fl = cost_lib.eqn_flops(eqn) + sum(
                    c["flops"] for sj in
                    (s for _l, s, _w in sub_jaxprs(eqn))
                    for c in cost_lib.per_eqn_costs(sj))
                nb = max((aval_bytes(v.aval) for v in eqn.outvars
                          if is_array_var(v)), default=0)
                heavy = (fl >= ctx.opt("dead_code_min_flops")
                         or nb >= ctx.opt("dead_code_min_bytes"))
                findings.append(Finding(
                    Severity.WARNING if heavy else Severity.INFO,
                    "DEAD_CODE", format_path(path, eqn),
                    f"{eqn.primitive.name} output {out} never reaches an "
                    "output — traced, compiled, and (until XLA DCE) "
                    "scheduled for nothing"
                    + (f" (~{fl:.3g} FLOPs)" if heavy and fl else ""),
                    "drop the computation, or return/consume its result"))
        # -- const subgraphs: forward taint from invars --------------------
        varying = {v for v in jaxpr.invars if is_array_var(v)}
        for i, eqn in enumerate(jaxpr.eqns):
            if not keep[i]:
                continue        # already reported as dead
            dep_varying = any(is_array_var(v) and v in varying
                              for v in eqn.invars)
            if dep_varying or eqn.effects:
                varying.update(v for v in eqn.outvars if is_array_var(v))
                continue
            # const-only eqn: flag when it does real compute or makes a
            # big buffer; pure creation prims are left to XLA folding
            prim = eqn.primitive.name
            out_nb = max((aval_bytes(v.aval) for v in eqn.outvars
                          if is_array_var(v)), default=0)
            heavy = prim in ("dot_general", "conv_general_dilated")
            if heavy or (out_nb >= const_thresh
                         and prim not in _CREATION_PRIMS):
                out = (fmt_aval(eqn.outvars[0].aval) if eqn.outvars
                       else "(no outputs)")
                findings.append(Finding(
                    Severity.INFO, "CONST_SUBGRAPH", format_path(path, eqn),
                    f"{prim} ({out}) depends only "
                    "on constants — recomputed at every trace, folded into "
                    "the executable as frozen data",
                    "hoist it out of the traced function (compute once, "
                    "pass as an argument)"))
    return findings
