"""Graph Doctor core: Finding/Report types, checker registry, jaxpr walker.

Reference analog: the *analysis* half of the reference IR pass pipeline
(~274 passes over ProgramDesc/PIR graphs in `paddle/fluid/framework/ir/`,
SURVEY C14).  `static/passes.py` reproduces the rewrite half at the record
level; this package is the analysis half at the JAXPR level — the typed IR
we actually traffic in (kernels, moe, generation, engine).  Checkers walk a
`ClosedJaxpr` (recursing into pjit/scan/cond/while/custom-vjp sub-jaxprs)
and emit structured `Finding` diagnostics instead of rewriting anything.

Registry mirrors `static/passes.py`: `register_checker(name)` /
`list_checkers()` / `analyze(fn, *args)`, plus per-call (`suppress=`) and
per-code (`suppressions(...)` context) suppression, matched exactly or by
`"PREFIX*"` glob.
"""

from __future__ import annotations

import contextlib
import dataclasses
import enum
import fnmatch
import functools
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.extend import core as jex_core

__all__ = [
    "Severity", "Finding", "Report", "register_checker", "list_checkers",
    "analyze", "analyze_jaxpr", "suppressions", "iter_eqns", "iter_jaxprs",
    "aval_bytes", "CheckContext", "load_rcfile", "find_rcfile",
    "merge_reports",
]

_DropVar = getattr(jax._src.core, "DropVar", ())


class Severity(enum.IntEnum):
    INFO = 1
    WARNING = 2
    ERROR = 3

    def __str__(self):  # "warning", for reports / JSON
        return self.name.lower()


@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: where (eqn_path), what (code/message), what to do.

    `data` carries machine-readable specifics (exact argnums, byte counts,
    suggested bucket menus) for consumers like fixes.suggest_fixes — the
    human message stays prose, the patch generator reads data."""

    severity: Severity
    code: str
    eqn_path: str
    message: str
    suggestion: str = ""
    checker: str = ""
    data: dict = dataclasses.field(default_factory=dict, compare=False)

    def to_dict(self) -> dict:
        return {"severity": str(self.severity), "code": self.code,
                "eqn_path": self.eqn_path, "message": self.message,
                "suggestion": self.suggestion, "checker": self.checker,
                "data": dict(self.data)}

    def __str__(self):
        tag = {"info": "I", "warning": "W", "error": "E"}[str(self.severity)]
        s = f"[{tag}] {self.code} @ {self.eqn_path}: {self.message}"
        if self.suggestion:
            s += f"  -> {self.suggestion}"
        return s


class Report:
    """Ordered findings (most severe first) + suppression accounting."""

    def __init__(self, findings: Sequence[Finding], suppressed: int = 0,
                 checkers: Sequence[str] = ()):
        self.findings = sorted(findings, key=lambda f: (-f.severity, f.code))
        self.suppressed = suppressed
        self.checkers = tuple(checkers)

    def __iter__(self) -> Iterator[Finding]:
        return iter(self.findings)

    def __len__(self) -> int:
        return len(self.findings)

    @property
    def codes(self) -> set:
        return {f.code for f in self.findings}

    def by_code(self, code: str) -> List[Finding]:
        return [f for f in self.findings if fnmatch.fnmatch(f.code, code)]

    def count(self, code: str,
              min_severity: Optional["Severity"] = None) -> int:
        """Findings matching a code glob (at/above min_severity) — the
        rewrite tier's before/after comparisons use this."""
        return sum(1 for f in self.by_code(code)
                   if min_severity is None or f.severity >= min_severity)

    def counts(self) -> Dict[str, int]:
        out = {"error": 0, "warning": 0, "info": 0}
        for f in self.findings:
            out[str(f.severity)] += 1
        return out

    def ok(self, fail_on: Severity = Severity.WARNING) -> bool:
        """True when no finding is at/above `fail_on` (after suppression)."""
        return all(f.severity < fail_on for f in self.findings)

    def to_json(self) -> dict:
        return {"findings": [f.to_dict() for f in self.findings],
                "counts": self.counts(), "suppressed": self.suppressed,
                "checkers": list(self.checkers)}

    def __str__(self):
        if not self.findings:
            body = "clean — no findings"
        else:
            body = "\n".join(str(f) for f in self.findings)
        c = self.counts()
        return (f"{body}\n-- {c['error']} error(s), {c['warning']} "
                f"warning(s), {c['info']} info, {self.suppressed} suppressed")


# ---------------------------------------------------------------------------
# Checker registry (mirrors static/passes.py's PASS_REGISTRY)
# ---------------------------------------------------------------------------

CHECKER_REGISTRY: Dict[str, Callable] = {}


def register_checker(name: str):
    """Register a checker: `fn(ctx: CheckContext) -> Iterable[Finding]`."""
    def deco(fn):
        CHECKER_REGISTRY[name] = fn
        fn._checker_name = name
        return fn
    return deco


def list_checkers() -> List[str]:
    return sorted(CHECKER_REGISTRY)


# -- suppression (per-call arg + process-wide context) ----------------------

_GLOBAL_SUPPRESS: set = set()


@contextlib.contextmanager
def suppressions(*codes: str):
    """Process-wide suppression of finding codes (exact or "PREFIX*" glob)
    for the duration of the context — the per-code half of the suppression
    story; `analyze(..., suppress=[...])` is the per-call half."""
    added = set(codes) - _GLOBAL_SUPPRESS
    _GLOBAL_SUPPRESS.update(added)
    try:
        yield
    finally:
        _GLOBAL_SUPPRESS.difference_update(added)


# -- project config (.graphlintrc) ------------------------------------------
#
# Project-level suppression + severity-override config, loaded by
# tools/graphlint.py and static.Program.lint() (and any caller passing
# config=load_rcfile(...) to analyze).  Two keys:
#
#   suppress = ["DTYPE_*", "DEAD_CODE@*scan/body*"]   # same syntax as
#                                                     # analyze(suppress=)
#   [severity]                                        # code (or glob) ->
#   RECOMPILE_CONST_CAPTURE = "info"                  # info|warning|error
#
# Precedence: severity overrides apply FIRST (so a code demoted to "info"
# stops failing the WARNING gate), then rc suppressions and per-call
# suppressions are UNIONED — a per-call suppress can only add to the rc
# file, never un-suppress it.  Format: TOML subset (sections, strings,
# single-line string arrays, comments) or a JSON object.


def _parse_toml_subset(text: str) -> dict:
    """Tiny TOML reader for the rc schema above (py3.10 has no tomllib):
    [section] headers, key = "str" | ["a", "b"] | number | true/false."""
    import ast

    out: dict = {}
    section = out
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line.startswith("[") and line.endswith("]"):
            section = out.setdefault(line[1:-1].strip(), {})
            continue
        key, eq, val = line.partition("=")
        if not eq:
            raise ValueError(f"unparseable .graphlintrc line: {raw!r}")
        val = val.split("#", 1)[0].strip() if not val.strip().startswith(
            ("'", '"', "[")) else val.strip()
        if val in ("true", "false"):
            parsed = val == "true"
        else:
            try:
                parsed = ast.literal_eval(val)
            except (ValueError, SyntaxError) as e:
                raise ValueError(
                    f"unparseable .graphlintrc value: {raw!r}") from e
        section[key.strip().strip('"').strip("'")] = parsed
    return out


def load_rcfile(path: str) -> dict:
    """Load a .graphlintrc (TOML subset or JSON) into
    {"suppress": [...], "severity": {CODE: "info"|"warning"|"error"}}."""
    import json

    with open(path) as f:
        text = f.read()
    raw = (json.loads(text) if text.lstrip().startswith("{")
           else _parse_toml_subset(text))
    cfg = {"suppress": list(raw.get("suppress", ())),
           "severity": dict(raw.get("severity", {}))}
    for code, level in cfg["severity"].items():
        if str(level).upper() not in Severity.__members__:
            raise ValueError(
                f".graphlintrc severity for {code!r} must be one of "
                f"info/warning/error, got {level!r}")
    return cfg


def find_rcfile(start: Optional[str] = None) -> Optional[str]:
    """Nearest .graphlintrc walking up from `start` (default: cwd)."""
    import os

    d = os.path.abspath(start or os.getcwd())
    while True:
        cand = os.path.join(d, ".graphlintrc")
        if os.path.isfile(cand):
            return cand
        parent = os.path.dirname(d)
        if parent == d:
            return None
        d = parent


def _apply_severity_overrides(findings: List["Finding"],
                              overrides: Dict[str, str]) -> List["Finding"]:
    if not overrides:
        return findings
    out = []
    for f in findings:
        for pat, level in overrides.items():
            if f.code == pat or fnmatch.fnmatch(f.code, pat):
                f = dataclasses.replace(
                    f, severity=Severity[str(level).upper()])
                break
        out.append(f)
    return out


def merge_reports(*reports: "Report") -> "Report":
    """Concatenate reports (e.g. the jaxpr tier + the HLO tier of one
    target) into one, keeping suppression accounting."""
    findings: List[Finding] = []
    suppressed = 0
    checkers: List[str] = []
    for r in reports:
        findings.extend(r.findings)
        suppressed += r.suppressed
        checkers.extend(c for c in r.checkers if c not in checkers)
    return Report(findings, suppressed=suppressed, checkers=checkers)


def _is_suppressed(finding: "Finding", patterns: Iterable[str]) -> bool:
    """Pattern syntax: "CODE", "PREFIX*", or "CODE@pathglob" scoping the
    suppression to eqn paths matching the glob."""
    for p in patterns:
        code_pat, _, path_pat = p.partition("@")
        if not (finding.code == code_pat
                or fnmatch.fnmatch(finding.code, code_pat)):
            continue
        if not path_pat or fnmatch.fnmatch(finding.eqn_path, path_pat):
            return True
    return False


# ---------------------------------------------------------------------------
# Jaxpr walking
# ---------------------------------------------------------------------------

# primitives whose param jaxprs run under different semantics (per-grid-step
# kernels); recursing into them would mis-count cost and mis-read liveness
_OPAQUE_PRIMS = frozenset({"pallas_call", "custom_partitioning"})


def _as_open(j):
    return j.jaxpr if isinstance(j, jex_core.ClosedJaxpr) else j


def _eqn_label(eqn) -> str:
    name = eqn.params.get("name") if isinstance(eqn.params, dict) else None
    if isinstance(name, str) and name:
        return f"{eqn.primitive.name}:{name}"
    return eqn.primitive.name


def sub_jaxprs(eqn) -> Iterator[Tuple[str, Any, int]]:
    """(label, sub-jaxpr, weight) under an eqn.  weight is the static trip
    count the body runs per call of the parent (scan length; 1 elsewhere —
    `while` trip counts are unknowable statically)."""
    if eqn.primitive.name in _OPAQUE_PRIMS:
        return
    p = eqn.params
    if eqn.primitive.name == "scan":
        yield "body", p["jaxpr"], int(p.get("length", 1))
        return
    if eqn.primitive.name == "while":
        yield "cond", p["cond_jaxpr"], 1
        yield "body", p["body_jaxpr"], 1
        return
    if eqn.primitive.name == "cond":
        for i, b in enumerate(p["branches"]):
            yield f"branch{i}", b, 1
        return
    for k, v in p.items():
        if isinstance(v, (jex_core.Jaxpr, jex_core.ClosedJaxpr)):
            yield k, v, 1
        elif isinstance(v, (tuple, list)) and v and all(
                isinstance(x, (jex_core.Jaxpr, jex_core.ClosedJaxpr))
                for x in v):
            for i, x in enumerate(v):
                yield f"{k}[{i}]", x, 1


def iter_eqns(jaxpr, path: Tuple[str, ...] = (), weight: int = 1,
              max_depth: int = 32):
    """Yield (eqn, path, weight) over a (Closed)Jaxpr, recursing into
    sub-jaxprs.  `weight` multiplies up static trip counts (scan length)."""
    jaxpr = _as_open(jaxpr)
    if max_depth <= 0:
        return
    for eqn in jaxpr.eqns:
        yield eqn, path, weight
        for label, sub, w in sub_jaxprs(eqn):
            yield from iter_eqns(sub, path + (_eqn_label(eqn), label),
                                 weight * w, max_depth - 1)


def iter_jaxprs(jaxpr, path: Tuple[str, ...] = (), weight: int = 1,
                max_depth: int = 32):
    """Yield (open_jaxpr, path, weight) for the jaxpr and every sub-jaxpr."""
    jaxpr = _as_open(jaxpr)
    yield jaxpr, path, weight
    if max_depth <= 0:
        return
    for eqn in jaxpr.eqns:
        for label, sub, w in sub_jaxprs(eqn):
            yield from iter_jaxprs(sub, path + (_eqn_label(eqn), label),
                                   weight * w, max_depth - 1)


def format_path(path: Tuple[str, ...], eqn=None) -> str:
    parts = list(path)
    if eqn is not None:
        parts.append(_eqn_label(eqn))
    return "/".join(parts) if parts else "<top>"


def aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    try:
        return int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
    except Exception:  # noqa: BLE001 — abstract/opaque dtypes
        return 0


def is_array_var(v) -> bool:
    return isinstance(v, jex_core.Var) and not isinstance(v, _DropVar)


def fmt_aval(aval) -> str:
    shape = getattr(aval, "shape", ())
    dtype = getattr(aval, "dtype", "?")
    return f"{np.dtype(dtype).name if dtype != '?' else '?'}" \
           f"[{','.join(str(d) for d in shape)}]"


def fmt_bytes(n: int) -> str:
    if n >= 1 << 30:
        return f"{n / (1 << 30):.2f} GiB"
    if n >= 1 << 20:
        return f"{n / (1 << 20):.2f} MiB"
    if n >= 1 << 10:
        return f"{n / (1 << 10):.1f} KiB"
    return f"{n} B"


# ---------------------------------------------------------------------------
# Analysis entry points
# ---------------------------------------------------------------------------

_DEFAULT_OPTIONS = {
    # byte thresholds: below these a copy / replica is considered noise
    "donation_min_bytes": 1 << 20,
    "sharding_min_bytes": 1 << 20,
    "const_capture_min_bytes": 1 << 20,
    "const_subgraph_min_bytes": 1 << 16,
    # dead eqns below BOTH of these are INFO (XLA DCEs them for free);
    # at/above either they warn — dead heavy compute is a real bug
    "dead_code_min_flops": 1e5,
    "dead_code_min_bytes": 1 << 16,
    "cost_top_k": 5,
    # at most this many findings per (checker, code) pair
    "max_findings_per_code": 8,
    # memory checker (analysis/memory.py): peak above this warns; None
    # keeps MEM_PEAK informational (the default — budgets are per-chip)
    "mem_peak_budget_bytes": None,
    "memory_top_k": 3,
    # HLO tier (analysis/hlo.py) --------------------------------------
    # unfused elementwise chains shorter than this, or on arrays smaller
    # than fusion_min_bytes, are noise (XLA fuses what pays on-chip)
    "fusion_chain_min": 4,
    "fusion_min_bytes": 1 << 20,
    # materialized transposes/copies below this are cheap shuffles
    "layout_min_bytes": 1 << 20,
    # adjacent same-group collectives smaller than this combine for free
    "collective_min_bytes": 1 << 10,
    # buffer-assignment temp bytes > ratio * (live args+outs) warns once
    # both exceed the floor — temporaries dominating a program is how a
    # "fits easily" model OOMs at 2x batch
    "mem_temp_bloat_ratio": 4.0,
    "mem_temp_min_bytes": 8 << 20,
    # recompile probe: this many distinct arg signatures are EXPECTED
    # (the engine's prefill bucket menu); only more than this warns
    "expected_signatures": 1,
    # bucket-menu lint: lengths in the upper bucket within slack*lower
    # edge "straddle" the edge (a near-duplicate compile + pad waste)
    "bucket_straddle_slack": 1.25,
    "bucket_align": 4,
    # kernellint tier (analysis/kernellint.py) ------------------------
    # chip kind for the VMEM budget (None = the v5e default fleet chip);
    # an explicit byte budget overrides the table entirely
    "kernellint_chip": None,
    "kernellint_vmem_budget_bytes": None,
}


@dataclasses.dataclass
class CheckContext:
    """Everything a checker may inspect.  `fn`/`args` are None when entering
    through analyze_jaxpr (jaxpr-only checkers must tolerate that)."""

    closed_jaxpr: Any
    fn: Optional[Callable] = None
    args: Tuple = ()
    kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)
    mesh: Any = None
    # abstract (shape, dtype) signatures of extra call sites, for the
    # compile-cache probe (see checkers.check_recompile_hazard)
    probe_signatures: List[Tuple] = dataclasses.field(default_factory=list)
    options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # flat-invar index -> human arg path ("args[0]['blocks']['wq']")
    arg_names: Dict[int, str] = dataclasses.field(default_factory=dict)
    # the checker names actually running in THIS analyze call — checkers
    # that defer to a sibling (the taint sharding walk stands down when
    # the spmd tier runs) must consult this, not the global registry
    active_checkers: Tuple[str, ...] = ()

    def opt(self, key: str, default=None):
        if key in self.options:
            return self.options[key]
        return _DEFAULT_OPTIONS.get(key, default)

    def invar_name(self, var) -> str:
        """Human name for a top-level invar, or a positional fallback."""
        invars = self.closed_jaxpr.jaxpr.invars
        for i, v in enumerate(invars):
            if v is var:
                return self.arg_names.get(i, f"arg#{i}")
        return "<non-toplevel>"


def _arg_signature(args, kwargs) -> Tuple:
    """The abstract signature jit keys its compile cache on: per-leaf
    (shape, dtype) + the pytree structure."""
    leaves, treedef = jax.tree_util.tree_flatten((args, kwargs))
    sig = tuple((tuple(np.shape(x)), str(jnp_result_type(x))) for x in leaves)
    return (str(treedef), sig)


def jnp_result_type(x):
    try:
        return jax.numpy.result_type(x)
    except Exception:  # noqa: BLE001
        return type(x).__name__


def _arg_name_map(args, kwargs) -> Dict[int, str]:
    try:
        flat, _ = jax.tree_util.tree_flatten_with_path((args, kwargs))
    except Exception:  # noqa: BLE001
        return {}
    names = {}
    for i, (path, _x) in enumerate(flat):
        label = jax.tree_util.keystr(path)
        # keystr of (args, kwargs): "[0][2]['blocks']['wq']" — rewrite the
        # leading tuple index into args[...] / kwargs[...]
        if label.startswith("[0]"):
            label = "args" + label[3:]
        elif label.startswith("[1]"):
            label = "kwargs" + label[3:]
        names[i] = label
    return names


def _run_checkers(ctx: CheckContext, checkers, suppress,
                  config: Optional[dict] = None) -> Report:
    names = list_checkers() if checkers is None else list(checkers)
    ctx.active_checkers = tuple(names)
    findings: List[Finding] = []
    for name in names:
        if name not in CHECKER_REGISTRY:
            raise ValueError(
                f"unknown checker {name!r}; available: {list_checkers()}")
        for f in CHECKER_REGISTRY[name](ctx):
            if not f.checker:
                f = dataclasses.replace(f, checker=name)
            findings.append(f)
    return finalize_findings(findings, names, ctx, suppress, config)


def finalize_findings(findings: List[Finding], names: Sequence[str],
                      ctx, suppress: Sequence[str],
                      config: Optional[dict] = None) -> Report:
    """Shared report assembly (jaxpr tier and HLO tier): apply
    .graphlintrc severity overrides, then suppression (per-call UNION rc
    file UNION process-wide context), then the per-code cap."""
    config = config or {}
    findings = _apply_severity_overrides(findings,
                                         config.get("severity", {}))
    patterns = (set(suppress) | set(config.get("suppress", ()))
                | _GLOBAL_SUPPRESS)
    kept, suppressed = [], 0
    per_code: Dict[Tuple[str, str], int] = {}
    cap = ctx.opt("max_findings_per_code")
    for f in sorted(findings, key=lambda f: -f.severity):
        if _is_suppressed(f, patterns):
            suppressed += 1
            continue
        key = (f.checker, f.code)
        per_code[key] = per_code.get(key, 0) + 1
        if cap and per_code[key] > cap:
            continue
        kept.append(f)
    for (checker, code), n in per_code.items():
        if cap and n > cap:
            kept.append(Finding(
                Severity.INFO, code, "<report>",
                f"{n - cap} further {code} finding(s) truncated "
                f"(max_findings_per_code={cap})", checker=checker))
    return Report(kept, suppressed=suppressed, checkers=names)


def analyze_jaxpr(closed_jaxpr, checkers: Optional[Sequence[str]] = None,
                  suppress: Sequence[str] = (), mesh=None,
                  options: Optional[dict] = None,
                  config: Optional[dict] = None) -> Report:
    """Run checkers over an already-traced ClosedJaxpr."""
    ctx = CheckContext(closed_jaxpr=closed_jaxpr, mesh=mesh,
                       options=dict(options or {}))
    return _run_checkers(ctx, checkers, suppress, config)


def analyze(fn, *args, checkers: Optional[Sequence[str]] = None,
            suppress: Sequence[str] = (), mesh=None,
            probe_args: Optional[Sequence[Tuple]] = None,
            options: Optional[dict] = None, static_argnums=(),
            config: Optional[dict] = None,
            **kwargs) -> Report:
    """Trace `fn(*args, **kwargs)` to a jaxpr and run every registered
    checker (or the named subset) over it.

    fn may be plain or jit-wrapped — a jitted fn traces to a `pjit` eqn
    carrying donation/sharding metadata, which the donation and sharding
    checkers read.  Args may be concrete arrays or `jax.ShapeDtypeStruct`s
    (nothing is executed; `analyze` only traces).

    probe_args: optional extra argument tuples representing other call
    sites of the same fn; differing abstract signatures are reported as
    recompile hazards (each signature compiles separately) unless the
    `expected_signatures` option covers them (the engine's bucket menu).
    suppress: per-call finding-code suppressions (exact or "PREFIX*").
    config: a load_rcfile() dict (severity overrides + rc suppressions).
    """
    traced = functools.partial(fn, **kwargs) if kwargs else fn
    closed = jax.make_jaxpr(traced, static_argnums=static_argnums)(*args)
    sigs = [_arg_signature(args, kwargs)]
    for extra in (probe_args or ()):
        sigs.append(_arg_signature(tuple(extra), {}))
    ctx = CheckContext(
        closed_jaxpr=closed, fn=fn, args=args, kwargs=kwargs, mesh=mesh,
        probe_signatures=sigs, options=dict(options or {}),
        arg_names=_arg_name_map(args, kwargs))
    return _run_checkers(ctx, checkers, suppress, config)
