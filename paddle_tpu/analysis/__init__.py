"""Graph Doctor — jaxpr/HLO static analysis for paddle_tpu models.

The analysis half of the reference's IR pass pipeline (~274 passes over
ProgramDesc/PIR graphs, `paddle/fluid/framework/ir/*_pass.cc`, SURVEY C14),
rebuilt where it belongs under XLA — in TWO tiers:

  tier 1 (jaxpr):  `analyze(fn, *args)` traces and walks the ClosedJaxpr;
                   catches silent f64 promotion, missed donation,
                   replicated intermediates, recompile churn, dead code,
                   cost hotspots, and static memory-liveness peaks —
                   attributable to eqn paths, no compilation needed.
  tier 2 (HLO):    `analyze_hlo(fn, *args)` lowers ONCE and lints the
                   COMPILED artifact — fusion breaks, combinable
                   collectives, materialized transposes, and buffer-
                   assignment memory (what jaxprs structurally cannot
                   see).  `core.merge_reports` joins both tiers.
  tier 3 (rewrite):`rewrite(fn, *args)` consumes findings and TRANSFORMS
                   the jaxpr (dce/dtype/fusion/shard_constraint/
                   donation), every pass gated by `equiv.verify`.
  tier 4 (SPMD):   under `analyze(..., mesh=...)` with a >1-device mesh,
                   `spmd.py` propagates PartitionSpecs per eqn and
                   prices every implied collective (`comm_cost.py`) —
                   SHARD_RESHARD / mesh-aware SHARD_REPLICATED /
                   COLLECTIVE_BOUND roofline.
  tier 5 (threads):`threadlint.analyze_modules()` walks the SERVING
                   stack's Python ASTs instead of jaxprs — per-class
                   lock protection maps, RACE_UNGUARDED_WRITE/READ,
                   LOCK_ORDER_CYCLE, LOCK_BLOCKING_CALL, THREAD_LEAK —
                   confirmed at runtime by `inference/faults.
                   LockWitness` (the chaos soaks' lock-order witness),
                   the same static-predicts/dynamic-confirms contract
                   `equiv.py` gives the rewrite tier.
  tier 6 (kernels):`kernellint.analyze_kernels()` opens every
                   `pallas_call` eqn the other tiers treat as opaque —
                   interval arithmetic over BlockSpec index maps proves
                   in-bounds block reads and exactly-once output
                   coverage (KERNEL_OOB_BLOCK / KERNEL_OUT_UNCOVERED /
                   KERNEL_OUT_OVERLAP / KERNEL_DEAD_GRID_CELL), a
                   per-chip VMEM footprint model predicts OOMs
                   (KERNEL_VMEM_OVERFLOW, exported as
                   `kernellint.vmem_bytes` for the autotuner), and
                   dtype discipline catches low-precision accumulators
                   (KERNEL_LOWP_ACCUM / KERNEL_DTYPE_MISMATCH).  Runs
                   inside every analyze call too, so the rewrite tier's
                   re-lint gate rejects generated kernels that fail it.

On top of findings, `fixes.suggest_fixes(report)` emits concrete patch
suggestions (exact donate_argnums, constraint insertion points, dtype
cast sites, bucket-menu edits) — `tools/graphlint.py --fix` prints them.

Suppression: per call (``analyze(..., suppress=["DTYPE_*"])``), per
process (``with analysis.suppressions(...)``), or per project via a
`.graphlintrc` file (``config=load_rcfile(find_rcfile())``) which can
also override finding severities.  Precedence: severity overrides apply
first; rc and per-call suppressions are unioned.

Three surfaces: the library (`analysis.analyze` / `analyze_hlo` /
`profiler.static_cost` / `profiler.static_memory`), the CLI
(``tools/graphlint.py`` — ``--fix``, ``--baseline``, ``--json``), and
pytest (``tests/test_graphlint*.py`` keep the shipped models clean).
"""

from __future__ import annotations

from .core import (  # noqa: F401
    CheckContext, Finding, Report, Severity, analyze, analyze_jaxpr,
    aval_bytes, find_rcfile, iter_eqns, iter_jaxprs, list_checkers,
    load_rcfile, merge_reports, register_checker, suppressions,
)
from . import cost  # noqa: F401
from . import comm_cost  # noqa: F401 — static collective cost model
from . import checkers as _checkers  # noqa: F401 — registers the jaxpr set
from . import memory  # noqa: F401 — registers the memory checker
from . import spmd  # noqa: F401 — registers the mesh-aware SPMD tier
from . import threadlint  # noqa: F401 — the lock-discipline tier (v5)
from . import kernellint  # noqa: F401 — the Pallas kernel verifier (v6)
from .hlo import (  # noqa: F401
    analyze_hlo, lint_bucket_menu, list_hlo_checkers, register_hlo_checker,
)
from . import hlo  # noqa: F401
from . import fixes  # noqa: F401
from . import equiv  # noqa: F401
from . import rewrite as rewrite_lib  # noqa: F401 — the module; the
# next import shadows the `rewrite` attr with the entry-point function
from .rewrite import (  # noqa: F401
    RewriteAction, RewriteReport, list_rewrites, register_rewrite, rewrite,
    rewrite_jaxpr,
)

__all__ = [
    "CheckContext", "Finding", "Report", "RewriteAction", "RewriteReport",
    "Severity", "analyze", "analyze_jaxpr", "analyze_hlo", "aval_bytes",
    "equiv", "find_rcfile", "iter_eqns", "iter_jaxprs", "lint_bucket_menu",
    "list_checkers", "list_hlo_checkers", "list_rewrites", "load_rcfile",
    "merge_reports", "register_checker", "register_hlo_checker",
    "register_rewrite", "rewrite", "rewrite_jaxpr", "rewrite_lib",
    "suppressions", "cost", "comm_cost", "memory", "hlo", "fixes", "spmd",
    "threadlint", "kernellint",
]
