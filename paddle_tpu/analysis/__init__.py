"""Graph Doctor — jaxpr/HLO static analysis for paddle_tpu models.

The analysis half of the reference's IR pass pipeline (~274 passes over
ProgramDesc/PIR graphs, `paddle/fluid/framework/ir/*_pass.cc`, SURVEY C14),
rebuilt where it belongs under XLA: over jaxprs.  `static/passes.py` holds
the record-level *rewrite* passes (DCE / folding / fusion); this package
holds the *analysis* passes that only diagnose — the lints that catch
silent f64 promotion, missed buffer donation, replicated giant
intermediates, and recompile churn before a TPU bill does (the TPU-MLIR /
MPK lesson: typed IR-level analysis is where correctness and cost
diagnostics belong).

Three entry points:

  * library:  ``paddle_tpu.analysis.analyze(fn, *args)`` -> ``Report``
  * CLI:      ``python tools/graphlint.py`` lints the shipped bench models
  * pytest:   ``tests/test_graphlint.py`` keeps the shipped models clean

Checkers (see `checkers.py` for codes): dtype_promotion, donation,
sharding, recompile_hazard, cost, dead_code.  Suppress per call with
``analyze(..., suppress=["DTYPE_*"])`` or per code/process with
``with analysis.suppressions("COST_*"): ...``.
"""

from __future__ import annotations

from .core import (  # noqa: F401
    CheckContext, Finding, Report, Severity, analyze, analyze_jaxpr,
    aval_bytes, iter_eqns, iter_jaxprs, list_checkers, register_checker,
    suppressions,
)
from . import cost  # noqa: F401
from . import checkers as _checkers  # noqa: F401 — registers the shipped set

__all__ = [
    "CheckContext", "Finding", "Report", "Severity", "analyze",
    "analyze_jaxpr", "aval_bytes", "iter_eqns", "iter_jaxprs",
    "list_checkers", "register_checker", "suppressions", "cost",
]
