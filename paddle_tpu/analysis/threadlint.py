"""threadlint — Graph Doctor v5: lock-discipline static analysis.

The jaxpr/HLO/rewrite/SPMD tiers lint the *compiled* program; this tier
lints the *concurrent* one.  The serving stack is a real multi-threaded
system — engine step thread, HTTP handler threads, router health tick,
supervisor rebuilds — and every shipped race (PR 9's lockless stats
`inc`, PR 10's post-teardown death sweep, PR 11's `verify_tokens`
identity tear) had the same shape: a `self._x` field touched both under
a lock and outside it.  threadlint walks the package ASTs and infers,
per class, a *lock protection map* — which fields are read/written
under which held locks — then emits graphlint-style `Finding`s:

  RACE_UNGUARDED_WRITE  field mutated both under a lock and outside it
                        (or outside its annotated owner thread)
  RACE_UNGUARDED_READ   multi-word read of lock-protected state outside
                        the lock (the PR 11 identity-tear shape), or
                        iteration over a protected container
  LOCK_ORDER_CYCLE      the static lock-acquisition graph has a cycle
                        (router lock vs engine lock vs registry lock)
  LOCK_BLOCKING_CALL    device dispatch / `.result()` / `time.sleep` /
                        HTTP I/O while holding a lock
  THREAD_LEAK           non-daemon Thread started with no join path

Opt-outs are in-source annotations, VERIFIED rather than trusted:

  self._slots = []   # threadlint: owned=_loop  <why it is safe>
      field-level (on the `__init__` assignment): the field is owned by
      the thread entering at method `<name>`.  Every non-init write
      site must be reachable from that method through the intra-class
      call graph — a lying `owned=` (a write from a second entry point)
      still fires, unless that site carries its own line annotation.

  # threadlint: atomic  <why it is safe>
      field-level in `__init__`: single-word/intentionally racy field,
      no write/read findings.  On any other line (including a `def`
      line): acknowledges the finding anchored at that line/method.

The dynamic half lives in `inference/faults.LockWitness`: an
instrumented lock wrapper armed by the chaos soaks that records the
per-thread acquisition order at runtime and fails the soak on any order
inversion or a lock held across a fenced dispatch — the dynamic tier
confirms what this static tier predicts, same contract as `equiv.py`
for the rewrite tier.  Both surface through `tools/graphlint.py
--threads` with baseline-diff CI semantics (schema v4).
"""

from __future__ import annotations

import ast
import dataclasses
import importlib.util
import os
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import CheckContext, Finding, Report, Severity, finalize_findings

__all__ = [
    "DEFAULT_MODULES", "analyze_modules", "analyze_source", "inventory",
    "scan_modules",
]

DEFAULT_MODULES = ("paddle_tpu.inference", "paddle_tpu.obs")

CHECKER = "threadlint"

# threading constructors -> lock kind.  "lock"/"rlock"/"condition"/
# "semaphore" are holdable (context managers that block); Event is
# inventoried but never "held".
_LOCK_CTORS = {
    "Lock": "lock", "RLock": "rlock", "Condition": "condition",
    "Semaphore": "semaphore", "BoundedSemaphore": "semaphore",
    "Event": "event",
}
_HOLDABLE = {"lock", "rlock", "condition", "semaphore"}

# method calls that mutate a container in place — `self._x.append(...)`
# is a write to `_x`
_MUTATORS = frozenset({
    "append", "appendleft", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "clear", "update", "add",
    "setdefault", "move_to_end", "rotate", "sort", "reverse",
})

# attribute calls that block (or dispatch to the device) — forbidden
# while holding any lock.  `.join` is gated on a thread-ish receiver so
# `", ".join(parts)` under a lock stays silent.
_BLOCKING_ATTRS = frozenset({
    "result", "serve_forever", "urlopen", "getresponse",
    "block_until_ready",
})
# jitted dispatch callables on the engine: a device dispatch under a
# lock serializes every other thread behind device latency
_DISPATCH_ATTRS = frozenset({
    "_ragged", "_ragged_fused", "_swap_out", "_swap_in", "_cow",
    "device_put",
})

_ANN_RE = re.compile(r"#\s*threadlint:\s*(\S+)")

# container/stdlib method names never treated as cross-class call
# targets (a `q.get()` under a lock is not a call into TieredPrefixStore
# just because the store also defines `get`)
_GENERIC_METHOD_NAMES = frozenset(_MUTATORS) | frozenset({
    "get", "keys", "values", "items", "copy", "put", "join", "start",
    "wait", "wait_for", "notify", "notify_all", "acquire", "release",
    "set", "is_set", "close", "open", "read", "write", "encode",
    "decode", "format", "split", "strip", "is_alive", "count",
    "tolist", "item", "sum", "mean", "any", "all",
})


# ---------------------------------------------------------------------------
# collected facts
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _LockDef:
    owner: str          # qualified class name (or "<module>")
    attr: str
    kind: str           # lock|rlock|condition|semaphore|event
    file: str
    line: int

    @property
    def node(self) -> str:
        return f"{self.owner}.{self.attr}"


@dataclasses.dataclass
class _Write:
    field: str
    line: int
    locks: Tuple[str, ...]
    method: str
    acked: bool


@dataclasses.dataclass
class _Read:
    field: str
    line: int
    locks: Tuple[str, ...]
    method: str
    iterated: bool
    closure: bool


@dataclasses.dataclass
class _Acquire:
    lock: str
    line: int
    held: Tuple[str, ...]
    method: str


@dataclasses.dataclass
class _CallSite:
    name: str           # callee method name
    held: Tuple[str, ...]
    line: int
    method: str
    on_self: bool


@dataclasses.dataclass
class _Blocking:
    what: str
    line: int
    held: Tuple[str, ...]
    method: str
    acked: bool


@dataclasses.dataclass
class _ThreadUse:
    target: str         # "self._loop", "srv.serve_forever", "?"
    line: int
    daemon: bool
    assigned: str       # storage expr ("self._thread", "t", "")
    method: str
    file: str


class _ClassInfo:
    def __init__(self, name: str, root: str, mod: str, file: str):
        self.name = name          # qualified class name
        self.root = root          # requested root module
        self.mod = mod            # full module name (for messages)
        self.file = file
        self.locks: Dict[str, _LockDef] = {}
        self.methods: Set[str] = set()
        self.field_ann: Dict[str, str] = {}   # field -> "atomic"|"owned=M"
        self.writes: List[_Write] = []
        self.reads: List[_Read] = []
        self.acquires: List[_Acquire] = []
        self.calls: List[_CallSite] = []
        self.blocking: List[_Blocking] = []
        self.threads: List[_ThreadUse] = []
        self.def_acked: Set[str] = set()      # methods with an acked def line
        self.acked_lines: Set[int] = set()    # annotated lines in this file

    def clear_method(self, m: str):
        for lst in (self.writes, self.reads, self.acquires, self.calls,
                    self.blocking, self.threads):
            lst[:] = [x for x in lst if x.method != m]

    @property
    def is_module(self) -> bool:
        return self.name == "<module>"


class _Program:
    """Every class (and module-level pseudo-class) across the analyzed
    modules, plus the module-wide join/daemon evidence for THREAD_LEAK."""

    def __init__(self):
        self.classes: List[_ClassInfo] = []
        self.joins: Set[str] = set()        # unparsed join receivers
        self.join_attrs: Set[str] = set()   # last attr of join receivers
        self.module_locks: Dict[str, _LockDef] = {}   # bare name -> def

    # name resolution ------------------------------------------------------
    def lock_owner_classes(self) -> Dict[str, List[_ClassInfo]]:
        out: Dict[str, List[_ClassInfo]] = {}
        for c in self.classes:
            for attr in c.locks:
                out.setdefault(attr, []).append(c)
        return out

    def method_owners(self, name: str) -> List[_ClassInfo]:
        return [c for c in self.classes
                if not c.is_module and name in c.methods]


# ---------------------------------------------------------------------------
# per-file AST walk
# ---------------------------------------------------------------------------

def _annotations(src: str) -> Dict[int, str]:
    """line -> annotation spec ("atomic" / "owned=M") for every
    `# threadlint:` comment in the source."""
    out: Dict[int, str] = {}
    for i, line in enumerate(src.splitlines(), start=1):
        m = _ANN_RE.search(line)
        if m:
            out[i] = m.group(1)
    return out


def _self_attr(node) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _lock_ctor_kind(call: ast.AST) -> Optional[str]:
    if not isinstance(call, ast.Call):
        return None
    f = call.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "threading":
        return _LOCK_CTORS.get(f.attr)
    if isinstance(f, ast.Name):
        return _LOCK_CTORS.get(f.id)
    return None


def _is_thread_ctor(call: ast.Call) -> bool:
    f = call.func
    if isinstance(f, ast.Attribute) and f.attr == "Thread" and \
            isinstance(f.value, ast.Name) and f.value.id == "threading":
        return True
    return isinstance(f, ast.Name) and f.id == "Thread"


class _FileWalker:
    """Walks one parsed file, filling `_ClassInfo`s into the program."""

    def __init__(self, prog: _Program, tree: ast.Module, src: str,
                 root: str, mod: str, file: str):
        self.prog = prog
        self.src = src
        self.root = root
        self.mod = mod
        self.file = file
        self.ann = _annotations(src)
        self.tree = tree
        self._src_lines = src.splitlines()

    def _ann_at(self, line: int) -> Optional[str]:
        """Annotation on the line itself, or in the contiguous comment
        block directly above it (multi-line justifications)."""
        if line in self.ann:
            return self.ann[line]
        lines = self._src_lines
        i = line - 1
        while i >= 1 and i <= len(lines) and \
                lines[i - 1].strip().startswith("#"):
            if i in self.ann:
                return self.ann[i]
            i -= 1
        return None

    # -- pass 1: discover classes, locks, methods, field annotations ------
    def collect(self):
        self._klass_nodes: List[Tuple[ast.ClassDef, _ClassInfo]] = []
        mod_cls = _ClassInfo("<module>", self.root, self.mod, self.file)
        self._collect_into(self.tree.body, mod_cls, top=True)
        self.prog.classes.append(mod_cls)
        for c in self.prog.classes:
            if c.file == self.file:
                c.acked_lines = set(self.ann)

    def _collect_into(self, body, mod_cls: _ClassInfo, top: bool,
                      prefix: str = ""):
        for node in body:
            if isinstance(node, ast.ClassDef):
                self._collect_class(node, prefix)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                mod_cls.methods.add(node.name)
                # classes nested in functions (serve_llm's handler)
                self._collect_into(node.body, mod_cls, top=False,
                                   prefix=prefix)
            elif top and isinstance(node, ast.Assign) and \
                    len(node.targets) == 1 and \
                    isinstance(node.targets[0], ast.Name):
                kind = _lock_ctor_kind(node.value)
                if kind:
                    name = node.targets[0].id
                    self.prog.module_locks[name] = _LockDef(
                        self.mod, name, kind, self.file, node.lineno)

    def _collect_class(self, node: ast.ClassDef, prefix: str):
        qname = f"{prefix}{node.name}"
        info = _ClassInfo(qname, self.root, self.mod, self.file)
        for st in node.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                info.methods.add(st.name)
            elif isinstance(st, ast.Assign):
                # class-level lock (flight.FlightRecorder._seq_lock)
                kind = _lock_ctor_kind(st.value)
                if kind:
                    for t in st.targets:
                        if isinstance(t, ast.Name):
                            info.locks[t.id] = _LockDef(
                                qname, t.id, kind, self.file, st.lineno)
        def _assigned_attr(sub):
            if isinstance(sub, ast.Assign) and sub.targets:
                return _self_attr(sub.targets[0]), sub.value
            if isinstance(sub, ast.AnnAssign):
                return _self_attr(sub.target), sub.value
            return None, None

        # instance locks + field annotations from every method (locks are
        # created in __init__ in practice, but attach_engine-style late
        # binds exist)
        for st in ast.walk(node):
            attr, value = _assigned_attr(st)
            if attr is None:
                continue
            kind = _lock_ctor_kind(value)
            if kind:
                info.locks.setdefault(attr, _LockDef(
                    qname, attr, kind, self.file, st.lineno))
        # field-level annotations: only on __init__ assignment lines
        for st in node.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and st.name == "__init__":
                for sub in ast.walk(st):
                    attr, _value = _assigned_attr(sub)
                    spec = self._ann_at(sub.lineno) if attr else None
                    if attr and spec:
                        info.field_ann[attr] = spec
        self.prog.classes.append(info)
        self._klass_nodes.append((node, info))
        # nested classes
        for st in node.body:
            if isinstance(st, ast.ClassDef):
                self._collect_class(st, prefix=f"{qname}.")

    # -- pass 2: walk method bodies ---------------------------------------
    def walk(self):
        for node, info in self._klass_nodes:
            defs = {st.name: st for st in node.body
                    if isinstance(st, (ast.FunctionDef,
                                       ast.AsyncFunctionDef))}
            for name, st in defs.items():
                if self._ann_at(st.lineno):
                    info.def_acked.add(name)
            # lock propagation through the intra-class call graph: a
            # private helper only ever called with L held effectively
            # runs under L — re-walk it with that baseline until the
            # baselines stabilize (put -> _enforce_capacity chains)
            baselines = {name: () for name in defs}
            for _round in range(4):
                for name in defs:
                    info.clear_method(name)
                for name, st in defs.items():
                    _MethodWalker(self, info, name).run(
                        st.body, baselines[name])
                new = self._baselines(info, defs, baselines)
                if new == baselines:
                    break
                baselines = new
        # module-level functions as methods of the pseudo-class
        mod_cls = next(c for c in self.prog.classes
                       if c.file == self.file and c.is_module)
        for st in self.tree.body:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self._ann_at(st.lineno):
                    mod_cls.def_acked.add(st.name)
                _MethodWalker(self, mod_cls, st.name).run(st.body, ())

    def _baselines(self, info: _ClassInfo, defs, prev) -> dict:
        """Entry-held baseline per method: the intersection of held sets
        across every intra-class call site — private methods only, and
        never thread entry points (they start with nothing held)."""
        entries = {tu.target.split(".")[-1] for tu in info.threads}
        sites: Dict[str, List[Tuple[str, ...]]] = {}
        for cs in info.calls:
            if cs.on_self and cs.name in defs:
                sites.setdefault(cs.name, []).append(cs.held)
        out = {}
        for name in defs:
            base = ()
            if name.startswith("_") and not name.startswith("__") \
                    and name not in entries and sites.get(name):
                common = None
                for held in sites[name]:
                    s = set(held)
                    common = s if common is None else (common & s)
                base = tuple(sorted(common or ()))
            out[name] = base
        return out


class _MethodWalker:
    """Walks one method body tracking the held-lock set through
    `with self._lock:` regions."""

    def __init__(self, fw: _FileWalker, info: _ClassInfo, method: str):
        self.fw = fw
        self.info = info
        self.method = method
        self.closure = 0
        self.daemon_sets: Set[str] = set()   # "<expr>.daemon = True"

    def run(self, body, baseline: Tuple[str, ...] = ()):
        self._stmts(body, baseline)
        # flush daemon post-assignments onto thread uses of this method
        for tu in self.info.threads:
            if tu.method == self.method and not tu.daemon and tu.assigned \
                    and tu.assigned in self.daemon_sets:
                tu.daemon = True

    # -- statements --------------------------------------------------------
    def _stmts(self, body, held):
        for st in body:
            self._stmt(st, held)

    def _stmt(self, st, held):
        if isinstance(st, (ast.With, ast.AsyncWith)):
            new = held
            for item in st.items:
                lock = self._lock_of(item.context_expr)
                if lock is not None:
                    self._acquire(lock, item.context_expr.lineno, new)
                    new = new + (lock,)
                else:
                    self._expr(item.context_expr, held)
                if item.optional_vars is not None:
                    self._expr(item.optional_vars, held)
            self._stmts(st.body, new)
            return
        if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: runs later, not under these locks
            self.closure += 1
            self._stmts(st.body, ())
            self.closure -= 1
            return
        if isinstance(st, ast.ClassDef):
            return      # handled at collection
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self._assign(st, held)
            return
        if isinstance(st, ast.Delete):
            for t in st.targets:
                self._write_target(t, st.lineno, held)
            return
        if isinstance(st, ast.For):
            self._expr(st.iter, held, iterated=True)
            self._expr(st.target, held)
            self._stmts(st.body, held)
            self._stmts(st.orelse, held)
            return
        if isinstance(st, ast.Try):
            self._stmts(st.body, held)
            for h in st.handlers:
                self._stmts(h.body, held)
            self._stmts(st.orelse, held)
            self._stmts(st.finalbody, held)
            return
        # generic: walk child statements/exprs with the same held set
        for _f, value in ast.iter_fields(st):
            if isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.stmt):
                        self._stmt(v, held)
                    elif isinstance(v, ast.expr):
                        self._expr(v, held)
            elif isinstance(value, ast.expr):
                self._expr(value, held)

    def _assign(self, st, held):
        value = getattr(st, "value", None)
        targets = st.targets if isinstance(st, ast.Assign) else [st.target]
        assigned = ""
        if isinstance(st, ast.Assign) and len(targets) == 1:
            try:
                assigned = ast.unparse(targets[0])
            except Exception:   # noqa: BLE001
                assigned = ""
        # `X.daemon = True` marks a thread daemon post-hoc
        if isinstance(targets[0], ast.Attribute) and \
                targets[0].attr == "daemon" and \
                isinstance(value, ast.Constant) and value.value is True:
            try:
                self.daemon_sets.add(ast.unparse(targets[0].value))
            except Exception:   # noqa: BLE001
                pass
        for t in targets:
            self._write_target(t, st.lineno, held)
        if isinstance(st, ast.AugAssign):
            # += reads then writes
            self._expr(st.target, held)
        if value is not None:
            self._expr(value, held, assigned_to=assigned)

    def _write_target(self, t, line, held):
        if isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                self._write_target(e, line, held)
            return
        if isinstance(t, ast.Starred):
            self._write_target(t.value, line, held)
            return
        attr = _self_attr(t)
        if attr is not None:
            self._record_write(attr, line, held)
            return
        if isinstance(t, ast.Subscript):
            base = _self_attr(t.value)
            if base is not None:
                self._record_write(base, line, held)
            else:
                self._expr(t.value, held)
            self._expr(t.slice, held)
            return
        if isinstance(t, ast.Attribute):
            self._expr(t.value, held)

    # -- expressions -------------------------------------------------------
    def _expr(self, e, held, iterated=False, assigned_to=""):
        if e is None:
            return
        if isinstance(e, ast.Lambda):
            self.closure += 1
            self._expr(e.body, ())
            self.closure -= 1
            return
        if isinstance(e, ast.Call):
            self._call(e, held, assigned_to=assigned_to)
            return
        attr = _self_attr(e)
        if attr is not None and isinstance(e.ctx, ast.Load):
            self._record_read(attr, e.lineno, held, iterated)
            return
        for child in ast.iter_child_nodes(e):
            if isinstance(child, ast.comprehension):
                self._expr(child.iter, held, iterated=True)
                self._expr(child.target, held)
                for cond in child.ifs:
                    self._expr(cond, held)
            elif isinstance(child, ast.keyword):
                self._expr(child.value, held)
            elif isinstance(child, ast.expr):
                self._expr(child, held, iterated=iterated)

    def _call(self, call: ast.Call, held, assigned_to=""):
        f = call.func
        if _is_thread_ctor(call):
            self._thread_ctor(call, assigned_to)
        elif _lock_ctor_kind(call) is None:
            self._check_blocking(call, held)
            self._record_callsite(call, held)
        # receiver + args are reads — except a mutating container call
        # (`self._pending.append(x)`), which is a WRITE to the field
        if isinstance(f, ast.Attribute):
            base = _self_attr(f.value)
            if base is not None and f.attr in _MUTATORS:
                self._record_write(base, call.lineno, held)
            elif isinstance(f.value, ast.Name) and f.value.id == "self":
                if f.attr not in self.info.methods:
                    # callable field (self._ragged(...)) — a read of it
                    self._record_read(f.attr, call.lineno, held, False)
            else:
                self._expr(f.value, held)
        elif not isinstance(f, ast.Name):
            self._expr(f, held)
        for a in call.args:
            self._expr(a, held)
        for kw in call.keywords:
            self._expr(kw.value, held)

    def _thread_ctor(self, call: ast.Call, assigned_to: str):
        target, daemon = "?", False
        for kw in call.keywords:
            if kw.arg == "target":
                try:
                    target = ast.unparse(kw.value)
                except Exception:   # noqa: BLE001
                    target = "?"
            elif kw.arg == "daemon" and \
                    isinstance(kw.value, ast.Constant) and \
                    kw.value.value is True:
                daemon = True
        self.info.threads.append(_ThreadUse(
            target, call.lineno, daemon, assigned_to, self.method,
            self.fw.file))

    def _check_blocking(self, call: ast.Call, held):
        if not held:
            return
        f = call.func
        what = None
        if isinstance(f, ast.Attribute):
            recv = f.value
            if f.attr == "sleep" and isinstance(recv, ast.Name) and \
                    recv.id == "time":
                what = "time.sleep"
            elif f.attr in ("wait", "wait_for"):
                # Condition.wait on a HELD lock releases it — exempt;
                # everything else (Event.wait, handle.result-ish waits)
                # blocks while we hold our locks
                lock = self._lock_of(recv)
                if lock is None or lock not in held:
                    try:
                        what = f"{ast.unparse(recv)}.{f.attr}"
                    except Exception:   # noqa: BLE001
                        what = f".{f.attr}"
            elif f.attr == "join":
                try:
                    rtxt = ast.unparse(recv)
                except Exception:   # noqa: BLE001
                    rtxt = ""
                if "thread" in rtxt.lower():
                    what = f"{rtxt}.join"
            elif f.attr in _BLOCKING_ATTRS:
                what = f".{f.attr}"
            elif f.attr in _DISPATCH_ATTRS:
                what = f"device dispatch .{f.attr}"
        elif isinstance(f, ast.Name) and f.id in ("sleep", "urlopen"):
            what = f.id
        if what:
            acked = self.fw._ann_at(call.lineno) is not None
            self.info.blocking.append(_Blocking(
                what, call.lineno, held, self.method, acked))

    def _record_callsite(self, call: ast.Call, held):
        f = call.func
        if isinstance(f, ast.Attribute):
            if isinstance(f.value, ast.Name) and f.value.id == "self":
                self.info.calls.append(_CallSite(
                    f.attr, held, call.lineno, self.method, True))
            elif held and isinstance(f.value, ast.Name) and \
                    f.attr not in _GENERIC_METHOD_NAMES and \
                    not f.attr.startswith("__"):
                # `eng.submit(...)` under a held lock: resolved later by
                # method-name uniqueness across the analyzed classes
                self.info.calls.append(_CallSite(
                    f.attr, held, call.lineno, self.method, False))
        # module-level functions call each other by bare name
        elif isinstance(f, ast.Name) and self.info.is_module and \
                f.id in self.info.methods:
            self.info.calls.append(_CallSite(
                f.id, held, call.lineno, self.method, True))

    # -- fact recording ----------------------------------------------------
    def _record_write(self, field, line, held):
        if field in self.info.locks:
            return
        acked = self.fw._ann_at(line) is not None
        self.info.writes.append(_Write(
            field, line, tuple(held), self.method, acked))

    def _record_read(self, field, line, held, iterated):
        if not field or field in self.info.locks or \
                field in self.info.methods:
            return
        self.info.reads.append(_Read(
            field, line, tuple(held), self.method, iterated,
            self.closure > 0))

    def _acquire(self, lock, line, held):
        self.info.acquires.append(_Acquire(
            lock, line, tuple(held), self.method))

    def _lock_of(self, expr) -> Optional[str]:
        """Lock-graph node id for an acquisition expression, or None.
        Unresolvable non-self receivers get a "?"-prefixed id: still
        HELD (so blocking calls under them fire) but excluded from the
        cycle graph."""
        attr = _self_attr(expr)
        if attr is not None:
            ld = self.info.locks.get(attr)
            if ld is not None and ld.kind in _HOLDABLE:
                return ld.node
            return None
        if isinstance(expr, ast.Name):
            ld = self.fw.prog.module_locks.get(expr.id)
            if ld is not None and ld.kind in _HOLDABLE:
                return f"{self.fw.mod}.{expr.id}"
            return None
        if isinstance(expr, ast.Attribute):
            # ClassName._seq_lock, or other.attr resolved by uniqueness
            if isinstance(expr.value, ast.Name):
                for c in self.fw.prog.classes:
                    if c.name == expr.value.id and expr.attr in c.locks \
                            and c.locks[expr.attr].kind in _HOLDABLE:
                        return c.locks[expr.attr].node
            owners = [c for c in self.fw.prog.classes
                      if expr.attr in c.locks
                      and c.locks[expr.attr].kind in _HOLDABLE]
            if len(owners) == 1:
                return owners[0].locks[expr.attr].node
            if owners:
                return f"?.{expr.attr}"
        return None


# ---------------------------------------------------------------------------
# program-level rules
# ---------------------------------------------------------------------------

def _reach(info: _ClassInfo, start: str) -> Set[str]:
    """Methods reachable from `start` through self-calls (the owner
    thread's intra-class footprint)."""
    out, frontier = {start}, [start]
    callmap: Dict[str, Set[str]] = {}
    for cs in info.calls:
        if cs.on_self:
            callmap.setdefault(cs.method, set()).add(cs.name)
    while frontier:
        m = frontier.pop()
        for n in callmap.get(m, ()):
            if n not in out and n in info.methods:
                out.add(n)
                frontier.append(n)
    return out


def _init_only(info: _ClassInfo) -> Set[str]:
    """Private methods reachable ONLY from __init__ (construction-time
    helpers like a spill-dir reindex): their writes are init writes."""
    entries = {tu.target.split(".")[-1] for tu in info.threads}
    callers: Dict[str, Set[str]] = {}
    for cs in info.calls:
        if cs.on_self:
            callers.setdefault(cs.name, set()).add(cs.method)
    out: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, frm in callers.items():
            if name in out or name in entries or \
                    not name.startswith("_") or name.startswith("__"):
                continue
            if all(m == "__init__" or m in out for m in frm):
                out.add(name)
                changed = True
    return out


def _check_writes(info: _ClassInfo) -> List[Finding]:
    findings = []
    init_m = _init_only(info) | {"__init__"}
    by_field: Dict[str, List[_Write]] = {}
    for w in info.writes:
        if w.method in init_m:
            continue
        by_field.setdefault(w.field, []).append(w)
    for field, writes in sorted(by_field.items()):
        ann = info.field_ann.get(field, "")
        if ann == "atomic":
            continue
        path = f"{info.root}.{info.name}.{field}"
        if ann.startswith("owned="):
            owner = ann.split("=", 1)[1]
            ok = _reach(info, owner)
            bad = [w for w in writes
                   if w.method not in ok and not w.acked]
            if bad:
                sites = ", ".join(f"{w.method}:{w.line}" for w in bad[:4])
                findings.append(Finding(
                    Severity.WARNING, "RACE_UNGUARDED_WRITE", path,
                    f"annotation `owned={owner}` violated: `self.{field}` "
                    f"is written outside the owner's call graph at "
                    f"{sites} ({os.path.basename(info.file)})",
                    "move the write onto the owning thread, or "
                    "acknowledge the site with a line-level "
                    "`# threadlint:` annotation explaining why it is "
                    "safe", CHECKER,
                    {"file": info.file, "field": field,
                     "owner": owner,
                     "lines": [w.line for w in bad]}))
            continue
        live = [w for w in writes if not w.acked]
        locked = [w for w in live if any(not h.startswith("?")
                                         for h in w.locks)]
        unlocked = [w for w in live if not w.locks]
        if locked and unlocked:
            lock = sorted({h for w in locked for h in w.locks})[0]
            findings.append(Finding(
                Severity.WARNING, "RACE_UNGUARDED_WRITE", path,
                f"`self.{field}` is written under {lock} at "
                f"{locked[0].method}:{locked[0].line} but also with no "
                f"lock held at "
                + ", ".join(f"{w.method}:{w.line}" for w in unlocked[:4])
                + f" ({os.path.basename(info.file)})",
                f"take {lock} around every write, or annotate the "
                "field `# threadlint: owned=<method>|atomic` if the "
                "discipline is intentional", CHECKER,
                {"file": info.file, "field": field, "lock": lock,
                 "locked_lines": [w.line for w in locked],
                 "unlocked_lines": [w.line for w in unlocked]}))
    return findings


def _protected_fields(info: _ClassInfo) -> Dict[str, str]:
    """field -> lock node, for fields whose every live non-init write
    holds that lock (the inferred protection map)."""
    init_m = _init_only(info) | {"__init__"}
    by_field: Dict[str, List[_Write]] = {}
    for w in info.writes:
        if w.method in init_m or w.acked:
            continue
        by_field.setdefault(w.field, []).append(w)
    out = {}
    for field, writes in by_field.items():
        if info.field_ann.get(field):
            continue
        common = None
        for w in writes:
            locks = {h for h in w.locks if not h.startswith("?")}
            common = locks if common is None else (common & locks)
            if not common:
                break
        if common:
            out[field] = sorted(common)[0]
    return out


def _check_reads(info: _ClassInfo) -> List[Finding]:
    findings = []
    prot = _protected_fields(info)
    if not prot:
        return findings
    # group unprotected reads per method
    init_m = _init_only(info) | {"__init__"}
    per_method: Dict[str, List[_Read]] = {}
    for r in info.reads:
        if r.method in init_m:
            continue
        lock = prot.get(r.field)
        if lock is None or lock in r.locks:
            continue
        per_method.setdefault(r.method, []).append(r)
    for method, reads in sorted(per_method.items()):
        if method in info.def_acked:
            continue
        live = [r for r in reads if r.line not in info.acked_lines and
                (r.line - 1) not in info.acked_lines]
        if not live:
            continue
        fields = sorted({r.field for r in live})
        iters = [r for r in live if r.iterated]
        path = f"{info.root}.{info.name}.{method}"
        if len(fields) >= 2:
            lock = prot[fields[0]]
            findings.append(Finding(
                Severity.WARNING, "RACE_UNGUARDED_READ", path,
                f"reads {len(fields)} {lock}-protected fields "
                f"({', '.join('self.' + f for f in fields[:5])}) without "
                f"holding it — a writer between the reads tears the "
                f"multi-word view (the PR 11 identity-tear shape) "
                f"({os.path.basename(info.file)}:{live[0].line})",
                f"snapshot the fields under one `with {lock.split('.')[-1]}:` "
                "block, or annotate the method "
                "`# threadlint: atomic` with why torn reads are "
                "acceptable", CHECKER,
                {"file": info.file, "fields": fields,
                 "lines": sorted({r.line for r in live})}))
        elif iters:
            r = iters[0]
            lock = prot[r.field]
            findings.append(Finding(
                Severity.WARNING, "RACE_UNGUARDED_READ", path,
                f"iterates `self.{r.field}` ({lock}-protected) without "
                f"holding the lock — a concurrent writer mutates the "
                f"container mid-iteration "
                f"({os.path.basename(info.file)}:{r.line})",
                f"copy it under the lock first "
                f"(`with {lock.split('.')[-1]}: snap = list(...)`)",
                CHECKER,
                {"file": info.file, "field": r.field, "line": r.line}))
    return findings


def _check_blocking(info: _ClassInfo) -> List[Finding]:
    findings = []
    for b in info.blocking:
        if b.acked or b.method in info.def_acked:
            continue
        path = f"{info.root}.{info.name}.{b.method}"
        held = ", ".join(h for h in b.held)
        findings.append(Finding(
            Severity.WARNING, "LOCK_BLOCKING_CALL", path,
            f"calls {b.what} while holding {held} — every thread "
            f"contending for the lock stalls behind the blocking call "
            f"({os.path.basename(info.file)}:{b.line})",
            "move the blocking call outside the locked region "
            "(snapshot state under the lock, block after), or "
            "acknowledge with `# threadlint:` and a reason", CHECKER,
            {"file": info.file, "line": b.line, "held": list(b.held),
             "call": b.what}))
    return findings


def _check_threads(prog: _Program) -> List[Finding]:
    findings = []
    for info in prog.classes:
        for tu in info.threads:
            if tu.daemon:
                continue
            joined = tu.assigned and (
                tu.assigned in prog.joins
                or tu.assigned.rsplit(".", 1)[-1] in prog.join_attrs)
            if joined:
                continue
            path = f"{info.root}.{info.name}.{tu.method}"
            findings.append(Finding(
                Severity.WARNING, "THREAD_LEAK", path,
                f"non-daemon Thread(target={tu.target}) started at "
                f"{os.path.basename(tu.file)}:{tu.line} with no join "
                f"path — it outlives shutdown() and wedges interpreter "
                f"exit",
                "join it on shutdown, or mark it daemon=True if it "
                "holds no state that must flush", CHECKER,
                {"file": tu.file, "line": tu.line, "target": tu.target,
                 "assigned": tu.assigned}))
    return findings


def _lock_graph(prog: _Program):
    """edges: (a, b) -> example site string, from syntactic nesting plus
    call-graph propagation (a held while b is acquired)."""
    # direct + effective acquisitions per (class, method)
    direct: Dict[Tuple[str, str], Set[str]] = {}
    calls: Dict[Tuple[str, str], List[_CallSite]] = {}
    keyed: Dict[Tuple[str, str], _ClassInfo] = {}
    for info in prog.classes:
        for a in info.acquires:
            if not a.lock.startswith("?"):
                direct.setdefault((info.name, a.method), set()).add(a.lock)
        for cs in info.calls:
            calls.setdefault((info.name, cs.method), []).append(cs)
        for m in info.methods:
            keyed[(info.name, m)] = info
    def resolve(info: _ClassInfo, cs: _CallSite):
        if cs.on_self and cs.name in info.methods and not info.is_module:
            return (info.name, cs.name)
        owners = prog.method_owners(cs.name)
        if len(owners) == 1:
            return (owners[0].name, cs.name)
        return None
    eff = {k: set(v) for k, v in direct.items()}
    for _ in range(20):
        changed = False
        for k, sites in calls.items():
            info = keyed.get(k)
            if info is None:
                continue
            acc = eff.setdefault(k, set())
            before = len(acc)
            for cs in sites:
                tgt = resolve(info, cs)
                if tgt and tgt in eff:
                    acc |= eff[tgt]
            if len(acc) != before:
                changed = True
        if not changed:
            break
    edges: Dict[Tuple[str, str], str] = {}
    kind_of = {}
    for info in prog.classes:
        for ld in info.locks.values():
            kind_of[ld.node] = ld.kind
    for ld in prog.module_locks.values():
        kind_of[ld.node] = ld.kind
    def add(a, b, site):
        if a.startswith("?") or b.startswith("?"):
            return
        if a == b and kind_of.get(a) in ("rlock", "condition"):
            return      # legal reentrancy
        edges.setdefault((a, b), site)
    for info in prog.classes:
        for a in info.acquires:
            site = f"{info.name}.{a.method} " \
                   f"({os.path.basename(info.file)}:{a.line})"
            for h in a.held:
                add(h, a.lock, site)
    for k, sites in calls.items():
        info = keyed.get(k)
        if info is None:
            continue
        for cs in sites:
            if not cs.held:
                continue
            tgt = resolve(info, cs)
            if not tgt:
                continue
            for acq in eff.get(tgt, ()):
                site = f"{info.name}.{cs.method} -> {tgt[0]}.{tgt[1]} " \
                       f"({os.path.basename(info.file)}:{cs.line})"
                for h in cs.held:
                    add(h, acq, site)
    return edges


def _check_cycles(prog: _Program) -> List[Finding]:
    edges = _lock_graph(prog)
    adj: Dict[str, List[str]] = {}
    for (a, b) in edges:
        adj.setdefault(a, []).append(b)
    # Tarjan SCC
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    onstack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v):
        work = [(v, iter(adj.get(v, ())))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        onstack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    onstack.add(w)
                    work.append((w, iter(adj.get(w, ()))))
                    advanced = True
                    break
                elif w in onstack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                low[work[-1][0]] = min(low[work[-1][0]], low[node])
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    onstack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)

    nodes = set(adj) | {b for bs in adj.values() for b in bs}
    for v in sorted(nodes):
        if v not in index:
            strongconnect(v)

    findings = []
    for scc in sccs:
        cyclic = len(scc) > 1 or (scc[0], scc[0]) in edges
        if not cyclic:
            continue
        scc = sorted(scc)
        examples = [f"{a} -> {b} via {site}"
                    for (a, b), site in sorted(edges.items())
                    if a in scc and b in scc]
        findings.append(Finding(
            Severity.WARNING, "LOCK_ORDER_CYCLE",
            " -> ".join(scc + [scc[0]]),
            f"lock-acquisition cycle: {'; '.join(examples[:4])} — two "
            f"threads taking these locks in opposite orders deadlock",
            "pick one canonical order (document it in ARCHITECTURE's "
            "threading model) and release the first lock before taking "
            "the second on the reversed path", CHECKER,
            {"locks": scc, "edges": examples}))
    return findings


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _package_files(modname: str) -> List[Tuple[str, str]]:
    """(module, file) for a module or package (non-recursive)."""
    spec = importlib.util.find_spec(modname)
    if spec is None:
        raise ImportError(f"cannot locate module {modname!r}")
    if spec.submodule_search_locations:
        out = []
        for d in spec.submodule_search_locations:
            for fn in sorted(os.listdir(d)):
                if fn.endswith(".py"):
                    sub = fn[:-3]
                    sub = modname if sub == "__init__" \
                        else f"{modname}.{sub}"
                    out.append((sub, os.path.join(d, fn)))
        return out
    if not spec.origin or not spec.origin.endswith(".py"):
        raise ImportError(f"{modname!r} has no python source to lint")
    return [(modname, spec.origin)]


def _scan_sources(sources, prog: Optional[_Program] = None) -> _Program:
    """sources: iterable of (root, mod, file, src)."""
    prog = prog or _Program()
    walkers = []
    for root, mod, file, src in sources:
        tree = ast.parse(src, filename=file)
        fw = _FileWalker(prog, tree, src, root, mod, file)
        fw.collect()
        walkers.append(fw)
        # module-wide join evidence (for THREAD_LEAK)
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "join":
                try:
                    recv = ast.unparse(node.func.value)
                except Exception:   # noqa: BLE001
                    continue
                prog.joins.add(recv)
                prog.join_attrs.add(recv.rsplit(".", 1)[-1])
    for fw in walkers:
        fw.walk()
    return prog


def scan_modules(modules: Sequence[str] = DEFAULT_MODULES) -> _Program:
    sources = []
    for root in modules:
        for mod, file in _package_files(root):
            with open(file) as f:
                sources.append((root, mod, file, f.read()))
    return _scan_sources(sources)


def _program_findings(prog: _Program) -> List[Finding]:
    findings: List[Finding] = []
    for info in prog.classes:
        findings.extend(_check_writes(info))
        findings.extend(_check_reads(info))
        findings.extend(_check_blocking(info))
    findings.extend(_check_threads(prog))
    findings.extend(_check_cycles(prog))
    return findings


def _to_reports(prog: _Program, roots: Sequence[str], suppress=(),
                config=None, options=None) -> Dict[str, Report]:
    findings = _program_findings(prog)
    ctx = CheckContext(closed_jaxpr=None, options=dict(options or {}))
    by_root: Dict[str, List[Finding]] = {r: [] for r in roots}
    root_of_class = {}
    for info in prog.classes:
        root_of_class[info.name] = info.root
    for f in findings:
        root = None
        for r in roots:
            if f.eqn_path.startswith(r + "."):
                root = r
                break
        if root is None and f.code == "LOCK_ORDER_CYCLE":
            # cycles span modules: file them under the first lock's class
            first = f.data.get("locks", [""])[0].split(".")[0]
            root = root_of_class.get(first, roots[0])
        by_root.setdefault(root or roots[0], []).append(f)
    return {r: finalize_findings(fs, [CHECKER], ctx, suppress, config)
            for r, fs in by_root.items()}


def analyze_modules(modules: Sequence[str] = DEFAULT_MODULES,
                    suppress: Sequence[str] = (), config=None,
                    options=None) -> Dict[str, Report]:
    """Lint modules/packages; one Report per requested root.  Classes
    across all roots are resolved TOGETHER (cross-module lock-order
    edges, e.g. router lock vs engine lock)."""
    prog = scan_modules(tuple(modules))
    return _to_reports(prog, tuple(modules), suppress, config, options)


def analyze_source(src: str, modname: str = "<memory>",
                   suppress: Sequence[str] = (), config=None,
                   options=None) -> Report:
    """Lint one source string (fixtures/tests)."""
    prog = _scan_sources([(modname, modname, f"<{modname}>", src)])
    return _to_reports(prog, (modname,), suppress, config, options)[modname]


def inventory(modules: Sequence[str] = DEFAULT_MODULES) -> dict:
    """Thread/lock inventory for docs and `graphlint --threads -v`."""
    prog = scan_modules(tuple(modules))
    locks, threads = [], []
    for info in prog.classes:
        for ld in sorted(info.locks.values(), key=lambda x: x.attr):
            locks.append({"lock": ld.node, "kind": ld.kind,
                          "module": info.mod,
                          "file": os.path.basename(ld.file),
                          "line": ld.line})
        for tu in info.threads:
            threads.append({"where": f"{info.mod}.{info.name}."
                                     f"{tu.method}",
                            "target": tu.target, "daemon": tu.daemon,
                            "stored_as": tu.assigned,
                            "file": os.path.basename(tu.file),
                            "line": tu.line})
    for _name, ld in sorted(prog.module_locks.items()):
        locks.append({"lock": ld.node, "kind": ld.kind,
                      "module": ld.owner,
                      "file": os.path.basename(ld.file),
                      "line": ld.line})
    edges = _lock_graph(prog)
    return {"locks": locks, "threads": threads,
            "lock_order_edges": sorted(f"{a} -> {b}"
                                       for (a, b) in edges)}
