"""Static memory-liveness estimation over jaxprs.

The jaxpr half of the Graph Doctor's memory story (the HLO half reads
XLA's buffer assignment via `compiled.memory_analysis()` in `hlo.py`).
Jaxprs are pre-buffer-assignment, so this walker can only ESTIMATE peak
live bytes — but unlike the compiled number it is attributable: the peak
comes with the `eqn_path` that produced it, so "your step peaks at 31 GiB"
becomes "the attention residuals inside `scan:layers/body` do".

Model (documented so the 2x-of-XLA acceptance bound is interpretable):

  * a value is live from the eqn that creates it to its last use;
  * NON-donated top-level args stay live for the whole program (the
    caller owns the buffer; XLA cannot reuse it) — donated args may
    ALIAS an output: at their last use they free BEFORE the eqn's
    outputs materialize, which is exactly what donation buys;
  * a traced jitted fn is one top-level pjit eqn: the walker descends
    into it with that eqn's `donated_invars` mask, so the estimate is
    the jitted program's, not the trivial wrapper's;
  * jaxpr outvars stay live to the end (they are the result);
  * scan/while bodies reuse one iteration's buffers across trips (memory
    does NOT scale with trip count — only the stacked ys do, and those
    are the scan eqn's outvars); the body's internal peak is measured
    recursively and added at the scan point;
  * `cond` takes the max across branches; pallas_call is opaque (its
    scratch is per-grid-step and registered kernels account their own
    cost) — operands/results are already counted.

XLA's fusion will beat these numbers (fused producers never materialize);
buffer assignment's padding/alignment will worsen them.  Empirically the
estimate lands within ~2x of `temp_size + output_size + aliased args`
for the shipped models, which is enough to rank models, catch a
temp-bloat regression in CI, and attribute it to source.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax

from .core import (
    CheckContext, Finding, Severity, aval_bytes, fmt_bytes, format_path,
    is_array_var, register_checker, sub_jaxprs, _as_open,
)

__all__ = ["MemoryEstimate", "estimate", "jaxpr_memory"]


@dataclasses.dataclass
class MemoryEstimate:
    """Static peak-live-bytes estimate with attribution."""

    peak_bytes: int
    peak_path: str              # eqn_path live at the peak
    args_bytes: int             # all top-level args (donated + not)
    donated_bytes: int          # of which donated (die at last use)
    consts_bytes: int           # captured constants (always live)
    out_bytes: int              # program outputs
    top: List[dict] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {"peak_bytes": self.peak_bytes, "peak_path": self.peak_path,
                "args_bytes": self.args_bytes,
                "donated_bytes": self.donated_bytes,
                "consts_bytes": self.consts_bytes,
                "out_bytes": self.out_bytes, "top": list(self.top)}


def _var_bytes(v) -> int:
    return aval_bytes(v.aval) if is_array_var(v) else 0


def _walk(jaxpr, donated: List[bool], path: Tuple[str, ...],
          record: Optional[List[Tuple[int, str]]], depth: int,
          ) -> Tuple[int, str, int]:
    """Peak live bytes of one (open) jaxpr, its invars counted as live.

    Returns (peak, peak_path, invars_bytes).  `donated[i]` marks invars
    that may die at last use; non-donated invars and the jaxpr's outvars
    are pinned.  `record` (top level only) collects (live_bytes, path)
    samples for the top-k table.
    """
    jaxpr = _as_open(jaxpr)
    eqns = jaxpr.eqns
    n = len(eqns)

    # last use index per var (invars + produced); pinned vars use `n`
    pinned = set()
    donated_set = set()
    for v, d in zip(jaxpr.invars, donated):
        if not is_array_var(v):
            continue
        if d:
            donated_set.add(v)
        else:
            pinned.add(v)
    pinned.update(v for v in jaxpr.outvars if is_array_var(v))
    last_use: Dict[Any, int] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.invars:
            if is_array_var(v):
                last_use[v] = i

    invars_b = sum(_var_bytes(v) for v in jaxpr.invars)
    live = invars_b
    peak, peak_path = live, format_path(path) + ":<args>"
    if record is not None:
        record.append((live, peak_path))

    for i, eqn in enumerate(eqns):
        # donated args at their LAST use free before the outputs
        # materialize — the output may alias their buffer (what
        # donate_argnums buys); everything else stays live while the
        # eqn reads it
        for v in eqn.invars:
            if is_array_var(v) and v in donated_set and v not in pinned \
                    and last_use.get(v) == i:
                live -= _var_bytes(v)
                pinned.add(v)
        out_b = sum(_var_bytes(v) for v in eqn.outvars)
        live += out_b
        eqn_label = format_path(path, eqn)
        attr = eqn_label            # where a new peak is attributed

        # recurse: the body's internal temporaries spike live memory at
        # this point.  Body invars alias eqn invars (already counted), so
        # subtract them from the sub-peak; pjit bodies keep their own
        # donation mask, loop bodies reuse one iteration's buffers.
        sub_extra = 0
        if depth > 0:
            for sublabel, sub, _w in sub_jaxprs(eqn):
                sub_open = _as_open(sub)
                mask = eqn.params.get("donated_invars") \
                    if eqn.primitive.name == "pjit" else None
                if mask is None or len(mask) != len(sub_open.invars):
                    mask = [True] * len(sub_open.invars)
                sp, spp, sb = _walk(
                    sub, list(mask),
                    path + (eqn_label.split("/")[-1], sublabel),
                    None, depth - 1)
                extra = max(0, sp - sb)
                if extra > sub_extra:
                    sub_extra = extra
                    if live + extra > peak:
                        attr = spp  # attribute into the body

        cand = live + sub_extra
        if record is not None:
            record.append((cand, eqn_label))
        if cand > peak:
            peak, peak_path = cand, attr

        # free values whose last use was this eqn (incl. dead outvars)
        for v in eqn.invars:
            if is_array_var(v) and v not in pinned \
                    and last_use.get(v) == i:
                live -= _var_bytes(v)
                pinned.add(v)      # freed once, never again
        for v in eqn.outvars:
            if is_array_var(v) and v not in pinned \
                    and last_use.get(v, i) == i:
                live -= _var_bytes(v)
                pinned.add(v)
    return peak, peak_path, invars_b


def jaxpr_memory(closed_jaxpr, donated_invars: Optional[List[bool]] = None,
                 top_k: int = 3, max_depth: int = 16) -> MemoryEstimate:
    """Estimate peak live bytes of an already-traced ClosedJaxpr.

    donated_invars: per-invar donation mask.  When None and the jaxpr is
    a single top-level pjit eqn (a traced jitted fn), the mask is read
    off that eqn's `donated_invars` — the common `analyze(jitted_fn, ...)`
    shape; otherwise nothing is donated (conservative).
    """
    jaxpr = closed_jaxpr.jaxpr
    consts_b = sum(int(getattr(c, "nbytes", 0) or 0)
                   for c in closed_jaxpr.consts)
    donated = donated_invars
    path: Tuple[str, ...] = ()
    if donated is None:
        donated = [False] * len(jaxpr.invars)
        # a traced jitted fn is one pjit eqn wrapping everything: walk
        # the INNER program under that eqn's donation mask (the outer
        # wrapper would hide both the donation and the real liveness)
        if len(jaxpr.eqns) == 1 and jaxpr.eqns[0].primitive.name == "pjit":
            eqn = jaxpr.eqns[0]
            inner = eqn.params.get("jaxpr")
            mask = eqn.params.get("donated_invars")
            if inner is not None:
                inner_open = _as_open(inner)
                if mask is None or len(mask) != len(inner_open.invars):
                    mask = [False] * len(inner_open.invars)
                consts_b += sum(
                    int(getattr(c, "nbytes", 0) or 0)
                    for c in getattr(inner, "consts", ()))
                jaxpr, donated = inner_open, list(mask)
                path = (f"pjit:{eqn.params.get('name', '')}",)
    record: List[Tuple[int, str]] = []
    peak, peak_path, _ = _walk(jaxpr, donated, path, record, max_depth)
    record.sort(key=lambda t: -t[0])
    seen, top = set(), []
    for b, p in record:
        if p in seen:
            continue
        seen.add(p)
        top.append({"live_bytes": int(b), "path": p})
        if len(top) >= top_k:
            break
    return MemoryEstimate(
        peak_bytes=int(peak + consts_b), peak_path=peak_path,
        args_bytes=sum(_var_bytes(v) for v in jaxpr.invars),
        donated_bytes=sum(_var_bytes(v)
                          for v, d in zip(jaxpr.invars, donated) if d),
        consts_bytes=int(consts_b),
        out_bytes=sum(_var_bytes(v) for v in jaxpr.outvars),
        top=top)


def estimate(fn_or_jaxpr, *args, top_k: int = 3, **kwargs) -> dict:
    """profiler.static_memory: trace `fn(*args)` (or take a ClosedJaxpr)
    and return the MemoryEstimate as a dict.  Nothing executes."""
    if args or kwargs or callable(fn_or_jaxpr):
        import functools
        traced = (functools.partial(fn_or_jaxpr, **kwargs) if kwargs
                  else fn_or_jaxpr)
        closed = jax.make_jaxpr(traced)(*args)
    else:
        closed = fn_or_jaxpr
    return jaxpr_memory(closed, top_k=top_k).to_dict()


# ---------------------------------------------------------------------------
# checker: MEM_PEAK (INFO always — the number every report should carry;
# WARNING when a budget is configured and exceeded)
# ---------------------------------------------------------------------------


@register_checker("memory")
def check_memory(ctx: CheckContext):
    est = jaxpr_memory(ctx.closed_jaxpr, top_k=ctx.opt("memory_top_k"))
    budget = ctx.opt("mem_peak_budget_bytes")
    over = budget is not None and est.peak_bytes > int(budget)
    msg = (f"static peak live ~{fmt_bytes(est.peak_bytes)} at "
           f"{est.peak_path} (args {fmt_bytes(est.args_bytes)}, "
           f"{fmt_bytes(est.donated_bytes)} donated; outputs "
           f"{fmt_bytes(est.out_bytes)})")
    if over:
        msg += f" — exceeds the configured budget {fmt_bytes(int(budget))}"
    yield Finding(
        Severity.WARNING if over else Severity.INFO, "MEM_PEAK",
        est.peak_path, msg,
        ("donate read-write args, shard or re-materialize the live set at "
         "the peak path, or raise the budget" if over else
         "profiler.static_memory(fn, *args) returns the same estimate "
         "as data"),
        data=est.to_dict())
