"""Graph Doctor tier 3: VERIFIED jaxpr rewrites — findings become transforms.

PRs 3+5 built the analysis half of the reference's ~274-pass IR pipeline
(diagnose, report, suggest).  This module is the rewrite half at the
jaxpr level: a registry of transform passes that MIRRORS the checker
registry in `core.py` — each pass declares which `Finding` codes it
CONSUMES, takes a `ClosedJaxpr` plus the findings, and returns a
rewritten jaxpr with a structured `RewriteAction` log.

    donation     consumes DONATION_MISSING   flips `donated_invars` on the
                                             flagged pjit eqns (the exact
                                             argnums fixes.py suggests)
    dce          consumes DEAD_CODE          drops dead eqns / unused
                                             consts, recursing pjit/scan
                                             bodies like `analyze` does
    dtype_cast   consumes DTYPE_F64_*        narrows the flagged f64/c128
                 / DTYPE_WEAK_F64            creation points to f32/c64 by
                                             re-tracing with cast rules
    fusion       consumes FUSION_BREAK       stitches hot unfused
                                             elementwise chains into ONE
                                             fused call (generated Pallas
                                             kernel on TPU, jitted closure
                                             or interpret-mode kernel off)
    inline_fusion consumes FUSION_BREAK      same stitching, but FIRST
                                             inlines worthwhile pjit
                                             edges so chains that today
                                             stop at a container boundary
                                             (the decode step body) become
                                             contiguous and fuse; runs
                                             ahead of `fusion`, which
                                             stays as the fallback when
                                             inlining finds nothing

The VERIFICATION GATE (the part the reference pipeline gets by code
review and we get by machine): every candidate rewrite must pass
`equiv.verify` — original vs rewritten evaluated on probe inputs,
forward at dtype-tiered tolerance (token-exact for ints) and gradients
where differentiable — AND a re-lint: the consumed findings must shrink
and no new warning-level codes may appear.  A rewrite that fails either
check is ROLLED BACK and reported; it is never silently applied.

Surfaces: `rewrite(fn, *args, passes=[...])` returns a drop-in callable
plus a `RewriteReport` (per-pass eqn deltas + static FLOPs/bytes
deltas); `tools/graphlint.py --fix --apply` runs it over the shipped
bench targets; `static.Program.rewrite()` bridges record programs.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from . import cost as cost_lib
from . import equiv
from .core import (
    Finding, Report, Severity, _as_open, _eqn_label, analyze_jaxpr,
    aval_bytes, fmt_bytes, format_path, is_array_var, iter_eqns,
    _OPAQUE_PRIMS,
)

__all__ = [
    "RewriteAction", "PassOutcome", "RewriteReport", "RewriteContext",
    "register_rewrite", "list_rewrites", "rewrite", "rewrite_jaxpr",
    "REWRITE_REGISTRY",
]

_Literal = jax.core.Literal

# wide -> narrow dtype map for the dtype_cast pass (TPUs emulate f64)
_NARROW = {"float64": jnp.float32, "complex128": jnp.complex64}


# ---------------------------------------------------------------------------
# result types
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RewriteAction:
    """One concrete edit a pass made: which finding code it settles,
    where, and what changed."""

    pass_name: str
    code: str
    eqn_path: str
    description: str
    data: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {"pass": self.pass_name, "code": self.code,
                "eqn_path": self.eqn_path, "description": self.description,
                "data": dict(self.data)}

    def __str__(self):
        return f"[{self.pass_name}] {self.code} @ {self.eqn_path}: " \
               f"{self.description}"


@dataclasses.dataclass
class PassOutcome:
    """One pass's run: what it did and what the verification gate said.

    status: "skipped" (no consumable findings), "no-op" (findings but
    nothing rewritable), "applied" (verified and kept), "rolled_back"
    (candidate produced but REJECTED by the gate), "failed" (the pass
    itself raised — treated like a rollback, the input jaxpr survives).
    """

    name: str
    status: str
    actions: List[RewriteAction] = dataclasses.field(default_factory=list)
    eqns_before: int = 0
    eqns_after: int = 0
    flops_before: float = 0.0
    flops_after: float = 0.0
    bytes_before: int = 0
    bytes_after: int = 0
    reason: str = ""
    equiv: Optional[dict] = None        # equiv.EquivResult.to_dict()

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["actions"] = [a.to_dict() for a in self.actions]
        return d


class RewriteReport:
    """Ordered pass outcomes + roll-ups — what `--fix --apply` writes."""

    def __init__(self, outcomes: Sequence[PassOutcome],
                 eqns_before: int = 0, eqns_after: int = 0,
                 flops_before: float = 0.0, flops_after: float = 0.0,
                 bytes_before: int = 0, bytes_after: int = 0):
        self.outcomes = list(outcomes)
        self.eqns_before, self.eqns_after = eqns_before, eqns_after
        self.flops_before, self.flops_after = flops_before, flops_after
        self.bytes_before, self.bytes_after = bytes_before, bytes_after

    @property
    def applied(self) -> List[str]:
        return [o.name for o in self.outcomes if o.status == "applied"]

    @property
    def rolled_back(self) -> List[str]:
        return [o.name for o in self.outcomes
                if o.status in ("rolled_back", "failed")]

    @property
    def ok(self) -> bool:
        """True when nothing was rejected — every attempted rewrite
        verified (a no-op run is ok; a rollback is not)."""
        return not self.rolled_back

    @property
    def actions(self) -> List[RewriteAction]:
        return [a for o in self.outcomes for a in o.actions]

    def to_json(self) -> dict:
        return {"passes": [o.to_dict() for o in self.outcomes],
                "applied": self.applied, "rolled_back": self.rolled_back,
                "ok": self.ok,
                "eqns_before": self.eqns_before,
                "eqns_after": self.eqns_after,
                "flops_before": self.flops_before,
                "flops_after": self.flops_after,
                "bytes_before": self.bytes_before,
                "bytes_after": self.bytes_after}

    def __str__(self):
        lines = []
        for o in self.outcomes:
            line = f"pass {o.name}: {o.status}"
            if o.status == "applied":
                line += (f" ({len(o.actions)} action(s), eqns "
                         f"{o.eqns_before} -> {o.eqns_after}, ~"
                         f"{o.flops_before:.3g} -> ~{o.flops_after:.3g} "
                         f"FLOPs, {fmt_bytes(o.bytes_before)} -> "
                         f"{fmt_bytes(o.bytes_after)})")
            elif o.reason:
                line += f" ({o.reason})"
            lines.append(line)
            for a in o.actions[:8]:
                lines.append(f"  {a}")
        lines.append(
            f"-- rewrite: eqns {self.eqns_before} -> {self.eqns_after}, "
            f"{len(self.applied)} applied, {len(self.rolled_back)} "
            "rolled back")
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# registry (mirrors core.CHECKER_REGISTRY)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _RewritePass:
    name: str
    consumes: Tuple[str, ...]           # finding-code globs this pass eats
    fn: Callable                        # fn(ctx: RewriteContext) -> jaxpr|None


REWRITE_REGISTRY: Dict[str, _RewritePass] = {}

# default order: shrink first (dce), then retype, then restructure, then
# annotate — inline_fusion ahead of fusion (when it applies it consumes
# the FUSION_BREAK findings, so the boundary-limited pass is skipped;
# when it rolls back or no-ops, plain fusion still runs), then
# shard_constraint before donation (it rebuilds pjit bodies), donation
# last so it sees the final pjit structure
_DEFAULT_PASSES = ("dce", "dtype_cast", "inline_fusion", "fusion",
                   "shard_constraint", "donation")


def register_rewrite(name: str, consumes: Sequence[str]):
    """Register a rewrite pass: `fn(ctx) -> ClosedJaxpr | None` (None =
    nothing to do).  `consumes` are the Finding codes (globs allowed)
    whose presence triggers the pass; ctx.findings holds the matches."""
    def deco(fn):
        REWRITE_REGISTRY[name] = _RewritePass(name, tuple(consumes), fn)
        fn._rewrite_name = name
        return fn
    return deco


def list_rewrites() -> List[str]:
    return sorted(REWRITE_REGISTRY)


@dataclasses.dataclass
class RewriteContext:
    """What a pass may inspect: the jaxpr, the findings it consumes, the
    option knobs (same keys as CheckContext), and the action log it
    appends to."""

    closed_jaxpr: Any
    findings: List[Finding]
    options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    actions: List[RewriteAction] = dataclasses.field(default_factory=list)
    notes: List[str] = dataclasses.field(default_factory=list)
    # the device mesh the program targets — the shard_constraint pass
    # needs it to build NamedShardings; None for single-device programs
    mesh: Any = None

    def opt(self, key: str, default=None):
        from .core import _DEFAULT_OPTIONS
        if key in self.options:
            return self.options[key]
        return _DEFAULT_OPTIONS.get(key, default)

    def act(self, code: str, eqn_path: str, description: str, **data):
        self.actions.append(RewriteAction(
            pass_name="", code=code, eqn_path=eqn_path,
            description=description, data=data))


# ---------------------------------------------------------------------------
# jaxpr plumbing shared by the passes
# ---------------------------------------------------------------------------


def _count_eqns(closed) -> int:
    return sum(1 for _ in iter_eqns(closed))


def _join_effects(eqns):
    join = getattr(jax.core, "join_effects", None)
    if join is None:
        out = set()
        for e in eqns:
            out |= set(e.effects)
        return frozenset(out)
    return join(*(e.effects for e in eqns))


def _sub_closed_params(eqn):
    """(label, getter_key, index, sub) for every jaxpr-valued param,
    labels matching core.sub_jaxprs so rewritten paths line up with
    checker paths.  Opaque prims yield nothing."""
    if eqn.primitive.name in _OPAQUE_PRIMS:
        return
    p = eqn.params
    if eqn.primitive.name == "scan":
        yield "body", "jaxpr", None, p["jaxpr"]
        return
    if eqn.primitive.name == "while":
        yield "cond", "cond_jaxpr", None, p["cond_jaxpr"]
        yield "body", "body_jaxpr", None, p["body_jaxpr"]
        return
    if eqn.primitive.name == "cond":
        for i, b in enumerate(p["branches"]):
            yield f"branch{i}", "branches", i, b
        return
    from jax.extend import core as jex_core
    for k, v in p.items():
        if isinstance(v, (jex_core.Jaxpr, jex_core.ClosedJaxpr)):
            yield k, k, None, v
        elif isinstance(v, (tuple, list)) and v and all(
                isinstance(x, (jex_core.Jaxpr, jex_core.ClosedJaxpr))
                for x in v):
            for i, x in enumerate(v):
                yield f"{k}[{i}]", k, i, x


def _replace_sub(eqn, replacements: Dict[Tuple[str, Optional[int]], Any]):
    """New eqn with jaxpr-valued params swapped per {(key, idx): sub}."""
    if not replacements:
        return eqn
    new_params = dict(eqn.params)
    for (key, idx), sub in replacements.items():
        if idx is None:
            new_params[key] = sub
        else:
            seq = list(new_params[key])
            seq[idx] = sub
            new_params[key] = type(eqn.params[key])(seq) \
                if isinstance(eqn.params[key], tuple) else seq
    return eqn.replace(params=new_params)


def _wrap_like(template, new_open):
    """Re-wrap an open jaxpr the way the template param was wrapped."""
    from jax.extend import core as jex_core
    if isinstance(template, jex_core.ClosedJaxpr):
        return jex_core.ClosedJaxpr(new_open, template.consts)
    return new_open


# ---------------------------------------------------------------------------
# pass 1: donation injection (surgery on pjit donated_invars)
# ---------------------------------------------------------------------------


def _donation_candidates(eqn, min_bytes: int) -> List[int]:
    """Positions of undonated big invars that aval-match a free output —
    the same matching the donation checker (and fixes.py) performs, so
    the flipped mask IS the suggested donate_argnums."""
    donated = eqn.params.get("donated_invars")
    if donated is None:
        return []
    out_pool: Dict[tuple, int] = {}
    for ov in eqn.outvars:
        if is_array_var(ov):
            k = (tuple(ov.aval.shape), str(ov.aval.dtype))
            out_pool[k] = out_pool.get(k, 0) + 1

    def take(k):
        if out_pool.get(k, 0) > 0:
            out_pool[k] -= 1
            return True
        return False

    undonated = []
    for i, (v, don) in enumerate(zip(eqn.invars, donated)):
        if not is_array_var(v):
            continue
        if don:
            take((tuple(v.aval.shape), str(v.aval.dtype)))
        else:
            undonated.append((i, v))
    picks = []
    for i, v in undonated:
        if aval_bytes(v.aval) < min_bytes:
            continue
        if take((tuple(v.aval.shape), str(v.aval.dtype))):
            picks.append(i)
    return picks


@register_rewrite("donation", consumes=("DONATION_MISSING",))
def rewrite_donation(ctx: RewriteContext):
    """Flip `donated_invars` on the flagged pjit eqns — the jaxpr-level
    equivalent of adding donate_argnums at the jit call site.  Numerics
    are untouched (donation is a buffer-aliasing hint); the gate still
    runs, catching a mask that desynchronizes the eqn."""
    flagged = {f.eqn_path for f in ctx.findings}
    min_bytes = ctx.opt("donation_min_bytes")
    changed = [0]

    def visit(jaxpr, path, depth=8):
        if depth <= 0:
            return jaxpr
        new_eqns = []
        for eqn in jaxpr.eqns:
            reps = {}
            for label, key, idx, sub in _sub_closed_params(eqn):
                new_sub_open = visit(
                    _as_open(sub), path + (_eqn_label(eqn), label),
                    depth - 1)
                if new_sub_open is not _as_open(sub):
                    reps[(key, idx)] = _wrap_like(sub, new_sub_open)
            eqn = _replace_sub(eqn, reps)
            if eqn.primitive.name == "pjit" \
                    and format_path(path, eqn) in flagged:
                picks = _donation_candidates(eqn, min_bytes)
                if picks:
                    mask = list(eqn.params["donated_invars"])
                    for i in picks:
                        mask[i] = True
                    eqn = eqn.replace(params=dict(
                        eqn.params, donated_invars=tuple(mask)))
                    changed[0] += 1
                    ctx.act(
                        "DONATION_MISSING", format_path(path, eqn),
                        f"donated invars {tuple(picks)} of jitted fn "
                        f"{eqn.params.get('name', '?')!r}",
                        argnums=picks)
            new_eqns.append(eqn)
        if all(a is b for a, b in zip(new_eqns, jaxpr.eqns)):
            return jaxpr
        return jaxpr.replace(eqns=new_eqns)

    closed = ctx.closed_jaxpr
    new_open = visit(closed.jaxpr, ())
    if not changed[0]:
        return None
    from jax.extend import core as jex_core
    return jex_core.ClosedJaxpr(new_open, closed.consts)


# ---------------------------------------------------------------------------
# pass 2: dead-code elimination (surgery, recursing like the checker)
# ---------------------------------------------------------------------------


@register_rewrite("dce", consumes=("DEAD_CODE",))
def rewrite_dce(ctx: RewriteContext):
    """Actually drop the dead eqns the liveness checker flags: reverse
    liveness per (sub-)jaxpr from its outvars, keeping effects, then
    prune constvars that lost their last reader.  Invars/outvars are
    never touched, so caller signatures are preserved by construction."""
    dropped: List[Tuple[str, str]] = []

    def dce(jaxpr, path, depth=8):
        eqns = jaxpr.eqns
        live = {v for v in jaxpr.outvars if is_array_var(v)}
        keep = [False] * len(eqns)
        for i in range(len(eqns) - 1, -1, -1):
            eqn = eqns[i]
            if eqn.effects or any(is_array_var(v) and v in live
                                  for v in eqn.outvars):
                keep[i] = True
                live.update(v for v in eqn.invars if is_array_var(v))
        new_eqns = []
        for i, eqn in enumerate(eqns):
            if not keep[i]:
                dropped.append((format_path(path, eqn),
                                eqn.primitive.name))
                continue
            if depth > 0:
                reps = {}
                for label, key, idx, sub in _sub_closed_params(eqn):
                    sub_open = _as_open(sub)
                    new_sub = dce(sub_open,
                                  path + (_eqn_label(eqn), label),
                                  depth - 1)
                    if new_sub is not sub_open:
                        reps[(key, idx)] = _wrap_like(sub, new_sub)
                eqn = _replace_sub(eqn, reps)
            new_eqns.append(eqn)
        if len(new_eqns) == len(eqns) and all(
                a is b for a, b in zip(new_eqns, eqns)):
            return jaxpr
        return jaxpr.replace(eqns=new_eqns,
                             effects=_join_effects(new_eqns))

    closed = ctx.closed_jaxpr
    new_open = dce(closed.jaxpr, ())
    if new_open is closed.jaxpr:
        return None
    # prune constvars whose last reader died with the dead eqns
    used = set()
    for eqn, _p, _w in iter_eqns(new_open):
        used.update(v for v in eqn.invars if is_array_var(v))
    used.update(v for v in new_open.outvars if is_array_var(v))
    kept_pairs = [(cv, c) for cv, c in
                  zip(new_open.constvars, closed.consts) if cv in used]
    if len(kept_pairs) != len(new_open.constvars):
        new_open = new_open.replace(
            constvars=[cv for cv, _ in kept_pairs])
    consts = [c for _, c in kept_pairs]
    for path, prim in dropped[:32]:
        ctx.act("DEAD_CODE", path, f"dropped dead {prim} eqn")
    if len(dropped) > 32:
        ctx.act("DEAD_CODE", "<report>",
                f"... and {len(dropped) - 32} further dead eqn(s)")
    from jax.extend import core as jex_core
    return jex_core.ClosedJaxpr(new_open, consts)


# ---------------------------------------------------------------------------
# re-tracing interpreter (shared by dtype_cast and fusion)
# ---------------------------------------------------------------------------

# containers we can rebuild with rules active inside; anything else with
# a flagged interior is left alone (the findings are skipped, not risked)
_REBUILDABLE = frozenset({"pjit", "scan", "cond"})

_UNSUPPORTED_SEGMENTS = frozenset({
    "while", "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
    "pallas_call", "remat", "checkpoint", "closed_call", "core_call",
    "named_call", "custom_partitioning",
})


def _path_supported(eqn_path: str) -> bool:
    """True when every container segment on the path is rebuildable."""
    for seg in eqn_path.split("/")[:-1]:
        if seg.split(":")[0] in _UNSUPPORTED_SEGMENTS:
            return False
    return True


class _RetraceRules:
    """Hook points for `_retrace`: a per-scope plan, a per-eqn override,
    and a recursion predicate for containers."""

    def scope_plan(self, jaxpr, path):
        return None

    def on_eqn(self, eqn, path, invals, plan, read):
        return None                     # default re-bind

    def wants(self, sub_jaxpr, path) -> bool:
        return False


def _cast_like(x, aval):
    dt = getattr(aval, "dtype", None)
    if dt is None or getattr(x, "dtype", dt) == dt:
        return x
    return jax.lax.convert_element_type(x, dt)


def _bind_default(eqn, invals):
    subfuns, bind_params = eqn.primitive.get_bind_params(eqn.params)
    out = eqn.primitive.bind(*subfuns, *invals, **bind_params)
    return list(out) if eqn.primitive.multiple_results else [out]


def _harmonize_drift(eqn, invals):
    """Raw primitive binds do not auto-promote: when an upstream rewrite
    narrowed one operand (f64 -> f32), sibling operands that shared the
    SAME original dtype (incl. wide literals) must follow, or lax prims
    bind inconsistent eqns.  Operands whose original dtype saw no drift
    are left alone (select_n preds, gather indices)."""
    remap: Dict[str, Any] = {}
    for x, v in zip(invals, eqn.invars):
        od = str(getattr(getattr(v, "aval", None), "dtype", ""))
        nd = str(getattr(x, "dtype", jnp.result_type(x)))
        if od and od != nd:
            remap.setdefault(od, nd)
    if not remap:
        return invals
    fixed = []
    for x, v in zip(invals, eqn.invars):
        od = str(getattr(getattr(v, "aval", None), "dtype", ""))
        nd = str(getattr(x, "dtype", jnp.result_type(x)))
        tgt = remap.get(od)
        if tgt is not None and nd != tgt:
            x = jax.lax.convert_element_type(x, jnp.dtype(tgt))
        fixed.append(x)
    return fixed


def _interp(jaxpr, consts, args, path, rules: _RetraceRules):
    env: Dict[Any, Any] = {}

    def read(v):
        return v.val if isinstance(v, _Literal) else env[v]

    for cv, c in zip(jaxpr.constvars, consts):
        env[cv] = c
    for iv, a in zip(jaxpr.invars, args):
        env[iv] = a
    plan = rules.scope_plan(jaxpr, path)
    for eqn in jaxpr.eqns:
        r = rules.on_eqn(eqn, path, None, plan, read)
        if r is not None and r[0] == "skip":
            continue
        if r is not None and r[0] == "compute":
            outs = r[1]()               # thunk reads its own operands
        else:
            invals = [read(v) for v in eqn.invars]
            subs = list(_sub_closed_params(eqn))
            recurse = (eqn.primitive.name in _REBUILDABLE and subs
                       and any(rules.wants(
                           _as_open(s), path + (_eqn_label(eqn), lbl))
                           for lbl, _k, _i, s in subs))
            if recurse:
                outs = _rebuild_container(eqn, invals, path, rules)
            else:
                if subs or eqn.primitive.name in _OPAQUE_PRIMS:
                    # container params were typed against the original
                    # dtypes: pin drifted operands back at the boundary
                    invals = [_cast_like(x, v.aval)
                              for x, v in zip(invals, eqn.invars)]
                else:
                    invals = _harmonize_drift(eqn, invals)
                outs = _bind_default(eqn, invals)
        for ov, o in zip(eqn.outvars, outs):
            if is_array_var(ov):
                env[ov] = o
    return [read(v) for v in jaxpr.outvars]


def _struct(x):
    return jax.ShapeDtypeStruct(np.shape(x), jnp.result_type(x))


def _rebuild_container(eqn, invals, path, rules):
    prim = eqn.primitive.name
    p = eqn.params
    label = _eqn_label(eqn)
    if prim == "pjit":
        inner = p["jaxpr"]

        def inner_fn(*xs):
            return _interp(inner.jaxpr, inner.consts, xs,
                           path + (label, "jaxpr"), rules)

        inner_fn.__name__ = str(p.get("name") or "fn")
        dn = tuple(i for i, d in enumerate(p.get("donated_invars") or ())
                   if d)
        try:
            jf = jax.jit(inner_fn, donate_argnums=dn) if dn \
                else jax.jit(inner_fn)
            return list(jf(*invals))
        except Exception:  # noqa: BLE001 — donation may not retrace
            return list(jax.jit(inner_fn)(*invals))
    if prim == "scan":
        nc, nk = p["num_consts"], p["num_carry"]
        body = p["jaxpr"]
        cvals, carry0, xs = invals[:nc], invals[nc:nc + nk], invals[nc + nk:]
        spath = path + (label, "body")

        def body_fn(carry, x):
            outs = _interp(body.jaxpr, body.consts,
                           [*cvals, *carry, *x], spath, rules)
            return tuple(outs[:nk]), tuple(outs[nk:])

        x_structs = tuple(jax.ShapeDtypeStruct(np.shape(x)[1:], x.dtype)
                          for x in xs)
        carry_t = tuple(_struct(c) for c in carry0)
        for _ in range(3):              # carry-dtype fixpoint after rules
            nxt, _ys = jax.eval_shape(body_fn, carry_t, x_structs)
            nxt = tuple(jax.ShapeDtypeStruct(c.shape, c.dtype) for c in nxt)
            if nxt == carry_t:
                break
            carry_t = nxt

        def body_pinned(carry, x):
            c, ys = body_fn(carry, x)
            return tuple(_cast_like(a, t) for a, t in zip(c, carry_t)), ys

        init = tuple(_cast_like(c, t) for c, t in zip(carry0, carry_t))
        carry_out, ys = jax.lax.scan(
            body_pinned, init, tuple(xs), length=p.get("length"),
            reverse=bool(p.get("reverse", False)),
            unroll=int(p.get("unroll", 1) or 1))
        return [*carry_out, *ys]
    if prim == "cond":
        branches = p["branches"]
        ops = invals[1:]

        def mk(i, b):
            def f(*xs):
                return tuple(_interp(b.jaxpr, b.consts, xs,
                                     path + (label, f"branch{i}"), rules))
            return f

        fns = [mk(i, b) for i, b in enumerate(branches)]
        shapes = [jax.eval_shape(f, *ops) for f in fns]
        joined = [jnp.result_type(*(s[i].dtype for s in shapes))
                  for i in range(len(shapes[0]))]

        def pin(f):
            return lambda *xs: tuple(
                _cast_like(o, jax.ShapeDtypeStruct((), d))
                for o, d in zip(f(*xs), joined))

        idx = jnp.clip(jnp.asarray(invals[0], jnp.int32), 0, len(fns) - 1)
        return list(jax.lax.switch(idx, [pin(f) for f in fns], *ops))
    raise NotImplementedError(prim)


def _retrace(closed, rules: _RetraceRules):
    def run(*flat):
        return _interp(closed.jaxpr, closed.consts, flat, (), rules)

    structs = [jax.ShapeDtypeStruct(tuple(v.aval.shape), v.aval.dtype)
               for v in closed.jaxpr.invars]
    return jax.make_jaxpr(run)(*structs)


# ---------------------------------------------------------------------------
# pass 3: dtype unification (retrace with narrowing rules)
# ---------------------------------------------------------------------------


def _narrow_val(x):
    dt = str(getattr(x, "dtype", jnp.result_type(x)))
    if dt in _NARROW:
        return jax.lax.convert_element_type(x, _NARROW[dt])
    return x


class _DtypeRules(_RetraceRules):
    def __init__(self, flagged: set, ctx: RewriteContext):
        self.flagged = flagged
        self.ctx = ctx
        self.hit: set = set()

    def wants(self, sub_jaxpr, path) -> bool:
        prefix = "/".join(path) + "/" if path else ""
        return any(f.startswith(prefix) for f in self.flagged)

    def on_eqn(self, eqn, path, invals, plan, read):
        p = format_path(path, eqn)
        if p not in self.flagged:
            return None
        # a flagged CONTAINER (pjit/scan whose output is wide) is fixed
        # from inside — the interior creation point carries its own
        # finding and the narrowed dtype propagates out on retrace
        if eqn.primitive.name in _OPAQUE_PRIMS \
                or any(True for _ in _sub_closed_params(eqn)):
            return None

        def compute():
            vals = [read(v) for v in eqn.invars]
            prim = eqn.primitive
            if prim.name == "convert_element_type":
                tgt = str(eqn.params.get("new_dtype"))
                if tgt in _NARROW:
                    self.hit.add(p)
                    self.ctx.act(
                        "DTYPE_F64_PROMOTION", p,
                        f"retargeted convert_element_type {tgt} -> "
                        f"{_NARROW[tgt].__name__}")
                    return [jax.lax.convert_element_type(
                        vals[0], _NARROW[tgt])]
            narrowed = [_narrow_val(v) for v in vals]
            outs = _bind_default(eqn, narrowed)
            outs = [_narrow_val(o) for o in outs]
            self.hit.add(p)
            self.ctx.act(
                "DTYPE_F64_PROMOTION", p,
                f"narrowed {prim.name} operands/output to float32 at the "
                "flagged creation point")
            return outs

        return ("compute", compute)


@register_rewrite("dtype_cast",
                  consumes=("DTYPE_F64_PROMOTION", "DTYPE_WEAK_F64"))
def rewrite_dtype(ctx: RewriteContext):
    """Narrow the flagged f64/c128 CREATION points to f32/c64 and let the
    retrace propagate the narrow dtype downstream — the mechanical form
    of the cast `fixes.py` suggests.  Sites under containers the
    retracer cannot rebuild (while/custom_vjp/pallas) are skipped, not
    guessed at."""
    flagged = {f.eqn_path for f in ctx.findings
               if _path_supported(f.eqn_path)}
    skipped = [f.eqn_path for f in ctx.findings
               if not _path_supported(f.eqn_path)]
    for s in skipped[:4]:
        ctx.notes.append(f"dtype site under unsupported container: {s}")
    if not flagged:
        return None
    rules = _DtypeRules(flagged, ctx)
    new_closed = _retrace(ctx.closed_jaxpr, rules)
    if not rules.hit:
        ctx.actions.clear()
        return None
    return new_closed


# ---------------------------------------------------------------------------
# pass 4: fusion stitching (retrace replacing chains with one fused call)
# ---------------------------------------------------------------------------

# jaxpr prims a generated elementwise kernel may contain
_EW_PRIMS = frozenset({
    "add", "sub", "mul", "div", "max", "min", "pow", "tanh", "exp", "log",
    "neg", "abs", "rsqrt", "sqrt", "logistic", "sign", "floor", "ceil",
    "round", "cos", "sin", "expm1", "log1p", "integer_pow", "square",
    "cbrt", "erf", "atan", "exp2",
})

# HLO op name (FUSION_BREAK data["chain"]) -> jaxpr prim name
_HLO_TO_PRIM = {
    "add": "add", "subtract": "sub", "multiply": "mul", "divide": "div",
    "maximum": "max", "minimum": "min", "power": "pow", "tanh": "tanh",
    "exponential": "exp", "log": "log", "negate": "neg", "abs": "abs",
    "rsqrt": "rsqrt", "sqrt": "sqrt", "logistic": "logistic",
    "sign": "sign", "floor": "floor", "ceil": "ceil",
    "round-nearest-even": "round", "cosine": "cos", "sine": "sin",
    "expm1": "expm1", "log-plus-one": "log1p",
}


def _chain_eligible(eqn, min_bytes: int) -> bool:
    if eqn.primitive.name not in _EW_PRIMS or len(eqn.outvars) != 1:
        return False
    ov = eqn.outvars[0]
    if not is_array_var(ov) or aval_bytes(ov.aval) < min_bytes:
        return False
    # jnp.issubdtype, not np kind: bfloat16 (kind 'V') is the dominant
    # TPU training dtype and must stay fusable
    if not jnp.issubdtype(ov.aval.dtype, jnp.floating):
        return False
    shape = tuple(ov.aval.shape)
    for v in eqn.invars:
        if isinstance(v, _Literal):
            if np.shape(v.val) not in ((), shape):
                return False
        elif is_array_var(v):
            if tuple(v.aval.shape) != shape \
                    or v.aval.dtype != ov.aval.dtype:
                return False
    return True


def _detect_chains(jaxpr, min_len: int, min_bytes: int,
                   finding_prims: List[set]) -> List[List[int]]:
    """Maximal single-consumer elementwise chains (eqn indices) whose
    external operands are all defined before the chain head, matched
    against the FUSION_BREAK findings' op sets."""
    eqns = jaxpr.eqns
    defidx: Dict[Any, int] = {}
    consumers: Dict[Any, List[int]] = {}
    for i, eqn in enumerate(eqns):
        for v in eqn.outvars:
            if is_array_var(v):
                defidx[v] = i
        # DISTINCT consumer eqns: y*y reads y twice but is one consumer
        for v in {v for v in eqn.invars if is_array_var(v)}:
            consumers.setdefault(v, []).append(i)
    outset = {v for v in jaxpr.outvars if is_array_var(v)}
    used: set = set()
    chains = []
    for i, eqn in enumerate(eqns):
        if i in used or not _chain_eligible(eqns[i], min_bytes):
            continue
        chain = [i]
        cur = eqns[i]
        while True:
            ov = cur.outvars[0]
            cons = consumers.get(ov, [])
            if ov in outset or len(cons) != 1:
                break
            j = cons[0]
            nxt = eqns[j]
            if j in used or not _chain_eligible(nxt, min_bytes):
                break
            # every external of nxt must predate the chain head (the
            # fused call is emitted at the head's program point)
            if any(is_array_var(v) and v is not ov
                   and defidx.get(v, -1) >= chain[0]
                   for v in nxt.invars):
                break
            chain.append(j)
            cur = nxt
        if len(chain) < min_len:
            continue
        prims = {eqns[k].primitive.name for k in chain}
        if finding_prims and not any(
                len(prims & fp) >= min(2, len(fp)) for fp in finding_prims):
            continue
        chains.append(chain)
        used.update(chain)
    return chains


def _fusion_site(path, chain_eqns, ordinal: int) -> str:
    """Short stable tag of ONE fusion site (eqn path + chain prims +
    tail shape + per-retrace ordinal), baked into the generated kernel's
    name: two equal-length chains fused in one target would otherwise
    emit name-identical kernels, silently aliasing their cost-formula
    and stepprof shape-class attribution."""
    import hashlib

    h = hashlib.blake2s(digest_size=4)
    h.update("/".join(str(s) for s in path).encode())
    h.update(b"|")
    h.update("->".join(e.primitive.name for e in chain_eqns).encode())
    ov = chain_eqns[-1].outvars[0].aval
    h.update(f"|{tuple(ov.shape)}|{ov.dtype}|{ordinal}".encode())
    return h.hexdigest()


class _FusionRules(_RetraceRules):
    def __init__(self, ctx: RewriteContext, finding_prims: List[set]):
        self.ctx = ctx
        self.finding_prims = finding_prims
        self.min_len = int(ctx.opt("fusion_chain_min"))
        self.min_bytes = int(ctx.opt("fusion_min_bytes"))
        self.emit = ctx.opt("fusion_emit", "auto")
        self.fused_count = 0

    def wants(self, sub_jaxpr, path) -> bool:
        return bool(_detect_chains(sub_jaxpr, self.min_len, self.min_bytes,
                                   self.finding_prims))

    def scope_plan(self, jaxpr, path):
        chains = _detect_chains(jaxpr, self.min_len, self.min_bytes,
                                self.finding_prims)
        # the fused call is emitted when the interpreter reaches the
        # TAIL eqn (all externals predate the head, so they exist by
        # then); head + interior eqns are skipped outright
        tails, skips = {}, set()
        for chain in chains:
            eqn_objs = [jaxpr.eqns[k] for k in chain]
            tails[id(eqn_objs[-1])] = eqn_objs
            skips.update(id(e) for e in eqn_objs[:-1])
        return (tails, skips)

    def on_eqn(self, eqn, path, invals, plan, read):
        tails, skips = plan
        if id(eqn) in skips:
            return ("skip",)
        chain_eqns = tails.get(id(eqn))
        if chain_eqns is None:
            return None

        produced = {e.outvars[0] for e in chain_eqns}
        ext: List[Any] = []
        for e in chain_eqns:
            for v in e.invars:
                if is_array_var(v) and v not in produced \
                        and all(v is not x for x in ext):
                    ext.append(v)

        def chain_fn(*xs):
            local = dict(zip((id(v) for v in ext), xs))
            for e in chain_eqns:
                vals = [v.val if isinstance(v, _Literal)
                        else local[id(v)] for v in e.invars]
                out = _bind_default(e, vals)
                local[id(e.outvars[0])] = out[0]
            return local[id(chain_eqns[-1].outvars[0])]

        def compute():
            from ..kernels import pallas_fused_chain as pfc
            site = _fusion_site(path, chain_eqns, self.fused_count)
            fused = pfc.fused_elementwise_chain(
                chain_fn, n_ops=len(chain_eqns), mode=self.emit, site=site)
            self.fused_count += 1
            head = chain_eqns[0]
            self.ctx.act(
                "FUSION_BREAK", format_path(path, head),
                f"stitched {len(chain_eqns)} elementwise eqns "
                f"({'->'.join(e.primitive.name for e in chain_eqns[:6])}"
                f"{'...' if len(chain_eqns) > 6 else ''}) into one fused "
                f"call ({len(ext)} input(s), "
                f"{fmt_bytes(aval_bytes(head.outvars[0].aval))}/op saved "
                "per elided round-trip)",
                chain=[e.primitive.name for e in chain_eqns],
                n_inputs=len(ext), site=site)
            return [fused(*[read(v) for v in ext])]

        return ("compute", compute)


@register_rewrite("fusion", consumes=("FUSION_BREAK",))
def rewrite_fusion(ctx: RewriteContext):
    """Consume FUSION_BREAK chains from the HLO tier: match them back to
    single-consumer elementwise eqn spans in the jaxpr and replace each
    span with ONE fused call — a generated Pallas kernel on TPU (the
    guaranteed fusion XLA declined), an interpret-mode kernel or jitted
    closure elsewhere.  The fused kernel registers a cost formula, so
    the cost pass stays truthful."""
    finding_prims = []
    for f in ctx.findings:
        ops = f.data.get("chain") or []
        mapped = {_HLO_TO_PRIM[o] for o in ops if o in _HLO_TO_PRIM}
        if mapped:
            finding_prims.append(mapped)
    rules = _FusionRules(ctx, finding_prims)
    new_closed = _retrace(ctx.closed_jaxpr, rules)
    if not rules.fused_count:
        ctx.actions.clear()
        return None
    return new_closed


# ---------------------------------------------------------------------------
# pass 4b: cross-container fusion (inline pjit edges, THEN stitch chains)
# ---------------------------------------------------------------------------


class _InlineRules(_RetraceRules):
    """Flatten worthwhile pjit edges during retrace so elementwise chains
    that today STOP at the container boundary (`_detect_chains` works one
    scope at a time) become contiguous in the caller and fusable.  Only
    pjit is inlined — flattening a scan would unroll the loop, and cond
    branches are control flow, not a boundary between chain halves.  A
    pjit is worthwhile when its body is small and carries at least one
    chain-eligible elementwise eqn (directly or through a nested pjit);
    pjits with donated invars are left alone — inlining would silently
    drop the buffer-aliasing hint."""

    def __init__(self, ctx: RewriteContext):
        self.ctx = ctx
        self.min_bytes = int(ctx.opt("fusion_min_bytes"))
        self.max_eqns = int(ctx.opt("inline_fusion_max_eqns", 64))
        self.inlined = 0

    def _worthwhile(self, eqn, depth: int = 3) -> bool:
        if eqn.primitive.name != "pjit":
            return False
        if any(eqn.params.get("donated_invars") or ()):
            return False
        body = eqn.params["jaxpr"].jaxpr
        if len(body.eqns) > self.max_eqns:
            return False
        if any(_chain_eligible(e, self.min_bytes) for e in body.eqns):
            return True
        return depth > 0 and any(
            e.primitive.name == "pjit" and self._worthwhile(e, depth - 1)
            for e in body.eqns)

    def _contains_worthwhile(self, jaxpr, depth: int = 4) -> bool:
        if depth <= 0:
            return False
        for e in jaxpr.eqns:
            if self._worthwhile(e):
                return True
            if e.primitive.name in _REBUILDABLE:
                for _lbl, _k, _i, s in _sub_closed_params(e):
                    if self._contains_worthwhile(_as_open(s), depth - 1):
                        return True
        return False

    def wants(self, sub_jaxpr, path) -> bool:
        # True for containers hiding a worthwhile pjit at ANY depth, so
        # a scan body's pjit edges flatten while the scan itself (and
        # its loop structure) is preserved by _rebuild_container
        return self._contains_worthwhile(sub_jaxpr)

    def on_eqn(self, eqn, path, invals, plan, read):
        if not self._worthwhile(eqn):
            return None
        inner = eqn.params["jaxpr"]
        p = format_path(path, eqn)
        inner_path = path + (_eqn_label(eqn), "jaxpr")

        def compute():
            # boundary pins like the container path does: the inner body
            # was typed against the original invar dtypes
            vals = [_cast_like(read(v), v.aval) for v in eqn.invars]
            self.inlined += 1
            self.ctx.act(
                "FUSION_BREAK", p,
                f"inlined jitted fn {eqn.params.get('name', '?')!r} "
                f"({len(inner.jaxpr.eqns)} eqn(s)) across the container "
                "edge so its elementwise chain is contiguous with the "
                "caller's")
            return list(_interp(inner.jaxpr, inner.consts, vals,
                                inner_path, self))

        return ("compute", compute)


@register_rewrite("inline_fusion", consumes=("FUSION_BREAK",))
def rewrite_inline_fusion(ctx: RewriteContext):
    """Cross-container chain stitching: retrace #1 inlines worthwhile
    pjit edges (`_InlineRules`), retrace #2 runs the SAME chain detection
    and kernel emission as the `fusion` pass over the flattened jaxpr —
    chains that previously died at a pjit boundary are now contiguous.

    The finding op-set filter is intentionally dropped for retrace #2:
    FUSION_BREAK chains were reported against the ORIGINAL program's HLO
    computations, and the whole point of inlining is to form chains that
    crossed those computation boundaries, so the old op sets cannot be
    matched back.  Applying consumes FUSION_BREAK (the later `fusion`
    pass is then skipped); a rollback or no-op leaves the findings for
    plain `fusion` to consume — the gate ladder never loses a fusion the
    old pass could do.  Pure inlining with zero resulting fusions is
    NEVER kept: flattening alone just discards container structure."""
    inline_rules = _InlineRules(ctx)
    flat = _retrace(ctx.closed_jaxpr, inline_rules)
    if not inline_rules.inlined:
        ctx.actions.clear()
        ctx.notes.append("no worthwhile pjit edge to inline")
        return None
    fusion_rules = _FusionRules(ctx, finding_prims=[])
    new_closed = _retrace(flat, fusion_rules)
    if not fusion_rules.fused_count:
        ctx.actions.clear()
        ctx.notes.append("inlining produced no fusable chain")
        return None
    return new_closed


# ---------------------------------------------------------------------------
# pass 5: sharding-constraint injection (mesh-aware retrace)
# ---------------------------------------------------------------------------


def _pspec_entries(spec) -> tuple:
    """Finding data carries the spec as a JSON-ish list (entries None /
    str / list-of-str) — normalize to PartitionSpec constructor args."""
    out = []
    for e in spec:
        out.append(tuple(e) if isinstance(e, (list, tuple)) else e)
    return tuple(out)


class _ShardRules(_RetraceRules):
    """inject: {eqn_path: pspec entries} — wrap that eqn's output in
    with_sharding_constraint; drop: {eqn_path} — elide a re-replicating
    sharding_constraint (identity on values, frees the all-gather)."""

    def __init__(self, ctx: RewriteContext, mesh, inject, drop):
        self.ctx = ctx
        self.mesh = mesh
        self.inject = dict(inject)
        self.drop = set(drop)
        self.hit: set = set()

    def wants(self, sub_jaxpr, path) -> bool:
        prefix = "/".join(path) + "/" if path else ""
        return any(t.startswith(prefix)
                   for t in (*self.inject, *self.drop))

    def on_eqn(self, eqn, path, invals, plan, read):
        p = format_path(path, eqn)
        if p in self.drop and eqn.primitive.name == "sharding_constraint":
            def elide():
                self.hit.add(p)
                self.ctx.act(
                    "SHARD_GAP", p,
                    "elided the re-replicating with_sharding_constraint "
                    "(identity on values; frees the implied all-gather)")
                return [read(eqn.invars[0])]

            return ("compute", elide)
        spec = self.inject.get(p)
        if spec is None or self.mesh is None:
            return None
        if eqn.primitive.name in _OPAQUE_PRIMS \
                or any(True for _ in _sub_closed_params(eqn)):
            return None                 # constrain leaf eqns only

        def constrain():
            from jax.sharding import NamedSharding, PartitionSpec as P

            vals = [read(v) for v in eqn.invars]
            outs = _bind_default(eqn, vals)
            sh = NamedSharding(self.mesh, P(*_pspec_entries(spec)))
            outs = [jax.lax.with_sharding_constraint(outs[0], sh)] \
                + outs[1:]
            self.hit.add(p)
            self.ctx.act(
                "SHARD_REPLICATED", p,
                f"injected with_sharding_constraint(P{_pspec_entries(spec)!r}) "
                f"at the replicated creation point",
                spec=list(spec))
            return outs

        return ("compute", constrain)


@register_rewrite("shard_constraint",
                  consumes=("SHARD_REPLICATED", "SHARD_GAP"))
def rewrite_shard_constraint(ctx: RewriteContext):
    """Consume the SPMD tier's findings: inject the EXACT PartitionSpec
    a mesh-aware SHARD_REPLICATED finding computed (data["spec"]) at its
    creation point, and elide re-replicating constraints (SHARD_GAP) —
    both via mesh-aware retrace.  Constraints are identity on values, so
    the equivalence gate checks numerics while the re-lint gate checks
    that the consumed findings actually disappeared (and no reshard
    boundary appeared downstream of the new layout)."""
    if ctx.mesh is None or getattr(ctx.mesh, "size", 1) <= 1:
        ctx.notes.append("no multi-device mesh — nothing to constrain")
        return None
    inject = {f.eqn_path: f.data["spec"] for f in ctx.findings
              if f.code == "SHARD_REPLICATED" and f.data.get("spec")
              and _path_supported(f.eqn_path)}
    drop = {f.eqn_path for f in ctx.findings
            if f.code == "SHARD_GAP" and _path_supported(f.eqn_path)}
    skipped = [f.eqn_path for f in ctx.findings
               if not _path_supported(f.eqn_path)]
    for s in skipped[:4]:
        ctx.notes.append(f"shard site under unsupported container: {s}")
    if not inject and not drop:
        return None
    rules = _ShardRules(ctx, ctx.mesh, inject, drop)
    new_closed = _retrace(ctx.closed_jaxpr, rules)
    if not rules.hit:
        ctx.actions.clear()
        return None
    return new_closed


# ---------------------------------------------------------------------------
# the engine: gate every pass through equiv + re-lint, roll back failures
# ---------------------------------------------------------------------------


def _cost_of(closed) -> Tuple[float, int]:
    est = cost_lib.estimate(closed, top_k=0)
    return est["total_flops"], est["total_bytes"]


def _warning_codes(report: Report) -> set:
    return {f.code for f in report if f.severity >= Severity.WARNING}


def _relint_gate(pass_: _RewritePass, before: Report, after: Report,
                 ) -> Tuple[bool, str]:
    """Consumed jaxpr-tier findings must shrink; no new warning-level
    codes may appear.  HLO-tier codes (FUSION_BREAK) are not visible to
    analyze_jaxpr — their regression check is the numeric gate plus the
    action log (and the CLI's next full two-tier run)."""
    new_codes = _warning_codes(after) - _warning_codes(before)
    if new_codes:
        return False, f"re-lint grew new warning codes: {sorted(new_codes)}"
    for glob in pass_.consumes:
        b = sum(1 for f in before if fnmatch.fnmatch(f.code, glob))
        a = sum(1 for f in after if fnmatch.fnmatch(f.code, glob))
        if b and a >= b:
            return False, (f"re-lint still reports {a} {glob} finding(s) "
                           f"(was {b})")
    return True, ""


def rewrite_jaxpr(closed, report: Optional[Report] = None,
                  passes: Optional[Sequence[str]] = None,
                  options: Optional[dict] = None,
                  verify: bool = True, verify_grads: bool = True,
                  probes: Optional[Sequence] = None,
                  suppress: Sequence[str] = (),
                  config: Optional[dict] = None, mesh=None):
    """Run the rewrite passes over an already-traced ClosedJaxpr.

    `report` seeds the pass gating (which findings exist) — pass the
    merged two-tier report so HLO findings (FUSION_BREAK) are visible;
    when None the jaxpr tier is analyzed here.  Returns
    `(new_closed_jaxpr, RewriteReport)`; with `verify=True` (the
    default) every pass that fails the equivalence-or-relint gate is
    rolled back, so the returned jaxpr is always safe to run.
    """
    options = dict(options or {})
    if report is None:
        report = analyze_jaxpr(closed, options=options, suppress=suppress,
                               config=config, mesh=mesh)
    names = list(passes) if passes is not None else list(_DEFAULT_PASSES)
    for n in names:
        if n not in REWRITE_REGISTRY:
            raise ValueError(
                f"unknown rewrite pass {n!r}; available: {list_rewrites()}")

    current = closed
    # HLO-tier findings (fusion/collective/layout/buffer stats) cannot be
    # refreshed by analyze_jaxpr — they persist until a pass consumes them
    _HLO_CHECKERS = ("fusion", "collective", "layout", "hlo_memory",
                     "bucket_menu")
    hlo_findings = [f for f in report.findings
                    if f.checker in _HLO_CHECKERS]
    jaxpr_findings = [f for f in report.findings
                      if f.checker not in _HLO_CHECKERS]
    outcomes: List[PassOutcome] = []
    total_before = _count_eqns(closed)
    fl0, by0 = _cost_of(closed)
    if verify and probes is None:
        probes = equiv.make_probes(closed)

    before_lint: Optional[Report] = None
    for name in names:
        p = REWRITE_REGISTRY[name]
        matched = [f for f in jaxpr_findings + hlo_findings
                   if any(fnmatch.fnmatch(f.code, g) for g in p.consumes)]
        eqns_b = _count_eqns(current)
        flb, byb = _cost_of(current)
        base = dict(eqns_before=eqns_b, eqns_after=eqns_b,
                    flops_before=flb, flops_after=flb,
                    bytes_before=byb, bytes_after=byb)
        if not matched:
            outcomes.append(PassOutcome(
                name, "skipped", reason="no consumable findings", **base))
            continue
        ctx = RewriteContext(closed_jaxpr=current, findings=matched,
                             options=options, mesh=mesh)
        try:
            candidate = p.fn(ctx)
        except Exception as e:  # noqa: BLE001 — a pass must never crash
            outcomes.append(PassOutcome(
                name, "failed",
                reason=f"pass raised {type(e).__name__}: {e}", **base))
            continue
        for a in ctx.actions:
            a.pass_name = name
        if candidate is None or not ctx.actions:
            outcomes.append(PassOutcome(
                name, "no-op", actions=ctx.actions,
                reason="; ".join(ctx.notes) or "nothing rewritable",
                **base))
            continue

        eqns_a = _count_eqns(candidate)
        fla, bya = _cost_of(candidate)
        outcome = PassOutcome(
            name, "applied", actions=ctx.actions,
            eqns_before=eqns_b, eqns_after=eqns_a,
            flops_before=flb, flops_after=fla,
            bytes_before=byb, bytes_after=bya,
            reason="; ".join(ctx.notes))
        if verify:
            eq = equiv.verify(current, candidate, probes=probes,
                              check_grads=verify_grads)
            outcome.equiv = eq.to_dict()
            if not eq.ok:
                outcome.status = "rolled_back"
                outcome.reason = f"equivalence check failed: {eq.reason}"
                outcomes.append(outcome)
                continue
            if before_lint is None:
                before_lint = analyze_jaxpr(
                    current, options=options, suppress=suppress,
                    config=config, mesh=mesh)
            after_lint = analyze_jaxpr(candidate, options=options,
                                       suppress=suppress, config=config,
                                       mesh=mesh)
            ok, why = _relint_gate(p, before_lint, after_lint)
            if not ok:
                outcome.status = "rolled_back"
                outcome.reason = why
                outcomes.append(outcome)
                continue
            before_lint = after_lint
            jaxpr_findings = list(after_lint.findings)
        else:
            jaxpr_findings = [f for f in jaxpr_findings
                              if not any(fnmatch.fnmatch(f.code, g)
                                         for g in p.consumes)]
        hlo_findings = [f for f in hlo_findings
                        if not any(fnmatch.fnmatch(f.code, g)
                                   for g in p.consumes)]
        current = candidate
        outcomes.append(outcome)

    fl1, by1 = _cost_of(current)
    rep = RewriteReport(
        outcomes, eqns_before=total_before, eqns_after=_count_eqns(current),
        flops_before=fl0, flops_after=fl1, bytes_before=by0, bytes_after=by1)
    return current, rep


def rewrite(fn, *args, passes: Optional[Sequence[str]] = None,
            verify: bool = True, verify_grads: bool = True,
            hlo: bool = False, report: Optional[Report] = None,
            options: Optional[dict] = None, suppress: Sequence[str] = (),
            config: Optional[dict] = None, mesh=None, **kwargs):
    """Trace `fn(*args, **kwargs)`, run the (verified) rewrite passes,
    and return `(rewritten_fn, RewriteReport)` — `rewritten_fn` is a
    drop-in callable for fn's POSITIONAL signature (kwargs are baked in
    at trace time), carrying the final jaxpr as `.rewritten_jaxpr`.

    `hlo=True` also lowers+compiles once so HLO-tier findings
    (FUSION_BREAK) can seed the fusion pass; `report=` injects an
    existing (merged) report instead of re-analyzing.
    """
    import functools as _ft

    from .core import analyze

    traced = _ft.partial(fn, **kwargs) if kwargs else fn
    closed, out_shape = jax.make_jaxpr(traced, return_shape=True)(*args)
    out_tree = jax.tree_util.tree_structure(out_shape)
    # kwargs were closed over via partial: their leaves are jaxpr CONSTS,
    # not invars — only positional leaves line up with the probe slots
    flat_args = jax.tree_util.tree_leaves(tuple(args))

    if report is None:
        report = analyze(fn, *args, options=options, suppress=suppress,
                         mesh=mesh, config=config, **kwargs)
        if hlo:
            from .core import merge_reports
            from .hlo import analyze_hlo
            try:
                report = merge_reports(report, analyze_hlo(
                    fn, *args, options=options, suppress=suppress,
                    config=config, **kwargs))
            except Exception:  # noqa: BLE001 — lint must not block rewrite
                pass

    probes = equiv.make_probes(closed, flat_args) if verify else None
    if mesh is not None and flat_args:
        # the re-lint gate runs analyze_jaxpr (no concrete args): hand it
        # the call site's input shardings so the spmd tier sees the same
        # sharding world before and after each pass
        from .spmd import spec_of_value
        options = dict(options or {})
        options.setdefault("spmd_in_specs",
                           [spec_of_value(x) for x in flat_args])
    new_closed, rep = rewrite_jaxpr(
        closed, report=report, passes=passes, options=options,
        verify=verify, verify_grads=verify_grads, probes=probes,
        suppress=suppress, config=config, mesh=mesh)

    def rewritten(*a, **kw):
        if kw:
            raise TypeError(
                "rewritten fn takes positional args only: kwargs "
                f"{sorted(kw)} were baked in at trace time — re-run "
                "analysis.rewrite() to change them")
        leaves = jax.tree_util.tree_leaves(tuple(a))
        outs = jax.core.eval_jaxpr(new_closed.jaxpr, new_closed.consts,
                                   *leaves)
        return jax.tree_util.tree_unflatten(out_tree, outs)

    rewritten.rewritten_jaxpr = new_closed
    rewritten.rewrite_report = rep
    rewritten.__name__ = f"rewritten_{getattr(fn, '__name__', 'fn')}"
    return rewritten, rep
