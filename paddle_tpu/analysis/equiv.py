"""Machine-checked equivalence for jaxpr rewrites.

The verification gate of the Graph Doctor's rewrite tier
(`analysis/rewrite.py`): a rewrite is ACCEPTED only if the rewritten
jaxpr evaluates equivalently to the original on probe inputs — forward
always, gradients where the program is differentiable — and is REJECTED
(rolled back by the engine) otherwise.  The reference pipeline trusts
each IR pass by construction; here the passes operate on jaxprs we
re-execute cheaply, so we buy trust by *checking*, not by proof review.

Tolerance policy is dtype-tiered: integer/bool/token outputs must be
EXACT; float outputs compare at the tolerance of the NARROWER of the two
dtypes (a dtype-unification rewrite legitimately narrows f64->f32 — both
sides are cast to the narrow dtype first, so "token-exact at matching
dtype" is the bar, not bit-equality across widths).

Nothing here knows about findings or passes — `verify()` takes two
ClosedJaxprs and probe inputs.  The re-lint half of the acceptance gate
(consumed finding gone, no new findings) lives with the engine in
`rewrite.py`, which knows what was consumed.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .core import is_array_var

__all__ = ["EquivResult", "make_probes", "verify", "tolerance_for",
           "chi2_sf", "verify_sampled"]


# rtol/atol per float dtype — the narrower side of a comparison picks the
# tier.  bf16 is generous: a fused kernel reassociates sums.
_TOL = {
    "float64": (1e-12, 1e-12),
    "complex128": (1e-12, 1e-12),
    "float32": (1e-5, 1e-6),
    "complex64": (1e-5, 1e-6),
    "float16": (1e-2, 1e-3),
    "bfloat16": (2e-2, 1e-2),
}

_FLOATY = tuple(_TOL)


def tolerance_for(*dtypes) -> Tuple[float, float]:
    """(rtol, atol) of the loosest (narrowest) dtype among `dtypes`;
    (0, 0) when none is floating — integer outputs must be exact."""
    worst = (0.0, 0.0)
    for dt in dtypes:
        pair = _TOL.get(str(np.dtype(dt)))
        if pair and pair > worst:
            worst = pair
    return worst


@dataclasses.dataclass
class EquivResult:
    """Outcome of one original-vs-rewritten comparison."""

    ok: bool
    reason: str = ""
    max_abs_err: float = 0.0
    n_outputs: int = 0
    grads_checked: bool = False
    max_grad_err: float = 0.0

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def __str__(self):
        s = "equivalent" if self.ok else f"NOT equivalent: {self.reason}"
        return (f"{s} (fwd max|err| {self.max_abs_err:.3g} over "
                f"{self.n_outputs} output(s)"
                + (f", grad max|err| {self.max_grad_err:.3g}"
                   if self.grads_checked else ", grads not checked") + ")")


# ---------------------------------------------------------------------------
# probe inputs
# ---------------------------------------------------------------------------


def _is_concrete(x) -> bool:
    return isinstance(x, (np.ndarray, np.generic)) or (
        isinstance(x, jax.Array) and not isinstance(
            x, jax.core.Tracer))


def make_probes(closed_jaxpr, args: Sequence = (), seed: int = 0,
                ) -> List[Any]:
    """One concrete value per top-level invar.  Flat `args` leaves that
    are already concrete arrays are used as-is (they exercise the real
    call site); abstract leaves (ShapeDtypeStructs) and missing
    positions are synthesized from the invar avals — normal floats,
    small non-negative ints (safe as indices/token ids), False bools."""
    rng = np.random.default_rng(seed)
    invars = closed_jaxpr.jaxpr.invars
    flat = list(args) + [None] * (len(invars) - len(args))
    out: List[Any] = []
    for v, a in zip(invars, flat):
        if a is not None and _is_concrete(a) \
                and tuple(np.shape(a)) == tuple(v.aval.shape):
            out.append(jnp.asarray(a))
            continue
        shape = tuple(v.aval.shape)
        dt = np.dtype(v.aval.dtype)
        # jnp.issubdtype, not dt.kind: ml_dtypes floats (bfloat16, fp8)
        # report kind 'V' and must still get real-valued probes
        if jnp.issubdtype(dt, jnp.floating):
            val = rng.standard_normal(shape).astype(dt)
        elif dt.kind == "c":
            val = (rng.standard_normal(shape)
                   + 1j * rng.standard_normal(shape)).astype(dt)
        elif dt.kind == "b":
            val = np.zeros(shape, dt)
        else:       # ints/uints: small values are safe as indices/ids
            val = rng.integers(0, 2, size=shape).astype(dt)
        out.append(jnp.asarray(val))
    return out


# ---------------------------------------------------------------------------
# evaluation
# ---------------------------------------------------------------------------


def _eval(closed, probes) -> List[Any]:
    # fresh copies per evaluation: a rewritten jaxpr carrying donation may
    # consume its input buffers on accelerators; probes must stay reusable
    fresh = [jnp.array(p, copy=True) for p in probes]
    return jax.core.eval_jaxpr(closed.jaxpr, closed.consts, *fresh)


def _float_positions(closed) -> Tuple[List[int], List[int]]:
    """(differentiable invar idxs, float outvar idxs) of a ClosedJaxpr."""
    ins = [i for i, v in enumerate(closed.jaxpr.invars)
           if is_array_var(v) and str(v.aval.dtype) in _FLOATY]
    outs = [i for i, v in enumerate(closed.jaxpr.outvars)
            if hasattr(v, "aval") and str(v.aval.dtype) in _FLOATY]
    return ins, outs


def _probe_loss(closed, float_in, float_out, seed: int = 17):
    """Scalar loss over the float outputs as a function of the float
    inputs only — a fixed random linear functional, so grad errors in
    any output element surface (a plain sum hides sign-symmetric bugs)."""
    rng = np.random.default_rng(seed)
    weights = {}

    def loss(*fvals):
        probes_full = list(loss.base)
        for i, fv in zip(float_in, fvals):
            probes_full[i] = fv
        outs = jax.core.eval_jaxpr(closed.jaxpr, closed.consts,
                                   *probes_full)
        total = jnp.zeros((), jnp.float64)
        for i in float_out:
            o = outs[i]
            if i not in weights:
                weights[i] = jnp.asarray(
                    rng.standard_normal(np.shape(o)), jnp.float64)
            total = total + jnp.sum(jnp.real(o).astype(jnp.float64)
                                    * weights[i])
        return total

    return loss, weights


def _max_err(a, b) -> float:
    try:
        return float(jnp.max(jnp.abs(
            jnp.asarray(a, jnp.float64) - jnp.asarray(b, jnp.float64))))
    except Exception:  # noqa: BLE001 — non-numeric
        return 0.0 if bool(jnp.all(a == b)) else float("inf")


def verify(original, rewritten, probes: Optional[Sequence] = None,
           check_grads: bool = True, seed: int = 0) -> EquivResult:
    """Evaluate `original` vs `rewritten` (ClosedJaxprs with identical
    invar signatures) on probe inputs; compare forward outputs at
    dtype-tiered tolerance and, where differentiable, gradients of a
    random linear probe loss.  Any structural/eval failure of the
    REWRITTEN side is a rejection (the original is ground truth)."""
    o_in, r_in = original.jaxpr.invars, rewritten.jaxpr.invars
    if len(o_in) != len(r_in):
        return EquivResult(False, reason=(
            f"invar arity changed: {len(o_in)} -> {len(r_in)}"))
    for i, (a, b) in enumerate(zip(o_in, r_in)):
        if tuple(a.aval.shape) != tuple(b.aval.shape) \
                or a.aval.dtype != b.aval.dtype:
            return EquivResult(False, reason=(
                f"invar {i} signature changed: {a.aval} -> {b.aval}"))
    if len(original.jaxpr.outvars) != len(rewritten.jaxpr.outvars):
        return EquivResult(False, reason=(
            f"output arity changed: {len(original.jaxpr.outvars)} -> "
            f"{len(rewritten.jaxpr.outvars)}"))

    if probes is None:
        probes = make_probes(original, seed=seed)
    probes = list(probes)

    ref = _eval(original, probes)
    try:
        got = _eval(rewritten, probes)
    except Exception as e:  # noqa: BLE001 — rewritten side must run
        return EquivResult(False, reason=f"rewritten jaxpr failed to "
                                         f"evaluate: {type(e).__name__}: {e}")

    max_err = 0.0
    for i, (a, b) in enumerate(zip(ref, got)):
        rtol, atol = tolerance_for(
            getattr(a, "dtype", np.float64), getattr(b, "dtype", np.float64))
        narrow = min((getattr(a, "dtype", None), getattr(b, "dtype", None)),
                     key=lambda d: np.dtype(d).itemsize if d is not None
                     else 99)
        if np.shape(a) != np.shape(b):
            return EquivResult(False, n_outputs=len(ref), reason=(
                f"output {i} shape changed: "
                f"{np.shape(a)} -> {np.shape(b)}"))
        av = jnp.asarray(a).astype(narrow) if narrow is not None else a
        bv = jnp.asarray(b).astype(narrow) if narrow is not None else b
        if rtol == atol == 0.0:         # integer/bool: token-exact
            if not bool(jnp.all(av == bv)):
                return EquivResult(
                    False, n_outputs=len(ref),
                    max_abs_err=_max_err(av, bv),
                    reason=f"integer output {i} differs (must be exact)")
        elif not bool(jnp.allclose(jnp.asarray(av, jnp.float64),
                                   jnp.asarray(bv, jnp.float64),
                                   rtol=rtol, atol=atol, equal_nan=True)):
            return EquivResult(
                False, n_outputs=len(ref), max_abs_err=_max_err(av, bv),
                reason=(f"float output {i} differs beyond "
                        f"rtol={rtol:g}/atol={atol:g} of {narrow}"))
        max_err = max(max_err, _max_err(av, bv))

    res = EquivResult(True, n_outputs=len(ref), max_abs_err=max_err)
    if not check_grads:
        return res

    float_in, float_out = _float_positions(original)
    if not float_in or not float_out:
        return res                      # not differentiable: fwd-only
    try:
        o_loss, _w = _probe_loss(original, float_in, float_out)
        r_loss, _w2 = _probe_loss(rewritten, float_in, float_out)
        # per-side copies: a donation-injected rewrite consumes its
        # input buffers when the grad executes; probes must survive
        o_loss.base = [jnp.array(p, copy=True) for p in probes]
        r_loss.base = [jnp.array(p, copy=True) for p in probes]
        argnums = tuple(range(len(float_in)))
        g_ref = jax.grad(o_loss, argnums=argnums)(
            *[jnp.array(probes[i], copy=True) for i in float_in])
        g_got = jax.grad(r_loss, argnums=argnums)(
            *[jnp.array(probes[i], copy=True) for i in float_in])
    except Exception:  # noqa: BLE001 — opaque/non-differentiable regions
        return res                      # fwd equivalence stands alone
    g_err = 0.0
    for i, (ga, gb) in enumerate(zip(g_ref, g_got)):
        rtol, atol = tolerance_for(probes[float_in[i]].dtype)
        rtol, atol = max(rtol, 1e-5), max(atol, 1e-6)
        if not bool(jnp.allclose(jnp.asarray(ga, jnp.float64),
                                 jnp.asarray(gb, jnp.float64),
                                 rtol=rtol, atol=atol, equal_nan=True)):
            return EquivResult(
                False, n_outputs=len(ref), max_abs_err=max_err,
                grads_checked=True, max_grad_err=_max_err(ga, gb),
                reason=(f"gradient wrt float input #{float_in[i]} differs "
                        f"beyond rtol={rtol:g}/atol={atol:g}"))
        g_err = max(g_err, _max_err(ga, gb))
    res.grads_checked = True
    res.max_grad_err = g_err
    return res


# ---------------------------------------------------------------------------
# distribution equality — the gate for SAMPLED rewrites.  A kernel that
# fuses categorical sampling cannot be verified value-exactly (two correct
# implementations may draw different tokens from the same distribution);
# the right bar is "draws are indistinguishable from the target
# distribution", checked with a Pearson chi-square goodness-of-fit test.
# ---------------------------------------------------------------------------


def chi2_sf(stat: float, dof: int) -> float:
    """Chi-square survival function P(X >= stat) via the Wilson–Hilferty
    cube-root normal approximation — no scipy in the container, and a
    rewrite gate needs a decision-grade p-value, not 12 digits.  Accurate
    to ~1e-3 for dof >= 3, conservative enough below that."""
    import math

    if dof <= 0:
        return 1.0
    if stat <= 0.0:
        return 1.0
    x = (stat / dof) ** (1.0 / 3.0)
    mu = 1.0 - 2.0 / (9.0 * dof)
    sigma = math.sqrt(2.0 / (9.0 * dof))
    z = (x - mu) / sigma
    return 0.5 * math.erfc(z / math.sqrt(2.0))


def verify_sampled(draw_fn, expected_probs, n_draws: int = 4000,
                   seed: int = 0, alpha: float = 1e-3,
                   min_expected: float = 5.0) -> EquivResult:
    """Goodness-of-fit gate: do `draw_fn`'s draws follow
    `expected_probs`?  `draw_fn(key) -> int32 token(s)` (scalar or
    array — a batched sampler contributes every element); `expected_probs`
    is the (V,) target distribution (e.g. `generation.filtered_probs` of
    the same logits the sampler saw).  Bins with expected count below
    `min_expected` are pooled (the chi-square approximation breaks on
    sparse bins); accepts when the p-value >= `alpha`.

    alpha is deliberately small: the gate must not flake in CI on a
    correct sampler (false-rejection rate == alpha) while still rejecting
    any systematic distribution shift, which drives the statistic up
    linearly in n_draws.  Reported via EquivResult with the statistic in
    `max_abs_err` (grads are meaningless for a sampler)."""
    probs = np.asarray(expected_probs, np.float64).reshape(-1)
    total = probs.sum()
    if not np.isfinite(total) or total <= 0:
        return EquivResult(False, reason="expected_probs do not sum > 0")
    probs = probs / total
    V = probs.size

    keys = jax.random.split(jax.random.PRNGKey(seed), n_draws)
    try:
        toks = np.asarray(jax.vmap(draw_fn)(keys)).reshape(-1)
    except Exception:  # noqa: BLE001 — draw_fn may not be vmappable
        try:
            toks = np.concatenate(
                [np.asarray(draw_fn(k)).reshape(-1) for k in keys])
        except Exception as e:  # noqa: BLE001 — sampler must run
            return EquivResult(False, reason=(
                f"draw_fn failed: {type(e).__name__}: {e}"))
    toks = toks.astype(np.int64)
    if toks.size == 0:
        return EquivResult(False, reason="draw_fn produced no draws")
    if (toks < 0).any() or (toks >= V).any():
        return EquivResult(False, reason=(
            f"draw outside [0, {V}): draws from a different support are "
            f"never distribution-equal"))

    counts = np.zeros(V, np.float64)
    np.add.at(counts, toks, 1.0)
    expected = probs * toks.size

    # zero-probability tokens must never be drawn — that is an exactness
    # violation (top-k/top-p masking broke), not a statistical question
    dead = expected == 0.0
    if counts[dead].sum() > 0:
        bad = int(np.flatnonzero(dead & (counts > 0))[0])
        return EquivResult(False, reason=(
            f"token {bad} drawn but has zero probability under the "
            f"target distribution"))

    big = expected >= min_expected
    obs = counts[big]
    exp = expected[big]
    tail_exp = expected[~big & ~dead].sum()
    if tail_exp > 0:
        obs = np.append(obs, counts[~big & ~dead].sum())
        exp = np.append(exp, tail_exp)
    if exp.size < 2:
        # everything pooled into one bin: nothing to test beyond support
        return EquivResult(True, n_outputs=1, reason="")
    stat = float(((obs - exp) ** 2 / exp).sum())
    dof = exp.size - 1
    p = chi2_sf(stat, dof)
    ok = p >= alpha
    return EquivResult(
        ok, n_outputs=1, max_abs_err=stat,
        reason="" if ok else (
            f"chi-square rejects distribution equality: stat={stat:.2f} "
            f"dof={dof} p={p:.3e} < alpha={alpha:g} over {toks.size} "
            f"draws"))
