"""Static collective cost model — price a collective from mesh + chip.

The comm-side analog of `analysis/cost.py`'s FLOPs roll-up: given a
collective kind, the logical array it moves, and the mesh axes it runs
over, return the bytes that actually cross ICI links and a time estimate
from a per-chip-generation link-bandwidth table.  The SPMD tier
(`analysis/spmd.py`) prices every implied collective this way and joins
the total against the cost pass's FLOPs to produce the per-step
comm-vs-compute roofline (`COLLECTIVE_BOUND`).

Model assumptions (stated, not hidden — see ARCHITECTURE.md table):

  * ring algorithms on one ICI axis: an all-gather of a FULL (logical)
    array of B bytes over an axis of size n moves B*(n-1)/n bytes
    through each chip's link -> t = B*(n-1)/(n*bw)
  * reduce-scatter prices identically; all-reduce = reduce-scatter +
    all-gather = 2x; all-to-all moves each chip's shard once ->
    B*(n-1)/n^2; ppermute is one shard hop -> B/n
  * multi-axis collectives (e.g. psum over ("data","sharding")) use the
    PRODUCT of the axis sizes and the single-link bandwidth — a
    conservative serial-ring bound (real pods overlap the axes)
  * bandwidth is one-way per-link ICI, bytes/s, from the public chip
    specs; CPU / unknown chips price at the v5e number so the roofline
    is still comparable across rounds (the `chip` option overrides)
  * latency per hop is a constant alpha added per (n-1) ring step —
    negligible for MB-scale tensors, dominant for the KB-scale ones the
    COLLECTIVE_SEQ lint wants combined.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "LINK_BW_BY_KIND", "CollectiveCost", "link_bandwidth", "chip_peak_flops",
    "price_collective", "roofline",
]

# one-way ICI bandwidth per link, bytes/s; most-specific-first substring
# match on the chip/device_kind string (same convention as
# obs.mfu.PEAK_FLOPS_BY_KIND — one table style, two tables of truth)
LINK_BW_BY_KIND: Tuple[Tuple[str, float], ...] = (
    ("v6e", 90e9), ("v6", 90e9),
    ("v5 lite", 45e9), ("v5e", 45e9), ("v5litepod", 45e9),
    ("v5p", 90e9), ("v5", 90e9),
    ("v4", 45e9),
    ("v3", 70e9),
)

_DEFAULT_CHIP = "v5e"

# per-hop launch/latency cost (s): ring collectives pay ~(n-1) of these;
# the number only matters for small tensors, where it IS the cost
_ALPHA_S = 1e-6


def link_bandwidth(chip: Optional[str] = None) -> float:
    """One-way per-link ICI bytes/s for a chip-kind string ("TPU v5
    lite", "v4", ...).  Unknown/CPU chips price at the v5e number."""
    kind = (chip or _DEFAULT_CHIP).lower()
    for k, bw in LINK_BW_BY_KIND:
        if k in kind:
            return bw
    return dict(LINK_BW_BY_KIND)["v5e"]


def chip_peak_flops(chip: Optional[str] = None) -> float:
    """bf16 peak FLOP/s for the chip string — obs.mfu's table, matched
    the same way (lazy import: obs depends on analysis.cost)."""
    from ..obs.mfu import PEAK_FLOPS_BY_KIND

    kind = (chip or _DEFAULT_CHIP).lower()
    for k, v in PEAK_FLOPS_BY_KIND:
        if k in kind:
            return v
    return dict(PEAK_FLOPS_BY_KIND)["v5e"]


@dataclasses.dataclass(frozen=True)
class CollectiveCost:
    """One priced collective: what moves, over which axes, how long."""

    kind: str                   # all_gather | reduce_scatter | all_reduce
    #                             | all_to_all | ppermute
    bytes: int                  # FULL logical array bytes (pre-shard)
    axes: Tuple[str, ...]       # mesh axes the collective runs over
    axis_size: int              # product of those axes' sizes
    moved_bytes: int            # bytes through one chip's link(s)
    seconds: float              # ring-model time estimate
    path: str = ""              # eqn path that implied it
    weight: int = 1             # scan trip multiplier already applied
    reason: str = ""            # why it exists ("grad psum", "reshard")

    def to_dict(self) -> dict:
        return {"kind": self.kind, "bytes": int(self.bytes),
                "axes": list(self.axes), "axis_size": int(self.axis_size),
                "moved_bytes": int(self.moved_bytes),
                "seconds": float(self.seconds), "path": self.path,
                "weight": int(self.weight), "reason": self.reason}


# moved-bytes fraction of the full array, as a function of axis size n
_MOVED_FRAC = {
    "all_gather": lambda n: (n - 1) / n,
    "reduce_scatter": lambda n: (n - 1) / n,
    "all_reduce": lambda n: 2.0 * (n - 1) / n,
    "all_to_all": lambda n: (n - 1) / (n * n),
    "ppermute": lambda n: 1.0 / n,
}


def price_collective(kind: str, nbytes: int, axes: Sequence[str],
                     axis_sizes: Dict[str, int],
                     chip: Optional[str] = None, path: str = "",
                     weight: int = 1, reason: str = "") -> CollectiveCost:
    """Price one collective of a FULL logical array of `nbytes` over the
    named mesh `axes` (sizes from `axis_sizes`) on `chip`."""
    n = 1
    for a in axes:
        n *= max(1, int(axis_sizes.get(a, 1)))
    frac_fn = _MOVED_FRAC.get(kind, _MOVED_FRAC["all_reduce"])
    moved = int(nbytes * frac_fn(max(n, 1)))
    bw = link_bandwidth(chip)
    secs = (moved / bw + _ALPHA_S * max(n - 1, 0)) * max(1, int(weight))
    return CollectiveCost(
        kind=kind, bytes=int(nbytes), axes=tuple(axes), axis_size=n,
        moved_bytes=moved * max(1, int(weight)), seconds=secs, path=path,
        weight=int(weight), reason=reason)


def roofline(total_flops: float, collectives: Iterable[CollectiveCost],
             mesh_size: int, chip: Optional[str] = None) -> dict:
    """Join the cost pass's FLOPs with the priced collectives into one
    comm-vs-compute verdict.  Compute time divides the program's TOTAL
    FLOPs over the mesh (SPMD: every chip runs 1/n of the math); comm
    time sums the ring estimates (serial bound — no overlap credit, so
    `bound == "comm"` means comm CANNOT hide behind compute even with a
    perfect scheduler at this mesh/chip)."""
    coll = list(collectives)
    t_comm = float(sum(c.seconds for c in coll))
    peak = chip_peak_flops(chip)
    t_compute = float(total_flops) / max(1, int(mesh_size)) / peak
    denom = max(t_comm + t_compute, 1e-30)
    return {
        "chip": chip or _DEFAULT_CHIP,
        "mesh_size": int(mesh_size),
        "t_compute_s": t_compute,
        "t_comm_s": t_comm,
        "comm_fraction": t_comm / denom,
        "bound": "comm" if t_comm > t_compute else "compute",
        "n_collectives": len(coll),
        "collective_bytes": int(sum(c.moved_bytes for c in coll)),
    }
