"""Graph Doctor tier 4: mesh-aware SPMD sharding propagation.

The taint-based `sharding` checker (tier 1) answers "does any sharded
value REACH this tensor"; this module answers the question GSPMD itself
answers at compile time: "what `PartitionSpec` does every eqn's output
carry, and which collectives does the program imply?"  It is an abstract
interpreter over the ClosedJaxpr — per-var state is a tuple of mesh-axis
sets (one per dim) plus a set of *partial* axes (pending psum, the way
GSPMD models a dot whose contracting dim was sharded) — seeded from the
actual arg shardings, pjit `in_shardings`/`out_shardings`, and every
in-graph `sharding_constraint`, and propagated forward through per-prim
rules (dot_general contraction -> partial, reduce over a sharded dim ->
partial, reshape/transpose/broadcast dim maps, scan carry fixpoint, ...).

Three finding families fall out:

  SHARD_RESHARD     an eqn boundary whose operand/result specs disagree
                    — the implied collective is NAMED (all-gather /
                    all-to-all / reduce-scatter) and PRICED (bytes +
                    ring-model seconds via `comm_cost`)
  SHARD_REPLICATED  (mesh-aware) a large fully-replicated value whose
                    dims are divisible by a free mesh axis — the finding
                    carries the EXACT PartitionSpec to apply, which the
                    `shard_constraint` rewrite pass injects verbatim
  SHARD_GAP         a sharding_constraint that re-replicates a sharded
                    value (the legacy code, now with the all-gather
                    priced)
  COLLECTIVE_BOUND  the per-step comm-vs-compute roofline: every implied
                    collective (including the EXPECTED ones — the grad
                    psum is not a bug, but it is a cost) summed against
                    the cost pass's FLOPs at the chip's peak

`propagate()` is the library surface (returns the per-eqn spec table +
priced collectives); the `spmd` checker wires it into `analyze(...,
mesh=...)`; `tools/graphlint.py --mesh dp=2,tp=4` is the CLI.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, FrozenSet, List, Optional, Sequence, Tuple

import numpy as np

from . import comm_cost
from . import cost as cost_lib
from .core import (
    CheckContext, Finding, Severity, _as_open, _eqn_label, aval_bytes,
    fmt_aval, fmt_bytes, format_path, is_array_var, register_checker,
)

__all__ = ["VSpec", "SpmdResult", "propagate", "spec_of_value",
           "suggest_spec", "check_spmd"]

_EMPTY: FrozenSet[str] = frozenset()


# ---------------------------------------------------------------------------
# value state
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class VSpec:
    """Abstract sharding of one value: per-dim mesh-axis sets + pending
    partial-sum axes (GSPMD's 'partial' annotation)."""

    dims: Tuple[FrozenSet[str], ...]
    partial: FrozenSet[str] = _EMPTY

    @property
    def is_replicated(self) -> bool:
        return not self.partial and all(not d for d in self.dims)

    @property
    def sharded_axes(self) -> FrozenSet[str]:
        out = set()
        for d in self.dims:
            out |= d
        return frozenset(out)

    def pspec(self) -> list:
        """PartitionSpec-shaped list: None / axis / tuple per dim."""
        out = []
        for d in self.dims:
            if not d:
                out.append(None)
            elif len(d) == 1:
                out.append(next(iter(d)))
            else:
                out.append(tuple(sorted(d)))
        return out

    def __str__(self):
        body = ", ".join("None" if p is None else repr(p)
                         for p in self.pspec())
        s = f"P({body})"
        if self.partial:
            s += f"+partial{sorted(self.partial)}"
        return s


def _repl(ndim: int) -> VSpec:
    return VSpec(dims=(_EMPTY,) * ndim)


def _from_pspec(pspec, ndim: int) -> VSpec:
    """PartitionSpec (or list of entries) -> VSpec, padded to ndim."""
    entries = list(pspec or ())[:ndim]
    dims = []
    for e in entries:
        if e is None:
            dims.append(_EMPTY)
        elif isinstance(e, (tuple, list)):
            dims.append(frozenset(a for a in e if a is not None))
        else:
            dims.append(frozenset({e}))
    dims += [_EMPTY] * (ndim - len(dims))
    return VSpec(dims=tuple(dims))


def _dedupe_axes(dims: Sequence[FrozenSet[str]],
                 partial: FrozenSet[str] = _EMPTY) -> VSpec:
    """An axis may shard at most one dim: keep its FIRST use."""
    seen: set = set()
    out = []
    for d in dims:
        keep = frozenset(a for a in d if a not in seen)
        seen |= keep
        out.append(keep)
    return VSpec(dims=tuple(out), partial=frozenset(partial - seen))


def spec_of_value(x) -> Optional[list]:
    """The PartitionSpec entries of a concrete array's NamedSharding
    (None for unsharded/unknown values) — the arg-seeding helper."""
    s = getattr(x, "sharding", None)
    spec = getattr(s, "spec", None)
    if spec is None:
        return None
    return list(spec)


def _named_spec(sharding) -> Optional[list]:
    spec = getattr(sharding, "spec", None)
    return None if spec is None else list(spec)


def suggest_spec(shape: Sequence[int], used_axes: FrozenSet[str],
                 axis_sizes: Dict[str, int]) -> Optional[Tuple[int, str]]:
    """(dim, axis) to shard a replicated value on: the largest free mesh
    axis that evenly divides some dim (leftmost dim wins).  None when no
    axis divides — the value is NOT provably shardable."""
    free = sorted(((n, a) for a, n in axis_sizes.items()
                   if n > 1 and a not in used_axes), reverse=True)
    for n, axis in free:
        for d, size in enumerate(shape):
            if size >= n and size % n == 0:
                return d, axis
    return None


# ---------------------------------------------------------------------------
# result container
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class SpmdResult:
    """What `propagate` returns: per-eqn predicted shardings, the priced
    collectives, the SHARD_* findings, and the roofline join."""

    eqn_rows: List[dict]
    collectives: List[comm_cost.CollectiveCost]
    findings: List[Finding]
    roofline: dict
    mesh_axes: Dict[str, int]
    chip: str

    def summary(self, top_k: int = 8) -> dict:
        coll = sorted(self.collectives, key=lambda c: -c.seconds)
        return {
            "mesh": dict(self.mesh_axes),
            "chip": self.chip,
            "n_eqns": len(self.eqn_rows),
            "n_collectives": len(self.collectives),
            "reshard_count": sum(1 for f in self.findings
                                 if f.code == "SHARD_RESHARD"),
            "collectives": [c.to_dict() for c in coll[:top_k]],
            "roofline": dict(self.roofline),
        }


# ---------------------------------------------------------------------------
# the interpreter
# ---------------------------------------------------------------------------

# partial-sum passes through these unchanged (linear, shape-only, or
# sum-reducing) — anything else materializes the psum first
_PARTIAL_LINEAR = frozenset({
    "add", "sub", "neg", "convert_element_type", "transpose", "reshape",
    "broadcast_in_dim", "squeeze", "slice", "copy", "reduce_sum", "rev",
    "real", "imag", "reduce_precision",
})

_REDUCE_PRIMS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod", "reduce_and",
    "reduce_or", "reduce_xor", "argmax", "argmin",
}

_CREATION_PRIMS = frozenset({
    "iota", "rng_bit_generator", "random_seed", "random_bits",
    "random_wrap", "random_unwrap",
})

# containers recursed with operand specs when arities line up
_GENERIC_CONTAINERS = frozenset({
    "custom_jvp_call", "custom_vjp_call", "custom_vjp_call_jaxpr",
    "remat", "checkpoint", "closed_call", "core_call", "named_call",
    "custom_vjp_call_lifted",
})


def _ndim(v) -> int:
    return len(getattr(getattr(v, "aval", None), "shape", ()) or ())


class _Interp:
    def __init__(self, mesh_axes: Dict[str, int], options, chip: str,
                 min_bytes: int):
        self.axis_sizes = dict(mesh_axes)
        self.opt = options                      # callable(key) -> value
        self.chip = chip
        self.min_bytes = min_bytes
        self.findings: List[Finding] = []
        self.collectives: List[comm_cost.CollectiveCost] = []
        self.eqn_rows: List[dict] = []
        self._mute = 0                          # >0 during fixpoint runs
        self._materialized: set = set()         # vars whose psum was priced

    # -- recording ----------------------------------------------------------

    def _collective(self, kind, nbytes, axes, path, weight, reason):
        if self._mute or not axes or nbytes <= 0:
            return None
        c = comm_cost.price_collective(
            kind, nbytes, sorted(axes), self.axis_sizes, chip=self.chip,
            path=path, weight=weight, reason=reason)
        self.collectives.append(c)
        return c

    def _find(self, severity, code, path, message, suggestion="", **data):
        if self._mute:
            return
        self.findings.append(Finding(
            severity, code, path, message, suggestion, checker="spmd",
            data=data))

    # -- partial materialization -------------------------------------------

    def _materialize(self, spec: VSpec, var, path: str, weight: int,
                     reason: str) -> VSpec:
        """Price the pending psum of a partial value (once per var) and
        return the full (non-partial) spec."""
        if not spec.partial:
            return spec
        if var not in self._materialized:
            if not self._mute:
                self._materialized.add(var)
            self._collective(
                "all_reduce", aval_bytes(var.aval) if is_array_var(var)
                else 0, spec.partial, path, weight, reason)
        return VSpec(dims=spec.dims)

    # -- reshard classification --------------------------------------------

    def _classify_reshard(self, src: VSpec, dst: VSpec, nbytes: int,
                          path: str, weight: int, who: str) -> List[str]:
        """Collectives implied by forcing a value from `src` to `dst`
        layout.  Returns the implied kinds (priced as a side effect)."""
        kinds: List[str] = []
        if src.partial:
            scatter = src.partial & dst.sharded_axes
            reduce_ = src.partial - scatter
            if scatter:
                self._collective("reduce_scatter", nbytes, scatter, path,
                                 weight, f"{who}: partial -> sharded")
                kinds.append("reduce_scatter")
            if reduce_:
                self._collective("all_reduce", nbytes, reduce_, path,
                                 weight, f"{who}: partial -> full")
                kinds.append("all_reduce")
            src = VSpec(dims=src.dims)
        moved, gathered = set(), set()
        for i, axes in enumerate(src.dims):
            for a in axes:
                dst_dim = next((j for j, dd in enumerate(dst.dims)
                                if a in dd), None)
                if dst_dim is None:
                    gathered.add(a)
                elif dst_dim != i:
                    moved.add(a)
        if moved:
            self._collective("all_to_all", nbytes, moved, path, weight,
                             f"{who}: axis moved dims")
            kinds.append("all_to_all")
        if gathered:
            self._collective("all_gather", nbytes, gathered, path, weight,
                             f"{who}: axis unsharded")
            kinds.append("all_gather")
        return kinds

    # -- elementwise join ---------------------------------------------------

    def _join_elementwise(self, eqn, in_specs, path, weight) -> VSpec:
        """Broadcast-aware join: output dim takes the first non-empty
        operand axis set; a CONFLICT (two different non-empty sets) is a
        resharding boundary — the minority operand gets gathered."""
        out_shape = tuple(eqn.outvars[0].aval.shape)
        out_nd = len(out_shape)
        dims: List[FrozenSet[str]] = [_EMPTY] * out_nd
        partial: set = set()
        prim = eqn.primitive.name
        n_partial = sum(1 for s in in_specs if s.partial)
        partial_sets = {s.partial for s in in_specs if s.partial}
        for pos, (v, spec) in enumerate(zip(eqn.invars, in_specs)):
            if spec.partial:
                # psum only distributes over ops it is linear in: +/-
                # need EVERY operand partial over the SAME axes (a
                # replicated addend would be summed n times); mul by one
                # replicated factor scales each shard; div only when the
                # pending sum is the NUMERATOR — sum_i(a_i/b) == a/b but
                # sum_i(a/b_i) != a/sum_i(b_i)
                if prim in ("add", "sub"):
                    keep = (n_partial == len(in_specs)
                            and len(partial_sets) == 1)
                elif prim == "mul":
                    keep = n_partial == 1
                elif prim == "div":
                    keep = n_partial == 1 and pos == 0
                else:
                    keep = False
                if keep:
                    partial |= spec.partial
                else:
                    spec = self._materialize(spec, v, path, weight,
                                             f"{prim} consumes partial")
            shape = tuple(getattr(getattr(v, "aval", None), "shape", ()))
            off = out_nd - len(shape)
            for i, axes in enumerate(spec.dims):
                if not axes or shape[i] != out_shape[off + i]:
                    continue            # size-1 broadcast contributes none
                j = off + i
                if not dims[j]:
                    dims[j] = axes
                elif dims[j] != axes:
                    nb = aval_bytes(v.aval)
                    self._collective("all_gather", nb, axes, path, weight,
                                     f"{prim} operand layout conflict")
                    if nb >= self.min_bytes:
                        self._find(
                            Severity.WARNING, "SHARD_RESHARD", path,
                            f"{prim} operands disagree on dim {j} layout "
                            f"({sorted(dims[j])} vs {sorted(axes)}) — "
                            f"GSPMD all-gathers {fmt_bytes(nb)} to "
                            "reconcile them",
                            "constrain both operands to one PartitionSpec "
                            "upstream of this eqn",
                            collective="all_gather", bytes=nb,
                            axes=sorted(axes))
        return _dedupe_axes(dims, frozenset(partial))

    # -- per-primitive rules ------------------------------------------------

    def _apply(self, eqn, in_specs: List[VSpec], path_t: Tuple[str, ...],
               weight: int) -> List[VSpec]:
        prim = eqn.primitive.name
        path = format_path(path_t, eqn)
        p = eqn.params

        if prim == "sharding_constraint":
            dst_entries = _named_spec(p.get("sharding"))
            src = in_specs[0]
            nd = _ndim(eqn.outvars[0])
            if dst_entries is None:
                return [src]
            dst = _from_pspec(dst_entries, nd)
            nb = aval_bytes(eqn.outvars[0].aval)
            kinds = self._classify_reshard(src, dst, nb, path, weight,
                                           "sharding_constraint")
            big = nb >= self.min_bytes
            if big and dst.is_replicated and "all_gather" in kinds:
                self._find(
                    Severity.WARNING, "SHARD_GAP", path,
                    "with_sharding_constraint re-replicates a sharded "
                    f"{fmt_aval(eqn.outvars[0].aval)} ({fmt_bytes(nb)}) — "
                    "an implicit all-gather on every device",
                    "constrain to a sharded PartitionSpec, or drop the "
                    "constraint and let GSPMD propagate",
                    collective="all_gather", bytes=nb,
                    src_spec=src.pspec(), dst_spec=dst.pspec())
            elif big and ("all_to_all" in kinds or "all_gather" in kinds):
                kind = ("all_to_all" if "all_to_all" in kinds
                        else "all_gather")
                self._find(
                    Severity.WARNING, "SHARD_RESHARD", path,
                    f"sharding_constraint reshards {src} -> {dst} on a "
                    f"{fmt_aval(eqn.outvars[0].aval)} ({fmt_bytes(nb)}) "
                    f"— an implied {kind}",
                    "align the constraint with the producer's layout, or "
                    "move the reshard off the hot path",
                    collective=kind, bytes=nb, src_spec=src.pspec(),
                    dst_spec=dst.pspec())
            return [dst]

        if prim == "pjit":
            return self._apply_pjit(eqn, in_specs, path_t, weight)
        if prim == "scan":
            return self._apply_scan(eqn, in_specs, path_t, weight)
        if prim == "cond":
            return self._apply_cond(eqn, in_specs, path_t, weight)
        if prim == "while":
            return self._apply_while(eqn, in_specs, path_t, weight)
        if prim in _GENERIC_CONTAINERS:
            return self._apply_generic_container(eqn, in_specs, path_t,
                                                 weight)

        if prim == "dot_general":
            return self._apply_dot(eqn, in_specs, path, weight)

        if prim in _REDUCE_PRIMS:
            spec = in_specs[0]
            if prim != "reduce_sum":
                spec = self._materialize(spec, eqn.invars[0], path, weight,
                                         f"{prim} consumes partial")
            axes_param = p.get("axes", ())
            reduced = set(spec.partial)
            dims = [d for i, d in enumerate(spec.dims)
                    if i not in axes_param]
            for i in axes_param:
                if i < len(spec.dims):
                    reduced |= spec.dims[i]
            out = [_dedupe_axes(dims, frozenset(reduced))]
            return out * len(eqn.outvars)

        if prim == "transpose":
            perm = p["permutation"]
            spec = in_specs[0]
            return [VSpec(dims=tuple(spec.dims[i] for i in perm),
                          partial=spec.partial)]

        if prim == "broadcast_in_dim":
            bd = p["broadcast_dimensions"]
            out_shape = tuple(eqn.outvars[0].aval.shape)
            in_shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
            dims = [_EMPTY] * len(out_shape)
            spec = in_specs[0]
            for i, j in enumerate(bd):
                if i < len(spec.dims) and in_shape[i] == out_shape[j]:
                    dims[j] = spec.dims[i]
            return [_dedupe_axes(dims, spec.partial)]

        if prim == "reshape":
            return [self._apply_reshape(eqn, in_specs[0])]

        if prim == "squeeze":
            drop = set(p.get("dimensions", ()))
            spec = in_specs[0]
            dims = [d for i, d in enumerate(spec.dims) if i not in drop]
            return [VSpec(dims=tuple(dims), partial=spec.partial)]

        if prim in ("slice", "dynamic_slice"):
            spec = in_specs[0]
            in_shape = tuple(eqn.invars[0].aval.shape)
            out_shape = tuple(eqn.outvars[0].aval.shape)
            dims = tuple(d if in_shape[i] == out_shape[i] else _EMPTY
                         for i, d in enumerate(spec.dims))
            return [VSpec(dims=dims, partial=spec.partial)]

        if prim == "dynamic_update_slice":
            spec = self._materialize(in_specs[0], eqn.invars[0], path,
                                     weight, "dus consumes partial")
            return [spec]

        if prim == "concatenate":
            d = int(p["dimension"])
            nd = _ndim(eqn.outvars[0])
            dims = [_EMPTY] * nd
            for spec in in_specs:
                for i in range(min(nd, len(spec.dims))):
                    if i != d and not dims[i]:
                        dims[i] = spec.dims[i]
            return [_dedupe_axes(dims)]

        if prim == "pad":
            spec = in_specs[0]
            in_shape = tuple(eqn.invars[0].aval.shape)
            out_shape = tuple(eqn.outvars[0].aval.shape)
            dims = tuple(d if i < len(in_shape)
                         and in_shape[i] == out_shape[i] else _EMPTY
                         for i, d in enumerate(spec.dims))
            return [VSpec(dims=dims)]

        if prim in _CREATION_PRIMS or prim in ("pallas_call",
                                               "custom_partitioning"):
            return [_repl(_ndim(v)) for v in eqn.outvars]

        # generic: broadcast-compatible elementwise join (covers the
        # long tail of unary/binary math prims), else conservative
        # replication with partials materialized
        out_shape = tuple(getattr(getattr(eqn.outvars[0], "aval", None),
                                  "shape", ()) or ())

        def bcast_ok(v):
            shape = tuple(getattr(getattr(v, "aval", None), "shape", ())
                          or ())
            if len(shape) > len(out_shape):
                return False
            off = len(out_shape) - len(shape)
            return all(s in (1, out_shape[off + i])
                       for i, s in enumerate(shape))

        if len(eqn.outvars) == 1 and all(bcast_ok(v) for v in eqn.invars):
            return [self._join_elementwise(eqn, in_specs, path, weight)]
        for v, s in zip(eqn.invars, in_specs):
            self._materialize(s, v, path, weight,
                              f"{prim} (opaque) consumes partial")
        return [_repl(_ndim(v)) for v in eqn.outvars]

    # -- structured rules ---------------------------------------------------

    def _apply_dot(self, eqn, in_specs, path, weight) -> List[VSpec]:
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        lhs, rhs = in_specs[0], in_specs[1]
        lhs = self._materialize(lhs, eqn.invars[0], path, weight,
                                "dot consumes partial")
        rhs = self._materialize(rhs, eqn.invars[1], path, weight,
                                "dot consumes partial")
        partial: set = set()
        for i, j in zip(lc, rc):
            la = lhs.dims[i] if i < len(lhs.dims) else _EMPTY
            ra = rhs.dims[j] if j < len(rhs.dims) else _EMPTY
            if la and ra and la != ra:
                nb = aval_bytes(eqn.invars[1].aval)
                self._collective("all_to_all", nb, ra, path, weight,
                                 "dot contracting layout conflict")
                if nb >= self.min_bytes:
                    self._find(
                        Severity.WARNING, "SHARD_RESHARD", path,
                        "dot_general contracting dims carry different "
                        f"axes ({sorted(la)} vs {sorted(ra)}) — GSPMD "
                        f"reshards {fmt_bytes(nb)} to align them",
                        "shard both operands' contracting dims the same "
                        "way (or neither)",
                        collective="all_to_all", bytes=nb,
                        axes=sorted(ra))
                ra = la
            partial |= la | ra
        batch = []
        for i, j in zip(lb, rb):
            la = lhs.dims[i] if i < len(lhs.dims) else _EMPTY
            ra = rhs.dims[j] if j < len(rhs.dims) else _EMPTY
            batch.append(la or ra)
        lfree = [lhs.dims[i] for i in range(len(lhs.dims))
                 if i not in lc and i not in lb]
        rfree = [rhs.dims[j] for j in range(len(rhs.dims))
                 if j not in rc and j not in rb]
        return [_dedupe_axes(batch + lfree + rfree, frozenset(partial))]

    def _apply_reshape(self, eqn, spec: VSpec) -> VSpec:
        in_shape = tuple(eqn.invars[0].aval.shape)
        out_shape = tuple(eqn.outvars[0].aval.shape)
        if in_shape == out_shape:
            return spec
        dims = [_EMPTY] * len(out_shape)
        i = j = 0
        while i < len(in_shape) and j < len(out_shape):
            if in_shape[i] == out_shape[j]:
                dims[j] = spec.dims[i] if i < len(spec.dims) else _EMPTY
                i += 1
                j += 1
                continue
            # split or merge group: assign the group's first in-dim axes
            # to the group's first out-dim (major-dim sharding survives
            # a merge/split whose major extent is unchanged)
            ip, jp, isz, osz = i, j, in_shape[i], out_shape[j]
            while isz != osz and ip + 1 <= len(in_shape) \
                    and jp + 1 <= len(out_shape):
                if isz < osz and ip + 1 < len(in_shape):
                    ip += 1
                    isz *= in_shape[ip]
                elif osz < isz and jp + 1 < len(out_shape):
                    jp += 1
                    osz *= out_shape[jp]
                else:
                    break
            axes = spec.dims[i] if i < len(spec.dims) else _EMPTY
            group_n = 1
            for a in axes:
                group_n *= self.axis_sizes.get(a, 1)
            if axes and out_shape[j] % max(group_n, 1) == 0:
                dims[j] = axes
            i, j = ip + 1, jp + 1
        return _dedupe_axes(dims, spec.partial)

    def _apply_pjit(self, eqn, in_specs, path_t, weight) -> List[VSpec]:
        sub = eqn.params["jaxpr"]
        in_sh = eqn.params.get("in_shardings") or ()
        out_sh = eqn.params.get("out_shardings") or ()
        path = format_path(path_t, eqn)
        sub_in: List[VSpec] = []
        for i, (v, spec) in enumerate(zip(eqn.invars, in_specs)):
            decl = _named_spec(in_sh[i]) if i < len(in_sh) else None
            if decl is not None:
                want = _from_pspec(decl, _ndim(v))
                if spec.dims != want.dims or spec.partial:
                    nb = aval_bytes(getattr(v, "aval", None)) \
                        if hasattr(v, "aval") else 0
                    kinds = self._classify_reshard(
                        spec, want, nb, path, weight, "pjit in_sharding")
                    if nb >= self.min_bytes and (
                            "all_gather" in kinds or "all_to_all" in kinds):
                        self._find(
                            Severity.WARNING, "SHARD_RESHARD", path,
                            f"pjit arg {i} arrives as {spec} but the jit "
                            f"declares {want} ({fmt_bytes(nb)} resharded "
                            "at the call boundary)",
                            "make the caller's layout match in_shardings "
                            "(or relax the declaration)",
                            collective=kinds[0], bytes=nb, argnum=i,
                            src_spec=spec.pspec(), dst_spec=want.pspec())
                spec = want
            sub_in.append(spec)
        sub_out = self.walk(_as_open(sub), sub_in,
                            path_t + (_eqn_label(eqn), "jaxpr"), weight)
        outs: List[VSpec] = []
        for i, ov in enumerate(eqn.outvars):
            decl = _named_spec(out_sh[i]) if i < len(out_sh) else None
            got = sub_out[i] if i < len(sub_out) else _repl(_ndim(ov))
            if decl is not None:
                want = _from_pspec(decl, _ndim(ov))
                if got.dims != want.dims or got.partial:
                    self._classify_reshard(
                        got, want, aval_bytes(ov.aval), path, weight,
                        "pjit out_sharding")
                got = want
            outs.append(got)
        return outs

    def _apply_scan(self, eqn, in_specs, path_t, weight) -> List[VSpec]:
        p = eqn.params
        nc, nk = p["num_consts"], p["num_carry"]
        length = int(p.get("length", 1) or 1)
        body = _as_open(p["jaxpr"])
        consts = in_specs[:nc]
        carry = [VSpec(dims=s.dims) for s in in_specs[nc:nc + nk]]
        xs = [VSpec(dims=s.dims[1:] if s.dims else ())
              for s in in_specs[nc + nk:]]
        sub_path = path_t + (_eqn_label(eqn), "body")
        self._mute += 1
        try:
            for _ in range(4):          # carry fixpoint (meet = intersect)
                outs = self.walk(body, consts + carry + xs, sub_path, weight)
                nxt = [VSpec(dims=tuple(
                    a & b for a, b in zip(c.dims, o.dims)))
                    for c, o in zip(carry, outs[:nk])]
                if nxt == carry:
                    break
                carry = nxt
        finally:
            self._mute -= 1
        outs = self.walk(body, consts + carry + xs, sub_path,
                         weight * length)
        carry_out = [VSpec(dims=tuple(a & b for a, b in
                                      zip(c.dims, o.dims)))
                     for c, o in zip(carry, outs[:nk])]
        ys = [VSpec(dims=(_EMPTY,) + o.dims) for o in outs[nk:]]
        return carry_out + ys

    def _apply_cond(self, eqn, in_specs, path_t, weight) -> List[VSpec]:
        branches = eqn.params["branches"]
        ops = in_specs[1:]
        all_outs = []
        for i, b in enumerate(branches):
            sub = _as_open(b)
            all_outs.append(self.walk(
                sub, list(ops)[:len(sub.invars)],
                path_t + (_eqn_label(eqn), f"branch{i}"), weight))
        outs = []
        for i, ov in enumerate(eqn.outvars):
            specs = [o[i] for o in all_outs if i < len(o)]
            if not specs:
                outs.append(_repl(_ndim(ov)))
                continue
            dims = specs[0].dims
            for s in specs[1:]:
                dims = tuple(a & b for a, b in zip(dims, s.dims))
            outs.append(VSpec(dims=dims))
        return outs

    def _apply_while(self, eqn, in_specs, path_t, weight) -> List[VSpec]:
        p = eqn.params
        cn, bn = p.get("cond_nconsts", 0), p.get("body_nconsts", 0)
        body = _as_open(p["body_jaxpr"])
        carry = [VSpec(dims=s.dims) for s in in_specs[cn + bn:]]
        bconsts = in_specs[cn:cn + bn]
        sub_path = path_t + (_eqn_label(eqn), "body")
        self._mute += 1
        try:
            for _ in range(4):
                outs = self.walk(body, bconsts + carry, sub_path, weight)
                nxt = [VSpec(dims=tuple(a & b for a, b in
                                        zip(c.dims, o.dims)))
                       for c, o in zip(carry, outs)]
                if nxt == carry:
                    break
                carry = nxt
        finally:
            self._mute -= 1
        self.walk(_as_open(p["cond_jaxpr"]),
                  in_specs[:cn] + carry,
                  path_t + (_eqn_label(eqn), "cond"), weight)
        outs = self.walk(body, bconsts + carry, sub_path, weight)
        return [VSpec(dims=tuple(a & b for a, b in zip(c.dims, o.dims)))
                for c, o in zip(carry, outs)]

    def _apply_generic_container(self, eqn, in_specs, path_t,
                                 weight) -> List[VSpec]:
        from jax.extend import core as jex_core

        subs = [(k, v) for k, v in eqn.params.items()
                if isinstance(v, (jex_core.Jaxpr, jex_core.ClosedJaxpr))]
        for key, sub in subs:
            oj = _as_open(sub)
            if len(oj.invars) == len(eqn.invars) \
                    and len(oj.outvars) == len(eqn.outvars):
                return self.walk(oj, in_specs,
                                 path_t + (_eqn_label(eqn), key), weight)
        path = format_path(path_t, eqn)
        for v, s in zip(eqn.invars, in_specs):
            self._materialize(s, v, path, weight, "opaque container")
        return [_repl(_ndim(v)) for v in eqn.outvars]

    # -- the walk -----------------------------------------------------------

    def walk(self, jaxpr, in_specs: List[VSpec],
             path_t: Tuple[str, ...] = (), weight: int = 1) -> List[VSpec]:
        jaxpr = _as_open(jaxpr)
        env: Dict[Any, VSpec] = {}
        for cv in jaxpr.constvars:
            env[cv] = _repl(_ndim(cv))
        for iv, s in zip(jaxpr.invars, list(in_specs)
                         + [None] * max(0, len(jaxpr.invars)
                                        - len(in_specs))):
            env[iv] = s if s is not None else _repl(_ndim(iv))

        def read(v):
            if is_array_var(v):
                return env.get(v, _repl(_ndim(v)))
            return _repl(_ndim(v))      # literals are replicated

        candidates: List[Tuple[Any, Any, str]] = []   # (var, eqn, path)
        big_repl: set = set()
        for eqn in jaxpr.eqns:
            specs_in = [read(v) for v in eqn.invars]
            try:
                outs = self._apply(eqn, specs_in, path_t, weight)
            except Exception:  # noqa: BLE001 — a rule miss must not kill lint
                outs = [_repl(_ndim(v)) for v in eqn.outvars]
            if len(outs) < len(eqn.outvars):
                outs = list(outs) + [_repl(_ndim(v))
                                     for v in eqn.outvars[len(outs):]]
            inherits = any(v in big_repl for v in eqn.invars
                           if is_array_var(v))
            # an explicit replicating constraint is the user's call (and
            # already SHARD_GAP when it undoes a sharding) — not a
            # replication CANDIDATE
            constrained = eqn.primitive.name == "sharding_constraint"
            for ov, spec in zip(eqn.outvars, outs):
                if is_array_var(ov):
                    env[ov] = spec
                    nb = aval_bytes(ov.aval)
                    if spec.is_replicated and nb >= self.min_bytes \
                            and not constrained and not self._mute:
                        big_repl.add(ov)
                        if not inherits:
                            candidates.append(
                                (ov, eqn, format_path(path_t, eqn)))
            if not self._mute:
                row_specs = [str(env[ov]) for ov in eqn.outvars
                             if is_array_var(ov)]
                self.eqn_rows.append({
                    "path": format_path(path_t, eqn),
                    "primitive": eqn.primitive.name,
                    "out_specs": row_specs,
                    "bytes": max((aval_bytes(ov.aval)
                                  for ov in eqn.outvars
                                  if is_array_var(ov)), default=0),
                })
        # backward sweep: values a later SHARDED constraint (or a cheap
        # view chain above one) reaches are effectively sharded — GSPMD
        # propagates constraints backward; don't accuse them
        btaint: set = set()
        for eqn in reversed(jaxpr.eqns):
            prim = eqn.primitive.name
            if prim == "sharding_constraint":
                spec = _named_spec(eqn.params.get("sharding"))
                if spec is not None and any(e is not None for e in spec):
                    btaint.update(v for v in eqn.invars if is_array_var(v))
            elif prim in ("convert_element_type", "transpose", "reshape",
                          "copy", "squeeze", "broadcast_in_dim") and any(
                    v in btaint for v in eqn.outvars if is_array_var(v)):
                btaint.update(v for v in eqn.invars if is_array_var(v))
        for ov, eqn, path in candidates:
            if ov in btaint:
                continue
            pick = suggest_spec(tuple(ov.aval.shape), _EMPTY,
                                self.axis_sizes)
            if pick is None:
                continue                # not provably shardable
            d, axis = pick
            pspec = [None] * _ndim(ov)
            pspec[d] = axis
            nb = aval_bytes(ov.aval)
            self._find(
                Severity.WARNING, "SHARD_REPLICATED", path,
                f"{fmt_aval(ov.aval)} ({fmt_bytes(nb)}) is fully "
                f"replicated under the mesh — dim {d} divides evenly "
                f"over mesh axis {axis!r} ({self.axis_sizes[axis]} ways)",
                "apply jax.lax.with_sharding_constraint with "
                f"PartitionSpec{tuple(pspec)!r}",
                spec=pspec, dim=d, axis=axis, bytes=nb,
                # shape-qualified site identity: two same-named eqns at
                # one path (e.g. two broadcast_in_dim in one jaxpr) must
                # not dedupe-collapse their patches
                target=f"{path} {fmt_aval(ov.aval)}")
        # materialize partial outvars of the TOP scope only (inner scopes
        # hand their partials to the caller)
        if not path_t:
            for ov in jaxpr.outvars:
                if is_array_var(ov) and env.get(ov) is not None:
                    env[ov] = self._materialize(
                        env[ov], ov, "<out>", weight, "program output")
        return [read(v) for v in jaxpr.outvars]


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def _mesh_axis_sizes(mesh) -> Dict[str, int]:
    try:
        return {str(a): int(n) for a, n in dict(mesh.shape).items()}
    except Exception:  # noqa: BLE001
        return {}


def _default_chip(options_opt) -> str:
    chip = options_opt("spmd_chip")
    if chip:
        return str(chip)
    try:
        import jax

        d = jax.devices()[0]
        if getattr(d, "platform", "") == "tpu":
            return getattr(d, "device_kind", "tpu")
    except Exception:  # noqa: BLE001
        pass
    return comm_cost._DEFAULT_CHIP


def propagate(closed_jaxpr, mesh, in_specs: Optional[Sequence] = None,
              options: Optional[dict] = None,
              chip: Optional[str] = None) -> SpmdResult:
    """Run the SPMD abstract interpreter over a ClosedJaxpr under `mesh`.

    `in_specs`: optional per-invar PartitionSpec entry lists (e.g. from
    `spec_of_value` on the real call args); None entries (and a None
    list) mean replicated/unknown — pjit `in_shardings` inside the graph
    still seed those.  Returns the per-eqn spec table, priced
    collectives, SHARD_* findings, and the comm-vs-compute roofline.
    """
    from .core import CheckContext as _CC

    opt_ctx = _CC(closed_jaxpr=closed_jaxpr, options=dict(options or {}))
    axis_sizes = _mesh_axis_sizes(mesh)
    chip = chip or _default_chip(opt_ctx.opt)
    interp = _Interp(axis_sizes, opt_ctx.opt, chip,
                     int(opt_ctx.opt("sharding_min_bytes")))
    jaxpr = closed_jaxpr.jaxpr
    seeds: List[VSpec] = []
    for i, v in enumerate(jaxpr.invars):
        entries = None
        if in_specs is not None and i < len(in_specs):
            entries = in_specs[i]
        seeds.append(_from_pspec(entries, _ndim(v)) if entries is not None
                     else _repl(_ndim(v)))
    interp.walk(jaxpr, seeds)
    est = cost_lib.estimate(closed_jaxpr, top_k=0)
    mesh_size = 1
    for n in axis_sizes.values():
        mesh_size *= max(1, n)
    roof = comm_cost.roofline(est["total_flops"], interp.collectives,
                              mesh_size, chip=chip)
    return SpmdResult(
        eqn_rows=interp.eqn_rows, collectives=interp.collectives,
        findings=interp.findings, roofline=roof, mesh_axes=axis_sizes,
        chip=chip)


@register_checker("spmd")
def check_spmd(ctx: CheckContext):
    """The tier-4 checker: SHARD_RESHARD / mesh-aware SHARD_REPLICATED /
    SHARD_GAP from the propagation walk, plus ONE COLLECTIVE_BOUND
    roofline finding (WARNING when the step is comm-bound at this
    mesh/chip) and an INFO SPMD_SUMMARY carrying the table sizes."""
    mesh = ctx.mesh
    if mesh is None or getattr(mesh, "size", 1) <= 1:
        return []
    import jax

    in_specs = None
    if ctx.args or ctx.kwargs:
        leaves = jax.tree_util.tree_leaves((ctx.args, ctx.kwargs))
        in_specs = [spec_of_value(x) for x in leaves]
    declared = ctx.opt("spmd_in_specs")
    if declared is not None:
        # explicit seed specs (ShardedTrainState.spmd_report, the rewrite
        # tier's re-lint gate) fill what the args cannot say: abstract
        # ShapeDtypeStruct args carry no .sharding
        declared = list(declared)
        if in_specs is None:
            in_specs = declared
        else:
            in_specs = [a if a is not None else (declared[i] if
                                                 i < len(declared) else None)
                        for i, a in enumerate(in_specs)]
    if in_specs is not None:
        n = len(ctx.closed_jaxpr.jaxpr.invars)
        in_specs = (in_specs + [None] * n)[:n]
    res = propagate(ctx.closed_jaxpr, mesh, in_specs=in_specs,
                    options=ctx.options)
    findings = list(res.findings)
    roof = res.roofline
    comm_bound = (roof["bound"] == "comm"
                  and roof["collective_bytes"]
                  >= ctx.opt("collective_min_bytes"))
    top = sorted(res.collectives, key=lambda c: -c.seconds)[:5]
    findings.append(Finding(
        Severity.WARNING if comm_bound else Severity.INFO,
        "COLLECTIVE_BOUND", "<top>",
        f"static roofline on {res.chip} x{roof['mesh_size']}: compute "
        f"~{roof['t_compute_s'] * 1e3:.3g} ms vs collectives "
        f"~{roof['t_comm_s'] * 1e3:.3g} ms "
        f"({roof['n_collectives']} collective(s), "
        f"{fmt_bytes(roof['collective_bytes'])} through ICI) — "
        f"{roof['bound']}-bound",
        ("grow per-chip batch/model work, or cut the biggest collective "
         "(see data.collectives)" if comm_bound else ""),
        data={"roofline": dict(roof),
              "collectives": [c.to_dict() for c in top],
              "mesh": dict(res.mesh_axes), "chip": res.chip}))
    findings.append(Finding(
        Severity.INFO, "SPMD_SUMMARY", "<top>",
        f"predicted shardings for {len(res.eqn_rows)} eqn(s) under mesh "
        f"{dict(res.mesh_axes)}; "
        f"{sum(1 for f in res.findings if f.code == 'SHARD_RESHARD')} "
        f"reshard boundary(ies), {len(res.collectives)} implied "
        "collective(s)",
        "spmd.propagate(jaxpr, mesh) returns the full per-eqn table",
        data={"n_eqns": len(res.eqn_rows),
              "rows": sorted(res.eqn_rows, key=lambda r: -r["bytes"])[:8]}))
    return findings
