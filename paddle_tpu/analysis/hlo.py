"""Graph Doctor tier 2: lint the COMPILED artifact, not just the trace.

Jaxprs (tier 1, `checkers.py`) are pre-XLA: they cannot see fusion,
layout, buffer-assignment, or collective-combining decisions — which is
where TPU performance is actually won or lost (the TPU-MLIR / MPK lesson:
lowering-level analysis catches what trace-level analysis structurally
cannot).  This module lowers a target ONCE (`jax.jit(fn).lower(*args)`),
keeps both artifacts —

  * the StableHLO module text (pre-optimization, metadata-rich), and
  * the optimized HLO text + `compiled.memory_analysis()` buffer stats —

and runs a second checker registry over them:

  fusion       FUSION_BREAK       chains of unfused elementwise ops in the
                                  optimized module (each one a full HBM
                                  round-trip a fused loop would elide)
  collective   COLLECTIVE_SEQ     independent same-group all-reduce/
                                  all-gathers that could combine into one
  layout       LAYOUT_TRANSPOSE   materialized transposes / layout copies
                                  that survived compilation on big arrays
  hlo_memory   MEM_PEAK           buffer-assignment peak (args+temps+outs)
               MEM_TEMP_BLOAT     temporaries dwarfing the live args/outs

Nothing executes — `.lower()` + `.compile()` only.  Checkers parse the
HLO *text* (the stable, version-tolerant surface; the in-memory HLO API
is private and churns), so every finding degrades gracefully: a parse
miss means a silent pass, never a crash.

`lint_bucket_menu` is the shape-poly probe grown into menu planning: the
LLMEngine hands it the prefill bucket menu plus an expected workload's
prompt lengths, and lengths that STRADDLE a bucket edge (9 tokens riding
a 16-wide compile next to 8-token traffic) come back as
RECOMPILE_BUCKET_MISS with the concrete menu edit that merges them.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax

from .core import (
    Finding, Report, Severity, finalize_findings, fmt_bytes,
    _DEFAULT_OPTIONS,
)

__all__ = [
    "analyze_hlo", "register_hlo_checker", "list_hlo_checkers",
    "HLOContext", "lint_bucket_menu", "lower_target",
]

HLO_CHECKER_REGISTRY: Dict[str, Callable] = {}


def register_hlo_checker(name: str):
    """Register an HLO-tier checker: fn(ctx: HLOContext) -> findings."""
    def deco(fn):
        HLO_CHECKER_REGISTRY[name] = fn
        fn._checker_name = name
        return fn
    return deco


def list_hlo_checkers() -> List[str]:
    return sorted(HLO_CHECKER_REGISTRY)


# ---------------------------------------------------------------------------
# HLO text parsing (optimized module)
# ---------------------------------------------------------------------------

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1, "s4": 1, "u4": 1,
}

_SHAPE_RE = re.compile(r"^([a-z][a-z0-9]*)\[([\d,]*)\]")


def shape_bytes(shape_str: str) -> int:
    """Bytes of one HLO shape string ("f32[2,16]{1,0}"); tuples -> 0."""
    m = _SHAPE_RE.match(shape_str)
    if not m:
        return 0
    nbytes = _DTYPE_BYTES.get(m.group(1), 0)
    for d in m.group(2).split(","):
        if d:
            nbytes *= int(d)
    return nbytes


@dataclasses.dataclass
class HloInstr:
    name: str
    op: str
    shape: str
    nbytes: int
    operands: List[str]          # referenced %names (instrs + computations)
    op_name: str                 # metadata op_name ("" when absent)
    comp: str
    # typed operands as written: [(shape_str, %name)] — layout checks
    # compare these {minor-to-major} braces against the result's
    typed_operands: List[Tuple[str, str]] = dataclasses.field(
        default_factory=list)

    def layout(self) -> str:
        return _layout_of(self.shape)


def _layout_of(shape_str: str) -> str:
    """The {minor-to-major} brace content of an HLO shape string."""
    m = re.search(r"\{([\d,]*)\}", shape_str)
    return m.group(1) if m else ""


_COMP_RE = re.compile(r"^(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->\s*.*\{")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%([\w.\-]+)\s*=\s*(\S+)\s+([\w\-]+)\((.*)$")
_METADATA_RE = re.compile(r'op_name="([^"]*)"')


def parse_hlo(text: str) -> Dict[str, List[HloInstr]]:
    """{computation_name: [instrs]} for an optimized-HLO module dump.
    Fusion computations keep their ``fused_`` names; callers use
    `fused_computations` to exclude them."""
    comps: Dict[str, List[HloInstr]] = {}
    cur: Optional[str] = None
    for line in text.splitlines():
        if cur is None:
            m = _COMP_RE.match(line.strip())
            if m:
                cur = m.group(2)
                comps[cur] = []
            continue
        if line.strip() == "}":
            cur = None
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, shape, op, rest = m.groups()
        meta = _METADATA_RE.search(rest)
        # operand refs: %names before any metadata={...} block
        op_part = rest.split("metadata=", 1)[0]
        operands = re.findall(r"%([\w.\-]+)", op_part)
        typed = re.findall(r"(\S+\[[^\]]*\](?:\{[\d,]*\})?)\s+%([\w.\-]+)",
                           op_part)
        comps[cur].append(HloInstr(
            name=name, op=op, shape=shape, nbytes=shape_bytes(shape),
            operands=operands, op_name=meta.group(1) if meta else "",
            comp=cur, typed_operands=typed))
    return comps


def fused_computations(comps: Dict[str, List[HloInstr]]) -> set:
    """Computations that run INSIDE a fusion (their instrs cost nothing
    individually): named `fused_*` or referenced by a fusion's calls=."""
    fused = {c for c in comps if c.startswith("fused_")}
    for instrs in comps.values():
        for ins in instrs:
            if ins.op == "fusion":
                fused.update(o for o in ins.operands if o in comps)
    return fused


# ---------------------------------------------------------------------------
# context + entry point
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class HLOContext:
    """What HLO-tier checkers may inspect.  `optimized`/`memory_stats`
    are None when compilation was skipped or failed (checkers needing
    them must silently pass)."""

    stablehlo: str
    optimized: Optional[str] = None
    memory_stats: Any = None
    comps: Optional[Dict[str, List[HloInstr]]] = None
    fn: Optional[Callable] = None
    args: Tuple = ()
    options: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def opt(self, key: str, default=None):
        if key in self.options:
            return self.options[key]
        return _DEFAULT_OPTIONS.get(key, default)


def lower_target(fn, *args, compile: bool = True, **kwargs):
    """Lower (and optionally compile) once: returns
    (stablehlo_text, optimized_text | None, memory_stats | None).
    `fn` may already be jitted (uses its .lower) or plain (wrapped)."""
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    lowered = jfn.lower(*args, **kwargs)
    stablehlo = lowered.as_text()
    optimized = stats = None
    if compile:
        compiled = lowered.compile()
        optimized = compiled.as_text()
        try:
            stats = compiled.memory_analysis()
        except Exception:  # noqa: BLE001 — not all backends implement it
            stats = None
    return stablehlo, optimized, stats


def analyze_hlo(fn, *args, checkers: Optional[Sequence[str]] = None,
                suppress: Sequence[str] = (),
                options: Optional[dict] = None,
                config: Optional[dict] = None,
                compile: bool = True, **kwargs) -> Report:
    """Tier-2 analysis: lower `fn(*args)` once and run the HLO checker
    registry over the StableHLO + optimized HLO + buffer stats.

    Composes with tier 1 via `core.merge_reports(analyze(...),
    analyze_hlo(...))` — tools/graphlint.py does exactly that per target.
    """
    stablehlo, optimized, stats = lower_target(
        fn, *args, compile=compile, **kwargs)
    return analyze_hlo_text(
        stablehlo, optimized, memory_stats=stats, checkers=checkers,
        suppress=suppress, options=options, config=config, fn=fn,
        args=args)


def analyze_hlo_text(stablehlo: str, optimized: Optional[str] = None,
                     memory_stats: Any = None,
                     checkers: Optional[Sequence[str]] = None,
                     suppress: Sequence[str] = (),
                     options: Optional[dict] = None,
                     config: Optional[dict] = None,
                     fn=None, args=()) -> Report:
    """Run the HLO checkers over already-obtained artifacts (a saved
    `.compile().as_text()` dump, a cross-compiled module, a test
    fixture).  `analyze_hlo` is this plus the lowering."""
    ctx = HLOContext(
        stablehlo=stablehlo, optimized=optimized, memory_stats=memory_stats,
        comps=parse_hlo(optimized) if optimized else None,
        fn=fn, args=tuple(args), options=dict(options or {}))
    names = list_hlo_checkers() if checkers is None else list(checkers)
    findings: List[Finding] = []
    for name in names:
        if name not in HLO_CHECKER_REGISTRY:
            raise ValueError(f"unknown HLO checker {name!r}; "
                             f"available: {list_hlo_checkers()}")
        for f in HLO_CHECKER_REGISTRY[name](ctx):
            if not f.checker:
                f = dataclasses.replace(f, checker=name)
            findings.append(f)
    return finalize_findings(findings, names, ctx, suppress, config)


# ---------------------------------------------------------------------------
# checker 1: FUSION_BREAK — unfused elementwise chains
# ---------------------------------------------------------------------------

_ELEMENTWISE = frozenset({
    "add", "subtract", "multiply", "divide", "maximum", "minimum", "power",
    "tanh", "exponential", "log", "negate", "abs", "rsqrt", "sqrt",
    "logistic", "sign", "floor", "ceil", "round-nearest-even", "cosine",
    "sine", "expm1", "log-plus-one", "select", "compare", "and", "or",
    "xor", "not", "clamp",
})

# ops that forward a value without compute: a chain may thread through
# them (optimization_barrier lowers to tuple/opt-barrier/get-tuple-element)
_PASS_THROUGH = frozenset({
    "bitcast", "copy", "tuple", "get-tuple-element", "opt-barrier",
})


@register_hlo_checker("fusion")
def check_fusion(ctx: HLOContext):
    if not ctx.comps:
        return
    min_b = ctx.opt("fusion_min_bytes")
    min_len = ctx.opt("fusion_chain_min")
    fused = fused_computations(ctx.comps)
    n_fusions = sum(1 for instrs in ctx.comps.values()
                    for i in instrs if i.op == "fusion")
    for cname, instrs in ctx.comps.items():
        if cname in fused:
            continue
        by_name = {i.name: i for i in instrs}

        def resolve(name, depth=0):
            """Follow pass-through ops back to a real producer."""
            ins = by_name.get(name)
            while ins is not None and ins.op in _PASS_THROUGH and depth < 8:
                nxt = next((o for o in ins.operands if o in by_name), None)
                if nxt is None:
                    return ins
                ins = by_name.get(nxt)
                depth += 1
            return ins

        # longest unfused-elementwise chain ending at each instr
        nodes = [i for i in instrs
                 if i.op in _ELEMENTWISE and i.nbytes >= min_b]
        node_names = {i.name for i in nodes}
        chain: Dict[str, List[str]] = {}
        for ins in instrs:            # program order = topological order
            if ins.name not in node_names:
                continue
            best: List[str] = []
            for o in ins.operands:
                src = resolve(o)
                if src is not None and src.name in chain \
                        and len(chain[src.name]) > len(best):
                    best = chain[src.name]
            chain[ins.name] = best + [ins.name]
        best_chain: List[str] = max(chain.values(), key=len, default=[])
        if len(best_chain) >= min_len:
            ops = [by_name[n].op for n in best_chain]
            head = by_name[best_chain[0]]
            yield Finding(
                Severity.WARNING, "FUSION_BREAK", f"hlo:{cname}",
                f"chain of {len(best_chain)} UNFUSED elementwise ops "
                f"({'->'.join(ops[:6])}{'...' if len(ops) > 6 else ''}) on "
                f"{head.shape.split('{')[0]} ({fmt_bytes(head.nbytes)}) — "
                f"each op is a full HBM read+write a fused loop would "
                f"elide (module has {n_fusions} fusions)",
                "remove optimization_barrier/custom-call boundaries "
                "between them, or restructure so XLA can fuse the chain",
                data={"chain": [by_name[n].op for n in best_chain],
                      "bytes": head.nbytes, "computation": cname,
                      "fusions_in_module": n_fusions})


# ---------------------------------------------------------------------------
# checker 2: COLLECTIVE_SEQ — combinable adjacent collectives (StableHLO
# tier: deterministic, pre-combiner; suggests combining at the SOURCE)
# ---------------------------------------------------------------------------

_COLLECTIVE_RE = re.compile(
    r'%(\S+)\s*=\s*"stablehlo\.(all_reduce|all_gather|reduce_scatter)"'
    r"\(([^)]*)\)")
_REPLICA_RE = re.compile(r"replica_groups\s*=\s*dense<([^>]*)>")
_RESULT_TY_RE = re.compile(r"->\s*tensor<([^>]+)>")


@register_hlo_checker("collective")
def check_collective(ctx: HLOContext):
    min_b = ctx.opt("collective_min_bytes")
    # SSA def-use over the whole module: value id -> collective ids it
    # (transitively) depends on.  Dependent collectives cannot combine.
    deps: Dict[str, set] = {}
    coll: List[dict] = []          # in program order
    lines = ctx.stablehlo.splitlines()
    for ln, line in enumerate(lines):
        s = line.strip()
        # multi-result ops print as "%5:3 = ..." and are referenced as
        # "%5#0" — track everything under the base id so a collective
        # feeding a while/sort result still counts as a dependency
        m = re.match(r"%([\w]+)(?::\d+)?\s*=", s)
        if not m:
            continue
        rid = m.group(1)
        operands = re.findall(r"%([\w#]+)", s.split("=", 1)[1])
        d: set = set()
        for o in operands:
            d |= deps.get(o.split("#", 1)[0], set())
        cm = _COLLECTIVE_RE.match(s)
        if cm:
            groups = _REPLICA_RE.search(s)
            # the reduction region spans lines; the result type lives on
            # the region's closing "}) : (...) -> tensor<...>" line
            ty = _RESULT_TY_RE.search(s)
            for look in lines[ln + 1:ln + 12]:
                if ty is not None:
                    break
                if ") : (" in look or look.strip().startswith("}) :"):
                    ty = _RESULT_TY_RE.search(look)
                    break
            nbytes = 0
            if ty:
                parts = ty.group(1).split("x")
                nbytes = _DTYPE_BYTES.get(parts[-1], 0)
                for p in parts[:-1]:
                    if p.isdigit():
                        nbytes *= int(p)
            coll.append({"id": rid, "kind": cm.group(2),
                         "groups": groups.group(1) if groups else "",
                         "bytes": nbytes, "deps": set(d)})
            d = d | {rid}
        deps[rid] = d
    by_key: Dict[Tuple[str, str], List[dict]] = {}
    for c in coll:
        by_key.setdefault((c["kind"], c["groups"]), []).append(c)
    for (kind, groups), ops in by_key.items():
        # greedy batch: later ops join unless they depend on a member
        batch: List[dict] = []
        for c in ops:
            if all(b["id"] not in c["deps"] for b in batch):
                batch.append(c)
        total = sum(c["bytes"] for c in batch)
        if len(batch) >= 2 and total >= min_b:
            yield Finding(
                Severity.WARNING, "COLLECTIVE_SEQ", f"stablehlo:{kind}",
                f"{len(batch)} independent {kind} ops over identical "
                f"replica groups ({fmt_bytes(total)} total) — each pays "
                "its own latency + launch; one combined collective "
                "moves the same bytes once",
                "combine at the source: flatten+concatenate the operands "
                "and issue one "
                + {"all_reduce": "jax.lax.psum",
                   "all_gather": "jax.lax.all_gather",
                   "reduce_scatter": "jax.lax.psum_scatter"}[kind]
                + " (a tuple psum still lowers to one collective per "
                "leaf; XLA's combiner pass may batch small ones, but "
                "upstream combining is guaranteed)",
                data={"kind": kind, "count": len(batch), "bytes": total})


# ---------------------------------------------------------------------------
# checker 3: LAYOUT_TRANSPOSE — materialized transposes / layout copies
# ---------------------------------------------------------------------------


@register_hlo_checker("layout")
def check_layout(ctx: HLOContext):
    """Physical relayouts that survived compilation.  Two shapes:

    * a `copy` whose operand {minor-to-major} layout differs from its
      result's — the layout-assignment pass materializing a relayout
      (counted even inside fusions: the copy is the fusion's real work);
    * a standalone `transpose` at non-fused scope — a data shuffle no
      consumer absorbed (a transpose folded into dot dimension numbers
      or fused into a loop never appears standalone).
    """
    if not ctx.comps:
        return
    min_b = ctx.opt("layout_min_bytes")
    fused = fused_computations(ctx.comps)
    for cname, instrs in ctx.comps.items():
        for ins in instrs:
            if ins.nbytes < min_b:
                continue
            relayout = (ins.op == "copy" and ins.typed_operands
                        and _layout_of(ins.typed_operands[0][0])
                        != ins.layout())
            standalone_t = ins.op == "transpose" and cname not in fused
            if not (relayout or standalone_t):
                continue
            user_written = any(t in ins.op_name.lower()
                               for t in ("transpose", "swapaxes", "permute"))
            who = ("a user-written transpose XLA could not fold into its "
                   "consumer" if user_written else
                   "a compiler-inserted layout change (two consumers want "
                   "different physical layouts)")
            yield Finding(
                Severity.WARNING, "LAYOUT_TRANSPOSE",
                f"hlo:{cname}/{ins.op_name or ins.name}",
                f"materialized {'relayout copy' if relayout else ins.op} "
                f"of {ins.shape.split('{')[0]} ({fmt_bytes(ins.nbytes)}) "
                f"survived compilation — {who}; on TPU this is a full "
                "relayout through HBM on the hot path",
                "reorder the einsum/dot dims so the transpose folds into "
                "dimension numbers, or keep the tensor in one layout "
                "end-to-end",
                data={"op": ins.op, "bytes": ins.nbytes,
                      "op_name": ins.op_name, "relayout": relayout,
                      "user_written": user_written})


# ---------------------------------------------------------------------------
# checker 4: MEM_PEAK / MEM_TEMP_BLOAT — buffer-assignment ground truth
# ---------------------------------------------------------------------------


@register_hlo_checker("hlo_memory")
def check_hlo_memory(ctx: HLOContext):
    st = ctx.memory_stats
    if st is None:
        return
    arg = int(getattr(st, "argument_size_in_bytes", 0) or 0)
    out = int(getattr(st, "output_size_in_bytes", 0) or 0)
    temp = int(getattr(st, "temp_size_in_bytes", 0) or 0)
    alias = int(getattr(st, "alias_size_in_bytes", 0) or 0)
    peak = arg + out - alias + temp
    data = {"argument_size_in_bytes": arg, "output_size_in_bytes": out,
            "temp_size_in_bytes": temp, "alias_size_in_bytes": alias,
            "peak_bytes": peak}
    budget = ctx.opt("mem_peak_budget_bytes")
    over = budget is not None and peak > int(budget)
    yield Finding(
        Severity.WARNING if over else Severity.INFO, "MEM_PEAK",
        "hlo:<buffer-assignment>",
        f"compiled peak ~{fmt_bytes(peak)} (args {fmt_bytes(arg)} "
        f"[{fmt_bytes(alias)} aliased] + temps {fmt_bytes(temp)} + "
        f"outputs {fmt_bytes(out)})"
        + (f" — exceeds the configured budget {fmt_bytes(int(budget))}"
           if over else ""),
        ("donate read-write args, shard the model, or rematerialize "
         "the biggest liveness peak" if over else ""),
        data=data)
    ratio = ctx.opt("mem_temp_bloat_ratio")
    floor = ctx.opt("mem_temp_min_bytes")
    live_io = max(arg + out - alias, 1)
    if temp >= floor and temp > ratio * live_io:
        yield Finding(
            Severity.WARNING, "MEM_TEMP_BLOAT", "hlo:<buffer-assignment>",
            f"temporaries ({fmt_bytes(temp)}) are {temp / live_io:.1f}x "
            f"the live args+outputs ({fmt_bytes(live_io)}) — the program's "
            "footprint is dominated by intermediates buffer assignment "
            "could not elide",
            "rematerialize (jax.checkpoint) the producing region, fuse "
            "reductions into producers, or donate buffers so XLA can "
            "reuse them; profiler.static_memory attributes the peak to "
            "an eqn path",
            data=data)


# ---------------------------------------------------------------------------
# bucket-menu lint (the shape-poly probe grown into menu planning)
# ---------------------------------------------------------------------------


def lint_bucket_menu(menu: Sequence[int], workload_lens: Sequence[int],
                     suppress: Sequence[str] = (),
                     options: Optional[dict] = None,
                     config: Optional[dict] = None) -> Report:
    """Lint a prefill bucket menu against an expected workload.

    DEPRECATED: LLMEngine no longer buckets prefill at all — the unified
    ragged step (kernels/pallas_ragged_attention.py) serves every prompt
    length through ONE compiled signature, so there is no menu to plan.
    The lint (and its RECOMPILE_BUCKET_MISS code + fix patch) stays
    loadable for anything still bucketing static shapes by hand, and so
    saved reports / `.graphlintrc` suppressions keep parsing.

    Every distinct bucket is one compiled executable; every token of
    padding is wasted prefill compute.  A workload whose lengths STRADDLE
    a bucket edge (all lengths in the upper bucket sit within
    `bucket_straddle_slack` * the lower edge) pays BOTH costs for nothing:
    near-identical requests compile twice and the longer ones pad nearly
    2x.  Emits RECOMPILE_BUCKET_MISS with the concrete menu edit (merge
    the two buckets into one sized to the real lengths, aligned to
    `bucket_align`).
    """
    ctx = HLOContext(stablehlo="", options=dict(options or {}))
    menu = sorted(set(int(b) for b in menu))
    findings: List[Finding] = []
    if not menu:
        raise ValueError("bucket menu is empty")
    by_bucket: Dict[int, List[int]] = {}
    for n in workload_lens:
        n = int(n)
        b = next((b for b in menu if b >= n), None)
        if b is None:
            findings.append(Finding(
                Severity.WARNING, "RECOMPILE_BUCKET_MISS", "<menu>",
                f"workload length {n} exceeds the largest bucket "
                f"{menu[-1]} — the request cannot be served by any "
                "compiled prefill",
                f"extend the menu past {n} (e.g. append "
                f"{_round_up(n, ctx.opt('bucket_align'))})",
                data={"menu": menu, "length": n}))
            continue
        by_bucket.setdefault(b, []).append(n)
    used = sorted(by_bucket)
    slack = float(ctx.opt("bucket_straddle_slack"))
    align = int(ctx.opt("bucket_align"))
    for lo, hi in zip(used, used[1:]):
        if menu.index(hi) != menu.index(lo) + 1:
            continue                # not adjacent in the menu
        hi_lens = by_bucket[hi]
        if max(hi_lens) > slack * lo:
            continue                # genuinely longer traffic, not straddle
        merged = sorted(by_bucket[lo] + hi_lens)
        new_b = _round_up(max(merged), align)
        # widen lo -> new_b so the whole straddle group shares ONE
        # compile; hi (and everything above) stays in the menu — unused
        # buckets compile lazily so keeping them is free, and dropping
        # the top bucket would shrink the menu's coverage (the engine
        # validates max(menu) >= max_seq_len and would reject the edit)
        suggested = sorted((set(menu) - {lo}) | {new_b})
        findings.append(Finding(
            Severity.WARNING, "RECOMPILE_BUCKET_MISS", "<menu>",
            f"prompt lengths {merged} straddle the {lo}/{hi} bucket edge: "
            f"lengths {sorted(hi_lens)} pay a {hi}-wide prefill "
            f"({hi / max(hi_lens):.2f}x padding) one compile apart from "
            f"their {lo}-bucket neighbours",
            f"widen bucket {lo} to {new_b} so the straddle group shares "
            f"one executable: prefill_buckets={suggested} "
            f"(<={new_b / max(min(merged), 1):.2f}x padding)",
            data={"menu": menu, "straddle_lens": merged,
                  "edge": [lo, hi], "suggested_menu": suggested}))
    return finalize_findings(findings, ["bucket_menu"], ctx, suppress,
                             config)


def _round_up(n: int, align: int) -> int:
    align = max(1, int(align))
    return -(-int(n) // align) * align
