"""Cauchy (reference: distribution/cauchy.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _fv, _key, _shape, _wrap


class Cauchy(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _fv(loc)
        self.scale = _fv(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        raise ValueError("Cauchy has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy has no variance")

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        u = jax.random.uniform(_key(), shp, self.loc.dtype, 1e-7, 1 - 1e-7)
        return _wrap(self.loc + self.scale * jnp.tan(math.pi * (u - 0.5)))

    def log_prob(self, value):
        v = _fv(value)
        z = (v - self.loc) / self.scale
        return _wrap(-math.log(math.pi) - jnp.log(self.scale)
                     - jnp.log1p(z ** 2))

    def entropy(self):
        return _wrap(jnp.broadcast_to(math.log(4 * math.pi)
                                      + jnp.log(self.scale), self.batch_shape))

    def cdf(self, value):
        v = _fv(value)
        return _wrap(jnp.arctan((v - self.loc) / self.scale) / math.pi + 0.5)

    def icdf(self, value):
        v = _fv(value)
        return _wrap(self.loc + self.scale * jnp.tan(math.pi * (v - 0.5)))

    def kl_divergence(self, other):
        if isinstance(other, Cauchy):
            # closed form (Chyzak & Nielsen 2019)
            num = (self.scale + other.scale) ** 2 + (self.loc - other.loc) ** 2
            den = 4 * self.scale * other.scale
            return _wrap(jnp.log(num / den))
        return super().kl_divergence(other)
