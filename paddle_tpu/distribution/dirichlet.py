"""Dirichlet (reference: distribution/dirichlet.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import ExponentialFamily, _fv, _key, _shape, _wrap


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration, name=None):
        self.concentration = _fv(concentration)
        super().__init__(self.concentration.shape[:-1],
                         self.concentration.shape[-1:])

    @property
    def mean(self):
        return _wrap(self.concentration
                     / self.concentration.sum(-1, keepdims=True))

    @property
    def variance(self):
        a0 = self.concentration.sum(-1, keepdims=True)
        m = self.concentration / a0
        return _wrap(m * (1 - m) / (a0 + 1))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape + self.event_shape
        g = jax.random.gamma(_key(), jnp.broadcast_to(self.concentration, shp))
        return _wrap(g / g.sum(-1, keepdims=True))

    def log_prob(self, value):
        v = _fv(value)
        a = self.concentration
        return _wrap(((a - 1) * jnp.log(v)).sum(-1)
                     + jax.lax.lgamma(a.sum(-1))
                     - jax.lax.lgamma(a).sum(-1))

    def entropy(self):
        a = self.concentration
        a0 = a.sum(-1)
        K = a.shape[-1]
        lnB = jax.lax.lgamma(a).sum(-1) - jax.lax.lgamma(a0)
        dg = jax.lax.digamma
        return _wrap(lnB + (a0 - K) * dg(a0) - ((a - 1) * dg(a)).sum(-1))

    def kl_divergence(self, other):
        if isinstance(other, Dirichlet):
            a, b = self.concentration, other.concentration
            a0 = a.sum(-1, keepdims=True)
            dg = jax.lax.digamma
            t = ((a - b) * (dg(a) - dg(a0))).sum(-1)
            return _wrap(t + jax.lax.lgamma(b).sum(-1)
                         - jax.lax.lgamma(a).sum(-1)
                         + jax.lax.lgamma(a0[..., 0])
                         - jax.lax.lgamma(b.sum(-1)))
        return super().kl_divergence(other)
