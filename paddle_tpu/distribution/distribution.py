"""Distribution base (reference: distribution/distribution.py Distribution,
exponential_family.py ExponentialFamily)."""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import framework
from ..tensor import Tensor, to_tensor


def _v(x):
    """Raw jnp value of a Tensor/array/scalar."""
    if isinstance(x, Tensor):
        return x._data
    return jnp.asarray(x)


def _fv(x):
    """Float raw value (ints promoted to default float dtype)."""
    r = _v(x)
    if not jnp.issubdtype(r.dtype, jnp.floating):
        r = r.astype(jnp.float32)
    return r


def _wrap(x):
    return Tensor(x)


def _key():
    return framework.next_rng_key()


def _shape(sample_shape) -> tuple:
    if sample_shape is None:
        return ()
    if isinstance(sample_shape, (int, np.integer)):
        return (int(sample_shape),)
    return tuple(int(s) for s in sample_shape)


class Distribution:
    def __init__(self, batch_shape=(), event_shape=()):
        self._batch_shape = tuple(batch_shape)
        self._event_shape = tuple(event_shape)

    @property
    def batch_shape(self):
        return self._batch_shape

    @property
    def event_shape(self):
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return _wrap(jnp.sqrt(_v(self.variance)))

    def sample(self, shape=()):
        """Non-differentiable draw (stops gradients)."""
        return _wrap(jax.lax.stop_gradient(_v(self.rsample(shape))))

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return _wrap(jnp.exp(_v(self.log_prob(value))))

    def entropy(self):
        raise NotImplementedError

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        # base case: no pairwise formula on the class — kl.kl_divergence (the
        # registry entry point) is responsible for dispatch, so raising here
        # keeps method-super() chains from recursing back into it
        raise NotImplementedError(
            f"no KL formula between {type(self).__name__} and "
            f"{type(other).__name__}; use distribution.register_kl")

    def _extend_shape(self, sample_shape):
        return _shape(sample_shape) + self._batch_shape + self._event_shape


class ExponentialFamily(Distribution):
    """Reference exponential_family.py: entropy via the Bregman divergence of
    the log-normalizer.  Subclasses define natural params + log_normalizer;
    here entropy is computed with autodiff on _log_normalizer when a subclass
    provides it (same trick as the reference's _entropy)."""

    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_params):
        raise NotImplementedError

    @property
    def _mean_carrier_measure(self):
        return 0.0

    def entropy(self):
        nparams = tuple(jnp.asarray(p) for p in self._natural_parameters)
        lg = self._log_normalizer(*nparams)  # elementwise over batch
        # d(log_normalizer)/d(natural params), elementwise via grad-of-sum
        grads = jax.grad(lambda ps: jnp.sum(self._log_normalizer(*ps)))(nparams)
        ent = lg - self._mean_carrier_measure
        for p, g in zip(nparams, grads):
            ent = ent - p * g
        return _wrap(ent)
