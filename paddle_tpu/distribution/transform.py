"""Transforms (reference: distribution/transform.py — 13 Transform classes
with forward/inverse/log_det_jacobian).

TPU-native: forward_log_det_jacobian uses jax.jacfwd-free closed forms; every
transform is a pure jnp function pair."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .distribution import _fv, _v, _wrap

__all__ = ["Type", "Transform", "AbsTransform", "AffineTransform",
           "ChainTransform", "ExpTransform", "IndependentTransform",
           "PowerTransform", "ReshapeTransform", "SigmoidTransform",
           "SoftmaxTransform", "StackTransform", "StickBreakingTransform",
           "TanhTransform"]


class Type:
    BIJECTION = "bijection"
    INJECTION = "injection"
    SURJECTION = "surjection"
    OTHER = "other"

    @classmethod
    def is_injective(cls, t):
        return t in (cls.BIJECTION, cls.INJECTION)


class Transform:
    _type = Type.INJECTION
    # how many rightmost dims the jacobian acts on (0 = elementwise)
    _domain_event_rank = 0
    _codomain_event_rank = 0

    def forward(self, x):
        return _wrap(self._forward(_fv(x)))

    def inverse(self, y):
        return _wrap(self._inverse(_fv(y)))

    def forward_log_det_jacobian(self, x):
        return _wrap(self._forward_log_det_jacobian(_fv(x)))

    def inverse_log_det_jacobian(self, y):
        yv = _fv(y)
        return _wrap(-self._forward_log_det_jacobian(self._inverse(yv)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    def __call__(self, x):
        return self.forward(x)

    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    _type = Type.SURJECTION

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y  # right-inverse (positive branch), like the reference


class AffineTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, loc, scale):
        self.loc = _fv(loc)
        self.scale = _fv(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, power):
        self.power = _fv(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    _type = Type.BIJECTION

    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(jnp.clip(y, -1 + 1e-7, 1 - 1e-7))

    def _forward_log_det_jacobian(self, x):
        return 2.0 * (math.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    _type = Type.OTHER
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, -1)

    def _inverse(self, y):
        return jnp.log(y)  # up to an additive constant (reference semantics)


class StickBreakingTransform(Transform):
    _type = Type.BIJECTION
    _domain_event_rank = 1
    _codomain_event_rank = 1

    def _forward(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zpad = jnp.concatenate([z, jnp.ones_like(z[..., :1])], -1)
        one_minus = jnp.concatenate([jnp.ones_like(z[..., :1]), 1 - z], -1)
        return zpad * jnp.cumprod(one_minus, -1)

    def _inverse(self, y):
        y_crop = y[..., :-1]
        rem = 1 - jnp.cumsum(y_crop, -1)
        rem_shift = jnp.concatenate(
            [jnp.ones_like(rem[..., :1]), rem[..., :-1]], -1)
        z = y_crop / jnp.clip(rem_shift, 1e-30, None)
        offset = y.shape[-1] - 1 - jnp.arange(y.shape[-1] - 1, dtype=y.dtype)
        return jnp.log(z / jnp.clip(1 - z, 1e-30, None)) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        offset = x.shape[-1] - jnp.arange(x.shape[-1], dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        one_minus = jnp.concatenate([jnp.ones_like(z[..., :1]), 1 - z[..., :-1]], -1)
        rem = jnp.cumprod(one_minus, -1)
        return (jnp.log(z) + jnp.log1p(-z) + jnp.log(rem)).sum(-1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ReshapeTransform(Transform):
    _type = Type.BIJECTION

    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(in_event_shape)
        self.out_event_shape = tuple(out_event_shape)
        if int(np.prod(self.in_event_shape)) != int(np.prod(self.out_event_shape)):
            raise ValueError("element count mismatch")
        self._domain_event_rank = len(self.in_event_shape)
        self._codomain_event_rank = len(self.out_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return x.reshape(batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return y.reshape(batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)


class IndependentTransform(Transform):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._rank = int(reinterpreted_batch_rank)
        self._domain_event_rank = base._domain_event_rank + self._rank
        self._codomain_event_rank = base._codomain_event_rank + self._rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ld = self.base._forward_log_det_jacobian(x)
        return ld.sum(tuple(range(-self._rank, 0))) if self._rank else ld


class ChainTransform(Transform):
    def __init__(self, transforms):
        self.transforms = list(transforms)
        self._domain_event_rank = max(
            [t._domain_event_rank for t in self.transforms] + [0])
        self._codomain_event_rank = max(
            [t._codomain_event_rank for t in self.transforms] + [0])

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        # each step's jacobian is summed down to this chain's domain event
        # rank so mixed-rank chains (elementwise + simplex ops) add scalars
        # to scalars instead of broadcasting
        ld = None
        for t in self.transforms:
            step = t._forward_log_det_jacobian(x)
            extra = self._domain_event_rank - t._domain_event_rank
            if extra > 0:
                step = step.sum(tuple(range(-extra, 0)))
            ld = step if ld is None else ld + step
            x = t._forward(x)
        return ld

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return shape

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return shape


class StackTransform(Transform):
    """Apply a different transform along `axis` slices (reference StackTransform)."""

    def __init__(self, transforms, axis=0):
        self.transforms = list(transforms)
        self.axis = axis

    def _map(self, fn_name, x):
        parts = []
        n = len(self.transforms)
        for i, t in enumerate(self.transforms):
            sl = jnp.take(x, i, axis=self.axis)
            parts.append(getattr(t, fn_name)(sl))
        return jnp.stack(parts, axis=self.axis)

    def _forward(self, x):
        return self._map("_forward", x)

    def _inverse(self, y):
        return self._map("_inverse", y)

    def _forward_log_det_jacobian(self, x):
        return self._map("_forward_log_det_jacobian", x)
