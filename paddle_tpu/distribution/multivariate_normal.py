"""MultivariateNormal (reference: distribution/multivariate_normal.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _fv, _key, _shape, _wrap


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None, name=None):
        self.loc = _fv(loc)
        given = sum(x is not None for x in
                    (covariance_matrix, precision_matrix, scale_tril))
        if given != 1:
            raise ValueError("pass exactly one of covariance_matrix/"
                             "precision_matrix/scale_tril")
        if scale_tril is not None:
            self._tril = _fv(scale_tril)
        elif covariance_matrix is not None:
            self._tril = jnp.linalg.cholesky(_fv(covariance_matrix))
        else:
            prec = _fv(precision_matrix)
            self._tril = jnp.linalg.cholesky(jnp.linalg.inv(prec))
        super().__init__(jnp.broadcast_shapes(
            self.loc.shape[:-1], self._tril.shape[:-2]), self.loc.shape[-1:])

    @property
    def scale_tril(self):
        return _wrap(self._tril)

    @property
    def covariance_matrix(self):
        return _wrap(self._tril @ jnp.swapaxes(self._tril, -1, -2))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc,
                                      self.batch_shape + self.event_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(
            jnp.sum(self._tril ** 2, -1),
            self.batch_shape + self.event_shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(_key(), shp, self.loc.dtype)
        return _wrap(self.loc + jnp.einsum("...ij,...j->...i", self._tril, eps))

    def log_prob(self, value):
        v = _fv(value)
        d = v - self.loc
        # solve L y = d
        y = jax.scipy.linalg.solve_triangular(self._tril, d[..., None],
                                              lower=True)[..., 0]
        k = self.loc.shape[-1]
        half_logdet = jnp.log(jnp.diagonal(self._tril, axis1=-2,
                                           axis2=-1)).sum(-1)
        return _wrap(-0.5 * (y ** 2).sum(-1) - half_logdet
                     - 0.5 * k * math.log(2 * math.pi))

    def entropy(self):
        k = self.loc.shape[-1]
        half_logdet = jnp.log(jnp.diagonal(self._tril, axis1=-2,
                                           axis2=-1)).sum(-1)
        e = 0.5 * k * (1 + math.log(2 * math.pi)) + half_logdet
        return _wrap(jnp.broadcast_to(e, self.batch_shape))

    def kl_divergence(self, other):
        if isinstance(other, MultivariateNormal):
            k = self.loc.shape[-1]
            L1, L2 = self._tril, other._tril
            M = jax.scipy.linalg.solve_triangular(L2, L1, lower=True)
            tr = (M ** 2).sum((-2, -1))
            d = other.loc - self.loc
            y = jax.scipy.linalg.solve_triangular(L2, d[..., None],
                                                  lower=True)[..., 0]
            maha = (y ** 2).sum(-1)
            ld1 = jnp.log(jnp.diagonal(L1, axis1=-2, axis2=-1)).sum(-1)
            ld2 = jnp.log(jnp.diagonal(L2, axis1=-2, axis2=-1)).sum(-1)
            return _wrap(0.5 * (tr + maha - k) + ld2 - ld1)
        return super().kl_divergence(other)
