"""Beta (reference: distribution/beta.py) — via two Gammas (implicit reparam)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import ExponentialFamily, _fv, _key, _shape, _wrap


def _betaln(a, b):
    return jax.lax.lgamma(a) + jax.lax.lgamma(b) - jax.lax.lgamma(a + b)


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta, name=None):
        self.alpha = _fv(alpha)
        self.beta = _fv(beta)
        super().__init__(jnp.broadcast_shapes(self.alpha.shape, self.beta.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.alpha / (self.alpha + self.beta),
                                      self.batch_shape))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return _wrap(jnp.broadcast_to(
            self.alpha * self.beta / (s ** 2 * (s + 1)), self.batch_shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        ga = jax.random.gamma(_key(), jnp.broadcast_to(self.alpha, shp))
        gb = jax.random.gamma(_key(), jnp.broadcast_to(self.beta, shp))
        return _wrap(ga / (ga + gb))

    def log_prob(self, value):
        v = _fv(value)
        return _wrap((self.alpha - 1) * jnp.log(v)
                     + (self.beta - 1) * jnp.log1p(-v)
                     - _betaln(self.alpha, self.beta))

    def entropy(self):
        a = jnp.broadcast_to(self.alpha, self.batch_shape)
        b = jnp.broadcast_to(self.beta, self.batch_shape)
        dg = jax.lax.digamma
        return _wrap(_betaln(a, b) - (a - 1) * dg(a) - (b - 1) * dg(b)
                     + (a + b - 2) * dg(a + b))

    def kl_divergence(self, other):
        if isinstance(other, Beta):
            dg = jax.lax.digamma
            a1, b1, a2, b2 = self.alpha, self.beta, other.alpha, other.beta
            s1 = a1 + b1
            return _wrap(_betaln(a2, b2) - _betaln(a1, b1)
                         + (a1 - a2) * dg(a1) + (b1 - b2) * dg(b1)
                         + (a2 - a1 + b2 - b1) * dg(s1))
        return super().kl_divergence(other)
