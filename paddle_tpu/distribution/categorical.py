"""Categorical / Multinomial (reference: distribution/categorical.py,
multinomial.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _fv, _key, _shape, _v, _wrap


class Categorical(Distribution):
    """Reference semantics (distribution/categorical.py): `logits` are
    UNNORMALIZED PROBABILITIES for probs/log_prob, which divide by the sum
    (:122 `self.logits / dist_sum`) — while sample() draws from
    softmax(logits) (Distribution._logits_to_probs, distribution.py:255-265,
    via multinomial) and entropy/kl_divergence also use the softmax
    (:226-269).  Both conventions are reproduced; for `probs=` input the two
    families coincide (stored logits are log-probs)."""

    def __init__(self, logits=None, probs=None, name=None):
        if (logits is None) == (probs is None):
            raise ValueError("pass exactly one of logits/probs")
        if probs is not None:
            # probs= extension: store log-probs as logits so BOTH families
            # (sum-normalize and softmax) recover exactly the given p
            p = _fv(probs)
            p = p / p.sum(-1, keepdims=True)
            self.logits = jnp.log(jnp.clip(p, 1e-37, None))
            self._sum_probs = p
        else:
            self.logits = _fv(logits)
            # sum-normalized (probs/log_prob family; sampling uses softmax)
            self._sum_probs = self.logits / self.logits.sum(-1, keepdims=True)
        self._logp = jnp.log(jnp.clip(self._sum_probs, 1e-37, None))
        # softmax-normalized (entropy/kl family)
        self._softmax_probs = jax.nn.softmax(self.logits, -1)
        super().__init__(self.logits.shape[:-1])

    @property
    def probs(self):
        return _wrap(self._sum_probs)

    @property
    def num_events(self):
        return self.logits.shape[-1]

    @property
    def mean(self):
        # moments follow the SAMPLING distribution (softmax of logits), so
        # empirical sample statistics match mean/variance
        return _wrap(jnp.sum(self._softmax_probs * jnp.arange(
            self.num_events, dtype=self._softmax_probs.dtype), -1))

    @property
    def variance(self):
        k = jnp.arange(self.num_events, dtype=self._softmax_probs.dtype)
        m = jnp.sum(self._softmax_probs * k, -1, keepdims=True)
        return _wrap(jnp.sum(self._softmax_probs * (k - m) ** 2, -1))

    def sample(self, shape=()):
        # reference Categorical.sample: multinomial over softmax(logits)
        # (_logits_to_probs) — NOT the sum-normalized probs/log_prob family
        shp = _shape(shape)
        out = jax.random.categorical(
            _key(), self.logits, axis=-1, shape=shp + self.batch_shape)
        return _wrap(out.astype(jnp.int64))

    def log_prob(self, value):
        v = _v(value).astype(jnp.int32)
        return _wrap(jnp.take_along_axis(
            jnp.broadcast_to(self._logp, v.shape + (self.num_events,)),
            v[..., None], axis=-1)[..., 0])

    def probabilities(self, value=None):
        return self.probs

    def entropy(self):
        logp = jax.nn.log_softmax(self.logits, -1)
        return _wrap(-jnp.sum(self._softmax_probs * logp, -1))

    def kl_divergence(self, other):
        if isinstance(other, Categorical):
            lp = jax.nn.log_softmax(self.logits, -1)
            lq = jax.nn.log_softmax(other.logits, -1)
            return _wrap(jnp.sum(self._softmax_probs * (lp - lq), -1))
        return super().kl_divergence(other)


class Multinomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        if int(total_count) < 1:
            raise ValueError("total_count should be >= 1")
        self.total_count = int(total_count)
        p = _fv(probs)
        self._probs = p / p.sum(-1, keepdims=True)
        super().__init__(self._probs.shape[:-1], self._probs.shape[-1:])

    @property
    def probs(self):
        return _wrap(self._probs)

    @property
    def mean(self):
        return _wrap(self.total_count * self._probs)

    @property
    def variance(self):
        return _wrap(self.total_count * self._probs * (1 - self._probs))

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        logits = jnp.log(jnp.clip(self._probs, 1e-12, None))
        draws = jax.random.categorical(
            _key(), logits, axis=-1, shape=(self.total_count,) + shp)
        K = self._probs.shape[-1]
        counts = jax.nn.one_hot(draws, K, dtype=jnp.float32).sum(0)
        return _wrap(counts)

    def log_prob(self, value):
        v = _fv(value)
        logp = jnp.log(jnp.clip(self._probs, 1e-12, None))
        coeff = (jax.lax.lgamma(jnp.asarray(self.total_count + 1.0))
                 - jax.lax.lgamma(v + 1.0).sum(-1))
        return _wrap(coeff + (v * logp).sum(-1))

    def entropy(self):
        """Exact entropy via the Binomial-marginal decomposition the
        reference uses (multinomial.py:166): H = n*H(p) - log(n!) +
        sum_i E[log X_i!], X_i ~ Binomial(n, p_i), the expectation an
        exact sum over the support 1..n."""
        import jax.lax as lax
        p = self._probs
        n = float(self.total_count)
        cat_ent = -(jnp.where(p > 0, p * jnp.log(p), 0.0)).sum(-1)
        s = jnp.arange(1, self.total_count + 1, dtype=p.dtype)
        s = s.reshape((-1,) + (1,) * p.ndim)               # (n, ..1.., 1)
        logp = jnp.where(p > 0, jnp.log(p), -jnp.inf)
        log1mp = jnp.log1p(-jnp.minimum(p, 1 - 1e-7))
        log_pmf = (lax.lgamma(jnp.asarray(n + 1.0)) - lax.lgamma(s + 1.0)
                   - lax.lgamma(n - s + 1.0) + s * logp + (n - s) * log1mp)
        corr = (jnp.exp(log_pmf) * lax.lgamma(s + 1.0)).sum((0, -1))
        return _wrap(n * cat_ent - lax.lgamma(jnp.asarray(n + 1.0)) + corr)
