"""Gamma / Chi2 / Exponential (reference: distribution/gamma.py, chi2.py,
exponential.py).  jax.random.gamma is pathwise-differentiable (implicit
reparameterization), so rsample is a true rsample."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import ExponentialFamily, _fv, _key, _shape, _v, _wrap


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate, name=None):
        self.concentration = _fv(concentration)
        self.rate = _fv(rate)
        super().__init__(jnp.broadcast_shapes(self.concentration.shape,
                                              self.rate.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.concentration / self.rate,
                                      self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(self.concentration / self.rate ** 2,
                                      self.batch_shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        g = jax.random.gamma(_key(), jnp.broadcast_to(self.concentration, shp))
        return _wrap(g / self.rate)

    def log_prob(self, value):
        v = _fv(value)
        a, b = self.concentration, self.rate
        return _wrap(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                     - jax.lax.lgamma(a))

    def entropy(self):
        a, b = jnp.broadcast_to(self.concentration, self.batch_shape), \
            jnp.broadcast_to(self.rate, self.batch_shape)
        return _wrap(a - jnp.log(b) + jax.lax.lgamma(a)
                     + (1 - a) * jax.lax.digamma(a))

    def kl_divergence(self, other):
        if isinstance(other, Gamma):
            a1, b1 = self.concentration, self.rate
            a2, b2 = other.concentration, other.rate
            return _wrap((a1 - a2) * jax.lax.digamma(a1)
                         - jax.lax.lgamma(a1) + jax.lax.lgamma(a2)
                         + a2 * (jnp.log(b1) - jnp.log(b2))
                         + a1 * (b2 - b1) / b1)
        return super().kl_divergence(other)


class Chi2(Gamma):
    def __init__(self, df, name=None):
        df = _fv(df)
        self.df = df
        super().__init__(df / 2, jnp.full_like(df, 0.5))


class Exponential(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _fv(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return _wrap(1 / self.rate)

    @property
    def variance(self):
        return _wrap(1 / self.rate ** 2)

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        u = jax.random.uniform(_key(), shp, self.rate.dtype, 1e-9, 1.0)
        return _wrap(-jnp.log(u) / self.rate)

    def log_prob(self, value):
        v = _fv(value)
        return _wrap(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return _wrap(1 - jnp.log(self.rate))

    def cdf(self, value):
        return _wrap(-jnp.expm1(-self.rate * _fv(value)))

    def kl_divergence(self, other):
        if isinstance(other, Exponential):
            r = self.rate / other.rate
            return _wrap(jnp.log(r) + other.rate / self.rate - 1)
        return super().kl_divergence(other)
