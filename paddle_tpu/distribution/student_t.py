"""StudentT (reference: distribution/student_t.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _fv, _key, _shape, _wrap


class StudentT(Distribution):
    def __init__(self, df, loc=0.0, scale=1.0, name=None):
        self.df = _fv(df)
        self.loc = _fv(loc)
        self.scale = _fv(scale)
        super().__init__(jnp.broadcast_shapes(
            self.df.shape, self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.where(self.df > 1,
                               jnp.broadcast_to(self.loc, self.batch_shape),
                               jnp.nan))

    @property
    def variance(self):
        v = jnp.where(self.df > 2,
                      self.scale ** 2 * self.df / (self.df - 2), jnp.inf)
        return _wrap(jnp.broadcast_to(jnp.where(self.df > 1, v, jnp.nan),
                                      self.batch_shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        z = jax.random.normal(_key(), shp, self.loc.dtype)
        g = jax.random.gamma(_key(), jnp.broadcast_to(self.df / 2, shp)) * 2
        return _wrap(self.loc + self.scale * z * jnp.sqrt(self.df / g))

    def log_prob(self, value):
        v = _fv(value)
        d = self.df
        z = (v - self.loc) / self.scale
        lg = jax.lax.lgamma
        return _wrap(lg((d + 1) / 2) - lg(d / 2)
                     - 0.5 * jnp.log(d * math.pi) - jnp.log(self.scale)
                     - (d + 1) / 2 * jnp.log1p(z ** 2 / d))

    def entropy(self):
        d = jnp.broadcast_to(self.df, self.batch_shape)
        s = jnp.broadcast_to(self.scale, self.batch_shape)
        lg, dg = jax.lax.lgamma, jax.lax.digamma
        return _wrap((d + 1) / 2 * (dg((d + 1) / 2) - dg(d / 2))
                     + 0.5 * jnp.log(d) + _lbeta(d / 2, 0.5) + jnp.log(s))


def _lbeta(a, b):
    return (jax.lax.lgamma(a) + jax.lax.lgamma(b) - jax.lax.lgamma(a + b))
