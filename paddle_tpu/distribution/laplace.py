"""Laplace (reference: distribution/laplace.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _fv, _key, _shape, _wrap


class Laplace(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _fv(loc)
        self.scale = _fv(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(2 * self.scale ** 2, self.batch_shape))

    @property
    def stddev(self):
        return _wrap(jnp.broadcast_to(math.sqrt(2) * self.scale,
                                      self.batch_shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        u = jax.random.uniform(_key(), shp, self.loc.dtype, -0.5 + 1e-7,
                               0.5 - 1e-7)
        return _wrap(self.loc - self.scale * jnp.sign(u)
                     * jnp.log1p(-2 * jnp.abs(u)))

    def log_prob(self, value):
        v = _fv(value)
        return _wrap(-jnp.abs(v - self.loc) / self.scale
                     - jnp.log(2 * self.scale))

    def entropy(self):
        return _wrap(jnp.broadcast_to(1 + jnp.log(2 * self.scale),
                                      self.batch_shape))

    def cdf(self, value):
        v = _fv(value)
        z = (v - self.loc) / self.scale
        return _wrap(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, value):
        v = _fv(value) - 0.5
        return _wrap(self.loc - self.scale * jnp.sign(v)
                     * jnp.log1p(-2 * jnp.abs(v)))

    def kl_divergence(self, other):
        if isinstance(other, Laplace):
            # log(b2/b1) + |u1-u2|/b2 + (b1/b2) e^{-|u1-u2|/b1} - 1
            d = jnp.abs(self.loc - other.loc)
            return _wrap(jnp.log(other.scale / self.scale)
                         + d / other.scale
                         + (self.scale / other.scale) * jnp.exp(-d / self.scale)
                         - 1)
        return super().kl_divergence(other)
