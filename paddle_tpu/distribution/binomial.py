"""Binomial (reference: distribution/binomial.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _fv, _key, _shape, _wrap


class Binomial(Distribution):
    def __init__(self, total_count, probs, name=None):
        self.total_count = jnp.asarray(total_count)
        self.probs = _fv(probs)
        super().__init__(jnp.broadcast_shapes(
            jnp.shape(self.total_count), self.probs.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.total_count * self.probs,
                                      self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(
            self.total_count * self.probs * (1 - self.probs),
            self.batch_shape))

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        n = int(jnp.max(self.total_count))
        u = jax.random.uniform(_key(), (n,) + shp, self.probs.dtype)
        k = jnp.arange(n, dtype=self.probs.dtype).reshape(
            (n,) + (1,) * len(shp))
        draws = ((u < self.probs) & (k < self.total_count)).sum(0)
        return _wrap(draws.astype(self.probs.dtype))

    rsample = sample

    def log_prob(self, value):
        v = _fv(value)
        n = jnp.broadcast_to(self.total_count, self.batch_shape).astype(v.dtype)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        logc = (jax.lax.lgamma(n + 1) - jax.lax.lgamma(v + 1)
                - jax.lax.lgamma(n - v + 1))
        return _wrap(logc + v * jnp.log(p) + (n - v) * jnp.log1p(-p))

    def entropy(self):
        n = int(jnp.max(self.total_count))
        k = jnp.arange(n + 1, dtype=self.probs.dtype)
        kshape = k.reshape((n + 1,) + (1,) * len(self.batch_shape))
        logp = jnp.asarray(self.log_prob(
            jnp.broadcast_to(kshape, (n + 1,) + self.batch_shape))._data)
        valid = kshape <= self.total_count
        p = jnp.where(valid, jnp.exp(logp), 0.0)
        return _wrap(-(p * jnp.where(valid, logp, 0.0)).sum(0))

    def kl_divergence(self, other):
        if isinstance(other, Binomial):
            if not bool(jnp.all(self.total_count == other.total_count)):
                raise NotImplementedError(
                    "KL between Binomials with different total_count has no "
                    "closed form (supports differ)")
            p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
            q = jnp.clip(other.probs, 1e-7, 1 - 1e-7)
            n = self.total_count
            return _wrap(n * (p * jnp.log(p / q)
                              + (1 - p) * jnp.log((1 - p) / (1 - q))))
        return super().kl_divergence(other)
