"""Poisson (reference: distribution/poisson.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import ExponentialFamily, _fv, _key, _shape, _v, _wrap


class Poisson(ExponentialFamily):
    def __init__(self, rate, name=None):
        self.rate = _fv(rate)
        super().__init__(self.rate.shape)

    @property
    def mean(self):
        return _wrap(self.rate)

    @property
    def variance(self):
        return _wrap(self.rate)

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return _wrap(jax.random.poisson(_key(), self.rate, shp)
                     .astype(self.rate.dtype))

    rsample = sample

    def log_prob(self, value):
        v = _fv(value)
        return _wrap(v * jnp.log(self.rate) - self.rate
                     - jax.lax.lgamma(v + 1))

    def entropy(self):
        # series approximation like the reference (exact for moderate rate via
        # summation over support up to a cutoff)
        kmax = 64
        k = jnp.arange(kmax, dtype=self.rate.dtype)
        r = self.rate[..., None]
        logp = k * jnp.log(r) - r - jax.lax.lgamma(k + 1)
        p = jnp.exp(logp)
        return _wrap(-(p * logp).sum(-1))

    def kl_divergence(self, other):
        if isinstance(other, Poisson):
            r1, r2 = self.rate, other.rate
            return _wrap(r1 * jnp.log(r1 / r2) - r1 + r2)
        return super().kl_divergence(other)
