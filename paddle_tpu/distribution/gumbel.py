"""Gumbel (reference: distribution/gumbel.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _fv, _key, _shape, _wrap

_EULER = 0.57721566490153286060


class Gumbel(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _fv(loc)
        self.scale = _fv(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc + self.scale * _EULER,
                                      self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(
            (math.pi ** 2 / 6) * self.scale ** 2, self.batch_shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        g = jax.random.gumbel(_key(), shp, self.loc.dtype)
        return _wrap(self.loc + self.scale * g)

    def log_prob(self, value):
        v = _fv(value)
        z = (v - self.loc) / self.scale
        return _wrap(-z - jnp.exp(-z) - jnp.log(self.scale))

    def entropy(self):
        return _wrap(jnp.broadcast_to(jnp.log(self.scale) + 1 + _EULER,
                                      self.batch_shape))

    def cdf(self, value):
        z = (_fv(value) - self.loc) / self.scale
        return _wrap(jnp.exp(-jnp.exp(-z)))
