"""TransformedDistribution (reference: distribution/transformed_distribution.py)."""

from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution, _fv, _v, _wrap
from .transform import ChainTransform, Transform


class TransformedDistribution(Distribution):
    def __init__(self, base, transforms):
        if isinstance(transforms, Transform):
            transforms = [transforms]
        self.base = base
        self.transforms = list(transforms)
        self._chain = ChainTransform(self.transforms)
        base_event = base.batch_shape + base.event_shape
        out_shape = self._chain.forward_shape(base_event)
        # event rank grows to cover the chain's codomain event rank
        ev = max(len(base.event_shape), self._chain._codomain_event_rank)
        super().__init__(out_shape[:len(out_shape) - ev],
                         out_shape[len(out_shape) - ev:])

    def sample(self, shape=()):
        x = _v(self.base.sample(shape))
        return _wrap(self._chain._forward(x))

    def rsample(self, shape=()):
        x = _v(self.base.rsample(shape))
        return _wrap(self._chain._forward(x))

    def log_prob(self, value):
        # reverse sweep with event-rank bookkeeping (the standard
        # change-of-variables algorithm: each jacobian is summed down to the
        # event rank it acts within, then the base log_prob is summed over any
        # dims the transforms reinterpreted as event dims)
        def sum_rightmost(a, n):
            return a.sum(tuple(range(-n, 0))) if n > 0 else a

        y = _fv(value)
        event_rank = len(self.event_shape)
        lp = 0.0
        for t in reversed(self.transforms):
            x = t._inverse(y)
            event_rank += t._domain_event_rank - t._codomain_event_rank
            ld = t._forward_log_det_jacobian(x)
            lp = lp - sum_rightmost(ld, event_rank - t._domain_event_rank)
            y = x
        base_lp = _v(self.base.log_prob(y))
        lp = lp + sum_rightmost(base_lp,
                                event_rank - len(self.base.event_shape))
        return _wrap(lp)
