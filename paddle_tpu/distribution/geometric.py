"""Geometric (reference: distribution/geometric.py — support {0, 1, 2, ...},
number of failures before first success)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import Distribution, _fv, _key, _shape, _wrap


class Geometric(Distribution):
    def __init__(self, probs, name=None):
        self.probs = _fv(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return _wrap((1 - self.probs) / self.probs)

    @property
    def variance(self):
        return _wrap((1 - self.probs) / self.probs ** 2)

    @property
    def stddev(self):
        return _wrap(jnp.sqrt((1 - self.probs)) / self.probs)

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        u = jax.random.uniform(_key(), shp, self.probs.dtype, 1e-9, 1.0)
        return _wrap(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs)))

    rsample = sample  # discrete: no pathwise gradient (reference also samples)

    def log_prob(self, value):
        v = _fv(value)
        return _wrap(v * jnp.log1p(-self.probs) + jnp.log(self.probs))

    def pmf(self, k):
        return _wrap(jnp.exp(self.log_prob(k)._data))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        q = 1 - p
        return _wrap(-(q * jnp.log(q) + p * jnp.log(p)) / p)

    def cdf(self, value):
        v = _fv(value)
        return _wrap(1 - jnp.power(1 - self.probs, jnp.floor(v) + 1))

    def kl_divergence(self, other):
        if isinstance(other, Geometric):
            p, q = self.probs, other.probs
            return _wrap(jnp.log(p / q)
                         + (1 - p) / p * jnp.log((1 - p) / (1 - q)))
        return super().kl_divergence(other)
