"""Normal / LogNormal (reference: distribution/normal.py, lognormal.py)."""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .distribution import Distribution, _fv, _key, _shape, _v, _wrap


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _fv(loc)
        self.scale = _fv(scale)
        super().__init__(jnp.broadcast_shapes(self.loc.shape, self.scale.shape))

    @property
    def mean(self):
        return _wrap(jnp.broadcast_to(self.loc, self.batch_shape))

    @property
    def variance(self):
        return _wrap(jnp.broadcast_to(self.scale ** 2, self.batch_shape))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        eps = jax.random.normal(_key(), shp, self.loc.dtype)
        return _wrap(self.loc + self.scale * eps)

    def log_prob(self, value):
        v = _fv(value)
        var = self.scale ** 2
        return _wrap(-((v - self.loc) ** 2) / (2 * var)
                     - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def entropy(self):
        e = 0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(self.scale)
        return _wrap(jnp.broadcast_to(e, self.batch_shape))

    def cdf(self, value):
        v = _fv(value)
        return _wrap(0.5 * (1 + jax.lax.erf(
            (v - self.loc) / (self.scale * math.sqrt(2)))))

    def icdf(self, value):
        v = _fv(value)
        return _wrap(self.loc + self.scale * math.sqrt(2)
                     * jax.lax.erf_inv(2 * v - 1))

    def kl_divergence(self, other):
        if isinstance(other, Normal):
            var_ratio = (self.scale / other.scale) ** 2
            t1 = ((self.loc - other.loc) / other.scale) ** 2
            return _wrap(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))
        return super().kl_divergence(other)


class LogNormal(Distribution):
    """exp(Normal(loc, scale)) — reference lognormal.py."""

    def __init__(self, loc, scale, name=None):
        self.base = Normal(loc, scale)
        self.loc = self.base.loc
        self.scale = self.base.scale
        super().__init__(self.base.batch_shape)

    @property
    def mean(self):
        return _wrap(jnp.exp(self.loc + self.scale ** 2 / 2))

    @property
    def variance(self):
        s2 = self.scale ** 2
        return _wrap((jnp.exp(s2) - 1) * jnp.exp(2 * self.loc + s2))

    def rsample(self, shape=()):
        return _wrap(jnp.exp(_v(self.base.rsample(shape))))

    def log_prob(self, value):
        v = _fv(value)
        return _wrap(_v(self.base.log_prob(jnp.log(v))) - jnp.log(v))

    def entropy(self):
        return _wrap(_v(self.base.entropy()) + self.loc)

    def probs(self, value):
        return _wrap(jnp.exp(_v(self.log_prob(value))))

    def kl_divergence(self, other):
        if isinstance(other, LogNormal):
            # KL is invariant under the shared exp transform -> normal KL
            return self.base.kl_divergence(other.base)
        return super().kl_divergence(other)
