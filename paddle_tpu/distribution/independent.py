"""Independent (reference: distribution/independent.py) — reinterprets batch
dims as event dims (log_prob sums over them)."""

from __future__ import annotations

import jax.numpy as jnp

from .distribution import Distribution, _v, _wrap


class Independent(Distribution):
    def __init__(self, base, reinterpreted_batch_rank):
        self.base = base
        self._rank = int(reinterpreted_batch_rank)
        if self._rank > len(base.batch_shape):
            raise ValueError("reinterpreted rank exceeds base batch rank")
        shape = base.batch_shape + base.event_shape
        split = len(base.batch_shape) - self._rank
        super().__init__(shape[:split],
                         shape[split:])

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def log_prob(self, value):
        lp = _v(self.base.log_prob(value))
        if self._rank:
            lp = lp.sum(tuple(range(-self._rank, 0)))
        return _wrap(lp)

    def entropy(self):
        e = _v(self.base.entropy())
        if self._rank:
            e = e.sum(tuple(range(-self._rank, 0)))
        return _wrap(e)
