"""kl_divergence / register_kl (reference: distribution/kl.py — dispatch table
with MRO-aware lookup)."""

from __future__ import annotations

from typing import Callable, Dict, Tuple, Type

from .distribution import Distribution

_KL_REGISTRY: Dict[Tuple[type, type], Callable] = {}


def register_kl(p_cls, q_cls):
    """Decorator: register a KL implementation for (p_cls, q_cls)."""

    def deco(fn):
        _KL_REGISTRY[(p_cls, q_cls)] = fn
        return fn

    return deco


def _lookup(p_cls, q_cls):
    best = None
    best_score = None
    for (pc, qc), fn in _KL_REGISTRY.items():
        if issubclass(p_cls, pc) and issubclass(q_cls, qc):
            score = (p_cls.__mro__.index(pc), q_cls.__mro__.index(qc))
            if best_score is None or score < best_score:
                best, best_score = fn, score
    return best


def kl_divergence(p: Distribution, q: Distribution):
    """KL(p || q).  Tries the registry, then p's own kl_divergence override
    (whose super() chain ends in Distribution raising NotImplementedError)."""
    fn = _lookup(type(p), type(q))
    if fn is not None:
        return fn(p, q)
    return type(p).kl_divergence(p, q)
