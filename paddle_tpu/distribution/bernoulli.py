"""Bernoulli / ContinuousBernoulli (reference: distribution/bernoulli.py,
continuous_bernoulli.py)."""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .distribution import ExponentialFamily, Distribution, _fv, _key, _shape, _wrap


class Bernoulli(ExponentialFamily):
    def __init__(self, probs, name=None):
        self.probs = _fv(probs)
        super().__init__(self.probs.shape)

    @property
    def mean(self):
        return _wrap(self.probs)

    @property
    def variance(self):
        return _wrap(self.probs * (1 - self.probs))

    def sample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        return _wrap(jax.random.bernoulli(
            _key(), self.probs, shp).astype(self.probs.dtype))

    def rsample(self, shape=(), temperature=1.0):
        """Gumbel-softmax style relaxed sample (reference rsample w/ temp)."""
        shp = _shape(shape) + self.batch_shape
        u = jax.random.uniform(_key(), shp, self.probs.dtype, 1e-6, 1 - 1e-6)
        logits = jnp.log(self.probs) - jnp.log1p(-self.probs)
        z = (logits + jnp.log(u) - jnp.log1p(-u)) / temperature
        return _wrap(jax.nn.sigmoid(z))

    def log_prob(self, value):
        v = _fv(value)
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return _wrap(v * jnp.log(p) + (1 - v) * jnp.log1p(-p))

    def entropy(self):
        p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
        return _wrap(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))

    def kl_divergence(self, other):
        if isinstance(other, Bernoulli):
            p = jnp.clip(self.probs, 1e-7, 1 - 1e-7)
            q = jnp.clip(other.probs, 1e-7, 1 - 1e-7)
            return _wrap(p * (jnp.log(p) - jnp.log(q))
                         + (1 - p) * (jnp.log1p(-p) - jnp.log1p(-q)))
        return super().kl_divergence(other)


class ContinuousBernoulli(Distribution):
    """Reference continuous_bernoulli.py — CB(lambda) on [0, 1]."""

    def __init__(self, probs, lims=(0.499, 0.501), name=None):
        self.probs = _fv(probs)
        self._lims = lims
        super().__init__(self.probs.shape)

    def _outside(self):
        return (self.probs < self._lims[0]) | (self.probs > self._lims[1])

    def _log_norm(self):
        """log C(lambda) with the Taylor patch near 0.5 (reference _cont_bern_log_norm)."""
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        safe = jnp.where(self._outside(), p, 0.25)  # keep grads finite at 0.5
        x = 1 - 2 * safe
        ln = jnp.log(2 * jnp.arctanh(x) / x)
        taylor = jnp.log(2.0) + 4 / 3 * (p - 0.5) ** 2
        return jnp.where(self._outside(), ln, taylor)

    @property
    def mean(self):
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        m = p / (2 * p - 1) + 1 / (2 * jnp.arctanh(1 - 2 * p))
        taylor = 0.5 + (p - 0.5) / 3
        return _wrap(jnp.where(self._outside(), m, taylor))

    @property
    def variance(self):
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        v = p * (p - 1) / (1 - 2 * p) ** 2 + 1 / (2 * jnp.arctanh(1 - 2 * p)) ** 2
        taylor = 1 / 12 - (p - 0.5) ** 2 / 15
        return _wrap(jnp.where(self._outside(), v, taylor))

    def rsample(self, shape=()):
        shp = _shape(shape) + self.batch_shape
        u = jax.random.uniform(_key(), shp, self.probs.dtype, 1e-6, 1 - 1e-6)
        return self.icdf(u)

    def icdf(self, value):
        v = _fv(value)
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        # F^-1(u) = log(1 + u*(2p-1)/(1-p)) / (log p - log(1-p))
        out = (jnp.log1p(v * (2 * p - 1) / (1 - p)) /
               (jnp.log(p) - jnp.log1p(-p)))
        return _wrap(jnp.where(self._outside(), out, v))

    def log_prob(self, value):
        v = _fv(value)
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        return _wrap(v * jnp.log(p) + (1 - v) * jnp.log1p(-p) + self._log_norm())

    def entropy(self):
        # -E[log p(x)] = -(mean*log p + (1-mean)*log(1-p) + log C)
        p = jnp.clip(self.probs, 1e-6, 1 - 1e-6)
        m = jnp.asarray(self.mean._data)
        return _wrap(-(m * jnp.log(p) + (1 - m) * jnp.log1p(-p)
                       + self._log_norm()))
