"""SelectedRows + StringTensor — the non-dense tensor types of C1.

Reference: `paddle/phi/core/selected_rows.h:27` (rows/value/height — the
container embedding gradients use so only touched rows materialize) and
`paddle/phi/core/string_tensor.h:33` (host-side pstring tensor used by the
text/tokenizer path).  TPU-native mapping: SelectedRows keeps (rows, value)
as device arrays — scattering to dense (`to_dense`) is one `segment_sum`
and stays jittable; `merge()` compacts duplicate rows eagerly on host (its
output size is data-dependent, which XLA cannot express — jitted code
should use `to_dense` and keep accumulation in segment_sum form instead).
StringTensor is host-only by design (XLA has no string dtype; the reference
pins it to CPU for the same reason) and wraps a numpy unicode array.
"""

from __future__ import annotations

from typing import Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["SelectedRows", "StringTensor"]


class SelectedRows:
    """Sparse row-set: `value[i]` is the data for logical row `rows[i]` of a
    dense (height, *value.shape[1:]) tensor.  Duplicate row ids are allowed
    (gradient accumulation semantics) until `merge()`."""

    def __init__(self, rows, value, height: int):
        self.rows = jnp.asarray(rows, jnp.int64)
        self.value = value.value if isinstance(value, SelectedRows) else (
            value._data if hasattr(value, "_data") else jnp.asarray(value))
        if self.rows.shape[0] != self.value.shape[0]:
            raise ValueError(
                f"rows ({self.rows.shape[0]}) and value "
                f"({self.value.shape[0]}) leading dims must match")
        self.height = int(height)

    @property
    def shape(self):
        return (self.height,) + tuple(self.value.shape[1:])

    def set_height(self, height: int):
        self.height = int(height)

    def to_dense(self):
        """Scatter-add to the dense (height, ...) tensor (one segment_sum —
        jittable, duplicate rows accumulate like the reference's
        merge+scatter)."""
        from .tensor import Tensor

        dense = jax.ops.segment_sum(self.value,
                                    self.rows.astype(jnp.int32),
                                    num_segments=self.height)
        return Tensor(dense)

    def merge(self) -> "SelectedRows":
        """Combine duplicate rows (reference scatter::MergeAdd).  Eager and
        host-synced: the merged row count is data-dependent, so this cannot
        run under jit — use `to_dense` on traced paths."""
        uniq, inv = jnp.unique(self.rows, return_inverse=True,
                               size=self.rows.shape[0],
                               fill_value=jnp.int64(self.height))
        merged = jax.ops.segment_sum(self.value, inv.astype(jnp.int32),
                                     num_segments=uniq.shape[0])
        keep = np.asarray(uniq) < self.height  # drop the fill slots
        return SelectedRows(np.asarray(uniq)[keep],
                            np.asarray(merged)[keep], self.height)

    def __repr__(self):
        return (f"SelectedRows(height={self.height}, "
                f"rows={np.asarray(self.rows).tolist()}, "
                f"value shape={tuple(self.value.shape)})")


class StringTensor:
    """Host-side string tensor (reference string_tensor.h; dtype pstring).

    XLA has no string dtype — the reference likewise pins StringTensor to
    CPU and only the tokenizer ops consume it.  Backed by a numpy unicode
    array; supports the surface the reference's faster-tokenizer path
    needs: shape/indexing/equality, lower/upper, and numpy round-trip.
    """

    def __init__(self, data: Union[np.ndarray, Sequence]):
        self._data = np.asarray(data, dtype=np.str_)

    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def dtype(self) -> str:
        return "pstring"

    @property
    def place(self) -> str:
        return "cpu"  # always host, like the reference

    def numpy(self) -> np.ndarray:
        return self._data

    def __getitem__(self, idx):
        out = self._data[idx]
        return StringTensor(out) if isinstance(out, np.ndarray) else str(out)

    def __len__(self):
        return len(self._data)

    def __eq__(self, other):
        other = other._data if isinstance(other, StringTensor) else other
        return np.asarray(self._data == other)

    def __ne__(self, other):
        # explicit elementwise __ne__: Python's default (`not __eq__`) would
        # raise on the multi-element ndarray __eq__ returns
        other = other._data if isinstance(other, StringTensor) else other
        return np.asarray(self._data != other)

    # elementwise __eq__ (numpy semantics) => not hashable, like np.ndarray
    __hash__ = None

    def lower(self) -> "StringTensor":
        return StringTensor(np.char.lower(self._data))

    def upper(self) -> "StringTensor":
        return StringTensor(np.char.upper(self._data))

    def __repr__(self):
        return f"StringTensor(shape={self.shape}, {self._data!r})"
