"""Eager Tensor + tape autograd + single-point op dispatch.

Reference parity (design, not translation):
  - Tensor: paddle/phi/core/dense_tensor.h:41 DenseTensor + pybind eager tensor
    (paddle/fluid/pybind/eager_method.cc).  Here a Tensor is a thin mutable handle
    over an immutable `jax.Array` — rebinding `.data` replaces the value, so the
    "in-place" Paddle APIs become copy-on-write (safe under XLA's functional model).
  - Autograd engine: paddle/fluid/eager/grad_node_info.h:168 GradNodeBase +
    backward.cc:104 RunBackward.  TPU-native twist: instead of hand-written grad
    kernels per op, every dispatched op records the `jax.vjp` pullback closure at
    forward time (the closure holds the residuals — the analog of TensorWrapper,
    eager/tensor_wrapper.h).  `Tensor.backward()` runs reverse topological order
    over recorded nodes, exactly like RunBackward's in-degree queue.
  - Dispatch point: paddle/phi/api/lib (generated experimental::op) — AMP casts and
    stop_gradient logic hook in here (eager_amp_auto_cast.h analog).

Everything under `jax.jit` traces through this same machinery (the tape records
tracers), which is how dygraph-to-static works without an AST transpiler.
"""

from __future__ import annotations

import numbers
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import framework
from .framework import convert_dtype, to_jax_dtype

__all__ = ["Tensor", "Parameter", "to_tensor", "apply_op", "is_tensor"]


# ---------------------------------------------------------------------------
# Tape node
# ---------------------------------------------------------------------------


class TapeNode:
    """One recorded op: pullback closure + differentiable input tensors + outputs.

    Analog of an eager GradNode (grad_node_info.h:168); `pullback` plays the role
    of the generated grad-op call, `inputs` the Edges, `outputs` the forward outs
    whose cotangents seed this node.
    """

    __slots__ = ("pullback", "inputs", "outputs", "name",
                 "fn", "args", "kwargs", "args_data")

    def __init__(self, name, pullback, inputs, outputs,
                 fn=None, args=None, kwargs=None, args_data=None):
        self.name = name
        self.pullback = pullback
        self.inputs = inputs  # tuple[Tensor] — differentiable inputs, in order
        self.outputs = outputs  # tuple[Tensor]
        # forward replay record (create_graph / higher-order AD): the op fn,
        # its full arg list (Tensor refs for env lookup) and the raw values
        # captured at record time (mutation-safe)
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.args_data = args_data


def _float0_zero(raw):
    return np.zeros(raw.shape, dtype=jax.dtypes.float0)


def _is_float(raw) -> bool:
    return jnp.issubdtype(raw.dtype, jnp.floating) or jnp.issubdtype(
        raw.dtype, jnp.complexfloating
    )


# ---------------------------------------------------------------------------
# Tensor
# ---------------------------------------------------------------------------

_tensor_counter = [0]


class Tensor:
    """Paddle-style eager tensor over a `jax.Array`."""

    __slots__ = ("_data", "stop_gradient", "grad", "_node", "_out_idx", "name",
                 "persistable", "_hooks", "__weakref__")

    def __init__(self, data, stop_gradient=True, name=None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, (jax.Array, jax.core.Tracer)):
            data = jnp.asarray(data)
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad = None
        self._node = None
        self._out_idx = 0
        if name is None:
            _tensor_counter[0] += 1
            name = f"generated_tensor_{_tensor_counter[0]}"
        self.name = name
        self.persistable = False
        self._hooks = None

    # -- value plumbing ----------------------------------------------------
    @property
    def data(self):
        return self._data

    @data.setter
    def data(self, value):
        if isinstance(value, Tensor):
            value = value._data
        self._data = value

    def __jax_array__(self):
        return self._data

    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return framework._REVERSE_DTYPE_MAP[np.dtype(self._data.dtype)]

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        try:
            dev = list(self._data.devices())[0]
            return f"{dev.platform}:{dev.id}"
        except Exception:  # noqa: BLE001 — tracers have no device
            return "traced"

    @property
    def is_leaf(self):
        return self._node is None

    def numel(self):
        return self.size

    def element_size(self):
        return np.dtype(self._data.dtype).itemsize

    def numpy(self):
        return np.asarray(self._data)

    def item(self):
        return self._data.item()

    def tolist(self):
        return np.asarray(self._data).tolist()

    def set_value(self, value):
        """In-place value assignment (reference Tensor.set_value,
        python/paddle/tensor/manipulation.py): shape must match; dtype is
        preserved."""
        raw = value._data if isinstance(value, Tensor) else jnp.asarray(value)
        if tuple(raw.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: tensor is "
                f"{tuple(self._data.shape)}, value is {tuple(raw.shape)}")
        self._data = raw.astype(self._data.dtype)
        return self

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name + ".detach")
        return t

    def detach_(self):
        self._node = None
        self.stop_gradient = True
        return self

    def clone(self):
        return apply_op("clone", lambda x: x + 0, self)

    def pin_memory(self):
        return self

    def cpu(self):
        return Tensor(jax.device_put(self._data, jax.devices("cpu")[0]), self.stop_gradient)

    def to(self, *args, **kwargs):
        # Accepts dtype or device strings; device moves are no-ops intra-host.
        for a in list(args) + list(kwargs.values()):
            try:
                return self.astype(convert_dtype(a))
            except (ValueError, TypeError):
                continue
        return self

    def block_until_ready(self):
        if hasattr(self._data, "block_until_ready"):
            self._data.block_until_ready()
        return self

    # -- autograd ----------------------------------------------------------
    def backward(self, grad_tensor=None, retain_graph=False):
        backward(self, grad_tensor=grad_tensor, retain_graph=retain_graph)

    def clear_grad(self):
        self.grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self.grad is not None:
            self.grad = Tensor(jnp.zeros_like(self.grad._data))
        else:
            self.grad = None

    def register_hook(self, hook):
        # Gradient hooks fire when backward() deposits this tensor's grad.
        # Stored on the tensor itself so the hook's lifetime is the tensor's.
        if self._hooks is None:
            self._hooks = []
        self._hooks.append(hook)
        hooks = self._hooks
        idx = len(hooks) - 1

        class _Handle:
            def remove(self_h):
                hooks[idx] = None

        return _Handle()

    # -- python protocol ---------------------------------------------------
    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __repr__(self):
        try:
            val = np.asarray(self._data)
            body = np.array2string(val, precision=8, separator=", ", threshold=40)
        except Exception:  # noqa: BLE001
            body = f"<traced {self._data.aval if hasattr(self._data, 'aval') else self._data}>"
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype}, "
            f"stop_gradient={self.stop_gradient},\n       {body})"
        )

    def __bool__(self):
        if isinstance(self._data, jax.core.Tracer):
            raise TypeError(
                "A Tensor's truth value is data-dependent and this code is "
                "being traced for compilation (to_static/jit), where python "
                "`if`/`while` over tensor values cannot branch. Use "
                "paddle.static.nn.cond(pred, true_fn, false_fn) / "
                "paddle.static.nn.while_loop(cond_fn, body_fn, vars) "
                "(reference dy2static's ifelse/while transformers, "
                "python/paddle/jit/dy2static/program_translator.py:313), or "
                "mark the function @paddle.jit.not_to_static.")
        return bool(self._data)

    def __int__(self):
        if isinstance(self._data, jax.core.Tracer):
            raise TypeError(
                "int(Tensor) requires a concrete value but this code is "
                "being traced for compilation. Pass the value as a python "
                "int argument instead (to_static specializes on python "
                "scalars), or keep it a Tensor and use tensor ops.")
        return int(self._data)

    def __float__(self):
        if isinstance(self._data, jax.core.Tracer):
            raise TypeError(
                "float(Tensor) requires a concrete value but this code is "
                "being traced for compilation. Pass it as a python float "
                "argument (to_static specializes on python scalars), or "
                "keep it a Tensor and use tensor ops.")
        return float(self._data)

    def __index__(self):
        if isinstance(self._data, jax.core.Tracer):
            raise TypeError(
                "Using a Tensor as a python index requires a concrete value "
                "but this code is being traced for compilation. Use python "
                "ints for shapes/indices (to_static specializes on them) or "
                "tensor indexing ops (gather/index_select).")
        return int(self._data)

    def __hash__(self):
        return id(self)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __format__(self, spec):
        if self.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    def __getitem__(self, index):
        index = _unwrap_index(index)
        return apply_op("slice", lambda x: x[index], self)

    def __setitem__(self, index, value):
        index = _unwrap_index(index)
        if isinstance(value, Tensor):
            out = apply_op(
                "set_value", lambda x, v: x.at[index].set(v.astype(x.dtype)), self, value
            )
        else:
            out = apply_op("set_value", lambda x: x.at[index].set(value), self)
        # Copy-on-write in-place: rebind this handle to the new value/node.
        self._data = out._data
        self._node = out._node
        self._out_idx = out._out_idx
        if out._node is not None:
            # make the node's output list point at self so backward reaches us
            outs = list(out._node.outputs)
            outs[out._out_idx] = self
            out._node.outputs = tuple(outs)

    # Arithmetic operators are patched in by paddle_tpu.ops (single source for
    # op definitions — the "one YAML, many artifacts" idea from phi/api/yaml).


class Parameter(Tensor):
    """Trainable tensor (python/paddle/base/framework.py Parameter parity)."""

    __slots__ = ("trainable", "optimize_attr", "regularizer", "is_distributed",
                 "placements", "_sharding_axes", "need_clip")

    def __init__(self, data, name=None, trainable=True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.is_distributed = False
        self.persistable = True
        # GSPMD sharding annotation: PartitionSpec-like tuple over global mesh
        # axes, set by distributed parallel layers (see distributed/mp_layers).
        self.placements = None
        self._sharding_axes = None


def is_tensor(x) -> bool:
    return isinstance(x, Tensor)


def _unwrap_index(index):
    if isinstance(index, Tensor):
        return index._data
    if isinstance(index, tuple):
        return tuple(i._data if isinstance(i, Tensor) else i for i in index)
    if isinstance(index, list) and any(isinstance(i, Tensor) for i in index):
        return [i._data if isinstance(i, Tensor) else i for i in index]
    return index


# ---------------------------------------------------------------------------
# to_tensor
# ---------------------------------------------------------------------------


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity (python/paddle/tensor/creation.py)."""
    if isinstance(data, Tensor):
        raw = data._data
    elif isinstance(data, (jax.Array, jax.core.Tracer)):
        raw = data
    else:
        arr = np.asarray(data)
        if arr.dtype == np.float64 and dtype is None:
            # Paddle's to_tensor keeps python floats at default dtype.
            if isinstance(data, (numbers.Number, list, tuple)):
                arr = arr.astype(to_jax_dtype(framework.get_default_dtype()))
        if np.iscomplexobj(arr):
            # python complex scalars/lists follow the default dtype's
            # complex analog (paddle parity: float32 -> complex64)
            if (arr.dtype == np.complex128 and dtype is None
                    and isinstance(data, (numbers.Number, list, tuple))
                    and framework.get_default_dtype() == "float32"):
                arr = arr.astype(np.complex64)
            # complex-less backends (axon TPU plugin): host the array on CPU
            from .fft import _complex_ok
            if not _complex_ok():
                raw = jax.device_put(arr, jax.devices("cpu")[0])
                if dtype is not None:
                    raw = raw.astype(to_jax_dtype(convert_dtype(dtype)))
                return Tensor(raw, stop_gradient=stop_gradient)
        raw = jnp.asarray(arr)
    if dtype is not None:
        raw = raw.astype(to_jax_dtype(convert_dtype(dtype)))
    return Tensor(raw, stop_gradient=stop_gradient)


# ---------------------------------------------------------------------------
# The dispatch point
# ---------------------------------------------------------------------------

_AMP_WHITE = frozenset({
    "matmul", "mm", "bmm", "einsum", "linear", "conv2d", "conv1d", "conv3d",
    "conv2d_transpose", "flash_attention", "scaled_dot_product_attention",
})
_AMP_BLACK = frozenset({
    "softmax_with_cross_entropy", "cross_entropy", "exp", "log", "log_softmax",
    "mean", "sum", "norm", "softmax", "layer_norm", "rms_norm", "square", "pow",
    "l2_normalize", "log_sigmoid", "logsumexp",
})


def _amp_cast_args(name, tensors_raw):
    amp = framework.get_state().amp_state
    if amp is None or not amp.enable:
        return tensors_raw
    target = to_jax_dtype(amp.dtype)
    if amp.level == "O2":
        # pure low-precision except black list
        if name in _AMP_BLACK or name in amp.custom_black_list:
            cast = jnp.float32
        else:
            cast = target
    else:  # O1
        if name in amp.custom_black_list or name in _AMP_BLACK:
            cast = jnp.float32
        elif name in _AMP_WHITE or name in amp.custom_white_list:
            cast = target
        else:
            return tensors_raw
    out = []
    for r in tensors_raw:
        # complex inputs (fft/signal ops) never cast: bf16 has no complex analog
        if (r is not None and _is_float(r)
                and not jnp.issubdtype(r.dtype, jnp.complexfloating)
                and r.dtype != cast and r.dtype != jnp.float64):
            out.append(r.astype(cast))
        else:
            out.append(r)
    return out


def apply_op(name: str, fn: Callable, *args: Any, nondiff: Sequence[int] = (), **kwargs):
    """Execute `fn` over raw arrays, wrap outputs, record the tape node.

    `fn` must be a pure JAX function over the raw values of `args` (Tensors are
    unwrapped positionally; non-Tensor args pass through).  `kwargs` are static
    and must already be closed over by callers that need them (we forward them).
    `nondiff`: positions of Tensor args to treat as constants (e.g. int indices).
    """
    raws = [a._data if isinstance(a, Tensor) else a for a in args]

    # positions of differentiable tensor inputs
    diff_pos = [
        i
        for i, a in enumerate(args)
        if isinstance(a, Tensor) and i not in nondiff and _is_float(raws[i])
    ]

    # AMP: cast differentiable float inputs per op lists
    if framework.get_state().amp_state is not None and diff_pos:
        cast_raws = _amp_cast_args(name, [raws[i] for i in diff_pos])
        for p, r in zip(diff_pos, cast_raws):
            raws[p] = r

    need_grad = framework.is_grad_enabled() and any(
        not args[i].stop_gradient for i in diff_pos
    )

    if not need_grad:
        outs = fn(*raws, **kwargs)
        wrapped = _wrap_outputs(outs, stop_gradient=True)
        _check_nan_inf(name, wrapped)
        cap = framework.get_state().capture_program
        if cap is not None:
            out_list = wrapped if isinstance(wrapped, tuple) else (wrapped,)
            cap._record(name, fn, args, kwargs, out_list)
        return wrapped

    def pure(*diff_raws):
        full = list(raws)
        for p, r in zip(diff_pos, diff_raws):
            full[p] = r
        return fn(*full, **kwargs)

    out_raws, pullback = jax.vjp(pure, *[raws[p] for p in diff_pos])
    wrapped = _wrap_outputs(out_raws, stop_gradient=False)
    _check_nan_inf(name, wrapped)
    out_list = wrapped if isinstance(wrapped, tuple) else (wrapped,)
    if framework.get_state().flags.get("FLAGS_enable_double_grad", True):
        node = TapeNode(name, pullback, tuple(args[p] for p in diff_pos),
                        out_list, fn=fn, args=tuple(args),
                        kwargs=dict(kwargs), args_data=tuple(raws))
    else:  # lighter nodes: no replay record -> no create_graph support
        node = TapeNode(name, pullback, tuple(args[p] for p in diff_pos),
                        out_list)
    for idx, o in enumerate(out_list):
        if isinstance(o, Tensor):
            o._node = node
            o._out_idx = idx
    cap = framework.get_state().capture_program
    if cap is not None:
        cap._record(name, fn, args, kwargs, out_list)
    return wrapped


def _check_nan_inf(name, wrapped):
    """FLAGS_check_nan_inf: raise on non-finite op outputs.

    Reference checks every kernel output when the flag is set
    (paddle/fluid/eager/nan_inf_utils.h:38).  Eager (concrete) values raise
    immediately with the op name; traced values (inside jit/capture) are
    skipped — use jax debug tooling for compiled NaN hunts.
    """
    if not framework.get_state().flags.get("FLAGS_check_nan_inf"):
        return
    outs = wrapped if isinstance(wrapped, tuple) else (wrapped,)
    for o in outs:
        if not (isinstance(o, Tensor) and _is_float(o._data)):
            continue
        if isinstance(o._data, jax.core.Tracer):
            continue
        if not bool(jnp.all(jnp.isfinite(o._data))):
            raise FloatingPointError(
                f"[FLAGS_check_nan_inf] op '{name}' produced NaN/Inf "
                f"(shape {tuple(o._data.shape)}, dtype {o._data.dtype})")


def _wrap_outputs(outs, stop_gradient):
    if isinstance(outs, (tuple, list)):
        return tuple(
            Tensor(o, stop_gradient=stop_gradient or not _is_float(o))
            if isinstance(o, (jax.Array, jax.core.Tracer, np.ndarray))
            else o
            for o in outs
        )
    return Tensor(outs, stop_gradient=stop_gradient or not _is_float(outs))


# ---------------------------------------------------------------------------
# backward — reverse topological sweep (backward.cc:104 RunBackward analog)
# ---------------------------------------------------------------------------


def backward(tensor: Tensor, grad_tensor=None, retain_graph=False,
             deposit_ids=None):
    """deposit_ids: extra tensor ids whose .grad must be populated even if
    they are not leaves — paddle.grad() wrt intermediate tensors."""
    if tensor._node is None:
        if not tensor.stop_gradient:
            g = jnp.ones_like(tensor._data) if grad_tensor is None else (
                grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)
            )
            _deposit_grad(tensor, g)
        return

    if grad_tensor is None:
        seed_grad = jnp.ones_like(tensor._data)
    else:
        seed_grad = grad_tensor._data if isinstance(grad_tensor, Tensor) else jnp.asarray(grad_tensor)

    # Topological order over nodes (DFS, iterative).
    topo: list[TapeNode] = []
    visited: set[int] = set()
    stack: list[tuple[TapeNode, bool]] = [(tensor._node, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            topo.append(node)
            continue
        if id(node) in visited:
            continue
        visited.add(id(node))
        stack.append((node, True))
        for inp in node.inputs:
            if inp._node is not None and id(inp._node) not in visited:
                stack.append((inp._node, False))

    # cotangent accumulation keyed by tensor identity
    cotangents: dict[int, Any] = {id(tensor): seed_grad}

    for node in reversed(topo):
        out_cts = []
        for o in node.outputs:
            ct = cotangents.get(id(o))
            if ct is None:
                if _is_float(o._data):
                    ct = jnp.zeros_like(o._data)
                else:
                    ct = _float0_zero(o._data)
            out_cts.append(ct)
        # jax.vjp pullback takes cotangents matching the fn output structure
        if len(node.outputs) == 1:
            in_cts = node.pullback(out_cts[0])
        else:
            in_cts = node.pullback(tuple(out_cts))
        for inp, ct in zip(node.inputs, in_cts):
            if ct is None or (hasattr(ct, "dtype") and ct.dtype == jax.dtypes.float0):
                continue
            if ct.dtype != inp._data.dtype:
                ct = ct.astype(inp._data.dtype)
            prev = cotangents.get(id(inp))
            cotangents[id(inp)] = ct if prev is None else prev + ct
        if not retain_graph:
            node.pullback = None  # free residuals ASAP

    # Deposit grads on leaves (and any tensor that wants grad).
    all_tensors: dict[int, Tensor] = {id(tensor): tensor}
    for node in topo:
        for t in node.inputs:
            all_tensors[id(t)] = t
        for t in node.outputs:
            all_tensors[id(t)] = t
    for tid, ct in cotangents.items():
        t = all_tensors.get(tid)
        if t is None or t.stop_gradient:
            continue
        if (t._node is None or tid == id(tensor)
                or (deposit_ids and tid in deposit_ids)):
            _deposit_grad(t, ct)

    if not retain_graph:
        for node in topo:
            node.inputs = ()
            node.outputs = ()
        tensor._node = None


def _deposit_grad(t: Tensor, raw):
    hooks = t._hooks
    if hooks:
        g = Tensor(raw)
        for h in hooks:
            if h is None:
                continue
            r = h(g)
            if r is not None:
                g = r if isinstance(r, Tensor) else Tensor(r)
        raw = g._data
    if t.grad is None:
        t.grad = Tensor(raw, stop_gradient=True, name=t.name + "@GRAD")
    else:
        t.grad = Tensor(t.grad._data + raw, stop_gradient=True, name=t.name + "@GRAD")


def _forward_topo(outputs, stop_ids=frozenset()):
    """Tape nodes reachable from `outputs`, in forward (execution) order.

    Traversal does NOT descend past tensors in `stop_ids` (the requested
    differentiation inputs): their producers must not be replayed, or the
    replay would recompute them from captured constants and sever the
    dependence on the traced input values."""
    topo, visited, stack = [], set(), [o._node for o in outputs if o._node]
    while stack:
        n = stack.pop()
        if id(n) in visited:
            continue
        visited.add(id(n))
        topo.append(n)
        for t in n.inputs:
            if t._node is not None and id(t) not in stop_ids:
                stack.append(t._node)
    # reverse DFS discovery, then stable re-sort so every node appears after
    # all producers of its inputs
    order, placed = [], set()
    pending = list(reversed(topo))
    while pending:
        progressed = False
        rest = []
        for n in pending:
            ready = all(t._node is None or id(t._node) in placed
                        for t in n.inputs)
            if ready:
                order.append(n)
                placed.add(id(n))
                progressed = True
            else:
                rest.append(n)
        if not progressed:  # cycle cannot happen on a tape; defensive
            order.extend(rest)
            break
        pending = rest
    return order


def _replay_fn(outputs, inputs):
    """Rebuild the recorded forward as a PURE function of `inputs`' raws —
    the bridge from the eager tape to jax transforms (higher-order AD).

    Returns (h, used): `used[i]` says whether inputs[i] actually feeds the
    replayed graph (the allow_unused contract needs it)."""
    input_ids = {id(t): i for i, t in enumerate(inputs)}
    order = _forward_topo(outputs, stop_ids=frozenset(input_ids))
    for n in order:
        if n.fn is None:
            raise NotImplementedError(
                f"tape node '{n.name}' has no replayable forward record")
    used = [False] * len(inputs)
    for n in order:
        for a in n.args:
            if isinstance(a, Tensor) and id(a) in input_ids:
                used[input_ids[id(a)]] = True
    for i, t in enumerate(inputs):
        if any(t is o for o in outputs):
            used[i] = True

    def h(*in_raws):
        env = {id(t): r for t, r in zip(inputs, in_raws)}
        for n in order:
            call = [env.get(id(a), d) if isinstance(a, Tensor) else a
                    for a, d in zip(n.args, n.args_data)]
            outs = n.fn(*call, **n.kwargs)
            outs = outs if isinstance(outs, tuple) else (outs,)
            for t, r in zip(n.outputs, outs):
                # never clobber a requested input: a multi-output producer
                # reached through a sibling tensor must not recompute it
                if isinstance(t, Tensor) and id(t) not in input_ids:
                    env[id(t)] = r
        return tuple(env.get(id(o), o._data) for o in outputs)

    return h, used


def grad(outputs, inputs, grad_outputs=None, retain_graph=False, create_graph=False, allow_unused=False):
    """paddle.grad parity (functional gradient of outputs wrt inputs)."""
    outputs_l = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs_l = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if create_graph:
        # Higher-order AD: replay the tape as a pure jax function and take
        # its vjp THROUGH apply_op, so the returned grads carry tape nodes
        # themselves (differentiable again, to any order).  Reference:
        # double_grad / higher-order GradNode chains (eager/backward.cc).
        # (retain_graph is moot here: replay never consumes the tape, which
        # matches create_graph implying retain_graph in the reference.)
        h, used = _replay_fn(outputs_l, inputs_l)
        if not all(used) and not allow_unused:
            bad = [t.name for t, u in zip(inputs_l, used) if not u]
            raise RuntimeError(f"Input tensor(s) {bad} unused in the graph "
                               "(pass allow_unused=True for None grads)")
        gos = (grad_outputs if isinstance(grad_outputs, (list, tuple))
               else [grad_outputs] * len(outputs_l))
        seeds = [g._data if isinstance(g, Tensor)
                 else (jnp.ones_like(o._data) if g is None else jnp.asarray(g))
                 for o, g in zip(outputs_l, gos)]

        def gfun(*in_raws):
            _, pull = jax.vjp(h, *in_raws)
            out = pull(tuple(seeds))
            # single-input: return a leaf so tape cotangent seeding matches
            return out if len(out) > 1 else out[0]

        res = apply_op("grad", gfun, *inputs_l)
        res = list(res) if isinstance(res, tuple) else [res]
        return [r if u else None for r, u in zip(res, used)]
    outputs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    grad_outputs = (
        grad_outputs if isinstance(grad_outputs, (list, tuple)) else [grad_outputs] * len(outputs)
    )
    # Save/restore .grad so paddle.grad doesn't clobber training state.
    saved = [(t, t.grad) for t in inputs]
    for t in inputs:
        t.grad = None
    prev_sg = [t.stop_gradient for t in inputs]
    for t in inputs:
        t.stop_gradient = False
    try:
        want = {id(t) for t in inputs}
        for o, g in zip(outputs, grad_outputs):
            backward(o, grad_tensor=g, retain_graph=True, deposit_ids=want)
        results = []
        for t in inputs:
            if t.grad is None:
                if not allow_unused:
                    raise RuntimeError(f"Input tensor {t.name} is unused in the graph")
                results.append(None)
            else:
                results.append(t.grad)
        return results
    finally:
        for (t, g), sg in zip(saved, prev_sg):
            t.grad = g
            t.stop_gradient = sg
        if not retain_graph:
            for o in outputs:
                o._node = None
