"""paddle.fft — discrete Fourier transform family.

Reference: `python/paddle/fft.py:1` (fft/ifft/rfft/irfft/hfft/ihfft, the 2-D and
N-D variants, fftfreq/rfftfreq, fftshift/ifftshift).  The reference lowers to
cuFFT/onemkl kernels (`paddle/phi/kernels/gpu/fft_kernel.cu`); here every
transform is `jnp.fft.*`, which XLA lowers to its native FFT HLO — jit-able,
differentiable (FFT is linear, so VJPs are again FFTs), and shardable over
batch axes.  All transforms dispatch through `apply_op` so the eager tape, AMP
black-listing (complex inputs are never downcast) and NaN checks apply.

Semantics parity notes:
  * real input to c2c transforms is promoted to complex (reference behavior);
  * `norm` in {"backward", "ortho", "forward"} as in the reference;
  * `n`/`s` crop or zero-pad the transformed axes before the transform
    (reference `_resize_fft_input`) — jnp.fft does this natively;
  * hfft/ihfft follow the reference's "hermitian symmetry in the signal
    domain" convention: hfft(x, n) == irfft(conj(x), n) scaled for forward.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .tensor import Tensor, apply_op, to_tensor

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")

# Some experimental TPU plugins (the axon PJRT plugin) have NO complex-dtype
# support: even `astype(complex64)` is UNIMPLEMENTED.  Mainline XLA:TPU
# decomposes complex into real pairs, so jnp.fft is the right primary path;
# on complex-less backends we fall back to a host numpy compute for concrete
# (eager) inputs — complex results then live on the CPU device, real results
# return to the default device.  Tracing/differentiating FFTs on such a
# backend raises a typed error instead of an opaque UNIMPLEMENTED.
_COMPLEX_OK: Optional[bool] = None


def _complex_ok() -> bool:
    global _COMPLEX_OK
    if _COMPLEX_OK is None:
        # The axon plugin must be detected by NAME: merely attempting a
        # complex op poisons its stream (later real ops fail too).  The
        # check uses a private API — contain ITS failure so a jax upgrade
        # can't poison the gate on mainline backends.
        try:
            from jax._src import xla_bridge as _xb
            pv = _xb.get_backend().platform_version.lower()
        except Exception:
            pv = ""
        try:
            if "axon" in pv:
                _COMPLEX_OK = False
            elif jax.default_backend() in ("cpu", "gpu", "cuda", "rocm",
                                           "tpu"):
                # mainline XLA backends all support complex (TPU decomposes
                # into real pairs) — decide by name, never by probing: a
                # probe that first runs INSIDE a jit trace raises and would
                # cache False for the whole process
                _COMPLEX_OK = True
            else:  # unknown plugin: probe OUTSIDE any trace context
                with jax.ensure_compile_time_eval():
                    np.asarray(jnp.zeros((1,), jnp.complex64)
                               + jnp.asarray(1j))
                _COMPLEX_OK = True
        except Exception:
            _COMPLEX_OK = False
    return _COMPLEX_OK


def _device_fft(name, jfn, nfn, *arrays):
    """jfn(*arrays) on complex-capable backends; host nfn fallback otherwise.

    jfn/nfn are closures over the static params (n/s/axes/norm); nfn receives
    numpy arrays and may use np.fft freely.
    """
    if _complex_ok():
        return jfn(*arrays)
    if any(isinstance(a, jax.core.Tracer) for a in arrays):
        raise RuntimeError(
            f"paddle_tpu.fft.{name}: the active backend "
            f"('{jax.default_backend()}') has no complex-dtype support, so "
            "FFT ops cannot be traced (jit/grad) on it. Run the op outside "
            "jit (the eager host fallback applies automatically), or move "
            "the computation to the CPU backend.")
    host = []
    for a in arrays:
        h = np.asarray(a)
        if h.dtype not in (np.float32, np.float64, np.complex64,
                           np.complex128):
            # bf16/f16 (np.fft can't take them) and ints promote to f32
            h = h.astype(np.float32)
        host.append(h)
    res = nfn(*host)
    # single precision result unless the input was genuinely double
    single = host[0].dtype not in (np.float64, np.complex128)
    if np.iscomplexobj(res):
        res = res.astype(np.complex64 if single else np.complex128)
        return jax.device_put(res, jax.devices("cpu")[0])
    res = np.asarray(res).astype(np.float32 if single else np.float64)
    return jnp.asarray(res)


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _apply_fft_op(name, f, *tensors):
    """apply_op, but on complex-less backends take the no-grad path.

    apply_op builds the VJP eagerly (jax.vjp traces `f`) whenever an input
    requires grad, which would hit the tracer error above on a plain forward
    pass.  FFT grads are impossible on such a backend anyway, so detach —
    with a one-time warning so training code doesn't silently lose the tape.
    """
    from . import framework
    if not _complex_ok() and framework.is_grad_enabled() and any(
            isinstance(t, Tensor) and not t.stop_gradient for t in tensors):
        import warnings
        warnings.warn(
            f"paddle_tpu.fft.{name}: backend "
            f"'{jax.default_backend()}' has no complex support; the op ran "
            "via the host fallback and its output is DETACHED from the "
            "autograd tape (no gradient will flow). Run on the CPU backend "
            "for differentiable FFTs.", RuntimeWarning, stacklevel=3)
        with framework.no_grad_guard():
            return apply_op(name, f, *tensors)
    return apply_op(name, f, *tensors)


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm!r}. Norm should be 'forward', 'backward' "
            "or 'ortho'")
    return norm


def _check_n(n):
    if n is not None and (not isinstance(n, int) or n <= 0):
        raise ValueError(f"Invalid FFT argument n({n}), it should be a "
                         "positive integer.")


def _check_s_axes(x, s, axes):
    if s is not None:
        if any((not isinstance(v, int)) or v <= 0 for v in s):
            raise ValueError(f"Invalid FFT argument s({s}), it should be a "
                             "sequence of positive integers.")
    if axes is not None:
        nd = x.ndim
        for a in axes:
            if not isinstance(a, int) or not -nd <= a < nd:
                raise ValueError(
                    f"Invalid FFT axis {a} for input with {nd} dimensions")
        norm_axes = [a % nd for a in axes]
        if len(set(norm_axes)) != len(norm_axes):
            raise ValueError(f"FFT axes {axes} contains duplicates")
    if s is not None and axes is not None and len(s) != len(axes):
        raise ValueError(
            f"Length of s ({len(s)}) must match length of axes ({len(axes)})")


def _promote_c(a):
    if not jnp.issubdtype(a.dtype, jnp.complexfloating):
        a = a.astype(jnp.complex128 if a.dtype == jnp.float64
                     else jnp.complex64)
    return a


def _fft_1d(name, x, n, axis, norm, promote=False):
    x = _t(x)
    _check_n(n)
    _check_norm(norm)
    jfn, nfn = getattr(jnp.fft, name), getattr(np.fft, name)

    def f(a):
        return _device_fft(
            name,
            lambda v: jfn(_promote_c(v) if promote else v,
                          n=n, axis=axis, norm=norm),
            lambda h: nfn(h, n=n, axis=axis, norm=norm), a)

    return _apply_fft_op(name, f, x)


def fft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft_1d("fft", x, n, axis, norm, promote=True)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft_1d("ifft", x, n, axis, norm, promote=True)


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft_1d("rfft", x, n, axis, norm)


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft_1d("irfft", x, n, axis, norm)


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft_1d("hfft", x, n, axis, norm)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    return _fft_1d("ihfft", x, n, axis, norm)


def _fft_nd(name, x, s, axes, norm, promote=False):
    x = _t(x)
    _check_s_axes(x, s, axes)
    _check_norm(norm)
    jfn, nfn = getattr(jnp.fft, name), getattr(np.fft, name)

    def f(a):
        return _device_fft(
            name,
            lambda v: jfn(_promote_c(v) if promote else v,
                          s=s, axes=axes, norm=norm),
            lambda h: nfn(h, s=s, axes=axes, norm=norm), a)

    return _apply_fft_op(name, f, x)


def fftn(x, s=None, axes=None, norm="backward", name=None):
    return _fft_nd("fftn", x, s, axes, norm, promote=True)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    return _fft_nd("ifftn", x, s, axes, norm, promote=True)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    return _fft_nd("rfftn", x, s, axes, norm)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    return _fft_nd("irfftn", x, s, axes, norm)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    # reference fftn_c2r: hermitian-input N-D transform = irfftn of conj with
    # inverted normalization; the last transformed axis carries the symmetry
    x = _t(x)
    _check_s_axes(x, s, axes)
    _check_norm(norm)
    inv = {"backward": "forward", "forward": "backward", "ortho": "ortho"}[norm]

    def f(a):
        return _device_fft(
            "hfftn",
            lambda v: jnp.fft.irfftn(jnp.conj(v), s=s, axes=axes, norm=inv),
            lambda h: np.fft.irfftn(np.conj(h), s=s, axes=axes, norm=inv), a)

    return _apply_fft_op("hfftn", f, x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    x = _t(x)
    _check_s_axes(x, s, axes)
    _check_norm(norm)
    inv = {"backward": "forward", "forward": "backward", "ortho": "ortho"}[norm]

    def f(a):
        return _device_fft(
            "ihfftn",
            lambda v: jnp.conj(jnp.fft.rfftn(v, s=s, axes=axes, norm=inv)),
            lambda h: np.conj(np.fft.rfftn(h, s=s, axes=axes, norm=inv)), a)

    return _apply_fft_op("ihfftn", f, x)


def _as_2d(s, axes, fn):
    if axes is not None and len(axes) != 2:
        raise ValueError(f"Invalid FFT axes {axes}: 2-D transforms take "
                         "exactly two axes")
    if s is not None and len(s) != 2:
        raise ValueError(f"Invalid FFT argument s ({s}): 2-D transforms take "
                         "a length-2 shape")
    return fn


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _as_2d(s, axes, fftn)(x, s, axes, norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _as_2d(s, axes, ifftn)(x, s, axes, norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _as_2d(s, axes, rfftn)(x, s, axes, norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _as_2d(s, axes, irfftn)(x, s, axes, norm)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _as_2d(s, axes, hfftn)(x, s, axes, norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return _as_2d(s, axes, ihfftn)(x, s, axes, norm)


def fftfreq(n, d=1.0, dtype=None, name=None):
    if n <= 0:
        raise ValueError(f"Invalid FFT argument n({n}), it should be a "
                         "positive integer.")
    from .framework import get_default_dtype, to_jax_dtype
    dt = to_jax_dtype(dtype or get_default_dtype())
    return to_tensor(jnp.fft.fftfreq(n, d).astype(dt))


def rfftfreq(n, d=1.0, dtype=None, name=None):
    if n <= 0:
        raise ValueError(f"Invalid FFT argument n({n}), it should be a "
                         "positive integer.")
    from .framework import get_default_dtype, to_jax_dtype
    dt = to_jax_dtype(dtype or get_default_dtype())
    return to_tensor(jnp.fft.rfftfreq(n, d).astype(dt))


def fftshift(x, axes=None, name=None):
    x = _t(x)
    return apply_op("fftshift", lambda a: jnp.fft.fftshift(a, axes=axes), x)


def ifftshift(x, axes=None, name=None):
    x = _t(x)
    return apply_op("ifftshift", lambda a: jnp.fft.ifftshift(a, axes=axes), x)
