"""Llama-family causal LM, TPU-first functional implementation.

Reference parity: this is the model behind the reference's headline benchmark
(BASELINE.json: "PaddleNLP Llama tokens/sec/chip").  The reference builds it
from paddle.nn layers + fleet hybrid-parallel wrappers
(fleet/layers/mpu/mp_layers.py ColumnParallelLinear:312 / RowParallelLinear:524,
fused rope/rmsnorm kernels phi/kernels/fusion/gpu/fused_rope_kernel.cu).

TPU-native design decisions (SURVEY.md §7):
  - Pure functions over a params pytree — jit/pjit/grad/remat compose directly.
  - Transformer blocks are STACKED along a leading `layer` axis and executed
    with `lax.scan` — compile time is O(1) in depth (70B = 80 layers compiles
    as fast as 2), and XLA pipelines the weight prefetch across layers.
  - Every parameter carries LOGICAL sharding axes; a rules table maps logical
    axes -> mesh axes (GSPMD).  TP/SP/DP/PP/EP are *sharding layouts*, not
    different model code — the direct analog of the reference's per-op dist
    rules (distributed/auto_parallel/static/operators/dist_matmul.py etc.).
  - bf16 activations/weights by default, fp32 rmsnorm/softmax/loss (MXU-native
    bf16 matmuls, numerically-safe reductions).
  - GQA (num_key_value_heads < num_attention_heads) as in Llama-3.
  - Attention/rmsnorm/rope route through paddle_tpu.kernels (Pallas on TPU).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
import numpy as np

from .. import kernels

# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 14336
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 8
    head_dim: Optional[int] = None  # defaults to hidden_size // num_attention_heads
    max_position_embeddings: int = 8192
    rms_norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    tie_word_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    # training-time knobs
    remat: bool = True           # jax.checkpoint each block (HBM <-> FLOPs trade)
    # "full" recomputes the whole block in backward; "save_attn" additionally
    # saves each block's attention output (O(S*E)/block HBM) so recompute of
    # its consumers starts there (attention VJP residuals still rematerialize
    # — see models/_utils.apply_remat)
    remat_policy: str = "full"
    scan_layers: bool = True     # lax.scan over stacked blocks
    # context parallelism over the mesh `sep` axis: None | "ring" | "ulysses"
    # (the capability the reference reserved but never implemented — SURVEY.md §5)
    context_parallel: Optional[str] = None
    # explicit mesh for context-parallel / pipeline shard_map (set by
    # ShardedTrainState; falls back to the global mesh when None)
    mesh: Any = None
    # pipeline microbatch count (defaults to the pipe-axis size)
    pp_microbatches: Optional[int] = None
    # pipeline schedule: "gpipe" (AD through the wavefront scan) or "1f1b"
    # (hand-scheduled one-forward-one-backward; <=P stashed microbatches —
    # reference fleet/meta_parallel/pipeline_parallel.py:387)
    # None = unset (runs as gpipe; auto_parallelize may choose 1f1b);
    # set "gpipe"/"1f1b" explicitly to pin the schedule
    pp_schedule: Optional[str] = None
    # interleaved virtual stages per device (pipeline_parallel.py:822)
    pp_virtual_stages: int = 1

    @property
    def hd(self) -> int:
        return self.head_dim or self.hidden_size // self.num_attention_heads

    # -- presets (shapes follow the public Llama-3 / test-scale configs) ----
    @staticmethod
    def tiny(vocab_size: int = 256) -> "LlamaConfig":
        """2-layer test model (the ERNIE-tiny-scale correctness slice)."""
        return LlamaConfig(
            vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, dtype=jnp.float32, remat=False)

    @staticmethod
    def llama3_8b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8)

    @staticmethod
    def llama3_70b() -> "LlamaConfig":
        return LlamaConfig(
            vocab_size=128256, hidden_size=8192, intermediate_size=28672,
            num_hidden_layers=80, num_attention_heads=64, num_key_value_heads=8)


# ---------------------------------------------------------------------------
# Parameter init + logical sharding axes
# ---------------------------------------------------------------------------
#
# params pytree layout (leading dim L = num_hidden_layers on block params):
# {
#   "embed":   {"weight": (V, E)},
#   "blocks": {
#     "input_norm":   (L, E),
#     "post_norm":    (L, E),
#     "wq": (L, E, Hq*D), "wk": (L, E, Hkv*D), "wv": (L, E, Hkv*D),
#     "wo": (L, Hq*D, E),
#     "w_gate": (L, E, F), "w_up": (L, E, F), "w_down": (L, F, E),
#   },
#   "final_norm": (E,),
#   "lm_head": (E, V)   [absent when tie_word_embeddings]
# }


def _normal(key, shape, std, dtype):
    return (std * jax.random.normal(key, shape, dtype=jnp.float32)).astype(dtype)


def init_params(config: LlamaConfig, key=None, seed: int = 0, init_ffn: bool = True):
    """Initialize the parameter pytree (truncated-normal-free, scaled-normal init).

    init_ffn=False skips the dense FFN weights — used by variants (MoE) that
    replace the FFN, so multi-GB dense experts are never materialized."""
    if key is None:
        key = jax.random.PRNGKey(seed)
    c = config
    E, F, V, L = c.hidden_size, c.intermediate_size, c.vocab_size, c.num_hidden_layers
    D = c.hd
    Hq, Hkv = c.num_attention_heads, c.num_key_value_heads
    std = 0.02
    ks = jax.random.split(key, 16)

    def blk(k, shape):
        # one key per stacked weight; layer axis folded into the shape
        return _normal(k, shape, std, c.dtype)

    params = {
        "embed": {"weight": _normal(ks[0], (V, E), std, c.dtype)},
        "blocks": {
            "input_norm": jnp.ones((L, E), dtype=jnp.float32),
            "post_norm": jnp.ones((L, E), dtype=jnp.float32),
            "wq": blk(ks[1], (L, E, Hq * D)),
            "wk": blk(ks[2], (L, E, Hkv * D)),
            "wv": blk(ks[3], (L, E, Hkv * D)),
            "wo": blk(ks[4], (L, Hq * D, E)),
        },
        "final_norm": jnp.ones((E,), dtype=jnp.float32),
    }
    if init_ffn:
        params["blocks"]["w_gate"] = blk(ks[5], (L, E, F))
        params["blocks"]["w_up"] = blk(ks[6], (L, E, F))
        params["blocks"]["w_down"] = blk(ks[7], (L, F, E))
    if not c.tie_word_embeddings:
        params["lm_head"] = _normal(ks[8], (E, V), std, c.dtype)
    return params


def param_logical_axes(config: LlamaConfig):
    """Logical sharding axes per parameter, same pytree structure as params.

    Axis vocabulary: "vocab", "embed", "mlp", "heads" (fused head*dim), "layer",
    None (replicated).  distributed.mesh.LOGICAL_RULES maps these to mesh axes.
    """
    axes = {
        "embed": {"weight": ("vocab", "embed")},
        "blocks": {
            "input_norm": ("layer", None),
            "post_norm": ("layer", None),
            "wq": ("layer", "embed", "heads"),
            "wk": ("layer", "embed", "heads"),
            "wv": ("layer", "embed", "heads"),
            "wo": ("layer", "heads", "embed"),
            "w_gate": ("layer", "embed", "mlp"),
            "w_up": ("layer", "embed", "mlp"),
            "w_down": ("layer", "mlp", "embed"),
        },
        "final_norm": (None,),
    }
    if not config.tie_word_embeddings:
        axes["lm_head"] = ("embed", "vocab")
    return axes


# ---------------------------------------------------------------------------
# RoPE tables
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=8)
def _rope_tables_np(head_dim: int, max_pos: int, theta: float):
    # cache numpy only — jnp values created under a trace must not be cached
    inv_freq = 1.0 / (theta ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    t = np.arange(max_pos, dtype=np.float64)
    freqs = np.outer(t, inv_freq)  # (max_pos, D/2)
    return (np.cos(freqs).astype(np.float32), np.sin(freqs).astype(np.float32))


def _rope_tables(head_dim: int, max_pos: int, theta: float):
    cos, sin = _rope_tables_np(head_dim, max_pos, theta)
    return jnp.asarray(cos), jnp.asarray(sin)


def _apply_rope(x, cos, sin):
    """x: (B, S, H, D); cos/sin: (S, D/2) shared tables, or (B, S, D/2)
    per-row tables (left-padded decode) — GPT-NeoX-style half rotation."""
    d2 = x.shape[-1] // 2
    xf = x.astype(jnp.float32)
    x1, x2 = xf[..., :d2], xf[..., d2:]
    if cos.ndim == 2:
        cos, sin = cos[None], sin[None]
    cos = cos[:, :, None, :]
    sin = sin[:, :, None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1).astype(x.dtype)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _block(c: LlamaConfig, x, lp, cos, sin, attn_mask, ffn_fn=None):
    """One transformer block. x: (B, S, E); lp: this layer's param slice.

    `ffn_fn(h, lp) -> (out, aux_loss)` overrides the dense SwiGLU FFN — the
    hook the MoE variant (models/moe_llama.py) plugs its expert FFN into.
    Returns (x, aux_loss) where aux is 0 for the dense path.
    """
    B, S, E = x.shape
    D, Hq, Hkv = c.hd, c.num_attention_heads, c.num_key_value_heads

    h = kernels.rms_norm(x, lp["input_norm"].astype(jnp.float32),
                         c.rms_norm_eps).astype(x.dtype)
    q = (h @ lp["wq"]).reshape(B, S, Hq, D)
    k = (h @ lp["wk"]).reshape(B, S, Hkv, D)
    v = (h @ lp["wv"]).reshape(B, S, Hkv, D)
    q = _apply_rope(q, cos, sin)
    k = _apply_rope(k, cos, sin)
    if c.context_parallel:
        from ..distributed.context_parallel import context_parallel_attention
        if attn_mask is not None and attn_mask.ndim != 2:
            raise ValueError(
                "context_parallel attention composes with a global (S, S) "
                "mask only (rows shard with q around the ring); batched/"
                "per-head masks need context_parallel disabled")
        attn = context_parallel_attention(
            q, k, v, mesh=c.mesh, impl=c.context_parallel, causal=True,
            mask=attn_mask)
    else:
        attn = kernels.attention(q, k, v, mask=attn_mask, causal=True)
    # no-op unless the enclosing jax.checkpoint uses the save_attn policy
    attn = checkpoint_name(attn, "attn_out")
    x = x + (attn.reshape(B, S, Hq * D) @ lp["wo"])

    h = kernels.rms_norm(x, lp["post_norm"].astype(jnp.float32),
                         c.rms_norm_eps).astype(x.dtype)
    if ffn_fn is not None:
        out, aux = ffn_fn(h, lp)
        return x + out.astype(x.dtype), aux
    gate = h @ lp["w_gate"]
    up = h @ lp["w_up"]
    mlp = (jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up) @ lp["w_down"]
    return x + mlp.astype(x.dtype), jnp.float32(0.0)


def forward(params, input_ids, config: LlamaConfig, positions=None, attn_mask=None,
            ffn_fn=None, return_aux_loss=False):
    """input_ids: (B, S) int32 -> logits (B, S, V) float32.

    `ffn_fn` replaces the dense FFN per block (see _block); aux losses from it
    accumulate across layers and are returned when `return_aux_loss`.
    """
    c = config
    x = jnp.take(params["embed"]["weight"], input_ids, axis=0)
    S = input_ids.shape[1]
    cos_full, sin_full = _rope_tables(c.hd, c.max_position_embeddings, c.rope_theta)
    if positions is None:
        cos, sin = cos_full[:S], sin_full[:S]
    else:
        cos, sin = cos_full[positions], sin_full[positions]

    blk = functools.partial(_block, c, ffn_fn=ffn_fn)

    from ..distributed import pipeline as pipe_lib
    # pipeline engages only via an EXPLICIT config.mesh (ShardedTrainState
    # threads it); the global mesh must not silently reroute plain forwards
    mesh = c.mesh
    pp = pipe_lib.num_stages(mesh) if mesh is not None else 1

    if pp > 1:
        # 1F1B-by-autodiff microbatch pipeline over the pipe axis (C27 analog)
        if attn_mask is not None:
            raise ValueError("pipeline parallel forward does not take attn_mask")
        if c.remat and c.remat_policy != "full":
            # pipeline_apply owns its own per-microbatch remat; named-save
            # policies are not threaded through it — fail instead of
            # silently training under a different policy than requested
            raise ValueError(
                f"remat_policy={c.remat_policy!r} is not supported under "
                f"pipeline parallelism; use 'full'")
        from jax.sharding import PartitionSpec as P
        sep_live = (c.context_parallel
                    and "sep" in mesh.axis_names and mesh.shape["sep"] > 1)
        if sep_live:
            # sep goes manual alongside pipe: activations + rope tables enter
            # seq-sharded and ring attention runs its local collective form
            manual, x_spec = ("sep",), P(None, "sep", None)
            ex_specs = (P("sep", None), P("sep", None))
        else:
            manual, x_spec, ex_specs = (), None, None
        x, aux_total = pipe_lib.pipeline_apply(
            lambda h, lp, cos, sin: blk(h, lp, cos, sin, None),
            params["blocks"], x, extras=(cos, sin), mesh=mesh,
            n_micro=c.pp_microbatches, remat=c.remat,
            manual_axes=manual, x_spec=x_spec, extras_specs=ex_specs,
            virtual_stages=c.pp_virtual_stages, returns_aux=True)
    else:
        if c.remat:
            from ._utils import apply_remat
            blk = apply_remat(blk, c.remat_policy)
        if c.scan_layers:
            def body(carry, lp):
                h, aux = carry
                h, a = blk(h, lp, cos, sin, attn_mask)
                return (h, aux + a), None
            (x, aux_total), _ = jax.lax.scan(
                body, (x, jnp.float32(0.0)), params["blocks"])
        else:
            aux_total = jnp.float32(0.0)
            for i in range(c.num_hidden_layers):
                lp = jax.tree.map(lambda a: a[i], params["blocks"])
                x, a = blk(x, lp, cos, sin, attn_mask)
                aux_total = aux_total + a

    x = kernels.rms_norm(x, params["final_norm"].astype(jnp.float32), c.rms_norm_eps)
    head = (params["embed"]["weight"].T if c.tie_word_embeddings
            else params["lm_head"])
    logits = (x @ head.astype(x.dtype)).astype(jnp.float32)
    if return_aux_loss:
        return logits, aux_total
    return logits


def masked_ce_loss(logits, labels, ignore_index: int = -100):
    """Token-masked cross entropy shared by all LM variants."""
    valid = labels != ignore_index
    safe = jnp.where(valid, labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    ll = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, logz - ll, 0.0)
    count = jnp.maximum(valid.sum(), 1)
    return nll.sum() / count


def loss_fn(params, batch, config: LlamaConfig):
    """Causal-LM loss.  batch: {"input_ids": (B,S), "labels": (B,S)} with -100 = ignore."""
    logits = forward(params, batch["input_ids"], config)
    return masked_ce_loss(logits, batch["labels"])


def lm_batch_from_tokens(tokens):
    """Next-token-prediction batch from a (B, S+1) token block."""
    return {"input_ids": tokens[:, :-1], "labels": tokens[:, 1:]}


def loss_and_grads(params, batch, config: LlamaConfig, ffn_fn=None,
                   ignore_index: int = -100):
    """(loss, grads) — routes to the hand-scheduled 1F1B pipeline when
    config.pp_schedule == '1f1b' on a live pipe mesh (reference 1F1B,
    fleet/meta_parallel/pipeline_parallel.py:387); otherwise plain
    jax.value_and_grad(loss_fn)."""
    c = config
    from ..distributed import pipeline as pipe_lib
    mesh = c.mesh
    pp = pipe_lib.num_stages(mesh) if mesh is not None else 1
    if pp <= 1 or c.pp_schedule != "1f1b":
        lf = loss_fn if ffn_fn is None else functools.partial(
            _loss_fn_with_ffn, ffn_fn=ffn_fn)
        return jax.value_and_grad(lf)(params, batch, c)

    from jax.sharding import PartitionSpec as P
    ids, labels = batch["input_ids"], batch["labels"]
    S = ids.shape[1]
    cos_full, sin_full = _rope_tables(c.hd, c.max_position_embeddings, c.rope_theta)
    cos, sin = cos_full[:S], sin_full[:S]

    def embed_fn(ep):
        return jnp.take(ep["weight"], ids, axis=0)

    x, embed_vjp = jax.vjp(embed_fn, params["embed"])

    blk = functools.partial(_block, c, ffn_fn=ffn_fn)
    denom = jnp.maximum(jnp.sum(labels != ignore_index), 1).astype(jnp.float32)
    tied = c.tie_word_embeddings
    head_params = {"final_norm": params["final_norm"]}
    head_params["head_w"] = (params["embed"]["weight"] if tied
                             else params["lm_head"])

    def head_fn(y, hp, lbl):
        """Per-microbatch loss CONTRIBUTION: token nll sum / global denom."""
        yn = kernels.rms_norm(y, hp["final_norm"].astype(jnp.float32),
                              c.rms_norm_eps)
        w = hp["head_w"].T if tied else hp["head_w"]
        logits = (yn @ w.astype(yn.dtype)).astype(jnp.float32)
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        logz = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
        return jnp.sum(jnp.where(valid, logz - ll, 0.0)) / denom

    sep_live = (c.context_parallel
                and "sep" in mesh.axis_names and mesh.shape["sep"] > 1)
    if sep_live:
        manual, x_spec, lbl_spec = ("sep",), P(None, "sep", None), P(None, "sep")
        ex_specs = (P("sep", None), P("sep", None))
    else:
        manual, x_spec, lbl_spec, ex_specs = (), None, None, None

    loss, _aux, (dblocks, dhp, dx) = pipe_lib.pipeline_1f1b(
        lambda h, lp, cos, sin: blk(h, lp, cos, sin, None),
        head_fn, params["blocks"], head_params, x, labels,
        extras=(cos, sin), mesh=mesh, n_micro=c.pp_microbatches,
        remat=c.remat, manual_axes=manual, x_spec=x_spec,
        extras_specs=ex_specs, labels_spec=lbl_spec,
        aux_scale=1.0, returns_aux=True)

    (dembed,) = embed_vjp(dx)
    grads = {"embed": dembed, "blocks": dblocks,
             "final_norm": dhp["final_norm"]}
    if tied:
        grads["embed"] = {"weight": dembed["weight"] + dhp["head_w"]}
    else:
        grads["lm_head"] = dhp["head_w"]
    return loss, grads


def _loss_fn_with_ffn(params, batch, config, ffn_fn=None):
    logits, aux = forward(params, batch["input_ids"], config,
                          ffn_fn=ffn_fn, return_aux_loss=True)
    return masked_ce_loss(logits, batch["labels"]) + aux


def num_params(config: LlamaConfig, init_fn=None) -> int:
    init = init_fn or init_params
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(
        jax.eval_shape(lambda: init(config, jax.random.PRNGKey(0)))))


def flops_per_token(config: LlamaConfig, seq_len: int) -> float:
    """Approximate training FLOPs/token (6*N_matmul + attention quadratic term)."""
    c = config
    E, F, V, L, D = (c.hidden_size, c.intermediate_size, c.vocab_size,
                     c.num_hidden_layers, c.hd)
    Hq, Hkv = c.num_attention_heads, c.num_key_value_heads
    matmul_params = L * (E * Hq * D + 2 * E * Hkv * D + Hq * D * E + 3 * E * F) + E * V
    attn = L * 2 * seq_len * Hq * D  # qk^T + av per token
    return 6.0 * (matmul_params + attn)
