"""Model zoo — TPU-native functional models (the flagship training path).

Reference analog: the reference framework itself ships no LLMs (they live in
PaddleNLP), but its headline benchmark configs (BASELINE.md) are Llama-3 /
ERNIE / MoE pretraining.  Here the model zoo is part of the framework: each
model is a pure-functional JAX program (params pytree + apply fn) with logical
sharding axes, so the same definition runs eager (via nn.Layer wrappers),
single-chip jit, or any GSPMD mesh layout (dp/tp/sp/pp/ep) unchanged.
"""

from . import llama  # noqa: F401
from .llama import LlamaConfig  # noqa: F401
from . import moe_llama  # noqa: F401
from .moe_llama import MoELlamaConfig  # noqa: F401
from . import generation  # noqa: F401
from . import bert  # noqa: F401
from .bert import BertConfig  # noqa: F401
from . import dit  # noqa: F401
from .dit import DiTConfig  # noqa: F401
