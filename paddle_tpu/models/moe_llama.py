"""MoE Llama (Mixtral-style) — expert-parallel flagship variant.

Reference parity: the reference trains MoE models through
`incubate/distributed/models/moe/moe_layer.py` (all-to-all dispatch) stacked
into its Llama/GPT trunks; gates under `moe/gate/`.  Here the dense SwiGLU FFN
of each block is replaced by `distributed.moe.moe_ffn` — expert weights carry
an ``expert`` logical axis so GSPMD lays them over the mesh's expert axis and
inserts the token all-to-alls (SURVEY.md §7 step 5).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from ..distributed import moe as moe_lib
from . import llama as llama_lib


@dataclasses.dataclass(frozen=True)
class MoELlamaConfig(llama_lib.LlamaConfig):
    num_experts: int = 8
    moe_top_k: int = 2
    # None = dropless: auto dispatch then runs the Pallas grouped-matmul
    # ("gmm") path, which needs no capacity buffers at all
    capacity_factor: "float | None" = 1.25
    aux_loss_weight: float = 0.01
    router_z_loss_weight: float = 1e-3
    # "einsum" | "scatter" | "gmm" | None (auto: gmm when capacity_factor
    # is None, else scatter/einsum by dispatch-tensor size)
    moe_dispatch: "str | None" = None

    @property
    def moe(self) -> moe_lib.MoEConfig:
        return moe_lib.MoEConfig(
            num_experts=self.num_experts, top_k=self.moe_top_k,
            capacity_factor=self.capacity_factor,
            aux_loss_weight=self.aux_loss_weight,
            z_loss_weight=self.router_z_loss_weight,
            dispatch_mode=self.moe_dispatch)

    @staticmethod
    def tiny(vocab_size: int = 256, num_experts: int = 4) -> "MoELlamaConfig":
        return MoELlamaConfig(
            vocab_size=vocab_size, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, dtype=jnp.float32, remat=False,
            num_experts=num_experts, capacity_factor=2.0)

    @staticmethod
    def mixtral_8x7b() -> "MoELlamaConfig":
        return MoELlamaConfig(
            vocab_size=32000, hidden_size=4096, intermediate_size=14336,
            num_hidden_layers=32, num_attention_heads=32, num_key_value_heads=8,
            rope_theta=1e6, num_experts=8, moe_top_k=2)


def init_params(config: MoELlamaConfig, key=None, seed: int = 0):
    """Llama trunk params with per-layer MoE FFN (experts stacked on axis 1)."""
    if key is None:
        key = jax.random.PRNGKey(seed)
    c = config
    kd, km = jax.random.split(key)
    params = llama_lib.init_params(c, kd, init_ffn=False)
    blocks = params["blocks"]
    L, E, F, X = c.num_hidden_layers, c.hidden_size, c.intermediate_size, c.num_experts
    std = 0.02
    ks = jax.random.split(km, 4)
    n = lambda k, s: (std * jax.random.normal(k, s, jnp.float32)).astype(c.dtype)
    blocks["router"] = std * jax.random.normal(ks[0], (L, E, X), jnp.float32)
    blocks["w_gate"] = n(ks[1], (L, X, E, F))
    blocks["w_up"] = n(ks[2], (L, X, E, F))
    blocks["w_down"] = n(ks[3], (L, X, F, E))
    return params


def param_logical_axes(config: MoELlamaConfig):
    axes = llama_lib.param_logical_axes(config)
    axes["blocks"]["router"] = ("layer", None, None)
    axes["blocks"]["w_gate"] = ("layer", "expert", "embed", "mlp")
    axes["blocks"]["w_up"] = ("layer", "expert", "embed", "mlp")
    axes["blocks"]["w_down"] = ("layer", "expert", "mlp", "embed")
    return axes


def forward(params, input_ids, config: MoELlamaConfig, positions=None,
            attn_mask=None, return_aux_loss=False):
    """input_ids (B, S) -> logits (B, S, V) fp32 [+ total router aux loss].

    Reuses the llama trunk verbatim — only the per-block FFN is swapped for
    the expert FFN via llama.forward's ffn_fn hook."""
    moe_cfg = config.moe

    def ffn(h, lp):
        return moe_lib.moe_ffn(h, lp, moe_cfg)

    return llama_lib.forward(
        params, input_ids, config, positions=positions, attn_mask=attn_mask,
        ffn_fn=ffn, return_aux_loss=return_aux_loss)


def routing_stats(params, input_ids, config: MoELlamaConfig):
    """Routing health of a full forward: summed router aux loss plus the
    fraction of (token, slot) picks the capacity buffers dropped.

    Rides the trunk's aux channel with a packed [aux, dropped, routed]
    vector — the python layer loop (scan_layers=False) sums any aux shape,
    so the trunk needs no changes.  Returns {"aux_loss", "dropped_fraction"}
    as f32 scalars; gmm dispatch reports 0 dropped by construction.
    """
    c = dataclasses.replace(config, scan_layers=False, remat=False)
    moe_cfg = c.moe

    def ffn(h, lp):
        out, aux, m = moe_lib.moe_ffn(h, lp, moe_cfg, return_metrics=True)
        return out, jnp.stack([aux.astype(jnp.float32),
                               m["dropped_count"], m["routed_count"]])

    _, vec = llama_lib.forward(params, input_ids, c, ffn_fn=ffn,
                               return_aux_loss=True)
    return {
        "aux_loss": vec[0],
        "dropped_fraction": vec[1] / jnp.maximum(vec[2], 1.0),
    }


def loss_fn(params, batch, config: MoELlamaConfig):
    """Causal-LM loss + router aux losses (batch: input_ids/labels, -100=ignore)."""
    logits, aux = forward(params, batch["input_ids"], config,
                          return_aux_loss=True)
    return llama_lib.masked_ce_loss(logits, batch["labels"]) + aux


def loss_and_grads(params, batch, config: MoELlamaConfig):
    """(loss, grads) with the 1F1B pipeline when pp_schedule='1f1b' — the
    expert FFN rides the same ffn_fn hook, so MoE composes with pipeline
    parallelism (the reference forbids exactly this pairing)."""
    moe_cfg = config.moe

    def ffn(h, lp):
        return moe_lib.moe_ffn(h, lp, moe_cfg)

    return llama_lib.loss_and_grads(params, batch, config, ffn_fn=ffn)


def num_params(config: MoELlamaConfig) -> int:
    return llama_lib.num_params(config, init_fn=init_params)


lm_batch_from_tokens = llama_lib.lm_batch_from_tokens


def flops_per_token(config: MoELlamaConfig, seq_len: int) -> float:
    """Training FLOPs/token under the ACTIVE-params 6N convention (Switch/
    GShard accounting): a token pays only for the top_k experts it visits,
    plus the router; attention terms match the dense trunk."""
    c = config
    moe_delta = c.num_hidden_layers * (
        3 * c.hidden_size * c.intermediate_size * (c.moe_top_k - 1)
        + c.hidden_size * c.num_experts)
    return llama_lib.flops_per_token(c, seq_len) + 6.0 * moe_delta
