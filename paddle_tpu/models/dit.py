"""DiT — Diffusion Transformer (class-conditional), TPU-first functional impl.

The BASELINE config-4 flagship ("DiT / Stable-Diffusion-3: conv + attention
kernels — trains").  The reference covers this capability through its conv
kernel stack (`paddle/phi/kernels/gpu/conv_kernel.cu:1`) plus the vision model
zoo (`python/paddle/vision/models/`); SD3-class diffusion models are DiT
backbones, so this module is the framework's diffusion flagship.

Architecture (DiT: Peebles & Xie, "Scalable Diffusion Models with
Transformers"): patchify conv -> pos-embed -> N transformer blocks with
adaLN-Zero conditioning on (timestep, class) -> adaLN final layer ->
unpatchify.  Training objective: predict the noise eps added by a cosine
diffusion schedule (MSE).

TPU-first design (same rules as models/llama.py):
  - pure functions over a params pytree; jit/grad/remat/pjit compose.
  - blocks STACKED on a leading layer axis + `lax.scan` — O(1) compile in
    depth; `jax.checkpoint` per block when config.remat.
  - patchify is a REAL strided conv (lax.conv_general_dilated) — the conv
    path the bench exercises on the MXU; attention routes through
    paddle_tpu.kernels.attention (Pallas flash when shapes allow).
  - bf16 matmuls, fp32 LayerNorm/modulation/loss.
  - logical sharding axes per param -> distributed.mesh.LOGICAL_RULES, so
    the same ShardedTrainState TP/DP/ZeRO layouts apply unchanged.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
import numpy as np

from .. import kernels

# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DiTConfig:
    image_size: int = 32          # SD latent grid (32x32x4 = 256x256 pixels)
    in_channels: int = 4
    patch_size: int = 2
    hidden_size: int = 768        # DiT-B
    depth: int = 12
    num_heads: int = 12
    mlp_ratio: float = 4.0
    num_classes: int = 1000
    freq_embed_size: int = 256
    num_train_timesteps: int = 1000
    dtype: Any = jnp.bfloat16
    remat: bool = True
    # "full" | "save_attn" (save per-block attention outputs; consumers
    # resume from them but attention VJP residuals still rematerialize —
    # see models/_utils.apply_remat; O(N*E)/block extra HBM)
    remat_policy: str = "full"
    scan_layers: bool = True
    fused_adaln: bool = False     # Pallas LN+modulate (bench A/Bs on chip)
    attn_impl: str = "auto"       # "auto" (flash when aligned) | "xla":
    #   at N=256 tokens the (B,H,N,N) score tensor is small and XLA's fused
    #   softmax can beat the flash kernel's grid overhead — bench A/Bs on chip
    fused_qkv: bool = False       # one (E,3E) matmul instead of three (E,E)
    mesh: Any = None              # threaded by ShardedTrainState

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def mlp_hidden(self) -> int:
        return int(self.hidden_size * self.mlp_ratio)

    @staticmethod
    def tiny():
        return DiTConfig(image_size=8, in_channels=3, patch_size=2,
                         hidden_size=32, depth=2, num_heads=4,
                         num_classes=10, freq_embed_size=32,
                         dtype=jnp.float32, remat=False)

    # DiT model zoo (the reference's vision zoo analog for diffusion)
    @staticmethod
    def B_2(**kw):
        return DiTConfig(hidden_size=768, depth=12, num_heads=12, **kw)

    @staticmethod
    def L_2(**kw):
        return DiTConfig(hidden_size=1024, depth=24, num_heads=16, **kw)

    @staticmethod
    def XL_2(**kw):
        return DiTConfig(hidden_size=1152, depth=28, num_heads=16, **kw)


# ---------------------------------------------------------------------------
# Cosine diffusion schedule (Nichol & Dhariwal improved-DDPM)
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=8)
def _alpha_bars_np(T: int, s: float = 0.008):
    t = np.arange(T + 1, dtype=np.float64) / T
    f = np.cos((t + s) / (1 + s) * np.pi / 2) ** 2
    ab = np.clip(f / f[0], 1e-5, 1.0)
    return ab.astype(np.float32)  # (T+1,), ab[0] = 1


def alpha_bars(config: DiTConfig):
    return jnp.asarray(_alpha_bars_np(config.num_train_timesteps))


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------


def _normal(key, shape, std, dtype):
    return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


def init_params(config: DiTConfig, key=None, seed: int = 0):
    if key is None:
        key = jax.random.PRNGKey(seed)
    c = config
    E, L, F, P = c.hidden_size, c.depth, c.mlp_hidden, c.patch_size
    C, N, FE = c.in_channels, c.num_patches, c.freq_embed_size
    std = 0.02
    ks = jax.random.split(key, 12)

    return {
        # patchify conv: OIHW (E out-channels over PxP patches)
        "patch": {"w": _normal(ks[0], (E, C, P, P), std, c.dtype),
                  "b": jnp.zeros((E,), jnp.float32)},
        "pos_emb": _normal(ks[1], (N, E), std, jnp.float32),
        "t_mlp": {"w1": _normal(ks[2], (FE, E), std, jnp.float32),
                  "b1": jnp.zeros((E,), jnp.float32),
                  "w2": _normal(ks[3], (E, E), std, jnp.float32),
                  "b2": jnp.zeros((E,), jnp.float32)},
        # +1 slot: the classifier-free-guidance null class
        "y_embed": _normal(ks[4], (c.num_classes + 1, E), std, jnp.float32),
        "blocks": {
            # adaLN-Zero: modulation projection out of silu(c); ZERO init so
            # every block starts as identity (gates = 0)
            "w_mod": jnp.zeros((L, E, 6 * E), c.dtype),
            "b_mod": jnp.zeros((L, 6 * E), jnp.float32),
            "wq": _normal(ks[5], (L, E, E), std, c.dtype),
            "wk": _normal(ks[6], (L, E, E), std, c.dtype),
            "wv": _normal(ks[7], (L, E, E), std, c.dtype),
            "wo": _normal(ks[8], (L, E, E), std, c.dtype),
            "b_qkv": jnp.zeros((L, 3, E), jnp.float32),
            "b_o": jnp.zeros((L, E), jnp.float32),
            "w_mlp1": _normal(ks[9], (L, E, F), std, c.dtype),
            "b_mlp1": jnp.zeros((L, F), jnp.float32),
            "w_mlp2": _normal(ks[10], (L, F, E), std, c.dtype),
            "b_mlp2": jnp.zeros((L, E), jnp.float32),
        },
        "final": {
            "w_mod": jnp.zeros((E, 2 * E), c.dtype),
            "b_mod": jnp.zeros((2 * E,), jnp.float32),
            # zero-init output projection: the model predicts 0 noise at init
            "w": jnp.zeros((E, P * P * C), c.dtype),
            "b": jnp.zeros((P * P * C,), jnp.float32),
        },
    }


def param_logical_axes(config: DiTConfig):
    """Logical axes (see distributed.mesh.LOGICAL_RULES): 'heads'/'mlp' are
    the tensor-parallel (column/row) dims, 'layer' the pipeline-stacked dim."""
    return {
        "patch": {"w": (None, None, None, None), "b": (None,)},
        "pos_emb": (None, "embed"),
        "t_mlp": {"w1": (None, "embed"), "b1": (None,),
                  "w2": (None, "embed"), "b2": (None,)},
        "y_embed": (None, "embed"),
        "blocks": {
            "w_mod": ("layer", "embed", None),
            "b_mod": ("layer", None),
            "wq": ("layer", "embed", "heads"),
            "wk": ("layer", "embed", "heads"),
            "wv": ("layer", "embed", "heads"),
            "wo": ("layer", "heads", "embed"),
            "b_qkv": ("layer", None, None),
            "b_o": ("layer", None),
            "w_mlp1": ("layer", "embed", "mlp"),
            "b_mlp1": ("layer", "mlp"),
            "w_mlp2": ("layer", "mlp", "embed"),
            "b_mlp2": ("layer", None),
        },
        "final": {"w_mod": ("embed", None), "b_mod": (None,),
                  "w": ("embed", None), "b": (None,)},
    }


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def timestep_embedding(t, dim: int, max_period: float = 10000.0):
    """Sinusoidal timestep embedding (f32), t: (B,) int/float."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    args = t.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)


def _layernorm(x):
    """Non-affine LayerNorm in f32 (DiT: elementwise_affine=False)."""
    x32 = x.astype(jnp.float32)
    mu = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    return (x32 - mu) * jax.lax.rsqrt(var + 1e-6)


def _modulate(x32, shift, scale):
    return x32 * (1.0 + scale[:, None, :]) + shift[:, None, :]


def _block(x, c_vec, bp, config: DiTConfig):
    """One DiT block.  x: (B, N, E) model-dtype; c_vec: (B, E) f32;
    bp: this layer's slice of the stacked block params."""
    cfg = config
    B, N, E = x.shape
    H, D = cfg.num_heads, cfg.head_dim
    dt = cfg.dtype

    mod = (jax.nn.silu(c_vec) @ bp["w_mod"].astype(jnp.float32)
           + bp["b_mod"])                                   # (B, 6E) f32
    # LN statistics stay f32 (inside _layernorm); the (B, N, E)-sized
    # modulate/gate elementwise work runs in the model dtype — per-image
    # scalars lose nothing meaningful in bf16 and the residual stream's
    # HBM traffic halves
    sh1, sc1, g1, sh2, sc2, g2 = [
        s.astype(dt)[:, None, :] for s in jnp.split(mod, 6, axis=-1)]

    if cfg.fused_adaln:
        h = kernels.adaln_modulate(x, sh1[:, 0], sc1[:, 0])
    else:
        h = _layernorm(x).astype(dt) * (1 + sc1) + sh1
    if cfg.fused_qkv:
        # one (E, 3E) matmul: XLA won't merge three separate-param matmuls,
        # and the per-layer weight concat is trivial next to the token matmul
        wqkv = jnp.concatenate([bp["wq"], bp["wk"], bp["wv"]], axis=-1)
        qkv = h @ wqkv + bp["b_qkv"].reshape(-1).astype(dt)
        q, k, v = [s.reshape(B, N, H, D) for s in jnp.split(qkv, 3, axis=-1)]
    else:
        q = (h @ bp["wq"] + bp["b_qkv"][0].astype(dt)).reshape(B, N, H, D)
        k = (h @ bp["wk"] + bp["b_qkv"][1].astype(dt)).reshape(B, N, H, D)
        v = (h @ bp["wv"] + bp["b_qkv"][2].astype(dt)).reshape(B, N, H, D)
    if cfg.attn_impl == "xla":
        a = kernels.attention_reference(q, k, v, causal=False)
    elif cfg.attn_impl == "auto":
        a = kernels.attention(q, k, v, causal=False)        # (B, N, H, D)
    else:
        raise ValueError(
            f"attn_impl must be 'auto' or 'xla', got {cfg.attn_impl!r}")
    # no-op unless the enclosing jax.checkpoint uses the save_attn policy
    a = checkpoint_name(a, "attn_out")
    a = a.reshape(B, N, E) @ bp["wo"] + bp["b_o"].astype(dt)
    x = x + g1 * a

    if cfg.fused_adaln:
        h = kernels.adaln_modulate(x, sh2[:, 0], sc2[:, 0])
    else:
        h = _layernorm(x).astype(dt) * (1 + sc2) + sh2
    h = jax.nn.gelu(h @ bp["w_mlp1"] + bp["b_mlp1"].astype(dt),
                    approximate=True)
    h = h @ bp["w_mlp2"] + bp["b_mlp2"].astype(dt)
    return x + g2 * h


def forward(params, x_t, t, y, config: DiTConfig):
    """Predict eps.  x_t: (B, C, H, W); t: (B,) int; y: (B,) int class ids
    (num_classes = the CFG null class).  Returns (B, C, H, W)."""
    c = config
    B = x_t.shape[0]
    P, E, N = c.patch_size, c.hidden_size, c.num_patches
    dt = c.dtype

    # patchify: strided conv on the MXU (NCHW x OIHW -> NCHW)
    h = jax.lax.conv_general_dilated(
        x_t.astype(dt), params["patch"]["w"], window_strides=(P, P),
        padding="VALID", dimension_numbers=("NCHW", "OIHW", "NCHW"))
    h = h + params["patch"]["b"].astype(dt)[None, :, None, None]
    h = h.reshape(B, E, N).transpose(0, 2, 1)               # (B, N, E)
    h = (h.astype(jnp.float32) + params["pos_emb"][None]).astype(dt)

    # conditioning vector (f32): timestep + class embedding
    te = timestep_embedding(t, c.freq_embed_size)
    te = jax.nn.silu(te @ params["t_mlp"]["w1"] + params["t_mlp"]["b1"])
    te = te @ params["t_mlp"]["w2"] + params["t_mlp"]["b2"]
    ye = params["y_embed"][y]
    c_vec = te + ye                                          # (B, E)

    block = functools.partial(_block, config=c)
    if c.remat:
        from ._utils import apply_remat
        block = apply_remat(block, c.remat_policy)
    if c.scan_layers:
        def body(x, bp):
            return block(x, c_vec, bp), None
        h, _ = jax.lax.scan(body, h, params["blocks"])
    else:
        for i in range(c.depth):
            bp = jax.tree.map(lambda a: a[i], params["blocks"])
            h = block(h, c_vec, bp)

    # final adaLN + zero-init projection, then unpatchify
    fm = (jax.nn.silu(c_vec) @ params["final"]["w_mod"].astype(jnp.float32)
          + params["final"]["b_mod"])
    fsh, fsc = jnp.split(fm, 2, axis=-1)
    h = _modulate(_layernorm(h), fsh, fsc).astype(dt)
    out = h @ params["final"]["w"] + params["final"]["b"].astype(dt)

    g = c.image_size // P
    out = out.reshape(B, g, g, P, P, c.in_channels)
    out = out.transpose(0, 5, 1, 3, 2, 4).reshape(
        B, c.in_channels, c.image_size, c.image_size)
    return out


# ---------------------------------------------------------------------------
# Training loss (eps-prediction MSE) + batch builder
# ---------------------------------------------------------------------------


def loss_fn(params, batch, config: DiTConfig):
    """batch: {"images": (B,C,H,W) f32 clean data, "labels": (B,) int,
    "timesteps": (B,) int in [1, T], "noise": (B,C,H,W) f32}."""
    x0 = batch["images"].astype(jnp.float32)
    eps = batch["noise"].astype(jnp.float32)
    t = batch["timesteps"]
    ab = alpha_bars(config)[t][:, None, None, None]          # (B,1,1,1)
    x_t = jnp.sqrt(ab) * x0 + jnp.sqrt(1.0 - ab) * eps
    pred = forward(params, x_t, t, batch["labels"], config)
    return jnp.mean((pred.astype(jnp.float32) - eps) ** 2)


def dit_batch(images, labels, key, config: DiTConfig):
    """Sample (timesteps, noise) for a training step — the data-pipeline
    half of the diffusion trainer, kept out of the jitted loss so the step
    stays deterministic in its inputs."""
    kt, kn = jax.random.split(key)
    B = images.shape[0]
    t = jax.random.randint(kt, (B,), 1, config.num_train_timesteps + 1)
    noise = jax.random.normal(kn, images.shape, jnp.float32)
    return {"images": images, "labels": labels,
            "timesteps": t, "noise": noise}


# ---------------------------------------------------------------------------
# DDIM sampling (generation parity; eta=0 deterministic)
# ---------------------------------------------------------------------------


def ddim_sample(params, key, config: DiTConfig, labels, steps: int = 50,
                cfg_scale: float = 1.0):
    """Generate images for `labels` ((B,) int).  cfg_scale > 1 enables
    classifier-free guidance against the null class."""
    c = config
    B = labels.shape[0]
    ab_full = alpha_bars(c)
    ts = jnp.linspace(c.num_train_timesteps, 1, steps).astype(jnp.int32)
    x = jax.random.normal(key, (B, c.in_channels, c.image_size,
                                c.image_size), jnp.float32)

    def pred_eps(x, t_scalar):
        tb = jnp.full((B,), t_scalar, jnp.int32)
        if cfg_scale != 1.0:
            null = jnp.full((B,), c.num_classes, jnp.int32)
            xx = jnp.concatenate([x, x])
            tt = jnp.concatenate([tb, tb])
            yy = jnp.concatenate([labels, null])
            e = forward(params, xx, tt, yy, c).astype(jnp.float32)
            e_cond, e_null = e[:B], e[B:]
            return e_null + cfg_scale * (e_cond - e_null)
        return forward(params, x, tb, labels, c).astype(jnp.float32)

    def step(i, x):
        t = ts[i]
        t_next = jnp.where(i + 1 < steps, ts[jnp.minimum(i + 1, steps - 1)],
                           0)
        ab_t = ab_full[t]
        ab_n = ab_full[t_next]
        eps = pred_eps(x, t)
        x0 = (x - jnp.sqrt(1 - ab_t) * eps) / jnp.sqrt(ab_t)
        x0 = jnp.clip(x0, -4.0, 4.0)
        return jnp.sqrt(ab_n) * x0 + jnp.sqrt(1 - ab_n) * eps

    return jax.lax.fori_loop(0, steps, step, x)


# ---------------------------------------------------------------------------
# Accounting
# ---------------------------------------------------------------------------


def num_params(config: DiTConfig) -> int:
    shapes = jax.eval_shape(lambda: init_params(config, jax.random.PRNGKey(0)))
    return int(sum(np.prod(l.shape) for l in jax.tree.leaves(shapes)))


def flops_per_image(config: DiTConfig) -> float:
    """Forward matmul FLOPs per image (train step ~ 3x this).  Counts the
    transformer (qkv/o/mlp/attention/modulation), patchify and final proj."""
    c = config
    E, F, N, L = c.hidden_size, c.mlp_hidden, c.num_patches, c.depth
    P, C = c.patch_size, c.in_channels
    per_tok_block = 2 * (4 * E * E) + 2 * (2 * E * F) + 4 * N * E
    per_img_block = N * per_tok_block + 2 * E * 6 * E  # + modulation (per img)
    patchify = N * 2 * (P * P * C) * E
    final = N * 2 * E * (P * P * C) + 2 * E * 2 * E
    return float(L * per_img_block + patchify + final)
