"""Shared model-building helpers."""

from __future__ import annotations

import jax


def apply_remat(fn, policy: str = "full"):
    """Wrap a block fn in jax.checkpoint under the named remat policy.

    "full" recomputes the whole block in backward; "save_attn" additionally
    saves tensors tagged `checkpoint_name(x, "attn_out")` (O(S*E)/block
    extra HBM) so recompute of attn_out's CONSUMERS (the wo projection and
    everything downstream in the block) starts from the saved value.  Note
    the attention VJP itself still rematerializes its residuals — q/k/v and
    the qkv matmuls are recomputed either way — which is why on 16 GB v5e
    "full" measured faster for both flagships (see ARCHITECTURE.md round-5
    notes); "save_attn" only pays off where HBM is plentiful and the
    post-attention segment dominates recompute.
    """
    if policy == "save_attn":
        return jax.checkpoint(
            fn, static_argnums=(),
            policy=jax.checkpoint_policies.save_only_these_names("attn_out"))
    if policy == "full":
        return jax.checkpoint(fn, static_argnums=())
    raise ValueError(
        f"remat_policy must be 'full' or 'save_attn', got {policy!r}")
