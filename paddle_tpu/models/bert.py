"""BERT-family encoder: bidirectional transformer with MLM/NSP heads.

Reference analog: PaddleNLP's BERT over the reference framework's
`nn.TransformerEncoder` (python/paddle/nn/layer/transformer.py:443) — the
encoder model family the reference serves besides decoder LMs.  TPU-native
design mirrors models/llama.py: functional pytree params, one jittable
forward, `lax.scan` over layer params so XLA compiles ONE block body
(compile time stays flat in depth), learned position embeddings, post-LN
(the BERT convention).  Padding masks run the XLA attention path (the
Pallas flash kernel currently dispatches only for mask=None and D%128==0;
BERT's D=64 takes XLA either way, where the mask fuses into the softmax).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from .. import kernels

__all__ = ["BertConfig", "init_params", "forward", "mlm_loss_fn",
           "num_params"]


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    dtype: object = jnp.float32
    remat: bool = False

    @property
    def hd(self) -> int:
        return self.hidden_size // self.num_attention_heads

    @staticmethod
    def tiny(vocab_size: int = 256) -> "BertConfig":
        return BertConfig(vocab_size=vocab_size, hidden_size=64,
                          num_hidden_layers=2, num_attention_heads=4,
                          intermediate_size=128, max_position_embeddings=64)

    @staticmethod
    def base() -> "BertConfig":
        return BertConfig()

    @staticmethod
    def large() -> "BertConfig":
        return BertConfig(hidden_size=1024, num_hidden_layers=24,
                          num_attention_heads=16, intermediate_size=4096)


def _normal(key, shape, std, dtype):
    return (jax.random.normal(key, shape, jnp.float32) * std).astype(dtype)


def init_params(config: BertConfig, key=None, seed: int = 0):
    c = config
    if key is None:
        key = jax.random.PRNGKey(seed)
    ks = jax.random.split(key, 8)
    E, L = c.hidden_size, c.num_hidden_layers
    std = 0.02

    def blk(k, shape):
        return _normal(k, shape, std, c.dtype)

    bk = jax.random.split(ks[7], 6)
    # stacked (L, ...) leaves: forward scans over layers
    blocks = {
        "wqkv": blk(bk[0], (L, E, 3 * E)),
        "wo": blk(bk[1], (L, E, E)),
        "w_in": blk(bk[2], (L, E, c.intermediate_size)),
        "w_out": blk(bk[3], (L, c.intermediate_size, E)),
        "b_qkv": jnp.zeros((L, 3 * E), c.dtype),
        "b_o": jnp.zeros((L, E), c.dtype),
        "b_in": jnp.zeros((L, c.intermediate_size), c.dtype),
        "b_out": jnp.zeros((L, E), c.dtype),
        "ln1_g": jnp.ones((L, E), jnp.float32),
        "ln1_b": jnp.zeros((L, E), jnp.float32),
        "ln2_g": jnp.ones((L, E), jnp.float32),
        "ln2_b": jnp.zeros((L, E), jnp.float32),
    }
    return {
        "tok_embed": blk(ks[0], (c.vocab_size, E)),
        "pos_embed": blk(ks[1], (c.max_position_embeddings, E)),
        "type_embed": blk(ks[2], (c.type_vocab_size, E)),
        "embed_ln_g": jnp.ones((E,), jnp.float32),
        "embed_ln_b": jnp.zeros((E,), jnp.float32),
        "blocks": blocks,
        "pooler_w": blk(ks[3], (E, E)),
        "pooler_b": jnp.zeros((E,), c.dtype),
        # MLM head: transform + decoder bias (weights tied to tok_embed)
        "mlm_w": blk(ks[4], (E, E)),
        "mlm_b": jnp.zeros((E,), c.dtype),
        "mlm_ln_g": jnp.ones((E,), jnp.float32),
        "mlm_ln_b": jnp.zeros((E,), jnp.float32),
        "mlm_bias": jnp.zeros((c.vocab_size,), jnp.float32),
        "nsp_w": blk(ks[5], (E, 2)),
        "nsp_b": jnp.zeros((2,), c.dtype),
    }


def _ln(x, g, b, eps):
    # f32 statistics regardless of activation dtype (XLA fuses this chain)
    xf = x.astype(jnp.float32)
    mu = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mu), axis=-1, keepdims=True)
    out = (xf - mu) * jax.lax.rsqrt(var + eps)
    return (out * g.astype(jnp.float32) + b.astype(jnp.float32)).astype(x.dtype)


def _block(c: BertConfig, x, lp, attn_mask):
    B, S, E = x.shape
    H, D = c.num_attention_heads, c.hd
    qkv = x @ lp["wqkv"] + lp["b_qkv"]
    q, k, v = (a.reshape(B, S, H, D) for a in jnp.split(qkv, 3, axis=-1))
    attn = kernels.attention(q, k, v, mask=attn_mask, causal=False)
    x = _ln(x + (attn.reshape(B, S, E) @ lp["wo"] + lp["b_o"]),
            lp["ln1_g"], lp["ln1_b"], c.layer_norm_eps)
    h = jax.nn.gelu(x @ lp["w_in"] + lp["b_in"], approximate=True)
    return _ln(x + (h @ lp["w_out"] + lp["b_out"]),
               lp["ln2_g"], lp["ln2_b"], c.layer_norm_eps)


def forward(params, input_ids, config: BertConfig, token_type_ids=None,
            attention_mask=None):
    """Encoder forward.

    attention_mask: (B, S) 1/0 padding mask (HF/Paddle convention) or None.
    Returns (sequence_output (B, S, E), pooled_output (B, E)).
    """
    c = config
    B, S = input_ids.shape
    x = jnp.take(params["tok_embed"], input_ids, axis=0)
    x = x + params["pos_embed"][None, :S]
    if token_type_ids is None:
        token_type_ids = jnp.zeros_like(input_ids)
    x = x + jnp.take(params["type_embed"], token_type_ids, axis=0)
    x = _ln(x, params["embed_ln_g"], params["embed_ln_b"], c.layer_norm_eps)

    mask = None
    if attention_mask is not None:
        # (B, S) keep-mask -> (B, 1, 1, S) bool over the key axis
        keep = attention_mask.astype(bool)
        # a fully-padded row would make every key -inf -> NaN softmax whose
        # backward poisons ALL gradients; attend uniformly instead (those
        # outputs are pad positions the loss ignores anyway)
        keep = keep | ~keep.any(axis=-1, keepdims=True)
        mask = keep[:, None, None, :]

    body = functools.partial(_block, c, attn_mask=mask)
    if c.remat:
        body = jax.checkpoint(body)

    def scan_body(h, lp):
        return body(h, lp), None

    x, _ = jax.lax.scan(scan_body, x, params["blocks"])
    pooled = jnp.tanh(x[:, 0] @ params["pooler_w"] + params["pooler_b"])
    return x, pooled


def mlm_loss_fn(params, batch, config: BertConfig):
    """Masked-LM + NSP loss.  batch: dict with input_ids, labels
    (-100 = unmasked), optional token_type_ids / attention_mask /
    next_sentence_label."""
    seq, pooled = forward(params, batch["input_ids"], config,
                          batch.get("token_type_ids"),
                          batch.get("attention_mask"))
    h = jax.nn.gelu(seq @ params["mlm_w"] + params["mlm_b"],
                    approximate=True)
    h = _ln(h, params["mlm_ln_g"], params["mlm_ln_b"],
            config.layer_norm_eps)
    logits = (h @ params["tok_embed"].T.astype(h.dtype)
              ).astype(jnp.float32) + params["mlm_bias"]
    labels = batch["labels"]
    valid = labels != -100
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, jnp.maximum(labels, 0)[..., None], axis=-1)[..., 0]
    nll = jnp.where(valid, lse - picked, 0.0)
    loss = nll.sum() / jnp.maximum(valid.sum(), 1)
    nsp = batch.get("next_sentence_label")
    if nsp is not None:
        nsp_logits = (pooled @ params["nsp_w"] + params["nsp_b"]
                      ).astype(jnp.float32)
        nsp_lse = jax.nn.logsumexp(nsp_logits, axis=-1)
        nsp_picked = jnp.take_along_axis(
            nsp_logits, nsp[:, None], axis=-1)[..., 0]
        nsp_nll = nsp_lse - nsp_picked
        am = batch.get("attention_mask")
        if am is not None:
            # fully-padded rows (ragged last batch) are not sentences:
            # exclude them from the NSP mean, like labels=-100 does for MLM
            row_ok = am.astype(bool).any(axis=-1)
            loss = loss + (jnp.where(row_ok, nsp_nll, 0.0).sum()
                           / jnp.maximum(row_ok.sum(), 1))
        else:
            loss = loss + jnp.mean(nsp_nll)
    return loss


def num_params(config: BertConfig) -> int:
    from . import llama
    return llama.num_params(config, init_fn=init_params)


def loss_fn(params, batch, config: BertConfig):
    """ShardedTrainState-compatible alias (same module interface as llama)."""
    return mlm_loss_fn(params, batch, config)


def param_logical_axes(config: BertConfig):
    """Logical sharding axes per parameter (llama.param_logical_axes
    vocabulary: vocab/embed/mlp/heads/layer/None -> mesh.LOGICAL_RULES)."""
    return {
        "tok_embed": ("vocab", "embed"),
        "pos_embed": (None, "embed"),
        "type_embed": (None, "embed"),
        "embed_ln_g": (None,),
        "embed_ln_b": (None,),
        "blocks": {
            "wqkv": ("layer", "embed", "heads"),
            "wo": ("layer", "heads", "embed"),
            "w_in": ("layer", "embed", "mlp"),
            "w_out": ("layer", "mlp", "embed"),
            "b_qkv": ("layer", "heads"),
            "b_o": ("layer", None),
            "b_in": ("layer", "mlp"),
            "b_out": ("layer", None),
            "ln1_g": ("layer", None),
            "ln1_b": ("layer", None),
            "ln2_g": ("layer", None),
            "ln2_b": ("layer", None),
        },
        "pooler_w": ("embed", "embed"),
        "pooler_b": (None,),
        "mlm_w": ("embed", "embed"),
        "mlm_b": (None,),
        "mlm_ln_g": (None,),
        "mlm_ln_b": (None,),
        "mlm_bias": ("vocab",),
        "nsp_w": ("embed", None),
        "nsp_b": (None,),
    }
