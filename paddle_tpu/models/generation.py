"""Decode path: KV-cache generation for the Llama family.

Reference analog: the decode-phase attention kernel the reference ships as
CUDA (`masked_multihead_attention`, phi/kernels/fusion/gpu/
masked_multihead_attention_kernel.cu, surfaced at
incubate/nn/functional/masked_multihead_attention.py) plus PaddleNLP's
generation loop over the inference predictor
(fluid/inference/api/analysis_predictor.h:94).

TPU-native design: a STATIC-shape KV cache (L, B, max_len, Hkv, D) updated
with `lax.dynamic_update_slice`, decode loop as `lax.scan` — one compiled
program for the whole generation, no per-token retrace.  GQA attends at Hkv
width via grouped einsum (no head expansion).  Sampling (greedy /
temperature / top-k / top-p) is jittable and keyed.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .. import kernels
from . import llama as llama_lib


def init_kv_cache(config, batch: int, max_len: int):
    """Zeroed (L, B, max_len, Hkv, D) k/v buffers in the model dtype."""
    c = config
    shape = (c.num_hidden_layers, batch, max_len, c.num_key_value_heads, c.hd)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def _cache_attention(q, ck, cv, pos, slot_mask=None):
    """q: (B, S, Hq, D) at cache slots [pos, pos+S); ck/cv: (B, M, Hkv, D)
    full cache (already containing this step's k/v).  Causal over the cache
    prefix: query i attends to cache slots j <= pos + i.  slot_mask: optional
    (B, M) keep-mask excluding left-pad slots (variable-length batches)."""
    B, S, Hq, D = q.shape
    M, Hkv = ck.shape[1], ck.shape[2]
    rep = Hq // Hkv
    scale = 1.0 / (D ** 0.5)
    qg = q.reshape(B, S, Hkv, rep, D).astype(jnp.float32) * scale
    s = jnp.einsum("bqhrd,bkhd->bhrqk", qg, ck.astype(jnp.float32))
    qpos = pos + jax.lax.broadcasted_iota(jnp.int32, (S, M), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (S, M), 1)
    keep = (kpos <= qpos)[None]                       # (1, S, M)
    if slot_mask is not None:
        keep = keep & slot_mask[:, None, :]           # (B, S, M)
    s = jnp.where(keep[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhrqk,bkhd->bqhrd", p, cv.astype(jnp.float32))
    return o.reshape(B, S, Hq, D).astype(q.dtype)


def _block_with_cache(c, x, lp, cos, sin, ck, cv, pos, ffn_fn=None,
                      slot_mask=None):
    """One block in cached mode.  ck/cv: (B, M, Hkv, D); returns updated.
    cos/sin are (S, D/2) shared or (B, S, D/2) per-row tables — llama's
    _apply_rope handles both."""
    B, S, E = x.shape
    D, Hq, Hkv = c.hd, c.num_attention_heads, c.num_key_value_heads
    h = kernels.rms_norm(x, lp["input_norm"].astype(jnp.float32),
                         c.rms_norm_eps).astype(x.dtype)
    q = (h @ lp["wq"]).reshape(B, S, Hq, D)
    k = (h @ lp["wk"]).reshape(B, S, Hkv, D)
    v = (h @ lp["wv"]).reshape(B, S, Hkv, D)
    q = llama_lib._apply_rope(q, cos, sin)
    k = llama_lib._apply_rope(k, cos, sin)
    ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (0, pos, 0, 0))
    cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (0, pos, 0, 0))
    attn = _cache_attention(q, ck, cv, pos, slot_mask=slot_mask)
    x = x + (attn.reshape(B, S, Hq * D) @ lp["wo"])
    h = kernels.rms_norm(x, lp["post_norm"].astype(jnp.float32),
                         c.rms_norm_eps).astype(x.dtype)
    if ffn_fn is not None:
        out, _aux = ffn_fn(h, lp)
        return x + out.astype(x.dtype), ck, cv
    gate = h @ lp["w_gate"]
    up = h @ lp["w_up"]
    return x + ((jax.nn.silu(gate) * up) @ lp["w_down"]).astype(x.dtype), ck, cv


def forward_with_cache(params, input_ids, config, cache, pos, ffn_fn=None,
                       positions=None, slot_mask=None):
    """Cached forward for prefill (S>=1) or decode (S=1) at cache offset
    `pos`.  positions: optional (B, S) PER-ROW rope positions (left-padded
    variable-length batches, where cache slot != sequence position);
    slot_mask: optional (B, M) keep-mask over cache slots.

    Returns (logits (B, S, V) f32, updated cache)."""
    c = config
    x = jnp.take(params["embed"]["weight"], input_ids, axis=0)
    S = input_ids.shape[1]
    cos_f, sin_f = llama_lib._rope_tables(c.hd, c.max_position_embeddings,
                                          c.rope_theta)
    d2 = cos_f.shape[-1]
    if positions is None:
        cos = jax.lax.dynamic_slice(cos_f, (pos, 0), (S, d2))
        sin = jax.lax.dynamic_slice(sin_f, (pos, 0), (S, d2))
    else:
        cos = jnp.take(cos_f, positions, axis=0)   # (B, S, d2)
        sin = jnp.take(sin_f, positions, axis=0)

    def body(x, layer):
        lp, ck, cv = layer
        x, ck, cv = _block_with_cache(c, x, lp, cos, sin, ck, cv, pos,
                                      ffn_fn=ffn_fn, slot_mask=slot_mask)
        return x, (ck, cv)

    x, (ck_new, cv_new) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = kernels.rms_norm(x, params["final_norm"].astype(jnp.float32),
                         c.rms_norm_eps)
    head = (params["embed"]["weight"].T if c.tie_word_embeddings
            else params["lm_head"])
    logits = (x @ head.astype(x.dtype)).astype(jnp.float32)
    return logits, {"k": ck_new, "v": cv_new}


def sample_logits(logits, key, temperature: float = 1.0, top_k: int = 0,
                  top_p: float = 1.0):
    """Jittable sampling: greedy (temperature == 0) / temperature /
    top-k / nucleus.  logits: (B, V) f32 -> (B,) int32."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / jnp.float32(max(temperature, 1e-6))
    V = logits.shape[-1]
    if top_k and top_k < V:
        kth = jnp.sort(logits, axis=-1)[:, V - top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set whose mass >= top_p: keep while cum - p < top_p
        keep = (cum - probs) < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_l, jnp.inf), axis=-1)[:, None]
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=(
    "config", "max_new_tokens", "temperature", "top_k", "top_p", "eos_id"))
def generate(params, input_ids, config, max_new_tokens: int,
             temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
             eos_id: Optional[int] = None, key: Optional[Any] = None,
             attention_mask=None):
    """Prefill + scan-decode.  input_ids: (B, S) prompts — equal-length, or
    LEFT-padded variable-length with `attention_mask` (B, S) marking real
    tokens (HF/PaddleNLP convention; left padding keeps every row's last
    real token in the final column, so one gather serves all rows).

    Returns (B, max_new_tokens) int32 — after eos (when given), positions
    are padded with eos.  One compiled program; cache is static-shaped
    S + max_new_tokens."""
    c = config
    B, S = input_ids.shape
    if key is None:
        key = jax.random.PRNGKey(0)
    cache = init_kv_cache(c, B, S + max_new_tokens)

    positions = slot_mask = None
    pos_last = None
    if attention_mask is not None:
        am = attention_mask.astype(jnp.int32)
        # rope position of column j = (# real tokens before j); pad columns
        # clamp to 0 (their k/v are excluded by slot_mask anyway)
        positions = jnp.maximum(jnp.cumsum(am, axis=1) - 1, 0)
        pos_last = positions[:, -1]                    # (B,) last real pos
        # static full-length slot mask: prompt slots follow the mask,
        # generated slots (>= S) are always real
        slot_mask = jnp.concatenate(
            [am.astype(bool),
             jnp.ones((B, max_new_tokens), bool)], axis=1)

    logits, cache = forward_with_cache(params, input_ids, c, cache, 0,
                                       positions=positions,
                                       slot_mask=slot_mask)
    next_tok = sample_logits(logits[:, -1], key, temperature, top_k, top_p)

    def step(carry, i):
        cache, tok, done, key = carry
        key, sub = jax.random.split(key)
        # `tok` was sampled at step i-1 and occupies CACHE slot S+i-1; its
        # rope position is S+i-1 for dense prompts, last_real_pos+i when
        # left-padded
        step_positions = (None if pos_last is None
                          else (pos_last + i)[:, None])
        logits, cache = forward_with_cache(
            params, tok[:, None], c, cache, S + i - 1,
            positions=step_positions, slot_mask=slot_mask)
        nxt = sample_logits(logits[:, -1], sub, temperature, top_k, top_p)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return (cache, nxt, done, key), tok

    done0 = (jnp.zeros((B,), bool) if eos_id is None
             else (next_tok == eos_id))
    (_, last, _, _), toks = jax.lax.scan(
        step, (cache, next_tok, done0, key), jnp.arange(1, max_new_tokens))
    out = jnp.concatenate([jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1)
    return out
