"""Decode path: KV-cache generation for the Llama family.

Reference analog: the decode-phase attention kernel the reference ships as
CUDA (`masked_multihead_attention`, phi/kernels/fusion/gpu/
masked_multihead_attention_kernel.cu, surfaced at
incubate/nn/functional/masked_multihead_attention.py) plus PaddleNLP's
generation loop over the inference predictor
(fluid/inference/api/analysis_predictor.h:94).

TPU-native design: a STATIC-shape KV cache (L, B, max_len, Hkv, D) updated
with `lax.dynamic_update_slice`, decode loop as `lax.scan` — one compiled
program for the whole generation, no per-token retrace.  GQA attends at Hkv
width via grouped einsum (no head expansion).  Sampling (greedy /
temperature / top-k / top-p) is jittable and keyed.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import kernels
from . import llama as llama_lib


def init_kv_cache(config, batch: int, max_len: int):
    """Zeroed (L, B, max_len, Hkv, D) k/v buffers in the model dtype."""
    c = config
    shape = (c.num_hidden_layers, batch, max_len, c.num_key_value_heads, c.hd)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


def _cache_attention(q, ck, cv, pos, slot_mask=None):
    """q: (B, S, Hq, D) at cache slots [pos, pos+S); ck/cv: (B, M, Hkv, D)
    full cache (already containing this step's k/v).  Causal over the cache
    prefix: query i attends to cache slots j <= pos + i.  slot_mask: optional
    (B, M) keep-mask excluding left-pad slots (variable-length batches)."""
    B, S, Hq, D = q.shape
    M, Hkv = ck.shape[1], ck.shape[2]
    rep = Hq // Hkv
    scale = 1.0 / (D ** 0.5)
    qg = q.reshape(B, S, Hkv, rep, D).astype(jnp.float32) * scale
    s = jnp.einsum("bqhrd,bkhd->bhrqk", qg, ck.astype(jnp.float32))
    qpos = pos + jax.lax.broadcasted_iota(jnp.int32, (S, M), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (S, M), 1)
    keep = (kpos <= qpos)[None]                       # (1, S, M)
    if slot_mask is not None:
        keep = keep & slot_mask[:, None, :]           # (B, S, M)
    s = jnp.where(keep[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhrqk,bkhd->bqhrd", p, cv.astype(jnp.float32))
    return o.reshape(B, S, Hq, D).astype(q.dtype)


def _block_with_cache(c, x, lp, cos, sin, ck, cv, pos, ffn_fn=None,
                      slot_mask=None):
    """One block in cached mode.  ck/cv: (B, M, Hkv, D); returns updated.
    cos/sin are (S, D/2) shared or (B, S, D/2) per-row tables — llama's
    _apply_rope handles both."""
    B, S, E = x.shape
    D, Hq, Hkv = c.hd, c.num_attention_heads, c.num_key_value_heads
    h = kernels.rms_norm(x, lp["input_norm"].astype(jnp.float32),
                         c.rms_norm_eps).astype(x.dtype)
    q = (h @ lp["wq"]).reshape(B, S, Hq, D)
    k = (h @ lp["wk"]).reshape(B, S, Hkv, D)
    v = (h @ lp["wv"]).reshape(B, S, Hkv, D)
    q = llama_lib._apply_rope(q, cos, sin)
    k = llama_lib._apply_rope(k, cos, sin)
    # pos may be a python int (one compile per prefill) OR a traced i32
    # scalar (serving decode loops reuse ONE compiled step across
    # positions); index tuples must be type-homogeneous under x64
    z = jnp.int32(0)
    p = jnp.asarray(pos, jnp.int32)
    ck = jax.lax.dynamic_update_slice(ck, k.astype(ck.dtype), (z, p, z, z))
    cv = jax.lax.dynamic_update_slice(cv, v.astype(cv.dtype), (z, p, z, z))
    attn = _cache_attention(q, ck, cv, pos, slot_mask=slot_mask)
    x = x + (attn.reshape(B, S, Hq * D) @ lp["wo"])
    h = kernels.rms_norm(x, lp["post_norm"].astype(jnp.float32),
                         c.rms_norm_eps).astype(x.dtype)
    if ffn_fn is not None:
        out, _aux = ffn_fn(h, lp)
        return x + out.astype(x.dtype), ck, cv
    gate = h @ lp["w_gate"]
    up = h @ lp["w_up"]
    # silu in fp32, matching the train path (llama._block): bf16 decode
    # must not drift from bf16 training numerics
    mlp = (jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up) \
        @ lp["w_down"]
    return x + mlp.astype(x.dtype), ck, cv


def forward_with_cache(params, input_ids, config, cache, pos, ffn_fn=None,
                       positions=None, slot_mask=None):
    """Cached forward for prefill (S>=1) or decode (S=1) at cache offset
    `pos`.  positions: optional (B, S) PER-ROW rope positions (left-padded
    variable-length batches, where cache slot != sequence position);
    slot_mask: optional (B, M) keep-mask over cache slots.

    Returns (logits (B, S, V) f32, updated cache)."""
    c = config
    x = jnp.take(params["embed"]["weight"], input_ids, axis=0)
    S = input_ids.shape[1]
    cos_f, sin_f = llama_lib._rope_tables(c.hd, c.max_position_embeddings,
                                          c.rope_theta)
    d2 = cos_f.shape[-1]
    if positions is None:
        start = (jnp.asarray(pos, jnp.int32), jnp.int32(0))
        cos = jax.lax.dynamic_slice(cos_f, start, (S, d2))
        sin = jax.lax.dynamic_slice(sin_f, start, (S, d2))
    else:
        cos = jnp.take(cos_f, positions, axis=0)   # (B, S, d2)
        sin = jnp.take(sin_f, positions, axis=0)

    def body(x, layer):
        lp, ck, cv = layer
        x, ck, cv = _block_with_cache(c, x, lp, cos, sin, ck, cv, pos,
                                      ffn_fn=ffn_fn, slot_mask=slot_mask)
        return x, (ck, cv)

    x, (ck_new, cv_new) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = kernels.rms_norm(x, params["final_norm"].astype(jnp.float32),
                         c.rms_norm_eps)
    head = (params["embed"]["weight"].T if c.tie_word_embeddings
            else params["lm_head"])
    logits = (x @ head.astype(x.dtype)).astype(jnp.float32)
    return logits, {"k": ck_new, "v": cv_new}


def filter_logits(logits, temperature: float = 1.0, top_k: int = 0,
                  top_p: float = 1.0):
    """Jittable temperature / top-k / nucleus filtering — the ONE
    device-side definition of the sampling distribution, shared by
    `sample_logits`, the fused decode-step kernel
    (kernels/pallas_decode_step.py) and its fallback, so the fused and
    unfused engine paths sample from identical logits by construction.
    logits: (B, V) f32 -> (B, V) f32 with masked entries at -inf.
    temperature == 0 is the caller's greedy case: filtering is an
    identity there (argmax ignores scale)."""
    if temperature == 0.0:
        return logits
    logits = logits / jnp.float32(max(temperature, 1e-6))
    V = logits.shape[-1]
    if top_k and top_k < V:
        kth = jnp.sort(logits, axis=-1)[:, V - top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_l, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set whose mass >= top_p: keep while cum - p < top_p
        keep = (cum - probs) < top_p
        cutoff = jnp.min(jnp.where(keep, sorted_l, jnp.inf), axis=-1)[:, None]
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return logits


def sample_logits(logits, key, temperature: float = 1.0, top_k: int = 0,
                  top_p: float = 1.0):
    """Jittable sampling: greedy (temperature == 0) / temperature /
    top-k / nucleus.  logits: (B, V) f32 -> (B,) int32."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    filtered = filter_logits(logits, temperature, top_k, top_p)
    return jax.random.categorical(key, filtered, axis=-1).astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=(
    "config", "max_new_tokens", "temperature", "top_k", "top_p", "eos_id"))
def generate(params, input_ids, config, max_new_tokens: int,
             temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
             eos_id: Optional[int] = None, key: Optional[Any] = None,
             attention_mask=None):
    """Prefill + scan-decode.  input_ids: (B, S) prompts — equal-length, or
    LEFT-padded variable-length with `attention_mask` (B, S) marking real
    tokens (HF/PaddleNLP convention; left padding keeps every row's last
    real token in the final column, so one gather serves all rows).

    Returns (B, max_new_tokens) int32 — after eos (when given), positions
    are padded with eos.  One compiled program; cache is static-shaped
    S + max_new_tokens."""
    c = config
    B, S = input_ids.shape
    if key is None:
        key = jax.random.PRNGKey(0)
    cache = init_kv_cache(c, B, S + max_new_tokens)

    positions = slot_mask = None
    pos_last = None
    if attention_mask is not None:
        am = attention_mask.astype(jnp.int32)
        # rope position of column j = (# real tokens before j); pad columns
        # clamp to 0 (their k/v are excluded by slot_mask anyway)
        positions = jnp.maximum(jnp.cumsum(am, axis=1) - 1, 0)
        pos_last = positions[:, -1]                    # (B,) last real pos
        # static full-length slot mask: prompt slots follow the mask,
        # generated slots (>= S) are always real
        slot_mask = jnp.concatenate(
            [am.astype(bool),
             jnp.ones((B, max_new_tokens), bool)], axis=1)

    logits, cache = forward_with_cache(params, input_ids, c, cache, 0,
                                       positions=positions,
                                       slot_mask=slot_mask)
    next_tok = sample_logits(logits[:, -1], key, temperature, top_k, top_p)

    def step(carry, i):
        cache, tok, done, key = carry
        key, sub = jax.random.split(key)
        # `tok` was sampled at step i-1 and occupies CACHE slot S+i-1; its
        # rope position is S+i-1 for dense prompts, last_real_pos+i when
        # left-padded
        step_positions = (None if pos_last is None
                          else (pos_last + i)[:, None])
        logits, cache = forward_with_cache(
            params, tok[:, None], c, cache, S + i - 1,
            positions=step_positions, slot_mask=slot_mask)
        nxt = sample_logits(logits[:, -1], sub, temperature, top_k, top_p)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return (cache, nxt, done, key), tok

    done0 = (jnp.zeros((B,), bool) if eos_id is None
             else (next_tok == eos_id))
    (_, last, _, _), toks = jax.lax.scan(
        step, (cache, next_tok, done0, key), jnp.arange(1, max_new_tokens))
    out = jnp.concatenate([jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1)
    return out


# ---------------------------------------------------------------------------
# Paged KV cache: block-paged pools + page tables (the serving decode path)
# ---------------------------------------------------------------------------


def init_paged_kv_pools(config, num_pages: int, page_size: int):
    """Zeroed (L, num_pages, page_size, Hkv, D) k/v page pools."""
    c = config
    shape = (c.num_hidden_layers, num_pages, page_size,
             c.num_key_value_heads, c.hd)
    return {"k": jnp.zeros(shape, c.dtype), "v": jnp.zeros(shape, c.dtype)}


class PagedKVCache:
    """Host-side page allocator over device-side page pools.

    HBM is carved into `num_pages` pages of `page_size` tokens; a sequence
    occupying a decode *slot* owns ceil(len/page_size) pages listed in its
    page-table row.  Pages are allocated on demand (`ensure_capacity`) and
    reclaimed on eviction (`release_slot`) — memory scales with the tokens
    actually resident, not num_slots * max_len.

    Pages are REFCOUNTED with copy-on-write semantics (cross-user prefix
    reuse): a page may appear in many slots' page lists and in the prefix
    index at once, each holder counted in `_refcount`.  `splice_pages`
    installs an already-prefilled prefix into a fresh slot (refcount + 1
    per page, no KV computed); release/truncate only return a page to the
    free pool when its count drops to zero; and a slot that must APPEND
    into a shared page first copies it privately (`cow_page` does the
    bookkeeping, the engine runs the device copy).  A page is writable by
    a slot iff its refcount is exactly 1.

    Page-table invariants (the Pallas kernel relies on these):
      * page 0 is RESERVED scratch — never allocated; empty slots point
        every entry (and their writes) at it;
      * entries past a slot's allocated range repeat the last allocated
        page, so skipped grid steps index a valid page and the Pallas
        pipeline elides the re-fetch.
    """

    def __init__(self, config, num_pages: int, page_size: int,
                 max_slots: int, pages_per_seq: int):
        if num_pages < 2:
            raise ValueError("num_pages must be >= 2 (page 0 is reserved)")
        self.config = config
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.pages_per_seq = int(pages_per_seq)
        self.max_slots = int(max_slots)
        self.pools = init_paged_kv_pools(config, num_pages, page_size)
        self.page_table = jnp.zeros((max_slots, pages_per_seq), jnp.int32)
        self._free_pages = list(range(num_pages - 1, 0, -1))  # page 0 reserved
        self._free_slots = list(range(max_slots - 1, -1, -1))
        self._slot_pages: dict[int, list] = {}
        self._refcount: dict[int, int] = {}   # page -> holders (slots+index)

    @property
    def free_page_count(self) -> int:
        return len(self._free_pages)

    @property
    def free_slot_count(self) -> int:
        return len(self._free_slots)

    def pages_needed(self, n_tokens: int) -> int:
        return -(-max(int(n_tokens), 0) // self.page_size)

    def acquire_slot(self) -> int:
        if not self._free_slots:
            raise RuntimeError("no free decode slots")
        slot = self._free_slots.pop()
        self._slot_pages[slot] = []
        return slot

    # -- refcount primitives (prefix sharing rides these) -------------------

    def refcount(self, page: int) -> int:
        """Current holder count for `page` (0 = free or never allocated)."""
        return self._refcount.get(int(page), 0)

    def add_ref(self, page: int) -> None:
        """Take one more reference on an ALLOCATED page (a prefix-index
        node or a splicing slot becoming a co-holder)."""
        page = int(page)
        rc = self._refcount.get(page, 0)
        if page == 0 or rc < 1:
            raise RuntimeError(
                f"add_ref on page {page} with refcount {rc} (free, "
                "reserved, or never allocated)")
        self._refcount[page] = rc + 1

    def drop_ref(self, page: int) -> bool:
        """Release one reference; returns the page to the free pool when
        the count hits zero.  Returns True iff the page was freed."""
        page = int(page)
        rc = self._refcount.get(page, 0)
        if rc < 1:
            raise RuntimeError(
                f"drop_ref on page {page} with refcount {rc} "
                "(double free)")
        if rc == 1:
            del self._refcount[page]
            self._free_pages.append(page)
            return True
        self._refcount[page] = rc - 1
        return False

    def _alloc_page(self) -> int:
        page = self._free_pages.pop()
        self._refcount[page] = 1
        return page

    def alloc_pages(self, n: int) -> list:
        """Allocate `n` caller-owned pages (refcount 1 each) outside any
        slot — the KV import path (prefix promotion / disaggregated
        handoff) scatters host KV into them and hands ownership to the
        prefix index.  All-or-nothing: raises RuntimeError without
        allocating when the pool cannot cover `n`.  The caller MUST end
        every page's life with `drop_ref` (directly, or via the index
        after `insert` took its own refs)."""
        n = int(n)
        if n > len(self._free_pages):
            raise RuntimeError(
                f"page pool exhausted ({n} pages requested, "
                f"{len(self._free_pages)} free)")
        return [self._alloc_page() for _ in range(n)]

    def _write_row(self, slot: int) -> None:
        pages = self._slot_pages[slot]
        row = pages + [pages[-1] if pages else 0] * \
            (self.pages_per_seq - len(pages))
        self.page_table = self.page_table.at[slot].set(
            jnp.asarray(row, jnp.int32))

    def ensure_capacity(self, slot: int, n_tokens: int) -> None:
        """Grow slot's page list to cover n_tokens, updating its page-table
        row.  Raises RuntimeError when the pool is exhausted (callers queue
        the request instead of admitting it)."""
        pages = self._slot_pages[slot]
        need = self.pages_needed(n_tokens)
        if need > self.pages_per_seq:
            raise RuntimeError(
                f"{n_tokens} tokens exceed pages_per_seq={self.pages_per_seq}"
                f" * page_size={self.page_size}")
        if need <= len(pages):
            return
        if need - len(pages) > len(self._free_pages):
            raise RuntimeError("page pool exhausted")
        while len(pages) < need:
            pages.append(self._alloc_page())
        self._write_row(slot)

    def splice_pages(self, slot: int, pages) -> None:
        """Install an already-prefilled page chain into an EMPTY slot (the
        prefix-index hit path): each page gains one reference; no KV is
        computed or copied.  The splicing slot must treat any page whose
        refcount exceeds 1 as read-only (`cow_page` before appending)."""
        lst = self._slot_pages[slot]
        if lst:
            raise RuntimeError(
                f"splice into slot {slot} that already holds pages {lst}")
        if len(pages) > self.pages_per_seq:
            raise RuntimeError(
                f"cannot splice {len(pages)} pages (pages_per_seq="
                f"{self.pages_per_seq})")
        for p in pages:
            self.add_ref(p)
        lst.extend(int(p) for p in pages)
        self._write_row(slot)

    def cow_page(self, slot: int, index: int):
        """Copy-on-write bookkeeping for the slot's `index`-th page: if it
        is shared (refcount > 1), allocate a private replacement, swap it
        into the slot's list/page-table row, and return (src, dst) so the
        caller can run the device page copy.  Returns None when the page
        is already exclusively owned.  Raises RuntimeError when the pool
        has no page for the copy (callers reclaim/preempt and retry)."""
        pages = self._slot_pages[slot]
        src = pages[index]
        if self._refcount.get(src, 0) <= 1:
            return None
        if not self._free_pages:
            raise RuntimeError("page pool exhausted (copy-on-write)")
        dst = self._alloc_page()
        pages[index] = dst
        self.drop_ref(src)
        self._write_row(slot)
        return src, dst

    def truncate_slot(self, slot: int, n_tokens: int) -> int:
        """Logically retire cached tokens past `n_tokens`: release the
        slot's TRAILING pages no longer needed and shrink its page-table
        row.  This is the speculative-decoding rollback — pages are
        append-only by position, so rejected draft tokens are retired by
        pure length bookkeeping: the kernel's ctx_len masking already
        guarantees slots past the sequence length are never read, and the
        next span overwrites them in place.  A released page returns to
        the free pool only once its LAST holder lets go (a spliced prefix
        page survives in the index and its co-holders).  Returns the
        number of pages this slot released."""
        pages = self._slot_pages[slot]
        need = self.pages_needed(n_tokens)
        freed = 0
        while len(pages) > max(need, 1) and pages:
            self.drop_ref(pages.pop())
            freed += 1
        if freed:
            self._write_row(slot)
        return freed

    def release_slot(self, slot: int) -> None:
        for p in self._slot_pages.pop(slot):
            self.drop_ref(p)
        self._free_slots.append(slot)
        self.page_table = self.page_table.at[slot].set(0)


def scatter_prefill_into_pages(cache, pools, page_table, seq_len: int,
                               true_len=None):
    """Scatter a dense prefill cache {"k","v"}: (L, B, S, Hkv, D) into the
    page pools.  Token j of row b lands at (page_table[b, j//ps], j%ps).
    true_len: optional (B,) — right-padded rows scatter positions >=
    true_len[b] into the reserved scratch page 0 instead."""
    ps = pools["k"].shape[2]
    B = cache["k"].shape[1]
    j = jnp.arange(seq_len, dtype=jnp.int32)
    pidx = jnp.take_along_axis(page_table,
                               jnp.broadcast_to((j // ps)[None], (B, seq_len)),
                               axis=1)                      # (B, S)
    if true_len is not None:
        pidx = jnp.where(j[None] < true_len[:, None], pidx, 0)
    poff = jnp.broadcast_to((j % ps)[None], (B, seq_len))
    return {
        "k": pools["k"].at[:, pidx, poff].set(
            cache["k"].astype(pools["k"].dtype)),
        "v": pools["v"].at[:, pidx, poff].set(
            cache["v"].astype(pools["v"].dtype)),
    }


def pad_page_idx(pages, pages_per_seq: int) -> np.ndarray:
    """The fixed-shape page-index vector every batched page transfer
    (preempt swap-out, resume swap-in, prefix demotion/promotion, the
    disaggregated prefill->decode handoff) feeds the jitted gather/
    scatter executables: `pages` zero-padded to `pages_per_seq`.  The
    padding aliases the reserved scratch page 0 — gathered as garbage
    nobody reads, scattered back only onto page 0 itself — so ONE
    compiled program covers every page count."""
    idx = np.zeros((int(pages_per_seq),), np.int32)
    n = len(pages)
    if n > pages_per_seq:
        raise ValueError(
            f"{n} pages exceed pages_per_seq={pages_per_seq}")
    idx[:n] = pages
    return idx


def gather_kv_pages(pools, page_idx):
    """Copy the listed pages out of the pools: {"k","v"} each
    (L, len(page_idx), page_size, Hkv, D).  The engine's preempt/swap path
    gathers a victim's pages here and moves them to host RAM."""
    return {"k": jnp.take(pools["k"], page_idx, axis=1),
            "v": jnp.take(pools["v"], page_idx, axis=1)}


def scatter_kv_pages(pools, page_idx, page_kv):
    """Inverse of gather_kv_pages: write page copies back at page_idx (the
    swap-in path, after fresh pages were allocated for a resumed sequence).
    Duplicate indices — page_idx padded to a fixed length with 0 — may only
    alias the reserved scratch page 0, whose contents are never read as
    real data."""
    return {
        "k": pools["k"].at[:, page_idx].set(
            page_kv["k"].astype(pools["k"].dtype)),
        "v": pools["v"].at[:, page_idx].set(
            page_kv["v"].astype(pools["v"].dtype)),
    }


def copy_kv_page(pools, src, dst):
    """Duplicate one page inside the pools: dst <- src across every layer
    (the device half of copy-on-write — a slot appending into a shared
    prefix page first clones it privately).  src/dst are int32 scalars so
    the jitted copy is ONE compiled executable for every page pair."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    return {
        "k": jax.lax.dynamic_update_index_in_dim(
            pools["k"], jax.lax.dynamic_index_in_dim(
                pools["k"], src, axis=1, keepdims=False), dst, axis=1),
        "v": jax.lax.dynamic_update_index_in_dim(
            pools["v"], jax.lax.dynamic_index_in_dim(
                pools["v"], src, axis=1, keepdims=False), dst, axis=1),
    }


def _block_paged(c, x, lp, cos, sin, kp, vp, page_table, ctx, ffn_fn=None):
    """One block in paged-decode mode.  x: (B, 1, E); kp/vp: one layer's
    (P, ps, Hkv, D) pools; ctx: (B,) tokens already cached per slot — the
    step's k/v are written at slot ctx, then attention runs over ctx+1
    tokens through the paged kernel."""
    B = x.shape[0]
    D, Hq, Hkv = c.hd, c.num_attention_heads, c.num_key_value_heads
    ps = kp.shape[1]
    h = kernels.rms_norm(x, lp["input_norm"].astype(jnp.float32),
                         c.rms_norm_eps).astype(x.dtype)
    q = (h @ lp["wq"]).reshape(B, 1, Hq, D)
    k = (h @ lp["wk"]).reshape(B, 1, Hkv, D)
    v = (h @ lp["wv"]).reshape(B, 1, Hkv, D)
    q = llama_lib._apply_rope(q, cos, sin)
    k = llama_lib._apply_rope(k, cos, sin)
    pidx = jnp.take_along_axis(page_table, (ctx // ps)[:, None], axis=1)[:, 0]
    poff = ctx % ps
    kp = kp.at[pidx, poff].set(k[:, 0].astype(kp.dtype))
    vp = vp.at[pidx, poff].set(v[:, 0].astype(vp.dtype))
    attn = kernels.paged_attention(q[:, 0], kp, vp, page_table, ctx + 1)
    x = x + (attn.reshape(B, 1, Hq * D) @ lp["wo"])
    h = kernels.rms_norm(x, lp["post_norm"].astype(jnp.float32),
                         c.rms_norm_eps).astype(x.dtype)
    if ffn_fn is not None:
        out, _aux = ffn_fn(h, lp)
        return x + out.astype(x.dtype), kp, vp
    gate = h @ lp["w_gate"]
    up = h @ lp["w_up"]
    mlp = (jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up) \
        @ lp["w_down"]
    return x + mlp.astype(x.dtype), kp, vp


def forward_paged_decode(params, tok, config, pools, page_table, ctx,
                         ffn_fn=None):
    """One decode step for every slot over the paged cache.  tok: (B,) the
    token sampled last step; ctx: (B,) tokens already cached per slot (the
    new token occupies slot ctx at rope position ctx).

    Returns (logits (B, V) f32, updated pools)."""
    c = config
    x = jnp.take(params["embed"]["weight"], tok[:, None], axis=0)  # (B, 1, E)
    cos_f, sin_f = llama_lib._rope_tables(c.hd, c.max_position_embeddings,
                                          c.rope_theta)
    cos = jnp.take(cos_f, ctx, axis=0)[:, None]                    # (B, 1, d2)
    sin = jnp.take(sin_f, ctx, axis=0)[:, None]

    def body(x, layer):
        lp, kp, vp = layer
        x, kp, vp = _block_paged(c, x, lp, cos, sin, kp, vp, page_table, ctx,
                                 ffn_fn=ffn_fn)
        return x, (kp, vp)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], pools["k"], pools["v"]))
    x = kernels.rms_norm(x, params["final_norm"].astype(jnp.float32),
                         c.rms_norm_eps)
    head = (params["embed"]["weight"].T if c.tie_word_embeddings
            else params["lm_head"])
    logits = (x @ head.astype(x.dtype)).astype(jnp.float32)
    return logits[:, 0], {"k": k_new, "v": v_new}


# ---------------------------------------------------------------------------
# Ragged prefill+decode: one dispatch for a mixed batch of per-seq spans
# ---------------------------------------------------------------------------


class RaggedSpan:
    """Host-side descriptor of one sequence's contribution to a ragged
    step: `tokens` (the span's token ids — 1 for decode, a chunk for
    prefill, last-token-plus-drafts for a speculative verify), `ctx_after`
    (the sequence's TOTAL cached length once this span's k/v land in the
    pool), `pages` (the slot's allocated page list, covering ctx_after
    tokens), and `n_out` — how many of the span's TRAILING rows need
    logits.  1 (the default) is the classic sample-the-next-token shape;
    a verify span asks for all its rows (n_out == len(tokens)) so the
    accept/reject pass can check every draft position."""

    __slots__ = ("tokens", "ctx_after", "pages", "n_out")

    def __init__(self, tokens, ctx_after: int, pages, n_out: int = 1):
        self.tokens = np.asarray(tokens, np.int32).reshape(-1)
        self.ctx_after = int(ctx_after)
        self.pages = list(pages)
        self.n_out = int(n_out)


def build_ragged_batch(spans, num_blocks: int, num_spans: int,
                       block_q: int, page_size: int, pages_per_seq: int,
                       num_out: Optional[int] = None):
    """Pack host-side span descriptors into the FIXED-SHAPE arrays one
    ragged dispatch consumes (the fixed shapes are what keep the step at
    O(1) compiled executables).  Spans are laid out consecutively, each
    starting on a `block_q` row boundary; unused blocks belong to the
    reserved padding span (index num_spans - 1, span_len 0, page 0).

    num_out sizes the fixed logits gather: each span claims `n_out`
    CONSECUTIVE out entries (its trailing rows, oldest first); unused
    entries point at row 0 and their logits are garbage the caller never
    reads.  The default (None -> num_spans) with all-n_out-1 spans is
    exactly the classic one-logits-row-per-span layout.

    Returns a dict of np arrays: tok/row_page/row_off/row_pos (T,),
    block_seq/block_qpos (num_blocks,), span_len/ctx_len (num_spans,),
    span_pt (num_spans, pages_per_seq), out_rows (num_out,) — the row
    indices whose logits the dispatch returns — plus host-side
    out_start/out_len (num_spans,): span i's logits live at out rows
    [out_start[i], out_start[i] + out_len[i])."""
    T = num_blocks * block_q
    pad = num_spans - 1
    if num_out is None:
        num_out = num_spans
    if len(spans) > pad:
        raise ValueError(f"{len(spans)} spans exceed num_spans-1={pad}")
    tok = np.zeros((T,), np.int32)
    row_page = np.zeros((T,), np.int32)     # padding rows -> scratch page 0
    row_off = np.zeros((T,), np.int32)
    row_pos = np.zeros((T,), np.int32)
    block_seq = np.full((num_blocks,), pad, np.int32)
    block_qpos = np.zeros((num_blocks,), np.int32)
    span_len = np.zeros((num_spans,), np.int32)
    ctx_len = np.zeros((num_spans,), np.int32)
    span_pt = np.zeros((num_spans, pages_per_seq), np.int32)
    out_rows = np.zeros((num_out,), np.int32)
    out_start = np.zeros((num_spans,), np.int32)
    out_len = np.zeros((num_spans,), np.int32)
    blk = 0
    out = 0
    for i, sp in enumerate(spans):
        L = sp.tokens.size
        if L < 1:
            raise ValueError("a ragged span must hold at least one token")
        n_out = getattr(sp, "n_out", 1)
        if not 1 <= n_out <= L:
            raise ValueError(f"span {i}: n_out={n_out} outside [1, {L}]")
        need_blocks = -(-L // block_q)
        if blk + need_blocks > num_blocks:
            raise ValueError(
                f"span {i} ({L} tokens) does not fit: {blk} of "
                f"{num_blocks} row blocks already used")
        if out + n_out > num_out:
            raise ValueError(
                f"span {i} (n_out={n_out}) does not fit: {out} of "
                f"{num_out} out rows already claimed")
        if sp.ctx_after < L:
            raise ValueError(
                f"span {i}: ctx_after={sp.ctx_after} < span length {L}")
        if -(-sp.ctx_after // page_size) > len(sp.pages):
            raise ValueError(
                f"span {i}: {len(sp.pages)} pages cannot hold "
                f"ctx_after={sp.ctx_after} tokens")
        span_len[i] = L
        ctx_len[i] = sp.ctx_after
        row = np.asarray(sp.pages + [sp.pages[-1]] *
                         (pages_per_seq - len(sp.pages)), np.int32)
        span_pt[i] = row
        r0 = blk * block_q
        out_start[i] = out
        out_len[i] = n_out
        out_rows[out:out + n_out] = r0 + L - n_out + np.arange(
            n_out, dtype=np.int32)
        out += n_out
        pos = sp.ctx_after - L + np.arange(L, dtype=np.int32)
        tok[r0:r0 + L] = sp.tokens
        row_pos[r0:r0 + L] = pos
        row_page[r0:r0 + L] = row[pos // page_size]
        row_off[r0:r0 + L] = pos % page_size
        for bi in range(need_blocks):
            block_seq[blk + bi] = i
            block_qpos[blk + bi] = bi * block_q
        blk += need_blocks
    return {"tok": tok, "row_page": row_page, "row_off": row_off,
            "row_pos": row_pos, "block_seq": block_seq,
            "block_qpos": block_qpos, "span_len": span_len,
            "ctx_len": ctx_len, "span_pt": span_pt, "out_rows": out_rows,
            "out_start": out_start, "out_len": out_len}


def _block_ragged(c, x, lp, cos, sin, kp, vp, row_page, row_off, span_pt,
                  block_seq, block_qpos, span_len, ctx_len, ffn_fn=None):
    """One block in ragged mode.  x: (T, E) span-packed rows; kp/vp: one
    layer's (P, ps, Hkv, D) pools.  Each row's k/v is scattered at its
    absolute position's (page, offset) BEFORE attention, so a prefill
    chunk's later rows attend its earlier rows through the pool."""
    T = x.shape[0]
    D, Hq, Hkv = c.hd, c.num_attention_heads, c.num_key_value_heads
    h = kernels.rms_norm(x, lp["input_norm"].astype(jnp.float32),
                         c.rms_norm_eps).astype(x.dtype)
    q = (h @ lp["wq"]).reshape(T, Hq, D)
    k = (h @ lp["wk"]).reshape(T, Hkv, D)
    v = (h @ lp["wv"]).reshape(T, Hkv, D)
    # rope rides the per-row position tables; _apply_rope wants (B,S,H,D)
    q = llama_lib._apply_rope(q[None], cos, sin)[0]
    k = llama_lib._apply_rope(k[None], cos, sin)[0]
    # padding rows target the reserved scratch page 0 (never read as data)
    kp = kp.at[row_page, row_off].set(k.astype(kp.dtype))
    vp = vp.at[row_page, row_off].set(v.astype(vp.dtype))
    attn = kernels.ragged_attention(q, kp, vp, span_pt, block_seq,
                                    block_qpos, span_len, ctx_len)
    x = x + (attn.reshape(T, Hq * D) @ lp["wo"])
    h = kernels.rms_norm(x, lp["post_norm"].astype(jnp.float32),
                         c.rms_norm_eps).astype(x.dtype)
    if ffn_fn is not None:
        out, _aux = ffn_fn(h, lp)
        return x + out.astype(x.dtype), kp, vp
    gate = h @ lp["w_gate"]
    up = h @ lp["w_up"]
    # silu in fp32, matching the train path (see _block_with_cache)
    mlp = (jax.nn.silu(gate.astype(jnp.float32)).astype(up.dtype) * up) \
        @ lp["w_down"]
    return x + mlp.astype(x.dtype), kp, vp


def _forward_ragged_trunk(params, tok, config, pools, row_page, row_off,
                          row_pos, block_seq, block_qpos, span_len,
                          ctx_len, span_pt, out_rows, ffn_fn=None):
    """Shared layer pipeline of the ragged dispatch: embed -> scanned
    blocks (with per-row KV scatter) -> final norm -> out-row gather.
    Returns (sel (num_out, E), head (E, V), updated pools) — the logits
    matmul is left to the caller so `forward_ragged` (host pulls the
    (rows, V) logits) and `forward_ragged_sample` (fused on-device
    epilogue, tokens only) stay bit-for-bit the same up to the tail."""
    c = config
    x = jnp.take(params["embed"]["weight"], tok, axis=0)           # (T, E)
    cos_f, sin_f = llama_lib._rope_tables(c.hd, c.max_position_embeddings,
                                          c.rope_theta)
    cos = jnp.take(cos_f, row_pos, axis=0)                         # (T, d2)
    sin = jnp.take(sin_f, row_pos, axis=0)

    def body(x, layer):
        lp, kp, vp = layer
        x, kp, vp = _block_ragged(c, x, lp, cos, sin, kp, vp, row_page,
                                  row_off, span_pt, block_seq, block_qpos,
                                  span_len, ctx_len, ffn_fn=ffn_fn)
        return x, (kp, vp)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], pools["k"], pools["v"]))
    x = kernels.rms_norm(x, params["final_norm"].astype(jnp.float32),
                         c.rms_norm_eps)
    sel = jnp.take(x, out_rows, axis=0)                 # (num_spans, E)
    head = (params["embed"]["weight"].T if c.tie_word_embeddings
            else params["lm_head"])
    return sel, head, {"k": k_new, "v": v_new}


def forward_ragged(params, tok, config, pools, row_page, row_off, row_pos,
                   block_seq, block_qpos, span_len, ctx_len, span_pt,
                   out_rows, ffn_fn=None):
    """ONE unified dispatch over a ragged batch of per-seq spans: decode
    tokens (span_len 1) and prefill chunks (span_len > 1) together.  tok:
    (T,) span-packed token ids; row_page/row_off/row_pos: (T,) per-row
    scatter/rope metadata; block/span arrays as built by
    `build_ragged_batch`; pools: the paged {"k","v"} pools.

    Returns (logits (num_spans, V) f32 — one row per span, taken at its
    LAST valid token (out_rows) — and the updated pools)."""
    sel, head, pools = _forward_ragged_trunk(
        params, tok, config, pools, row_page, row_off, row_pos, block_seq,
        block_qpos, span_len, ctx_len, span_pt, out_rows, ffn_fn=ffn_fn)
    logits = (sel @ head.astype(sel.dtype)).astype(jnp.float32)
    return logits, pools


def forward_ragged_sample(params, tok, config, pools, row_page, row_off,
                          row_pos, block_seq, block_qpos, span_len,
                          ctx_len, span_pt, out_rows, key,
                          temperature: float = 0.0, top_k: int = 0,
                          top_p: float = 1.0, ffn_fn=None):
    """`forward_ragged` with the sampling epilogue fused on-device: the
    lm_head matmul, temperature/top-k/top-p filtering and categorical
    sampling run in ONE Pallas dispatch (kernels.fused_decode_step), so
    plain-decode steps pull (num_out,) int32 token ids off the device
    instead of (num_out, V) f32 logits.  `key` is a threaded PRNG key —
    sampling happens device-side; greedy (temperature == 0) ignores it.

    Returns (tokens (num_out,) int32, updated pools)."""
    sel, head, pools = _forward_ragged_trunk(
        params, tok, config, pools, row_page, row_off, row_pos, block_seq,
        block_qpos, span_len, ctx_len, span_pt, out_rows, ffn_fn=ffn_fn)
    toks = kernels.fused_decode_step(sel, head, key, temperature=temperature,
                                     top_k=top_k, top_p=top_p)
    return toks, pools


def generate_ragged(params, input_ids, config, max_new_tokens: int,
                    page_size: int = 16, prefill_chunk_tokens: int = 8,
                    block_q: int = 4):
    """`generate()` through the unified ragged path: the prompt is
    prefilled in bounded chunks and every decode token is a 1-token span,
    all through `forward_ragged` — greedy only, equal-length prompts.

    This is the functional proof that chunked ragged prefill + ragged
    decode reproduces the dense `generate()` chain exactly; the
    continuous-batching engine builds the same batches incrementally with
    slots arriving and leaving mid-flight."""
    B, S = input_ids.shape
    ids = np.asarray(input_ids, np.int32)
    total = S + max_new_tokens
    pages_per_seq = -(-total // page_size)
    cache = PagedKVCache(config, num_pages=1 + B * pages_per_seq,
                         page_size=page_size, max_slots=B,
                         pages_per_seq=pages_per_seq)
    slots = [cache.acquire_slot() for _ in range(B)]
    num_spans = B + 1
    chunk = max(1, int(prefill_chunk_tokens))
    num_blocks = B * -(-max(chunk, 1) // block_q)
    pools = cache.pools

    def dispatch(spans):
        b = build_ragged_batch(spans, num_blocks, num_spans, block_q,
                               page_size, pages_per_seq)
        return forward_ragged(
            params, jnp.asarray(b["tok"]), config, pools,
            jnp.asarray(b["row_page"]), jnp.asarray(b["row_off"]),
            jnp.asarray(b["row_pos"]), jnp.asarray(b["block_seq"]),
            jnp.asarray(b["block_qpos"]), jnp.asarray(b["span_len"]),
            jnp.asarray(b["ctx_len"]), jnp.asarray(b["span_pt"]),
            jnp.asarray(b["out_rows"]))

    logits = None
    for c0 in range(0, S, chunk):
        n = min(chunk, S - c0)
        spans = []
        for b_i in range(B):
            cache.ensure_capacity(slots[b_i], c0 + n)
            spans.append(RaggedSpan(ids[b_i, c0:c0 + n], c0 + n,
                                    cache._slot_pages[slots[b_i]]))
        logits, pools = dispatch(spans)
    tok = np.asarray(jnp.argmax(logits[:B], axis=-1), np.int32)
    out = [tok.copy()]
    for step in range(1, max_new_tokens):
        ctx = S + step
        spans = []
        for b_i in range(B):
            cache.ensure_capacity(slots[b_i], ctx)
            spans.append(RaggedSpan([int(tok[b_i])], ctx,
                                    cache._slot_pages[slots[b_i]]))
        logits, pools = dispatch(spans)
        tok = np.asarray(jnp.argmax(logits[:B], axis=-1), np.int32)
        out.append(tok.copy())
    return jnp.asarray(np.stack(out, axis=1))


# ---------------------------------------------------------------------------
# Speculative decoding: draft proposals + the verify-span accept/reject pass
# ---------------------------------------------------------------------------


class Drafter:
    """Proposes up to k draft tokens for a decoding sequence.  The engine
    packs [last_token] + proposal as ONE (k+1)-row ragged verify span
    through the same unified dispatch as prefill chunks — verifying k
    drafts costs one span, not k steps.  Implementations must be pure
    functions of the history (preempt/resume replays them safely)."""

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        """history: (n,) int32 prompt + generated tokens so far (the last
        entry is the sampled-but-not-yet-cached token).  Returns up to k
        proposed continuation tokens (possibly empty -> no speculation
        this step)."""
        raise NotImplementedError


class NGramDrafter(Drafter):
    """Prompt-lookup drafting: no second model.  Match the longest recent
    suffix (ngram_max down to ngram_min tokens) of the sequence's own
    prompt+output history against an EARLIER occurrence and propose the
    tokens that followed it.  Free on repetitive continuations (copy
    tasks, code, summaries quoting the prompt, greedy cycles); proposes
    nothing when the history never repeats — the engine then falls back
    to a plain 1-token decode span.

    max_history bounds the scanned window (the TRAILING tokens): the
    scan runs per decoding slot per step on the serial step thread, so
    an unbounded window would grow drafting cost linearly with sequence
    length.  Matches beyond the window are lost — the usual
    prompt-lookup trade; raise it for very long quoted prompts."""

    def __init__(self, ngram_max: int = 3, ngram_min: int = 1,
                 max_history: int = 2048):
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError("need ngram_max >= ngram_min >= 1")
        if max_history < 2:
            raise ValueError("max_history must be >= 2")
        self.ngram_max = int(ngram_max)
        self.ngram_min = int(ngram_min)
        self.max_history = int(max_history)

    def propose(self, history: np.ndarray, k: int) -> np.ndarray:
        h = np.asarray(history, np.int32).reshape(-1)
        if h.size > self.max_history:
            h = h[-self.max_history:]
        n = h.size
        k = int(k)
        if k < 1 or n < self.ngram_min + 1:
            return np.zeros((0,), np.int32)
        for g in range(min(self.ngram_max, n - 1), self.ngram_min - 1, -1):
            suffix = h[n - g:]
            # windows of length g ending strictly before the suffix
            win = np.lib.stride_tricks.sliding_window_view(h[:n - 1], g)
            hits = np.flatnonzero((win == suffix).all(axis=1))
            if hits.size == 0:
                continue
            start = int(hits[-1]) + g     # most recent match's continuation
            cont = h[start:start + k]
            if cont.size:
                return cont.astype(np.int32)
        return np.zeros((0,), np.int32)


def filtered_probs(logits, temperature: float, top_k: int = 0,
                   top_p: float = 1.0) -> np.ndarray:
    """Numpy mirror of `sample_logits`' temperature/top-k/top-p filtering:
    the exact TARGET distribution the non-speculative sampler draws from,
    row-wise.  logits: (N, V) f32 -> (N, V) probabilities."""
    lg = np.asarray(logits, np.float64) / max(float(temperature), 1e-6)
    N, V = lg.shape
    if top_k and top_k < V:
        kth = np.sort(lg, axis=-1)[:, V - top_k][:, None]
        lg = np.where(lg < kth, -np.inf, lg)
    if top_p < 1.0:
        sorted_l = np.sort(lg, axis=-1)[:, ::-1]
        e = np.exp(sorted_l - sorted_l[:, :1])
        probs = e / e.sum(-1, keepdims=True)
        cum = np.cumsum(probs, axis=-1)
        # same keep rule as sample_logits: smallest set with mass >= top_p
        keep = (cum - probs) < top_p
        cutoff = np.min(np.where(keep, sorted_l, np.inf), axis=-1)[:, None]
        lg = np.where(lg < cutoff, -np.inf, lg)
    e = np.exp(lg - lg.max(-1, keepdims=True))
    return e / e.sum(-1, keepdims=True)


def verify_greedy(logits, draft) -> tuple:
    """Greedy accept/reject over one verify span.  logits: (k+1, V) rows
    for [last_token, d_1..d_k] (row j's logits are the target's next-token
    scores AFTER d_1..d_j landed); draft: (k,) proposed tokens.

    Accepts the longest prefix where argmax agrees, then emits the
    target's own next token (the correction at the first disagreement, or
    the bonus token after full acceptance).  Every emitted token equals
    argmax given the true prefix, so greedy speculative decoding is
    TOKEN-EXACT vs the non-speculative chain by construction.

    Returns (emitted tokens: accepted drafts + 1, n_accepted)."""
    lg = np.asarray(logits)
    d = np.asarray(draft, np.int32).reshape(-1)
    g = np.argmax(lg, axis=-1).astype(np.int32)
    m = 0
    while m < d.size and g[m] == d[m]:
        m += 1
    return [int(t) for t in d[:m]] + [int(g[m])], m


def verify_rejection(probs, draft, rng) -> tuple:
    """Rejection-sampling accept/reject over one verify span (temperature
    sampling).  probs: (k+1, V) TARGET distributions (filtered_probs of
    the verify logits); draft: (k,) tokens from a DETERMINISTIC drafter
    (draft distribution q = a point mass, q(d_i) = 1); rng: numpy
    Generator.

    Standard speculative sampling: accept d_i with prob
    min(1, p_i(d_i)/q(d_i)) = p_i(d_i); on the first rejection resample
    from the residual max(p - q, 0) normalized — p with d_i zeroed.  The
    emitted-token DISTRIBUTION is exactly the target's: P(x) =
    q(x)min(1,p(x)) + P(reject)·residual(x) = p(x) for every x.  After
    full acceptance the bonus token is drawn from the last row's p.

    Returns (emitted tokens: accepted drafts + 1, n_accepted)."""
    p = np.asarray(probs, np.float64)
    d = np.asarray(draft, np.int32).reshape(-1)
    V = p.shape[-1]
    for i in range(d.size):
        row = p[i]
        if rng.random() < row[d[i]]:
            continue
        residual = row.copy()
        residual[d[i]] = 0.0
        tot = residual.sum()
        if tot <= 0.0:
            # p was (numerically) a point mass at the draft: accept
            continue
        nxt = int(rng.choice(V, p=residual / tot))
        return [int(t) for t in d[:i]] + [nxt], i
    row = p[d.size]
    nxt = int(rng.choice(V, p=row / row.sum()))
    return [int(t) for t in d] + [nxt], int(d.size)


@functools.partial(jax.jit, static_argnames=(
    "config", "max_new_tokens", "temperature", "top_k", "top_p", "eos_id"))
def _generate_paged_core(params, input_ids, k_pool, v_pool, page_table, key,
                         config, max_new_tokens, temperature, top_k, top_p,
                         eos_id):
    c = config
    B, S = input_ids.shape
    # prefill through the dense cached forward (flash-style attention over
    # the prompt), then scatter the prompt's k/v into pages
    dense = init_kv_cache(c, B, S)
    logits, dense = forward_with_cache(params, input_ids, c, dense, 0)
    pools = scatter_prefill_into_pages(dense, {"k": k_pool, "v": v_pool},
                                       page_table, S)
    next_tok = sample_logits(logits[:, -1], key, temperature, top_k, top_p)

    def step(carry, i):
        pools, tok, done, key = carry
        key, sub = jax.random.split(key)
        ctx = jnp.full((B,), S, jnp.int32) + i - 1
        logits, pools = forward_paged_decode(params, tok, c, pools,
                                             page_table, ctx)
        nxt = sample_logits(logits, sub, temperature, top_k, top_p)
        if eos_id is not None:
            nxt = jnp.where(done, eos_id, nxt)
            done = done | (nxt == eos_id)
        return (pools, nxt, done, key), tok

    done0 = (jnp.zeros((B,), bool) if eos_id is None
             else (next_tok == eos_id))
    (_, last, _, _), toks = jax.lax.scan(
        step, (pools, next_tok, done0, key), jnp.arange(1, max_new_tokens))
    return jnp.concatenate([jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1)


def generate_paged(params, input_ids, config, max_new_tokens: int,
                   page_size: int = 16, temperature: float = 0.0,
                   top_k: int = 0, top_p: float = 1.0,
                   eos_id: Optional[int] = None, key: Optional[Any] = None):
    """`generate()` over a block-paged KV cache: prefill lands in pages, the
    decode scan runs the Pallas paged-attention kernel.  Token-exact with
    `generate()` for greedy decoding (same math, paged layout).

    Single-shot generation knows its max length, so all pages are allocated
    up front through the PagedKVCache allocator; the continuous-batching
    engine (paddle_tpu.inference.LLMEngine) allocates them on demand
    instead.  Equal-length prompts only (the engine handles ragged prompts
    by per-request prefill)."""
    B, S = input_ids.shape
    if key is None:
        key = jax.random.PRNGKey(0)
    total = S + max_new_tokens
    pages_per_seq = -(-total // page_size)
    cache = PagedKVCache(config, num_pages=1 + B * pages_per_seq,
                         page_size=page_size, max_slots=B,
                         pages_per_seq=pages_per_seq)
    for _ in range(B):
        cache.ensure_capacity(cache.acquire_slot(), total)
    return _generate_paged_core(
        params, input_ids, cache.pools["k"], cache.pools["v"],
        cache.page_table, key, config, max_new_tokens, temperature, top_k,
        top_p, eos_id)
