"""paddle.geometric — graph message passing + segment ops (SURVEY C48).

Reference: python/paddle/geometric/message_passing/send_recv.py:36
(send_u_recv), :187 (send_ue_recv), send_uv, and geometric/math.py segment
ops.  TPU-native: gather + `jax.ops.segment_*` — static shapes (out_size /
num_segments must be concrete under jit), fully differentiable, and XLA
lowers the scatter-reduce onto the VPU.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..tensor import Tensor, apply_op

__all__ = [
    "send_u_recv", "send_ue_recv", "send_uv",
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "sample_neighbors", "reindex_graph",
    "weighted_sample_neighbors", "reindex_heter_graph",
]


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


def _t(x):
    from ..tensor import to_tensor
    return x if isinstance(x, Tensor) else to_tensor(x)


_SEG = {
    "sum": jax.ops.segment_sum,
    "mean": None,  # composed below
    "min": jax.ops.segment_min,
    "max": jax.ops.segment_max,
}


def _segment(reduce_op, data, seg_ids, num_segments):
    if reduce_op == "mean":
        s = jax.ops.segment_sum(data, seg_ids, num_segments=num_segments)
        n = jax.ops.segment_sum(jnp.ones(seg_ids.shape, data.dtype), seg_ids,
                                num_segments=num_segments)
        return s / jnp.maximum(n, 1).reshape(
            (-1,) + (1,) * (data.ndim - 1))
    out = _SEG[reduce_op](data, seg_ids, num_segments=num_segments)
    if reduce_op in ("min", "max"):
        # empty segments come back as the dtype's +/-identity (inf for
        # floats, INT_MIN/MAX for ints); the reference zeroes them — detect
        # emptiness by count so integer dtypes zero correctly too
        n = jax.ops.segment_sum(jnp.ones(seg_ids.shape, jnp.int32), seg_ids,
                                num_segments=num_segments)
        empty = (n == 0).reshape((-1,) + (1,) * (data.ndim - 1))
        out = jnp.where(empty, jnp.zeros((), data.dtype), out)
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum",
                out_size: Optional[int] = None, name=None):
    """Gather x[src] along edges, reduce onto dst (send_recv.py:36)."""
    if reduce_op not in ("sum", "mean", "min", "max"):
        raise ValueError(f"unsupported reduce_op {reduce_op}")
    xt, st, dt = _t(x), _t(src_index), _t(dst_index)
    n_out = int(out_size) if out_size is not None else int(xt.shape[0])

    def f(xr, sr, dr):
        msg = jnp.take(xr, sr, axis=0)
        return _segment(reduce_op, msg, dr, n_out)

    return apply_op("send_u_recv", f, xt, st, dt, nondiff=(1, 2))


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size: Optional[int] = None, name=None):
    """x[src] (op) y_edge, reduced onto dst (send_recv.py:187)."""
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    if message_op not in ops:
        raise ValueError(f"unsupported message_op {message_op}")
    if reduce_op not in ("sum", "mean", "min", "max"):
        raise ValueError(f"unsupported reduce_op {reduce_op}")
    xt, yt, st, dt = _t(x), _t(y), _t(src_index), _t(dst_index)
    n_out = int(out_size) if out_size is not None else int(xt.shape[0])

    def f(xr, yr, sr, dr):
        msg = ops[message_op](jnp.take(xr, sr, axis=0), yr)
        return _segment(reduce_op, msg, dr, n_out)

    return apply_op("send_ue_recv", f, xt, yt, st, dt, nondiff=(2, 3))


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message x[src] (op) y[dst] (send_recv.py send_uv)."""
    ops = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}
    if message_op not in ops:
        raise ValueError(f"unsupported message_op {message_op}")
    xt, yt, st, dt = _t(x), _t(y), _t(src_index), _t(dst_index)

    def f(xr, yr, sr, dr):
        return ops[message_op](jnp.take(xr, sr, axis=0),
                               jnp.take(yr, dr, axis=0))

    return apply_op("send_uv", f, xt, yt, st, dt, nondiff=(2, 3))


def _segment_api(reduce_op):
    def op(data, segment_ids, name=None):
        dt, st = _t(data), _t(segment_ids)
        n = int(jnp.max(st._data)) + 1 if st._data.size else 0

        def f(dr, sr):
            return _segment(reduce_op, dr, sr, n)

        return apply_op(f"segment_{reduce_op}", f, dt, st, nondiff=(1,))
    op.__name__ = f"segment_{reduce_op}"
    return op


segment_sum = _segment_api("sum")
segment_mean = _segment_api("mean")
segment_min = _segment_api("min")
segment_max = _segment_api("max")


# ---------------------------------------------------------------------------
# Graph sampling (reference geometric/sampling/neighbors.py:23,
# geometric/reindex.py:25) — GNN data-pipeline ops.  Like the reference's
# CPU kernels these run HOST-side (numpy): sampling produces ragged,
# data-dependent shapes that have no business inside an XLA program; the
# sampled subgraph then feeds the jit-ed message-passing ops above.
# ---------------------------------------------------------------------------

# stateful sampler RNG: PERSISTS across calls (each minibatch draws a fresh
# subgraph) and re-seeds exactly when paddle.seed() changes the global seed
_SAMPLER_RNG = [None, None]  # [seed_at_creation, np.random.Generator]


def _sampler_rng():
    import numpy as np
    from .. import framework
    seed = framework.default_generator().initial_seed()
    if _SAMPLER_RNG[1] is None or _SAMPLER_RNG[0] != seed:
        _SAMPLER_RNG[0] = seed
        _SAMPLER_RNG[1] = np.random.default_rng(seed)
    return _SAMPLER_RNG[1]


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Sample up to `sample_size` neighbors per input node from a CSC graph
    (reference geometric/sampling/neighbors.py:23).

    row/colptr: CSC components; input_nodes: nodes to sample for.
    Returns (out_neighbors, out_count) and out_eids when return_eids.
    """
    import numpy as np
    from ..tensor import to_tensor
    from .. import framework

    rown = np.asarray(_raw(row)).reshape(-1)
    cp = np.asarray(_raw(colptr)).reshape(-1)
    nodes = np.asarray(_raw(input_nodes)).reshape(-1)
    if return_eids and eids is None:
        raise ValueError("return_eids=True needs eids")
    eidn = np.asarray(_raw(eids)).reshape(-1) if eids is not None else None
    rng = _sampler_rng()
    neigh, counts, out_eids = [], [], []
    for v in nodes:
        beg, end = int(cp[v]), int(cp[v + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            idx = np.arange(beg, end)
        else:
            idx = beg + rng.choice(deg, size=sample_size, replace=False)
        neigh.append(rown[idx])
        counts.append(len(idx))
        if eidn is not None:
            out_eids.append(eidn[idx])
    out_n = to_tensor(np.concatenate(neigh) if neigh
                      else np.zeros((0,), rown.dtype))
    out_c = to_tensor(np.asarray(counts, np.int32))
    if return_eids:
        return out_n, out_c, to_tensor(
            np.concatenate(out_eids) if out_eids
            else np.zeros((0,), eidn.dtype))
    return out_n, out_c


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Reindex sampled nodes to a dense 0..n-1 id space (reference
    geometric/reindex.py:25).  Returns (reindex_src, reindex_dst,
    out_nodes): out_nodes = input nodes then first-seen-order new
    neighbors; reindex_src maps `neighbors`; reindex_dst repeats each input
    node's new id `count` times."""
    import numpy as np
    from ..tensor import to_tensor

    xs = np.asarray(_raw(x)).reshape(-1)
    nb = np.asarray(_raw(neighbors)).reshape(-1)
    ct = np.asarray(_raw(count)).reshape(-1)
    if ct.sum() != nb.size:
        raise ValueError(
            f"count sums to {int(ct.sum())} but neighbors has {nb.size} "
            "entries")
    table = {int(v): i for i, v in enumerate(xs)}
    out_nodes = list(xs)
    src = np.empty_like(nb)
    for i, v in enumerate(nb):
        j = table.get(int(v))
        if j is None:
            j = len(out_nodes)
            table[int(v)] = j
            out_nodes.append(v)
        src[i] = j
    dst = np.repeat(np.arange(xs.size), ct).astype(nb.dtype)
    return (to_tensor(src), to_tensor(dst),
            to_tensor(np.asarray(out_nodes, xs.dtype)))


def weighted_sample_neighbors(row, colptr, weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """Weight-biased neighbor sampling without replacement (reference
    geometric/sampling/neighbors.py weighted_sample_neighbors)."""
    import numpy as np
    from ..tensor import to_tensor

    rown = np.asarray(_raw(row)).reshape(-1)
    cp = np.asarray(_raw(colptr)).reshape(-1)
    w = np.asarray(_raw(weight)).reshape(-1).astype(np.float64)
    nodes = np.asarray(_raw(input_nodes)).reshape(-1)
    if return_eids and eids is None:
        raise ValueError("return_eids=True needs eids")
    eidn = np.asarray(_raw(eids)).reshape(-1) if eids is not None else None
    rng = _sampler_rng()
    neigh, counts, out_eids = [], [], []
    for v in nodes:
        beg, end = int(cp[v]), int(cp[v + 1])
        deg = end - beg
        if sample_size < 0 or deg <= sample_size:
            idx = np.arange(beg, end)
        else:
            pw = w[beg:end]
            pw = pw / pw.sum() if pw.sum() > 0 else None
            idx = beg + rng.choice(deg, size=sample_size, replace=False,
                                   p=pw)
        neigh.append(rown[idx])
        counts.append(len(idx))
        if eidn is not None:
            out_eids.append(eidn[idx])
    out_n = to_tensor(np.concatenate(neigh) if neigh
                      else np.zeros((0,), rown.dtype))
    out_c = to_tensor(np.asarray(counts, np.int32))
    if return_eids:
        return out_n, out_c, to_tensor(
            np.concatenate(out_eids) if out_eids
            else np.zeros((0,), eidn.dtype))
    return out_n, out_c


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Reindex over multiple edge types sharing ONE id table (reference
    geometric/reindex.py:139): neighbors/count are lists; edges of all
    types are renumbered consistently and concatenated."""
    import numpy as np
    from ..tensor import to_tensor

    xs = np.asarray(_raw(x)).reshape(-1)
    table = {int(v): i for i, v in enumerate(xs)}
    out_nodes = list(xs)
    srcs, dsts = [], []
    for nb_t, ct_t in zip(neighbors, count):
        nb = np.asarray(_raw(nb_t)).reshape(-1)
        ct = np.asarray(_raw(ct_t)).reshape(-1)
        if ct.sum() != nb.size:
            raise ValueError(
                f"count sums to {int(ct.sum())} but neighbors has "
                f"{nb.size} entries")
        src = np.empty_like(nb)
        for i, v in enumerate(nb):
            j = table.get(int(v))
            if j is None:
                j = len(out_nodes)
                table[int(v)] = j
                out_nodes.append(v)
            src[i] = j
        srcs.append(src)
        dsts.append(np.repeat(np.arange(xs.size), ct).astype(nb.dtype))
    return (to_tensor(np.concatenate(srcs)),
            to_tensor(np.concatenate(dsts)),
            to_tensor(np.asarray(out_nodes, xs.dtype)))
